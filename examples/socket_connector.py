"""SocketConnector: a TCP transport on the AbstractConnector base,
carried by the session layer (``yjs_tpu.sync.session``).

A second transport example beyond ``server_demo.py``'s in-process
provider: each peer binds one ``Y.Doc`` to a length-prefixed TCP framing
of the sync messages.  Since ISSUE 5 the frames ride a
:class:`~yjs_tpu.sync.session.SyncSession`, so this connector gets
ack-based retransmission, heartbeat/liveness detection, backpressure
coalescing, and the anti-entropy repair loop for free — while the inner
frames stay exactly what a JS ``y-websocket`` peer would exchange: a
peer that never speaks the session envelope is detected by its bare
step 1 and the session negotiates down to the plain protocol.

Since ISSUE 14 the socket plumbing is the cluster's own
:class:`~yjs_tpu.cluster.rpc.SocketTransport` — the same rx/tx thread
pair the shard RPC rides — whose ``close()`` contract is drain-then-
join: every frame accepted before close reaches the wire, then both
threads exit (``tests/test_connector.py`` pins this).  Passing
``room=`` sends the raw-dialect preamble, which makes this connector a
ready-made client for the cluster gateway
(``yjs_tpu.cluster.gateway``).

Run in two terminals (the first becomes the listener):

    python examples/socket_connector.py server 47800
    python examples/socket_connector.py client 47800

Both processes make concurrent edits and print the converged text.
Reference seams: src/utils/AbstractConnector.js:16-26 (the base),
y-protocols/sync.js (the message flow the protocol module mirrors).
"""

from __future__ import annotations

import os
import socket
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import yjs_tpu as Y
from yjs_tpu.cluster.gateway import encode_room_preamble
from yjs_tpu.cluster.rpc import SocketTransport
from yjs_tpu.sync.session import DocSessionHost, SessionConfig, SyncSession
from yjs_tpu.utils.abstract_connector import AbstractConnector

# seconds of wall time per session tick: with the default knobs that
# makes a heartbeat every ~0.4s, a liveness timeout after ~1.6s of
# silence, and first retransmission of a lost frame after ~0.1s
TICK_SECONDS = 0.05


class SocketConnector(AbstractConnector):
    """Bind one doc to one TCP peer through a resumable session.

    The Doc is NOT thread-safe; the transport delivers frames and the
    ticker thread drives the session under ``self.lock``, and local
    edits from other threads must take the same lock (see ``_demo``)."""

    def __init__(
        self, ydoc: Y.Doc, sock: socket.socket, awareness=None,
        config: SessionConfig | None = None,
        room: str | None = None, peer: str | None = None,
    ):
        super().__init__(ydoc, awareness)
        self._sock = sock
        #: guards every doc access (remote applies, local edits, reads)
        self.lock = threading.RLock()
        self._closed = False
        peer_name = peer or f"fd{sock.fileno()}"
        # the transport owns the rx/tx threads; inbound frames are
        # delivered under self.lock (the session is not thread-safe)
        self._transport = SocketTransport(
            sock, frame_lock=self.lock, name=peer_name
        )
        self.room = room
        self.session = SyncSession(
            DocSessionHost(ydoc, origin=self),
            config=config,
            peer=peer_name,
        )
        ydoc.on("update", self._on_local_update)
        self._ticker = threading.Thread(target=self._tick_loop, daemon=True)

    # -- sync flow ----------------------------------------------------------

    def connect(self) -> None:
        """Start the session handshake and the transport/ticker threads."""
        with self.lock:
            if self.room is not None:
                # the gateway's raw-dialect hello MUST be the first
                # frame on the wire; it is queued ahead of the HELLO
                # that attach() emits, and the drained-in-order tx
                # thread preserves that
                self._transport.send(encode_room_preamble(
                    self.room, self.session.peer
                ))
            self.session.connect(self._transport)
            inner_close = self._transport.on_close
            def _closed(_cb=inner_close):
                if _cb is not None:
                    _cb()
                self.emit("close", [])
                self.on_disconnect("eof")
            self._transport.on_close = _closed
        self._transport.start()
        self._ticker.start()
        self.on_connect()

    def _on_local_update(self, update: bytes, origin, doc) -> None:
        if origin is self or self._closed:
            return  # don't echo remote updates back
        # the editor already holds self.lock (RLock: re-entry is fine)
        with self.lock:
            self.session.send_update(update)

    def _tick_loop(self) -> None:
        # session time advances on a fixed wall cadence; everything the
        # tick drives (retransmit backoff, heartbeats, liveness, the
        # anti-entropy digests) counts in these ticks
        import time

        while True:
            time.sleep(TICK_SECONDS)
            with self.lock:
                if self._closed:
                    break
                self.session.tick()

    def close(self) -> None:
        """Shutdown contract (pinned by ``tests/test_connector.py``):
        stop the ticker, stop inbound delivery, then let the transport
        drain its outbox to the wire and JOIN both of its threads —
        nothing accepted before close is dropped.  Frames the peer
        never acked stay in the session outbox for the next attach."""
        with self.lock:
            if self._closed:
                return
            self._closed = True
        self.doc.off("update", self._on_local_update)
        me = threading.current_thread()
        if self._ticker.is_alive() and self._ticker is not me:
            self._ticker.join(timeout=2.0)
        with self.lock:
            # no more inbound deliveries race the teardown; the rx
            # thread drains to EOF on its own
            self._transport.on_frame = None
        # session.close() closes the transport: drain outbox → join tx
        # → close socket → join rx → single on_close
        self.session.close()
        self.on_disconnect("closed")

    def join(self, timeout: float = 2.0) -> bool:
        """True when the ticker and both transport threads exited."""
        me = threading.current_thread()
        if self._ticker.is_alive() and self._ticker is not me:
            self._ticker.join(timeout=timeout)
        transport_done = self._transport.join(timeout=timeout)
        return transport_done and not (
            self._ticker.is_alive() and self._ticker is not me
        )


def _demo(role: str, port: int) -> None:
    doc = Y.Doc(gc=False)
    doc.client_id = 1 if role == "server" else 2
    text = doc.get_text("text")
    if role == "server":
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(1)
        text.insert(0, "listener says hi. ")
        conn, _ = srv.accept()
    else:
        conn = socket.create_connection(("127.0.0.1", port))
        text.insert(0, "joiner says hi. ")

    connector = SocketConnector(doc, conn)
    connector.connect()

    import time

    time.sleep(1.0)  # let the handshake settle
    with connector.lock:  # doc access shares the lock with the rx thread
        text.insert(len(text.to_string()), f"[{role} concurrent edit]")
    time.sleep(1.0)
    with connector.lock:
        print(f"{role}: {text.to_string()!r}")
        print(f"{role}: sv={Y.encode_state_vector(doc).hex()}")
        print(f"{role}: session={connector.session.snapshot()}")
    connector.close()


if __name__ == "__main__":
    if len(sys.argv) < 2 or sys.argv[1] not in ("server", "client"):
        print(f"usage: {sys.argv[0]} server|client [port]")
        sys.exit(2)
    _demo(sys.argv[1], int(sys.argv[2]) if len(sys.argv) > 2 else 47800)
