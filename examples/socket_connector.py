"""SocketConnector: a TCP transport on the AbstractConnector base.

A second transport example beyond ``server_demo.py``'s in-process
provider: each peer binds one ``Y.Doc`` to a length-prefixed TCP framing
of the y-protocols sync messages (step 1 / step 2 / incremental update —
``yjs_tpu.sync.protocol``), so the wire bytes are exactly what a JS
``y-websocket`` peer would exchange.

Run in two terminals (the first becomes the listener):

    python examples/socket_connector.py serve 47800
    python examples/socket_connector.py join  47800

Both processes make concurrent edits and print the converged text.
Reference seams: src/utils/AbstractConnector.js:16-26 (the base),
y-protocols/sync.js (the message flow the protocol module mirrors).
"""

from __future__ import annotations

import os
import socket
import struct
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import yjs_tpu as Y
from yjs_tpu.lib0.decoding import Decoder
from yjs_tpu.lib0.encoding import Encoder
from yjs_tpu.sync import protocol
from yjs_tpu.utils.abstract_connector import AbstractConnector


class SocketConnector(AbstractConnector):
    """Bind one doc to one TCP peer: handshake on connect, then stream
    local transactions as incremental update frames.

    The Doc is NOT thread-safe; the receive thread applies remote
    messages under ``self.lock``, and local edits from other threads
    must take the same lock (see ``_demo``)."""

    def __init__(self, ydoc: Y.Doc, sock: socket.socket, awareness=None):
        super().__init__(ydoc, awareness)
        self._sock = sock
        self._send_lock = threading.Lock()
        #: guards every doc access (remote applies, local edits, reads)
        self.lock = threading.RLock()
        self._closed = False
        # outbound frames ride a queue drained by a writer thread: the
        # update handler fires while the editor holds self.lock, and
        # blocking in sendall there would deadlock two back-pressured
        # peers whose rx threads both wait on that lock
        import queue

        self._outbox: "queue.Queue[bytes | None]" = queue.Queue()
        ydoc.on("update", self._on_local_update)
        self._rx = threading.Thread(target=self._recv_loop, daemon=True)
        self._tx = threading.Thread(target=self._send_loop, daemon=True)

    # -- framing ------------------------------------------------------------

    def _send(self, payload: bytes) -> None:
        with self._send_lock:
            self._sock.sendall(struct.pack("<I", len(payload)) + payload)

    def _recv(self) -> bytes | None:
        hdr = b""
        while len(hdr) < 4:
            chunk = self._sock.recv(4 - len(hdr))
            if not chunk:
                return None
            hdr += chunk
        (n,) = struct.unpack("<I", hdr)
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    # -- sync flow ----------------------------------------------------------

    def connect(self) -> None:
        """Send sync step 1 and start the reader/writer threads."""
        enc = Encoder()
        protocol.write_sync_step1(enc, self.doc)
        self._outbox.put(enc.to_bytes())
        self._rx.start()
        self._tx.start()

    def _on_local_update(self, update: bytes, origin, doc) -> None:
        if origin is self or self._closed:
            return  # don't echo remote updates back
        enc = Encoder()
        protocol.write_update(enc, update)
        self._outbox.put(enc.to_bytes())  # never blocks the editor

    def _send_loop(self) -> None:
        try:
            while True:
                payload = self._outbox.get()
                if payload is None:
                    break
                self._send(payload)
        except OSError:
            pass  # peer vanished: rx loop emits the close event

    def _recv_loop(self) -> None:
        try:
            while not self._closed:
                payload = self._recv()
                if payload is None:
                    break
                dec = Decoder(payload)
                enc = Encoder()
                # replies (our step 2) ride the outbox too; the doc
                # mutation happens under the shared doc lock
                with self.lock:
                    protocol.read_sync_message(dec, enc, self.doc, self)
                reply = enc.to_bytes()
                if reply:
                    self._outbox.put(reply)
        except (OSError, ValueError):
            pass  # peer vanished / malformed frame: fall through to close
        finally:
            self.emit("close", [])

    def close(self) -> None:
        self._closed = True
        self.doc.off("update", self._on_local_update)
        self._outbox.put(None)  # unblock the writer thread
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


def _demo(role: str, port: int) -> None:
    doc = Y.Doc(gc=False)
    doc.client_id = 1 if role == "serve" else 2
    text = doc.get_text("text")
    if role == "serve":
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(1)
        text.insert(0, "listener says hi. ")
        conn, _ = srv.accept()
    else:
        conn = socket.create_connection(("127.0.0.1", port))
        text.insert(0, "joiner says hi. ")

    connector = SocketConnector(doc, conn)
    connector.connect()

    import time

    time.sleep(1.0)  # let the handshake settle
    with connector.lock:  # doc access shares the lock with the rx thread
        text.insert(len(text.to_string()), f"[{role} concurrent edit]")
    time.sleep(1.0)
    with connector.lock:
        print(f"{role}: {text.to_string()!r}")
        print(f"{role}: sv={Y.encode_state_vector(doc).hex()}")
    connector.close()


if __name__ == "__main__":
    _demo(sys.argv[1], int(sys.argv[2]) if len(sys.argv) > 2 else 47800)
