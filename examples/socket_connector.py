"""SocketConnector: a TCP transport on the AbstractConnector base,
carried by the session layer (``yjs_tpu.sync.session``).

A second transport example beyond ``server_demo.py``'s in-process
provider: each peer binds one ``Y.Doc`` to a length-prefixed TCP framing
of the sync messages.  Since ISSUE 5 the frames ride a
:class:`~yjs_tpu.sync.session.SyncSession`, so this connector gets
ack-based retransmission, heartbeat/liveness detection, backpressure
coalescing, and the anti-entropy repair loop for free — while the inner
frames stay exactly what a JS ``y-websocket`` peer would exchange: a
peer that never speaks the session envelope is detected by its bare
step 1 and the session negotiates down to the plain protocol.

Run in two terminals (the first becomes the listener):

    python examples/socket_connector.py server 47800
    python examples/socket_connector.py client 47800

Both processes make concurrent edits and print the converged text.
Reference seams: src/utils/AbstractConnector.js:16-26 (the base),
y-protocols/sync.js (the message flow the protocol module mirrors).
"""

from __future__ import annotations

import os
import queue
import socket
import struct
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import yjs_tpu as Y
from yjs_tpu.sync.session import DocSessionHost, SessionConfig, SyncSession
from yjs_tpu.sync.transport import CallbackTransport
from yjs_tpu.utils.abstract_connector import AbstractConnector

# seconds of wall time per session tick: with the default knobs that
# makes a heartbeat every ~0.4s, a liveness timeout after ~1.6s of
# silence, and first retransmission of a lost frame after ~0.1s
TICK_SECONDS = 0.05


class SocketConnector(AbstractConnector):
    """Bind one doc to one TCP peer through a resumable session.

    The Doc is NOT thread-safe; the receive and ticker threads drive
    the session under ``self.lock``, and local edits from other threads
    must take the same lock (see ``_demo``)."""

    def __init__(
        self, ydoc: Y.Doc, sock: socket.socket, awareness=None,
        config: SessionConfig | None = None,
    ):
        super().__init__(ydoc, awareness)
        self._sock = sock
        self._send_lock = threading.Lock()
        #: guards every doc access (remote applies, local edits, reads)
        self.lock = threading.RLock()
        self._closed = False
        # outbound frames ride a queue drained by a writer thread: the
        # update handler fires while the editor holds self.lock, and
        # blocking in sendall there would deadlock two back-pressured
        # peers whose rx threads both wait on that lock
        self._outbox: "queue.Queue[bytes | None]" = queue.Queue()
        self._transport = CallbackTransport(self._enqueue)
        self.session = SyncSession(
            DocSessionHost(ydoc, origin=self),
            config=config,
            peer=f"fd{sock.fileno()}",
        )
        ydoc.on("update", self._on_local_update)
        self._rx = threading.Thread(target=self._recv_loop, daemon=True)
        self._tx = threading.Thread(target=self._send_loop, daemon=True)
        self._ticker = threading.Thread(target=self._tick_loop, daemon=True)

    # -- framing ------------------------------------------------------------

    def _enqueue(self, payload: bytes) -> None:
        self._outbox.put(bytes(payload))  # never blocks the editor

    def _send(self, payload: bytes) -> None:
        with self._send_lock:
            self._sock.sendall(struct.pack("<I", len(payload)) + payload)

    def _recv(self) -> bytes | None:
        hdr = b""
        while len(hdr) < 4:
            chunk = self._sock.recv(4 - len(hdr))
            if not chunk:
                return None
            hdr += chunk
        (n,) = struct.unpack("<I", hdr)
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    # -- sync flow ----------------------------------------------------------

    def connect(self) -> None:
        """Start the session handshake and the rx/tx/ticker threads."""
        with self.lock:
            self.session.connect(self._transport)
        self._rx.start()
        self._tx.start()
        self._ticker.start()
        self.on_connect()

    def _on_local_update(self, update: bytes, origin, doc) -> None:
        if origin is self or self._closed:
            return  # don't echo remote updates back
        # the editor already holds self.lock (RLock: re-entry is fine)
        with self.lock:
            self.session.send_update(update)

    def _send_loop(self) -> None:
        try:
            while True:
                payload = self._outbox.get()
                if payload is None:
                    break
                self._send(payload)
        except OSError as e:
            self.on_error(e)  # peer vanished: rx loop emits the close

    def _recv_loop(self) -> None:
        reason = "eof"
        try:
            while not self._closed:
                payload = self._recv()
                if payload is None:
                    break
                with self.lock:
                    self._transport.deliver(payload)
        except (OSError, ValueError) as e:
            reason = f"error: {type(e).__name__}"
            self.on_error(e)
        finally:
            self.emit("close", [])
            self.on_disconnect(reason)

    def _tick_loop(self) -> None:
        # session time advances on a fixed wall cadence; everything the
        # tick drives (retransmit backoff, heartbeats, liveness, the
        # anti-entropy digests) counts in these ticks
        import time

        while not self._closed:
            time.sleep(TICK_SECONDS)
            with self.lock:
                if self._closed:
                    break
                self.session.tick()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.doc.off("update", self._on_local_update)
        with self.lock:
            self.session.close()
        self._outbox.put(None)  # unblock the writer thread
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()
        self.on_disconnect("closed")


def _demo(role: str, port: int) -> None:
    doc = Y.Doc(gc=False)
    doc.client_id = 1 if role == "server" else 2
    text = doc.get_text("text")
    if role == "server":
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(1)
        text.insert(0, "listener says hi. ")
        conn, _ = srv.accept()
    else:
        conn = socket.create_connection(("127.0.0.1", port))
        text.insert(0, "joiner says hi. ")

    connector = SocketConnector(doc, conn)
    connector.connect()

    import time

    time.sleep(1.0)  # let the handshake settle
    with connector.lock:  # doc access shares the lock with the rx thread
        text.insert(len(text.to_string()), f"[{role} concurrent edit]")
    time.sleep(1.0)
    with connector.lock:
        print(f"{role}: {text.to_string()!r}")
        print(f"{role}: sv={Y.encode_state_vector(doc).hex()}")
        print(f"{role}: session={connector.session.snapshot()}")
    connector.close()


if __name__ == "__main__":
    if len(sys.argv) < 2 or sys.argv[1] not in ("server", "client"):
        print(f"usage: {sys.argv[0]} server|client [port]")
        sys.exit(2)
    _demo(sys.argv[1], int(sys.argv[2]) if len(sys.argv) > 2 else 47800)
