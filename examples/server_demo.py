"""A miniature collaboration server on the y-tpu Provider.

Runs entirely in-process: N rooms, two simulated Yjs-wire clients per
room editing concurrently, the y-protocols 3-message handshake for a
late joiner, typed change events, and rich exports — the end-to-end
product loop (reference seams: README.md:101-137 providers,
INTERNALS.md:145-166 sync).

    JAX_PLATFORMS=cpu python examples/server_demo.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import yjs_tpu as Y  # noqa: E402
from yjs_tpu.provider import TpuProvider  # noqa: E402


def main(n_rooms: int = 8) -> None:
    server = TpuProvider(n_docs=n_rooms)
    broadcasts: list[tuple[str, bytes]] = []
    server.on_update(lambda guid, u: broadcasts.append((guid, u)))
    server.observe(
        "room-0", ["text"],
        lambda guid, ev: print(f"  event {guid}: delta={ev['delta']}"),
    )

    # two clients per room edit concurrently, server integrates in batches
    clients = {}
    for r in range(n_rooms):
        guid = f"room-{r}"
        a = Y.Doc(gc=False); a.client_id = 100 + r
        b = Y.Doc(gc=False); b.client_id = 200 + r
        clients[guid] = (a, b)
        a.get_text("text").insert(0, f"[{guid}] alice says hi. ")
        b.get_text("text").insert(0, f"[{guid}] bob says yo. ")
        b.get_text("text").format(0, 5, {"bold": True})
        b.get_map("meta").set("topic", f"demo-{r}")
        for d in (a, b):
            server.receive_update(guid, Y.encode_state_as_update(d))
    server.flush()  # ONE batched device step for every room
    print(f"flushed {n_rooms} rooms: "
          f"{server.metrics['n_docs_flushed']} integrated, "
          f"{len(broadcasts)} update broadcasts queued")

    # keep the clients in sync from the server's broadcasts
    for guid, update in broadcasts:
        for d in clients[guid]:
            Y.apply_update(d, update)

    # a late joiner syncs with the y-protocols handshake: it announces its
    # (empty) state vector, the server answers with the missing diff
    from yjs_tpu.lib0.decoding import Decoder
    from yjs_tpu.lib0.encoding import Encoder
    from yjs_tpu.lib0 import decoding
    from yjs_tpu.sync import protocol

    joiner = Y.Doc(gc=False)
    e = Encoder()
    protocol.write_sync_step1(e, joiner)
    server_reply = server.handle_sync_message("room-0", e.to_bytes())
    d = Decoder(server_reply)
    assert decoding.read_var_uint(d) == protocol.MESSAGE_YJS_SYNC_STEP_2
    Y.apply_update(joiner, decoding.read_var_uint8_array(d))

    a, _b = clients["room-0"]
    assert joiner.get_text("text").to_string() == a.get_text("text").to_string()
    print(f"late joiner converged: {joiner.get_text('text').to_string()!r}")
    print(f"rich delta: {server.to_delta('room-0')}")
    print(f"meta: {server.engine.map_json(server.doc_id('room-0'), 'meta')}")
    print("demo OK")


if __name__ == "__main__":
    main()
