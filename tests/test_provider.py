"""TpuProvider: CPU clients syncing against the batched device backend with
randomized delivery — the provider-boundary fuzz of SURVEY.md §4.2-4.3."""

import random

import pytest

import yjs_tpu as Y
from yjs_tpu.provider import TpuProvider


def client_edit(gen, doc):
    t = doc.get_text("text")
    ln = len(t.to_string())
    if gen.random() < 0.7 or ln == 0:
        t.insert(gen.randint(0, ln), gen.choice(["x", "yy", "zzz", "🙂", "word "]))
    else:
        pos = gen.randrange(ln)
        t.delete(pos, min(gen.randint(1, 3), ln - pos))


class TestProvider:
    def test_single_room_two_clients(self):
        prov = TpuProvider(4)
        a = Y.Doc(gc=False)
        a.client_id = 1
        b = Y.Doc(gc=False)
        b.client_id = 2
        a.get_text("text").insert(0, "from-a ")
        b.get_text("text").insert(0, "from-b ")
        prov.receive_update("room", Y.encode_state_as_update(a))
        prov.receive_update("room", Y.encode_state_as_update(b))
        # handshake: each client syncs down the provider's merged state
        for d in (a, b):
            reply = prov.handle_sync_message("room", _step1(d))
            _apply_step2(d, reply)
        assert a.get_text("text").to_string() == b.get_text("text").to_string()
        assert prov.text("room") == a.get_text("text").to_string()

    def test_many_rooms_batched(self):
        n = 8
        prov = TpuProvider(n)
        docs = []
        for i in range(n):
            d = Y.Doc(gc=False)
            d.client_id = 100 + i
            d.get_text("text").insert(0, f"room-{i} content")
            docs.append(d)
            prov.receive_update(f"room{i}", Y.encode_state_as_update(d))
        prov.flush()
        for i, d in enumerate(docs):
            assert prov.text(f"room{i}") == d.get_text("text").to_string()

    def test_unsupported_room_falls_back(self):
        prov = TpuProvider(2)
        d = Y.Doc(gc=False)
        d.client_id = 5
        d.get_map("meta").set("sub", Y.Doc(guid="child"))  # ContentDoc
        d.get_text("text").insert(0, "t")
        prov.receive_update("mixed", Y.encode_state_as_update(d))
        prov.flush()
        assert prov.n_fallback_docs == 1
        assert prov.text("mixed") == "t"
        # the demotion is visible with its reason, not silent
        assert prov.demotions == [
            {"guid": "mixed", "reason": "subdocument (content ref 9)"}
        ]
        assert prov.metrics["n_demoted"] == 1

    def test_backend_cpu_serves_everything_without_device(self):
        prov = TpuProvider(2, backend="cpu")
        d = Y.Doc(gc=False)
        d.client_id = 5
        d.get_text("text").insert(0, "cpu-only")
        d.get_map("m").set("sub", Y.Doc(guid="child"))  # fine on CPU
        prov.receive_update("room", Y.encode_state_as_update(d))
        prov.flush()
        assert prov.text("room") == "cpu-only"
        assert prov.n_fallback_docs == 1  # lazily, only the allocated room
        assert prov.demotions == []  # by configuration, not by gap

    def test_backend_device_forbids_fallback(self):
        import pytest as _pytest

        prov = TpuProvider(2, backend="device")
        ok = Y.Doc(gc=False)
        ok.client_id = 6
        ok.get_text("text").insert(0, "fine")
        prov.receive_update("a", Y.encode_state_as_update(ok))
        prov.flush()
        assert prov.text("a") == "fine"
        bad = Y.Doc(gc=False)
        bad.client_id = 7
        bad.get_map("m").set("sub", Y.Doc(guid="child"))
        prov.receive_update("b", Y.encode_state_as_update(bad))
        with _pytest.raises(RuntimeError, match="forbids CPU fallback"):
            prov.flush()
        # the alert persists on every flush while the demotion exists —
        # not a one-shot warning (data stays served by the CPU core)
        prov.receive_update("a", Y.encode_state_as_update(ok))
        with _pytest.raises(RuntimeError, match="forbids CPU fallback"):
            prov.flush()

    def test_nested_room_stays_on_device(self):
        prov = TpuProvider(2)
        d = Y.Doc(gc=False)
        d.client_id = 5
        inner = Y.YMap()
        d.get_map("meta").set("nested", inner)
        inner.set("x", 1)
        prov.receive_update("room", Y.encode_state_as_update(d))
        prov.flush()
        assert prov.n_fallback_docs == 0
        assert prov.engine.map_json(0, "meta") == {"nested": {"x": 1}}

    def test_flush_metrics_phases_and_occupancy(self):
        prov = TpuProvider(4)
        for room in ("r0", "r1"):
            d = Y.Doc(gc=False)
            d.client_id = 7
            d.get_text("text").insert(0, "hello")
            prov.receive_update(room, Y.encode_state_as_update(d))
        prov.flush()
        m = prov.metrics
        assert m["n_docs_flushed"] == 2
        assert m["n_demoted"] == 0 and m["n_fallback_docs"] == 0
        assert m["n_sched_entries"] >= 2
        assert 0.0 < m["schedule_occupancy"] <= 1.0
        assert m["n_pending_docs"] == 0 and m["pending_depth"] == 0
        for k in ("t_compact_s", "t_plan_s", "t_pack_s", "t_dispatch_s",
                  "t_emit_s", "t_total_s"):
            assert m[k] >= 0.0
        assert m["t_total_s"] >= m["t_plan_s"]

    def test_map_room_served_on_device(self):
        prov = TpuProvider(2)
        a = Y.Doc(gc=False)
        a.client_id = 5
        b = Y.Doc(gc=False)
        b.client_id = 6
        a.get_map("meta").set("k", 1)
        a.get_text("text").insert(0, "t")
        b.get_map("meta").set("k", 2)  # concurrent LWW conflict
        prov.receive_update("room", Y.encode_state_as_update(a))
        prov.receive_update("room", Y.encode_state_as_update(b))
        prov.flush()
        assert prov.n_fallback_docs == 0
        # both clients sync down; all three agree on the LWW winner
        for d in (a, b):
            _apply_step2(d, prov.handle_sync_message("room", _step1(d)))
        assert a.get_map("meta").to_json() == b.get_map("meta").to_json()
        assert prov.engine.map_json(prov.doc_id("room"), "meta") == \
            a.get_map("meta").to_json()

    @pytest.mark.parametrize("seed", range(4))
    def test_fuzz_random_delivery(self, seed):
        gen = random.Random(seed)
        n_clients = 3
        prov = TpuProvider(2)
        docs = [Y.Doc(gc=False) for _ in range(n_clients)]
        queues = [[] for _ in range(n_clients)]  # provider -> nothing; client updates
        for i, d in enumerate(docs):
            d.client_id = 10 + i
            d.on("update", lambda u, o, dd, i=i: queues[i].append(u))
        for _ in range(60):
            i = gen.randrange(n_clients)
            client_edit(gen, docs[i])
            if gen.random() < 0.4:
                # deliver a random prefix of a random client's updates
                src = gen.randrange(n_clients)
                if queues[src]:
                    k = gen.randint(1, len(queues[src]))
                    picks = gen.sample(queues[src], k)  # random order + subset
                    for u in picks:
                        prov.receive_update("room", u)
            if gen.random() < 0.3:
                prov.flush()
        # final: everything reaches the provider, clients sync down
        for q in queues:
            for u in q:
                prov.receive_update("room", u)
        prov.flush()
        for d in docs:
            reply = prov.handle_sync_message("room", _step1(d))
            _apply_step2(d, reply)
            # push anything the provider missed (none expected) then compare
        texts = {d.get_text("text").to_string() for d in docs}
        assert len(texts) == 1
        assert prov.text("room") in texts
        assert not prov.engine.has_pending(prov.doc_id("room"))


def _step1(doc):
    from yjs_tpu.lib0.encoding import Encoder
    from yjs_tpu.sync import protocol

    enc = Encoder()
    protocol.write_sync_step1(enc, doc)
    return enc.to_bytes()


def _apply_step2(doc, reply):
    from yjs_tpu.lib0.decoding import Decoder
    from yjs_tpu.lib0.encoding import Encoder
    from yjs_tpu.sync import protocol

    protocol.read_sync_message(Decoder(reply), Encoder(), doc)


class TestUpdateEmission:
    """VERDICT item 7: after flush() the engine emits per-doc incremental
    updates (reference Transaction.js:339-352) so a server can broadcast
    to peers; a third replica stays in sync purely from emitted updates."""

    def test_observer_replica_syncs_from_emissions_only(self):
        gen = random.Random(7)
        prov = TpuProvider(2)
        observer = Y.Doc(gc=False)
        observer.client_id = 999
        prov.on_update(
            lambda guid, u: Y.apply_update(observer, u) if guid == "room" else None
        )
        a = Y.Doc(gc=False)
        a.client_id = 1
        b = Y.Doc(gc=False)
        b.client_id = 2
        pending = []
        for d in (a, b):
            d.on("update", lambda u, o, dd: pending.append(u))
        for step in range(30):
            client_edit(gen, gen.choice((a, b)))
            a_map = a.get_map("meta")
            if gen.random() < 0.3:
                a_map.set(gen.choice("xyz"), step)
            if gen.random() < 0.5 and pending:
                gen.shuffle(pending)
                for u in pending:
                    prov.receive_update("room", u)
                pending.clear()
                prov.flush()
        for u in pending:
            prov.receive_update("room", u)
        prov.flush()
        # the observer NEVER talked to the provider: emissions only
        i = prov.doc_id("room")
        assert observer.get_text("text").to_string() == prov.text("room")
        assert observer.get_map("meta").to_json() == prov.engine.map_json(i, "meta")
        assert not observer.store.pending_clients_struct_refs
        assert not observer.store.pending_stack

    def test_emission_after_demotion_keeps_flowing(self):
        prov = TpuProvider(2)
        observer = Y.Doc(gc=False)
        observer.client_id = 998
        prov.on_update(lambda guid, u: Y.apply_update(observer, u))
        d = Y.Doc(gc=False)
        d.client_id = 3
        d.get_text("text").insert(0, "pre ")
        prov.receive_update("r", Y.encode_state_as_update(d))
        prov.flush()
        # demote mid-stream with a subdocument, then keep editing
        d.get_map("m").set("sub", Y.Doc(guid="child"))
        sv = Y.encode_state_vector(d)
        prov.receive_update("r", Y.encode_state_as_update(d, None))
        prov.flush()
        assert prov.n_fallback_docs == 1
        d.get_text("text").insert(4, "post")
        prov.receive_update("r", Y.encode_state_as_update(d, sv))
        prov.flush()
        assert observer.get_text("text").to_string() == d.get_text("text").to_string()


def test_server_demo_runs():
    """examples/server_demo.py is the documented end-to-end product loop;
    keep it green."""
    import examples.server_demo as demo

    demo.main(n_rooms=4)
