"""Replication + failure-detection suite (ISSUE 8): journal-only
replica fan-out, the tick-deterministic heartbeat detector, freshest-
replica promotion under a fencing epoch, and the stale-primary fencing
paths (revival and WAL recovery).

Everything is seeded and tick-driven.  In tier-1 under the ``failover``
+ ``fleet`` markers; ``scripts/ci_check.sh`` runs the ``failover``
marker standalone as the newest-subsystem smoke.
"""

import random

import pytest

import yjs_tpu as Y
from yjs_tpu.fleet import (
    FailoverConfig,
    FailureDetector,
    FleetRouter,
    ReplicationConfig,
    ShardDownError,
)
from yjs_tpu.persistence import WalConfig
from yjs_tpu.provider import TpuProvider
from yjs_tpu.sync.session import SessionConfig
from yjs_tpu.sync.transport import PipeNetwork
from yjs_tpu.updates import encode_state_as_update, encode_state_vector

pytestmark = [pytest.mark.failover, pytest.mark.fleet]

SMALL = WalConfig(segment_bytes=256, fsync="never")

# jitter off + tight thresholds: conviction lands on an exact tick
FAST = FailoverConfig(suspect_ticks=2, confirm_ticks=1, jitter_ticks=0)


def quiet_config(**kw):
    base = dict(
        heartbeat=0, liveness=0, antientropy=0, hello_timeout=0,
        retry_base=4, retry_jitter=0.0, seed=1,
    )
    base.update(kw)
    return SessionConfig(**base)


def update_for(text, client_id=99):
    d = Y.Doc(gc=False)
    d.client_id = client_id
    d.get_text("text").insert(0, text)
    return encode_state_as_update(d)


def edit(doc, text, pos=0):
    sv = encode_state_vector(doc)
    doc.get_text("text").insert(pos, text)
    return encode_state_as_update(doc, sv)


def seeded_rooms(seed, n_rooms=6, n_ops=10):
    out = {}
    for j in range(n_rooms):
        gen = random.Random(seed * 1000 + j)
        d = Y.Doc(gc=False)
        d.client_id = 100 + j
        updates = []
        d.on("update", lambda u, origin, doc: updates.append(bytes(u)))
        t = d.get_text("text")
        for _ in range(n_ops):
            if len(t) and gen.random() < 0.3:
                t.delete(gen.randrange(len(t)), 1)
            else:
                t.insert(gen.randrange(len(t) + 1), gen.choice("abcdef "))
        out[f"room-{j}"] = (d, updates)
    return out


def slot_owners(fleet):
    out = {}
    for k, p in enumerate(fleet.shards):
        if fleet._is_stub(k):
            continue
        for g in p.guids():
            out.setdefault(g, []).append(k)
    return out


def convict(fleet, shard, budget=16):
    """Tick until the detector confirms ``shard`` dead (and the
    coordinator has failed it over)."""
    for _ in range(budget):
        fleet.tick()
        if shard in fleet._down:
            return
    raise AssertionError(f"shard {shard} never convicted")


def crash(fleet):
    for k, p in enumerate(fleet.shards):
        if not fleet._is_stub(k):
            p.wal.abandon()


# -- metric surface ----------------------------------------------------------


def test_repl_and_failover_metric_families_register():
    fleet = FleetRouter(1, 1, backend="cpu")
    names = set(fleet.metrics.registry.names())
    for n in (
        "ytpu_repl_records_total",
        "ytpu_repl_outbox_depth",
        "ytpu_repl_lag",
        "ytpu_repl_replica_docs",
        "ytpu_repl_backpressure_total",
        "ytpu_repl_reseeds_total",
        "ytpu_repl_stalls_total",
        "ytpu_failover_heartbeats_total",
        "ytpu_failover_shard_state",
        "ytpu_failover_suspects_total",
        "ytpu_failover_deaths_total",
        "ytpu_failover_promotions_total",
        "ytpu_failover_fenced_total",
        "ytpu_failover_seconds",
        "ytpu_failover_unavailable_ticks",
    ):
        assert n in names, n


# -- replication fan-out -----------------------------------------------------


def test_fanout_journals_replica_copies(tmp_path):
    fleet = FleetRouter(
        3, 4, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
        failover_config=FAST,
    )
    for g, (_d, ups) in seeded_rooms(seed=3).items():
        for u in ups:
            fleet.receive_update(g, u)
    fleet.flush()
    fleet.repl.repair_all()
    snap = fleet.repl.snapshot()
    # every accepted doc has exactly ``factor`` replica copies and the
    # outbox fully drained (lag zero once repaired)
    assert snap["factor"] == 1
    assert sum(snap["replica_docs"].values()) == snap["docs_tracked"] == 6
    assert all(v == 0 for v in snap["lag"].values())
    pairs = set(fleet.repl._applied) | fleet.repl._marked
    for g in [f"room-{j}" for j in range(6)]:
        holders = {s for (g2, s) in pairs if g2 == g}
        assert len(holders) == 1
        assert fleet.owner_of(g) not in holders


def test_outbox_backpressure_drains_inline_never_drops(tmp_path):
    fleet = FleetRouter(
        2, 8, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
        repl_config=ReplicationConfig(outbox_max=2, batch=1),
        failover_config=FAST,
    )
    d = Y.Doc(gc=False)
    d.client_id = 7
    for i in range(12):
        fleet.receive_update("room", edit(d, f"{i} "))
    fleet.flush()
    snap = fleet.metrics_snapshot()
    assert snap["counters"]["ytpu_repl_backpressure_total"].get("", 0) > 0
    # despite the tiny outbox nothing was dropped: the replica holds
    # the full history, so killing the primary loses no acked update
    owner = fleet.owner_of("room")
    fleet.kill_shard(owner)
    convict(fleet, owner)
    assert fleet.owner_of("room") != owner
    assert fleet.text("room") == str(d.get_text("text"))


# -- failure detector --------------------------------------------------------


def test_detector_timeline_is_tick_exact_without_jitter():
    det = FailureDetector(
        range(2), config=FailoverConfig(
            suspect_ticks=3, confirm_ticks=2, jitter_ticks=0,
        ),
    )
    timeline = []
    for _ in range(6):
        timeline += det.tick(lambda k: k != 1)
    # shard 1: suspect after exactly 3 misses, dead after 2 more
    assert timeline == [(1, "alive", "suspect"), (1, "suspect", "dead")]
    assert det.state_of(0) == "alive" and det.state_of(1) == "dead"


def test_detector_jitter_is_seed_deterministic():
    cfg = FailoverConfig(suspect_ticks=3, confirm_ticks=2,
                         jitter_ticks=2, seed=42)
    runs = []
    for _ in range(2):
        det = FailureDetector(range(4), config=cfg)
        events = []
        for _ in range(12):
            events += det.tick(lambda k: False)
        runs.append(events)
    assert runs[0] == runs[1]
    # jitter decorrelates: not every shard flips on the same tick —
    # group events by transition and check the per-shard orderings
    # aren't all identical positions
    death_order = [e[0] for e in runs[0] if e[2] == "dead"]
    assert sorted(death_order) == [0, 1, 2, 3]


def test_suspect_acquitted_by_good_probe():
    det = FailureDetector(
        range(1), config=FailoverConfig(
            suspect_ticks=2, confirm_ticks=2, jitter_ticks=0,
        ),
    )
    det.tick(lambda k: False)
    det.tick(lambda k: False)
    assert det.state_of(0) == "suspect"
    det.tick(lambda k: True)  # one good heartbeat clears the strike
    assert det.state_of(0) == "alive"
    det.tick(lambda k: False)
    assert det.state_of(0) == "alive"  # counter restarted from zero


# -- failover ----------------------------------------------------------------


def test_failover_promotes_replica_and_bumps_epoch(tmp_path):
    fleet = FleetRouter(
        3, 4, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
        failover_config=FAST,
    )
    rooms = seeded_rooms(seed=8)
    for g, (_d, ups) in rooms.items():
        for u in ups:
            fleet.receive_update(g, u)
    fleet.flush()
    fleet.tick()  # drain the replication outbox
    victim = fleet.owner_of("room-0")
    owned = [g for g in rooms if fleet.owner_of(g) == victim]
    epoch0 = fleet.table.epoch
    fleet.kill_shard(victim)
    convict(fleet, victim)
    assert fleet.table.epoch > epoch0
    roles = {r["shard"]: r["role"] for r in
             fleet.fleet_snapshot()["shards"]}
    assert roles[victim] == "dead"
    for g in owned:
        k = fleet.owner_of(g)
        assert k is not None and k != victim
        # byte-identical against the uninterrupted reference doc
        ref = Y.merge_updates([encode_state_as_update(rooms[g][0])])
        assert Y.merge_updates([fleet.encode_state_as_update(g)]) == ref
    # exactly one engine slot per doc after promotion
    owners = slot_owners(fleet)
    assert all(len(v) == 1 for g, v in owners.items() if g in rooms)
    snap = fleet.metrics_snapshot()
    assert snap["counters"]["ytpu_failover_deaths_total"].get("", 0) >= 1
    assert (
        snap["counters"]["ytpu_failover_promotions_total"]
        .get("outcome=promoted", 0) >= len(owned)
    )
    # and the recovered fleet keeps taking traffic on the moved doc
    fleet.receive_update("room-0", edit(rooms["room-0"][0], "after!"))
    assert "after" in fleet.text("room-0")


def test_unreplicated_update_survives_synchronous_absorb(tmp_path):
    """An update accepted the instant before (or after) the primary
    dies is journaled synchronously on the replica set — acknowledged
    means durable, even with the outbox never drained."""
    fleet = FleetRouter(
        3, 4, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
        failover_config=FAST,
    )
    d = Y.Doc(gc=False)
    d.client_id = 5
    fleet.receive_update("room", edit(d, "base "))
    victim = fleet.owner_of("room")
    # kill with the outbox still holding the only copy: no tick has run
    fleet.kill_shard(victim)
    # the stub raises ShardDownError; receive_update absorbs onto the
    # replicas instead of losing the write
    fleet.receive_update("room", edit(d, "late ", pos=5))
    convict(fleet, victim)
    assert fleet.text("room") == str(d.get_text("text"))
    assert "late" in fleet.text("room")


def test_stale_primary_is_fenced_on_revival(tmp_path):
    fleet = FleetRouter(
        3, 4, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
        failover_config=FAST,
    )
    d = Y.Doc(gc=False)
    d.client_id = 9
    fleet.receive_update("room", edit(d, "hello "))
    fleet.flush()
    fleet.tick()
    victim = fleet.owner_of("room")
    fleet.kill_shard(victim)
    convict(fleet, victim)
    survivor = fleet.owner_of("room")
    fleet.receive_update("room", edit(d, "world ", pos=6))
    fleet.flush()
    # the old machine comes back with its stale copy: it must be
    # fenced (its claim merged into the current owner), never a second
    # primary
    res = fleet.revive_shard(victim)
    assert "room" in res["fenced"]
    assert fleet.owner_of("room") == survivor
    owners = slot_owners(fleet)
    assert owners.get("room") == [survivor]
    assert fleet.text("room") == str(d.get_text("text"))
    snap = fleet.metrics_snapshot()
    assert snap["counters"]["ytpu_failover_fenced_total"].get("", 0) >= 1


def test_recover_resolves_primary_claims_by_epoch(tmp_path):
    """Crash the whole fleet after a failover: WAL recovery must elect
    the highest-epoch primary claim and fold the stale one."""
    fleet = FleetRouter(
        3, 4, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
        failover_config=FAST,
    )
    d = Y.Doc(gc=False)
    d.client_id = 11
    fleet.receive_update("room", edit(d, "pre "))
    fleet.flush()
    fleet.tick()
    victim = fleet.owner_of("room")
    fleet.kill_shard(victim)
    convict(fleet, victim)
    survivor = fleet.owner_of("room")
    fleet.receive_update("room", edit(d, "post ", pos=4))
    fleet.flush()
    crash(fleet)
    del fleet
    rec = FleetRouter.recover(
        tmp_path, backend="cpu", wal_config=SMALL,
    )
    # the victim's WAL still claims the doc at the old epoch; the
    # survivor's primary role marker carries the post-failover epoch
    assert rec.owner_of("room") == survivor
    owners = slot_owners(rec)
    assert owners.get("room") == [survivor]
    assert rec.text("room") == str(d.get_text("text"))
    res = rec.last_recovery["resolution"]
    assert res["fenced"] >= 1


def test_checkpoint_reseeds_replicas(tmp_path):
    """WAL compaction folds only owned docs — the fleet checkpoint must
    re-seed every replica pair so promotion still has the full state."""
    fleet = FleetRouter(
        3, 4, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
        failover_config=FAST,
    )
    d = Y.Doc(gc=False)
    d.client_id = 13
    fleet.receive_update("room", edit(d, "kept across checkpoint"))
    fleet.flush()
    fleet.tick()
    fleet.checkpoint()
    snap = fleet.metrics_snapshot()
    assert snap["counters"]["ytpu_repl_reseeds_total"].get("", 0) >= 1
    victim = fleet.owner_of("room")
    fleet.kill_shard(victim)
    convict(fleet, victim)
    assert fleet.text("room") == "kept across checkpoint"


# -- satellite: placement never targets unhealthy shards ---------------------


def test_drain_and_rebalance_skip_suspect_shards(tmp_path):
    fleet = FleetRouter(
        3, 8, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
        failover_config=FailoverConfig(
            suspect_ticks=1, confirm_ticks=8, jitter_ticks=0,
        ),
    )
    for g, (_d, ups) in seeded_rooms(seed=4).items():
        for u in ups:
            fleet.receive_update(g, u)
    fleet.flush()
    # one missed probe turns shard 2 suspect (but far from dead)
    fleet.detector.tick(lambda k: k != 2)
    assert fleet.detector.state_of(2) == "suspect"
    before = {g: fleet.owner_of(g) for g in slot_owners(fleet)}
    src = next(k for k in (0, 1) if any(v == k for v in before.values()))
    moved = fleet.drain_shard(src)
    assert moved == sum(1 for v in before.values() if v == src)
    # every migrated doc landed on the one healthy destination
    for g, k0 in before.items():
        if k0 == src:
            assert fleet.owner_of(g) not in (src, 2)
    assert all(d["dst"] != 2 for d in fleet.rebalancer.plan())


# -- satellite: sessions resume (not resync) across recovery ----------------


def _drive(*providers):
    def fn():
        for p in providers:
            p.flush()
        for p in providers:
            p.tick_sessions()

    return fn


def test_session_survives_failover_without_full_resync(tmp_path):
    """The failover-path resume pin: the primary dies under a live
    session; rehome onto the promoted shard keeps the session live —
    no reconnect, no second full resync."""
    fleet = FleetRouter(
        3, 4, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
        failover_config=FAST,
    )
    peer = TpuProvider(1, backend="cpu")
    net = PipeNetwork()
    tf, tp = net.pair()
    sf = fleet.session("room", "peer", quiet_config(antientropy=2))
    sp = peer.session("room", "fleet", quiet_config(antientropy=2))
    sf.connect(tf)
    sp.connect(tp)
    net.settle((_drive(fleet, peer),))
    peer.receive_update("room", update_for("pre-failover "))
    net.settle((_drive(fleet, peer),))
    assert fleet.text("room") == "pre-failover "
    fleet.flush()
    fleet.tick()
    victim = fleet.owner_of("room")
    fleet.kill_shard(victim)
    convict(fleet, victim)
    assert sf.routing_epoch == fleet.table.epoch
    assert not sf._closed and sf.state == "live"
    net.settle((_drive(fleet, peer),), max_rounds=80, idle_rounds=3)
    peer.receive_update("room", update_for("post-failover", client_id=3))
    net.settle((_drive(fleet, peer),), max_rounds=80, idle_rounds=3)
    assert "post-failover" in fleet.text("room")
    assert fleet.text("room") == peer.text("room")
    assert sf.n_full_resyncs == 1 and sp.n_full_resyncs == 1


def test_session_resumes_after_fleet_recovery(tmp_path):
    """The recovery-path resume pin (satellite 1): a fleet killed and
    rebuilt from its WALs re-arms sessions with the journaled receive
    floor — the surviving peer RESUMES (``ytpu_net_resumes_total``
    increments, ``full_resyncs`` stays 1)."""
    cfg = quiet_config()
    fleet = FleetRouter(
        2, 4, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
        failover_config=FAST,
    )
    peer = TpuProvider(2, backend="cpu")
    net = PipeNetwork()
    tf, tp = net.pair()
    fleet.session("room", "peer", cfg).connect(tf)
    s2 = peer.session("room", "fleet", cfg)
    s2.connect(tp)
    net.settle((_drive(fleet, peer),))
    peer.receive_update("room", update_for("before crash"))
    net.settle((_drive(fleet, peer),))
    assert fleet.text("room") == "before crash"
    net.kill(tf, tp)
    crash(fleet)
    del fleet
    # the survivor keeps editing while the fleet is down
    peer.receive_update("room", update_for("offline edit / ", client_id=3))
    rec = FleetRouter.recover(tmp_path, backend="cpu", wal_config=SMALL)
    sr = rec.session("room", "peer", cfg)  # armed with the WAL ack floor
    tf2, tp2 = net.pair()
    sr.connect(tf2)
    s2.attach(tp2)
    net.settle((_drive(rec, peer),))
    assert rec.text("room") == peer.text("room")
    assert "offline edit" in rec.text("room")
    assert s2.n_resumes == 1
    assert s2.n_full_resyncs == 1
    # the pin lands in the metric family too (survivor's registry)
    snap = peer.metrics_snapshot()
    assert snap["counters"]["ytpu_net_resumes_total"].get("", 0) >= 1
