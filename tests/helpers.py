"""Multi-client simulation without a network + replica-equality oracle.

Ports the reference's test strategy (SURVEY.md §4, reference
tests/testHelper.js): an in-memory connector buffers per-sender messages,
delivers them in PRNG-chosen random order through the real sync protocol,
and can disconnect/reconnect random clients.  ``compare()`` is the
gold-standard convergence check (struct-by-struct store identity).
"""

from __future__ import annotations

import random

import yjs_tpu as Y
from yjs_tpu.core import (
    Item,
    create_delete_set_from_struct_store,
    get_state_vector,
)
from yjs_tpu.ids import compare_ids
from yjs_tpu.lib0.decoding import Decoder
from yjs_tpu.lib0.encoding import Encoder
from yjs_tpu.lib0.u16 import to_u16
from yjs_tpu.sync import protocol as sync


def broadcast_message(y: "TestYInstance", m: bytes) -> None:
    if y in y.tc.online_conns:
        for remote in list(y.tc.online_conns):
            if remote is not y:
                remote._receive(m, y)


class TestYInstance(Y.Doc):
    def __init__(self, test_connector: "TestConnector", client_id: int):
        super().__init__()
        self.user_id = client_id
        self.tc = test_connector
        self.receiving: dict[TestYInstance, list[bytes]] = {}
        test_connector.all_conns.add(self)

        def _on_update(update, origin, _doc):
            if origin is not test_connector:
                encoder = Encoder()
                sync.write_update(encoder, update)
                broadcast_message(self, encoder.to_bytes())

        self.on("update", _on_update)
        self.connect()

    def disconnect(self) -> None:
        self.receiving = {}
        self.tc.online_conns.discard(self)

    def connect(self) -> None:
        if self not in self.tc.online_conns:
            self.tc.online_conns.add(self)
            encoder = Encoder()
            sync.write_sync_step1(encoder, self)
            broadcast_message(self, encoder.to_bytes())
            for remote in list(self.tc.online_conns):
                if remote is not self:
                    enc = Encoder()
                    sync.write_sync_step1(enc, remote)
                    self._receive(enc.to_bytes(), remote)

    def _receive(self, message: bytes, remote_client: "TestYInstance") -> None:
        self.receiving.setdefault(remote_client, []).append(message)


class TestConnector:
    def __init__(self, gen: random.Random):
        self.all_conns: set[TestYInstance] = set()
        self.online_conns: set[TestYInstance] = set()
        self.prng = gen

    def create_y(self, client_id: int) -> TestYInstance:
        return TestYInstance(self, client_id)

    def flush_random_message(self) -> bool:
        gen = self.prng
        conns = sorted(
            (c for c in self.online_conns if c.receiving),
            key=lambda c: c.user_id,
        )
        if conns:
            receiver = gen.choice(conns)
            sender, messages = gen.choice(
                sorted(receiver.receiving.items(), key=lambda e: e[0].user_id)
            )
            m = messages.pop(0)
            if not messages:
                del receiver.receiving[sender]
            encoder = Encoder()
            # replies produced while processing are not re-broadcast
            sync.read_sync_message(Decoder(m), encoder, receiver, receiver.tc)
            if len(encoder) > 0:
                sender._receive(encoder.to_bytes(), receiver)
            return True
        return False

    def flush_all_messages(self) -> bool:
        did_something = False
        while self.flush_random_message():
            did_something = True
        return did_something

    def reconnect_all(self) -> None:
        for conn in list(self.all_conns):
            conn.connect()

    def disconnect_all(self) -> None:
        for conn in list(self.all_conns):
            conn.disconnect()

    def sync_all(self) -> None:
        self.reconnect_all()
        self.flush_all_messages()

    def disconnect_random(self) -> bool:
        if not self.online_conns:
            return False
        self.prng.choice(sorted(self.online_conns, key=lambda c: c.user_id)).disconnect()
        return True

    def reconnect_random(self) -> bool:
        reconnectable = sorted(
            (c for c in self.all_conns if c not in self.online_conns),
            key=lambda c: c.user_id,
        )
        if not reconnectable:
            return False
        self.prng.choice(reconnectable).connect()
        return True


def init(gen: random.Random, users: int = 5):
    """Build N synced clients; the encoding version (V1/V2) is chosen at
    random for the whole run (reference testHelper.js:233-263)."""
    result = {"users": []}
    if gen.random() < 0.5:
        Y.use_v2_encoding()
    else:
        Y.use_v1_encoding()
    test_connector = TestConnector(gen)
    result["testConnector"] = test_connector
    for i in range(users):
        y = test_connector.create_y(i)
        y.client_id = i
        result["users"].append(y)
        result[f"array{i}"] = y.get_array("array")
        result[f"map{i}"] = y.get_map("map")
        result[f"xml{i}"] = y.get("xml", Y.YXmlElement)
        result[f"text{i}"] = y.get_text("text")
    test_connector.sync_all()
    Y.use_v1_encoding()
    return result


def compare_item_ids(a, b) -> bool:
    return a is b or (a is not None and b is not None and compare_ids(a.id, b.id))


def compare_struct_stores(ss1, ss2) -> None:
    """Struct-by-struct identity + linked-list invariants
    (reference testHelper.js:326-363)."""
    assert len(ss1.clients) == len(ss2.clients)
    for client, structs1 in ss1.clients.items():
        structs2 = ss2.clients.get(client)
        assert structs2 is not None and len(structs1) == len(structs2)
        for s1, s2 in zip(structs1, structs2):
            assert type(s1) is type(s2)
            assert compare_ids(s1.id, s2.id)
            assert s1.deleted == s2.deleted, (s1.id, s1.deleted, s2.deleted)
            assert s1.length == s2.length
            if type(s1) is Item:
                assert type(s2) is Item
                assert (s1.left is None and s2.left is None) or (
                    s1.left is not None
                    and s2.left is not None
                    and compare_ids(s1.left.last_id, s2.left.last_id)
                )
                assert compare_item_ids(s1.right, s2.right)
                assert compare_ids(s1.origin, s2.origin)
                assert compare_ids(s1.right_origin, s2.right_origin)
                assert s1.parent_sub == s2.parent_sub
                assert s1.left is None or s1.left.right is s1
                assert s1.right is None or s1.right.left is s1
                assert s2.left is None or s2.left.right is s2
                assert s2.right is None or s2.right.left is s2


def compare_ds(ds1, ds2) -> None:
    assert len(ds1.clients) == len(ds2.clients)
    for client, delete_items1 in ds1.clients.items():
        delete_items2 = ds2.clients.get(client)
        assert delete_items2 is not None and len(delete_items1) == len(delete_items2)
        for d1, d2 in zip(delete_items1, delete_items2):
            assert d1.clock == d2.clock and d1.len == d2.len


def compare(users: list[TestYInstance]) -> None:
    """Reconnect, flush to quiescence, then assert full replica equality
    (reference testHelper.js:274-313)."""
    for u in users:
        u.connect()
    while users[0].tc.flush_all_messages():
        pass
    user_array_values = [u.get_array("array").to_json() for u in users]
    user_map_values = [u.get_map("map").to_json() for u in users]
    user_xml_values = [u.get("xml", Y.YXmlElement).to_string() for u in users]
    user_text_values = [u.get_text("text").to_delta() for u in users]
    for u in users:
        assert len(u.store.pending_delete_readers) == 0
        assert len(u.store.pending_stack) == 0
        assert len(u.store.pending_clients_struct_refs) == 0
    # array iterator agrees with to_array
    assert users[0].get_array("array").to_array() == list(users[0].get_array("array"))
    # map iterator agrees with to_json
    ymap_keys = list(users[0].get_map("map").keys())
    assert len(ymap_keys) == len(user_map_values[0])
    for key in ymap_keys:
        assert key in user_map_values[0]
    map_res = {
        k: (v.to_json() if isinstance(v, Y.AbstractType) else v)
        for k, v in users[0].get_map("map")
    }
    assert user_map_values[0] == map_res
    for i in range(len(users) - 1):
        assert len(user_array_values[i]) == users[i].get_array("array").length
        assert user_array_values[i] == user_array_values[i + 1]
        assert user_map_values[i] == user_map_values[i + 1]
        assert user_xml_values[i] == user_xml_values[i + 1]
        assert (
            sum(
                len(to_u16(a["insert"])) if isinstance(a["insert"], str) else 1
                for a in user_text_values[i]
            )
            == users[i].get_text("text").length
        )
        assert user_text_values[i] == user_text_values[i + 1]
        assert get_state_vector(users[i].store) == get_state_vector(users[i + 1].store)
        compare_ds(
            create_delete_set_from_struct_store(users[i].store),
            create_delete_set_from_struct_store(users[i + 1].store),
        )
        compare_struct_stores(users[i].store, users[i + 1].store)
    for u in users:
        u.destroy()


def apply_random_tests(
    gen: random.Random, mods, iterations: int, users: int = 5, compare_fn=None
):
    """Randomized convergence fuzzing (reference testHelper.js:398-423):
    random partitions, random delivery order, random mutations.

    ``compare_fn`` overrides the final oracle (default: full struct-store
    identity via :func:`compare`).  Op tables that mix in undo/redo need a
    content-level oracle instead: ``redone`` pointers are local-only state
    (reference Item.js mergeWith requires ``redone === null``), so the
    undoing replica legitimately merges differently than its peers."""
    result = init(gen, users=users)
    test_connector = result["testConnector"]
    users_list = result["users"]
    for _ in range(iterations):
        if gen.randint(0, 100) <= 2:
            # 2% chance to disconnect/reconnect a random user
            if gen.random() < 0.5:
                test_connector.disconnect_random()
            else:
                test_connector.reconnect_random()
        elif gen.randint(0, 100) <= 1:
            test_connector.flush_all_messages()
        elif gen.randint(0, 100) <= 50:
            test_connector.flush_random_message()
        user = users_list[gen.randint(0, len(users_list) - 1)]
        mod = gen.choice(mods)
        mod(user, gen)
    (compare_fn or compare)(users_list)
    return result
