"""Embedded telemetry-history store tests (ISSUE 19).

Covers the Gorilla codec (delta-of-delta timestamps + XOR floats) on
pathological point sets, downsample-tier correctness against a
brute-force oracle, the torn-read hammer (concurrent writers vs range
queries), crash-mid-persist reload (truncated files keep exactly the
intact frame prefix, never invent samples), series-cap enforcement,
the admin-plane ``/query`` + ``/debug/tsdb`` endpoints, cross-shard
federation (``query_endpoints`` / ``merge_points``), the
flight-recorder window embedding, and the ytpu_top snapshot-dir mtime
cache.
"""

from __future__ import annotations

import json
import math
import os
import struct
import threading
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from yjs_tpu.obs import MetricsRegistry
from yjs_tpu.obs.admin import AdminServer
from yjs_tpu.obs.federate import read_snapshot_dir
from yjs_tpu.obs.tsdb import (
    KEY_SERIES_PREFIXES,
    Tsdb,
    TsdbConfig,
    decode_chunk,
    encode_chunk,
    merge_points,
    query_endpoints,
    tsdb,
    tsdb_enabled,
    tsdb_window,
)

pytestmark = pytest.mark.tsdb


def _store(**kw) -> Tsdb:
    """A private store with huge retentions so injected-clock tests
    never race the retention sweeps (constructor args beat env)."""
    kw.setdefault("interval_s", 1.0)
    kw.setdefault("retention_raw_s", 10 * 24 * 3600.0)
    kw.setdefault("retention_1m_s", 20 * 24 * 3600.0)
    kw.setdefault("retention_10m_s", 30 * 24 * 3600.0)
    kw.setdefault("directory", None)
    return Tsdb(TsdbConfig(**kw))


# -- codec -------------------------------------------------------------------


def _bits(v: float) -> bytes:
    return struct.pack(">d", v)


PATHOLOGICAL_POINTS = [
    # (ts_ms, value): irregular cadence, sign flips, denormals, huge
    # jumps, repeats, infinities — everything the XOR window must survive
    (1_000, 0.0),
    (1_001, 0.0),
    (6_000, -0.0),
    (6_001, 1e300),
    (6_002, -1e300),
    (66_002, 5e-324),          # smallest denormal
    (66_003, 5e-324),
    (1_066_003, math.pi),
    (1_066_004, math.pi),
    (1_066_005, -math.pi),
    (1_066_006, float("inf")),
    (1_066_007, float("-inf")),
    (9_999_999_999, 42.5),     # ~year 2286, 64-bit dod escape
    (10_000_000_000, 42.5),
    (10_000_000_001, 1.0 / 3.0),
]


def test_codec_roundtrip_pathological_points():
    data = encode_chunk(PATHOLOGICAL_POINTS)
    out = decode_chunk(data, len(PATHOLOGICAL_POINTS))
    assert len(out) == len(PATHOLOGICAL_POINTS)
    for (ts, v), (ts2, v2) in zip(PATHOLOGICAL_POINTS, out):
        assert ts2 == ts
        # bit-exact, so -0.0 vs 0.0 and denormals count
        assert _bits(v2) == _bits(v)


def test_codec_roundtrip_nan_payload_preserved():
    pts = [(100, 1.0), (200, float("nan")), (300, 1.0)]
    out = decode_chunk(encode_chunk(pts), 3)
    assert [ts for ts, _ in out] == [100, 200, 300]
    assert math.isnan(out[1][1])
    assert out[0][1] == out[2][1] == 1.0


def test_codec_compresses_steady_cadence(rng):
    # the sampler's common case: fixed cadence, slowly-drifting floats.
    # dod==0 costs 1 bit; identical values cost 1 bit — the whole point
    # of carrying Gorilla instead of 16-byte raw pairs.
    pts = []
    v = 100.0
    for i in range(1024):
        v += rng.choice((0.0, 0.0, 1.0))
        pts.append((1_000_000 + 5000 * i, v))
    data = encode_chunk(pts)
    assert decode_chunk(data, len(pts)) == pts
    assert len(data) < 16 * len(pts) / 2  # at least 2x vs raw pairs


def test_codec_empty_and_single_point():
    assert decode_chunk(encode_chunk([]), 0) == []
    one = [(123_456, -7.25)]
    assert decode_chunk(encode_chunk(one), 1) == one


# -- record / query ----------------------------------------------------------


def test_record_and_query_range_filtering():
    st = _store()
    for i in range(10):
        st.record("s", float(i), now=1000.0 + i)
    pts = st.query("s", start=1003.0, end=1006.0, tier="raw")
    assert pts == [(1003.0, 3.0), (1004.0, 4.0), (1005.0, 5.0),
                   (1006.0, 6.0)]
    # default window is the last hour up to clock(); unknown series []
    assert st.query("nope") == []


def test_record_clock_going_backwards_keeps_order():
    st = _store()
    st.record("s", 1.0, now=2000.0)
    st.record("s", 2.0, now=1000.0)  # clock jumped back an hour
    pts = st.query("s", start=0.0, end=3000.0, tier="raw")
    assert [v for _, v in pts] == [1.0, 2.0]
    ts = [t for t, _ in pts]
    assert ts == sorted(ts) and len(set(ts)) == 2


def test_query_rejects_bad_agg_and_tier():
    st = _store()
    with pytest.raises(ValueError):
        st.query("s", agg="median")
    with pytest.raises(ValueError):
        st.query("s", tier="5m")
    with pytest.raises(ValueError):
        st.query_params({})  # missing name
    with pytest.raises(ValueError):
        st.query_params({"name": "s", "start": "yesterday"})


def test_chunk_sealing_spans_queries():
    # cross the 128-point seal boundary several times: the range read
    # must stitch sealed chunks + the open tail seamlessly
    st = _store()
    n = 300
    for i in range(n):
        st.record("s", float(i), now=1000.0 + i)
    assert st.stats()["sealed_chunks"] == n // 128
    pts = st.query("s", start=1000.0, end=1000.0 + n, tier="raw")
    assert [v for _, v in pts] == [float(i) for i in range(n)]


# -- downsample tiers vs brute-force oracle ----------------------------------


def _oracle(points, bucket_ms, agg):
    buckets: dict = {}
    for ts_ms, v in points:
        buckets.setdefault(ts_ms - ts_ms % bucket_ms, []).append(v)
    out = []
    for b in sorted(buckets):
        vals = buckets[b]
        if agg == "min":
            o = min(vals)
        elif agg == "max":
            o = max(vals)
        elif agg == "last":
            o = vals[-1]
        elif agg == "sum":
            o = sum(vals)
        elif agg == "count":
            o = float(len(vals))
        else:
            o = sum(vals) / len(vals)
        out.append((b / 1000.0, o))
    return out


@pytest.mark.parametrize("tier,bucket_ms", [("1m", 60_000),
                                            ("10m", 600_000)])
@pytest.mark.parametrize("agg", ["avg", "min", "max", "last", "sum",
                                 "count"])
def test_downsample_tier_matches_bruteforce_oracle(tier, bucket_ms, agg,
                                                   rng):
    st = _store()
    fed = []
    t = 50_000.0  # seconds
    for _ in range(500):
        t += rng.uniform(0.5, 90.0)  # irregular cadence crossing buckets
        v = rng.uniform(-100.0, 100.0)
        st.record("s", v, now=t)
        fed.append((int(t * 1000), v))
    got = st.query("s", start=0.0, end=2 * t, agg=agg, tier=tier)
    want = _oracle(fed, bucket_ms, agg)
    assert len(got) == len(want)
    for (gt, gv), (wt, wv) in zip(got, want):
        assert gt == wt
        assert gv == pytest.approx(wv, rel=1e-12, abs=1e-12)


def test_tier_autopick_prefers_finest_covering_retention():
    st = _store(retention_raw_s=60.0, retention_1m_s=3600.0,
                retention_10m_s=24 * 3600.0)
    now = 100_000.0
    for i in range(100):
        st.record("s", float(i), now=now + i)
    last = now + 99
    # span within raw retention -> raw (exact timestamps)
    raw = st.query("s", start=last - 50, end=last + 1)
    assert raw == st.query("s", start=last - 50, end=last + 1, tier="raw")
    assert len(raw) == 51  # exact per-second points, not buckets
    # span beyond raw but within 1m retention -> 1m buckets
    mid = st.query("s", start=last - 1800, end=last + 1)
    assert mid == st.query("s", start=last - 1800, end=last + 1,
                           tier="1m")
    assert all(int(ts * 1000) % 60_000 == 0 for ts, _ in mid)
    # span beyond 1m retention -> 10m buckets
    old = st.query("s", start=last - 7200, end=last + 1)
    assert old == st.query("s", start=last - 7200, end=last + 1,
                           tier="10m")
    assert all(int(ts * 1000) % 600_000 == 0 for ts, _ in old)


def test_retention_trims_sealed_raw_chunks_before_tiers():
    st = _store(retention_raw_s=60.0, retention_1m_s=3600.0,
                retention_10m_s=24 * 3600.0)
    t0 = 10_000.0
    n = 600  # 10 minutes of 1s cadence: 4 sealed chunks + open tail
    for i in range(n):
        st.record("s", float(i), now=t0 + i)
    end = t0 + n - 1
    assert st.stats()["sealed_chunks"] == 0  # all aged out
    raw = st.query("s", start=0.0, end=end, tier="raw")
    assert raw  # the open tail survives
    assert len(raw) < n
    assert min(ts for ts, _ in raw) == t0 + 512  # 4 * 128 sealed, gone
    m1 = st.query("s", start=0.0, end=end, tier="1m", agg="count")
    assert sum(v for _, v in m1) == n  # the tier kept everything


# -- series cap + sampler ----------------------------------------------------


def test_max_series_cap_drops_and_counts():
    st = _store(max_series=16)
    for i in range(25):
        st.record(f"s{i:02d}", 1.0, now=1000.0)
    stats = st.stats()
    assert stats["series"] == 16
    assert stats["dropped_series"] == 9
    assert st.query("s00", start=0, end=2000, tier="raw")
    assert st.query("s20", start=0, end=2000, tier="raw") == []


def test_sample_once_walks_registry_counters_gauges_histograms():
    st = _store()
    reg = MetricsRegistry()
    c = reg.counter("t_ctr", "d", labelnames=("k",))
    g = reg.gauge("t_gauge", "d")
    h = reg.histogram("t_hist", "d")
    c.labels(k="a").inc(3)
    g.set(7.5)
    for v in (0.01, 0.02, 0.04):
        h.observe(v)
    st.add_registry(reg)
    st.sample_once(now=500.0)
    c.labels(k="a").inc(2)
    st.sample_once(now=505.0)
    names = dict(st.series_names())
    assert "t_ctr" in names and "t_gauge" in names
    pts = st.query("t_ctr", labels="k=a", start=0, end=1000,
                   tier="raw")
    assert [v for _, v in pts] == [3.0, 5.0]
    assert st.query("t_gauge", start=0, end=1000, tier="raw") == [
        (500.0, 7.5), (505.0, 7.5)
    ]
    # histograms land as derived :p50/:p99/:count series
    assert "t_hist:p50" in names and "t_hist:p99" in names
    counts = st.query("t_hist:count", start=0, end=1000, tier="raw")
    assert [v for _, v in counts] == [3.0, 3.0]


def test_dead_registry_pruned_from_sampler():
    st = _store()
    reg = MetricsRegistry()
    reg.counter("gone_ctr", "d").inc()
    st.add_registry(reg)
    st.sample_once(now=100.0)
    assert any(n == "gone_ctr" for n, _ in st.series_names())
    del reg
    import gc

    gc.collect()
    st.sample_once(now=105.0)  # must not raise; source is pruned
    pts = st.query("gone_ctr", start=0, end=1000, tier="raw")
    assert len(pts) == 1  # no new point after the registry died


# -- torn-read hammer --------------------------------------------------------


def test_torn_read_hammer_concurrent_writers_vs_queries():
    """Writers (direct records + sampler passes) race range queries;
    every answer must be well-formed: in-range, time-ordered, and
    values from the written alphabet — a torn chunk/tier read would
    surface as an exception or a garbage float."""
    st = _store()
    reg = MetricsRegistry()
    ctr = reg.counter("hammer_ctr", "d")
    st.add_registry(reg)
    stop = threading.Event()
    errors: list = []
    written_values = {float(i) for i in range(100_000)}

    def writer(tid: int):
        t = 1_000.0 + tid * 1_000_000.0
        i = 0
        try:
            while not stop.is_set():
                st.record(f"w{tid}", float(i % 100_000), now=t)
                ctr.inc()
                st.sample_once(now=t)
                t += 1.0
                i += 1
        except Exception as e:  # pragma: no cover - the assertion
            errors.append(e)

    def reader(tid: int):
        try:
            while not stop.is_set():
                for name in ("w0", "w1", "hammer_ctr"):
                    lo, hi = 0.0, 3_000_000.0
                    for tier in (None, "raw", "1m", "10m"):
                        pts = st.query(name, start=lo, end=hi,
                                       tier=tier)
                        ts = [p[0] for p in pts]
                        assert ts == sorted(ts)
                        assert all(lo <= t <= hi for t in ts)
                    raw = st.query(name, start=lo, end=hi, tier="raw")
                    if name.startswith("w"):
                        assert all(
                            v in written_values for _, v in raw
                        )
                st.stats()
                st.window(1e9, prefixes=("w", "hammer"))
        except Exception as e:  # pragma: no cover - the assertion
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(2)]
    threads += [threading.Thread(target=reader, args=(i,))
                for i in range(3)]
    for t in threads:
        t.start()
    import time

    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=10.0)
    assert not errors, errors[:3]
    assert st.stats()["points_raw"] > 0


# -- persistence + crash truncation ------------------------------------------


def _fill(st: Tsdb, n: int = 300) -> None:
    for i in range(n):
        st.record("a", float(i), now=1000.0 + i)
        st.record("b", float(-i), now=1000.0 + i, labels='x="1"')


def test_persist_reload_roundtrip(tmp_path):
    st = _store(directory=str(tmp_path))
    _fill(st)
    assert st.persist(now=2000.0)
    st2 = _store(directory=str(tmp_path))
    assert st2.series_names() == st.series_names()
    for name, labels in st.series_names():
        assert st2.query(name, labels=labels, start=0, end=1e9,
                         tier="raw") == st.query(
            name, labels=labels, start=0, end=1e9, tier="raw")
        for tier in ("1m", "10m"):
            for agg in ("avg", "min", "max", "sum", "count", "last"):
                assert st2.query(
                    name, labels=labels, start=0, end=1e9, tier=tier,
                    agg=agg,
                ) == st.query(name, labels=labels, start=0, end=1e9,
                              tier=tier, agg=agg)
    assert st2.stats()["reload_truncated"] == 0


def test_crash_mid_persist_keeps_intact_prefix_only(tmp_path):
    st = _store(directory=str(tmp_path))
    _fill(st)
    st.persist(now=2000.0)
    path = tmp_path / "tsdb.bin"
    blob = path.read_bytes()
    full = {
        key: st.query(key[0], labels=key[1], start=0, end=1e9,
                      tier="raw")
        for key in st.series_names()
    }
    all_points = {
        (name, labels, ts, v)
        for (name, labels), pts in full.items()
        for ts, v in pts
    }
    # cut at every byte class: inside the magic, inside a frame header,
    # mid-payload, and just shy of the end
    for cut in (4, len(blob) // 3, len(blob) // 2, len(blob) - 1):
        path.write_bytes(blob[:cut])
        st2 = _store(directory=str(tmp_path))
        loaded = {
            (name, labels, ts, v)
            for (name, labels) in st2.series_names()
            for ts, v in st2.query(name, labels=labels, start=0,
                                   end=1e9, tier="raw")
        }
        # never invents a sample: loaded is a strict subset
        assert loaded <= all_points
        assert len(st2.series_names()) < len(full)
        if cut > len(_magic()):
            assert st2.stats()["reload_truncated"] == 1


def _magic() -> bytes:
    return _tsdb_module()._MAGIC


def _tsdb_module():
    # ``yjs_tpu.obs.tsdb`` the MODULE — the package re-exports the
    # ``tsdb()`` accessor under the same name, shadowing attribute-style
    # imports
    import importlib

    return importlib.import_module("yjs_tpu.obs.tsdb")


def test_corrupted_crc_drops_frame_and_tail(tmp_path):
    st = _store(directory=str(tmp_path))
    _fill(st, n=50)
    st.persist(now=2000.0)
    path = tmp_path / "tsdb.bin"
    blob = bytearray(path.read_bytes())
    # flip one payload byte in the FIRST frame: everything after the
    # torn frame is dropped too (the stream offset can't be trusted)
    blob[len(_magic()) + 8 + 4] ^= 0xFF
    path.write_bytes(bytes(blob))
    st2 = _store(directory=str(tmp_path))
    assert st2.series_names() == []
    assert st2.stats()["reload_truncated"] == 1


def test_missing_or_foreign_file_loads_empty(tmp_path):
    assert _store(directory=str(tmp_path)).series_names() == []
    (tmp_path / "tsdb.bin").write_bytes(b"not a tsdb file at all")
    st = _store(directory=str(tmp_path))
    assert st.series_names() == []
    assert st.stats()["reload_truncated"] == 0  # wrong magic != torn


def test_sampler_persists_on_cadence(tmp_path):
    st = _store(directory=str(tmp_path), persist_s=10.0)
    reg = MetricsRegistry()
    reg.counter("p_ctr", "d").inc()
    st.add_registry(reg)
    st.sample_once(now=100.0)   # first pass persists (last_persist=0)
    assert (tmp_path / "tsdb.bin").exists()
    mtime = (tmp_path / "tsdb.bin").stat().st_mtime_ns
    st.sample_once(now=105.0)   # within cadence: no rewrite
    assert (tmp_path / "tsdb.bin").stat().st_mtime_ns == mtime
    st.sample_once(now=111.0)   # past cadence: rewritten
    st2 = _store(directory=str(tmp_path))
    assert st2.query("p_ctr", start=0, end=1e9, tier="raw")


# -- window / flight-recorder embedding --------------------------------------


def test_window_filters_by_key_prefix_and_span():
    st = _store()
    st.record("ytpu_cost_wal_bytes_total", 5.0, labels='tenant="t"',
              now=1000.0)
    st.record("ytpu_cost_wal_bytes_total", 9.0, labels='tenant="t"',
              now=1050.0)
    st.record("unrelated_series", 1.0, now=1050.0)
    win = st.window(60.0, now=1105.0)
    assert list(win) == ['ytpu_cost_wal_bytes_total{tenant="t"}']
    # only the last 60s: the t=1000 point is outside
    assert win['ytpu_cost_wal_bytes_total{tenant="t"}'] == [[1050.0, 9.0]]
    assert all(
        any(k.startswith(p) for p in KEY_SERIES_PREFIXES) for k in win
    )


def test_blackbox_dump_embeds_tsdb_window(monkeypatch):
    import time

    from yjs_tpu.obs.blackbox import reset_flight_recorder

    mod = _tsdb_module()

    monkeypatch.delenv("YTPU_TSDB_DISABLED", raising=False)
    # the dump reads the process-global store; swap in a private one so
    # series accumulated by other tests can't crowd the window cap
    st = _store()
    st.record("ytpu_cost_host_seconds_total", 1.25,
              labels='tenant="bb"', now=time.time())
    monkeypatch.setattr(mod, "_TSDB", st)
    rec = reset_flight_recorder()
    rec.record("tsdb-test", "boom", severity="error")
    dump = rec.dump("tsdb-embed-test")
    assert dump is not None
    assert 'ytpu_cost_host_seconds_total{tenant="bb"}' in dump["tsdb"]


def test_tsdb_window_empty_when_disabled(monkeypatch):
    monkeypatch.setenv("YTPU_TSDB_DISABLED", "1")
    assert not tsdb_enabled()
    assert tsdb_window() == {}
    from yjs_tpu.obs.tsdb import maybe_attach_tsdb

    assert maybe_attach_tsdb(MetricsRegistry()) is None


# -- admin endpoints ---------------------------------------------------------


def _get(url: str, timeout: float = 10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class _TsdbTarget:
    """Admin target exposing a PRIVATE store via the facade override
    hooks, so endpoint tests never touch the process-global one."""

    def __init__(self, store: Tsdb):
        self.store = store

    def tsdb_query(self, params: dict) -> dict:
        return self.store.query_params(params)

    def tsdb_stats(self) -> dict:
        out = self.store.stats()
        out["enabled"] = True
        return out


@pytest.fixture
def tsdb_admin():
    st = _store()
    for i in range(5):
        st.record("adm_series", float(i * i), now=1000.0 + i)
    admin = AdminServer(_TsdbTarget(st), role="tsdb-test").start()
    try:
        yield st, admin
    finally:
        admin.close()


@pytest.mark.admin
def test_admin_query_endpoint_returns_points(tsdb_admin):
    st, admin = tsdb_admin
    code, body = _get(
        admin.url + "/query?name=adm_series&start=1001&end=1003"
        "&tier=raw"
    )
    assert code == 200
    out = json.loads(body)
    assert out["name"] == "adm_series"
    assert out["tier"] == "raw"
    assert out["points"] == [[1001.0, 1.0], [1002.0, 4.0],
                             [1003.0, 9.0]]


@pytest.mark.admin
def test_admin_query_endpoint_malformed_is_400(tsdb_admin):
    _, admin = tsdb_admin
    for qs in ("", "name=adm_series&agg=median",
               "name=adm_series&start=noon", "name=adm_series&tier=2m"):
        code, body = _get(admin.url + "/query?" + qs)
        assert code == 400, qs
        assert "error" in json.loads(body)


@pytest.mark.admin
def test_admin_debug_tsdb_stats(tsdb_admin):
    st, admin = tsdb_admin
    code, body = _get(admin.url + "/debug/tsdb")
    assert code == 200
    out = json.loads(body)
    assert out["enabled"] is True
    assert out["series"] == 1
    assert out["points_raw"] == 5


# -- federation --------------------------------------------------------------


def test_merge_points_buckets_and_aggs():
    per_shard = {
        "s0": {"points": [[100.0, 1.0], [105.0, 3.0]]},
        "s1": {"points": [[101.0, 5.0]]},
        "dead": {"points": [], "stale": True},
    }
    assert merge_points(per_shard, agg="sum", bucket_s=5.0) == [
        [100.0, 6.0], [105.0, 3.0]
    ]
    assert merge_points(per_shard, agg="avg", bucket_s=5.0) == [
        [100.0, 3.0], [105.0, 3.0]
    ]
    assert merge_points(per_shard, agg="max", bucket_s=5.0) == [
        [100.0, 5.0], [105.0, 3.0]
    ]
    assert merge_points(per_shard, agg="min", bucket_s=5.0) == [
        [100.0, 1.0], [105.0, 3.0]
    ]
    assert merge_points(per_shard, agg="count", bucket_s=5.0) == [
        [100.0, 2.0], [105.0, 1.0]
    ]
    assert merge_points({}, agg="sum") == []


@pytest.mark.admin
def test_query_endpoints_federates_and_tolerates_dead_shard():
    stores = []
    admins = []
    try:
        for k in range(2):
            st = _store()
            for i in range(4):
                st.record("fed_series", float(10 * k + i),
                          now=1000.0 + i)
            stores.append(st)
            admins.append(
                AdminServer(_TsdbTarget(st), role=f"shard{k}").start()
            )
        urls = {f"shard{k}": a.url for k, a in enumerate(admins)}
        urls["dead"] = "http://127.0.0.1:9"  # discard port: refused
        per_shard = query_endpoints(
            urls,
            {"name": "fed_series", "start": "1000", "end": "2000",
             "tier": "raw", "agg": "avg", "empty": ""},
            timeout_s=5.0,
        )
        assert per_shard["dead"] == {"points": [], "stale": True}
        assert [v for _, v in per_shard["shard0"]["points"]] == [
            0.0, 1.0, 2.0, 3.0
        ]
        merged = merge_points(
            {k: v for k, v in per_shard.items()}, agg="sum",
            bucket_s=1.0,
        )
        assert [v for _, v in merged] == [10.0, 12.0, 14.0, 16.0]
    finally:
        for a in admins:
            a.close()


# -- ytpu_top snapshot-dir mtime cache (satellite) ---------------------------


def _write_snap(path: Path, docs: int) -> None:
    tmp = path.with_suffix(".tmp")
    tmp.write_text(json.dumps({
        "role": "shard",
        "counters": {"ytpu_docs_resident": {"": docs}},
        "gauges": {}, "histograms": {},
    }))
    os.replace(tmp, path)


def test_read_snapshot_dir_mtime_cache_skips_unchanged(tmp_path,
                                                       monkeypatch):
    import types

    import yjs_tpu.obs.federate as fed

    _write_snap(tmp_path / "a.json", 3)
    _write_snap(tmp_path / "b.json", 5)
    cache: dict = {}
    first = fed.read_snapshot_dir(str(tmp_path), cache=cache)
    assert [s["label"] for s in first] == ["a", "b"]
    assert len(cache) == 2

    parses = []
    real_json = fed.json

    def counting_load(f):
        parses.append(1)
        return real_json.loads(f.read())

    monkeypatch.setattr(
        fed, "json",
        types.SimpleNamespace(load=counting_load,
                              loads=real_json.loads),
    )
    second = fed.read_snapshot_dir(str(tmp_path), cache=cache)
    assert [s["label"] for s in second] == ["a", "b"]
    assert not parses  # both files served from the (mtime, size) cache

    # rewrite one file with new content: exactly that one re-parses
    _write_snap(tmp_path / "a.json", 9)
    third = fed.read_snapshot_dir(str(tmp_path), cache=cache)
    assert len(parses) == 1
    got = {s["label"]: s["snapshot"] for s in third}
    assert got["a"]["counters"]["ytpu_docs_resident"][""] == 9


def test_read_snapshot_dir_never_caches_stale_reads(tmp_path):
    import yjs_tpu.obs.federate as fed

    _write_snap(tmp_path / "a.json", 1)
    # a writer caught mid-replace: rendered as a stale row, NOT cached,
    # so the next frame retries the parse
    (tmp_path / "torn.json").write_text('{"role": "shard", "cou')
    cache: dict = {}
    snaps = fed.read_snapshot_dir(str(tmp_path), cache=cache)
    assert [(s["label"], s["stale"]) for s in snaps] == [
        ("a", False), ("torn", True)
    ]
    assert len(cache) == 1
    _write_snap(tmp_path / "torn.json", 7)  # the writer finished
    snaps = fed.read_snapshot_dir(str(tmp_path), cache=cache)
    assert [(s["label"], s["stale"]) for s in snaps] == [
        ("a", False), ("torn", False)
    ]
    assert len(cache) == 2
    (tmp_path / "a.json").unlink()
    (tmp_path / "torn.json").unlink()
    assert fed.read_snapshot_dir(str(tmp_path), cache=cache) == []
    assert cache == {}  # vanished entries pruned


def _load_top():
    import importlib.util

    root = Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "ytpu_top", root / "scripts" / "ytpu_top.py"
    )
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)
    return top


def test_ytpu_top_sparkline_shapes():
    top = _load_top()
    assert top.sparkline([]) == "-"
    assert top.sparkline([1.0, 1.0], 4) == "▁▁"
    line = top.sparkline([0.0, 5.0, 10.0])
    assert line[0] == "▁" and line[-1] == "█"
    assert len(top.sparkline(list(range(100)), 10)) == 10  # width trims


@pytest.mark.admin
def test_ytpu_top_range_mode_renders_query():
    import io
    import time

    top = _load_top()
    st = _store()
    t0 = time.time() - 30.0
    for i in range(6):
        st.record("rng_series", float(i), now=t0 + i)
    admin = AdminServer(_TsdbTarget(st), role="range").start()
    try:
        out = io.StringIO()
        rc = top.run_range(
            [admin.url], "rng_series", labels="", last_s=3600.0,
            agg="avg", out=out,
        )
        text = out.getvalue()
        assert rc == 0
        assert "rng_series" in text and "n=6" in text
        out = io.StringIO()
        rc = top.run_range(
            [admin.url], "no_such_series", labels="", last_s=3600.0,
            agg="avg", out=out,
        )
        assert rc == 1
        assert "(no data)" in out.getvalue()
    finally:
        admin.close()
