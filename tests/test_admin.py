"""Per-process introspection plane tests (ISSUE 16).

Covers the AdminServer endpoint surface over a live provider, the
liveness/readiness split (including the shard fencing-epoch state
machine), the inflight bound, the env opt-in for library objects, the
HTTP/file scrape hardening against mid-death races, the
concurrent-scrape hammer against a flushing provider, and the
bench-regression gate's comparison logic.

Cluster end-to-end probes (SIGSTOP liveness, mid-recovery readiness,
fencing over real sockets, HTTP-vs-file federation byte equivalence)
are additionally marked ``cluster`` — they spawn real shard processes.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from yjs_tpu.core import Doc
from yjs_tpu.obs.admin import AdminConfig, AdminServer, maybe_start_admin
from yjs_tpu.obs.federate import (
    federate_snapshots,
    read_snapshot_dir,
    scrape_endpoints,
)
from yjs_tpu.provider import TpuProvider
from yjs_tpu.updates import encode_state_as_update

pytestmark = pytest.mark.admin


def _get(url: str, timeout: float = 10.0):
    """GET -> (status, body bytes); 4xx/5xx don't raise."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _edit(prov: TpuProvider, room: str, text: str) -> None:
    d = Doc(gc=False)
    d.get_text("text").insert(0, text)
    prov.receive_update(room, encode_state_as_update(d))


@pytest.fixture
def prov_admin():
    prov = TpuProvider(8)
    admin = AdminServer(prov, role="provider").start()
    try:
        yield prov, admin
    finally:
        admin.close()
        prov.close()


# -- endpoint surface ---------------------------------------------------------


def test_all_endpoints_answer_over_live_provider(prov_admin):
    prov, admin = prov_admin
    _edit(prov, "room0", "hello admin")
    prov.flush()
    base = admin.url
    assert base.startswith("http://127.0.0.1:")

    code, body = _get(base + "/healthz")
    assert (code, body) == (200, b"ok\n")

    code, body = _get(base + "/metrics")
    assert code == 200
    text = body.decode()
    assert "ytpu_engine_flushes_total" in text

    code, body = _get(base + "/metrics.json")
    assert code == 200
    snap = json.loads(body)
    assert set(snap) >= {"counters", "gauges", "histograms"}

    code, body = _get(base + "/readyz")
    assert code == 200
    verdict = json.loads(body)
    assert verdict["ready"] is True
    assert verdict["checks"]["recovery_complete"] is True

    code, body = _get(base + "/statusz")
    assert code == 200
    status = json.loads(body)
    assert status["role"] == "provider"
    assert status["pid"] == os.getpid()
    assert status["docs"] == 1
    assert "residue_fraction" in status
    assert "plan_cache_hit_rate" in status
    assert status["admission"]["level_name"] in (
        "normal", "shed-bg", "coalesce", "rej-write"
    )

    code, body = _get(base + "/debug/blackbox")
    assert code == 200
    bb = json.loads(body)
    assert "stats" in bb and "events" in bb

    code, body = _get(base + "/debug/prof")
    assert code == 200
    prof = json.loads(body)
    assert "device_memory" in prof

    code, body = _get(base + "/debug/trace?n=3")
    assert code == 200
    tr = json.loads(body)
    assert len(tr["events"]) <= 3
    assert tr["total"] >= len(tr["events"])

    code, body = _get(base + "/nope")
    assert code == 404


def test_metrics_exposition_well_formed(prov_admin):
    import re

    prov, admin = prov_admin
    _edit(prov, "roomx", "expo")
    prov.flush()
    code, body = _get(admin.url + "/metrics")
    assert code == 200
    line_re = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})?"
        r" [-+]?([0-9.eE+-]+|NaN|Inf)( [0-9]+)?$"
    )
    for ln in body.decode().splitlines():
        if ln and not ln.startswith("#"):
            assert line_re.match(ln), f"malformed exposition line: {ln!r}"


def test_request_counter_and_busy_shed():
    """max_inflight=1 with a blocked handler: the second request is
    shed with 503 'admin busy' instead of queueing behind the stall."""
    from yjs_tpu.obs.admin import admin_metrics

    hold = threading.Event()
    entered = threading.Event()

    class SlowTarget:
        def statusz(self):
            entered.set()
            hold.wait(10)
            return {"slow": True}

    admin = AdminServer(
        SlowTarget(), role="slow",
        config=AdminConfig(max_inflight=1),
    ).start()
    try:
        t = threading.Thread(
            target=lambda: _get(admin.url + "/statusz"), daemon=True
        )
        t.start()
        assert entered.wait(5)
        before = admin_metrics().requests.labels(
            endpoint="/healthz", code=503
        ).value
        code, body = _get(admin.url + "/healthz")
        assert code == 503
        assert json.loads(body)["error"] == "admin busy"
        after = admin_metrics().requests.labels(
            endpoint="/healthz", code=503
        ).value
        assert after == before + 1
        hold.set()
        t.join(timeout=5)
        # the gate released: the plane serves again
        assert _get(admin.url + "/healthz")[0] == 200
    finally:
        hold.set()
        admin.close()


def test_target_exception_renders_500_and_plane_survives():
    class BadTarget:
        def statusz(self):
            raise RuntimeError("target on fire")

    admin = AdminServer(BadTarget(), role="bad").start()
    try:
        code, body = _get(admin.url + "/statusz")
        assert code == 500
        err = json.loads(body)
        assert err["error"] == "RuntimeError"
        # liveness untouched by the target bug
        assert _get(admin.url + "/healthz")[0] == 200
    finally:
        admin.close()


# -- lifecycle / opt-in -------------------------------------------------------


def test_maybe_start_admin_env_optin(monkeypatch):
    monkeypatch.delenv("YTPU_ADMIN_PORT", raising=False)
    prov = TpuProvider(2)
    try:
        assert prov.admin is None  # no env: libraries stay silent
        assert maybe_start_admin(prov, "provider") is None
    finally:
        prov.close()

    monkeypatch.setenv("YTPU_ADMIN_PORT", "0")
    prov = TpuProvider(2)
    try:
        assert prov.admin is not None
        assert _get(prov.admin.url + "/healthz")[0] == 200
    finally:
        prov.close()
    # close() shut the plane down with the provider
    assert prov.admin is None or prov.admin._httpd is None

    monkeypatch.setenv("YTPU_ADMIN_DISABLED", "1")
    prov = TpuProvider(2)
    try:
        assert prov.admin is None
    finally:
        prov.close()


def test_maybe_start_admin_port_collision_yields_none():
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    sock.listen(1)
    taken = sock.getsockname()[1]
    try:
        admin = maybe_start_admin(
            object(), "provider", config=AdminConfig(port=taken)
        )
        assert admin is None
    finally:
        sock.close()


def test_disabled_config_makes_start_a_noop():
    admin = AdminServer(None, config=AdminConfig(disabled=True)).start()
    assert admin.port == 0
    assert admin.url == ""
    admin.close()  # no-op, no raise


def test_fleet_router_owns_one_plane(monkeypatch):
    monkeypatch.setenv("YTPU_ADMIN_PORT", "0")
    from yjs_tpu.fleet import FleetRouter

    fleet = FleetRouter(n_shards=2, docs_per_shard=4)
    try:
        # per-provider auto-planes were folded into the fleet's one
        assert all(p.admin is None for p in fleet.shards)
        assert fleet.admin is not None
        code, body = _get(fleet.admin.url + "/statusz")
        assert code == 200
        status = json.loads(body)
        assert status["role"] == "fleet"
        assert status["n_shards"] == 2
        assert _get(fleet.admin.url + "/readyz")[0] == 200
    finally:
        fleet.close()


# -- readiness semantics ------------------------------------------------------


def test_provider_readyz_flips_on_recovering_and_brownout(prov_admin):
    prov, admin = prov_admin
    assert _get(admin.url + "/readyz")[0] == 200

    prov.recovering = True
    code, body = _get(admin.url + "/readyz")
    assert code == 503
    assert json.loads(body)["checks"]["recovery_complete"] is False
    prov.recovering = False
    assert _get(admin.url + "/readyz")[0] == 200

    prov.admission.brownout.level = 3  # reject-writes
    code, body = _get(admin.url + "/readyz")
    assert code == 503
    assert json.loads(body)["checks"]["accepting_writes"] is False
    prov.admission.brownout.level = 0
    assert _get(admin.url + "/readyz")[0] == 200


def test_shard_fencing_epoch_readiness(tmp_path):
    """The fenced-corpse state machine, driven through the real RPC
    dispatch seam: witnessing a fleet epoch ahead of the routing epoch
    flips /readyz 503; the supervisor's epoch push restores it."""
    from yjs_tpu.cluster.shard import ShardServer

    shard = ShardServer(7, str(tmp_path / "wal7"), n_docs=4)
    try:
        base = shard.admin.url
        assert _get(base + "/readyz")[0] == 200

        # a fence: demoted to replica at epoch 5 (we think we're at 0)
        shard.handle_rpc_request(
            "journal_repl_role",
            {"guid": "roomf", "role": "replica", "epoch": 5,
             "primary": 1},
            None,
        )
        code, body = _get(base + "/readyz")
        assert code == 503
        checks = json.loads(body)["checks"]
        assert checks["epoch_current"] is False
        assert checks["epoch_seen"] == 5
        assert checks["recovery_complete"] is True  # ONLY the fence

        # statusz keeps serving (and shows the lag) while not ready
        code, body = _get(base + "/statusz")
        assert code == 200
        status = json.loads(body)
        assert status["epoch_seen"] == 5
        assert status["routing_epoch"] == 0

        # the supervisor's post-resolution push: current again
        shard.handle_rpc_request("epoch", {"epoch": 6}, None)
        code, body = _get(base + "/readyz")
        assert code == 200
        assert json.loads(body)["checks"]["routing_epoch"] == 6
    finally:
        shard.close()


def test_shard_recovered_wal_history_does_not_fence(tmp_path):
    """Replayed repl_role WAL records must NOT raise _epoch_seen: only
    live control frames fence, else every recovered shard would boot
    not-ready with no supervisor around to push an epoch."""
    from yjs_tpu.cluster.shard import ShardServer

    wal = str(tmp_path / "wal0")
    shard = ShardServer(0, wal, n_docs=4)
    shard.handle_rpc_request(
        "journal_repl_role",
        {"guid": "roomr", "role": "replica", "epoch": 9, "primary": 1},
        None,
    )
    shard.close()

    shard = ShardServer(0, wal, n_docs=4)
    try:
        assert shard.recovery["outcome"] == "recovered"
        assert shard._epoch_seen == 0  # history replayed, not witnessed
        assert _get(shard.admin.url + "/readyz")[0] == 200
    finally:
        shard.close()


# -- scrape hardening ---------------------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_scrape_endpoints_dead_target_is_stale_not_error(prov_admin):
    from yjs_tpu.obs.federate import fed_metrics

    prov, admin = prov_admin
    prov.flush()
    dead = f"127.0.0.1:{_free_port()}"
    before = fed_metrics().scrape_errors.labels(mode="http").value
    sources = scrape_endpoints([admin.url, dead], timeout_s=1.0)
    assert len(sources) == 2
    live, gone = sources
    assert live["stale"] is False
    assert live["snapshot"].get("counters")
    assert gone["stale"] is True
    assert gone["snapshot"] == {}
    assert gone["label"] == dead
    after = fed_metrics().scrape_errors.labels(mode="http").value
    assert after == before + 1
    # federation renders the blank row and names the stale source
    fed = federate_snapshots(sources)
    assert fed["federation"]["stale"] == [dead]


def test_scrape_endpoints_truncated_body_is_stale():
    """An endpoint that promises a Content-Length then dies mid-body
    (the shard was SIGKILLed mid-scrape) must yield a stale entry."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def truncating_server():
        conn, _ = srv.accept()
        conn.recv(4096)
        conn.sendall(
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: 100000\r\n\r\n"
            b'{"counters": {'
        )
        conn.close()  # mid-body: the promised bytes never arrive

    t = threading.Thread(target=truncating_server, daemon=True)
    t.start()
    try:
        sources = scrape_endpoints(
            [f"127.0.0.1:{port}"], timeout_s=5.0
        )
        assert sources[0]["stale"] is True
        assert sources[0]["snapshot"] == {}
    finally:
        srv.close()
        t.join(timeout=5)


def test_read_snapshot_dir_file_deleted_mid_listing(tmp_path, monkeypatch):
    """A shard dying between listdir and open contributes a stale
    blank source, never an exception."""
    from yjs_tpu.obs import federate as fed_mod

    good = tmp_path / "shard-000.json"
    good.write_text(json.dumps({"counters": {"c": {"": 1}}}))
    doomed = tmp_path / "shard-001.json"
    doomed.write_text("{}")

    real_listdir = os.listdir

    def racing_listdir(path):
        names = real_listdir(path)
        if doomed.exists():
            doomed.unlink()  # dies right after the listing
        return names

    monkeypatch.setattr(fed_mod.os, "listdir", racing_listdir)
    before = fed_mod.fed_metrics().scrape_errors.labels(mode="file").value
    sources = read_snapshot_dir(str(tmp_path))
    assert [s["label"] for s in sources] == ["shard-000", "shard-001"]
    assert sources[0]["stale"] is False
    assert sources[1]["stale"] is True
    after = fed_mod.fed_metrics().scrape_errors.labels(mode="file").value
    assert after == before + 1


def test_read_snapshot_dir_mid_write_torn_json(tmp_path):
    (tmp_path / "shard-000.json").write_text('{"counters": {"tor')
    sources = read_snapshot_dir(str(tmp_path))
    assert sources[0]["stale"] is True
    assert sources[0]["snapshot"] == {}
    # federation over the torn dir still renders
    fed = federate_snapshots(sources)
    assert fed["federation"]["stale"] == ["shard-000"]


# -- concurrency --------------------------------------------------------------


def test_concurrent_scrape_hammer_against_flushing_provider():
    """N scraper threads x every endpoint while the provider flushes:
    no torn exposition, no deadlock, every response well-formed."""
    prov = TpuProvider(8)
    admin = AdminServer(
        prov, role="provider", config=AdminConfig(max_inflight=16)
    ).start()
    stop = threading.Event()
    failures: list[str] = []

    def flusher():
        n = 0
        while not stop.is_set():
            n += 1
            _edit(prov, f"room{n % 8}", f"edit {n} ")
            prov.flush()

    endpoints = (
        "/metrics", "/metrics.json", "/healthz", "/readyz",
        "/statusz", "/debug/blackbox", "/debug/prof", "/debug/trace",
    )

    def scraper(k: int):
        for i in range(12):
            ep = endpoints[(k + i) % len(endpoints)]
            try:
                code, body = _get(admin.url + ep, timeout=30)
            except Exception as e:
                failures.append(f"{ep}: {type(e).__name__}: {e}")
                continue
            if code == 503 and ep not in ("/readyz",):
                continue  # inflight shed under the hammer is legal
            if code != 200:
                failures.append(f"{ep}: HTTP {code}")
            elif ep == "/metrics":
                import re

                line_re = re.compile(
                    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})?"
                    r" [-+]?([0-9.eE+-]+|NaN|Inf)$"
                )
                for ln in body.decode("utf-8").splitlines():
                    if ln and not ln.startswith("#") \
                            and not line_re.match(ln):
                        failures.append(f"{ep}: torn line {ln!r}")
                        break
            elif ep != "/healthz":
                try:
                    json.loads(body)
                except ValueError:
                    failures.append(f"{ep}: torn JSON")

    ft = threading.Thread(target=flusher, daemon=True)
    ft.start()
    threads = [
        threading.Thread(target=scraper, args=(k,)) for k in range(8)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "scraper deadlocked"
    finally:
        stop.set()
        ft.join(timeout=30)
        admin.close()
        prov.close()
    assert not failures, failures


# -- bench-regression gate ----------------------------------------------------


def _write_baselines(d, planner=2.0, overlap=0.85, p50=2.5, shed=0.86,
                     geo_p99=1.27, geo_heal=105.0, capacity=120.0,
                     obs_pct=0.6):
    (d / "BENCH_planner.json").write_text(
        json.dumps({"cold_vs_warm_ratio": planner})
    )
    (d / "BENCH_flush.json").write_text(
        json.dumps({"overlap_fraction": overlap})
    )
    (d / "BENCH_cluster.json").write_text(
        json.dumps({"process": {"converge_ms_p50": p50}})
    )
    (d / "BENCH_overload.json").write_text(
        json.dumps({"shed_fraction": shed})
    )
    (d / "BENCH_geo.json").write_text(
        json.dumps({
            "rtt_ms_150": {"p99_over_floor": geo_p99},
            "heal": {"catchup_ms": geo_heal},
        })
    )
    (d / "BENCH_capacity.json").write_text(
        json.dumps({"sessions_per_device": capacity})
    )
    (d / "BENCH_obs_tsdb.json").write_text(
        json.dumps({"overhead_pct": obs_pct})
    )


def test_check_bench_tolerance_bands(tmp_path):
    import sys
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts"),
    )
    try:
        from check_bench import compare
    finally:
        sys.path.pop(0)

    base = tmp_path / "base"
    fresh = tmp_path / "fresh"
    base.mkdir()
    fresh.mkdir()
    _write_baselines(base)

    # identical numbers: all ok
    _write_baselines(fresh)
    assert all(
        v["status"] == "ok" for v in compare(fresh, base, {})
    )

    # better in the metric's own direction never fails
    _write_baselines(fresh, planner=1.0, overlap=0.99, p50=1.0, shed=0.99,
                     geo_p99=1.0, geo_heal=50.0, capacity=999.0,
                     obs_pct=0.01)
    assert all(
        v["status"] == "ok" for v in compare(fresh, base, {})
    )

    # each metric regressed past its band fails, direction-aware
    _write_baselines(fresh, planner=99.0, overlap=0.1, p50=99.0, shed=0.1,
                     geo_p99=99.0, geo_heal=9999.0, capacity=1.0,
                     obs_pct=99.0)
    verdicts = compare(fresh, base, {})
    assert all(v["status"] == "regression" for v in verdicts)

    # inside the band: jitter passes
    _write_baselines(
        fresh, planner=2.0 * 1.3, overlap=0.85 * 0.9,
        p50=2.5 * 1.5, shed=0.86 * 0.95,
        geo_p99=1.27 * 1.5, geo_heal=105.0 * 1.8,
        capacity=120.0 * 0.55, obs_pct=0.6 * 1.9,
    )
    assert all(v["status"] == "ok" for v in compare(fresh, base, {}))

    # a silently-dead bench block is itself a failure
    (fresh / "BENCH_flush.json").unlink()
    verdicts = {v["metric"]: v for v in compare(fresh, base, {})}
    assert verdicts["flush.overlap_fraction"]["status"] == "missing-fresh"

    # tolerance override flips a verdict
    _write_baselines(fresh, planner=2.0 * 1.6)
    verdicts = {v["metric"]: v for v in compare(fresh, base, {})}
    assert verdicts["planner.cold_vs_warm_ratio"]["status"] == "regression"
    verdicts = {
        v["metric"]: v
        for v in compare(
            fresh, base, {"planner.cold_vs_warm_ratio": 1.0}
        )
    }
    assert verdicts["planner.cold_vs_warm_ratio"]["status"] == "ok"


# -- cluster end-to-end -------------------------------------------------------


FAST = dict(heartbeat_s=0.1, restart_backoff_s=0.05, spawn_timeout_s=120.0)


@pytest.mark.cluster
def test_cluster_admin_everywhere_and_federation_equivalence(tmp_path):
    """Every process serves the plane, and HTTP-scrape federation is
    byte-equivalent with the file-drop mode over the SAME payloads."""
    from yjs_tpu.cluster import (
        ClusterConfig, Gateway, GatewayConfig, Supervisor,
    )

    sup = Supervisor(
        3, str(tmp_path / "wal"), docs_per_shard=4,
        config=ClusterConfig(snapshot_dir="", **FAST),
    ).start()
    gw = Gateway(sup, config=GatewayConfig(port=0)).start()
    try:
        urls = sup.admin_urls()
        assert set(urls) == {
            "supervisor", "shard-000", "shard-001", "shard-002"
        }
        urls["gateway"] = gw.admin.url
        for name, base in urls.items():
            assert _get(base + "/healthz")[0] == 200, name
            assert _get(base + "/readyz")[0] == 200, name
            code, body = _get(base + "/statusz")
            assert code == 200, name
            status = json.loads(body)
            expect = name.split("-")[0] if name.startswith("shard") else name
            assert status["role"] == expect
            code, body = _get(base + "/metrics")
            assert code == 200 and b"ytpu_" in body, name

        srcs = sup.scrape_sources()
        assert [s["label"] for s in srcs] == [
            "shard-000", "shard-001", "shard-002"
        ]
        assert not any(s["stale"] for s in srcs)
        out = sup.dump_snapshots(path=str(tmp_path / "snap"), sources=srcs)
        file_srcs = [
            s for s in read_snapshot_dir(out) if s["label"] != "cluster"
        ]
        via_http = json.dumps(federate_snapshots(srcs), sort_keys=True)
        via_file = json.dumps(
            federate_snapshots(file_srcs), sort_keys=True
        )
        assert via_http == via_file
    finally:
        gw.close()
        sup.close()


@pytest.mark.cluster
def test_cluster_kill_shard_mid_scrape_yields_stale_row(tmp_path):
    """SIGKILL a shard, scrape immediately: its row is stale-marked,
    the others merge, federation never raises."""
    from yjs_tpu.cluster import ClusterConfig, Supervisor

    sup = Supervisor(
        2, str(tmp_path / "wal"), docs_per_shard=4,
        config=ClusterConfig(
            snapshot_dir="", restart_max=0, probe_timeout_s=60.0,
            scrape_timeout_s=1.0, **FAST,
        ),
    ).start()
    try:
        victim = sup._shards[0].pid
        os.kill(victim, signal.SIGKILL)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            srcs = sup.scrape_sources()
            if srcs[0]["stale"]:
                break
            time.sleep(0.05)
        else:
            pytest.fail(f"killed shard never went stale: {srcs}")
        assert srcs[0]["label"] == "shard-000"
        assert srcs[1]["stale"] is False
        fed = federate_snapshots(srcs)
        assert fed["federation"]["stale"] == ["shard-000"]
    finally:
        sup.close()


@pytest.mark.cluster
def test_cluster_healthz_flips_on_sigstop(tmp_path):
    """/healthz is pure liveness: a SIGSTOPped (hung) shard times the
    probe out; SIGCONT restores it.  probe_timeout_s is generous so
    the supervisor doesn't restart the shard under the test."""
    from yjs_tpu.cluster import ClusterConfig, Supervisor

    sup = Supervisor(
        1, str(tmp_path / "wal"), docs_per_shard=4,
        config=ClusterConfig(
            snapshot_dir="", probe_timeout_s=600.0, **FAST
        ),
    ).start()
    pid = sup._shards[0].pid
    stopped = False
    try:
        base = sup.admin_urls()["shard-000"]
        assert _get(base + "/healthz")[0] == 200

        os.kill(pid, signal.SIGSTOP)
        stopped = True
        with pytest.raises(OSError):
            urllib.request.urlopen(base + "/healthz", timeout=1.0)

        os.kill(pid, signal.SIGCONT)
        stopped = False
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                if _get(base + "/healthz", timeout=2.0)[0] == 200:
                    break
            except OSError:
                pass
            time.sleep(0.1)
        else:
            pytest.fail("healthz never recovered after SIGCONT")
    finally:
        if stopped:
            os.kill(pid, signal.SIGCONT)
        sup.close()


@pytest.mark.cluster
def test_readyz_flips_during_wal_recovery(tmp_path):
    """A shard replaying a big WAL answers /healthz 200 and /readyz
    503 (recovery_complete false) until replay completes, then 200 —
    the plane comes up BEFORE the provider."""
    from yjs_tpu.cluster.shard import ShardServer

    wal = str(tmp_path / "wal")
    prov = TpuProvider(8, wal_dir=wal)
    for i in range(400):
        _edit(prov, f"room{i % 8}", f"record {i} " * 8)
    prov.flush()
    prov.close()

    admin_port = _free_port()
    built: dict = {}

    def build():
        built["shard"] = ShardServer(
            0, wal, n_docs=8, admin_port=admin_port
        )

    t = threading.Thread(target=build, daemon=True)
    base = f"http://127.0.0.1:{admin_port}"
    codes: list[int] = []
    t.start()
    try:
        deadline = time.monotonic() + 120
        while t.is_alive() and time.monotonic() < deadline:
            try:
                codes.append(_get(base + "/readyz", timeout=2.0)[0])
            except OSError:
                pass  # socket not bound yet
        t.join(timeout=120)
        assert "shard" in built, "shard construction failed"
        # during replay the plane answered, and answered NOT READY
        assert 503 in codes, f"never saw 503 during recovery: {codes}"
        assert _get(base + "/readyz")[0] == 200
        assert built["shard"].recovery["outcome"] == "recovered"
    finally:
        sh = built.get("shard")
        if sh is not None:
            sh.close()


@pytest.mark.cluster
def test_cluster_fencing_flips_readyz_over_real_sockets(tmp_path):
    """The fence window end-to-end: a live shard witnessing a fleet
    epoch ahead of its routing epoch (the frame a real failover sends
    to a stale primary) goes 503 until the supervisor's broadcast."""
    from yjs_tpu.cluster import ClusterConfig, Supervisor

    sup = Supervisor(
        2, str(tmp_path / "wal"), docs_per_shard=4,
        config=ClusterConfig(snapshot_dir="", **FAST),
    ).start()
    try:
        base = sup.admin_urls()["shard-000"]
        assert _get(base + "/readyz")[0] == 200
        # the fence frame, over the real RPC socket
        sup._call(0, "journal_repl_role", {
            "guid": "roomf", "role": "replica", "epoch": 3,
            "primary": 1,
        })
        code, body = _get(base + "/readyz")
        assert code == 503
        assert json.loads(body)["checks"]["epoch_current"] is False
        # the supervisor's post-resolution push restores readiness
        sup._broadcast_epoch(4)
        assert _get(base + "/readyz")[0] == 200
    finally:
        sup.close()
