"""Relative positions (scenarios modeled on reference README examples and
RelativePosition.js behavior)."""

import yjs_tpu as Y


def _check_rel_pos(text, rpos, expected_index):
    apos = Y.create_absolute_position_from_relative_position(rpos, text.doc)
    assert apos is not None
    assert apos.type is text
    assert apos.index == expected_index


def test_rel_pos_survives_inserts():
    doc = Y.Doc()
    text = doc.get_text("t")
    text.insert(0, "abc")
    rpos = Y.create_relative_position_from_type_index(text, 2)
    text.insert(0, "xxx")
    _check_rel_pos(text, rpos, 5)
    text.delete(0, 1)
    _check_rel_pos(text, rpos, 4)


def test_rel_pos_end_of_type():
    doc = Y.Doc()
    text = doc.get_text("t")
    text.insert(0, "ab")
    rpos = Y.create_relative_position_from_type_index(text, 2)
    text.insert(2, "cd")
    _check_rel_pos(text, rpos, 4)


def test_rel_pos_codec_roundtrip():
    doc = Y.Doc()
    text = doc.get_text("t")
    text.insert(0, "hello")
    for index in (0, 2, 5):
        rpos = Y.create_relative_position_from_type_index(text, index)
        decoded = Y.decode_relative_position(Y.encode_relative_position(rpos))
        # note: when `item` is set, the codec intentionally drops tname/type
        # (reference RelativePosition.js:145-160), so compare against a
        # re-encoded copy rather than the original
        decoded2 = Y.decode_relative_position(Y.encode_relative_position(decoded))
        assert Y.compare_relative_positions(decoded, decoded2)
        _check_rel_pos(text, decoded, index)


def test_rel_pos_from_json():
    doc = Y.Doc()
    text = doc.get_text("t")
    text.insert(0, "hello")
    rpos = Y.create_relative_position_from_type_index(text, 3)
    rpos2 = Y.create_relative_position_from_json(rpos.to_json())
    assert Y.compare_relative_positions(rpos, rpos2)


def test_rel_pos_deleted_target():
    doc = Y.Doc()
    text = doc.get_text("t")
    text.insert(0, "abcdef")
    rpos = Y.create_relative_position_from_type_index(text, 3)
    text.delete(2, 3)
    apos = Y.create_absolute_position_from_relative_position(rpos, doc)
    assert apos is not None
    assert apos.index == 2


def test_rel_pos_missing_client_returns_none():
    doc = Y.Doc()
    text = doc.get_text("t")
    text.insert(0, "ab")
    rpos = Y.create_relative_position_from_type_index(text, 1)
    other = Y.Doc()
    other.get_text("t")
    assert Y.create_absolute_position_from_relative_position(rpos, other) is None
