"""Relative positions (scenarios modeled on reference README examples and
RelativePosition.js behavior)."""

import yjs_tpu as Y


def _check_rel_pos(text, rpos, expected_index):
    apos = Y.create_absolute_position_from_relative_position(rpos, text.doc)
    assert apos is not None
    assert apos.type is text
    assert apos.index == expected_index


def test_rel_pos_survives_inserts():
    doc = Y.Doc()
    text = doc.get_text("t")
    text.insert(0, "abc")
    rpos = Y.create_relative_position_from_type_index(text, 2)
    text.insert(0, "xxx")
    _check_rel_pos(text, rpos, 5)
    text.delete(0, 1)
    _check_rel_pos(text, rpos, 4)


def test_rel_pos_end_of_type():
    doc = Y.Doc()
    text = doc.get_text("t")
    text.insert(0, "ab")
    rpos = Y.create_relative_position_from_type_index(text, 2)
    text.insert(2, "cd")
    _check_rel_pos(text, rpos, 4)


def test_rel_pos_codec_roundtrip():
    doc = Y.Doc()
    text = doc.get_text("t")
    text.insert(0, "hello")
    for index in (0, 2, 5):
        rpos = Y.create_relative_position_from_type_index(text, index)
        decoded = Y.decode_relative_position(Y.encode_relative_position(rpos))
        # note: when `item` is set, the codec intentionally drops tname/type
        # (reference RelativePosition.js:145-160), so compare against a
        # re-encoded copy rather than the original
        decoded2 = Y.decode_relative_position(Y.encode_relative_position(decoded))
        assert Y.compare_relative_positions(decoded, decoded2)
        _check_rel_pos(text, decoded, index)


def test_rel_pos_from_json():
    doc = Y.Doc()
    text = doc.get_text("t")
    text.insert(0, "hello")
    rpos = Y.create_relative_position_from_type_index(text, 3)
    rpos2 = Y.create_relative_position_from_json(rpos.to_json())
    assert Y.compare_relative_positions(rpos, rpos2)


def test_rel_pos_deleted_target():
    doc = Y.Doc()
    text = doc.get_text("t")
    text.insert(0, "abcdef")
    rpos = Y.create_relative_position_from_type_index(text, 3)
    text.delete(2, 3)
    apos = Y.create_absolute_position_from_relative_position(rpos, doc)
    assert apos is not None
    assert apos.index == 2


def test_rel_pos_missing_client_returns_none():
    doc = Y.Doc()
    text = doc.get_text("t")
    text.insert(0, "ab")
    rpos = Y.create_relative_position_from_type_index(text, 1)
    other = Y.Doc()
    other.get_text("t")
    assert Y.create_absolute_position_from_relative_position(rpos, other) is None


# ---------------------------------------------------------------------------
# Engine-path cursors (VERDICT r4 item 4): create/resolve straight from
# mirror columns, parity-pinned against the CPU reference path under
# concurrent edits, compaction, and undo/redo (redone chains).
# ---------------------------------------------------------------------------

import random

from yjs_tpu.ops import BatchEngine
from yjs_tpu.provider import TpuProvider


def _two_client_conflict_doc(seed=7, n_ops=120):
    """Two clients typing/deleting concurrently with periodic syncs;
    returns (merged_update, reference_doc)."""
    gen = random.Random(seed)
    a = Y.Doc(gc=False)
    a.client_id = 101
    b = Y.Doc(gc=False)
    b.client_id = 202

    def sync():
        ua = Y.encode_state_as_update(a, Y.encode_state_vector(b))
        ub = Y.encode_state_as_update(b, Y.encode_state_vector(a))
        Y.apply_update(b, ua)
        Y.apply_update(a, ub)

    for _ in range(n_ops):
        d = a if gen.random() < 0.5 else b
        t = d.get_text("text")
        ln = len(t.to_string())
        if gen.random() < 0.7 or ln == 0:
            t.insert(gen.randint(0, ln), gen.choice(["ab", "c", "def ", "🙂"]))
        else:
            pos = gen.randrange(ln)
            t.delete(pos, min(gen.randint(1, 3), ln - pos))
        if gen.random() < 0.25:
            sync()
    sync()
    return Y.encode_state_as_update(a), a


def _assert_rpos_equal(ra, rb):
    assert ra.tname == rb.tname
    assert Y.compare_ids(ra.item, rb.item)
    assert Y.compare_ids(ra.type, rb.type)


def test_engine_cursor_create_resolve_parity():
    update, ref = _two_client_conflict_doc()
    eng = BatchEngine(1)
    eng.queue_update(0, update)
    eng.flush()
    text = ref.get_text("text")
    n = len(text.to_string())
    rposes = []
    for i in range(0, n + 1):
        rc = Y.create_relative_position_from_type_index(text, i)
        re_ = eng.relative_position_from_index(0, i, "text")
        _assert_rpos_equal(rc, re_)
        rposes.append(rc)
        # resolve immediately: same index back on both paths
        a = Y.create_absolute_position_from_relative_position(rc, ref)
        assert a is not None and a.index == i
        assert eng.absolute_index_from_relative(0, rc) == i


def test_engine_cursor_survives_concurrent_edits():
    update, ref = _two_client_conflict_doc(seed=13)
    eng = BatchEngine(1)
    eng.queue_update(0, update)
    eng.flush()
    text = ref.get_text("text")
    n = len(text.to_string())
    step = max(1, n // 17)
    rposes = [
        Y.create_relative_position_from_type_index(text, i)
        for i in range(0, n + 1, step)
    ]
    # a second wave of concurrent edits (insert before/after anchors,
    # delete ranges covering some anchors) applied to both replicas
    c = Y.Doc(gc=False)
    c.client_id = 303
    Y.apply_update(c, update)
    t2 = c.get_text("text")
    gen = random.Random(99)
    for _ in range(60):
        ln = len(t2.to_string())
        if gen.random() < 0.6 or ln == 0:
            t2.insert(gen.randint(0, ln), gen.choice(["XX", "y", "zz "]))
        else:
            pos = gen.randrange(ln)
            t2.delete(pos, min(gen.randint(1, 4), ln - pos))
    wave = Y.encode_state_as_update(c, Y.encode_state_vector(ref))
    Y.apply_update(ref, wave)
    eng.queue_update(0, wave)
    eng.flush()
    assert eng.text(0) == ref.get_text("text").to_string()
    for rp in rposes:
        a = Y.create_absolute_position_from_relative_position(rp, ref)
        got = eng.absolute_index_from_relative(0, rp)
        assert a is not None
        assert got == a.index, (rp.to_json(), got, a.index)


def test_engine_cursor_post_compaction():
    # low compaction threshold: the flush after the second wave rebuilds
    # the mirror's rows; anchors inside MERGED runs must still resolve
    update, ref = _two_client_conflict_doc(seed=21)
    eng = BatchEngine(1, gc=False, compact_min_rows=4)
    eng.queue_update(0, update)
    eng.flush()
    text = ref.get_text("text")
    n = len(text.to_string())
    rposes = [
        Y.create_relative_position_from_type_index(text, i)
        for i in range(0, n + 1, max(1, n // 11))
    ]
    # more traffic to trigger another compaction cycle
    c = Y.Doc(gc=False)
    c.client_id = 404
    Y.apply_update(c, update)
    for k in range(40):
        t2 = c.get_text("text")
        t2.insert(len(t2.to_string()), f"tail{k} ")
    wave = Y.encode_state_as_update(c, Y.encode_state_vector(ref))
    Y.apply_update(ref, wave)
    eng.queue_update(0, wave)
    eng.flush()
    assert eng.last_compaction, "compaction must have run for this test"
    assert eng.text(0) == ref.get_text("text").to_string()
    for rp in rposes:
        a = Y.create_absolute_position_from_relative_position(rp, ref)
        got = eng.absolute_index_from_relative(0, rp)
        assert a is not None and got == a.index
    # fresh cursors created post-compaction still match the CPU path
    for i in range(0, len(ref.get_text("text").to_string()) + 1, 7):
        rc = Y.create_relative_position_from_type_index(ref.get_text("text"), i)
        re_ = eng.relative_position_from_index(0, i, "text")
        _assert_rpos_equal(rc, re_)


def test_engine_cursor_deleted_anchor_and_end():
    a = Y.Doc(gc=False)
    a.client_id = 5
    t = a.get_text("text")
    t.insert(0, "hello world")
    u = Y.encode_state_as_update(a)
    eng = BatchEngine(1)
    eng.queue_update(0, u)
    eng.flush()
    # end-of-list cursor (item=None, tname case)
    rend = eng.relative_position_from_index(0, 11, "text")
    assert rend.item is None and rend.tname == "text"
    # cursor inside a range that then gets deleted -> clamps to run start
    rmid = eng.relative_position_from_index(0, 8, "text")
    t.delete(4, 6)  # delete "o worl"
    eng.queue_update(0, Y.encode_state_as_update(a))
    eng.flush()
    acpu = Y.create_absolute_position_from_relative_position(rmid, a)
    assert eng.absolute_index_from_relative(0, rmid) == acpu.index
    aend = Y.create_absolute_position_from_relative_position(rend, a)
    assert eng.absolute_index_from_relative(0, rend) == aend.index
    # unknown-client anchor resolves to None on both paths
    ghost = Y.RelativePosition(None, "text", Y.create_id(999, 0)) if hasattr(Y, "RelativePosition") else None
    if ghost is not None:
        assert eng.absolute_index_from_relative(0, ghost) is None


def test_provider_cursor_redone_chain():
    """Cursor anchored in content that is undone then redone: the
    undo-enabled room resolves through the replica's follow-redone walk
    and must agree with a pure-CPU UndoManager replay."""
    prov = TpuProvider(n_docs=2)
    guid = "room"
    a = Y.Doc(gc=False)
    a.client_id = 9
    a.get_text("text").insert(0, "base ")
    base = Y.encode_state_as_update(a)
    prov.receive_update(guid, base)
    prov.flush()
    prov.enable_undo(guid)
    # undoable edit adds "mark " at 0; cursor anchored inside it
    b = Y.Doc(gc=False)
    b.client_id = 10
    Y.apply_update(b, base)
    b.get_text("text").insert(0, "mark ")
    wave = Y.encode_state_as_update(b, Y.encode_state_vector(a))
    prov.receive_update(guid, wave, undoable=True)
    prov.flush()
    rp = prov.create_relative_position(guid, 2)  # inside "mark "
    assert prov.resolve_relative_position(guid, rp) == 2
    # CPU twin: same updates + same undo/redo sequence via UndoManager
    cpu = Y.Doc(gc=False)
    Y.apply_update(cpu, base)
    um = Y.UndoManager(cpu.get_text("text"), capture_timeout=0,
                       tracked_origins={"remote"})
    cpu.transact(lambda tr: Y.apply_update(cpu, wave, "remote"), "remote")
    rev = prov.undo(guid)
    assert rev is not None
    um.undo()
    prov.flush()
    rev2 = prov.redo(guid)
    assert rev2 is not None
    um.redo()
    prov.flush()
    assert prov.text(guid) == cpu.get_text("text").to_string()
    got = prov.resolve_relative_position(guid, rp)
    acpu = Y.create_absolute_position_from_relative_position(rp, cpu)
    # follow-redone lands the cursor back inside the redone "mark "
    assert acpu is not None and got == acpu.index == 2
    # contrast: the pure-mirror path has no redone chains (they are
    # replica-local, never on the wire) and resolves past the tombstoned
    # original instead — the documented deviation this test pins
    assert prov.engine.absolute_index_from_relative(0, rp) == 5
