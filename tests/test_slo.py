"""yjs_tpu.obs.slo: convergence-latency SLOs (ISSUE 4 tentpole).

Covers: the zero-wire-change update key (first-struct id + digest
fallback), the origin clock, the receive→integrate→visible pipeline
under a fake clock, multiwindow burn-rate transitions (ok / warning /
page, incl. the required two-provider breach→page test), window
aging, duplicate/rejected handling, bounded pending state, env knobs,
and the CPU-doc protocol seam.
"""

import json

import pytest

import yjs_tpu as Y
from yjs_tpu.lib0.decoding import Decoder
from yjs_tpu.lib0.encoding import Encoder
from yjs_tpu.obs.registry import MetricsRegistry
from yjs_tpu.obs.slo import (
    ConvergenceTracker,
    OriginClock,
    update_key,
)
from yjs_tpu.provider import TpuProvider
from yjs_tpu.sync import protocol
from yjs_tpu.updates import encode_state_as_update, encode_state_vector


def _update(text="hello", client=None):
    d = Y.Doc(gc=False)
    if client is not None:
        d.client_id = client
    d.get_text("text").insert(0, text)
    return encode_state_as_update(d)


class _Clock:
    """Injectable deterministic clock for the tracker's ``now``."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _tracker(clock, **kw):
    kw.setdefault("origins", OriginClock())
    return ConvergenceTracker(MetricsRegistry(), now=clock, **kw)


def _key_bytes(i):
    """Unique unparseable payloads (numClients=0 -> digest fallback)."""
    return b"\x00" + str(i).encode()


# -- update keys -------------------------------------------------------------


def test_update_key_is_first_struct_client_clock():
    u = _update("hi", client=12345)
    assert update_key(u) == (12345, 0)
    # the key is computed from the BYTES both sides transport: identical
    # bytes, identical key, no wire change needed
    assert update_key(bytes(u)) == update_key(u)


def test_update_key_delete_only_digest_fallback():
    d = Y.Doc(gc=False)
    t = d.get_text("text")
    t.insert(0, "abc")
    sv = encode_state_vector(d)
    t.delete(0, 3)
    delete_only = encode_state_as_update(d, sv)
    key = update_key(delete_only)
    assert key[0] == -1  # no struct blocks: digest fallback
    assert key == update_key(delete_only)  # deterministic
    assert key != update_key(b"\x00other")


def test_update_key_garbage_never_raises():
    for junk in (b"", b"\xff\xff\xff\xff", b"\x00"):
        client, _ = update_key(junk)
        assert client == -1


# -- origin clock ------------------------------------------------------------


def test_origin_clock_first_sighting_wins_and_bounded():
    oc = OriginClock(maxlen=4)
    oc.record_once("k", 1.0)
    oc.record_once("k", 99.0)  # later sighting must not overwrite
    assert oc.lookup("k") == 1.0
    for i in range(6):
        oc.record_once(f"x{i}", float(i))
    assert len(oc) <= 4
    assert oc.lookup("k") is None  # oldest evicted


# -- the pipeline under a fake clock -----------------------------------------


def test_pipeline_stages_and_latency_histogram():
    clock = _Clock()
    tr = _tracker(clock, target_ms=250.0)
    u = _update("stage test", client=7)
    clock.t = 1.0
    key = tr.receive(u)
    clock.t = 1.01
    tr.integrated(key)
    clock.t = 1.05
    assert tr.visible() == 1
    snap = tr.snapshot()
    assert snap["completed"] == 1 and snap["pending"] == 0
    assert snap["state"] == "ok"  # 50ms < 250ms target
    lat = tr._latency.summary()
    assert lat["count"] == 1
    assert lat["max"] == pytest.approx(0.05, abs=1e-6)
    # stage decomposition: receive 0 (origin floored at receive),
    # integrate 10ms, visible 40ms
    assert tr._stage["integrate"].summary()["max"] == pytest.approx(
        0.01, abs=1e-6
    )
    assert tr._stage["visible"].summary()["max"] == pytest.approx(
        0.04, abs=1e-6
    )


def test_origin_stamp_measures_true_end_to_end():
    clock = _Clock()
    tr = _tracker(clock, target_ms=250.0)
    u = _update("origin test", client=9)
    clock.t = 0.0
    tr.origin(u)  # emitted now (the broadcasting provider stamps)
    clock.t = 0.4  # transport delay
    key = tr.receive(u)
    tr.integrated(key)
    clock.t = 0.5
    tr.visible()
    # latency is origin->visible (500ms), not receive->visible (100ms)
    assert tr._latency.summary()["max"] == pytest.approx(0.5, abs=1e-6)
    assert tr.snapshot()["state"] == "page"  # 500ms > 250ms, 100% breach


def test_duplicate_delivery_completes_once():
    clock = _Clock()
    tr = _tracker(clock)
    u = _update("dup", client=3)
    k1 = tr.receive(u)
    k2 = tr.receive(u)  # duplicate: first delivery wins
    assert k1 == k2
    tr.integrated(k1)
    assert tr.visible() == 1
    assert tr.visible() == 0  # nothing left
    assert tr.snapshot()["completed"] == 1


def test_rejected_updates_stop_tracking():
    clock = _Clock()
    tr = _tracker(clock)
    key = tr.receive(_update("bad", client=4))
    tr.rejected(key)
    assert tr.visible() == 0
    assert tr.snapshot()["pending"] == 0


def test_unintegrated_pending_survives_flush():
    clock = _Clock()
    tr = _tracker(clock)
    tr.receive(_update("parked", client=5))  # never integrated (parked)
    assert tr.visible() == 0  # a flush does NOT complete it
    assert tr.snapshot()["pending"] == 1


def test_pending_bounded():
    clock = _Clock()
    tr = _tracker(clock, max_pending=8)
    for i in range(50):
        tr.receive(_key_bytes(i))
    assert tr.snapshot()["pending"] <= 8


# -- burn-rate state machine -------------------------------------------------


def _drive(tr, clock, n, breach_every=None, dt=0.001, breach_s=1.0):
    """Complete ``n`` convergences; every ``breach_every``-th one is slow."""
    for i in range(n):
        clock.t += dt
        key = tr.receive(_key_bytes(i))
        tr.integrated(key)
        if breach_every and i % breach_every == 0:
            clock.t += breach_s
        tr.visible()


def test_all_fast_stays_ok():
    clock = _Clock()
    tr = _tracker(clock, target_ms=250.0, window_s=1200.0, objective=0.99)
    _drive(tr, clock, 50)
    snap = tr.snapshot()
    assert snap["state"] == "ok"
    assert snap["burn_rates"]["long"] == 0.0


def test_warning_state_at_moderate_burn():
    clock = _Clock()
    tr = _tracker(clock, target_ms=250.0, window_s=1200.0, objective=0.99)
    # 10% breaches against a 1% budget -> burn 10: warning (>=6, <14.4)
    _drive(tr, clock, 100, breach_every=10)
    snap = tr.snapshot()
    assert snap["state"] == "warning"
    assert snap["burn_rates"]["long"] == pytest.approx(10.0)
    assert snap["windows"]["long"]["breached"] == 10


def test_page_state_at_high_burn():
    clock = _Clock()
    tr = _tracker(clock, target_ms=250.0, window_s=1200.0, objective=0.99)
    # 20% breaches -> burn 20 on BOTH windows: page
    _drive(tr, clock, 50, breach_every=5)
    assert tr.snapshot()["state"] == "page"


def test_breaches_age_out_of_the_windows():
    clock = _Clock()
    tr = _tracker(clock, target_ms=250.0, window_s=10.0, objective=0.99)
    _drive(tr, clock, 10, breach_every=2)  # heavy breaching -> page
    assert tr.snapshot()["state"] == "page"
    clock.t += 100.0  # both windows age out completely
    snap = tr.snapshot()
    assert snap["state"] == "ok"
    assert snap["windows"]["long"]["total"] == 0


def test_env_knobs_configure_tracker(monkeypatch):
    monkeypatch.setenv("YTPU_SLO_CONVERGENCE_MS", "42")
    monkeypatch.setenv("YTPU_SLO_WINDOW", "60")
    monkeypatch.setenv("YTPU_SLO_OBJECTIVE", "0.999")
    tr = ConvergenceTracker(MetricsRegistry(), origins=OriginClock())
    assert tr.target_ms == 42.0
    assert tr.window_s == 60.0
    assert tr.short_window_s == 5.0  # window/12
    assert tr.objective == 0.999


def test_snapshot_is_json_able():
    clock = _Clock()
    tr = _tracker(clock)
    _drive(tr, clock, 3)
    snap = json.loads(json.dumps(tr.snapshot()))
    assert set(snap) >= {
        "target_ms", "window_s", "objective", "state", "burn_rates",
        "windows", "completed", "pending",
    }


# -- two-provider end-to-end (the ISSUE acceptance test) ---------------------


def test_two_provider_breach_transitions_to_page(monkeypatch):
    """Provider A broadcasts, provider B converges; with a 0 ms target
    every real convergence breaches, and B's multiwindow burn rate must
    transition its verdict to ``page``."""
    monkeypatch.setenv("YTPU_SLO_CONVERGENCE_MS", "0")
    a = TpuProvider(4)
    b = TpuProvider(4)
    a.on_update(lambda guid, u: b.receive_update(guid, u))
    for k in range(3):
        d = Y.Doc(gc=False)
        d.get_text("text").insert(0, f"edit {k} ")
        a.receive_update("room", encode_state_as_update(d))
        a.flush()  # emits the broadcast -> B receives
        b.flush()  # B integrates: convergence completes
    assert "edit 0" in b.text("room")
    snap = b.slo_snapshot()
    assert snap["completed"] >= 3
    assert snap["windows"]["long"]["breached"] == snap["windows"]["long"]["total"]
    assert snap["state"] == "page"
    # the verdict also rides the exposition surfaces
    assert b.metrics_snapshot()["slo"]["state"] == "page"
    text = b.metrics_text()
    assert "ytpu_slo_state 2" in text


def test_two_provider_convergence_within_target():
    """With a generous target the same exchange stays ``ok`` and the
    latency histogram records one completion per converged update."""
    a = TpuProvider(4)
    b = TpuProvider(4)
    a.on_update(lambda guid, u: b.receive_update(guid, u))
    d = Y.Doc(gc=False)
    d.get_text("text").insert(0, "hello peer")
    a.receive_update(
        "room", encode_state_as_update(d)
    )
    a.flush()
    b.flush()
    assert b.text("room") == "hello peer"
    fam = b.engine.obs.registry.get("ytpu_convergence_latency_seconds")
    assert fam.count == 1


# -- the CPU-doc protocol seam -----------------------------------------------


def test_protocol_slo_seam_zero_wire_change():
    d1 = Y.Doc(gc=False)
    d1.get_text("text").insert(0, "wire test")
    enc_plain = Encoder()
    protocol.write_update(enc_plain, encode_state_as_update(d1))
    frame = enc_plain.to_bytes()

    clock = _Clock()
    tr = _tracker(clock)
    d2 = Y.Doc(gc=False)
    reply = Encoder()
    mt = protocol.read_sync_message(Decoder(frame), reply, d2, slo=tr)
    assert mt == protocol.MESSAGE_YJS_UPDATE
    assert str(d2.get_text("text")) == "wire test"
    # a CPU Doc integrates synchronously: the pipeline completed inline
    snap = tr.snapshot()
    assert snap["completed"] == 1 and snap["pending"] == 0
    # zero wire change: the tracked frame IS the plain frame
    d3 = Y.Doc(gc=False)
    protocol.read_sync_message(Decoder(frame), Encoder(), d3)
    assert str(d3.get_text("text")) == "wire test"
