"""Distributed-observability unit suite (ISSUE 11): trace-context wire
format and deterministic identity, in-process context propagation, the
black-box flight recorder (ring bound, dump dedupe, file dumps, the
torn-scrape concurrency contract), and cross-shard metrics federation
(merge semantics, file scrape, the fleet router's federated snapshot,
and the ``ytpu_top`` directory mode).

Everything is deterministic: trace ids are keyed hashes of update
bytes, sampling is a residue test, and the concurrency test asserts
structural invariants that hold under any interleaving.
"""

import json
import sys
import threading
from pathlib import Path

import pytest

import yjs_tpu as Y
from yjs_tpu.fleet import FleetRouter
from yjs_tpu.obs.blackbox import (
    FlightRecorder,
    flight_recorder,
    reset_flight_recorder,
)
from yjs_tpu.obs.dist import (
    TRACE_CTX_LEN,
    TraceContext,
    current_context,
    flow_id_for,
    mint_for_update,
    sample_rate,
    trace_metrics,
    use_context,
)
from yjs_tpu.obs.expo import registry_snapshot
from yjs_tpu.obs.federate import (
    federate_snapshots,
    merge_summaries,
    read_snapshot_dir,
)
from yjs_tpu.updates import encode_state_as_update

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "scripts"))

pytestmark = pytest.mark.tracing


# -- trace context: wire + identity ------------------------------------------


def test_trace_context_wire_roundtrip():
    ctx = TraceContext(0xDEADBEEF << 64 | 0x1234, 0xCAFE, True)
    raw = ctx.to_bytes()
    assert len(raw) == TRACE_CTX_LEN
    back = TraceContext.from_bytes(raw)
    assert back == ctx
    assert back.sampled
    # future flag bytes may extend the blob: a longer buffer still
    # parses (only the 25-byte prefix is interpreted)
    assert TraceContext.from_bytes(raw + b"\xff\xff") == ctx
    # unsampled flag survives too
    cold = TraceContext(1, 2, False)
    assert not TraceContext.from_bytes(cold.to_bytes()).sampled


def test_trace_context_rejects_malformed_blobs():
    assert TraceContext.from_bytes(b"") is None
    assert TraceContext.from_bytes(b"\x00" * (TRACE_CTX_LEN - 1)) is None
    assert TraceContext.from_bytes(None) is None
    assert TraceContext.from_bytes("not-bytes") is None


def test_mint_is_deterministic_across_providers(monkeypatch):
    # two providers hashing the same raw update bytes must agree on the
    # trace id AND the sampling verdict — stitching without coordination
    monkeypatch.setenv("YTPU_TRACE_SAMPLE", "1")
    a = mint_for_update(b"update-payload")
    b = mint_for_update(b"update-payload")
    assert a == b
    assert a.sampled
    assert a.trace_hex == b.trace_hex
    assert mint_for_update(b"other-payload") != a
    # salted mints occupy a distinct id space (failover episodes)
    assert mint_for_update(b"update-payload", salt=b"failover") != a


def test_sampling_rate_knob(monkeypatch):
    monkeypatch.setenv("YTPU_TRACE_SAMPLE", "1")
    assert sample_rate() == 1
    assert mint_for_update(b"x").sampled
    monkeypatch.setenv("YTPU_TRACE_SAMPLE", "0")
    assert sample_rate() == 0
    assert not mint_for_update(b"x").sampled
    monkeypatch.setenv("YTPU_TRACE_SAMPLE", "garbage")
    assert sample_rate() == 64  # malformed -> default
    monkeypatch.delenv("YTPU_TRACE_SAMPLE")
    # default head-samples 1-in-64: the verdict is a pure residue test
    ctx = mint_for_update(b"x")
    assert ctx.sampled == (ctx.trace_id % 64 == 0)


def test_force_sampling_preserves_identity(monkeypatch):
    monkeypatch.setenv("YTPU_TRACE_SAMPLE", "0")
    ctx = mint_for_update(b"doomed-update")
    assert not ctx.sampled
    before = trace_metrics().forced.labels(reason="dlq").value
    forced = ctx.force("dlq")
    assert forced.sampled
    assert forced.trace_id == ctx.trace_id
    assert forced.span_id == ctx.span_id
    assert trace_metrics().forced.labels(reason="dlq").value == before + 1
    # already-sampled contexts pass through without a second count
    assert forced.force("dlq") is forced
    assert trace_metrics().forced.labels(reason="dlq").value == before + 1


def test_child_spans_are_deterministic():
    ctx = TraceContext(77, 88, True)
    c1, c2 = ctx.child("flush"), ctx.child("flush")
    assert c1 == c2
    assert c1.trace_id == ctx.trace_id and c1.sampled
    assert c1.span_id != ctx.span_id
    assert ctx.child("repl").span_id != c1.span_id


def test_flow_id_for_is_stable_and_collision_resistant():
    key = ("abc123", "repl", "room-0", 7, 2)
    assert flow_id_for(key) == flow_id_for(key)
    assert flow_id_for(key) != flow_id_for(("abc123", "repl", "room-0", 7, 1))
    ids = {flow_id_for((i, j)) for i in range(50) for j in range(50)}
    assert len(ids) == 2500  # no collisions across a realistic key space
    assert all(isinstance(i, int) and i > 0 for i in ids)


def test_use_context_nests_and_clears():
    assert current_context() is None
    outer = TraceContext(1, 1, True)
    inner = TraceContext(2, 2, True)
    with use_context(outer):
        assert current_context() is outer
        with use_context(inner):
            assert current_context() is inner
        assert current_context() is outer
        with use_context(None):  # nested ingress isolation
            assert current_context() is None
        assert current_context() is outer
    assert current_context() is None


# -- flight recorder ----------------------------------------------------------


def test_ring_bound_and_dropped_accounting():
    rec = FlightRecorder(cap=16)
    for i in range(40):
        rec.record("test", "evt", guid=f"doc-{i}", i=i)
    assert len(rec) == 16
    st = rec.stats()
    assert st["cap"] == 16
    assert st["events"] == 40
    assert st["in_ring"] == 16
    assert st["dropped"] == 40 - 16
    # the ring keeps the NEWEST events (a black box records the crash,
    # not the takeoff)
    snap = rec.snapshot()
    assert snap[0]["kv"]["i"] == 24 and snap[-1]["kv"]["i"] == 39
    assert all(snap[i]["tick"] < snap[i + 1]["tick"]
               for i in range(len(snap) - 1))


def test_record_shapes_entries():
    rec = FlightRecorder(cap=64)
    rec.record("failover", "conviction", severity="error", guid="g",
               tenant="t", shard=2, trace="ab" * 16,
               reason="missed heartbeats", payload=b"\x00" * 9)
    (e,) = rec.snapshot()
    assert e["subsystem"] == "failover" and e["event"] == "conviction"
    assert e["severity"] == "error"
    assert e["guid"] == "g" and e["tenant"] == "t" and e["shard"] == 2
    assert e["trace"] == "ab" * 16
    assert e["kv"]["reason"] == "missed heartbeats"
    assert e["kv"]["payload"] == "<9 bytes>"  # bytes never leak raw
    json.dumps(e)  # every entry must be JSON-able as recorded
    rec.record("x", "y", severity="not-a-severity")
    assert rec.snapshot()[-1]["severity"] == "info"


def test_dump_dedupes_until_new_events():
    rec = FlightRecorder(cap=64)
    rec.record("resilience", "quarantine", severity="error", guid="g")
    out = rec.dump("quarantine", doc="g", cause="boom")
    assert out is not None
    assert out["reason"] == "quarantine" and out["seq"] == 1
    assert out["context"] == {"doc": "g", "cause": "boom"}
    assert len(out["events"]) == 1
    assert rec.last_dump is out
    # a hot failure seam re-dumping with nothing new is suppressed
    assert rec.dump("quarantine", doc="g") is None
    assert rec.stats()["dumps"] == 1
    rec.record("resilience", "quarantine", severity="error", guid="h")
    again = rec.dump("quarantine")
    assert again is not None and again["seq"] == 2
    assert rec.last_dump is again and len(rec.dumps) == 2


def test_dump_writes_json_file(tmp_path, monkeypatch):
    monkeypatch.setenv("YTPU_BLACKBOX_DIR", str(tmp_path / "bb"))
    rec = FlightRecorder(cap=64)
    rec.record("fleet", "shard_killed", shard=1)
    out = rec.dump("failover: shard 1 died", shard=1)
    path = Path(out["path"])
    assert path.parent == tmp_path / "bb"
    assert path.name == "blackbox-failover--shard-1-died-0001.json"
    loaded = json.loads(path.read_text())
    assert loaded["reason"] == "failover: shard 1 died"
    assert loaded["events"] == out["events"]
    assert not list(path.parent.glob("*.tmp"))  # atomic rename, no turds


def test_blackbox_disable_knob(monkeypatch):
    monkeypatch.setenv("YTPU_BLACKBOX", "0")
    rec = FlightRecorder(cap=64)
    rec.record("test", "evt")
    assert len(rec) == 0
    assert rec.dump("anything") is None
    assert rec.stats()["events"] == 0


def test_global_recorder_reset_isolation():
    a = flight_recorder()
    assert flight_recorder() is a
    b = reset_flight_recorder()
    assert b is not a and flight_recorder() is b


def test_concurrent_writers_never_tear_a_scrape():
    """Satellite 3: hammer the recorder from writer threads while other
    threads scrape.  Every scraped entry must be complete (no torn
    dicts), ticks strictly increase, and stats stay self-consistent —
    under the same lock discipline that fixed the FlushHistory race."""
    rec = FlightRecorder(cap=128)
    n_writers, n_events = 4, 300
    stop = threading.Event()
    errors: list = []

    def write(w):
        try:
            for i in range(n_events):
                rec.record("stress", "evt", guid=f"w{w}-{i}", w=w, i=i)
                if i % 50 == 0:
                    rec.dump(f"w{w}")
        except Exception as e:  # pragma: no cover - the assertion
            errors.append(e)

    def scrape():
        try:
            while not stop.is_set():
                for e in rec.snapshot():
                    # a torn entry would miss keys written before the
                    # ring append (entries are fully built pre-lock)
                    assert "subsystem" in e and "event" in e and "tick" in e
                st = rec.stats()
                assert st["in_ring"] <= st["cap"]
                assert st["dropped"] <= st["events"]
                snap = rec.snapshot()
                assert all(snap[i]["tick"] < snap[i + 1]["tick"]
                           for i in range(len(snap) - 1))
        except Exception as e:  # pragma: no cover - the assertion
            errors.append(e)

    writers = [threading.Thread(target=write, args=(w,))
               for w in range(n_writers)]
    scrapers = [threading.Thread(target=scrape) for _ in range(2)]
    for t in scrapers + writers:
        t.start()
    for t in writers:
        t.join()
    stop.set()
    for t in scrapers:
        t.join()
    assert not errors, errors[:3]
    st = rec.stats()
    assert st["events"] == n_writers * n_events
    assert st["in_ring"] == min(128, st["events"])
    assert st["dropped"] == st["events"] - st["in_ring"]


# -- metrics federation -------------------------------------------------------


def _summary(count, total, mn, mx, p50, p95, p99):
    return {"count": count, "sum": total, "min": mn, "max": mx,
            "p50": p50, "p95": p95, "p99": p99}


def test_merge_summaries_weighted():
    merged = merge_summaries([
        _summary(3, 30.0, 5.0, 15.0, 10.0, 14.0, 15.0),
        _summary(1, 100.0, 100.0, 100.0, 100.0, 100.0, 100.0),
        _summary(0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0),  # empty part ignored
    ])
    assert merged["count"] == 4
    assert merged["sum"] == 130.0
    assert merged["min"] == 5.0 and merged["max"] == 100.0
    # count-weighted estimate: (3*10 + 1*100) / 4, clamped to [min,max]
    assert merged["p50"] == pytest.approx(32.5)
    empty = merge_summaries([])
    assert empty["count"] == 0 and empty["p99"] == 0.0


def test_federate_counters_sum_gauges_keep_shards():
    s0 = {
        "counters": {"ytpu_x_total": {"": 3, "kind=a": 1}},
        "gauges": {"ytpu_depth": {"": 5}},
        "histograms": {"ytpu_lat": {"": _summary(2, 2.0, 0.5, 1.5,
                                                 1.0, 1.5, 1.5)}},
    }
    s1 = {
        "counters": {"ytpu_x_total": {"": 4}},
        "gauges": {"ytpu_depth": {"": 7}},
        "histograms": {"ytpu_lat": {"": _summary(2, 6.0, 2.0, 4.0,
                                                 3.0, 4.0, 4.0)}},
    }
    fed = federate_snapshots([
        {"label": "0", "role": "primary", "snapshot": s0},
        {"label": "1", "role": "replica", "snapshot": s1},
    ])
    # counters: summed per labels-key
    assert fed["counters"]["ytpu_x_total"][""] == 7
    assert fed["counters"]["ytpu_x_total"]["kind=a"] == 1
    # gauges: per-shard labeled series AND the unlabeled aggregate
    assert fed["gauges"]["ytpu_depth"]["shard=0,role=primary"] == 5
    assert fed["gauges"]["ytpu_depth"]["shard=1,role=replica"] == 7
    assert fed["gauges"]["ytpu_depth"][""] == 12
    # histograms: counts/sums add, min/max widen, quantiles weighted
    lat = fed["histograms"]["ytpu_lat"][""]
    assert lat["count"] == 4 and lat["sum"] == 8.0
    assert lat["min"] == 0.5 and lat["max"] == 4.0
    assert lat["p50"] == pytest.approx(2.0)
    assert fed["federation"] == {
        "sources": 2, "roles": {"0": "primary", "1": "replica"},
        "stale": [],
    }


def test_federate_layers_global_once():
    shard = {"counters": {"ytpu_x_total": {"": 1}}}
    glob = {"counters": {"ytpu_x_total": {"": 999},
                         "ytpu_fleet_total": {"": 10}}}
    fed = federate_snapshots(
        [{"label": str(k), "snapshot": shard} for k in range(3)],
        global_snapshot=glob,
    )
    # the shard-local family wins (never double-counted with global)...
    assert fed["counters"]["ytpu_x_total"][""] == 3
    # ...and the shared global family is layered exactly once, not x3
    assert fed["counters"]["ytpu_fleet_total"][""] == 10


def test_read_snapshot_dir(tmp_path):
    (tmp_path / "shard-1.json").write_text(json.dumps(
        {"role": "replica", "counters": {"ytpu_x_total": {"": 2}}}
    ))
    (tmp_path / "shard-0.json").write_text(json.dumps(
        {"counters": {"ytpu_x_total": {"": 1}}}
    ))
    (tmp_path / "torn.json").write_text('{"counters": {')  # mid-write
    (tmp_path / "notes.txt").write_text("ignored")
    sources = read_snapshot_dir(str(tmp_path))
    assert [s["label"] for s in sources] == ["shard-0", "shard-1", "torn"]
    assert sources[1]["role"] == "replica"
    assert sources[2]["snapshot"] == {}  # unreadable -> blank row
    fed = federate_snapshots(sources)
    assert fed["counters"]["ytpu_x_total"][""] == 3
    assert read_snapshot_dir(str(tmp_path / "missing")) == []


def test_router_snapshot_is_federated():
    fleet = FleetRouter(3, 2, backend="cpu")
    d = Y.Doc(gc=False)
    d.client_id = 7
    d.get_text("text").insert(0, "hello fleet")
    fleet.receive_update("room-0", encode_state_as_update(d))
    fleet.flush()
    snap = fleet.metrics_snapshot()
    fed = snap["federation"]
    assert fed["sources"] == 3
    assert set(fed["roles"]) == {"0", "1", "2"}
    # per-shard gauge series exist alongside the unlabeled aggregate the
    # single-provider dashboards keep reading
    pend = snap["gauges"]["ytpu_engine_pending_docs"]
    assert "" in pend
    assert any(k.startswith("shard=0") for k in pend)
    # engine-local counters summed across shards match the edit we made
    flushes = snap["counters"]["ytpu_engine_flushes_total"]
    assert sum(v for k, v in flushes.items() if k == "") >= 1
    # the shared process-global families are present but NOT multiplied
    assert snap["gauges"]["ytpu_fed_sources"][""] == 3
    assert "fleet" in snap and "admission" in snap


def test_ytpu_top_directory_mode(tmp_path):
    import ytpu_top

    fleet = FleetRouter(2, 2, backend="cpu")
    d = Y.Doc(gc=False)
    d.client_id = 9
    d.get_text("text").insert(0, "dir mode")
    fleet.receive_update("room-0", encode_state_as_update(d))
    fleet.flush()
    for k, p in enumerate(fleet.shards):
        snap = registry_snapshot(p.engine.obs.registry)
        snap["role"] = "primary" if k == 0 else "replica"
        (tmp_path / f"shard-{k}.json").write_text(json.dumps(snap))
    rows = ytpu_top.DirSource(str(tmp_path)).poll()
    assert [name for name, _ in rows] == ["FLEET", "shard-0", "shard-1"]
    fleet_snap = rows[0][1]
    assert fleet_snap["federation"]["sources"] == 2
    # every row renders through the shared column collector
    rendered = [
        ytpu_top.collect_row(name, s, None, 1.0) for name, s in rows
    ]
    assert rendered[0]["provider"] == "FLEET"
    assert all("flushes" in r for r in rendered)
