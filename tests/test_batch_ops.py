"""Doc-free columnar batch ops vs the doc-level oracle
(yjs_tpu.updates.merge_updates / diff_update)."""

import random

import pytest

import yjs_tpu as Y
from yjs_tpu.ops import (
    diff_update_columnar,
    encode_state_vector_from_update_columnar,
    merge_updates_columnar,
)
from yjs_tpu.updates import (
    diff_update,
    encode_state_vector_from_update,
    merge_updates,
)


def _state(update: bytes, v2: bool = False):
    d = Y.Doc(gc=False)
    (Y.apply_update_v2 if v2 else Y.apply_update)(d, update)
    return (
        d.get_text("text").to_string(),
        d.get_map("map").to_json(),
        Y.decode_state_vector(Y.encode_state_vector(d)),
    )


def _concurrent_updates(seed: int, v2: bool = False):
    gen = random.Random(seed)
    docs = []
    updates = []
    for i in range(3):
        d = Y.Doc(gc=False)
        d.client_id = i + 1
        docs.append(d)
    base = None
    for step in range(25):
        d = gen.choice(docs)
        op = gen.random()
        if op < 0.6:
            t = d.get_text("text")
            ln = len(t.to_string())
            if gen.random() < 0.7 or ln == 0:
                t.insert(gen.randint(0, ln), gen.choice(["x", "yy🙂", "z "]))
            else:
                pos = gen.randrange(ln)
                t.delete(pos, min(gen.randint(1, 2), ln - pos))
        else:
            d.get_map("map").set(gen.choice("ab"), gen.randrange(50))
        if gen.random() < 0.3:
            src, dst = gen.choice(docs), gen.choice(docs)
            Y.apply_update(dst, Y.encode_state_as_update(src))
    enc = Y.encode_state_as_update_v2 if v2 else Y.encode_state_as_update
    return [enc(d) for d in docs]


@pytest.mark.parametrize("seed", range(3))
def test_merge_matches_oracle(seed):
    updates = _concurrent_updates(seed)
    merged_col = merge_updates_columnar(updates)
    merged_doc = merge_updates(updates)
    assert _state(merged_col) == _state(merged_doc)


def test_merge_v2_in_v1_out_and_back():
    updates_v2 = _concurrent_updates(9, v2=True)
    # V2 in, V1 out: one-pass format conversion during the merge
    merged_v1 = merge_updates_columnar(updates_v2, v2=True, out_v2=False)
    merged_v2 = merge_updates_columnar(updates_v2, v2=True)
    assert _state(merged_v1) == _state(merged_v2, v2=True)


@pytest.mark.parametrize("seed", range(3))
def test_diff_matches_oracle(seed):
    updates = _concurrent_updates(100 + seed)
    merged = merge_updates(updates)
    # a peer that saw only the first update asks for the rest
    peer_sv = encode_state_vector_from_update_columnar(updates[0])
    diff_col = diff_update_columnar(merged, peer_sv)
    diff_doc = diff_update(merged, peer_sv)
    # applying either diff on top of the peer's state converges identically
    for diff in (diff_col, diff_doc):
        d = Y.Doc(gc=False)
        Y.apply_update(d, updates[0])
        Y.apply_update(d, diff)
        assert _state(Y.encode_state_as_update(d)) == _state(merged)


def test_incomplete_deps_withheld_like_oracle():
    # an update missing its causal prefix: both paths withhold the structs
    d = Y.Doc(gc=False)
    d.client_id = 5
    d.get_text("text").insert(0, "one ")
    sv = Y.encode_state_vector(d)
    d.get_text("text").insert(4, "two ")
    tail_only = Y.encode_state_as_update(d, sv)
    assert _state(merge_updates_columnar([tail_only])) == _state(
        merge_updates([tail_only])
    )


def test_subdoc_updates_fall_back_to_oracle():
    d = Y.Doc(gc=False)
    d.client_id = 5
    d.get_map("m").set("sub", Y.Doc(guid="child"))
    d.get_text("text").insert(0, "t")
    u = Y.encode_state_as_update(d)
    merged = merge_updates_columnar([u])
    assert _state(merged) == _state(merge_updates([u]))
    sv = encode_state_vector_from_update_columnar(u)
    assert Y.decode_state_vector(sv) == Y.decode_state_vector(
        encode_state_vector_from_update(u)
    )
    assert _state(diff_update_columnar(u, Y.encode_state_vector(Y.Doc(gc=False)))) \
        == _state(u)


def test_state_vector_from_update():
    updates = _concurrent_updates(7)
    merged = merge_updates(updates)
    assert Y.decode_state_vector(
        encode_state_vector_from_update_columnar(merged)
    ) == Y.decode_state_vector(encode_state_vector_from_update(merged))
