"""Differential tests: NativeMirror (C++ plan core) vs DocMirror (Python
oracle).  The two implement the same flush pipeline (reference
encoding.js:225-321 recast per SURVEY.md §7); plans and columns must agree
step for step on arbitrary traffic."""

import random

import pytest

import yjs_tpu as Y
from yjs_tpu.ops.columns import DocMirror, UnsupportedUpdate
from yjs_tpu.ops.native_mirror import NativeMirror, native_plan_available

pytestmark = pytest.mark.skipif(
    not native_plan_available(), reason="native plan core unavailable"
)

COLS = (
    "row_slot", "row_clock", "row_len", "row_origin_slot",
    "row_origin_clock", "row_right_slot", "row_right_clock", "row_is_gc",
    "row_countable", "row_content_ref", "row_seg", "client_of_slot",
    "state", "seg_info", "list_next", "head_of_seg",
)


def assert_step_equal(pm, nm, pp, np_, ctx=""):
    assert pm.n_rows == nm.n_rows, ctx
    assert pp.n_levels == np_.n_levels, ctx
    assert getattr(pp, "max_width", 0) == np_.max_width, ctx
    assert pp.splits == list(map(tuple, np_.splits.tolist())), ctx
    assert pp.sched == list(map(tuple, np_.sched.tolist())), ctx
    assert pp.sched8 == list(map(tuple, np_.sched8.tolist())), ctx
    assert pp.levels == np_.levels.tolist(), ctx
    assert sorted(pp.delete_rows) == sorted(np_.delete_rows.tolist()), ctx
    assert sorted(pp.applied_ds) == sorted(np_.applied_ds), ctx
    # bulk-apply form: final link/head values must agree exactly
    assert pp.link_rows == np_.link_rows.tolist(), ctx
    assert pp.link_vals == np_.link_vals.tolist(), ctx
    assert pp.head_segs == np_.head_segs.tolist(), ctx
    assert pp.head_vals == np_.head_vals.tolist(), ctx


def assert_state_equal(pm, nm, ctx="", encode=True):
    for attr in COLS:
        assert list(getattr(pm, attr)) == list(getattr(nm, attr)), (
            f"{attr} differs {ctx}"
        )
    assert pm.state_vector() == nm.state_vector(), ctx
    assert pm.has_pending() == nm.has_pending(), ctx
    assert pm.pending_depth() == nm.pending_depth(), ctx
    sp, sn = pm.static_columns(), nm.static_columns()
    for k in sp:
        assert (sp[k] == sn[k]).all(), f"static {k} {ctx}"
    assert pm.map_chain == {
        k: list(v) for k, v in nm.map_chain.items()
    }, ctx
    assert pm._lww_deleted == nm._lww_deleted, ctx
    assert pm._host_deleted_rows == nm._host_deleted_rows, ctx
    if encode:
        assert pm.encode_state_vector() == nm.encode_state_vector(), ctx
        # state equivalence of the wire encodes (bytes may differ when the
        # Python mirror spills realized content; decoded state must not)
        a, b = Y.Doc(gc=False), Y.Doc(gc=False)
        Y.apply_update(a, pm.encode_state_as_update())
        Y.apply_update(b, nm.encode_state_as_update())
        assert Y.encode_state_as_update(a) is not None
        assert a.get_text("text").to_string() == b.get_text("text").to_string(), ctx
        assert Y.decode_state_vector(
            Y.encode_state_vector(a)
        ) == Y.decode_state_vector(Y.encode_state_vector(b)), ctx


def run_differential(updates, v2=False, flush_every=1):
    pm, nm = DocMirror("text"), NativeMirror("text")
    for j, u in enumerate(updates):
        pm.ingest(u, v2)
        nm.ingest(u, v2)
        if (j + 1) % flush_every == 0 or j == len(updates) - 1:
            pp = pm.prepare_step(want_levels=True)
            np_ = nm.prepare_step(want_levels=True)
            assert_step_equal(pm, nm, pp, np_, ctx=f"flush after update {j}")
    assert_state_equal(pm, nm, ctx="final")
    return pm, nm


def two_client_session(rng, n_rounds, rich=False, astral=False):
    """Concurrent editing session; returns the per-round deltas of both
    clients (interleaved) plus the final docs."""
    a = Y.Doc(gc=False); a.client_id = 100
    b = Y.Doc(gc=False); b.client_id = 200
    updates = []
    words = ["alpha ", "beta ", "gamma", "δδ ", "é "]
    if astral:
        words += ["x\U0001F600y", "\U0001F680\U0001F680"]
    for _ in range(n_rounds):
        for d in (a, b):
            sv = Y.encode_state_vector(d)
            t = d.get_text("text")
            m = d.get_map("meta")
            arr = d.get_array("list")
            op = rng.random()
            if op < 0.45 or len(t) == 0:
                t.insert(rng.randint(0, len(t)), rng.choice(words))
            elif op < 0.65:
                pos = rng.randrange(len(t))
                t.delete(pos, min(rng.randint(1, 5), len(t) - pos))
            elif op < 0.75:
                m.set(rng.choice("abc"), rng.randint(0, 99))
            elif op < 0.85:
                arr.insert(
                    rng.randint(0, len(arr)),
                    [rng.randint(0, 9), "s", None, True],
                )
            elif rich:
                if rng.random() < 0.5 and len(t) > 2:
                    pos = rng.randrange(len(t) - 1)
                    t.format(pos, 2, {"bold": True})
                else:
                    nested = Y.YMap()
                    m.set("nested", nested)
                    nested.set("k", rng.randint(0, 9))
            elif len(t) > 0:
                pos = rng.randrange(len(t))
                t.delete(pos, min(1, len(t) - pos))
            updates.append(Y.encode_state_as_update(d, sv))
        if rng.random() < 0.4:  # cross-sync so edits become concurrent
            ua = Y.encode_state_as_update(a, Y.encode_state_vector(b))
            ub = Y.encode_state_as_update(b, Y.encode_state_vector(a))
            Y.apply_update(b, ua)
            Y.apply_update(a, ub)
    ua = Y.encode_state_as_update(a, Y.encode_state_vector(b))
    ub = Y.encode_state_as_update(b, Y.encode_state_vector(a))
    Y.apply_update(b, ua)
    Y.apply_update(a, ub)
    updates += [ua, ub]
    return updates, a, b


def test_plain_text_session(rng):
    updates, a, _ = two_client_session(rng, 60)
    pm, nm = run_differential(updates, flush_every=3)
    # converged content matches the CPU doc
    assert pm.state_vector() == {
        c: v for c, v in Y.get_state_vector(a.store).items() if v > 0
    }


def test_rich_session_maps_nested_formats(rng):
    updates, _, _ = two_client_session(rng, 60, rich=True)
    run_differential(updates, flush_every=2)


def test_astral_surrogate_splits(rng):
    updates, _, _ = two_client_session(rng, 40, astral=True)
    run_differential(updates, flush_every=1)


def test_random_delivery_order_pending(rng):
    updates, _, _ = two_client_session(rng, 50)
    shuffled = list(updates)
    rng.shuffle(shuffled)
    run_differential(shuffled, flush_every=4)


def test_v2_wire(rng):
    from yjs_tpu.coding import use_v1_encoding, use_v2_encoding

    use_v2_encoding()
    try:
        updates, _, _ = two_client_session(rng, 40, rich=True)
    finally:
        use_v1_encoding()
    run_differential(updates, v2=True, flush_every=2)


def test_gc_tombstones_in_updates(rng):
    # a doc WITH gc produces GC structs in its full-state updates
    d = Y.Doc(gc=True)
    d.client_id = 77
    t = d.get_text("text")
    t.insert(0, "hello world, this will be partially gc'd")
    t.delete(3, 10)
    t.insert(5, "more")
    u = Y.encode_state_as_update(d)
    run_differential([u])


def test_subdocument_raises_unsupported():
    d = Y.Doc(gc=False)
    d.client_id = 5
    sub = Y.Doc()
    d.get_map("m").set("sub", sub)
    u = Y.encode_state_as_update(d)
    nm = NativeMirror("text")
    nm.ingest(u)
    with pytest.raises(UnsupportedUpdate):
        nm.prepare_step()


def test_malformed_raises_like_python():
    nm = NativeMirror("text")
    nm.ingest(b"\x9f\x83garbage!!\x00\xff")
    with pytest.raises(Exception) as native_err:
        nm.prepare_step()
    pm = DocMirror("text")
    pm.ingest(b"\x9f\x83garbage!!\x00\xff")
    with pytest.raises(Exception) as py_err:
        pm.prepare_step()
    assert type(native_err.value) is type(py_err.value)
    assert not isinstance(native_err.value, UnsupportedUpdate)


def test_compaction_parity(rng):
    """Full engine-level compaction: run the same traffic through two
    engines (one per mirror backend) and compare exports after compaction
    triggers."""
    import os

    from yjs_tpu.ops import BatchEngine

    updates, a, _ = two_client_session(rng, 80)
    texts = {}
    for backend in ("native", "python"):
        if backend == "python":
            os.environ["YTPU_NO_NATIVE_PLAN"] = "1"
        try:
            eng = BatchEngine(1, compact_min_rows=8, gc=True)
            for j, u in enumerate(updates):
                eng.queue_update(0, u)
                if j % 5 == 4:
                    eng.flush()
            eng.flush()
            texts[backend] = (
                eng.text(0),
                eng.state_vector(0),
                eng.to_json(0, "list"),
                eng.map_json(0, "meta"),
            )
        finally:
            os.environ.pop("YTPU_NO_NATIVE_PLAN", None)
    assert texts["native"] == texts["python"]
    assert texts["native"][0] == a.get_text("text").to_string()


def test_apply_vs_levels_vs_seq_device_state(rng):
    """The three kernel paths (bulk apply / level-parallel YATA / per-item
    YATA scan) must produce identical device link state and exports."""
    import os

    from yjs_tpu.ops import BatchEngine
    import numpy as np

    updates, a, _ = two_client_session(rng, 50, rich=True)
    states = {}
    for mode in ("apply", "levels", "seq"):
        os.environ["YTPU_KERNEL"] = mode
        try:
            eng = BatchEngine(2)
            for j, u in enumerate(updates):
                eng.queue_update(0, u)
                eng.queue_update(1, u)
                if j % 7 == 6:
                    eng.flush()
            eng.flush()
            n = eng.mirrors[0].n_rows
            states[mode] = (
                np.asarray(eng._right)[:, :n].tolist(),
                np.asarray(eng._deleted)[:, :n].tolist(),
                np.asarray(eng._starts).tolist(),
                eng.text(0),
                eng.map_json(0, "meta"),
                eng.to_json(0, "list"),
            )
        finally:
            os.environ.pop("YTPU_KERNEL", None)
    assert states["apply"] == states["levels"]
    assert states["apply"] == states["seq"]
    assert states["apply"][3] == a.get_text("text").to_string()


def test_host_links_match_device(rng):
    """The planner's host list state IS the device state after a flush."""
    import numpy as np

    from yjs_tpu.ops import BatchEngine

    updates, _, _ = two_client_session(rng, 40)
    eng = BatchEngine(1)
    for j, u in enumerate(updates):
        eng.queue_update(0, u)
        if j % 5 == 4:
            eng.flush()
    eng.flush()
    m = eng.mirrors[0]
    n = m.n_rows
    dev_right = np.asarray(eng._right)[0, :n]
    host_next = np.asarray(m.list_next if hasattr(m, "list_next")
                           else m._py.list_next)
    # device rows never touched by any list stay NULL on both sides
    assert (dev_right == host_next[:n]).all()
    dev_starts = np.asarray(eng._starts)[0, : m.n_segs]
    host_heads = np.asarray(m.head_of_seg if hasattr(m, "head_of_seg")
                            else m._py.head_of_seg)
    assert (dev_starts == host_heads).all()


def test_deleted_run_split_stays_deleted():
    """Splitting an already-deleted run in a LATER flush must ship the new
    fragment's deleted bit on the bulk-apply path (r3 review finding: the
    levels/seq kernels copy it in their on-device split surgery, the apply
    path has none — without the host-emitted delete lane the fragment's
    text resurrected)."""
    import os

    from yjs_tpu.ops import BatchEngine

    a = Y.Doc(gc=False)
    a.client_id = 1
    a.get_text("text").insert(0, "hello")
    u1 = Y.encode_state_as_update(a)
    sv1 = Y.encode_state_vector(a)
    # B diverges BEFORE the delete: its insert's origin is mid-run
    b = Y.Doc(gc=False)
    b.client_id = 2
    Y.apply_update(b, u1)
    a.get_text("text").delete(0, 5)
    u2 = Y.encode_state_as_update(a, sv1)
    b.get_text("text").insert(1, "X")
    u3 = Y.encode_state_as_update(b, sv1)
    Y.apply_update(a, u3)
    expect = a.get_text("text").to_string()
    assert expect == "X"
    for mode in ("apply", "levels", "seq"):
        os.environ["YTPU_KERNEL"] = mode
        try:
            eng = BatchEngine(1)
            for u in (u1,):
                eng.queue_update(0, u)
            eng.flush()
            eng.queue_update(0, u2)
            eng.flush()
            eng.queue_update(0, u3)
            eng.flush()
            assert eng.text(0) == expect, f"{mode}: {eng.text(0)!r}"
        finally:
            os.environ.pop("YTPU_KERNEL", None)


def test_native_v2_encode_byte_parity(rng):
    """Native V2 wire encode (plancore ymx_encode_diff_v2) is byte-identical
    to the pure-Python UpdateEncoderV2 writer on fuzzed traffic, including
    diffs against arbitrary state vectors (reference UpdateEncoder.js:
    264-408)."""
    from yjs_tpu.coding import use_v1_encoding, use_v2_encoding

    for wire_v2 in (False, True):
        if wire_v2:
            use_v2_encoding()
        try:
            updates, a, _ = two_client_session(rng, 50, rich=True, astral=True)
        finally:
            use_v1_encoding()
        pm, nm = DocMirror("text"), NativeMirror("text")
        for u in updates:
            pm.ingest(u, wire_v2)
            nm.ingest(u, wire_v2)
        pm.prepare_step()
        nm.prepare_step()
        svs = [None, {a.client_id: 7},
               Y.decode_state_vector(Y.encode_state_vector(a))]
        for sv in svs:
            pb = pm.encode_state_as_update(sv, v2=True)
            nb = nm.encode_state_as_update(sv, v2=True)
            assert pb == nb, (
                f"v2 encode differs (src_v2={wire_v2}, sv={sv}): "
                f"{len(pb)} vs {len(nb)}"
            )
            # and the bytes round-trip into an equivalent doc
            d = Y.Doc(gc=False)
            Y.apply_update_v2(d, nb)
            if sv is None:
                assert (
                    d.get_text("text").to_string()
                    == a.get_text("text").to_string()
                )


def test_host_export_matches_device(rng):
    """The default (host list walk) export equals the device-rank export
    on fuzzed traffic — the per-doc device dispatch in exports is gone
    from the product path but stays the verification path."""
    from yjs_tpu.ops import BatchEngine

    updates, a, _ = two_client_session(rng, 50, rich=True)
    eng = BatchEngine(1)
    for j, u in enumerate(updates):
        eng.queue_update(0, u)
        if j % 6 == 5:
            eng.flush()
    eng.flush()
    eng.export_from_device = False
    host = (eng.rows_in_order(0), eng.text(0), eng.to_json(0, "list"),
            eng.map_json(0, "meta"), eng.to_delta(0))
    eng.export_from_device = True
    dev = (eng.rows_in_order(0), eng.text(0), eng.to_json(0, "list"),
           eng.map_json(0, "meta"), eng.to_delta(0))
    assert host == dev
    assert host[1] == a.get_text("text").to_string()


def test_broadcast_kernels_agree(rng):
    """The broadcast YATA kernel (batch_step_levels_shared: one schedule,
    vmap in_axes=None) and the broadcast bulk apply (apply_plan_shared:
    host-resolved final links) produce identical device state — the
    kernel-level form of the apply/levels/seq engine cross-check, on the
    B4-replay shape."""
    import jax.numpy as jnp
    import numpy as np

    from yjs_tpu.ops import kernels
    from yjs_tpu.ops.columns import NULL, DocMirror

    updates, a, _ = two_client_session(rng, 40)
    mirror = DocMirror("text")
    for u in updates:
        mirror.ingest(u)
    plan = mirror.prepare_step(want_levels=True)
    n = mirror.n_rows
    n_docs = 4
    w_pad = max((plan.max_width, 1))
    cap = max(64, n + 2 * w_pad)
    seg_cap = max(8, mirror.n_segs)
    cols = mirror.static_columns()

    def pad_col(key, fill, dtype):
        arr = np.full((cap + 1,), fill, dtype)
        arr[:n] = cols[key]
        return arr

    statics = {
        "client_key": jnp.asarray(pad_col("client_key", 0, np.uint32)),
        "origin_slot": jnp.asarray(pad_col("origin_slot", NULL, np.int32)),
        "origin_clock": jnp.asarray(pad_col("origin_clock", 0, np.int32)),
        "right_slot": jnp.asarray(pad_col("right_slot", NULL, np.int32)),
        "right_clock": jnp.asarray(pad_col("right_clock", 0, np.int32)),
        "origin_row": jnp.asarray(pad_col("origin_row", NULL, np.int32)),
    }
    packed = plan.packed_levels()
    lv = np.full((max(1, len(packed)), w_pad, 8), NULL, np.int32)
    for j, entries in enumerate(packed):
        if entries:
            lv[j, : len(entries)] = entries
    splits = np.full((max(1, len(plan.splits)), 2), NULL, np.int32)
    if plan.splits:
        splits[: len(plan.splits)] = np.asarray(plan.splits, np.int32)
    dels = np.full((max(1, len(plan.delete_rows)),), NULL, np.int32)
    if plan.delete_rows:
        dels[: len(plan.delete_rows)] = np.asarray(plan.delete_rows, np.int32)

    def fresh():
        return (
            jnp.full((n_docs, cap + 1), NULL, jnp.int32),
            jnp.zeros((n_docs, cap + 1), bool),
            jnp.full((n_docs, seg_cap + 1), NULL, jnp.int32),
        )

    out_yata = kernels.batch_step_levels_shared(
        statics, fresh(), jnp.asarray(splits), jnp.asarray(lv),
        jnp.asarray(dels), jnp.full((n_docs,), n, jnp.int32),
    )

    def pad_lanes(idx, vals, minimum, oob):
        k = len(idx)
        padded = max(minimum, 1 << max(0, (k - 1).bit_length()))
        i = np.full(padded, oob, np.int32)
        i[:k] = np.asarray(idx, np.int32)
        if vals is None:
            return i
        v = np.full(padded, NULL, np.int32)
        v[:k] = np.asarray(vals, np.int32)
        return i, v

    rows_p, vals_p = pad_lanes(plan.link_rows, plan.link_vals, 64, cap + 1)
    segs_p, hvals_p = pad_lanes(plan.head_segs, plan.head_vals, 8, seg_cap + 1)
    dels_p = pad_lanes(plan.delete_rows, None, 64, cap + 1)
    lanes = jnp.asarray(np.concatenate([rows_p, vals_p, segs_p, hvals_p, dels_p]))
    out_apply = kernels.apply_plan_shared(
        fresh(), lanes, len(rows_p), len(segs_p), len(dels_p)
    )
    for name, x, y in zip(("right", "deleted", "starts"), out_yata, out_apply):
        xa, ya = np.asarray(x), np.asarray(y)
        if name != "starts":
            xa, ya = xa[:, :n], ya[:, :n]
        else:
            xa, ya = xa[:, : mirror.n_segs], ya[:, : mirror.n_segs]
        assert (xa == ya).all(), name


def test_pool_width_engine_state_identical(monkeypatch):
    """Plans must be bit-identical at any worker-pool width: same updates
    flushed under YTPU_PLAN_THREADS=1 and =4 produce identical engine
    text, state vectors, and link/deleted exports (oversubscription on a
    1-core host exercises the pool code path either way)."""
    import random

    import numpy as np

    import yjs_tpu as Y
    from yjs_tpu.ops import BatchEngine

    def mk(seed):
        gen = random.Random(seed)
        a = Y.Doc(gc=False)
        a.client_id = 900 + seed
        b = Y.Doc(gc=False)
        b.client_id = 950 + seed
        for _ in range(120):
            d = a if gen.random() < 0.5 else b
            t = d.get_text("text")
            ln = len(t.to_string())
            if gen.random() < 0.7 or ln == 0:
                t.insert(gen.randint(0, ln), gen.choice(["ab", "c ", "🙂"]))
            else:
                pos = gen.randrange(ln)
                t.delete(pos, min(gen.randint(1, 3), ln - pos))
            if gen.random() < 0.2:
                ua = Y.encode_state_as_update(a, Y.encode_state_vector(b))
                ub = Y.encode_state_as_update(b, Y.encode_state_vector(a))
                Y.apply_update(b, ua)
                Y.apply_update(a, ub)
        u = Y.encode_state_as_update(a, Y.encode_state_vector(b))
        Y.apply_update(b, u)
        return Y.encode_state_as_update(a)

    updates = [mk(s) for s in range(12)]

    def run(width):
        monkeypatch.setenv("YTPU_PLAN_THREADS", width)
        eng = BatchEngine(len(updates))
        for i, u in enumerate(updates):
            eng.queue_update(i, u)
        eng.flush()
        out = []
        for i in range(len(updates)):
            out.append((eng.text(i), tuple(sorted(eng.state_vector(i).items()))))
        links = np.asarray(eng._right)
        dels = np.asarray(eng._deleted)
        return out, links, dels

    out1, l1, d1 = run("1")
    out4, l4, d4 = run("4")
    assert out1 == out4
    assert (l1 == l4).all()
    assert (d1 == d4).all()
