"""Round-trip tests for the lib0-compatible binary primitives."""

import random

from yjs_tpu.lib0 import decoding, encoding
from yjs_tpu.lib0.encoding import UNDEFINED


def test_var_uint_roundtrip():
    values = [0, 1, 127, 128, 255, 256, 16383, 16384, 2**31 - 1, 2**32 - 1, 2**53 - 1]
    enc = encoding.Encoder()
    for v in values:
        encoding.write_var_uint(enc, v)
    dec = decoding.Decoder(enc.to_bytes())
    for v in values:
        assert decoding.read_var_uint(dec) == v


def test_var_int_roundtrip():
    values = [0, 1, -1, 63, -63, 64, -64, 127, -128, 2**31 - 1, -(2**31), 2**40]
    enc = encoding.Encoder()
    for v in values:
        encoding.write_var_int(enc, v)
    dec = decoding.Decoder(enc.to_bytes())
    for v in values:
        assert decoding.read_var_int(dec) == v


def test_var_int_negative_zero():
    enc = encoding.Encoder()
    encoding.write_var_int(enc, 0, negative_zero=True)
    dec = decoding.Decoder(enc.to_bytes())
    num, sign = decoding.read_var_int_signed(dec)
    assert num == 0 and sign == -1


def test_var_string_roundtrip():
    values = ["", "hello", "héllo wörld", "こんにちは", "a" * 1000, "emoji \U0001f600 pair"]
    enc = encoding.Encoder()
    for v in values:
        encoding.write_var_string(enc, v)
    dec = decoding.Decoder(enc.to_bytes())
    from yjs_tpu.lib0.u16 import from_u16

    for v in values:
        assert from_u16(decoding.read_var_string(dec)) == v


def test_any_roundtrip():
    values = [
        None,
        UNDEFINED,
        True,
        False,
        0,
        -1,
        42,
        2**31 - 1,
        -(2**31),
        2**40,  # exceeds BITS31 -> float64
        1.5,
        -0.25,
        3.141592653589793,
        "string",
        b"\x00\x01\x02",
        [1, "two", None, [3]],
        {"a": 1, "b": {"c": [True]}},
    ]
    enc = encoding.Encoder()
    encoding.write_any(enc, values)
    out = decoding.read_any(decoding.Decoder(enc.to_bytes()))
    assert out == values


def test_any_integral_float_is_int():
    enc = encoding.Encoder()
    encoding.write_any(enc, 5.0)
    assert decoding.read_any(decoding.Decoder(enc.to_bytes())) == 5


def test_rle_encoder_roundtrip():
    rng = random.Random(42)
    values = [rng.choice([1, 2, 3]) for _ in range(1000)]
    enc = encoding.RleEncoder()
    for v in values:
        enc.write(v)
    dec = decoding.RleDecoder(enc.to_bytes())
    for v in values:
        assert dec.read() == v


def test_uint_opt_rle_roundtrip():
    rng = random.Random(7)
    values = []
    for _ in range(100):
        v = rng.randint(0, 2**20)
        values.extend([v] * rng.randint(1, 10))
    values.extend([0, 0, 0, 5, 0])
    enc = encoding.UintOptRleEncoder()
    for v in values:
        enc.write(v)
    dec = decoding.UintOptRleDecoder(enc.to_bytes())
    for v in values:
        assert dec.read() == v


def test_int_diff_opt_rle_roundtrip():
    rng = random.Random(13)
    values = []
    cur = 0
    for _ in range(500):
        cur += rng.randint(-50, 50)
        values.append(cur)
    values.extend([10, 11, 12, 13, 5, 4, 3, 0, 0, 0])
    enc = encoding.IntDiffOptRleEncoder()
    for v in values:
        enc.write(v)
    dec = decoding.IntDiffOptRleDecoder(enc.to_bytes())
    for v in values:
        assert dec.read() == v


def test_string_encoder_roundtrip():
    values = ["hello", "", "wörld", "x" * 50, "short", "\n"]
    enc = encoding.StringEncoder()
    for v in values:
        enc.write(v)
    dec = decoding.StringDecoder(enc.to_bytes())
    for v in values:
        assert dec.read() == v
