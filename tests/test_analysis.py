"""ytpu-lint framework tests (ISSUE 13).

Three layers, all jax-free at lint time (fixtures are parsed, never
imported):

1. the fixture corpus under tests/fixtures/lint/ — every known-bad file
   is flagged with its expected rule id, every known-clean file is
   silent;
2. the escape hatches — suppressions and the committed baseline are
   self-verifying (deleting either reproduces the finding; a dead one
   is itself reported);
3. the repo itself — a whole-tree self-run against the committed
   baseline must come back with zero unsuppressed findings, which is
   exactly the `scripts/ytpu_lint.py --ci` gate.
"""
from __future__ import annotations

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

from yjs_tpu.analysis import (
    Baseline,
    Finding,
    RULE_BARE_SUPPRESSION,
    RULE_DISCIPLINE,
    RULE_DONATION,
    RULE_FORCE,
    RULE_KNOB,
    RULE_METRIC,
    RULE_ORDERING,
    RULE_RETRACE,
    RULE_TRACE,
    RULE_USELESS_SUPPRESSION,
    RULE_WAL_KIND,
    all_rules,
    default_checkers,
    parse_suppressions,
    run_lint,
)

pytestmark = pytest.mark.analysis

ROOT = Path(__file__).resolve().parent.parent
FIX = Path(__file__).resolve().parent / "fixtures" / "lint"


def lint(target, root=FIX, **kw):
    """One fixture (file or mini-project dir) through the full runner.

    exclude=() because the corpus lives under tests/, which the
    repo-level default excludes."""
    kw.setdefault("emit_metrics", False)
    return run_lint(root, targets=[target], exclude=(), **kw)


def rules_of(result):
    return sorted(f.rule for f in result.findings)


# -- 1. fixture corpus: every known-bad flagged, every clean silent --------

BAD = [
    ("donation_read_after.py", [RULE_DONATION]),
    ("donation_splat.py", [RULE_DONATION]),
    ("retrace_inline_ctor.py", [RULE_RETRACE]),
    ("retrace_static_argnum.py", [RULE_RETRACE]),
    ("locks_unguarded_read.py", [RULE_DISCIPLINE]),
    ("locks_ordering_cycle.py", [RULE_ORDERING]),
    ("seams_bad_ingress.py", [RULE_TRACE, RULE_TRACE]),
    ("seams_bad_cluster_ingress.py",
     [RULE_TRACE, RULE_TRACE, RULE_TRACE, RULE_TRACE]),
    ("seams_bad_force.py", [RULE_FORCE]),
]

CLEAN = [
    "donation_clean.py",
    "retrace_clean.py",
    "retrace_clean_pad_pow2.py",
    "locks_clean.py",
    "seams_clean.py",
    "seams_clean_cluster.py",
]


@pytest.mark.parametrize("name,expected", BAD, ids=[b[0] for b in BAD])
def test_known_bad_fixture_flagged(name, expected):
    result = lint(FIX / name)
    assert rules_of(result) == sorted(expected), [
        f.render() for f in result.findings
    ]
    assert result.failed


@pytest.mark.parametrize("name", CLEAN)
def test_known_clean_fixture_silent(name):
    result = lint(FIX / name)
    assert result.findings == [], [f.render() for f in result.findings]
    assert not result.failed


def test_finding_severity_matches_registered_rule():
    registered = all_rules()
    for name, _expected in BAD:
        for f in lint(FIX / name).findings:
            assert f.severity == registered[f.rule]


def test_donation_finding_points_at_the_read():
    result = lint(FIX / "donation_read_after.py")
    (f,) = result.findings
    assert f.severity == "error"
    assert "dyn" in f.message and "step" in f.message
    # anchored on the read line, not the call line
    assert "BAD" in (FIX / "donation_read_after.py").read_text().splitlines()[
        f.line - 1
    ]


def test_wal_kind_bad_project():
    result = lint(FIX / "walmod_bad", root=FIX / "walmod_bad")
    assert rules_of(result) == [RULE_WAL_KIND, RULE_WAL_KIND]
    # one finding for the unmapped KIND_NAMES entry, one for the
    # handler module that never references the kind
    assert {f.path for f in result.findings} == {
        "persistence/records.py",
        "persistence/recovery.py",
    }
    assert all(f.symbol == "KIND_ROTATE" for f in result.findings)


def test_wal_kind_clean_project():
    result = lint(FIX / "walmod_clean", root=FIX / "walmod_clean")
    assert result.findings == [], [f.render() for f in result.findings]


def test_drift_bad_project_all_four_directions():
    # the mini-project dir IS the whole project, so opt the stale-docs
    # direction back in (explicit targets turn it off by default)
    result = lint(
        FIX / "driftproj_bad",
        root=FIX / "driftproj_bad",
        checkers=default_checkers(),
    )
    by_rule = {}
    for f in result.findings:
        by_rule.setdefault(f.rule, set()).add(f.symbol)
    assert by_rule[RULE_KNOB] == {"YTPU_SECRET_DEPTH", "YTPU_WAL_GHOST_KNOB"}
    assert by_rule[RULE_METRIC] == {
        "ytpu_hidden_total",
        "ytpu_ghost_metric_total",
    }
    # stale-docs findings anchor on the README, code drift on the code
    paths = {(f.rule, f.symbol): f.path for f in result.findings}
    assert paths[(RULE_KNOB, "YTPU_SECRET_DEPTH")] == "app.py"
    assert paths[(RULE_KNOB, "YTPU_WAL_GHOST_KNOB")] == "README.md"


def test_drift_clean_project_silent():
    result = lint(
        FIX / "driftproj_clean",
        root=FIX / "driftproj_clean",
        checkers=default_checkers(),
    )
    assert result.findings == [], [f.render() for f in result.findings]


def test_partial_target_run_skips_stale_docs_direction():
    # linting ONE file of a project must not call every knob the file
    # doesn't read "stale docs" — only the code -> README direction runs
    result = lint(FIX / "driftproj_bad" / "app.py", root=FIX / "driftproj_bad")
    assert {f.symbol for f in result.findings} == {
        "YTPU_SECRET_DEPTH",
        "ytpu_hidden_total",
    }


# -- 2. escape hatches: suppressions and baseline are self-verifying -------

def test_reasoned_suppression_silences_and_is_counted():
    result = lint(FIX / "suppressed_ok.py")
    assert result.findings == [], [f.render() for f in result.findings]
    assert [f.rule for f in result.suppressed] == [RULE_DONATION]


def test_deleting_a_suppression_reproduces_the_finding(tmp_path):
    text = (FIX / "suppressed_ok.py").read_text()
    stripped = re.sub(r"\s*# ytpu-lint:[^\n]*", "", text)
    target = tmp_path / "suppressed_ok.py"
    target.write_text(stripped)
    result = lint(target, root=tmp_path)
    assert rules_of(result) == [RULE_DONATION]


def test_bare_suppression_is_reported():
    result = lint(FIX / "suppressed_bare.py")
    assert rules_of(result) == [RULE_BARE_SUPPRESSION]
    # the disable still worked — the donation finding is suppressed,
    # but the missing reason is a finding of its own
    assert [f.rule for f in result.suppressed] == [RULE_DONATION]


def test_useless_suppression_is_reported():
    result = lint(FIX / "suppressed_useless.py")
    assert rules_of(result) == [RULE_USELESS_SUPPRESSION]


def test_docstring_example_is_not_a_suppression():
    text = (
        '"""Example::\n\n'
        "    x = f(buf)  # ytpu-lint: disable=donation-aliasing -- demo\n"
        '"""\n'
        "y = 1  # ytpu-lint: disable=retrace-hazard -- real comment\n"
    )
    sups = parse_suppressions("demo.py", text)
    assert len(sups) == 1
    assert sups[0].rules == ("retrace-hazard",)
    assert sups[0].reason == "real comment"


def test_baseline_covers_then_goes_stale(tmp_path):
    bad = FIX / "donation_read_after.py"
    (finding,) = lint(bad).findings

    baseline = Baseline([Baseline.entry_for(finding, note="grandfathered")])
    covered = lint(bad, baseline=baseline)
    assert covered.findings == [] and not covered.failed
    assert [f.rule for f in covered.baselined] == [RULE_DONATION]

    # deleting the baseline entry reproduces the finding
    reproduced = lint(bad, baseline=Baseline([]))
    assert rules_of(reproduced) == [RULE_DONATION]

    # an entry matching nothing is stale and fails the run
    ghost = Finding(
        rule=RULE_DONATION,
        severity="error",
        path="gone.py",
        line=1,
        message="was fixed long ago",
    )
    stale = lint(bad, baseline=Baseline(
        [Baseline.entry_for(finding), Baseline.entry_for(ghost)]
    ))
    assert stale.failed
    assert [e["path"] for e in stale.stale_baseline] == ["gone.py"]


def test_fingerprint_ignores_line_numbers():
    a = Finding(
        rule=RULE_DONATION, severity="error", path="x.py",
        line=10, message="m", symbol="f",
    )
    b = Finding(
        rule=RULE_DONATION, severity="error", path="x.py",
        line=99, message="m", symbol="f",
    )
    assert a.fingerprint == b.fingerprint
    assert a.fingerprint != Finding(
        rule=RULE_DONATION, severity="error", path="y.py",
        line=10, message="m", symbol="f",
    ).fingerprint


# -- 3. the repo itself: the --ci gate in-process and end-to-end -----------

def test_all_nine_rules_registered():
    rules = all_rules()
    for rule in (
        RULE_DONATION, RULE_RETRACE, RULE_DISCIPLINE, RULE_ORDERING,
        RULE_TRACE, RULE_WAL_KIND, RULE_FORCE, RULE_KNOB, RULE_METRIC,
    ):
        assert rule in rules


def test_repo_self_lint_zero_unsuppressed():
    baseline = Baseline.load(ROOT / ".ytpu-lint-baseline.json")
    result = run_lint(ROOT, baseline=baseline, emit_metrics=False)
    assert result.findings == [], [f.render() for f in result.findings]
    assert result.stale_baseline == []
    assert not result.failed


def test_lint_metric_emitted_on_global_registry():
    from yjs_tpu.obs import global_registry

    run_lint(
        FIX,
        targets=[FIX / "donation_read_after.py"],
        exclude=(),
        emit_metrics=True,
    )
    assert "ytpu_lint_findings_total" in set(global_registry().names())


def test_cli_ci_gate_and_json(tmp_path):
    proc = subprocess.run(
        [sys.executable, "scripts/ytpu_lint.py", "--ci", "--json"],
        cwd=ROOT,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
