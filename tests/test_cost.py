"""Cost-attribution ledger + capacity-model tests (ISSUE 19).

Covers flush-seam attribution (device/host seconds split across docs
proportional to staged bytes), the bounded top-K doc map under a
10k-doc churn storm (with exact conservation through the sampled
tail), tenant-label folding at the cardinality cap, the geo-link
shipped/deferred accounting seam, provider wiring, byte-identical
engine output with the whole telemetry plane disabled vs enabled, and
the TSDB-derived sessions-per-device capacity knee.
"""

from __future__ import annotations

import pytest

import yjs_tpu as Y
from yjs_tpu.obs import MetricsRegistry
from yjs_tpu.obs.capacity import (
    CapacityConfig,
    ramp_capacity,
    read_knee,
    sessions_per_device,
)
from yjs_tpu.obs.cost import DIMS, CostLedger, cost_enabled
from yjs_tpu.obs.expo import registry_snapshot
from yjs_tpu.obs.tsdb import Tsdb, TsdbConfig
from yjs_tpu.provider import TpuProvider
from yjs_tpu.updates import encode_state_as_update

pytestmark = pytest.mark.cost

FLUSH = {
    "t_dispatch_s": 0.8,
    "t_compact_s": 0.05, "t_plan_s": 0.05,
    "t_pack_s": 0.05, "t_emit_s": 0.05,
}


def _ledger(**kw) -> CostLedger:
    kw.setdefault("max_docs", 32)
    kw.setdefault("max_tenants", 8)
    kw.setdefault("tail_sample", 1)
    return CostLedger(MetricsRegistry(), **kw)


def _store() -> Tsdb:
    return Tsdb(TsdbConfig(
        interval_s=1.0, retention_raw_s=10 * 24 * 3600.0,
        retention_1m_s=20 * 24 * 3600.0,
        retention_10m_s=30 * 24 * 3600.0, directory=None,
    ))


# -- flush-seam attribution ---------------------------------------------------


def test_on_flush_splits_time_proportional_to_staged_bytes():
    led = _ledger()
    led.staged("acme/doc-a", 300)
    led.staged("acme/doc-a", 0)     # zero-byte stage is harmless
    led.staged("beta/doc-b", 100)
    led.on_flush(dict(FLUSH))
    snap = led.snapshot()
    top = {d["guid"]: d for d in snap["top"]}
    assert top["acme/doc-a"]["device_s"] == pytest.approx(0.6)
    assert top["beta/doc-b"]["device_s"] == pytest.approx(0.2)
    assert top["acme/doc-a"]["host_s"] == pytest.approx(0.15)
    assert top["beta/doc-b"]["host_s"] == pytest.approx(0.05)
    assert top["acme/doc-a"]["tenant"] == "acme"
    assert snap["tenants"]["acme"]["device_s"] == pytest.approx(0.6)
    # conservation: the whole flush is attributed, nothing minted
    t = led.totals()
    assert t["device_s"] == pytest.approx(0.8)
    assert t["host_s"] == pytest.approx(0.2)


def test_on_flush_resets_staging_between_flushes():
    led = _ledger()
    led.staged("t/d", 64)
    led.on_flush(dict(FLUSH))
    led.on_flush(dict(FLUSH))  # nothing staged since: must be a no-op
    assert led.totals()["device_s"] == pytest.approx(0.8)
    led.on_flush(None)         # idle-flush seam passes None
    assert led.totals()["device_s"] == pytest.approx(0.8)


def test_hooks_land_in_their_own_dimensions():
    led = _ledger()
    led.wal_bytes("t/d", 100)
    led.repl_bytes("t/d", 250)
    led.session_frame("t/d")
    led.session_frame("t/d", n=3)
    t = led.totals()
    assert set(t) == set(DIMS)
    assert t["wal_bytes"] == 100.0
    assert t["repl_bytes"] == 250.0
    assert t["session_frames"] == 4.0
    assert t["device_s"] == t["host_s"] == 0.0


def test_geo_bytes_exports_per_peer_kind_labels():
    reg = MetricsRegistry()
    led = CostLedger(reg, max_docs=8, max_tenants=4, tail_sample=1)
    led.geo_bytes("euw", 1000, kind="shipped")
    led.geo_bytes("euw", 200, kind="deferred")
    led.geo_bytes("apne", 50)  # kind defaults to shipped
    snap = registry_snapshot(reg)
    geo = snap["counters"]["ytpu_cost_geo_link_bytes_total"]
    assert geo["peer=euw,kind=shipped"] == 1000
    assert geo["peer=euw,kind=deferred"] == 200
    assert geo["peer=apne,kind=shipped"] == 50
    # link bytes are per-peer, not per-doc: the doc ledger is untouched
    assert led.totals()["geo_bytes"] == 0.0


def test_exported_tenant_families_follow_attribution():
    reg = MetricsRegistry()
    led = CostLedger(reg, max_docs=8, max_tenants=4, tail_sample=1)
    led.wal_bytes("acme/doc-1", 500)
    led.wal_bytes("acme/doc-2", 300)
    led.wal_bytes("beta/doc-9", 100)
    wal = registry_snapshot(reg)["counters"]["ytpu_cost_wal_bytes_total"]
    assert wal["tenant=acme"] == 800
    assert wal["tenant=beta"] == 100


# -- bounded top-K under churn ------------------------------------------------


def test_topk_bound_and_conservation_under_10k_doc_churn(rng):
    led = _ledger(max_docs=32, max_tenants=8, tail_sample=1)
    fed_wal = 0
    heavy = "tenant0/doc-heavy"
    # the heavy doc earns device time first, so compaction must keep it
    led.staged(heavy, 1000)
    led.on_flush(dict(FLUSH))
    for i in range(10_000):
        nbytes = rng.randrange(1, 64)
        led.wal_bytes(f"tenant{i % 20}/doc-{i}", nbytes)
        fed_wal += nbytes
        assert len(led._docs) <= 2 * led.max_docs  # hard bound, always
    snap = led.snapshot(top=40)
    assert snap["tracked_docs"] <= 2 * led.max_docs
    assert snap["folded_docs"] > 9_000
    # conservation at tail_sample=1: tracked + tail == everything fed
    t = led.totals()
    assert t["wal_bytes"] == pytest.approx(float(fed_wal))
    assert t["device_s"] == pytest.approx(0.8)
    # the heaviest doc (by device+host burn) survived every compaction
    assert any(d["guid"] == heavy for d in snap["top"])
    # tenant label cardinality stays bounded: 8 exact + __other__
    assert len(snap["tenants"]) <= led.max_tenants + 1


def test_folded_doc_contributions_keep_flowing_into_tail():
    led = _ledger(max_docs=4, tail_sample=1)
    for i in range(20):  # force compactions; 8-doc hard cap
        led.wal_bytes(f"t/d{i:02d}", 10)
    folded = [g for g in (f"t/d{i:02d}" for i in range(20))
              if g not in led._docs]
    assert folded
    before = led.totals()["wal_bytes"]
    led.wal_bytes(folded[0], 7)  # a folded doc writes again
    assert led.totals()["wal_bytes"] == pytest.approx(before + 7)
    assert folded[0] not in led._docs  # stays in the sampled tail


def test_sampled_tail_counts_one_in_n_scaled():
    led = _ledger(max_docs=4, tail_sample=4)
    for i in range(20):
        led.wal_bytes(f"t/d{i:02d}", 10)
    folded = next(g for g in (f"t/d{i:02d}" for i in range(20))
                  if g not in led._docs)
    before = led.totals()["wal_bytes"]
    for _ in range(8):  # 8 events at 1-in-4: 2 samples x 10 x 4 = 80
        led.wal_bytes(folded, 10)
    assert led.totals()["wal_bytes"] == pytest.approx(before + 80)


def test_tenant_fold_to_other_at_cap():
    led = _ledger(max_docs=64, max_tenants=4)
    for i in range(10):
        led.wal_bytes(f"tenant{i}/doc", 100)
    snap = led.snapshot()
    assert len(snap["tenants"]) == 5
    assert "__other__" in snap["tenants"]
    assert snap["tenants"]["__other__"]["wal_bytes"] == 600.0
    # per-tenant rows + overflow row still conserve the fed total
    assert sum(t["wal_bytes"] for t in snap["tenants"].values()) \
        == 1000.0


def test_disabled_ledger_is_inert(monkeypatch):
    monkeypatch.setenv("YTPU_COST_DISABLED", "1")
    assert not cost_enabled()
    led = _ledger()
    led.staged("t/d", 100)
    led.wal_bytes("t/d", 100)
    led.session_frame("t/d")
    led.geo_bytes("euw", 100)
    led.on_flush(dict(FLUSH))
    snap = led.snapshot()
    assert snap["disabled"] is True
    assert snap["top"] == [] and snap["tenants"] == {}
    assert all(v == 0.0 for v in led.totals().values())


# -- geo-link shipped/deferred accounting seam --------------------------------


def test_geo_link_shipment_accounting_marks_late_bytes():
    from yjs_tpu.geo.replicator import GeoLink

    led = _ledger()

    class _FakeLink:
        region = "euw"
        shipped_bytes = 0
        deferred_bytes = 0
        _deferred = {"t/doc-late"}

        def _ledger(self):
            return led

    link = _FakeLink()
    payload = b"x" * 120
    parts = [("t/doc-now", b"a" * 40), ("t/doc-late", b"b" * 60)]
    GeoLink._account_shipment(link, payload, parts)
    assert link.shipped_bytes == 120
    assert link.deferred_bytes == 60   # only the budget-held doc
    assert link._deferred == set()     # cleared once shipped
    # second shipment with no deferred docs adds only shipped bytes
    GeoLink._account_shipment(link, b"y" * 10, [("t/doc-now", b"c")])
    assert link.shipped_bytes == 130
    assert link.deferred_bytes == 60


def test_geo_link_accounting_survives_missing_ledger():
    from yjs_tpu.geo.replicator import GeoLink

    class _FakeLink:
        region = "use"
        shipped_bytes = 0
        deferred_bytes = 0
        _deferred: set = set()

        def _ledger(self):
            return None  # supervisor facade: no per-shard ledger

    link = _FakeLink()
    GeoLink._account_shipment(link, b"z" * 30, [("t/d", b"z" * 30)])
    assert link.shipped_bytes == 30  # per-link counters still advance


# -- provider wiring ----------------------------------------------------------


def _edit(prov: TpuProvider, room: str, text: str) -> None:
    d = Y.Doc(gc=False)
    d.get_text("text").insert(0, text)
    prov.receive_update(room, encode_state_as_update(d))


def test_provider_attributes_flush_costs_per_tenant(tmp_path):
    from yjs_tpu.persistence import WalConfig

    prov = TpuProvider(8, wal_dir=tmp_path,
                       wal_config=WalConfig(fsync="never"))
    try:
        _edit(prov, "acme/room-0", "hello cost ledger")
        _edit(prov, "beta/room-1", "hi")
        prov.flush()
        snap = prov.metrics_snapshot()["cost"]
        assert snap["tracked_docs"] == 2
        tenants = snap["tenants"]
        assert set(tenants) == {"acme", "beta"}
        assert tenants["acme"]["wal_bytes"] > 0
        # the flush's device+host seconds were split across both docs
        t = prov.cost.totals()
        assert t["device_s"] > 0.0 or t["host_s"] > 0.0
        assert tenants["acme"]["device_s"] + tenants["beta"]["device_s"] \
            == pytest.approx(t["device_s"])
    finally:
        prov.close()


def test_byte_identical_engine_output_telemetry_on_vs_off(monkeypatch,
                                                          rng):
    """Acceptance bar: YTPU_TSDB_DISABLED=1 + YTPU_COST_DISABLED=1 vs
    enabled produce byte-identical engine output for the same trace —
    the telemetry plane observes, never steers."""
    # one fixed trace (pinned client ids) fed to BOTH runs
    trace = []
    for j in range(6):
        d = Y.Doc(gc=False)
        d.client_id = 1000 + j
        d.get_text("text").insert(
            0, "".join(rng.choice("abcdef ") for _ in range(12))
        )
        trace.append(
            (f"t{j % 2}/room-{j % 3}", encode_state_as_update(d))
        )

    def run(disabled: bool) -> dict:
        if disabled:
            monkeypatch.setenv("YTPU_TSDB_DISABLED", "1")
            monkeypatch.setenv("YTPU_COST_DISABLED", "1")
        else:
            monkeypatch.delenv("YTPU_TSDB_DISABLED", raising=False)
            monkeypatch.delenv("YTPU_COST_DISABLED", raising=False)
        prov = TpuProvider(8)
        try:
            for j, (guid, update) in enumerate(trace):
                prov.receive_update(guid, update)
                if j % 2:
                    prov.flush()
            prov.flush()
            return {
                g: prov.encode_state_as_update(g)
                for g, _ in trace
            }
        finally:
            prov.close()

    on = run(disabled=False)
    off = run(disabled=True)
    assert on == off
    assert any(len(v) > 0 for v in on.values())


# -- capacity model -----------------------------------------------------------


def test_read_knee_from_recorded_ramp_history():
    st = _store()
    t = 1000.0
    for n, ok in ((8, 1.0), (16, 1.0), (32, 0.0)):
        st.record("ytpu_capacity_sessions", float(n), now=t)
        st.record("ytpu_capacity_ok", ok, now=t)
        st.record("ytpu_capacity_p99_ticks", 2.0, now=t)
        t += 1.0
    assert read_knee(st, 999.0, t) == 16
    # a window that misses the ramp reads zero, never a stale figure
    assert read_knee(st, 0.0, 500.0) == 0


def test_sessions_per_device_divides_by_visible_devices():
    import jax

    n_dev = max(1, len(jax.devices()))
    out = sessions_per_device({"sessions_at_slo": 4 * n_dev,
                               "ceiling_hit": True})
    assert out["n_devices"] == n_dev
    assert out["sessions_per_device"] == pytest.approx(4.0)
    assert out["ceiling_hit"] is True
    assert sessions_per_device({})["sessions_per_device"] == 0.0


def test_capacity_config_stage_plan_is_geometric():
    c = CapacityConfig(start_sessions=8, max_sessions=100, growth=2.0)
    assert c.stages() == [8, 16, 32, 64, 100]
    assert CapacityConfig(start_sessions=5, max_sessions=5).stages() \
        == [5]
    assert c.p99_limit_ticks == 4 * c.flush_every


def test_ramp_capacity_records_stages_and_reads_knee_from_tsdb():
    st = _store()
    cfg = CapacityConfig(
        start_sessions=2, max_sessions=4, growth=2.0,
        ticks_per_stage=4, flush_every=2, slo_target_ms=60_000.0,
        seed=0,
    )
    result = ramp_capacity(
        lambda n: TpuProvider(n + 4), config=cfg, store=st, now=5000.0,
    )
    assert [s["sessions"] for s in result["stages"]] == [2, 4]
    assert all(s["ok"] for s in result["stages"])
    assert result["ceiling_hit"] is True
    assert result["sessions_at_slo"] == 4
    # the figure is, by construction, a TSDB query over the ramp
    assert read_knee(st, *result["window"]) == 4
    pts = st.query("ytpu_capacity_sessions", start=4999.0, end=5010.0,
                   tier="raw")
    assert [v for _, v in pts] == [2.0, 4.0]


def test_ramp_capacity_stops_at_degraded_stage():
    st = _store()
    # an impossible visibility budget: every stage degrades, so the
    # ramp must stop after the first stage and publish a zero knee
    cfg = CapacityConfig(
        start_sessions=2, max_sessions=8, growth=2.0,
        ticks_per_stage=4, flush_every=2, p99_limit_ticks=-1,
        slo_target_ms=60_000.0,
    )
    result = ramp_capacity(
        lambda n: TpuProvider(n + 4), config=cfg, store=st, now=9000.0,
    )
    assert result["ceiling_hit"] is False
    assert len(result["stages"]) == 1  # degraded on the very first
    assert result["stages"][0]["ok"] is False
    assert result["sessions_at_slo"] == 0
    assert read_knee(st, *result["window"]) == 0
    # the degraded stage is still in the history (ok recorded as 0)
    assert st.query("ytpu_capacity_ok", start=8999.0, end=9010.0,
                    tier="raw") == [(9000.0, 0.0)]
