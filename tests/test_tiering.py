"""Tiered doc-lifecycle suite (ISSUE 7): heat tracking, hot→warm→cold
demotion, demand promotion, auto-eviction past the slot cap, crash
recovery placement, tombstone GC, and the fleet/rebalancer integration.

Everything is deterministic (injected clocks, tmp-dir WALs, seeded
PRNGs).  In tier-1; the ``tiering`` marker deselects it with
``-m 'not tiering'`` and ci_check.sh runs it standalone first.
"""

import pytest

import yjs_tpu as Y
from yjs_tpu.fleet import FleetRouter
from yjs_tpu.persistence import (
    KIND_TIER,
    WalConfig,
    encode_tier_payload,
)
from yjs_tpu.provider import ProviderFullError, TpuProvider
from yjs_tpu.sync.session import SessionConfig
from yjs_tpu.sync.transport import PipeNetwork
from yjs_tpu.tiering import HeatTracker, TierConfig
from yjs_tpu.updates import encode_state_as_update, encode_state_vector

pytestmark = pytest.mark.tiering

SMALL = WalConfig(segment_bytes=256, fsync="never")


def tiered(**kw) -> TierConfig:
    kw.setdefault("enabled", True)
    return TierConfig(**kw)


def upd(text: str, cid: int = 1) -> bytes:
    d = Y.Doc(gc=False)
    d.client_id = cid
    d.get_text("text").insert(0, text)
    return encode_state_as_update(d)


def canonical(prov: TpuProvider, guid: str) -> bytes:
    """Canonicalized full state (promotes a demoted room on the way)."""
    return Y.merge_updates([prov.encode_state_as_update(guid)])


def quiet_config(**kw) -> SessionConfig:
    base = dict(
        heartbeat=0, liveness=0, antientropy=0, hello_timeout=0,
        retry_base=4, retry_jitter=0.0, seed=1,
    )
    base.update(kw)
    return SessionConfig(**base)


def drive(pa, pb):
    def fn():
        pa.flush()
        pb.flush()
        pa.tick_sessions()
        pb.tick_sessions()

    return fn


# -- policy plumbing ---------------------------------------------------------


def test_disabled_by_default_keeps_provider_full_error():
    # the seed contract: without opt-in the hard cap still raises
    p = TpuProvider(1)
    p.receive_update("a", upd("first"))
    assert not p.tiers.enabled
    with pytest.raises(ProviderFullError):
        p.receive_update("b", upd("second", cid=2))
    # and manual demotion of nothing stays an error, not a silent no-op
    with pytest.raises(KeyError):
        p.demote_doc("missing")


def test_env_knobs_configure_tier_policy(monkeypatch):
    monkeypatch.setenv("YTPU_TIER_ENABLED", "1")
    monkeypatch.setenv("YTPU_TIER_HALF_LIFE_S", "60")
    monkeypatch.setenv("YTPU_TIER_WARM_MAX", "3")
    monkeypatch.setenv("YTPU_TIER_SESSION_WEIGHT", "2.5")
    monkeypatch.setenv("YTPU_TIER_OVERCOMMIT", "16")
    monkeypatch.setenv("YTPU_TIER_GC_MIN_ROWS", "32")
    monkeypatch.setenv("YTPU_TIER_GC_DELETED_RATIO", "0.25")
    monkeypatch.setenv("YTPU_TIER_GC_MAX_DOCS", "2")
    cfg = TierConfig()
    assert cfg.enabled
    assert cfg.half_life_s == 60.0
    assert cfg.warm_max == 3
    assert cfg.session_weight == 2.5
    assert cfg.overcommit == 16
    assert cfg.gc_min_rows == 32
    assert cfg.gc_deleted_ratio == 0.25
    assert cfg.gc_max_docs == 2
    # constructor args beat the env
    assert not TierConfig(enabled=False).enabled
    # garbage env values fall back to defaults, never raise
    monkeypatch.setenv("YTPU_TIER_HALF_LIFE_S", "not-a-number")
    assert TierConfig().half_life_s == 300.0


def test_heat_decays_with_injected_clock():
    now = [0.0]
    h = HeatTracker(half_life_s=10.0, clock=lambda: now[0])
    for _ in range(50):
        h.touch("old")
    now[0] = 100.0  # ten half-lives: 50 touches decay to ~0.05
    h.touch("fresh")
    h.touch("fresh")
    assert h.score("old") < 0.1
    assert h.score("fresh") == pytest.approx(2.0)
    # "touched 50 times an hour ago" loses to "touched twice just now"
    assert h.coldest(["fresh", "old"]) == ["old", "fresh"]
    # never-touched docs score 0.0 and tie-break by guid
    assert h.score("never") == 0.0
    assert h.coldest(["b-never", "a-never"]) == ["a-never", "b-never"]
    h.forget("fresh")
    assert h.score("fresh") == 0.0


# -- demotion / promotion ----------------------------------------------------


def test_warm_demote_promote_byte_identical():
    p = TpuProvider(2, tier_config=tiered())
    p.receive_update("r", upd("warm round-trip"))
    canon = canonical(p, "r")
    assert p.demote_doc("r", "warm")
    assert not p.has_doc("r")
    assert p.tiers.tier_of("r") == "warm"
    assert p.resident_docs == 1  # still addressable, just not hot
    # first touch promotes: no resync, no decode round-trip, same bytes
    assert p.text("r") == "warm round-trip"
    assert p.tiers.tier_of("r") == "hot"
    assert canonical(p, "r") == canon


def test_cold_demote_promote_blob_path_without_wal():
    # no WAL: the cold tier keeps a compressed blob instead of a locator
    p = TpuProvider(2, tier_config=tiered())
    p.receive_update("r", upd("cold blob round-trip"))
    canon = canonical(p, "r")
    assert p.demote_doc("r", "cold")
    assert p.tiers.tier_of("r") == "cold"
    assert p.tiers.cold["r"].ref is None  # blob path
    assert p.text("r") == "cold blob round-trip"
    assert p.tiers.tier_of("r") == "hot"
    assert canonical(p, "r") == canon


def test_cold_demote_promote_wal_locator_path(tmp_path):
    p = TpuProvider(
        2, wal_dir=str(tmp_path), wal_config=SMALL, tier_config=tiered()
    )
    p.receive_update("r", upd("cold locator round-trip"))
    canon = canonical(p, "r")
    assert p.demote_doc("r", "cold")
    e = p.tiers.cold["r"]
    assert e.ref is not None and e.blob is None  # locator, no copy held
    assert p.text("r") == "cold locator round-trip"
    assert canonical(p, "r") == canon


def test_demote_frees_the_slot_for_new_docs():
    p = TpuProvider(1, tier_config=tiered())
    p.receive_update("a", upd("first"))
    p.demote_doc("a")
    # the freed slot admits a new room without any eviction machinery
    p.receive_update("b", upd("second", cid=2))
    assert p.has_doc("b") and p.tiers.tier_of("a") == "warm"
    # reading a promotes it back, auto-evicting b into its place
    assert p.text("a") == "first"
    assert p.tiers.tier_of("b") == "warm"
    assert p.tiers.resident_count() == 2


def test_doc_id_auto_evicts_the_coldest_hot_doc():
    p = TpuProvider(2, tier_config=tiered())
    p.receive_update("a", upd("keep me hot"))
    p.receive_update("b", upd("barely used", cid=2))
    for _ in range(5):
        p.text("a")  # heat a well past b
    p.receive_update("c", upd("newcomer", cid=3))  # full: evicts coldest
    assert p.has_doc("a") and p.has_doc("c")
    assert not p.has_doc("b")
    assert p.tiers.tier_of("b") == "warm"
    assert p.text("b") == "barely used"  # still addressable


def test_cpu_pinned_docs_are_not_evictable():
    # backend="cpu" serves every doc from the fallback core: slot-bound,
    # so tiering cannot free anything and the hard cap still applies
    p = TpuProvider(1, backend="cpu", tier_config=tiered())
    p.receive_update("a", upd("pinned"))
    with pytest.raises(ValueError):
        p.demote_doc("a")
    with pytest.raises(ProviderFullError):
        p.receive_update("b", upd("no room", cid=2))


def test_observed_docs_are_pinned_until_unobserved():
    p = TpuProvider(2, tier_config=tiered())
    p.receive_update("a", upd("watched"))
    unobserve = p.observe("a", ["text"], lambda g, ev: None)
    with pytest.raises(ValueError):
        p.demote_doc("a")
    unobserve()
    assert p.demote_doc("a")
    assert p.tiers.tier_of("a") == "warm"


def test_warm_max_spills_coldest_to_cold():
    p = TpuProvider(3, tier_config=tiered(warm_max=1))
    p.receive_update("a", upd("older"))
    p.receive_update("b", upd("newer", cid=2))
    p.text("b")  # b is hotter than a
    p.demote_doc("a")
    p.demote_doc("b")
    snap = p.tier_snapshot()
    assert snap["warm"] == 1 and snap["cold"] == 1
    # the coldest (a) was the one spilled to cold
    assert p.tiers.tier_of("a") == "cold"
    assert p.tiers.tier_of("b") == "warm"
    assert p.text("a") == "older" and p.text("b") == "newer"


# -- overcommit churn (acceptance: >= 50x slots, zero full errors) -----------


def test_two_slots_sustain_fifty_x_docs_under_churn(rng):
    n_slots, n_docs = 2, 100
    p = TpuProvider(n_slots, tier_config=tiered())
    texts = {}
    for k in range(n_docs):
        g = f"doc-{k:03d}"
        texts[g] = f"payload {k}"
        p.receive_update(g, upd(texts[g], cid=k + 1))
    # random demand: every touch promotes on a full provider, so each
    # one exercises auto-evict + promote; none may raise
    for _ in range(150):
        g = rng.choice(sorted(texts))
        assert p.text(g) == texts[g]
    snap = p.tier_snapshot()
    assert snap["resident"] == n_docs
    assert snap["hot"] <= n_slots
    assert snap["resident"] >= 50 * n_slots
    # the engine never grew past its cap
    assert p.engine.n_docs == n_slots


# -- dead letters ride evictions ---------------------------------------------


def test_release_doc_preserves_slot_dead_letters():
    p = TpuProvider(2, tier_config=tiered())
    p.receive_update("r", upd("kept state"))
    i = p.doc_id("r")
    p.engine.dead_letters.append(i, b"poison-a", False, "test-injected")
    p.release_doc("r")
    # re-tagged unattributed, room named in the reason, payload intact
    letters = p.engine.dead_letters.list(doc=-1)
    assert [e.update for e in letters] == [b"poison-a"]
    assert "evicted 'r'" in letters[0].reason
    assert "test-injected" in letters[0].reason


def test_release_of_demoted_doc_preserves_riding_letters():
    p = TpuProvider(2, tier_config=tiered())
    p.receive_update("r", upd("demoted then dropped"))
    p.engine.dead_letters.append(
        p.doc_id("r"), b"poison-b", True, "test-injected"
    )
    p.demote_doc("r", "warm")  # the letter rides the warm entry
    assert p.engine.dead_letters.list() == []
    final = p.release_doc("r")
    assert Y.merge_updates([final]) == Y.merge_updates(
        [upd("demoted then dropped")]
    )
    assert p.tiers.tier_of("r") is None and not p.has_doc("r")
    letters = p.engine.dead_letters.list(doc=-1)
    assert [e.update for e in letters] == [b"poison-b"]
    assert letters[0].v2 and "evicted 'r'" in letters[0].reason


def test_demoted_letters_return_to_the_slot_on_promotion():
    p = TpuProvider(2, tier_config=tiered())
    p.receive_update("r", upd("round trip"))
    p.engine.dead_letters.append(
        p.doc_id("r"), b"poison-c", False, "test-injected"
    )
    p.demote_doc("r", "cold")
    p.text("r")  # promote
    letters = p.engine.dead_letters.list(doc=p.doc_id("r"))
    assert [e.update for e in letters] == [b"poison-c"]
    assert letters[0].reason == "test-injected"  # untouched, re-attributed


# -- crash recovery placement ------------------------------------------------


def test_crash_mid_demotion_lands_in_exactly_one_tier(tmp_path):
    # satellite: the tier record reached the WAL but the crash hit
    # before the slot was freed — recovery must not double-place the doc
    p = TpuProvider(
        2, wal_dir=str(tmp_path), wal_config=SMALL, tier_config=tiered()
    )
    p.receive_update("r", upd("survives the torn demote"))
    p.flush()
    canon = canonical(p, "r")
    state = p.encode_state_as_update("r")
    # hand-append the demote marker the crashed demote() wrote first
    p.wal.append(KIND_TIER, "r", encode_tier_payload("warm", 1.5, state))
    p.wal.abandon()
    del p
    pr = TpuProvider.recover(
        str(tmp_path), n_docs=2, wal_config=SMALL, tier_config=tiered()
    )
    assert pr.last_recovery["tier_records"] == 1
    assert pr.last_recovery["tier_placements"] == {"r": "warm"}
    tiers = [
        t for t in ("hot", "warm", "cold") if pr.tiers.tier_of("r") == t
    ]
    assert tiers == ["warm"]  # exactly one tier
    assert not pr.has_doc("r")
    assert canonical(pr, "r") == canon  # byte-identical on promotion
    assert pr.tiers.tier_of("r") == "hot"


def test_crash_after_demotion_recovers_doc_demoted(tmp_path):
    p = TpuProvider(
        2, wal_dir=str(tmp_path), wal_config=SMALL, tier_config=tiered()
    )
    p.receive_update("w", upd("goes warm"))
    p.receive_update("c", upd("goes cold", cid=2))
    canon_w, canon_c = canonical(p, "w"), canonical(p, "c")
    p.demote_doc("w", "warm")
    p.demote_doc("c", "cold")
    p.wal.abandon()
    del p
    pr = TpuProvider.recover(
        str(tmp_path), n_docs=2, wal_config=SMALL, tier_config=tiered()
    )
    assert pr.last_recovery["tier_placements"] == {"w": "warm", "c": "cold"}
    assert canonical(pr, "w") == canon_w
    assert canonical(pr, "c") == canon_c


def test_promotion_marker_clears_demote_on_recovery(tmp_path):
    p = TpuProvider(
        2, wal_dir=str(tmp_path), wal_config=SMALL, tier_config=tiered()
    )
    p.receive_update("r", upd("demoted then touched"))
    p.demote_doc("r", "cold")
    assert p.text("r") == "demoted then touched"  # journals a hot marker
    p.wal.abandon()
    del p
    pr = TpuProvider.recover(
        str(tmp_path), n_docs=2, wal_config=SMALL, tier_config=tiered()
    )
    # the last record standing is the hot marker: no demote replays
    assert pr.last_recovery["tier_placements"] == {}
    assert pr.has_doc("r")
    assert pr.text("r") == "demoted then touched"


def test_recovery_into_untiered_provider_lands_hot_keeps_letters(tmp_path):
    p = TpuProvider(
        2, wal_dir=str(tmp_path), wal_config=SMALL, tier_config=tiered()
    )
    p.receive_update("r", upd("tiering removed at restart"))
    canon = canonical(p, "r")
    p.engine.dead_letters.append(
        p.doc_id("r"), b"poison-d", False, "test-injected"
    )
    p.demote_doc("r", "warm")
    p.wal.abandon()
    del p
    pr = TpuProvider.recover(str(tmp_path), n_docs=2, wal_config=SMALL)
    assert not pr.tiers.enabled
    assert pr.has_doc("r")  # no tiering: the doc simply stays hot
    assert canonical(pr, "r") == canon
    # ...but the letters that rode the demote marker are not lost
    assert b"poison-d" in [e.update for e in pr.engine.dead_letters.list()]


def test_checkpoint_preserves_demoted_tiers(tmp_path):
    p = TpuProvider(
        3, wal_dir=str(tmp_path), wal_config=SMALL, tier_config=tiered()
    )
    p.receive_update("hot", upd("stays hot"))
    p.receive_update("w", upd("warm across checkpoint", cid=2))
    p.receive_update("c", upd("cold across checkpoint", cid=3))
    canon_w, canon_c = canonical(p, "w"), canonical(p, "c")
    p.demote_doc("w", "warm")
    p.demote_doc("c", "cold")
    p.checkpoint()  # compaction: markers + cold locators re-anchored
    # the cold locator survives the segment deletion: promote still works
    assert canonical(p, "c") == canon_c
    p.demote_doc("c", "cold")
    p.receive_update("hot", upd("post-checkpoint tail", cid=4))
    p.wal.abandon()
    del p
    pr = TpuProvider.recover(
        str(tmp_path), n_docs=3, wal_config=SMALL, tier_config=tiered()
    )
    placements = pr.last_recovery["tier_placements"]
    assert placements.get("w") == "warm" and placements.get("c") == "cold"
    assert canonical(pr, "w") == canon_w
    assert canonical(pr, "c") == canon_c
    assert "post-checkpoint tail" in pr.text("hot")


# -- promotion under a live session (satellite) ------------------------------


def test_promotion_under_live_session_needs_no_second_resync():
    # demote a room out from under a live peer session; the next inbound
    # delta promotes it back and the session heals with NO full resync
    # beyond the handshake's one
    cfg = quiet_config(antientropy=2)
    pa = TpuProvider(2, tier_config=tiered())
    pb = TpuProvider(2, tier_config=tiered())
    net = PipeNetwork()
    ta, tb = net.pair()
    sa = pa.session("room", "pb", cfg)
    sb = pb.session("room", "pa", cfg)
    sa.connect(ta)
    sb.connect(tb)
    net.settle((drive(pa, pb),))
    d = Y.Doc(gc=False)
    d.client_id = 11
    d.get_text("text").insert(0, "kept")
    pb.receive_update("room", encode_state_as_update(d))
    net.settle((drive(pa, pb),))
    assert pa.text("room") == "kept"

    assert pa.demote_doc("room", "warm")  # demote under the live session
    assert not pa.has_doc("room")
    sv = encode_state_vector(d)
    d.get_text("text").insert(0, "next ")
    pb.receive_update("room", encode_state_as_update(d, sv))
    net.settle((drive(pa, pb),), max_rounds=120, idle_rounds=5)
    assert pa.tiers.tier_of("room") == "hot"  # first touch promoted it
    assert pa.text("room") == pb.text("room") == "next kept"
    assert Y.merge_updates([pa.encode_state_as_update("room")]) == (
        Y.merge_updates([pb.encode_state_as_update("room")])
    )
    # the handshake's full resync stayed the only one on both sides
    assert sa.n_full_resyncs == 1 and sb.n_full_resyncs == 1
    assert sa.state == sb.state == "live"


# -- tombstone GC ------------------------------------------------------------


def test_gc_pass_reclaims_tombstones_from_long_lived_hot_docs():
    p = TpuProvider(
        2,
        tier_config=tiered(gc_min_rows=4, gc_deleted_ratio=0.25),
    )
    d = Y.Doc(gc=False)
    d.client_id = 7
    t = d.get_text("text")
    # incremental appends integrate as fragmented same-client runs —
    # exactly the long-lived hot doc shape the amortized pass misses
    for k in range(16):
        sv = encode_state_vector(d)
        t.insert(len(t.to_string()), f"x{k},")
        p.receive_update("r", encode_state_as_update(d, sv))
        p.flush()
    sv = encode_state_vector(d)
    t.delete(0, len(t.to_string()) - 4)  # tombstone most of the content
    p.receive_update("r", encode_state_as_update(d, sv))
    text_before = p.text("r")
    assert text_before == d.get_text("text").to_string()
    stats = p.tiers.gc_pass()
    assert stats["docs"] == 1
    assert stats["rows_reclaimed"] > 0
    assert p.text("r") == text_before  # live content untouched
    # below-threshold docs are skipped entirely
    p.receive_update("small", upd("tiny", cid=8))
    assert p.tiers.gc_pass()["docs"] == 0


def test_tick_tiering_is_inert_when_disabled():
    p = TpuProvider(1)
    p.receive_update("r", upd("plain"))
    p.tick_tiering()  # must not raise, must not move anything
    assert p.has_doc("r")
    assert p.tier_snapshot()["enabled"] is False


# -- observability -----------------------------------------------------------


def test_tier_metrics_and_snapshot_track_transitions():
    p = TpuProvider(2, tier_config=tiered())
    p.receive_update("r", upd("measured"))
    p.demote_doc("r", "warm")
    p.text("r")  # promote
    p.demote_doc("r", "cold")
    snap = p.metrics_snapshot()
    tiers = snap["tiers"]
    assert tiers["enabled"] and tiers["cold"] == 1 and tiers["hot"] == 0
    assert tiers["resident"] == 1 and tiers["capacity"] == 2
    gauges = snap["gauges"]["ytpu_tier_docs"]
    assert gauges["tier=cold"] == 1 and gauges["tier=hot"] == 0
    trans = snap["counters"]["ytpu_tier_transitions_total"]
    assert sum(trans.values()) >= 3  # hot→warm, warm→hot, hot→warm→cold


# -- fleet integration -------------------------------------------------------


def test_fleet_overcommit_admits_past_slot_capacity(rng):
    f = FleetRouter(
        n_shards=2,
        docs_per_shard=2,
        tier_config=tiered(overcommit=16),
    )
    assert f.capacity == 2 * 2 * 16
    texts = {}
    for k in range(24):  # 6x the 4 physical slots, zero full errors
        g = f"room-{k:02d}"
        texts[g] = f"sharded {k}"
        f.receive_update(g, upd(texts[g], cid=k + 1))
    assert f.doc_count == 24
    for _ in range(40):
        g = rng.choice(sorted(texts))
        assert f.text(g) == texts[g]
    f.tick()  # tier maintenance runs fleet-wide without raising
    rows = f.fleet_snapshot()["shards"]
    assert sum(r["resident"] for r in rows) == 24
    assert all(r["docs"] <= 2 for r in rows)  # hot never exceeds slots


def test_rebalancer_sheds_cold_docs_before_hot_ones():
    # satellite 1: the shed order is real heat, not guid sort — make the
    # LOWEST guid the hottest doc; guid order would move it first, heat
    # order must keep it
    f = FleetRouter(
        n_shards=1,
        docs_per_shard=4,
        tier_config=tiered(overcommit=1),
    )
    guids = ["aa-hottest", "bb-mid", "cc-mid", "dd-coldest"]
    for k, g in enumerate(guids):
        f.receive_update(g, upd(f"doc {k}", cid=k + 1))
    for _ in range(6):
        f.text("aa-hottest")
    f.text("bb-mid")
    f.text("cc-mid")
    f.add_shard()  # empty destination; shard 0 is at 100% occupancy
    moves = [m["guid"] for m in f.rebalancer.plan()]
    assert moves  # over the high watermark: the planner does shed
    assert "dd-coldest" in moves
    assert "aa-hottest" not in moves


def test_migration_carries_heat_to_the_destination_shard():
    f = FleetRouter(
        n_shards=2, docs_per_shard=4, tier_config=tiered()
    )
    f.receive_update("mover", upd("travels with heat"))
    for _ in range(5):
        f.text("mover")
    src = f.owner_of("mover")
    score = f.shards[src].tiers.heat_of("mover")
    assert score > 1.0
    dst = 1 - src
    f.migrate_doc("mover", dst)
    assert f.owner_of("mover") == dst
    # the destination inherits the source score (plus its own admission
    # touches) instead of restarting from a cold ~1.0
    assert f.shards[dst].tiers.heat_of("mover") >= score * 0.95
