"""Black-box + tracing acceptance suite (ISSUE 11): a kill-primary
chaos run must auto-emit a flight-recorder dump whose conviction,
promotion, and session-rehome events all share ONE forced-sampled
episode trace id; across 20 storm seeds every dead-letter / failover
event lands in a dump with a resolvable trace id; convergence is
byte-identical with tracing fully on vs ``YTPU_OBS_DISABLED=1``; and a
3-shard fleet's merged Perfetto trace validates green under
``scripts/check_trace.py``'s invariants.

Deterministic end to end: seeded edits, hash-minted trace ids, a
jitter-free detector config so conviction lands on an exact tick.
"""

import random
import sys
from pathlib import Path

import pytest

import yjs_tpu as Y
from yjs_tpu.fleet import FailoverConfig, FleetRouter
from yjs_tpu.obs.blackbox import flight_recorder, reset_flight_recorder
from yjs_tpu.persistence import WalConfig
from yjs_tpu.provider import TpuProvider
from yjs_tpu.sync.session import SessionConfig
from yjs_tpu.sync.transport import PipeNetwork
from yjs_tpu.updates import encode_state_as_update, encode_state_vector

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "scripts"))

pytestmark = [
    pytest.mark.tracing, pytest.mark.failover, pytest.mark.chaos,
]

SMALL = WalConfig(segment_bytes=256, fsync="never")
FAST = FailoverConfig(suspect_ticks=2, confirm_ticks=1, jitter_ticks=0)
STORM_SEEDS = tuple(range(20))


def seeded_rooms(seed, n_rooms=4, n_ops=8):
    out = {}
    for j in range(n_rooms):
        gen = random.Random(seed * 1000 + j)
        d = Y.Doc(gc=False)
        d.client_id = 100 + j
        updates = []
        d.on("update", lambda u, origin, doc: updates.append(bytes(u)))
        t = d.get_text("text")
        for _ in range(n_ops):
            if len(t) and gen.random() < 0.3:
                t.delete(gen.randrange(len(t)), 1)
            else:
                t.insert(gen.randrange(len(t) + 1), gen.choice("abcdef "))
        out[f"room-{j}"] = (d, updates)
    return out


def edit(doc, text, pos=0):
    sv = encode_state_vector(doc)
    doc.get_text("text").insert(pos, text)
    return encode_state_as_update(doc, sv)


def canonical(fleet, guid):
    return Y.merge_updates([fleet.encode_state_as_update(guid)])


def canonical_doc(doc):
    return Y.merge_updates([encode_state_as_update(doc)])


def convict(fleet, shard, budget=16):
    for _ in range(budget):
        fleet.tick()
        if shard in fleet._down:
            return
    raise AssertionError(f"shard {shard} never convicted")


def _resolvable(trace):
    return (
        isinstance(trace, str) and len(trace) == 32
        and int(trace, 16) >= 0
    )


# -- the headline acceptance criterion ---------------------------------------


def test_kill_primary_dumps_one_traced_episode(tmp_path, monkeypatch):
    """Kill a primary under live sessions: the failover auto-dump must
    contain conviction + promotion + rehome + complete events all
    stamped with the SAME forced-sampled trace id, and the dump must
    land on disk when ``YTPU_BLACKBOX_DIR`` is set."""
    monkeypatch.setenv("YTPU_BLACKBOX_DIR", str(tmp_path / "bb"))
    rec = reset_flight_recorder()
    fleet = FleetRouter(
        3, 4, backend="cpu", wal_dir=tmp_path / "wal", wal_config=SMALL,
        failover_config=FAST,
    )
    rooms = seeded_rooms(seed=21)
    for g, (_d, ups) in rooms.items():
        for u in ups:
            fleet.receive_update(g, u)
    fleet.flush()
    fleet.tick()
    # a live peer session on room-0 so the failover has one to rehome
    cfg = SessionConfig(
        heartbeat=0, liveness=0, antientropy=0, hello_timeout=0,
        retry_base=4, retry_jitter=0.0, seed=1,
    )
    pa = TpuProvider(1, backend="cpu")
    net = PipeNetwork()
    tx, ty = net.pair("fleet", "A")
    sx = fleet.session("room-0", "A", cfg)
    sy = pa.session("room-0", "fleet", cfg)
    sx.connect(tx)
    sy.connect(ty)
    net.settle((sx.tick, sy.tick))
    assert sx.state == "live"

    victim = fleet.owner_of("room-0")
    fleet.kill_shard(victim)
    convict(fleet, victim)

    dump = rec.last_dump
    assert dump is not None and dump["reason"] == "failover"
    fo = [e for e in dump["events"] if e["subsystem"] == "failover"]
    kinds = {e["event"] for e in fo}
    assert {"conviction", "promotion", "rehome", "complete"} <= kinds
    # ONE episode trace ties the whole story together
    traces = {e["trace"] for e in fo}
    assert len(traces) == 1
    (episode,) = traces
    assert _resolvable(episode)
    assert dump["context"]["trace"] == episode
    assert dump["context"]["shard"] == victim
    # the dump also shipped to disk
    files = sorted((tmp_path / "bb").glob("blackbox-failover-*.json"))
    assert files and files[-1].name.endswith("-0001.json")
    # the conviction names a rehomed peer for the session we attached
    rehomes = [e for e in fo if e["event"] == "rehome"]
    assert any(e["guid"] == "room-0" and e["kv"]["peer"] == "A"
               for e in rehomes)
    # forensics never cost correctness: every doc survived promotion
    for g, (d, _ups) in rooms.items():
        assert canonical(fleet, g) == canonical_doc(d), g


# -- 20-seed storm: every failure event is dumped, traced --------------------


@pytest.mark.parametrize("seed", STORM_SEEDS)
def test_storm_every_failure_event_dumped_with_trace(seed, tmp_path):
    """Per seed: poison one room (dead letters + rollback), then kill
    the primary.  Every dead-letter / failover event recorded during
    the run must appear in an emitted dump, and every failover event
    must carry the episode's resolvable trace id."""
    rec = reset_flight_recorder()
    fleet = FleetRouter(
        3, 3, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
        failover_config=FAST,
    )
    rooms = seeded_rooms(seed, n_rooms=3, n_ops=6)
    for g, (_d, ups) in rooms.items():
        for u in ups:
            fleet.receive_update(g, u)
    fleet.flush()
    fleet.tick()
    # dead-letter seam: a poison update rolls back and dead-letters on
    # the owner (and on any replica that mirrors it)
    gen = random.Random(seed)
    poison_room = f"room-{gen.randrange(3)}"
    fleet.receive_update(poison_room, b"\xff\xff\xff\xff\xff")
    fleet.flush()
    # failover seam
    victim = fleet.owner_of("room-0")
    fleet.kill_shard(victim)
    convict(fleet, victim)

    must_dump = [
        e for e in rec.snapshot()
        if (e["subsystem"], e["event"]) in (
            ("resilience", "dead_letter"),
            ("failover", "conviction"),
            ("failover", "promotion"),
            ("failover", "doc_lost"),
            ("failover", "rehome"),
        )
    ]
    assert any(e["event"] == "dead_letter" for e in must_dump), seed
    assert any(e["event"] == "conviction" for e in must_dump), seed
    dumped_ticks = {
        e["tick"] for d in rec.dumps for e in d["events"]
    }
    for e in must_dump:
        assert e["tick"] in dumped_ticks, (seed, e)
        if e["subsystem"] == "failover":
            assert _resolvable(e["trace"]), (seed, e)
    episode = {
        e["trace"] for e in must_dump if e["subsystem"] == "failover"
    }
    assert len(episode) == 1, seed
    # and the storm never cost convergence on the healthy rooms
    for g, (d, _ups) in rooms.items():
        if g != poison_room:
            assert canonical(fleet, g) == canonical_doc(d), (seed, g)


# -- tracing must be free: byte-identical on vs off --------------------------


def test_convergence_identical_tracing_on_vs_obs_disabled(
    tmp_path, monkeypatch
):
    """The full pipeline — ingest, flush, replication, failover — must
    produce byte-identical documents with everything sampled vs
    ``YTPU_OBS_DISABLED=1`` (the acceptance criterion that tracing is
    observation, never participation)."""

    def run(flag_env):
        for k, v in flag_env.items():
            monkeypatch.setenv(k, v)
        try:
            reset_flight_recorder()
            fleet = FleetRouter(
                3, 4, backend="cpu",
                wal_dir=tmp_path / "-".join(sorted(flag_env)),
                wal_config=SMALL, failover_config=FAST,
            )
            rooms = seeded_rooms(seed=33)
            for g, (_d, ups) in rooms.items():
                for u in ups:
                    fleet.receive_update(g, u)
            fleet.flush()
            fleet.tick()
            victim = fleet.owner_of("room-0")
            fleet.kill_shard(victim)
            convict(fleet, victim)
            for g, (d, _ups) in rooms.items():
                fleet.receive_update(g, edit(d, "after failover "))
            fleet.flush()
            out = {g: canonical(fleet, g) for g in rooms}
            refs = {g: canonical_doc(d) for g, (d, _u) in rooms.items()}
            return out, refs
        finally:
            for k in flag_env:
                monkeypatch.delenv(k)

    traced, refs_a = run({"YTPU_TRACE_SAMPLE": "1"})
    dark, refs_b = run({"YTPU_OBS_DISABLED": "1", "YTPU_BLACKBOX": "0"})
    assert traced == dark
    assert traced == refs_a == refs_b


# -- the merged trace validates under check_trace's invariants ----------------


def test_merged_fleet_trace_validates_green(monkeypatch):
    """Everything-sampled 3-shard run, all shard tracers merged: every
    flow arrow resolves both ways and every sampled ingress chain
    reaches a convergence flow-finish (the same invariants CI enforces
    via ``check_trace --selftest``)."""
    import check_trace

    monkeypatch.setenv("YTPU_TRACE_SAMPLE", "1")
    fleet = FleetRouter(3, 4, backend="cpu")
    rooms = seeded_rooms(seed=44)
    for _round in range(2):
        for g, (d, _ups) in sorted(rooms.items()):
            fleet.receive_update(g, edit(d, f"{g} r{_round} "))
        fleet.flush()
        fleet.tick()
    fleet.repl.repair_all()
    fleet.flush()

    events = []
    for p in fleet.shards:
        events.extend(p.engine.obs.tracer.trace_events())
    events.sort(key=lambda e: e.get("ts", 0.0))
    assert check_trace.validate_events(events) == []
    ingress = {
        (e.get("args") or {}).get("trace")
        for e in events
        if str(e.get("name", "")).startswith(check_trace.INGRESS_NAMES)
        and (e.get("args") or {}).get("trace")
    }
    assert ingress, "no sampled ingress spans in the merged trace"
    assert any(
        e.get("name") == "ytpu.repl.fanout" and e.get("ph") == "f"
        for e in events
    ), "no replication fan-out arrows in the merged trace"
