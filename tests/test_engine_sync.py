"""Wire emission from the columnar mirror: sync steps without a CPU Doc."""

import random

import pytest

import yjs_tpu as Y
from yjs_tpu.ops import BatchEngine


def build_traced_doc(seed, client_id):
    gen = random.Random(seed)
    d = Y.Doc(gc=False)
    d.client_id = client_id
    t = d.get_text("text")
    for _ in range(30):
        ln = len(t.to_string())
        if gen.random() < 0.7 or ln == 0:
            t.insert(gen.randint(0, ln), gen.choice(["ab", "c", "ddd", "🙂"]))
        else:
            pos = gen.randrange(ln)
            t.delete(pos, min(gen.randint(1, 2), ln - pos))
    return d


def loaded_engine(doc):
    eng = BatchEngine(1)
    eng.queue_update(0, Y.encode_state_as_update(doc))
    eng.flush()
    return eng


class TestMirrorEmission:
    @pytest.mark.parametrize("v2", [False, True])
    def test_full_state_round_trip(self, v2):
        doc = build_traced_doc(1, 11)
        eng = loaded_engine(doc)
        update = eng.encode_state_as_update(0, v2=v2)
        fresh = Y.Doc(gc=False)
        (Y.apply_update_v2 if v2 else Y.apply_update)(fresh, update)
        assert fresh.get_text("text").to_string() == doc.get_text("text").to_string()
        assert Y.decode_state_vector(Y.encode_state_vector(fresh)) == (
            Y.decode_state_vector(Y.encode_state_vector(doc))
        )
        # delete sets must be equivalent after merge
        from yjs_tpu.core import create_delete_set_from_struct_store

        ds_a = create_delete_set_from_struct_store(fresh.store)
        ds_b = create_delete_set_from_struct_store(doc.store)
        assert {
            c: [(d.clock, d.len) for d in v] for c, v in ds_a.clients.items()
        } == {c: [(d.clock, d.len) for d in v] for c, v in ds_b.clients.items()}

    def test_diff_against_state_vector(self):
        doc = Y.Doc(gc=False)
        doc.client_id = 21
        updates = []
        doc.on("update", lambda u, o, d: updates.append(u))
        t = doc.get_text("text")
        for i in range(12):
            t.insert(len(t.to_string()) // 2, f"w{i} ")
            if i % 3 == 2:
                t.delete(0, 2)
        # peer holds a true prefix of the history
        partial = Y.Doc(gc=False)
        for u in updates[:5]:
            Y.apply_update(partial, u)

        eng = loaded_engine(doc)
        # ask the engine for exactly what `partial` is missing
        diff = eng.encode_state_as_update(0, Y.encode_state_vector(partial))
        Y.apply_update(partial, diff)
        assert partial.get_text("text").to_string() == t.to_string()

    def test_engine_to_engine_sync(self):
        a = build_traced_doc(3, 31)
        b = build_traced_doc(4, 32)
        ea, eb = loaded_engine(a), loaded_engine(b)
        # 2-step handshake in both directions, engine-to-engine
        upd_for_b = ea.encode_state_as_update(0, eb.encode_state_vector(0))
        upd_for_a = eb.encode_state_as_update(0, ea.encode_state_vector(0))
        ea.queue_update(0, upd_for_a)
        eb.queue_update(0, upd_for_b)
        ea.flush()
        eb.flush()
        assert ea.text(0) == eb.text(0)
        assert ea.state_vector(0) == eb.state_vector(0)
        # oracle: CPU docs syncing the same histories agree with the engines
        Y.apply_update(a, Y.encode_state_as_update(b))
        assert ea.text(0) == a.get_text("text").to_string()

    def test_emitted_update_feeds_engine(self):
        doc = build_traced_doc(5, 41)
        eng = loaded_engine(doc)
        again = BatchEngine(1)
        again.queue_update(0, eng.encode_state_as_update(0))
        again.flush()
        assert again.text(0) == eng.text(0)
        assert again.state_vector(0) == eng.state_vector(0)

    def test_incremental_then_emit(self):
        doc = Y.Doc(gc=False)
        doc.client_id = 51
        updates = []
        doc.on("update", lambda u, o, d: updates.append(u))
        t = doc.get_text("text")
        eng = BatchEngine(1)
        for step in range(5):
            t.insert(len(t.to_string()) // 2, f"<{step}>")
            if step % 2:
                t.delete(0, 1)
            for u in updates:
                eng.queue_update(0, u)
            updates.clear()
            eng.flush()
        out = Y.Doc(gc=False)
        Y.apply_update(out, eng.encode_state_as_update(0))
        assert out.get_text("text").to_string() == t.to_string()
