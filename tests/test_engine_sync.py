"""Wire emission from the columnar mirror: sync steps without a CPU Doc."""

import random

import pytest

import yjs_tpu as Y
from yjs_tpu.ops import BatchEngine


def build_traced_doc(seed, client_id):
    gen = random.Random(seed)
    d = Y.Doc(gc=False)
    d.client_id = client_id
    t = d.get_text("text")
    for _ in range(30):
        ln = len(t.to_string())
        if gen.random() < 0.7 or ln == 0:
            t.insert(gen.randint(0, ln), gen.choice(["ab", "c", "ddd", "🙂"]))
        else:
            pos = gen.randrange(ln)
            t.delete(pos, min(gen.randint(1, 2), ln - pos))
    return d


def loaded_engine(doc):
    eng = BatchEngine(1)
    eng.queue_update(0, Y.encode_state_as_update(doc))
    eng.flush()
    return eng


class TestMirrorEmission:
    @pytest.mark.parametrize("v2", [False, True])
    def test_full_state_round_trip(self, v2):
        doc = build_traced_doc(1, 11)
        eng = loaded_engine(doc)
        update = eng.encode_state_as_update(0, v2=v2)
        fresh = Y.Doc(gc=False)
        (Y.apply_update_v2 if v2 else Y.apply_update)(fresh, update)
        assert fresh.get_text("text").to_string() == doc.get_text("text").to_string()
        assert Y.decode_state_vector(Y.encode_state_vector(fresh)) == (
            Y.decode_state_vector(Y.encode_state_vector(doc))
        )
        # delete sets must be equivalent after merge
        from yjs_tpu.core import create_delete_set_from_struct_store

        ds_a = create_delete_set_from_struct_store(fresh.store)
        ds_b = create_delete_set_from_struct_store(doc.store)
        assert {
            c: [(d.clock, d.len) for d in v] for c, v in ds_a.clients.items()
        } == {c: [(d.clock, d.len) for d in v] for c, v in ds_b.clients.items()}

    def test_diff_against_state_vector(self):
        doc = Y.Doc(gc=False)
        doc.client_id = 21
        updates = []
        doc.on("update", lambda u, o, d: updates.append(u))
        t = doc.get_text("text")
        for i in range(12):
            t.insert(len(t.to_string()) // 2, f"w{i} ")
            if i % 3 == 2:
                t.delete(0, 2)
        # peer holds a true prefix of the history
        partial = Y.Doc(gc=False)
        for u in updates[:5]:
            Y.apply_update(partial, u)

        eng = loaded_engine(doc)
        # ask the engine for exactly what `partial` is missing
        diff = eng.encode_state_as_update(0, Y.encode_state_vector(partial))
        Y.apply_update(partial, diff)
        assert partial.get_text("text").to_string() == t.to_string()

    def test_engine_to_engine_sync(self):
        a = build_traced_doc(3, 31)
        b = build_traced_doc(4, 32)
        ea, eb = loaded_engine(a), loaded_engine(b)
        # 2-step handshake in both directions, engine-to-engine
        upd_for_b = ea.encode_state_as_update(0, eb.encode_state_vector(0))
        upd_for_a = eb.encode_state_as_update(0, ea.encode_state_vector(0))
        ea.queue_update(0, upd_for_a)
        eb.queue_update(0, upd_for_b)
        ea.flush()
        eb.flush()
        assert ea.text(0) == eb.text(0)
        assert ea.state_vector(0) == eb.state_vector(0)
        # oracle: CPU docs syncing the same histories agree with the engines
        Y.apply_update(a, Y.encode_state_as_update(b))
        assert ea.text(0) == a.get_text("text").to_string()

    def test_emitted_update_feeds_engine(self):
        doc = build_traced_doc(5, 41)
        eng = loaded_engine(doc)
        again = BatchEngine(1)
        again.queue_update(0, eng.encode_state_as_update(0))
        again.flush()
        assert again.text(0) == eng.text(0)
        assert again.state_vector(0) == eng.state_vector(0)

    def test_incremental_then_emit(self):
        doc = Y.Doc(gc=False)
        doc.client_id = 51
        updates = []
        doc.on("update", lambda u, o, d: updates.append(u))
        t = doc.get_text("text")
        eng = BatchEngine(1)
        for step in range(5):
            t.insert(len(t.to_string()) // 2, f"<{step}>")
            if step % 2:
                t.delete(0, 1)
            for u in updates:
                eng.queue_update(0, u)
            updates.clear()
            eng.flush()
        out = Y.Doc(gc=False)
        Y.apply_update(out, eng.encode_state_as_update(0))
        assert out.get_text("text").to_string() == t.to_string()


class TestBatchedSyncKernels:
    """Sync step 1 + 2 across many docs in single kernel dispatches
    (VERDICT item 5; reference encoding.js:490-526,94-116 batched)."""

    def _make_engine(self, n):
        import yjs_tpu as Y
        from yjs_tpu.ops import BatchEngine

        docs, eng = [], BatchEngine(n)
        for i in range(n):
            d = Y.Doc(gc=False)
            d.client_id = 100 + i
            t = d.get_text("text")
            t.insert(0, f"doc{i} " * (i + 1))
            t.delete(0, 2)
            d.get_map("m").set("k", i)
            docs.append(d)
            eng.queue_update(i, Y.encode_state_as_update(d))
        eng.flush()
        return docs, eng

    def test_state_vectors_batched_matches_per_doc(self):
        docs, eng = self._make_engine(6)
        svs = eng.state_vectors_batched(list(range(6)))
        for i in range(6):
            assert svs[i] == eng.state_vector(i)

    def test_sync_step2_batch_matches_per_doc_and_cpu(self):
        import yjs_tpu as Y

        docs, eng = self._make_engine(6)
        # mixed targets: empty, full, and partial state vectors
        partial = {100 + 3: 4}
        requests = [(0, None), (1, {}), (3, partial), (5, None)]
        replies = eng.sync_step2_batch(requests)
        for (i, sv), u in zip(requests, replies):
            import yjs_tpu.updates as upd
            from yjs_tpu.coding import DSEncoderV1

            enc_sv = None
            if sv:
                e = DSEncoderV1()
                upd.write_state_vector(e, sv)
                enc_sv = e.to_bytes()
            assert u == eng.encode_state_as_update(i, enc_sv)
            fresh = Y.Doc(gc=False)
            if sv:  # partial target: seed the fresh doc with the prefix
                continue
            Y.apply_update(fresh, u)
            assert fresh.get_text("text").to_string() == docs[i].get_text(
                "text"
            ).to_string()
            assert fresh.get_map("m").to_json() == docs[i].get_map("m").to_json()

    def test_partial_target_resyncs_stale_client(self):
        import yjs_tpu as Y

        docs, eng = self._make_engine(4)
        stale = Y.Doc(gc=False)
        stale.client_id = 900
        # stale client knows a prefix of doc 2
        d = docs[2]
        t = d.get_text("text")
        Y.apply_update(stale, Y.encode_state_as_update(d))
        t.insert(3, "[new]")
        u = Y.encode_state_as_update(d, Y.encode_state_vector(stale))
        eng.queue_update(2, u)
        eng.flush()
        sv = {c: v for c, v in Y.decode_state_vector(
            Y.encode_state_vector(stale)).items()}
        (reply,) = eng.sync_step2_batch([(2, sv)])
        Y.apply_update(stale, reply)
        Y.apply_update(d, u)  # author applies its own edit too (already has)
        assert stale.get_text("text").to_string() == d.get_text("text").to_string()

    def test_provider_batch_handshake(self):
        import yjs_tpu as Y
        from yjs_tpu.provider import TpuProvider
        from yjs_tpu.lib0.encoding import Encoder
        from yjs_tpu.lib0.decoding import Decoder
        from yjs_tpu.sync import protocol

        n = 5
        prov = TpuProvider(n)
        clients = []
        for i in range(n):
            d = Y.Doc(gc=False)
            d.client_id = 200 + i
            d.get_text("text").insert(0, f"room{i}")
            prov.receive_update(f"r{i}", Y.encode_state_as_update(d))
            clients.append(d)
        # every client reconnects at once: one dispatch answers all
        msgs = []
        for i, d in enumerate(clients):
            enc = Encoder()
            protocol.write_sync_step1(enc, d)
            msgs.append((f"r{i}", enc.to_bytes()))
        replies = prov.handle_sync_step1_batch(msgs)
        for d, reply in zip(clients, replies):
            protocol.read_sync_message(Decoder(reply), Encoder(), d)
        for i, d in enumerate(clients):
            assert prov.text(f"r{i}") == d.get_text("text").to_string()
