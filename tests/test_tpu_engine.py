"""Convergence tests: the TPU batch engine vs the CPU reference core.

The oracle (mirroring tests/testHelper.js compare(), reference
tests/testHelper.js:274-313): after applying the same updates, the device
engine must produce the same document text, the same state vector, and the
same element order as the CPU core.
"""

import random

import pytest

import yjs_tpu as Y
from yjs_tpu.ops import BatchEngine


def cpu_rows_in_order(doc: Y.Doc, name: str = "text"):
    """(client, clock, length, deleted) per item in list order, split to the
    same granularity the engine reports (runs may differ; flatten to unit
    granularity for comparison)."""
    out = []
    item = doc.get_text(name)._start
    while item is not None:
        for off in range(item.length):
            out.append((item.id.client, item.id.clock + off, item.deleted))
        item = item.right
    return out


def engine_rows_unit(eng: BatchEngine, i: int, name: str = "text"):
    out = []
    for client, clock, length, deleted in eng.rows_in_order(i, name):
        for off in range(length):
            out.append((client, clock + off, deleted))
    return out


def make_doc(client_id: int) -> Y.Doc:
    d = Y.Doc(gc=False)
    d.client_id = client_id
    return d


def assert_engine_matches(eng, doc: Y.Doc, idx=0, name="text"):
    assert eng.text(idx, name) == doc.get_text(name).to_string()
    assert eng.state_vector(idx) == {
        c: v for c, v in Y.get_state_vector(doc.store).items() if v > 0
    }
    assert engine_rows_unit(eng, idx, name) == cpu_rows_in_order(doc, name)


def replay_into_engine(updates, n_docs=1, v2=False):
    eng = BatchEngine(n_docs)
    for i in range(n_docs):
        for u in updates:
            eng.queue_update(i, u, v2=v2)
    eng.flush()
    return eng


def collect_updates(doc: Y.Doc):
    """Record incremental update blobs from a doc."""
    updates = []
    doc.on("update", lambda u, origin, d: updates.append(u))
    return updates


class TestAppendOnly:
    def test_single_client_appends(self):
        doc = make_doc(1)
        updates = collect_updates(doc)
        t = doc.get_text("text")
        for i in range(50):
            t.insert(len(t.to_string()), f"w{i} ")
        eng = replay_into_engine(updates)
        assert_engine_matches(eng, doc)

    def test_full_state_update(self):
        doc = make_doc(1)
        t = doc.get_text("text")
        t.insert(0, "hello world")
        t.insert(5, ", brave")
        eng = replay_into_engine([Y.encode_state_as_update(doc)])
        assert_engine_matches(eng, doc)


class TestConcurrent:
    def test_two_clients_interleaved(self):
        a, b = make_doc(1), make_doc(2)
        ua, ub = collect_updates(a), collect_updates(b)
        a.get_text("text").insert(0, "aaa")
        b.get_text("text").insert(0, "bbb")
        # cross-sync (updates are idempotent+commutative: deliver everything)
        for u in list(ub):
            Y.apply_update(a, u)
        for u in list(ua):
            Y.apply_update(b, u)
        a.get_text("text").insert(3, "XYZ")
        b.get_text("text").insert(1, "qq")
        for u in list(ub):
            Y.apply_update(a, u)
        for u in list(ua):
            Y.apply_update(b, u)
        assert a.get_text("text").to_string() == b.get_text("text").to_string()
        eng = replay_into_engine(ua + ub)
        assert_engine_matches(eng, a)

    def test_concurrent_same_position(self):
        docs = [make_doc(i + 1) for i in range(4)]
        upds = [collect_updates(d) for d in docs]
        for i, d in enumerate(docs):
            d.get_text("text").insert(0, f"<{i}>")
        all_updates = [u for us in upds for u in us]
        for d in docs:
            for u in all_updates:
                Y.apply_update(d, u)
        for d in docs[1:]:
            assert d.get_text("text").to_string() == docs[0].get_text("text").to_string()
        eng = replay_into_engine(all_updates)
        assert_engine_matches(eng, docs[0])

    def test_deletes(self):
        a, b = make_doc(1), make_doc(2)
        ua, ub = collect_updates(a), collect_updates(b)
        a.get_text("text").insert(0, "abcdefgh")
        for u in list(ua):
            Y.apply_update(b, u)
        a.get_text("text").delete(2, 3)
        b.get_text("text").insert(4, "ZZ")
        for u in list(ub):
            Y.apply_update(a, u)
        for u in list(ua):
            Y.apply_update(b, u)
        assert a.get_text("text").to_string() == b.get_text("text").to_string()
        eng = replay_into_engine(ua + ub)
        assert_engine_matches(eng, a)

    def test_out_of_order_delivery_buffers_pending(self):
        doc = make_doc(7)
        updates = collect_updates(doc)
        t = doc.get_text("text")
        t.insert(0, "one ")
        t.insert(4, "two ")
        t.insert(8, "three")
        eng = BatchEngine(1)
        # deliver newest first: must park in pending, then resolve
        eng.queue_update(0, updates[2])
        eng.flush()
        assert eng.has_pending(0)
        eng.queue_update(0, updates[0])
        eng.queue_update(0, updates[1])
        eng.flush()
        assert not eng.has_pending(0)
        assert_engine_matches(eng, doc)


class TestRandomizedConvergence:
    @pytest.mark.parametrize("seed", range(8))
    def test_fuzz_text_edits(self, seed):
        gen = random.Random(seed)
        n_clients = gen.randint(2, 4)
        docs = [make_doc(i + 1) for i in range(n_clients)]
        upds = [collect_updates(d) for d in docs]
        sent: list[int] = [0] * n_clients  # per-doc cursor into peers
        for _ in range(40):
            i = gen.randrange(n_clients)
            d = docs[i]
            t = d.get_text("text")
            ln = len(t.to_string())
            op = gen.random()
            if op < 0.65 or ln == 0:
                pos = gen.randint(0, ln)
                t.insert(pos, gen.choice(["a", "bb", "ccc", "x", "🙂"]))
            else:
                pos = gen.randrange(ln)
                t.delete(pos, min(gen.randint(1, 3), ln - pos))
            if gen.random() < 0.3:
                # deliver a random peer's pending updates to a random doc
                src = gen.randrange(n_clients)
                dst = gen.randrange(n_clients)
                for u in upds[src]:
                    Y.apply_update(docs[dst], u)
        # final full sync
        all_updates = [u for us in upds for u in us]
        gen.shuffle(all_updates)
        for d in docs:
            for u in all_updates:
                Y.apply_update(d, u)
        for d in docs[1:]:
            assert d.get_text("text").to_string() == docs[0].get_text("text").to_string()
        eng = replay_into_engine(all_updates)
        assert not eng.has_pending(0)
        assert_engine_matches(eng, docs[0])

    def test_v2_encoding(self):
        doc = make_doc(3)
        t = doc.get_text("text")
        t.insert(0, "hello")
        t.insert(2, "XX")
        t.delete(1, 3)
        eng = BatchEngine(1)
        eng.queue_update(0, Y.encode_state_as_update_v2(doc), v2=True)
        eng.flush()
        assert_engine_matches(eng, doc)


class TestBatch:
    def test_many_docs_one_flush(self):
        n = 16
        docs = [make_doc(100 + i) for i in range(n)]
        eng = BatchEngine(n)
        for i, d in enumerate(docs):
            t = d.get_text("text")
            t.insert(0, f"doc-{i}:")
            t.insert(len(t.to_string()), "payload" * (i % 3 + 1))
            t.delete(0, 2)
            eng.queue_update(i, Y.encode_state_as_update(d))
        eng.flush()
        for i, d in enumerate(docs):
            assert eng.text(i) == d.get_text("text").to_string()
            assert_engine_matches(eng, d, idx=i)

    def test_incremental_flushes(self):
        doc = make_doc(5)
        updates = collect_updates(doc)
        t = doc.get_text("text")
        eng = BatchEngine(1)
        for step in range(6):
            t.insert(len(t.to_string()) // 2, f"[{step}]")
            if step % 2 == 1:
                t.delete(0, 1)
            for u in updates:
                eng.queue_update(0, u)
            updates.clear()
            eng.flush()
            assert_engine_matches(eng, doc)


class TestFallback:
    def test_map_and_multiroot_stay_on_device(self):
        doc = make_doc(9)
        doc.get_map("m").set("k", 1)
        doc.get_text("text").insert(0, "hi")
        doc.get_text("notes").insert(0, "n0")
        eng = BatchEngine(1)
        eng.queue_update(0, Y.encode_state_as_update(doc))
        eng.flush()
        assert 0 not in eng.fallback
        assert eng.text(0) == "hi"
        assert eng.text(0, "notes") == "n0"
        assert eng.map_json(0, "m") == {"k": 1}

    def test_subdoc_demotes_to_cpu(self):
        doc = make_doc(9)
        doc.get_map("m").set("sub", Y.Doc(guid="child"))  # ContentDoc
        doc.get_text("text").insert(0, "hi")
        eng = BatchEngine(1)
        eng.queue_update(0, Y.encode_state_as_update(doc))
        eng.flush()
        assert 0 in eng.fallback
        assert eng.demotions[0]["reason"] == "subdocument (content ref 9)"
        assert eng.text(0) == "hi"

    def test_mixed_demotions_inside_chunked_flush(self, monkeypatch):
        """Docs demoting mid-chunk (subdoc updates) must not disturb the
        rest of the batched flush: per-doc rc routing in prepare_many."""
        from yjs_tpu.ops.native_mirror import native_plan_available

        if not native_plan_available():
            pytest.skip("chunked batched flush requires the native planner")
        monkeypatch.setenv("YTPU_FLUSH_CHUNK", "8")
        n = 20
        eng = BatchEngine(n)
        docs = [make_doc(200 + i) for i in range(n)]
        for i, d in enumerate(docs):
            d.get_text("text").insert(0, f"doc{i} body")
            if i % 7 == 3:  # 3, 10, 17 -> one demotion per chunk
                d.get_map("m").set("sub", Y.Doc(guid=f"child{i}"))
            eng.queue_update(i, Y.encode_state_as_update(d))
        eng.flush()
        demoted = {i for i in range(n) if i % 7 == 3}
        assert set(eng.fallback) == demoted
        assert len(eng.demotions) == len(demoted)
        for i in range(n):
            if i in demoted:
                assert eng.text(i) == docs[i].get_text("text").to_string(), i
            else:
                assert_engine_matches(eng, docs[i], i)
        # native docs keep flowing through later chunked flushes
        for i, d in enumerate(docs):
            d.get_text("text").insert(0, "more ")
            eng.queue_update(i, Y.encode_state_as_update(d))
        eng.flush()
        assert set(eng.fallback) == demoted  # no new demotions
        for i in range(n):
            if i in demoted:
                assert eng.text(i) == docs[i].get_text("text").to_string(), i
            else:
                assert_engine_matches(eng, docs[i], i)


class TestNestedTypes:
    """Nested shared types integrate on device as parent-row-keyed segments
    (reference ContentType.js); only subdocuments fall back."""

    def test_nested_map_array_text_stay_on_device(self):
        a = make_doc(5)
        m = a.get_map("root")
        inner = Y.YMap()
        m.set("inner", inner)
        inner.set("k", 42)
        arr = a.get_array("arr")
        nt = Y.YText()
        arr.insert(0, ["plain", nt])
        nt.insert(0, "nested text")
        nt.insert(6, "🙂")
        eng = BatchEngine(1)
        eng.queue_update(0, Y.encode_state_as_update(a))
        eng.flush()
        assert not eng.fallback
        assert eng.map_json(0, "root") == a.get_map("root").to_json()
        assert eng.to_json(0, "arr") == a.get_array("arr").to_json()
        # the mirror's wire export reconstructs the nested state
        d = Y.Doc(gc=False)
        Y.apply_update(d, eng.encode_state_as_update(0))
        assert d.get_map("root").to_json() == a.get_map("root").to_json()
        assert d.get_array("arr").to_json() == a.get_array("arr").to_json()

    def test_parent_arrives_after_children(self):
        # children reference the type item causally: delivering them first
        # must park them in pending, not corrupt state
        a = make_doc(6)
        sv0 = Y.encode_state_vector(a)
        nt = Y.YText()
        a.get_map("root").set("t", nt)
        u_parent = Y.encode_state_as_update(a, sv0)
        sv1 = Y.encode_state_vector(a)
        nt.insert(0, "abc")
        u_children = Y.encode_state_as_update(a, sv1)
        eng = BatchEngine(1)
        eng.queue_update(0, u_children)
        eng.flush()
        assert eng.has_pending(0)
        eng.queue_update(0, u_parent)
        eng.flush()
        assert not eng.has_pending(0)
        assert eng.map_json(0, "root") == {"t": "abc"}

    def test_deleting_type_deletes_subtree(self):
        a = make_doc(7)
        arr = a.get_array("arr")
        nested = Y.YArray()
        arr.insert(0, [nested, "tail"])
        nested.insert(0, [1, 2, 3])
        eng = BatchEngine(1)
        eng.queue_update(0, Y.encode_state_as_update(a))
        eng.flush()
        assert eng.to_json(0, "arr") == [[1, 2, 3], "tail"]
        sv = Y.encode_state_vector(a)
        arr.delete(0, 1)  # deletes the nested type + its subtree
        eng.queue_update(0, Y.encode_state_as_update(a, sv))
        eng.flush()
        assert eng.to_json(0, "arr") == a.get_array("arr").to_json() == ["tail"]
        d = Y.Doc(gc=False)
        Y.apply_update(d, eng.encode_state_as_update(0))
        assert d.get_array("arr").to_json() == ["tail"]

    def test_gc_compaction_preserves_nested_parent_rows(self):
        # a deleted nested type row must survive GC compaction un-merged:
        # its children's wire parent id is that row's identity
        a = make_doc(8)
        arr = a.get_array("arr")
        arr.insert(0, ["s0", "s1", "s2"])
        nested = Y.YMap()
        arr.insert(3, [nested])
        nested.set("k", 1)
        arr.insert(4, ["t0", "t1", "t2"])
        eng = BatchEngine(1, gc=True, compact_min_rows=4)
        eng.queue_update(0, Y.encode_state_as_update(a))
        eng.flush()
        sv = Y.encode_state_vector(a)
        arr.delete(0, 7)  # everything, nested type included
        eng.queue_update(0, Y.encode_state_as_update(a, sv))
        eng.flush()
        # append until compaction triggers with the tombstoned type inside
        t = a.get_text("text")
        for i in range(12):
            sv = Y.encode_state_vector(a)
            t.insert(len(t.to_string()), f"w{i} ")
            eng.queue_update(0, Y.encode_state_as_update(a, sv))
            eng.flush()
        assert eng.last_compaction, "compaction should have run"
        # exports still work and round-trip
        assert eng.to_json(0, "arr") == a.get_array("arr").to_json() == []
        d = Y.Doc(gc=False)
        Y.apply_update(d, eng.encode_state_as_update(0))
        assert d.get_array("arr").to_json() == []
        assert d.get_text("text").to_string() == t.to_string()

    @pytest.mark.parametrize("seed", range(4))
    def test_fuzz_nested_ops(self, seed):
        gen = random.Random(5000 + seed)
        n_clients = 3
        docs = [make_doc(i + 1) for i in range(n_clients)]
        upds = [collect_updates(d) for d in docs]
        # everyone starts from a shared nested skeleton
        nt = Y.YText()
        na = Y.YArray()
        docs[0].get_map("root").set("text", nt)
        docs[0].get_map("root").set("list", na)
        for d in docs[1:]:
            Y.apply_update(d, Y.encode_state_as_update(docs[0]))
        for _ in range(40):
            i = gen.randrange(n_clients)
            d = docs[i]
            op = gen.random()
            root = d.get_map("root")
            if op < 0.35:
                t = root.get("text")
                if t is not None:
                    ln = len(t.to_string())
                    if gen.random() < 0.7 or ln == 0:
                        t.insert(gen.randint(0, ln), gen.choice(["x", "yz "]))
                    else:
                        pos = gen.randrange(ln)
                        t.delete(pos, min(gen.randint(1, 2), ln - pos))
            elif op < 0.6:
                arr = root.get("list")
                if arr is not None:
                    if gen.random() < 0.7 or len(arr.to_json()) == 0:
                        arr.insert(
                            gen.randint(0, len(arr.to_json())),
                            [gen.randrange(100)],
                        )
                    else:
                        arr.delete(gen.randrange(len(arr.to_json())), 1)
            elif op < 0.8:
                root.set(gen.choice("abc"), gen.randrange(100))
            else:
                inner = Y.YMap()
                root.set(gen.choice("mn"), inner)
            if gen.random() < 0.3:
                src, dst = gen.randrange(n_clients), gen.randrange(n_clients)
                for u in upds[src]:
                    Y.apply_update(docs[dst], u)
        all_updates = [u for us in upds for u in us]
        gen.shuffle(all_updates)
        for d in docs:
            for u in all_updates:
                Y.apply_update(d, u)
        eng = replay_into_engine(all_updates)
        assert not eng.fallback, eng.demotions
        ref = docs[0]
        for other in docs[1:]:
            assert other.get_map("root").to_json() == ref.get_map("root").to_json()
        assert eng.map_json(0, "root") == ref.get_map("root").to_json()
        # wire export round-trips the full nested state
        d2 = Y.Doc(gc=False)
        Y.apply_update(d2, eng.encode_state_as_update(0))
        assert d2.get_map("root").to_json() == ref.get_map("root").to_json()

    def test_concurrent_nested_edits_converge(self):
        a, b = make_doc(1), make_doc(2)
        nt = Y.YText()
        a.get_map("root").set("doc", nt)
        Y.apply_update(b, Y.encode_state_as_update(a))
        # concurrent edits in the nested text
        a.get_map("root").get("doc").insert(0, "AA")
        b.get_map("root").get("doc").insert(0, "BB")
        ua, ub = Y.encode_state_as_update(a), Y.encode_state_as_update(b)
        Y.apply_update(a, ub)
        Y.apply_update(b, ua)
        assert (
            a.get_map("root").to_json() == b.get_map("root").to_json()
        )
        eng = BatchEngine(1)
        eng.queue_update(0, ub)
        eng.queue_update(0, ua)
        eng.flush()
        assert not eng.fallback
        assert eng.map_json(0, "root") == a.get_map("root").to_json()


class TestUpdateLogCompaction:
    def test_log_bounded_and_demotion_replays_snapshot(self):
        """After >64 pending-free flushes the demotion-replay log collapses
        to one columnar export; a later demotion must still rebuild the full
        doc from it (engine._update_log compaction)."""
        doc = make_doc(31)
        t = doc.get_text("text")
        eng = BatchEngine(1)
        sv = None
        for step in range(70):
            t.insert(len(t.to_string()), f"w{step} ")
            u = Y.encode_state_as_update(doc, sv)
            sv = Y.encode_state_vector(doc)
            eng.queue_update(0, u)
            eng.flush()
        # compacted at the 65th flush to [snapshot], then the tail appended
        assert len(eng._update_log[0]) <= 6
        assert_engine_matches(eng, doc)
        # demotion after compaction replays the snapshot + tail correctly
        doc.get_map("m").set("sub", Y.Doc(guid="kid"))  # unsupported -> demote
        t.insert(0, "head ")
        eng.queue_update(0, Y.encode_state_as_update(doc, sv))
        eng.flush()
        assert 0 in eng.fallback
        assert eng.text(0) == t.to_string()


class TestMapConvergence:
    """Device-path YMap LWW (ported MAP_MODS fuzz, reference
    tests/y-map.tests.js:438-481): random sets/deletes from several clients
    under random delivery must converge to the CPU core's winners."""

    @pytest.mark.parametrize("seed", range(6))
    def test_fuzz_map_ops(self, seed):
        gen = random.Random(1000 + seed)
        n_clients = gen.randint(2, 4)
        docs = [make_doc(i + 1) for i in range(n_clients)]
        upds = [collect_updates(d) for d in docs]
        keys = ["a", "b", "c", "d"]
        values = [0, 1, "s", 3.5, None, True, [1, 2], {"x": 1}]
        for _ in range(35):
            i = gen.randrange(n_clients)
            m = docs[i].get_map("map")
            if gen.random() < 0.8:
                m.set(gen.choice(keys), gen.choice(values))
            else:
                m.delete(gen.choice(keys))
            if gen.random() < 0.3:
                src, dst = gen.randrange(n_clients), gen.randrange(n_clients)
                for u in upds[src]:
                    Y.apply_update(docs[dst], u)
        all_updates = [u for us in upds for u in us]
        gen.shuffle(all_updates)
        for d in docs:
            for u in all_updates:
                Y.apply_update(d, u)
        for d in docs[1:]:
            assert d.get_map("map").to_json() == docs[0].get_map("map").to_json()
        eng = replay_into_engine(all_updates)
        assert not eng.has_pending(0)
        assert eng.map_json(0, "map") == docs[0].get_map("map").to_json()

    @pytest.mark.parametrize("seed", range(4))
    def test_fuzz_mixed_text_map_multiroot(self, seed):
        gen = random.Random(2000 + seed)
        n_clients = 3
        docs = [make_doc(i + 1) for i in range(n_clients)]
        upds = [collect_updates(d) for d in docs]
        for _ in range(30):
            i = gen.randrange(n_clients)
            d = docs[i]
            op = gen.random()
            if op < 0.4:
                t = d.get_text(gen.choice(["text", "notes"]))
                ln = len(t.to_string())
                if gen.random() < 0.7 or ln == 0:
                    t.insert(gen.randint(0, ln), gen.choice(["x", "yy", "zz "]))
                else:
                    pos = gen.randrange(ln)
                    t.delete(pos, min(gen.randint(1, 2), ln - pos))
            elif op < 0.8:
                d.get_map("map").set(gen.choice("abc"), gen.randrange(100))
            else:
                d.get_map("map").delete(gen.choice("abc"))
            if gen.random() < 0.25:
                src, dst = gen.randrange(n_clients), gen.randrange(n_clients)
                for u in upds[src]:
                    Y.apply_update(docs[dst], u)
        all_updates = [u for us in upds for u in us]
        gen.shuffle(all_updates)
        for d in docs:
            for u in all_updates:
                Y.apply_update(d, u)
        eng = replay_into_engine(all_updates)
        ref = docs[0]
        for name in ("text", "notes"):
            assert eng.text(0, name) == ref.get_text(name).to_string()
            assert_engine_matches(eng, ref, name=name)
        assert eng.map_json(0, "map") == ref.get_map("map").to_json()


class TestChainStitching:
    """Cross-group chain stitching (StepPlan.assign_levels): sequential
    typing must flatten to O(1) levels, and broken chains must still
    converge through the deferred fallback's original-gap inputs."""

    def test_sequential_typing_one_level(self):
        # alternating clients typing at their own cursors, fully synced:
        # every run's origin is a prior run's tail -> everything stitches
        a, b = make_doc(1), make_doc(2)
        for i in range(30):
            d, o = (a, b) if i % 2 == 0 else (b, a)
            t = d.get_text("text")
            t.insert(len(t.to_string()), f"w{i} ")
            Y.apply_update(o, Y.encode_state_as_update(d, Y.encode_state_vector(o)))
        from yjs_tpu.ops.columns import DocMirror

        m = DocMirror("text")
        m.ingest(Y.encode_state_as_update(a))
        plan = m.prepare_step()
        assert plan.n_levels == 1
        # stitched entries carry their true gap in the fb fields
        stitched = [e for e in plan.sched8 if (e[3], e[2]) != (e[6], e[7])]
        assert stitched
        eng = replay_into_engine([Y.encode_state_as_update(a)])
        assert_engine_matches(eng, a)

    def test_concurrent_insert_breaks_chain_but_converges(self):
        # two clients insert concurrently at the same position mid-chain:
        # the stitch's fast check fails on one side and the deferred
        # fallback must use the ORIGINAL gap (fb fields), not the head's
        a, b = make_doc(1), make_doc(2)
        a.get_text("text").insert(0, "base ")
        Y.apply_update(b, Y.encode_state_as_update(a))
        # concurrent: both extend + insert at position 2
        a.get_text("text").insert(5, "AA ")
        a.get_text("text").insert(8, "A2 ")
        b.get_text("text").insert(5, "BB ")
        b.get_text("text").insert(2, "X")
        ua, ub = Y.encode_state_as_update(a), Y.encode_state_as_update(b)
        for d, u in ((a, ub), (b, ua)):
            Y.apply_update(d, u)
        assert a.get_text("text").to_string() == b.get_text("text").to_string()
        eng = replay_into_engine([ua, ub])
        assert_engine_matches(eng, a)


class TestBlockwiseDispatch:
    """Level-axis tiling of long schedules (the long-context analogue,
    SURVEY.md §5): forcing one-level blocks must integrate identically to
    the single-dispatch path."""

    def test_forced_single_level_blocks_converge(self, monkeypatch):
        monkeypatch.setenv("YTPU_BLOCK_LEVELS", "1")
        gen = random.Random(99)
        docs = [make_doc(i + 1) for i in range(3)]
        for _ in range(60):
            d = docs[gen.randrange(3)]
            t = d.get_text("text")
            ln = len(t.to_string())
            if gen.random() < 0.7 or ln == 0:
                t.insert(gen.randint(0, ln), gen.choice(["x", "yy", "z "]))
            else:
                pos = gen.randrange(ln)
                t.delete(pos, min(gen.randint(1, 2), ln - pos))
        updates = [Y.encode_state_as_update(d) for d in docs]
        for d in docs:
            for u in updates:
                Y.apply_update(d, u)
        eng = replay_into_engine([Y.encode_state_as_update(docs[0])])
        assert_engine_matches(eng, docs[0])


class TestCompaction:
    """Run-merge + GC keep the device table bounded (VERDICT item 3; the
    engine-side analogue of reference Transaction.js:165-238,299-332)."""

    def _long_append_trace(self, eng, doc, n_flushes, per_flush=20):
        t = doc.get_text("text")
        sv = None
        for _ in range(n_flushes):
            for _ in range(per_flush):
                t.insert(len(t.to_string()), "w ")
            u = Y.encode_state_as_update(doc, sv)
            sv = Y.encode_state_vector(doc)
            eng.queue_update(0, u)
            eng.flush()

    def test_append_trace_rows_bounded(self):
        doc = make_doc(41)
        eng = BatchEngine(1, compact_min_rows=64)
        self._long_append_trace(eng, doc, 80)  # 1600 inserts, 80 flushes
        m = eng.mirrors[0]
        # contiguous same-client typing collapses to a handful of runs
        assert m.n_rows < 100, m.n_rows
        assert eng.last_compaction is not None
        assert_engine_matches(eng, doc)

    def test_delete_heavy_trace_with_gc(self):
        doc = make_doc(42)
        eng = BatchEngine(1, gc=True, compact_min_rows=64)
        t = doc.get_text("text")
        sv = None
        for step in range(40):
            for _ in range(15):
                t.insert(len(t.to_string()), "xy")
            t.delete(0, len(t.to_string()) - 4)  # tombstone almost everything
            u = Y.encode_state_as_update(doc, sv)
            sv = Y.encode_state_vector(doc)
            eng.queue_update(0, u)
            eng.flush()
        m = eng.mirrors[0]
        assert m.n_rows < 120, m.n_rows
        # gc dropped tombstone payloads: deleted rows became ContentDeleted
        # (wire ref 1; backend-neutral — the native mirror realizes lazily)
        n_tombstone = sum(1 for ref in m.row_content_ref if ref == 1)
        assert n_tombstone > 0
        assert eng.text(0) == t.to_string()

    def test_convergence_after_compaction(self):
        """Edits arriving after a compaction must still integrate and sync
        correctly (origins point inside merged runs -> re-split)."""
        doc = make_doc(43)
        eng = BatchEngine(1, compact_min_rows=64)
        self._long_append_trace(eng, doc, 30)
        # a second client edits concurrently against the synced state
        remote = make_doc(900)
        Y.apply_update(remote, Y.encode_state_as_update(doc))
        remote.get_text("text").insert(5, "[mid]")
        remote.get_text("text").delete(20, 6)
        u = Y.encode_state_as_update(remote, Y.encode_state_vector(doc))
        Y.apply_update(doc, u)
        eng.queue_update(0, u)
        eng.flush()
        assert_engine_matches(eng, doc)
        # and the mirror's wire export round-trips into a fresh CPU doc
        fresh = Y.Doc(gc=False)
        Y.apply_update(fresh, eng.encode_state_as_update(0))
        assert fresh.get_text("text").to_string() == doc.get_text("text").to_string()


class TestCompactionScale:
    def test_batch_compaction_no_readback(self):
        """Compacting a whole batch of fragmented docs converges and
        shrinks rows — decided purely from mirror state (the device
        gather that bounded r3's 100k-doc scaling is gone; this test
        drives the rebuild_compacted_self path for every doc at once)."""
        import yjs_tpu as Y

        n_docs = 256
        eng = BatchEngine(n_docs, compact_min_rows=8)
        docs = [Y.Doc(gc=False) for _ in range(n_docs)]
        svs = [None] * n_docs
        # several rounds of tiny appends -> heavily fragmented run tables
        for rnd in range(10):
            for i, d in enumerate(docs):
                t = d.get_text("text")
                t.insert(len(t.to_string()), f"r{rnd}d{i % 7},")
                u = Y.encode_state_as_update(d, svs[i])
                svs[i] = Y.encode_state_vector(d)
                eng.queue_update(i, u)
            eng.flush()
        assert eng.last_compaction, "batch compaction should have fired"
        compacted_docs = {c["doc"] for c in eng.last_compaction}
        assert len(compacted_docs) > n_docs // 2
        assert all(
            c["rows_after"] <= c["rows_before"] for c in eng.last_compaction
        )
        for i in (0, 7, 100, n_docs - 1):
            assert eng.text(i) == docs[i].get_text("text").to_string()
        # post-compaction traffic still integrates correctly
        for i, d in enumerate(docs):
            t = d.get_text("text")
            t.insert(0, "HEAD:")
            u = Y.encode_state_as_update(d, svs[i])
            svs[i] = Y.encode_state_vector(d)
            eng.queue_update(i, u)
        eng.flush()
        for i in (0, 55, n_docs - 1):
            assert eng.text(i) == docs[i].get_text("text").to_string()


class TestChunkedFlushStress:
    """Adversarial coverage of the chunked batched flush (r4): capacity
    growth BETWEEN chunks mid-flush, duplicated/out-of-order delivery,
    and causal gaps parked/resumed across chunk boundaries."""

    def test_uneven_growth_across_chunks(self, monkeypatch):
        from yjs_tpu.ops.native_mirror import native_plan_available

        if not native_plan_available():
            pytest.skip("chunked batched flush requires the native planner")
        monkeypatch.setenv("YTPU_FLUSH_CHUNK", "8")
        rng = random.Random(42)
        n = 48
        eng = BatchEngine(n, compact_min_rows=16)
        docs = [make_doc(100 + i) for i in range(n)]
        for rnd in range(5):
            batches = []
            for i, d in enumerate(docs):
                t = d.get_text("text")
                size = rng.choice([1, 3, 200])  # uneven chunk-local caps
                pos = rng.randint(0, len(t.to_string()))
                t.insert(pos, "x" * size + f"[{rnd}.{i}]")
                if rng.random() < 0.4 and len(t.to_string()) > 10:
                    t.delete(rng.randint(0, 5), 5)
                batches.append(Y.encode_state_as_update(d))
            order = list(range(n))
            rng.shuffle(order)
            for i in order:
                eng.queue_update(i, batches[i])
                if rng.random() < 0.2:
                    eng.queue_update(i, batches[i])  # duplicate delivery
            eng.flush()
            assert not eng.fallback, eng.demotions  # fast path every round
        for i in range(n):
            assert_engine_matches(eng, docs[i], i)

    def test_causal_gaps_park_and_resume(self, monkeypatch):
        from yjs_tpu.ops.native_mirror import native_plan_available

        if not native_plan_available():
            pytest.skip("chunked batched flush requires the native planner")
        monkeypatch.setenv("YTPU_FLUSH_CHUNK", "4")
        rng = random.Random(7)
        n = 24
        eng = BatchEngine(n)
        docs = [make_doc(100 + i) for i in range(n)]
        peers = [make_doc(500 + i) for i in range(n)]
        svs = [None] * n
        held = [[] for _ in range(n)]
        for rnd in range(8):
            for i in range(n):
                d = docs[i]
                t = d.get_text("text")
                t.insert(rng.randint(0, len(t.to_string())), f"a{rnd}")
                u = Y.encode_state_as_update(d, svs[i])
                svs[i] = Y.encode_state_vector(d)
                if rng.random() < 0.4:
                    held[i].append(u)  # causal gap until released below
                else:
                    eng.queue_update(i, u)
                    for h in reversed(held[i]):
                        eng.queue_update(i, h)
                    held[i].clear()
                if rng.random() < 0.3:
                    p = peers[i]
                    Y.apply_update(p, Y.encode_state_as_update(d))
                    p.get_text("text").insert(0, f"P{rnd}.")
                    pu = Y.encode_state_as_update(
                        p, Y.encode_state_vector(d)
                    )
                    Y.apply_update(d, pu)
                    svs[i] = Y.encode_state_vector(d)
                    eng.queue_update(i, pu)
            eng.flush()
            assert not eng.fallback, eng.demotions
        for i in range(n):
            for h in held[i]:
                eng.queue_update(i, h)
        eng.flush()
        assert not eng.fallback, eng.demotions
        for i in range(n):
            assert_engine_matches(eng, docs[i], i)
        assert eng.last_flush_metrics["n_pending_docs"] == 0


class TestLaneBucketing:
    """_bucket_lanes (VERDICT r4 item 9): mantissa-quantized lane widths
    cap padding waste at 12.5% while keeping compiled shapes bounded."""

    def test_properties(self):
        from yjs_tpu.ops.engine import _bucket_lanes

        assert _bucket_lanes(0) == 64 and _bucket_lanes(64) == 64
        prev = 0
        seen_per_octave: dict[int, set] = {}
        for n in range(1, 200000, 7):
            b = _bucket_lanes(n)
            assert b >= n and b >= 64
            assert b >= prev or n <= 64  # monotone
            prev = b
            if n > 64:
                assert b / n <= 1.125 + 1e-9, (n, b)
            assert _bucket_lanes(b) == b  # idempotent (stable shapes)
            seen_per_octave.setdefault(b.bit_length(), set()).add(b)
        # bounded distinct shapes: at most 2**bits per power-of-two octave
        for octave, vals in seen_per_octave.items():
            assert len(vals) <= 8 + 1, (octave, sorted(vals))

    def test_flush_occupancy_and_shape_stability(self, rng):
        """Multi-doc flush occupancy >= 0.92, and flushes whose lane
        demand differs by <12.5% reuse the SAME padded widths (= the
        dispatch hits the jit cache by construction).

        Specific to the NATIVE bulk-apply lane packing: the levels/seq
        cross-check kernels report schedule (not lane) occupancy, and
        the Python-planner fallback takes the non-batched pack path."""
        import os as _os

        import pytest as _pytest

        if _os.environ.get("YTPU_KERNEL", "apply") != "apply" or _os.environ.get(
            "YTPU_NO_NATIVE_PLAN"
        ):
            _pytest.skip("bulk-apply native lane packing only")
        import yjs_tpu as Y
        from yjs_tpu.ops import BatchEngine

        def mk_updates(n_docs, ops, seed0):
            # two-client conflict texture: realistic fragmentation so the
            # lane demand is real work, not floor padding
            outs = []
            for k in range(n_docs):
                gen = random.Random(seed0 + k)
                a = Y.Doc(gc=False)
                a.client_id = 1000 + 2 * k
                b = Y.Doc(gc=False)
                b.client_id = 1001 + 2 * k

                def sync(a=a, b=b):
                    ua = Y.encode_state_as_update(a, Y.encode_state_vector(b))
                    ub = Y.encode_state_as_update(b, Y.encode_state_vector(a))
                    Y.apply_update(b, ua)
                    Y.apply_update(a, ub)

                for i in range(ops + gen.randint(0, ops // 20)):
                    d = a if gen.random() < 0.5 else b
                    t = d.get_text("text")
                    ln = len(t.to_string())
                    if gen.random() < 0.75 or ln == 0:
                        t.insert(gen.randint(0, ln), gen.choice(["ab", "c "]))
                    else:
                        pos = gen.randrange(ln)
                        t.delete(pos, min(gen.randint(1, 3), ln - pos))
                    if gen.random() < 0.2:
                        sync()
                sync()
                outs.append(Y.encode_state_as_update(a))
            return outs

        eng = BatchEngine(32)
        for i, u in enumerate(mk_updates(32, 120, 5000)):
            eng.queue_update(i, u)
        eng.flush()
        occ = eng.last_flush_metrics["schedule_occupancy"]
        # >=0.90 at this 32-doc scale (the fixed 64/64/8/64 minimum-width
        # floors are ~5% of demand here); the 1024-doc distinct fixture
        # measures 0.96+ (BASELINE.md r5), vs 0.844 with pure powers of two
        assert occ >= 0.90, occ
        # second engine, ~5% different demand -> identical lane widths
        import yjs_tpu.ops.engine as engine_mod

        widths = []
        orig = engine_mod.pack_apply_lanes

        def spy(work, doc_ids, b_loc, n_shards, w, *a, **k):
            widths.append(w)
            return orig(work, doc_ids, b_loc, n_shards, w, *a, **k)

        engine_mod.pack_apply_lanes = spy
        try:
            for run, seed0 in enumerate(range(6000, 6600, 100)):
                e1 = BatchEngine(32)
                ops = 120 + (run % 3) * 4  # ±~5% demand wobble per run
                for i, u in enumerate(mk_updates(32, ops, seed0)):
                    e1.queue_update(i, u)
                e1.flush()
        finally:
            engine_mod.pack_apply_lanes = orig
        assert len(widths) >= 6
        # bucketing must COLLAPSE the wobble onto few padded shapes (each
        # repeat = a jit-cache hit); exact widths would give one distinct
        # tuple per run
        assert len(set(widths)) <= len(widths) // 2, widths
