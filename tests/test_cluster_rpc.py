"""Cluster RPC wire + transport contracts (ISSUE 14).

The envelope-121 RPC kinds (K_RPC_REQ/RSP/EVT), the correlation-matched
client, BUSY propagation, trace carry across the socket, and the
:class:`SocketTransport` drain-then-join shutdown pin (satellite 1)."""

import socket
import threading
import time

import pytest

from yjs_tpu.cluster.rpc import (
    K_RPC_EVT,
    K_RPC_REQ,
    K_RPC_RSP,
    STATUS_BUSY,
    STATUS_OK,
    FrameConn,
    RpcBusy,
    RpcClient,
    RpcError,
    RpcServer,
    SocketTransport,
    decode_frame,
    encode_event,
    encode_request,
    encode_response,
)
from yjs_tpu.obs import dist as obs_dist

pytestmark = pytest.mark.cluster


# -- wire ---------------------------------------------------------------------


def test_request_roundtrip_with_trace():
    ctx = obs_dist.mint_for_update(b"seed")
    frame = encode_request(7, "sync", {"guid": "room-a"}, ctx)
    kind, corr, method, payload, got = decode_frame(frame)
    assert kind == K_RPC_REQ
    assert (corr, method) == (7, "sync")
    assert payload == {"guid": "room-a"}
    assert got is not None and got.trace_id == ctx.trace_id


def test_response_and_event_roundtrip():
    rsp = decode_frame(encode_response(9, STATUS_OK, {"ok": 1}))
    assert rsp == (K_RPC_RSP, 9, STATUS_OK, {"ok": 1})
    evt = decode_frame(encode_event("update", {"guid": "g"}))
    assert evt == (K_RPC_EVT, "update", {"guid": "g"})


def test_unknown_kind_and_garbage_skip():
    # a future kind inside the 121 envelope decodes to None (skip), as
    # does non-envelope garbage — the tolerance contract
    assert decode_frame(bytes([121, 99, 1, 2, 3])) is None
    assert decode_frame(b"\x00\xffgarbage") is None
    assert decode_frame(b"") is None


# -- client/server ------------------------------------------------------------


class _Handler:
    def __init__(self):
        self.seen = []

    def handle_rpc_request(self, method, payload, ctx):
        self.seen.append((method, payload, ctx))
        if method == "busy":
            raise RpcBusy(5)
        if method == "boom":
            raise ValueError("deliberate")
        return {"echo": payload, "method": method}


def test_rpc_call_busy_error_and_trace_carry():
    handler = _Handler()
    server = RpcServer(handler, host="127.0.0.1", port=0)
    client = RpcClient("127.0.0.1", server.port, timeout=10.0)
    try:
        body = client.call("hello", {"x": 1})
        assert body == {"echo": {"x": 1}, "method": "hello"}

        # the current TraceContext rides the request: the remote seam
        # adopts the SAME trace id instead of re-minting
        ctx = obs_dist.mint_for_update(b"traced-update")
        with obs_dist.use_context(ctx):
            client.call("traced", {})
        got = handler.seen[-1][2]
        assert got is not None and got.trace_id == ctx.trace_id

        try:
            client.call("busy", {})
            raise AssertionError("expected RpcBusy")
        except RpcBusy as e:
            assert e.retry_after == 5

        try:
            client.call("boom", {})
            raise AssertionError("expected RpcError")
        except RpcError:
            pass
        # the connection survives handler errors
        assert client.call("after", {})["method"] == "after"
    finally:
        client.close()
        server.close()


def test_rpc_event_broadcast():
    handler = _Handler()
    server = RpcServer(handler, host="127.0.0.1", port=0)
    client = RpcClient("127.0.0.1", server.port, timeout=10.0)
    got = []
    ev = threading.Event()

    def on_event(topic, payload):
        got.append((topic, payload))
        ev.set()

    client.on_event = on_event
    try:
        client.call("hello", {})  # ensures the conn is registered
        assert server.broadcast("update", {"guid": "g"}) >= 1
        assert ev.wait(5.0)
        assert got[0] == ("update", {"guid": "g"})
    finally:
        client.close()
        server.close()


def test_dead_server_fails_pending_with_closed():
    handler = _Handler()
    server = RpcServer(handler, host="127.0.0.1", port=0)
    client = RpcClient("127.0.0.1", server.port, timeout=10.0)
    server.close()
    deadline = time.time() + 5
    while client.alive and time.time() < deadline:
        time.sleep(0.02)
    try:
        client.call("hello", {})
        raise AssertionError("expected a closed-connection error")
    except Exception as e:
        assert type(e).__name__ in ("RpcClosed", "RpcError")
    finally:
        client.close()


# -- SocketTransport shutdown pin (satellite 1) -------------------------------


def test_socket_transport_drains_outbox_before_close():
    """Every frame accepted by ``send()`` before ``close()`` reaches the
    wire, and both transport threads join — the satellite-1 contract."""
    a, b = socket.socketpair()
    tx = SocketTransport(a, name="tx")
    got = []
    done = threading.Event()

    def reader():
        conn = FrameConn(b)
        while True:
            frame = conn.recv()
            if frame is None:
                break
            got.append(frame)
        done.set()

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    tx.start()
    frames = [bytes([i % 251]) * (i + 1) for i in range(200)]
    for f in frames:
        assert tx.send(f)
    tx.close()  # must drain all 200 queued frames first
    assert tx.join(timeout=5.0), "transport threads did not exit"
    assert done.wait(5.0), "reader never saw EOF"
    assert got == frames, (
        f"dropped {len(frames) - len(got)} of {len(frames)} frames on close"
    )
    t.join(timeout=5.0)
    b.close()


def test_socket_transport_close_idempotent_and_queued_gauge():
    a, b = socket.socketpair()
    tr = SocketTransport(a, name="idem")
    tr.start()
    assert tr.queued == 0
    tr.send(b"x")
    tr.close()
    tr.close()  # second close is a no-op
    assert tr.join(timeout=5.0)
    assert not tr.send(b"late"), "send after close must be refused"
    b.close()


def test_socket_transport_peer_eof_fires_on_close_once():
    a, b = socket.socketpair()
    tr = SocketTransport(a, name="eof")
    closes = []
    tr.on_close = lambda: closes.append(1)
    tr.start()
    b.close()  # peer vanishes
    deadline = time.time() + 5
    while not closes and time.time() < deadline:
        time.sleep(0.02)
    assert closes == [1]
    assert tr.join(timeout=5.0)
