"""YMap behavior + randomized convergence (scenarios modeled on reference
tests/y-map.tests.js)."""

import random

import pytest

import yjs_tpu as Y
from helpers import apply_random_tests, compare, init


def test_basic_map_ops():
    doc = Y.Doc()
    m = doc.get_map("map")
    m.set("a", 1)
    m.set("b", "two")
    m.set("c", {"nested": True})
    assert m.get("a") == 1
    assert m.has("b")
    assert not m.has("zz")
    assert m.size == 3
    m.delete("b")
    assert m.size == 2
    assert m.get("b") is None
    assert sorted(m.keys()) == ["a", "c"]
    assert m.to_json() == {"a": 1, "c": {"nested": True}}


def test_map_prelim():
    m = Y.YMap({"x": 10})
    m.set("y", 20)
    doc = Y.Doc()
    doc.get_array("a").insert(0, [m])
    assert m.get("x") == 10
    assert m.to_json() == {"x": 10, "y": 20}


def test_map_last_writer_wins(rng):
    result = init(rng, users=3)
    result["map0"].set("key", "c0")
    result["map1"].set("key", "c1")
    result["map2"].set("key", "c2")
    compare(result["users"])
    # highest client id wins concurrent map sets
    assert result["users"] == result["users"]


def test_get_and_set_and_delete(rng):
    result = init(rng, users=3)
    map0 = result["map0"]
    map0.set("stuff", "c0")
    map0.delete("stuff")
    result["testConnector"].flush_all_messages()
    for u in result["users"]:
        assert u.get_map("map").get("stuff") is None
    compare(result["users"])


def test_concurrent_set_converges(rng):
    result = init(rng, users=3)
    result["testConnector"].flush_all_messages()
    result["map0"].set("k", "v0")
    result["map1"].set("k", "v1")
    compare(result["users"])


def test_map_events():
    doc = Y.Doc()
    m = doc.get_map("map")
    events = []
    m.observe(lambda e, txn: events.append(dict(e.changes["keys"])))
    m.set("a", 1)
    assert events[-1]["a"]["action"] == "add"
    m.set("a", 2)
    assert events[-1]["a"]["action"] == "update"
    assert events[-1]["a"]["oldValue"] == 1
    m.delete("a")
    assert events[-1]["a"]["action"] == "delete"
    assert events[-1]["a"]["oldValue"] == 2


def test_nested_maps():
    doc = Y.Doc()
    m = doc.get_map("map")
    inner = Y.YMap()
    m.set("inner", inner)
    inner.set("deep", Y.YArray())
    inner.get("deep").push([1])
    assert m.to_json() == {"inner": {"deep": [1]}}
    assert m.get("inner").parent is m


# -- randomized fuzz (reference y-map.tests.js:426-606) ---------------------

def _set_key(user, gen: random.Random):
    key = gen.choice(["one", "two"])
    value = "val" + str(gen.randint(0, 100))
    user.get_map("map").set(key, value)


def _set_type(user, gen: random.Random):
    key = gen.choice(["one", "two"])
    typ = gen.choice(["array", "map"])
    if typ == "array":
        nested = Y.YArray()
        user.get_map("map").set(key, nested)
        nested.insert(0, [gen.randint(0, 10) for _ in range(3)])
    else:
        nested = Y.YMap()
        user.get_map("map").set(key, nested)
        nested.set("deepkey", "deepvalue" + str(gen.randint(0, 10)))


def _delete_key(user, gen: random.Random):
    key = gen.choice(["one", "two"])
    user.get_map("map").delete(key)


MAP_MODS = [_set_key, _set_type, _delete_key]


@pytest.mark.parametrize("iterations", [6, 40, 120])
def test_repeat_random_map_ops(rng, iterations):
    apply_random_tests(rng, MAP_MODS, iterations)
