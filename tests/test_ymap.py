"""YMap behavior + randomized convergence (scenarios modeled on reference
tests/y-map.tests.js)."""

import random

import pytest

import yjs_tpu as Y
from helpers import apply_random_tests, compare, compare_ids, init
from yjs_tpu.lib0.encoding import UNDEFINED


def test_basic_map_ops():
    doc = Y.Doc()
    m = doc.get_map("map")
    m.set("a", 1)
    m.set("b", "two")
    m.set("c", {"nested": True})
    assert m.get("a") == 1
    assert m.has("b")
    assert not m.has("zz")
    assert m.size == 3
    m.delete("b")
    assert m.size == 2
    assert m.get("b") is None
    assert sorted(m.keys()) == ["a", "c"]
    assert m.to_json() == {"a": 1, "c": {"nested": True}}


def test_map_prelim():
    m = Y.YMap({"x": 10})
    m.set("y", 20)
    doc = Y.Doc()
    doc.get_array("a").insert(0, [m])
    assert m.get("x") == 10
    assert m.to_json() == {"x": 10, "y": 20}


def test_map_last_writer_wins(rng):
    result = init(rng, users=3)
    result["map0"].set("key", "c0")
    result["map1"].set("key", "c1")
    result["map2"].set("key", "c2")
    compare(result["users"])
    # highest client id wins concurrent map sets
    assert result["users"] == result["users"]


def test_get_and_set_and_delete(rng):
    result = init(rng, users=3)
    map0 = result["map0"]
    map0.set("stuff", "c0")
    map0.delete("stuff")
    result["testConnector"].flush_all_messages()
    for u in result["users"]:
        assert u.get_map("map").get("stuff") is None
    compare(result["users"])


def test_concurrent_set_converges(rng):
    result = init(rng, users=3)
    result["testConnector"].flush_all_messages()
    result["map0"].set("k", "v0")
    result["map1"].set("k", "v1")
    compare(result["users"])


def test_map_events():
    doc = Y.Doc()
    m = doc.get_map("map")
    events = []
    m.observe(lambda e, txn: events.append(dict(e.changes["keys"])))
    m.set("a", 1)
    assert events[-1]["a"]["action"] == "add"
    m.set("a", 2)
    assert events[-1]["a"]["action"] == "update"
    assert events[-1]["a"]["oldValue"] == 1
    m.delete("a")
    assert events[-1]["a"]["action"] == "delete"
    assert events[-1]["a"]["oldValue"] == 2


def test_nested_maps():
    doc = Y.Doc()
    m = doc.get_map("map")
    inner = Y.YMap()
    m.set("inner", inner)
    inner.set("deep", Y.YArray())
    inner.get("deep").push([1])
    assert m.to_json() == {"inner": {"deep": [1]}}
    assert m.get("inner").parent is m


# -- randomized fuzz (reference y-map.tests.js:426-606) ---------------------

def _set_key(user, gen: random.Random):
    key = gen.choice(["one", "two"])
    value = "val" + str(gen.randint(0, 100))
    user.get_map("map").set(key, value)


def _set_type(user, gen: random.Random):
    key = gen.choice(["one", "two"])
    typ = gen.choice(["array", "map"])
    if typ == "array":
        nested = Y.YArray()
        user.get_map("map").set(key, nested)
        nested.insert(0, [gen.randint(0, 10) for _ in range(3)])
    else:
        nested = Y.YMap()
        user.get_map("map").set(key, nested)
        nested.set("deepkey", "deepvalue" + str(gen.randint(0, 10)))


def _delete_key(user, gen: random.Random):
    key = gen.choice(["one", "two"])
    user.get_map("map").delete(key)


MAP_MODS = [_set_key, _set_type, _delete_key]


@pytest.mark.parametrize("iterations", [6, 40, 120])
def test_repeat_random_map_ops(rng, iterations):
    apply_random_tests(rng, MAP_MODS, iterations)


def test_map_having_iterable_as_constructor_param(rng):
    """(reference y-map.tests.js
    testMapHavingIterableAsConstructorParamTests)."""
    result = init(rng, users=1)
    map0 = result["map0"]
    m1 = Y.YMap({"number": 1, "string": "hello"})
    map0.set("m1", m1)
    assert m1.get("number") == 1
    assert m1.get("string") == "hello"
    m2 = Y.YMap([("object", {"x": 1}), ("boolean", True)])
    map0.set("m2", m2)
    assert m2.get("object")["x"] == 1
    assert m2.get("boolean") is True
    m3 = Y.YMap(
        list(dict(m1.entries()).items()) + list(dict(m2.entries()).items())
    )
    map0.set("m3", m3)
    assert m3.get("number") == 1
    assert m3.get("string") == "hello"
    assert m3.get("object")["x"] == 1
    assert m3.get("boolean") is True


def test_ymap_sets_ymap(rng):
    """(reference y-map.tests.js testYmapSetsYmap)."""
    result = init(rng, users=2)
    map0 = result["map0"]
    m = map0.set("Map", Y.YMap())
    assert map0.get("Map") is m
    m.set("one", 1)
    assert m.get("one") == 1
    compare(result["users"])


def test_ymap_sets_yarray(rng):
    """(reference y-map.tests.js testYmapSetsYarray)."""
    result = init(rng, users=2)
    map0 = result["map0"]
    arr = map0.set("Array", Y.YArray())
    assert arr is map0.get("Array")
    arr.insert(0, [1, 2, 3])
    assert map0.to_json() == {"Array": [1, 2, 3]}
    compare(result["users"])


def test_size_and_delete_of_map_property(rng):
    """(reference y-map.tests.js testSizeAndDeleteOfMapProperty)."""
    result = init(rng, users=1)
    map0 = result["map0"]
    map0.set("stuff", "c0")
    map0.set("otherstuff", "c1")
    assert map0.size == 2
    map0.delete("stuff")
    assert map0.size == 1
    map0.delete("otherstuff")
    assert map0.size == 0


def test_get_set_map_property_three_conflicts(rng):
    """(reference y-map.tests.js
    testGetAndSetOfMapPropertyWithThreeConflicts)."""
    result = init(rng, users=3)
    map0, map1, map2 = result["map0"], result["map1"], result["map2"]
    map0.set("stuff", "c0")
    map1.set("stuff", "c1")
    map1.set("stuff", "c2")
    map2.set("stuff", "c3")
    result["testConnector"].flush_all_messages()
    for user in result["users"]:
        assert user.get_map("map").get("stuff") == "c3"
    compare(result["users"])


def test_get_set_delete_map_property_three_conflicts(rng):
    """(reference y-map.tests.js
    testGetAndSetAndDeleteOfMapPropertyWithThreeConflicts)."""
    result = init(rng, users=4)
    map0, map1, map2, map3 = (
        result["map0"], result["map1"], result["map2"], result["map3"]
    )
    map0.set("stuff", "c0")
    map1.set("stuff", "c1")
    map1.set("stuff", "c2")
    map2.set("stuff", "c3")
    result["testConnector"].flush_all_messages()
    map0.set("stuff", "deleteme")
    map1.set("stuff", "c1")
    map2.set("stuff", "c2")
    map3.set("stuff", "c3")
    map3.delete("stuff")
    result["testConnector"].flush_all_messages()
    for user in result["users"]:
        assert user.get_map("map").get("stuff") is None
    compare(result["users"])


def test_observe_deep_properties(rng):
    """(reference y-map.tests.js testObserveDeepProperties)."""
    result = init(rng, users=4)
    map1, map2, map3 = result["map1"], result["map2"], result["map3"]
    _map1 = map1.set("map", Y.YMap())
    calls = [0]
    seen = {}

    def deep(events, _tr=None):
        for event in events:
            calls[0] += 1
            assert "deepmap" in event.keys_changed
            assert len(event.path) == 1 and event.path[0] == "map"
            seen["id"] = event.target.get("deepmap")._item.id

    map1.observe_deep(deep)
    result["testConnector"].flush_all_messages()
    _map3 = map3.get("map")
    _map3.set("deepmap", Y.YMap())
    result["testConnector"].flush_all_messages()
    _map2 = map2.get("map")
    _map2.set("deepmap", Y.YMap())
    result["testConnector"].flush_all_messages()
    dmap1 = _map1.get("deepmap")
    dmap2 = _map2.get("deepmap")
    dmap3 = _map3.get("deepmap")
    assert calls[0] > 0
    assert compare_ids(dmap1._item.id, dmap2._item.id)
    assert compare_ids(dmap1._item.id, dmap3._item.id)
    assert compare_ids(dmap1._item.id, seen["id"])
    compare(result["users"])


def test_throws_add_update_delete_events(rng):
    """(reference y-map.tests.js testThrowsAddAndUpdateAndDeleteEvents)."""
    result = init(rng, users=2)
    map0 = result["map0"]
    box = {}
    map0.observe(lambda e, _tr=None: box.__setitem__("e", e))
    map0.set("stuff", 4)
    assert box["e"].target is map0 and box["e"].keys_changed == {"stuff"}
    map0.set("stuff", Y.YArray())  # update, oldValue in contents
    assert box["e"].target is map0 and box["e"].keys_changed == {"stuff"}
    map0.set("stuff", 5)  # update, oldValue in opContents
    assert box["e"].target is map0 and box["e"].keys_changed == {"stuff"}
    map0.delete("stuff")  # delete
    assert box["e"].target is map0 and box["e"].keys_changed == {"stuff"}
    compare(result["users"])


def test_map_change_event_payload(rng):
    """keys action/oldValue across transactions (reference
    y-map.tests.js testChangeEvent)."""
    from yjs_tpu.lib0.encoding import UNDEFINED

    result = init(rng, users=2)
    map0 = result["map0"]
    users = result["users"]
    box = {}
    map0.observe(lambda e, _tr=None: box.__setitem__("ch", e.changes))
    map0.set("a", 1)
    kc = box["ch"]["keys"]["a"]
    assert kc["action"] == "add" and kc["oldValue"] is UNDEFINED
    map0.set("a", 2)
    kc = box["ch"]["keys"]["a"]
    assert kc["action"] == "update" and kc["oldValue"] == 1
    users[0].transact(lambda _t: (map0.set("a", 3), map0.set("a", 4)))
    kc = box["ch"]["keys"]["a"]
    assert kc["action"] == "update" and kc["oldValue"] == 2
    users[0].transact(lambda _t: (map0.set("b", 1), map0.set("b", 2)))
    kc = box["ch"]["keys"]["b"]
    assert kc["action"] == "add" and kc["oldValue"] is UNDEFINED
    users[0].transact(lambda _t: (map0.set("c", 1), map0.delete("c")))
    assert len(box["ch"]["keys"]) == 0
    users[0].transact(lambda _t: (map0.set("d", 1), map0.set("d", 2)))
    kc = box["ch"]["keys"]["d"]
    assert kc["action"] == "add" and kc["oldValue"] is UNDEFINED
    compare(result["users"])


def test_ymap_event_exceptions_complete_transaction():
    """A throwing observer must not corrupt the transaction (reference
    y-map.tests.js testYmapEventExceptionsShouldCompleteTransaction)."""
    doc = Y.Doc()
    m = doc.get_map("map")
    called = {"update": False, "obs": False, "deep": False}
    doc.on("update", lambda *a: called.__setitem__("update", True))

    def throwing(e, _tr=None):
        called.__setitem__("obs", True)
        raise RuntimeError("Failure")

    def throwing_deep(es, _tr=None):
        called.__setitem__("deep", True)
        raise RuntimeError("Failure")

    m.observe(throwing)
    m.observe_deep(throwing_deep)
    with pytest.raises(RuntimeError):
        m.set("y", "2")
    assert all(called.values())
    for k in called:
        called[k] = False
    with pytest.raises(RuntimeError):
        m.set("z", "3")
    assert all(called.values())
    assert m.get("z") == "3"
