"""Device-authoritative cold planning suite (ISSUE 15).

The correctness bar: under every seeded corpus shape (prepend-storm,
interleaved, 4-client conflict storm, B4-texture trace head) the
device-planned integration must equal the sequential YATA walk
**struct-for-struct** — identical sched/link/head/delete plans — and
the engine must converge byte-identically `YTPU_PLAN_SEGMENT=device`
vs `off` on both native and pure-Python mirrors, including across
demotion→promotion and kill-primary failover.  Plus the ISSUE 15
satellite pins: snapshot reuse on monotone prepend runs (the
`plan_snapshot` host op must stay cold) and the fast-set/residue
metrics accounting.
"""

import random

import pytest

import yjs_tpu as Y
from yjs_tpu.obs import FLUSH_METRICS_SCHEMA
from yjs_tpu.obs.prof import kernel_profiler
from yjs_tpu.ops import BatchEngine
from yjs_tpu.ops import plan_cache
from yjs_tpu.ops import segment_planner
from yjs_tpu.ops.columns import DocMirror
from yjs_tpu.updates import (
    apply_update,
    encode_state_as_update,
    encode_state_vector,
)

pytestmark = pytest.mark.planner

SHAPES = ("prepend_storm", "interleaved", "storm", "b4_head")


@pytest.fixture(autouse=True)
def _fresh_cache():
    plan_cache.reset_cache()
    yield
    plan_cache.reset_cache()


def corpus(shape: str, seed: int, n_ops: int = 90) -> list[bytes]:
    """Seeded incremental updates from concurrent editors, one list per
    (shape, seed).  ``b4_head`` reproduces the head of the B4 fixture's
    editing texture (scripts/gen_b4_fixture.py): single-char typing and
    backspace runs at a mostly-sequential cursor, periodic syncs."""
    gen = random.Random(seed)
    n_clients = 4 if shape == "storm" else 2 if shape == "b4_head" else 3
    docs = []
    for k in range(n_clients):
        d = Y.Doc(gc=False)
        d.client_id = 300 + k
        docs.append(d)
    out: list[bytes] = []
    cursors = {id(d): 0 for d in docs}
    j = 0
    while len(out) < n_ops:
        if shape == "b4_head" and gen.random() < 0.1:
            j = gen.randrange(n_clients)
        elif shape != "b4_head":
            j = gen.randrange(n_clients)
        d = docs[j]
        t = d.get_text("text")
        sv = encode_state_vector(d)
        if shape == "prepend_storm":
            t.insert(0, gen.choice("abcdef") * gen.randint(1, 2))
        elif shape == "storm":
            t.insert(min(len(t), gen.randrange(3)), gen.choice("xyz "))
        elif shape == "b4_head":
            cur = min(cursors[id(d)], len(t))
            if gen.random() < 0.05:
                cur = gen.randint(0, len(t))
            if len(t) and cur and gen.random() < 0.3:
                t.delete(cur - 1, 1)  # backspace
                cur -= 1
            else:
                t.insert(cur, gen.choice("etaoin shr"))
                cur += 1
            cursors[id(d)] = cur
        elif len(t) and gen.random() < 0.25:
            t.delete(gen.randrange(len(t)), 1)
        else:
            t.insert(gen.randrange(len(t) + 1), gen.choice("abcdef "))
        out.append(encode_state_as_update(d, sv))
        sync_p = 0.05 if shape == "storm" else 0.3
        if gen.random() < sync_p:
            k = gen.randrange(n_clients)
            if k != j:
                apply_update(docs[k], encode_state_as_update(d))
    return out


# -- oracle: device-planned ranks == sequential YATA walk ---------------------


def plan_tuple(p):
    return (
        p.sched, p.splits, p.link_rows, p.link_vals,
        p.head_segs, p.head_vals, sorted(p.delete_rows),
    )


@pytest.mark.parametrize("shape", SHAPES)
def test_device_ranks_match_sequential_walk(shape, monkeypatch):
    """Struct-for-struct: every flush's sched entries, link writes, head
    writes and delete rows must be identical between the authoritative
    device plan and the pure sequential walk."""
    updates = corpus(shape, seed=15)

    def drive(mode):
        monkeypatch.setenv("YTPU_PLAN_SEGMENT", mode)
        m = DocMirror("text")
        plans = []
        for j, u in enumerate(updates):
            m.ingest(u, False)
            if (j + 1) % 6 == 0 or j == len(updates) - 1:
                plans.append(plan_tuple(m.prepare_step()))
        return plans, m.encode_state_as_update(), m.plan_frontier

    ref = drive("off")
    for mode in ("device", "np", "jax"):
        assert drive(mode) == ref, f"mode={mode} diverged from walk"


@pytest.mark.parametrize("shape", SHAPES)
def test_native_plans_match_walk(shape, monkeypatch):
    """The native core's chain-run anchor adoption must not change one
    plan array either."""
    from yjs_tpu.ops.native_mirror import NativeMirror, native_plan_available

    if not native_plan_available():
        pytest.skip("native plancore unavailable")
    updates = corpus(shape, seed=23)

    def drive(mode):
        monkeypatch.setenv("YTPU_PLAN_SEGMENT", mode)
        m = NativeMirror("text")
        plans = []
        for j, u in enumerate(updates):
            m.ingest(u, False)
            if (j + 1) % 6 == 0 or j == len(updates) - 1:
                p = m.prepare_step()
                plans.append((
                    p.sched.tolist(), p.splits.tolist(),
                    p.link_rows.tolist(), p.link_vals.tolist(),
                    p.head_segs.tolist(), p.head_vals.tolist(),
                    sorted(int(r) for r in p.delete_rows),
                ))
        return plans, m.encode_state_as_update(), m.plan_frontier

    assert drive("device") == drive("off")


# -- engine-level byte identity: device vs off --------------------------------


def run_engine(updates, n_docs, mode, monkeypatch, py=False, flush_every=6):
    monkeypatch.setenv("YTPU_PLAN_SEGMENT", mode)
    if py:
        monkeypatch.setenv("YTPU_NO_NATIVE_PLAN", "1")
    eng = BatchEngine(n_docs)
    deltas = {i: [] for i in range(n_docs)}
    eng.on_update(lambda i, u: deltas[i].append(u))
    sums = {"plan_segment_fast": 0, "plan_segment_residue": 0,
            "plan_threads": 0}
    keysets = set()
    for j, u in enumerate(updates):
        for i in range(n_docs):
            eng.queue_update(i, u)
        if (j + 1) % flush_every == 0 or j == len(updates) - 1:
            eng.flush()
            m = eng.last_flush_metrics
            keysets.add(frozenset(m))
            sums["plan_segment_fast"] += m["plan_segment_fast"]
            sums["plan_segment_residue"] += m["plan_segment_residue"]
            sums["plan_threads"] = max(
                sums["plan_threads"], m["plan_threads"]
            )
    states = [eng.encode_state_as_update(i) for i in range(n_docs)]
    texts = [eng.text(i) for i in range(n_docs)]
    return states, texts, deltas, sums, keysets


@pytest.mark.parametrize("py", [False, True], ids=["native", "python"])
@pytest.mark.parametrize("shape", ["prepend_storm", "storm", "b4_head"])
def test_engine_device_vs_off_byte_identical(shape, py, monkeypatch):
    updates = corpus(shape, seed=31)
    monkeypatch.setenv("YTPU_PLAN_CACHE", "0")
    s_dev, t_dev, d_dev, sums_dev, keys_dev = run_engine(
        updates, 3, "device", monkeypatch, py=py
    )
    s_off, t_off, d_off, sums_off, keys_off = run_engine(
        updates, 3, "off", monkeypatch, py=py
    )
    assert (t_dev, s_dev, d_dev) == (t_off, s_off, d_off)
    # the off lane really is the pure walk: zero fast-set structs
    assert sums_off["plan_segment_fast"] == 0
    assert sums_off["plan_segment_residue"] == 0
    # ONE metrics schema either way
    assert keys_dev == keys_off == {frozenset(FLUSH_METRICS_SCHEMA)}


def test_device_mode_counts_fast_set(monkeypatch):
    """Typing/prepend-heavy traffic must actually exercise the fast set
    (bulk integration from device ranks), not silently fall back."""
    updates = corpus("prepend_storm", seed=47)
    monkeypatch.setenv("YTPU_PLAN_CACHE", "0")
    _s, _t, _d, sums, _k = run_engine(
        updates, 2, "device", monkeypatch, py=True
    )
    assert sums["plan_segment_fast"] > 0


# -- plan-cache interop: warm hits byte-identical, cache on vs off ------------


def test_device_plans_fold_same_frontier_as_walk(monkeypatch):
    """Cache interop is exact: a device-planned prepare folds the same
    frontier digest as the walk, so warm cache hits replay states that
    are byte-identical across planner modes."""
    updates = corpus("interleaved", seed=7)
    monkeypatch.setenv("YTPU_PLAN_CACHE", "1")
    plan_cache.reset_cache()
    s_on, t_on, d_on, _s1, _k1 = run_engine(
        updates, 2, "device", monkeypatch, py=True
    )
    plan_cache.reset_cache()
    monkeypatch.setenv("YTPU_PLAN_CACHE", "0")
    s_off, t_off, d_off, _s2, _k2 = run_engine(
        updates, 2, "device", monkeypatch, py=True
    )
    assert (t_on, s_on, d_on) == (t_off, s_off, d_off)


# -- lifecycle: demotion→promotion and failover with the planner on -----------


def test_demotion_promotion_device_vs_off(monkeypatch):
    from yjs_tpu.provider import TpuProvider
    from yjs_tpu.tiering import TierConfig

    def upd(text, cid=1, at=0):
        d = Y.Doc(gc=False)
        d.client_id = cid
        d.get_text("text").insert(at, text)
        return encode_state_as_update(d)

    def drive(mode):
        monkeypatch.setenv("YTPU_PLAN_SEGMENT", mode)
        plan_cache.reset_cache()
        p = TpuProvider(2, tier_config=TierConfig(enabled=True))
        p.receive_update("r", upd("round trip "))
        p.flush()
        assert p.demote_doc("r", "warm")
        assert p.text("r") == "round trip "  # demand promotion
        p.receive_update("r", upd("second", cid=2))
        p.flush()
        return Y.merge_updates([p.encode_state_as_update("r")]), p.text("r")

    assert drive("device") == drive("off")


def test_failover_promotion_with_planner_on(tmp_path, monkeypatch):
    """Kill-primary failover with the segment planner on (the default):
    promoted slots rebuild from journals and must converge to the
    uninterrupted reference byte-for-byte."""
    from yjs_tpu.fleet import FailoverConfig, FleetRouter
    from yjs_tpu.persistence import WalConfig

    monkeypatch.setenv("YTPU_PLAN_SEGMENT", "device")
    fleet = FleetRouter(
        3, 4, backend="cpu", wal_dir=tmp_path,
        wal_config=WalConfig(segment_bytes=256, fsync="never"),
        failover_config=FailoverConfig(
            suspect_ticks=2, confirm_ticks=1, jitter_ticks=0
        ),
    )
    rooms = {}
    for j in range(4):
        d = Y.Doc(gc=False)
        d.client_id = 100 + j
        g = f"room-{j}"
        rooms[g] = d
        for step in range(6):
            sv = encode_state_vector(d)
            d.get_text("text").insert(0, f"{j}:{step} ")
            fleet.receive_update(g, encode_state_as_update(d, sv))
    fleet.flush()
    fleet.tick()
    victim = fleet.owner_of("room-0")
    fleet.kill_shard(victim)
    for _ in range(16):
        fleet.tick()
        if victim in fleet._down:
            break
    else:
        raise AssertionError("victim never convicted")
    for g, d in rooms.items():
        ref = Y.merge_updates([encode_state_as_update(d)])
        assert Y.merge_updates([fleet.encode_state_as_update(g)]) == ref
    d = rooms["room-0"]
    sv = encode_state_vector(d)
    d.get_text("text").insert(0, "after! ")
    fleet.receive_update("room-0", encode_state_as_update(d, sv))
    assert fleet.text("room-0") == d.get_text("text").to_string()


# -- satellite 6: monotone runs reuse the sorted segment ----------------------


def _snapshot_ops() -> int:
    return kernel_profiler().host_op_stats().get(
        "plan_snapshot", {"count": 0}
    )["count"]


def test_monotone_prepend_skips_snapshot_rebuild(monkeypatch):
    """A pure head-prepend run is one monotone chain: the planner must
    reuse the prior sorted segment instead of re-sorting (rebuilding)
    the whole fragment snapshot every flush."""
    monkeypatch.setenv("YTPU_PLAN_SEGMENT", "device")
    d = Y.Doc(gc=False)
    d.client_id = 9
    t = d.get_text("text")
    m = DocMirror("text")
    before = _snapshot_ops()
    for j in range(120):
        sv = encode_state_vector(d)
        t.insert(0, "p")
        m.ingest(encode_state_as_update(d, sv), False)
        if (j + 1) % 12 == 0:
            m.prepare_step()
    assert _snapshot_ops() == before, (
        "head-prepend flushes must not rebuild the fragment snapshot"
    )
    ref = Y.Doc(gc=False)
    apply_update(ref, m.encode_state_as_update())
    assert ref.get_text("text").to_string() == t.to_string()


def test_conflicted_runs_still_build_snapshot(monkeypatch):
    """The reuse shortcut must not swallow real anchor lookups: a
    conflicted corpus with many non-chained anchors rebuilds."""
    monkeypatch.setenv("YTPU_PLAN_SEGMENT", "device")
    updates = corpus("interleaved", seed=3, n_ops=120)
    m = DocMirror("text")
    before = _snapshot_ops()
    for j, u in enumerate(updates):
        m.ingest(u, False)
        if (j + 1) % 30 == 0 or j == len(updates) - 1:
            m.prepare_step()
    assert _snapshot_ops() > before


# -- whole-chunk planner internals --------------------------------------------


def test_plan_chunk_matches_per_doc_plans(monkeypatch):
    """plan_chunk's doc-composed global keys must resolve the same
    hints/chains as independent per-doc plan_doc calls."""
    monkeypatch.setenv("YTPU_PLAN_SEGMENT", "device")
    shapes = ["prepend_storm", "storm", "interleaved", "b4_head"]
    tokens = []
    for k, shape in enumerate(shapes):
        m = DocMirror("text")
        for u in corpus(shape, seed=60 + k, n_ops=40):
            m.ingest(u, False)
        tokens.append((m, m.prepare_step_begin()))
    items = [(tok.queries, m._segment_snapshot) for m, tok in tokens]
    chunked = segment_planner.plan_chunk(items, mode="device")
    solo = [
        segment_planner.plan_doc(q, mode="jax", snapshot=snap)
        for q, snap in items
    ]
    assert len(chunked) == len(solo)
    for c, s in zip(chunked, solo):
        if c is None or s is None:
            assert c is None and s is None
            continue
        assert c.spans == s.spans
        assert (c.chain_l == s.chain_l).all()
        assert (c.chain_r == s.chain_r).all()
        if c.hint_l is None or s.hint_l is None:
            assert c.snapshot_reused == s.snapshot_reused
        else:
            assert (c.hint_l == s.hint_l).all()
            assert (c.hint_r == s.hint_r).all()
    # the mirrors are mid-prepare; finish them so nothing leaks poisoned
    for (m, tok), sp in zip(tokens, chunked):
        m.prepare_step_finish(tok, sp)


def test_modes_table_is_closed():
    assert set(segment_planner.MODES) == {"device", "np", "jax", "off"}
    assert segment_planner.plan_segment_mode() in segment_planner.MODES
