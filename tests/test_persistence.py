"""Durability suite (ISSUE 3): WAL record codec, rotation, compaction,
and crash-recovery semantics — torn tails truncate, mid-log corruption
dead-letters, replay is idempotent, the DLQ survives a checkpoint.

Recovery property under test throughout: the CRDT merge contract makes
log replay safe — updates commute and are idempotent, so any prefix of
snapshot+tail replay, applied any number of times, converges to the
state the journaled traffic describes.
"""

from __future__ import annotations

import json
import random
import shutil
from pathlib import Path

import pytest

import yjs_tpu as Y
from yjs_tpu.persistence import (
    KIND_SNAPSHOT,
    KIND_UPDATE,
    SEG_HEADER,
    WalConfig,
    WriteAheadLog,
    encode_record,
    list_checkpoints,
    list_segments,
    replay_wal,
    try_decode_at,
)
from yjs_tpu.provider import ProviderFullError, TpuProvider
from yjs_tpu.resilience import DiskFaultInjector

pytestmark = pytest.mark.durability

FIXTURES = Path(__file__).parent / "fixtures" / "wal"
SMALL = WalConfig(segment_bytes=256, fsync="never")


def phased_streams(seed: int, rooms=("alpha", "beta"), phases=(30, 12)):
    """Per-room per-phase incremental update streams from CONTINUING
    client sessions (phase 2 extends phase 1's causal history)."""
    out = {}
    for j, room in enumerate(rooms):
        gen = random.Random(seed + j)
        docs, updates = [], []
        for k in range(3):
            d = Y.Doc(gc=False)
            d.client_id = 1000 * (j + 1) + k
            d.on("update", lambda u, origin, doc: updates.append(bytes(u)))
            docs.append(d)
        room_phases = []
        for n in phases:
            for _ in range(n):
                d = gen.choice(docs)
                t = d.get_text("text")
                if len(t) and gen.random() < 0.3:
                    t.delete(gen.randrange(len(t)), 1)
                else:
                    t.insert(gen.randrange(len(t) + 1), gen.choice("abcdef "))
            room_phases.append(list(updates))
            updates.clear()
        out[room] = room_phases
    return out


def canonical(prov: TpuProvider, guid: str) -> bytes:
    """Canonical full-state bytes: merge_updates normalizes struct
    splits, so equal stores yield IDENTICAL bytes regardless of the
    order their history arrived in."""
    return Y.merge_updates([prov.encode_state_as_update(guid)])


# -- record codec --------------------------------------------------------


def test_record_roundtrip_and_crc():
    rec = encode_record(KIND_UPDATE, "room/x", b"payload bytes", v2=True)
    status, decoded, end = try_decode_at(rec, 0)
    assert status == "ok" and end == len(rec)
    assert decoded.kind == KIND_UPDATE
    assert decoded.guid == "room/x"
    assert decoded.payload == b"payload bytes"
    assert decoded.v2 is True
    # every single-byte damage is caught (CRC-32 covers header + body)
    for i in range(len(rec)):
        bad = bytearray(rec)
        bad[i] ^= 0x40
        status, _v, _e = try_decode_at(bytes(bad), 0)
        assert status != "ok" or bytes(bad) == rec

    short, _v, _e = try_decode_at(rec[: len(rec) - 3], 0)
    assert short == "short"


# -- journal + recover ---------------------------------------------------


def test_recover_matches_uninterrupted_reference(tmp_path):
    streams = phased_streams(seed=11)
    ref = TpuProvider(2, backend="cpu")
    victim = TpuProvider(2, backend="cpu", wal_dir=tmp_path, wal_config=SMALL)
    for room, (p1, p2) in streams.items():
        for u in p1 + p2:
            ref.receive_update(room, u)
            victim.receive_update(room, u)
    victim.flush()
    assert len(list_segments(tmp_path)) > 1  # rotation happened
    victim.wal.abandon()  # crash: no orderly close

    rec = TpuProvider.recover(tmp_path, backend="cpu")
    assert rec.last_recovery["outcome"] == "clean"
    for room in streams:
        assert rec.text(room) == ref.text(room)
        assert rec.state_vector(room) == ref.state_vector(room)
        assert canonical(rec, room) == canonical(ref, room)


def test_recover_integrates_without_new_traffic_on_auto(tmp_path):
    """Replay enqueues below the provider's dirty-tracking seam; on a
    device-backed engine the final flush must still run — the recovered
    state has to be readable IMMEDIATELY, not after the next unrelated
    update happens to dirty the provider (regression: replay left the
    records queued and every read path no-op'd the flush)."""
    streams = phased_streams(seed=77)
    prov = TpuProvider(2, wal_dir=tmp_path, wal_config=SMALL)
    for room, (p1, p2) in streams.items():
        for u in p1 + p2:
            prov.receive_update(room, u)
    prov.flush()
    texts = {room: prov.text(room) for room in streams}
    prov.close()  # orderly: the dir is checkpoint-only (pure snapshots)
    assert list_segments(tmp_path) == []

    rec = TpuProvider.recover(tmp_path)  # default (auto) backend
    assert rec.last_recovery["snapshots_applied"] == 2
    for room in streams:
        assert rec.text(room) == texts[room]


def test_checkpoint_compacts_and_recovers(tmp_path):
    streams = phased_streams(seed=22)
    prov = TpuProvider(2, backend="cpu", wal_dir=tmp_path, wal_config=SMALL)
    for room, (p1, _p2) in streams.items():
        for u in p1:
            prov.receive_update(room, u)
    before = len(list_segments(tmp_path))
    stats = prov.checkpoint()
    assert stats["docs"] == 2
    assert stats["segments_removed"] == before
    assert len(list_checkpoints(tmp_path)) == 1
    # post-checkpoint traffic lands in fresh tail segments
    for room, (_p1, p2) in streams.items():
        for u in p2:
            prov.receive_update(room, u)
    prov.flush()
    texts = {room: prov.text(room) for room in streams}
    prov.wal.abandon()

    rec = TpuProvider.recover(tmp_path, backend="cpu")
    assert rec.last_recovery["snapshots_applied"] == 2
    for room in streams:
        assert rec.text(room) == texts[room]

    # a second checkpoint supersedes the first
    rec.checkpoint()
    assert len(list_checkpoints(tmp_path)) == 1


def test_close_writes_final_checkpoint(tmp_path):
    streams = phased_streams(seed=33, phases=(20,))
    prov = TpuProvider(2, backend="cpu", wal_dir=tmp_path, wal_config=SMALL)
    for room, (p1,) in streams.items():
        for u in p1:
            prov.receive_update(room, u)
    texts = {room: prov.text(room) for room in streams}
    prov.close()
    assert len(list_checkpoints(tmp_path)) == 1
    assert list_segments(tmp_path) == []  # everything folded in
    with pytest.raises(RuntimeError):
        prov.wal.append(KIND_UPDATE, "alpha", b"x")
    rec = TpuProvider.recover(tmp_path, backend="cpu")
    for room in streams:
        assert rec.text(room) == texts[room]


def test_torn_tail_truncated_and_reconverges(tmp_path, rng):
    streams = phased_streams(seed=44)
    ref = TpuProvider(2, backend="cpu")
    victim = TpuProvider(2, backend="cpu", wal_dir=tmp_path, wal_config=SMALL)
    for room, (p1, _p2) in streams.items():
        for u in p1:
            ref.receive_update(room, u)
            victim.receive_update(room, u)
    victim.wal.abandon()
    inj = DiskFaultInjector(seed=rng.randrange(1 << 30))
    _idx, last = list_segments(tmp_path)[-1]
    assert inj.tear(last) > 0
    size_after_tear = last.stat().st_size

    rec = TpuProvider.recover(tmp_path, backend="cpu")
    assert rec.last_recovery["torn_truncations"] >= 1
    assert rec.last_recovery["outcome"] == "torn_tail"
    # recovery TRUNCATED the torn tail in place: the file now ends at
    # the last intact record
    assert last.stat().st_size <= size_after_tear
    # the lost suffix is bounded traffic; a sync round re-delivers it
    for room in streams:
        diff = ref.encode_state_as_update(
            room, Y.encode_state_vector_from_update(canonical(rec, room))
        )
        rec.receive_update(room, diff)
        assert rec.text(room) == ref.text(room)
        assert canonical(rec, room) == canonical(ref, room)
    # and a re-recovery of the truncated dir is clean
    rec.wal.abandon()
    rec2 = TpuProvider.recover(tmp_path, backend="cpu")
    assert rec2.last_recovery["torn_truncations"] == 0


def test_midlog_corruption_dead_letters_not_aborts(tmp_path):
    streams = phased_streams(seed=55)
    prov = TpuProvider(2, backend="cpu", wal_dir=tmp_path, wal_config=SMALL)
    for room, (p1, p2) in streams.items():
        for u in p1 + p2:
            prov.receive_update(room, u)
    prov.flush()
    prov.wal.abandon()
    segs = list_segments(tmp_path)
    assert len(segs) > 2
    inj = DiskFaultInjector(seed=5)
    off = inj.bitflip(segs[0][1], lo=len(SEG_HEADER))
    assert off >= len(SEG_HEADER)

    rec = TpuProvider.recover(tmp_path, backend="cpu")
    lr = rec.last_recovery
    assert lr["outcome"] == "corrupt_records"
    assert lr["corrupt_records"] >= 1
    # the damaged record went to the DLQ with the wal-corrupt reason...
    reasons = [d["reason"] for d in rec.dead_letters()]
    assert any(r.startswith("wal-corrupt") for r in reasons)
    # ...and everything after it still applied (one record lost, the
    # rest of the log replayed: strictly more than the damaged segment)
    assert lr["records_applied"] > 0


def test_recovery_idempotent_same_wal_twice(tmp_path):
    """Property: replaying the same WAL into the same provider twice
    (or recovering the same directory twice) is a no-op the second
    time — per doc AND per batch, SV and canonical bytes equal."""
    streams = phased_streams(seed=66)
    prov = TpuProvider(2, backend="cpu", wal_dir=tmp_path, wal_config=SMALL)
    for room, (p1, _p2) in streams.items():
        for u in p1:
            prov.receive_update(room, u)
    prov.checkpoint()  # snapshot + tail both present
    for room, (_p1, p2) in streams.items():
        for u in p2:
            prov.receive_update(room, u)
    prov.flush()
    prov.wal.abandon()

    once = TpuProvider.recover(tmp_path, backend="cpu")
    svs1 = {room: once.state_vector(room) for room in streams}
    exports1 = {room: canonical(once, room) for room in streams}
    # replay the SAME directory into the already-recovered provider
    replay_wal(once, tmp_path, exclude_from=once.wal.first_index)
    for room in streams:
        assert once.state_vector(room) == svs1[room]
        assert canonical(once, room) == exports1[room]
    # batched export path agrees with the per-doc path
    docs = sorted(once._guid_of)
    batch = once.engine.encode_states_batched(docs)
    for i, u in zip(docs, batch):
        room = once._guid_of[i]
        assert Y.merge_updates([u]) == exports1[room]

    # an independent second recovery converges to the same state
    twice = TpuProvider.recover(tmp_path, backend="cpu")
    for room in streams:
        assert twice.state_vector(room) == svs1[room]
        assert canonical(twice, room) == exports1[room]


def test_recovery_idempotent_prefix_then_full(tmp_path):
    """Property: replaying a PREFIX of the log and then the full log
    equals replaying the full log once (snapshot/tail overlap is the
    real-world case: a checkpoint covers traffic the tail repeats)."""
    streams = phased_streams(seed=77)
    prov = TpuProvider(2, backend="cpu", wal_dir=tmp_path, wal_config=SMALL)
    for room, (p1, p2) in streams.items():
        for u in p1 + p2:
            prov.receive_update(room, u)
    prov.flush()
    prov.wal.abandon()
    segs = list_segments(tmp_path)
    assert len(segs) >= 2
    cut = segs[len(segs) // 2][0]

    full = TpuProvider(2, backend="cpu")
    replay_wal(full, tmp_path, truncate_torn=False)

    prefixed = TpuProvider(2, backend="cpu")
    replay_wal(prefixed, tmp_path, exclude_from=cut, truncate_torn=False)
    replay_wal(prefixed, tmp_path, truncate_torn=False)

    for room in streams:
        assert prefixed.state_vector(room) == full.state_vector(room)
        assert canonical(prefixed, room) == canonical(full, room)


# -- DLQ persistence -----------------------------------------------------


def test_dlq_survives_checkpoint_and_replays(tmp_path):
    streams = phased_streams(seed=88, phases=(20,))
    prov = TpuProvider(2, backend="cpu", wal_dir=tmp_path, wal_config=SMALL)
    (good,) = streams["alpha"]
    held_back = good[-1]
    for u in good[:-1]:
        prov.receive_update("alpha", u)
    # dead-letter a VALID update (simulates an operator-fixable refusal:
    # the bytes themselves replay fine once re-admitted)
    prov.engine._dead_letter(prov.doc_id("alpha"), held_back, False, "test-hold")
    prov.checkpoint()
    prov.wal.abandon()

    rec = TpuProvider.recover(tmp_path, backend="cpu")
    assert rec.last_recovery["dlq_restored"] == 1
    letters = rec.dead_letters("alpha")
    assert [d["reason"] for d in letters] == ["test-hold"]
    res = rec.replay_dead_letters("alpha")
    assert res["replayed"] == 1
    oracle = Y.Doc(gc=False)
    for u in good:
        Y.apply_update(oracle, u)
    assert rec.text("alpha") == str(oracle.get_text("text"))


# -- slot lifecycle ------------------------------------------------------


def test_recovery_overflow_dead_letters_instead_of_dropping(tmp_path):
    # regression (ISSUE 6 satellite): replay used to DISCARD a doc's
    # records silently when the recovered provider was smaller than the
    # journaled fleet — durably-written state vanished.  Overflowed
    # records must ride the DLQ with their guid in the reason (so an
    # operator or the fleet rebalancer can re-route them) and count on
    # ytpu_wal_recovery_overflow_total.
    streams = phased_streams(
        seed=33, rooms=("alpha", "beta", "gamma"), phases=(10,)
    )
    prov = TpuProvider(3, backend="cpu", wal_dir=tmp_path, wal_config=SMALL)
    for room, (p1,) in streams.items():
        for u in p1:
            prov.receive_update(room, u)
    prov.flush()
    prov.wal.abandon()  # crash

    rec = TpuProvider.recover(tmp_path, n_docs=2, backend="cpu")
    stats = rec.last_recovery
    assert stats["overflowed"] >= 1
    admitted = [r for r in streams if rec.has_doc(r)]
    assert len(admitted) == 2  # first-come admission filled both slots
    (evicted,) = set(streams) - set(admitted)
    letters = [
        e for e in rec.dead_letters()
        if e["reason"].startswith("wal-overflow:")
    ]
    assert len(letters) == stats["overflowed"]
    assert all(repr(evicted) in e["reason"] for e in letters)
    # the new counter moved in lockstep with the stats
    overflow = rec.engine.obs.registry.get(
        "ytpu_wal_recovery_overflow_total"
    )
    assert overflow.value == stats["overflowed"]
    assert stats["dead_lettered"] >= stats["overflowed"]


def test_full_release_reuse_and_eviction_counter(tmp_path):
    streams = phased_streams(seed=99, phases=(15,))
    prov = TpuProvider(2, backend="cpu", wal_dir=tmp_path, wal_config=SMALL)
    for room, (p1,) in streams.items():
        for u in p1:
            prov.receive_update(room, u)
    with pytest.raises(ProviderFullError, match="provider is full"):
        prov.doc_id("gamma")
    # the typed error still satisfies legacy except ValueError handlers
    with pytest.raises(ValueError):
        prov.doc_id("gamma")

    slot = prov.doc_id("beta")
    final = prov.release_doc("beta")
    assert prov._wal_metrics is not None
    assert prov.engine.obs.registry.counter(
        "ytpu_provider_docs_evicted_total"
    ).value == 1
    # the final snapshot is the room's complete state
    d = Y.Doc(gc=False)
    Y.apply_update(d, final)
    oracle = Y.Doc(gc=False)
    for u in streams["beta"][0]:
        Y.apply_update(oracle, u)
    assert str(d.get_text("text")) == str(oracle.get_text("text"))
    # the slot is reusable and starts empty
    assert prov.doc_id("gamma") == slot
    assert prov.text("gamma") == ""
    prov.receive_update("gamma", streams["beta"][0][0])
    prov.flush()
    prov.wal.abandon()

    # recovery honors the release record: beta is NOT resurrected into
    # a slot (its archived snapshot is in the log, deliberately parked)
    rec = TpuProvider.recover(tmp_path, n_docs=2, backend="cpu")
    assert rec.last_recovery["released"] == 1
    assert "beta" not in rec._guids
    assert sorted(rec._guids) == ["alpha", "gamma"]


def test_release_unknown_room_raises():
    prov = TpuProvider(1, backend="cpu")
    with pytest.raises(KeyError):
        prov.release_doc("nope")


# -- fixture corpus ------------------------------------------------------


def _fixture_cases():
    manifest = json.loads((FIXTURES / "manifest.json").read_text())
    return [pytest.param(c, id=c["dir"]) for c in manifest["cases"]]


@pytest.mark.parametrize("case", _fixture_cases())
def test_fixture_corpus_recovers_as_recorded(case, tmp_path):
    """The versioned damaged-WAL corpus (scripts/gen_wal_fixtures.py)
    recovers to its manifest-recorded golden state — a format change
    that breaks old logs fails HERE, not in production."""
    work = tmp_path / "wal"
    shutil.copytree(FIXTURES / case["dir"], work)  # recovery mutates
    prov = TpuProvider.recover(work, backend="cpu")
    lr = prov.last_recovery
    exp = case["expected"]
    assert lr["outcome"] == exp["outcome"]
    assert lr["torn_truncations"] == exp["torn_truncations"]
    assert lr["corrupt_records"] == exp["corrupt_records"]
    assert {g: prov.text(g) for g in sorted(prov._guids)} == exp["texts"]


# -- fsync policy + metrics ----------------------------------------------


@pytest.mark.parametrize("mode", ["always", "interval", "never"])
def test_fsync_policy_counters(tmp_path, mode):
    cfg = WalConfig(segment_bytes=1 << 20, fsync=mode, fsync_interval=4)
    wal = WriteAheadLog(tmp_path, cfg)
    prov_like_metrics = wal.metrics  # no-op bundle; count manually
    assert prov_like_metrics is not None
    import yjs_tpu.persistence.wal as walmod

    calls = []
    orig = walmod.os.fsync
    walmod.os.fsync = lambda fd: calls.append(fd)
    try:
        for k in range(10):
            wal.append(KIND_UPDATE, "g", b"x" * 8)
        wal.close()
    finally:
        walmod.os.fsync = orig
    if mode == "always":
        assert len(calls) == 11  # one per append + seal
    elif mode == "interval":
        assert len(calls) == 3  # appends 4 and 8, + seal
    else:
        assert calls == []


def test_env_config_and_validation(tmp_path, monkeypatch):
    monkeypatch.setenv("YTPU_WAL_SEGMENT_BYTES", "12345")
    monkeypatch.setenv("YTPU_WAL_FSYNC", "never")
    monkeypatch.setenv("YTPU_WAL_FSYNC_INTERVAL", "7")
    cfg = WalConfig()
    assert cfg.as_dict() == {
        "segment_bytes": 12345, "fsync": "never", "fsync_interval": 7
    }
    monkeypatch.setenv("YTPU_WAL_FSYNC", "sometimes")
    with pytest.raises(ValueError, match="YTPU_WAL_FSYNC"):
        WalConfig()
    # YTPU_WAL_DIR enables journaling without a constructor arg
    monkeypatch.setenv("YTPU_WAL_FSYNC", "never")
    monkeypatch.setenv("YTPU_WAL_DIR", str(tmp_path / "envwal"))
    prov = TpuProvider(1, backend="cpu")
    assert prov.wal is not None
    prov.receive_update("r", phased_streams(3, rooms=("r",))["r"][0][0])
    assert list_segments(tmp_path / "envwal")


def test_wal_metric_families_always_registered():
    prov = TpuProvider(1, backend="cpu")  # no WAL attached
    names = set(prov.engine.obs.registry.names())
    expected = {
        "ytpu_wal_records_appended_total",
        "ytpu_wal_bytes_appended_total",
        "ytpu_wal_fsyncs_total",
        "ytpu_wal_segments_sealed_total",
        "ytpu_wal_compactions_total",
        "ytpu_wal_compaction_reclaimed_bytes_total",
        "ytpu_wal_recoveries_total",
        "ytpu_wal_replay_records_total",
        "ytpu_wal_torn_tail_truncations_total",
        "ytpu_wal_corrupt_records_total",
        "ytpu_wal_replay_seconds",
        "ytpu_provider_docs_evicted_total",
    }
    assert expected <= names


def test_wal_counters_move_with_traffic(tmp_path):
    prov = TpuProvider(1, backend="cpu", wal_dir=tmp_path, wal_config=SMALL)
    (p1,) = phased_streams(7, rooms=("r",), phases=(20,))["r"]
    for u in p1:
        prov.receive_update("r", u)
    m = prov._wal_metrics
    assert m.records.labels(kind="update").value == len(p1)
    assert m.bytes.value > 0
    assert m.segments.value > 0  # rotation sealed at least one
    prov.checkpoint()
    assert m.compactions.value == 1
    assert m.reclaimed.value > 0
