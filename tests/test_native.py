"""Native C++ transcoder vs the pure-Python decoder: byte-exact metadata
equivalence on updates exercising every content kind, plus fallback."""

import pytest

import yjs_tpu as Y
from yjs_tpu.ops.columns import (
    LazyContent,
    LazyContentV2,
    _decode_update_refs_native,
    _decode_update_refs_native_v2,
    decode_update_refs,
)
from yjs_tpu import native


requires_native = pytest.mark.skipif(
    native.load() is None, reason="native transcoder not built"
)


def python_decode(update, v2=False):
    """Force the pure-Python path."""
    import yjs_tpu.native as nat

    old_lib, old_tried = nat._lib, nat._tried
    nat._lib, nat._tried = None, True
    try:
        return decode_update_refs(update, v2)
    finally:
        nat._lib, nat._tried = old_lib, old_tried


def ref_meta(r):
    return (
        r.client, r.clock, r.length, r.origin, r.right_origin,
        r.parent_name, r.parent_id, r.parent_sub, r.content_ref, r.is_gc,
    )


def assert_equivalent(update, v2=False):
    if v2:
        refs_n, ds_n = _decode_update_refs_native_v2(update)
    else:
        refs_n, ds_n = _decode_update_refs_native(update)
    refs_p, ds_p = python_decode(update, v2)
    assert sorted(refs_n.keys()) == sorted(refs_p.keys())
    for client in refs_p:
        metas_n = [ref_meta(r) for r in refs_n[client]]
        metas_p = [ref_meta(r) for r in refs_p[client]]
        assert metas_n == metas_p
        # lazily-realized payloads must equal the eagerly-decoded ones
        for rn, rp in zip(refs_n[client], refs_p[client]):
            if isinstance(rn.content, (LazyContent, LazyContentV2)):
                cn = rn.materialize()
                assert type(cn) is type(rp.content)
                if rn.content_ref == 7:  # nested type: compare structurally
                    assert type(cn.type) is type(rp.content.type)
                    assert getattr(cn.type, "node_name", None) == getattr(
                        rp.content.type, "node_name", None
                    )
                else:
                    assert cn.get_content() == rp.content.get_content()
    assert sorted(ds_n) == sorted(ds_p)


@requires_native
class TestNativeEquivalence:
    def test_text_doc(self):
        d = Y.Doc(gc=False)
        d.client_id = 42
        t = d.get_text("text")
        t.insert(0, "hello wörld 🙂")
        t.insert(3, "XY")
        t.delete(1, 4)
        t.format(0, 3, {"bold": True})
        assert_equivalent(Y.encode_state_as_update(d))

    def test_all_content_kinds(self):
        d = Y.Doc(gc=False)
        d.client_id = 7
        arr = d.get_array("arr")
        arr.insert(0, [1, 2.5, "s", True, None, {"k": [1, 2]}, b"\x00\xff"])
        m = d.get_map("map")
        m.set("num", 3)
        m.set("nested", {"deep": {"er": [1]}})
        t = d.get_text("text")
        t.insert(0, "abc")
        t.insert(1, "🙂🙂")
        assert_equivalent(Y.encode_state_as_update(d))

    def test_xml_and_types(self):
        from yjs_tpu.types.yxml import YXmlElement, YXmlText

        d = Y.Doc(gc=False)
        d.client_id = 9
        frag = d.get("xml", Y.YXmlFragment)
        el = YXmlElement("div")
        frag.insert(0, [el, YXmlText("txt")])
        el.set_attribute("class", "c1")
        assert_equivalent(Y.encode_state_as_update(d))

    def test_multi_client_with_deletes_and_gc(self):
        a = Y.Doc(gc=False)
        a.client_id = 1
        b = Y.Doc(gc=True)
        b.client_id = 2
        a.get_text("text").insert(0, "shared text")
        Y.apply_update(b, Y.encode_state_as_update(a))
        b.get_text("text").delete(2, 5)
        b.get_text("text").insert(0, "B")
        assert_equivalent(Y.encode_state_as_update(b))

    def test_garbage_rejected(self):
        from yjs_tpu.native import NativeDecodeError, decode_v1_columns

        with pytest.raises(NativeDecodeError):
            decode_v1_columns(b"\x99\xfe\x03garbage")


@requires_native
class TestNativeEquivalenceV2:
    """The V2 9-stream columnar container (reference
    UpdateDecoder.js:270-293) through the native scanner."""

    def test_text_doc_v2(self):
        d = Y.Doc(gc=False)
        d.client_id = 42
        t = d.get_text("text")
        t.insert(0, "hello wörld 🙂")
        t.insert(3, "XY")
        t.delete(1, 4)
        t.format(0, 3, {"bold": True})
        assert_equivalent(Y.encode_state_as_update_v2(d), v2=True)

    def test_all_content_kinds_v2(self):
        d = Y.Doc(gc=False)
        d.client_id = 7
        arr = d.get_array("arr")
        arr.insert(0, [1, 2.5, "s", True, None, {"k": [1, 2]}, b"\x00\xff"])
        m = d.get_map("map")
        m.set("num", 3)
        m.set("nested", {"deep": {"er": [1]}})
        t = d.get_text("text")
        t.insert(0, "abc")
        t.insert(1, "🙂🙂")
        assert_equivalent(Y.encode_state_as_update_v2(d), v2=True)

    def test_xml_and_types_v2(self):
        from yjs_tpu.types.yxml import YXmlElement, YXmlText

        d = Y.Doc(gc=False)
        d.client_id = 9
        frag = d.get("xml", Y.YXmlFragment)
        el = YXmlElement("div")
        frag.insert(0, [el, YXmlText("txt")])
        el.set_attribute("class", "c1")
        assert_equivalent(Y.encode_state_as_update_v2(d), v2=True)

    def test_multi_client_with_deletes_and_gc_v2(self):
        a = Y.Doc(gc=False)
        a.client_id = 1
        b = Y.Doc(gc=True)
        b.client_id = 2
        a.get_text("text").insert(0, "shared text")
        Y.apply_update(b, Y.encode_state_as_update(a))
        b.get_text("text").delete(2, 5)
        b.get_text("text").insert(0, "B")
        assert_equivalent(Y.encode_state_as_update_v2(b), v2=True)

    def test_map_key_dictionary_v2(self):
        # repeated map keys exercise the keyClock dictionary
        # (UpdateDecoder.js:382-391)
        a = Y.Doc(gc=False)
        a.client_id = 3
        b = Y.Doc(gc=False)
        b.client_id = 4
        for i in range(5):
            a.get_map("m").set("shared", i)
            b.get_map("m").set("shared", 10 + i)
            Y.apply_update(a, Y.encode_state_as_update(b))
            Y.apply_update(b, Y.encode_state_as_update(a))
        assert_equivalent(Y.encode_state_as_update_v2(a), v2=True)

    def test_subdoc_falls_back_v2(self):
        # ContentDoc payloads punt to the Python decoder (error -4)
        d = Y.Doc(gc=False)
        d.client_id = 6
        d.get_map("m").set("sub", Y.Doc(guid="child"))
        u = Y.encode_state_as_update_v2(d)
        with pytest.raises(native.NativeDecodeError):
            native.decode_v2_columns(u)
        refs, _ds = decode_update_refs(u, v2=True)  # silent fallback
        assert refs[6][0].content_ref == 9

    def test_garbage_rejected_v2(self):
        with pytest.raises(native.NativeDecodeError):
            native.decode_v2_columns(b"\x00\x01\x02junk")

    def test_key_caching_encoder_xml_names_v2(self, monkeypatch):
        # a spec-compliant encoder MAY cache keys and emit keyClock-only
        # references for repeated Xml names (readKey, YXmlElement.js:225);
        # the v13.4 reference never does (its writeKey quirk), so simulate
        # a caching writeKey and ensure the native scanner's key dictionary
        # handles it identically to the Python decoder
        from yjs_tpu.coding import UpdateEncoderV2
        from yjs_tpu.types.yxml import YXmlElement

        def caching_write_key(self, key):
            clock = self.key_map.get(key)
            if clock is None:
                clock = len(self.key_map)
                self.key_map[key] = clock
                self.key_clock_encoder.write(clock)
                self.string_encoder.write(key)
            else:
                self.key_clock_encoder.write(clock)

        monkeypatch.setattr(UpdateEncoderV2, "write_key", caching_write_key)
        d = Y.Doc(gc=False)
        d.client_id = 11
        frag = d.get("xml", Y.YXmlFragment)
        frag.insert(0, [YXmlElement("div"), YXmlElement("span"),
                        YXmlElement("div"), YXmlElement("div")])
        u = Y.encode_state_as_update_v2(d)
        assert_equivalent(u, v2=True)


@requires_native
class TestNativeEncode:
    """ytpu_encode_v1: the native writer must be byte-identical to the
    Python encoder for every mirror state (reference encoding.js:71-116,
    Item.js:625-658)."""

    def _python_encode(self, mirror, target_sv=None):
        import yjs_tpu.native as nat

        old_lib, old_tried = nat._lib, nat._tried
        nat._lib, nat._tried = None, True
        try:
            return mirror.encode_state_as_update(target_sv)
        finally:
            nat._lib, nat._tried = old_lib, old_tried

    def _assert_byte_equal(self, mirror, target_sv=None):
        assert mirror.encode_state_as_update(target_sv) == self._python_encode(
            mirror, target_sv
        )

    @pytest.mark.parametrize("seed", range(4))
    def test_fuzz_encode_parity(self, seed):
        import random

        from yjs_tpu.ops.columns import DocMirror

        gen = random.Random(7000 + seed)
        docs = []
        for i in range(3):
            d = Y.Doc(gc=False)
            d.client_id = i + 1
            docs.append(d)
        upds = []
        for d in docs:
            d.on("update", lambda u, origin, _d: upds.append(u))
        for _ in range(60):
            d = gen.choice(docs)
            op = gen.random()
            if op < 0.55:
                t = d.get_text("text")
                ln = len(t.to_string())
                if gen.random() < 0.7 or ln == 0:
                    t.insert(gen.randint(0, ln), gen.choice(["x", "🙂y", "zz "]))
                else:
                    pos = gen.randrange(ln)
                    t.delete(pos, min(gen.randint(1, 3), ln - pos))
            elif op < 0.85:
                d.get_map("map").set(gen.choice("abc"), gen.randrange(50))
            else:
                d.get_array("arr").insert(0, [gen.randrange(9), "s"])
            if gen.random() < 0.3:
                src, dst = gen.choice(docs), gen.choice(docs)
                for u in upds:
                    Y.apply_update(dst, u)
        v2 = gen.random() < 0.5
        mirror = DocMirror("text")
        merged = (Y.encode_state_as_update_v2 if v2 else Y.encode_state_as_update)(
            docs[0]
        )
        mirror.ingest(merged, v2=v2)
        mirror.prepare_step()
        self._assert_byte_equal(mirror)
        # diff against a random partial state vector (offset cuts)
        full_sv = mirror.state_vector()
        partial = {c: gen.randint(0, v) for c, v in full_sv.items()}
        self._assert_byte_equal(mirror, partial)
        # the emitted update reproduces the doc
        d2 = Y.Doc(gc=False)
        Y.apply_update(d2, mirror.encode_state_as_update())
        assert d2.get_text("text").to_string() == docs[0].get_text("text").to_string()
        assert d2.get_map("map").to_json() == docs[0].get_map("map").to_json()

    def test_fallback_when_disabled(self, monkeypatch):
        d = Y.Doc(gc=False)
        d.client_id = 3
        d.get_text("text").insert(0, "plain")
        refs, ds = python_decode(Y.encode_state_as_update(d))
        assert refs[3][0].length == 5


def test_wide_key_dictionary_stays_native():
    """>4096 distinct map keys ride the native V2 scan without demotion
    (the old fixed key-table cap silently demoted wide docs; ADVICE r3)."""
    import yjs_tpu as Y
    from yjs_tpu.ops import BatchEngine

    d = Y.Doc(gc=False)
    m = d.get_map("meta")
    for i in range(4200):
        m.set(f"key{i}", i)
    eng = BatchEngine(1, root_name="meta")
    eng.queue_update(0, Y.encode_state_as_update_v2(d), v2=True)
    eng.flush()
    assert eng.demotions == []
    assert eng.map_json(0, "meta") == m.to_json()


def test_malformed_utf8_matches_python_error(monkeypatch):
    """Adversarial bytes with invalid UTF-8 continuations must raise the
    same error the Python decoder raises — not silently miscount on the
    native path (ADVICE r3: continuation-byte validation).

    Strict mode: by default the resilience layer isolates a poisoned doc
    instead of raising, so disable it to assert raw error-type parity
    (the isolation-path contract is covered by tests/test_resilience.py).
    """
    import pytest

    import yjs_tpu as Y
    from yjs_tpu.ops import BatchEngine

    monkeypatch.setenv("YTPU_RESILIENCE_DISABLED", "1")

    base = Y.Doc(gc=False)
    base.get_text("text").insert(0, "AAAA")
    u = bytearray(Y.encode_state_as_update(base))
    pos = bytes(u).find(b"AAAA")
    u[pos] = 0xE2   # 3-byte lead ...
    u[pos + 1] = 0x28  # ... with an invalid continuation byte
    with pytest.raises(Exception) as py_err:
        ref = Y.Doc(gc=False)
        Y.apply_update(ref, bytes(u))
    eng = BatchEngine(1)
    eng.queue_update(0, bytes(u))
    with pytest.raises(Exception) as nat_err:
        eng.flush()
    assert type(nat_err.value) is type(py_err.value)
