"""Native C++ transcoder vs the pure-Python decoder: byte-exact metadata
equivalence on updates exercising every content kind, plus fallback."""

import pytest

import yjs_tpu as Y
from yjs_tpu.ops.columns import LazyContent, _decode_update_refs_native, decode_update_refs
from yjs_tpu import native


requires_native = pytest.mark.skipif(
    native.load() is None, reason="native transcoder not built"
)


def python_decode(update):
    """Force the pure-Python path."""
    import yjs_tpu.native as nat

    old_lib, old_tried = nat._lib, nat._tried
    nat._lib, nat._tried = None, True
    try:
        return decode_update_refs(update, False)
    finally:
        nat._lib, nat._tried = old_lib, old_tried


def ref_meta(r):
    return (
        r.client, r.clock, r.length, r.origin, r.right_origin,
        r.parent_name, r.parent_id, r.parent_sub, r.content_ref, r.is_gc,
    )


def assert_equivalent(update):
    refs_n, ds_n = _decode_update_refs_native(update)
    refs_p, ds_p = python_decode(update)
    assert sorted(refs_n.keys()) == sorted(refs_p.keys())
    for client in refs_p:
        metas_n = [ref_meta(r) for r in refs_n[client]]
        metas_p = [ref_meta(r) for r in refs_p[client]]
        assert metas_n == metas_p
        # lazily-realized payloads must equal the eagerly-decoded ones
        for rn, rp in zip(refs_n[client], refs_p[client]):
            if isinstance(rn.content, LazyContent):
                cn = rn.materialize()
                assert type(cn) is type(rp.content)
                if rn.content_ref == 7:  # nested type: compare structurally
                    assert type(cn.type) is type(rp.content.type)
                    assert getattr(cn.type, "node_name", None) == getattr(
                        rp.content.type, "node_name", None
                    )
                else:
                    assert cn.get_content() == rp.content.get_content()
    assert sorted(ds_n) == sorted(ds_p)


@requires_native
class TestNativeEquivalence:
    def test_text_doc(self):
        d = Y.Doc(gc=False)
        d.client_id = 42
        t = d.get_text("text")
        t.insert(0, "hello wörld 🙂")
        t.insert(3, "XY")
        t.delete(1, 4)
        t.format(0, 3, {"bold": True})
        assert_equivalent(Y.encode_state_as_update(d))

    def test_all_content_kinds(self):
        d = Y.Doc(gc=False)
        d.client_id = 7
        arr = d.get_array("arr")
        arr.insert(0, [1, 2.5, "s", True, None, {"k": [1, 2]}, b"\x00\xff"])
        m = d.get_map("map")
        m.set("num", 3)
        m.set("nested", {"deep": {"er": [1]}})
        t = d.get_text("text")
        t.insert(0, "abc")
        t.insert(1, "🙂🙂")
        assert_equivalent(Y.encode_state_as_update(d))

    def test_xml_and_types(self):
        from yjs_tpu.types.yxml import YXmlElement, YXmlText

        d = Y.Doc(gc=False)
        d.client_id = 9
        frag = d.get("xml", Y.YXmlFragment)
        el = YXmlElement("div")
        frag.insert(0, [el, YXmlText("txt")])
        el.set_attribute("class", "c1")
        assert_equivalent(Y.encode_state_as_update(d))

    def test_multi_client_with_deletes_and_gc(self):
        a = Y.Doc(gc=False)
        a.client_id = 1
        b = Y.Doc(gc=True)
        b.client_id = 2
        a.get_text("text").insert(0, "shared text")
        Y.apply_update(b, Y.encode_state_as_update(a))
        b.get_text("text").delete(2, 5)
        b.get_text("text").insert(0, "B")
        assert_equivalent(Y.encode_state_as_update(b))

    def test_garbage_rejected(self):
        from yjs_tpu.native import NativeDecodeError, decode_v1_columns

        with pytest.raises(NativeDecodeError):
            decode_v1_columns(b"\x99\xfe\x03garbage")

    def test_fallback_when_disabled(self, monkeypatch):
        d = Y.Doc(gc=False)
        d.client_id = 3
        d.get_text("text").insert(0, "plain")
        refs, ds = python_decode(Y.encode_state_as_update(d))
        assert refs[3][0].length == 5
