"""yjs_tpu.obs: metrics registry, flush-history ring, span tracing,
exposition (ISSUE 1).

Fast host-only tests: ring semantics, histogram bucket/percentile math,
Chrome-trace JSON validity, flush-metrics schema parity across every
flush mode, Prometheus text, and the provider's defensive metrics copy.
"""

import json
import math
import os

import pytest

import yjs_tpu as Y
from yjs_tpu.obs import FLUSH_METRICS_SCHEMA, global_registry, new_flush_metrics
from yjs_tpu.obs.history import FlushHistory
from yjs_tpu.obs.registry import Histogram, MetricsRegistry
from yjs_tpu.ops import BatchEngine
from yjs_tpu.provider import TpuProvider
from yjs_tpu.updates import encode_state_as_update


def _update(text="hello"):
    d = Y.Doc(gc=False)
    d.get_text("text").insert(0, text)
    return encode_state_as_update(d)


# -- flush-history ring ------------------------------------------------------


def test_ring_bounded_fifo_and_alias():
    ring = FlushHistory(maxlen=4)
    entries = [{"i": i} for i in range(6)]
    for e in entries:
        ring.append(e)
    assert len(ring) == 4
    # FIFO eviction: the two oldest entries are gone
    assert [m["i"] for m in ring] == [2, 3, 4, 5]
    assert ring[0] is entries[2]
    # latest is the SAME object as the newest append (the
    # last_flush_metrics alias contract), while snapshot() copies
    assert ring.latest is entries[-1]
    assert ring.snapshot() == [{"i": 2}, {"i": 3}, {"i": 4}, {"i": 5}]
    assert ring.snapshot()[0] is not entries[2]
    assert ring.total == 6


def test_engine_ring_one_entry_per_flush(monkeypatch):
    monkeypatch.setenv("YTPU_OBS_HISTORY", "3")
    eng = BatchEngine(2)
    for k in range(5):
        eng.queue_update(0, _update(f"v{k}"))
        eng.flush()
    assert eng.obs.history.total == 5
    assert len(eng.obs.history) == 3  # bounded by YTPU_OBS_HISTORY
    # last_flush_metrics is the newest ring entry ITSELF, not a copy
    assert eng.last_flush_metrics is eng.obs.history.latest
    assert eng.last_flush_metrics["n_docs_flushed"] == 1
    # empty flushes are real flushes: they get a ring entry too
    eng.flush()
    assert eng.obs.history.total == 6
    assert eng.last_flush_metrics["n_docs_flushed"] == 0


# -- histogram math ----------------------------------------------------------


def test_histogram_exact_stats_and_percentiles():
    h = Histogram("t")
    for v in range(1, 1001):
        h.observe(v)
    s = h.summary()
    assert s["count"] == 1000
    assert s["sum"] == pytest.approx(500500.0)
    assert s["min"] == 1.0
    assert s["max"] == 1000.0
    # 8 buckets/octave => quantiles land within ~4.5% of the true value
    assert s["p50"] == pytest.approx(500.0, rel=0.05)
    assert s["p95"] == pytest.approx(950.0, rel=0.05)
    assert s["p99"] == pytest.approx(990.0, rel=0.05)


def test_histogram_quantile_clamped_and_zero_bucket():
    h = Histogram("t")
    h.observe(42.0)
    # single observation: every quantile IS that value (midpoint clamped
    # into [min, max])
    assert h.quantile(0.5) == 42.0
    assert h.quantile(0.99) == 42.0
    z = Histogram("z")
    z.observe(0.0)
    z.observe(0.0)
    z.observe(8.0)
    assert z.quantile(0.5) == 0.0  # underflow bucket reports min
    assert z.summary()["max"] == 8.0
    assert Histogram("e").summary() == {
        "count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
        "p50": 0.0, "p95": 0.0, "p99": 0.0,
    }


def test_histogram_bucket_relative_error_across_decades():
    # the geometric-midpoint readback stays within the 8-per-octave bound
    # (2**(1/16) - 1 ~ 4.4%) from microseconds to kiloseconds
    for v in (1e-6, 3.7e-4, 0.02, 1.5, 88.0, 4096.0):
        h = Histogram("t")
        for _ in range(100):
            h.observe(v)
        assert h.quantile(0.5) == pytest.approx(v, rel=0.045)


def test_registry_kind_mismatch_and_reuse():
    r = MetricsRegistry()
    c = r.counter("x", "help")
    assert r.counter("x") is c  # re-registration returns the family
    with pytest.raises(ValueError):
        r.gauge("x")
    lab = r.counter("y", labelnames=("k",))
    lab.labels(k="a").inc(2)
    lab.labels(k="a").inc()
    assert lab.labels(k="a").value == 3
    assert lab.labels(k="b").value == 0


# -- flush-metrics schema ----------------------------------------------------


def test_new_flush_metrics_rejects_unknown_keys():
    m = new_flush_metrics(n_demoted=2)
    assert m["n_demoted"] == 2
    assert set(m) == set(FLUSH_METRICS_SCHEMA)
    with pytest.raises(KeyError):
        new_flush_metrics(no_such_metric=1)


def test_flush_metrics_schema_identical_across_modes():
    """apply / levels / seq / pure-Python planner: one key set
    (FLUSH_METRICS_SCHEMA), no mode-specific drift."""
    keysets = {}
    for mode in ("native", "apply", "levels", "seq", "python"):
        if mode == "python":
            os.environ["YTPU_NO_NATIVE_PLAN"] = "1"
        elif mode != "native":
            os.environ["YTPU_KERNEL"] = mode
        try:
            eng = BatchEngine(2)
            eng.queue_update(0, _update())
            eng.queue_update(1, _update("other"))
            eng.flush()
            keysets[mode] = set(eng.last_flush_metrics)
        finally:
            os.environ.pop("YTPU_KERNEL", None)
            os.environ.pop("YTPU_NO_NATIVE_PLAN", None)
    for mode, keys in keysets.items():
        assert keys == set(FLUSH_METRICS_SCHEMA), mode


# -- span tracing ------------------------------------------------------------


def test_chrome_trace_json_valid_and_phased():
    eng = BatchEngine(2)
    n_flushes = 2
    for k in range(n_flushes):
        eng.queue_update(0, _update(f"flush{k}"))
        eng.flush()
    trace = eng.export_chrome_trace()
    # loadable: a strict JSON round trip of the Perfetto container shape
    loaded = json.loads(json.dumps(trace))
    assert loaded["displayTimeUnit"] == "ms"
    events = loaded["traceEvents"]
    assert events
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)  # monotonic (metadata events sit at ts 0.0)
    for e in events:
        assert e["ph"] in ("X", "i", "M", "s", "f")
        if e["ph"] == "X":  # complete events carry a duration
            assert e["dur"] >= 0.0
        assert {"name", "pid", "tid", "cat"} <= set(e)
    # pid/tid metadata present so Perfetto names the process lanes
    meta = [e for e in events if e["ph"] == "M"]
    assert {m["name"] for m in meta} >= {"process_name", "thread_name"}
    names = [e["name"] for e in events]
    # one flush span per flush, one span per host phase per flush
    assert names.count("ytpu.flush") == n_flushes
    for phase in ("compact", "emit"):
        assert names.count(f"ytpu.{phase}") == n_flushes
    # work flushed every time, so plan+pack+dispatch ran each flush (the
    # chunked batched path emits one span per chunk on top of the
    # prepare-scan span: >=)
    for phase in ("plan", "pack", "dispatch"):
        assert names.count(f"ytpu.{phase}") >= n_flushes


def test_trace_instant_on_demotion():
    eng = BatchEngine(1)
    d = Y.Doc(gc=False)
    d.get_text("text").insert(0, "x")
    sub = Y.Doc(gc=False)
    d.get_map("m").set("sub", sub)  # subdoc -> device demotion
    eng.queue_update(0, encode_state_as_update(d))
    eng.flush()
    assert len(eng.fallback) == 1
    events = eng.export_chrome_trace()["traceEvents"]
    inst = [e for e in events if e["ph"] == "i" and e["name"] == "ytpu.demote"]
    assert len(inst) == 1
    assert inst[0]["s"] == "t"
    assert inst[0]["args"]["doc"] == 0
    # and the labeled demotion counter matches the ledger
    fams = dict.fromkeys(eng.obs.registry.names())
    assert "ytpu_engine_demotions_total" in fams
    total = sum(
        series.value
        for _labels, series in eng.obs.registry.get(
            "ytpu_engine_demotions_total"
        ).samples()
    )
    assert total == len(eng.demotions) == 1


def test_tracer_save(tmp_path):
    eng = BatchEngine(1)
    eng.queue_update(0, _update())
    eng.flush()
    p = eng.save_trace(str(tmp_path / "trace.json"))
    with open(p) as f:
        assert json.load(f)["traceEvents"]


# -- exposition --------------------------------------------------------------


def test_prometheus_text_dump():
    prov = TpuProvider(2)
    prov.receive_update("room", _update())
    prov.flush()
    prov.handle_sync_message("room", prov.sync_step1("room"))
    text = prov.metrics_text()
    assert "# TYPE ytpu_engine_flushes_total counter" in text
    assert "# TYPE ytpu_engine_fallback_docs gauge" in text
    # histograms render as summaries with the three quantile series
    assert "# TYPE ytpu_engine_flush_seconds summary" in text
    assert 'ytpu_engine_flush_seconds{quantile="0.5"}' in text
    assert 'ytpu_engine_flush_seconds{quantile="0.95"}' in text
    assert "ytpu_engine_flush_seconds_count" in text
    assert 'ytpu_engine_phase_seconds{phase="plan",quantile="0.5"}' in text
    assert "ytpu_provider_updates_received_total 1" in text
    assert 'ytpu_provider_sync_messages_total{type="step1"} 1' in text
    # every line is name{labels} value or a comment
    for line in text.strip().splitlines():
        assert line.startswith("#") or len(line.rsplit(" ", 1)) == 2


def test_json_snapshot_round_trips():
    eng = BatchEngine(1)
    eng.queue_update(0, _update())
    eng.flush()
    snap = json.loads(json.dumps(eng.metrics_snapshot()))
    assert snap["schema"] == 1
    assert snap["counters"]["ytpu_engine_flushes_total"][""] == 1
    assert snap["flush"] == eng.last_flush_metrics
    assert snap["flush_history"] == [eng.last_flush_metrics]
    assert snap["n_flushes_recorded"] == 1
    assert snap["histograms"]["ytpu_engine_flush_seconds"][""]["count"] == 1


def test_provider_metrics_is_defensive_copy():
    prov = TpuProvider(1)
    prov.receive_update("r", _update())
    prov.flush()
    m = prov.metrics
    assert set(m) == set(FLUSH_METRICS_SCHEMA)
    m["n_docs_flushed"] = 999
    m.clear()
    assert prov.metrics["n_docs_flushed"] == 1
    assert prov.engine.last_flush_metrics["n_docs_flushed"] == 1
    # history snapshot is copies too
    prov.metrics_history[0]["n_docs_flushed"] = 999
    assert prov.metrics["n_docs_flushed"] == 1


def test_sync_protocol_frame_counters():
    fam = global_registry().get("ytpu_sync_messages_total")
    if fam is None:  # process-global obs disabled by the environment
        pytest.skip("YTPU_OBS_DISABLED in this process")

    def val(direction, typ):
        return fam.labels(dir=direction, type=typ).value

    before = {
        (d, t): val(d, t)
        for d in ("read", "write")
        for t in ("step1", "step2", "update")
    }
    from yjs_tpu.lib0.decoding import Decoder
    from yjs_tpu.lib0.encoding import Encoder
    from yjs_tpu.sync import protocol

    a, b = Y.Doc(gc=False), Y.Doc(gc=False)
    a.get_text("text").insert(0, "sync me")
    enc = Encoder()
    protocol.write_sync_step1(enc, b)
    reply = Encoder()
    protocol.read_sync_message(Decoder(enc.to_bytes()), reply, a)
    protocol.read_sync_message(Decoder(reply.to_bytes()), Encoder(), b)
    upd = Encoder()
    protocol.write_update(upd, encode_state_as_update(a))
    protocol.read_sync_message(Decoder(upd.to_bytes()), Encoder(), b)
    assert b.get_text("text").to_string() == "sync me"
    assert val("write", "step1") - before[("write", "step1")] == 1
    assert val("read", "step1") - before[("read", "step1")] == 1
    assert val("write", "step2") - before[("write", "step2")] == 1
    assert val("read", "step2") - before[("read", "step2")] == 1
    assert val("write", "update") - before[("write", "update")] == 1
    assert val("read", "update") - before[("read", "update")] == 1


def test_obs_disabled_keeps_flush_metrics(monkeypatch):
    monkeypatch.setenv("YTPU_OBS_DISABLED", "1")
    eng = BatchEngine(1)
    assert not eng.obs.enabled
    eng.queue_update(0, _update())
    eng.flush()
    # the compatibility surface survives: ring + last_flush_metrics work
    assert set(eng.last_flush_metrics) == set(FLUSH_METRICS_SCHEMA)
    assert eng.last_flush_metrics["n_docs_flushed"] == 1
    assert len(eng.obs.history) == 1
    # but nothing is registered, recorded, or traced for this engine
    assert eng.obs.registry.names() == []
    assert "ytpu_engine_" not in eng.metrics_text()
    assert eng.export_chrome_trace()["traceEvents"] == []


def test_metrics_schema_matches_readme():
    """Every registered family is in README's Observability table and
    vice versa (the scripts/check_metrics_schema.py contract, enforced
    in tier-1 so docs can't drift)."""
    import importlib.util
    from pathlib import Path

    root = Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "check_metrics_schema", root / "scripts" / "check_metrics_schema.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    live = mod.registered_names()
    if not live:
        pytest.skip("YTPU_OBS_DISABLED in this process")
    doc = mod.documented_names((root / "README.md").read_text())
    assert live - doc == set(), "registered but undocumented"
    assert doc - live == set(), "documented but not registered"


def test_native_prepare_histograms_on_batched_path():
    eng = BatchEngine(2)
    eng.queue_update(0, _update())
    eng.queue_update(1, _update("two"))
    eng.flush()
    from yjs_tpu.ops.native_mirror import native_plan_available

    fam = eng.obs.registry.get("ytpu_native_prepare_many_docs")
    if not native_plan_available():
        assert fam.count == 0  # python planner: batched path never runs
        return
    assert fam.count == 1
    assert fam.summary()["max"] == 2.0  # both docs planned in one call
