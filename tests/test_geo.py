"""Geo replication unit suite (ISSUE 17): doc-space codecs, the
space session host, the WAN chaos profile (one-way partitions,
deterministic flapping, bandwidth caps, RTT floors), anti-entropy
digest jitter, the retry-cap force-sample seam, KIND_GEO journaling /
recovery, and the GeoReplicator's scheduler + epoch machinery.

Everything is tick-driven and seeded.  The ``geo`` marker deselects
the suite with ``-m 'not geo'``.
"""

import json

import pytest

import yjs_tpu as Y
from yjs_tpu.geo import (
    GeoConfig,
    GeoReplicator,
    SpaceSessionHost,
    decode_space_sv,
    decode_space_update,
    encode_space_sv,
    encode_space_update,
)
from yjs_tpu.obs.blackbox import reset_flight_recorder
from yjs_tpu.persistence import KIND_GEO
from yjs_tpu.provider import TpuProvider
from yjs_tpu.resilience import NetChaosConfig, NetworkFaultInjector
from yjs_tpu.sync.session import (
    DocSessionHost,
    SessionConfig,
    SyncSession,
)
from yjs_tpu.sync.transport import PipeNetwork
from yjs_tpu.updates import encode_state_as_update

pytestmark = pytest.mark.geo


GEO_SESSION = dict(
    heartbeat=0, liveness=0, antientropy=8, hello_timeout=0,
    retry_base=4, retry_cap=16, retry_max=6, retry_jitter=0.25,
)


def _mk_update(text: str, client_id: int = 7) -> bytes:
    d = Y.Doc(gc=False)
    d.client_id = client_id
    d.get_text("text").insert(0, text)
    return encode_state_as_update(d)


def _mk_pair(seed=1, n_docs=4, wal_a=None, wal_b=None, geo_kw=None):
    cfg = SessionConfig(seed=seed, **GEO_SESSION)
    a = TpuProvider(n_docs, backend="cpu",
                    wal_dir=None if wal_a is None else str(wal_a))
    b = TpuProvider(n_docs, backend="cpu",
                    wal_dir=None if wal_b is None else str(wal_b))
    net = PipeNetwork()
    ta, tb = net.pair("geo:A", "geo:B")
    kw = dict(geo_kw or {})
    ra = GeoReplicator(a, GeoConfig(region="A", seed=seed, **kw))
    rb = GeoReplicator(b, GeoConfig(region="B", seed=seed + 1, **kw))
    ra.add_peer("B", lambda: ta, session_config=cfg)
    rb.add_peer("A", lambda: tb, session_config=cfg)
    return a, b, ra, rb, net


def _run(net, provs, reps, rounds):
    for _ in range(rounds):
        for p in provs:
            p.flush()
        for r in reps:
            r.tick()
        net.pump()


# -- codecs -------------------------------------------------------------------


def test_space_sv_roundtrip():
    svs = {"room-a": {1: 5, 9: 2}, "room-b": {3: 1}, "empty": {}}
    assert decode_space_sv(encode_space_sv(svs)) == svs


def test_space_sv_tolerates_garbage():
    assert decode_space_sv(None) == {}
    assert decode_space_sv(b"") == {}
    assert decode_space_sv(b"\xff\xff\xff\xff") == {}


def test_space_update_roundtrip():
    parts = [("room-a", b"\x01\x02\x03"), ("room-b", b"")]
    assert decode_space_update(encode_space_update(parts)) == parts


def test_space_update_raises_on_malformed():
    with pytest.raises(Exception):
        decode_space_update(b"\x05only-one-entry")


# -- the space session host ---------------------------------------------------


def test_ahead_behind_space_granularity():
    p = TpuProvider(4, backend="cpu")
    p.receive_update("room-a", _mk_update("local", 11))
    p.flush()
    host = SpaceSessionHost(p)
    # peer has nothing: strictly ahead
    ahead, behind = host.ahead_behind(encode_space_sv({}))
    assert ahead and not behind
    # peer mirrors us exactly: neither
    mine = decode_space_sv(host.state_vector())
    ahead, behind = host.ahead_behind(encode_space_sv(mine))
    assert not ahead and not behind
    # peer holds a doc we never heard of: behind
    theirs = dict(mine)
    theirs["room-z"] = {42: 3}
    ahead, behind = host.ahead_behind(encode_space_sv(theirs))
    assert behind and not ahead


def test_diff_update_ships_only_missing_docs():
    p = TpuProvider(4, backend="cpu")
    p.receive_update("room-a", _mk_update("alpha", 11))
    p.receive_update("room-b", _mk_update("beta", 12))
    p.flush()
    host = SpaceSessionHost(p)
    mine = decode_space_sv(host.state_vector())
    # the peer already has room-a; only room-b should ship
    peer_sv = {"room-a": mine["room-a"]}
    parts = decode_space_update(
        host.diff_update(encode_space_sv(peer_sv))
    )
    assert [g for g, _ in parts] == ["room-b"]


def test_apply_update_routes_through_internal_ingress():
    p = TpuProvider(4, backend="cpu")
    host = SpaceSessionHost(p)
    payload = encode_space_update([("room-x", _mk_update("wan", 13))])
    host.apply_update(payload)
    p.flush()
    assert p.text("room-x") == "wan"
    assert "room-x" in host.docs()  # remote applies feed doc discovery


# -- WAN chaos profile --------------------------------------------------------


def _due(dst, n):
    return [(0, dst, bytes([i])) for i in range(n)]


class _FakeDst:
    def __init__(self, name):
        self.name = name


def test_oneway_partition_loses_one_direction_only():
    inj = NetworkFaultInjector(NetChaosConfig(seed=3, oneway=1.0))
    inj.register_link("geo:A", "geo:B")
    a, b = _FakeDst("geo:A"), _FakeDst("geo:B")
    lost = {"geo:A": 0, "geo:B": 0}
    passed = {"geo:A": 0, "geo:B": 0}
    for rnd in range(200):
        due = _due(a, 1) + _due(b, 1)
        deliver, defer = inj.filter_due(due, rnd)
        assert not defer
        for name in lost:
            got = sum(1 for e in deliver if e[1].name == name)
            (passed if got else lost)[name] += 1
    # windows opened (frames were lost) but never both directions in
    # the same round — the injector kills exactly one victim direction
    assert inj.fault_counts["net_oneway"] > 0
    for rnd in range(50):
        due = _due(a, 1) + _due(b, 1)
        deliver, _ = inj.filter_due(due, rnd)
        names = {e[1].name for e in deliver}
        assert names, "one-way partition must never drop BOTH directions"


def test_flap_windows_are_deterministic():
    inj = NetworkFaultInjector(NetChaosConfig(seed=3, flap_ticks=5))
    # 75% duty cycle: up for rounds 0..14, down for 15..19, repeating
    assert not inj._flap_down(0)
    assert not inj._flap_down(14)
    assert inj._flap_down(15)
    assert inj._flap_down(19)
    assert not inj._flap_down(20)
    dst = _FakeDst("geo:A")
    deliver, _ = inj.filter_due(_due(dst, 2), 15)
    assert deliver == []
    assert inj.fault_counts["net_flap"] == 2


def test_bandwidth_cap_defers_instead_of_losing():
    inj = NetworkFaultInjector(NetChaosConfig(seed=3, bw_frames=2))
    dst = _FakeDst("geo:A")
    due = _due(dst, 5)
    deliver, defer = inj.filter_due(due, 1)
    assert len(deliver) == 2 and len(defer) == 3
    assert deliver == due[:2]  # FIFO under the cap, not sampling
    assert inj.fault_counts["net_bw"] == 3


def test_rtt_floor_delays_every_frame():
    inj = NetworkFaultInjector(
        NetChaosConfig(seed=3, rtt_ticks=7, rtt_jitter_ticks=2)
    )
    for _ in range(50):
        for delay in inj.fates(b"frame"):
            assert delay is not None and 7 <= delay <= 9
    # a latency profile, not a counted fault
    assert inj.fault_counts["net_drop"] == 0


def test_wan_env_knobs(monkeypatch):
    monkeypatch.setenv("YTPU_CHAOS_NET_PARTITION_ONEWAY", "0.25")
    monkeypatch.setenv("YTPU_CHAOS_NET_FLAP_TICKS", "9")
    monkeypatch.setenv("YTPU_CHAOS_NET_RTT_TICKS", "15")
    monkeypatch.setenv("YTPU_CHAOS_NET_RTT_JITTER_TICKS", "4")
    monkeypatch.setenv("YTPU_CHAOS_NET_BW_FRAMES", "32")
    cfg = NetChaosConfig.from_env()
    assert cfg.oneway == 0.25
    assert cfg.flap_ticks == 9
    assert cfg.rtt_ticks == 15
    assert cfg.rtt_jitter_ticks == 4
    assert cfg.bw_frames == 32
    assert cfg.any_faults()


# -- anti-entropy jitter (satellite) ------------------------------------------


def test_ae_jitter_spreads_digest_ticks():
    """Two sessions sharing a seed draw DIFFERENT digest jitter (the
    per-peer keyed stream), and the jitter never exceeds a quarter of
    the anti-entropy interval."""
    cfg = SessionConfig(seed=9, antientropy=16, heartbeat=0,
                        liveness=0, hello_timeout=0)
    docs = [Y.Doc(gc=False), Y.Doc(gc=False)]
    sessions = [
        SyncSession(DocSessionHost(d), cfg, peer=f"p{i}")
        for i, d in enumerate(docs)
    ]
    net = PipeNetwork()
    jitters = set()
    for s in sessions:
        t, _ = net.pair()
        s.connect(t)
        s._send_digest()
        assert 0 <= s._ae_jitter <= cfg.antientropy // 4
        jitters.add(s._ae_jitter)
    assert len(jitters) == 2  # distinct per-peer streams, distinct draws


def test_ae_jitter_stream_is_separate_from_backoff():
    """Drawing digest jitter must not perturb the retransmit backoff
    sequence — the two RNGs are independent keyed streams."""
    cfg = SessionConfig(seed=4, antientropy=16, heartbeat=0,
                        liveness=0, hello_timeout=0)
    a = SyncSession(DocSessionHost(Y.Doc(gc=False)), cfg, peer="a")
    b = SyncSession(DocSessionHost(Y.Doc(gc=False)), cfg, peer="b")
    b.sid = a.sid  # same identity -> same seeded backoff stream
    import random as _random

    b._rng = _random.Random((cfg.seed << 8) ^ b.sid)
    b._ae_rng = _random.Random(f"ae:{cfg.seed}:{a.peer}")
    for _ in range(5):
        b._ae_rng.random()  # extra jitter draws on one side only
    assert [a._backoff(i) for i in range(1, 6)] == [
        b._backoff(i) for i in range(1, 6)
    ]


# -- retry-cap force-sample seam (satellite) ----------------------------------


def test_retry_cap_dead_letter_is_force_sampled():
    """A frame that exhausts its retry budget must land a blackbox
    event carrying a FORCED trace — loss evidence survives production
    sampling rates (the seam-force-sample lint rule pins the code
    shape; this pins the behavior)."""
    rec = reset_flight_recorder()
    cfg = SessionConfig(seed=2, retry_base=1, retry_cap=1, retry_max=2,
                        retry_jitter=0.0, heartbeat=0, liveness=0,
                        antientropy=0, hello_timeout=0)
    doc = Y.Doc(gc=False)
    sess = SyncSession(DocSessionHost(doc), cfg, peer="wan")
    net = PipeNetwork()
    ta, tb = net.pair()
    sess.connect(ta)
    peer = SyncSession(DocSessionHost(Y.Doc(gc=False)), cfg, peer="rev")
    peer.connect(tb)
    for _ in range(6):
        net.pump()
        sess.tick()
        peer.tick()
    assert sess.state == "live"
    # black-hole the wire: sends still "succeed" (no transport loss,
    # so the session keeps retrying) but every frame — data and acks —
    # is dropped, burning the retry budget
    net.injector = NetworkFaultInjector(NetChaosConfig(seed=1, drop=1.0))
    doc.get_text("text").insert(0, "doomed")
    sess.send_update(encode_state_as_update(doc))
    for _ in range(40):
        net.pump()
        sess.tick()
        peer.tick()
        if sess.n_dead_lettered:
            break
    assert sess.n_dead_lettered >= 1
    events = [
        e for e in rec.snapshot()
        if e.get("event") == "retry_cap_dead_letter"
    ]
    assert events, "retry-cap exhaustion must land a blackbox event"
    evt = events[-1]
    assert evt["subsystem"] == "session"
    assert evt["severity"] == "warning"
    assert evt.get("trace"), "the dead-letter trace must be force-sampled"
    assert evt["kv"]["attempts"] >= cfg.retry_max


# -- KIND_GEO journaling + recovery -------------------------------------------


def test_kind_geo_roundtrips_through_recovery(tmp_path):
    p = TpuProvider(2, backend="cpu", wal_dir=str(tmp_path))
    p.journal_geo_link("region-b", sid=12, seq=34, epoch=2)
    p.journal_geo_link("region-b", sid=12, seq=99, epoch=3)  # LAST wins
    p.journal_geo_link("region-c", sid=7, seq=1, epoch=3)
    del p
    pr = TpuProvider.recover(str(tmp_path), backend="cpu")
    assert pr.last_recovery["geo_links"] == 2
    assert pr._recovered_geo["region-b"] == {
        "sid": 12, "seq": 99, "epoch": 3,
    }
    assert pr._recovered_geo["region-c"] == {
        "sid": 7, "seq": 1, "epoch": 3,
    }


def test_recovered_replicator_bumps_fencing_epoch(tmp_path):
    p = TpuProvider(2, backend="cpu", wal_dir=str(tmp_path))
    p.journal_geo_link("B", sid=5, seq=17, epoch=4)
    del p
    pr = TpuProvider.recover(str(tmp_path), backend="cpu")
    rep = GeoReplicator(pr, GeoConfig(region="A", seed=1))
    # the restart is a new fencing era: max journaled epoch + 1
    assert rep.epoch == 5
    link = rep.add_peer("B", lambda: None)
    # the journaled floor armed the session's resume hint
    assert link.session._resume_hint == (5, 17)


def test_checkpoint_rejournals_geo_floors(tmp_path):
    a, b, ra, rb, net = _mk_pair(wal_a=tmp_path / "a")
    a.receive_update("room", _mk_update("floor me"))
    _run(net, (a, b), (ra, rb), 40)
    assert ra.links["B"].floor["seq"] >= 1
    a.checkpoint()
    del a, ra
    pr = TpuProvider.recover(str(tmp_path / "a"), backend="cpu")
    assert pr._recovered_geo["B"]["seq"] >= 1


# -- replicator behavior ------------------------------------------------------


def test_two_region_convergence_and_floors():
    a, b, ra, rb, net = _mk_pair()
    a.receive_update("room-1", _mk_update("hello from A", 11))
    b.receive_update("room-2", _mk_update("hello from B", 12))
    _run(net, (a, b), (ra, rb), 50)
    assert a.text("room-1") == b.text("room-1") == "hello from A"
    assert a.text("room-2") == b.text("room-2") == "hello from B"
    for rep, peer in ((ra, "B"), (rb, "A")):
        link = rep.links[peer]
        assert link.session.state == "live"
        assert link.session.n_full_resyncs == 1
        assert link.floor["seq"] >= 1
        assert rep.detector.state_of(peer) == "alive"


def test_budget_scheduler_defers_oldest_first():
    """A tiny link budget forces one doc per tick, oldest dirty doc
    first; deferred docs are counted and eventually ship."""
    geo_kw = dict(link_budget_bps=800, tick_ms=10)  # 1 B/tick accrual
    a, b, ra, rb, net = _mk_pair(n_docs=8, geo_kw=geo_kw)
    _run(net, (a, b), (ra, rb), 12)  # settle handshake
    before = ra.metrics.deferrals.value
    for i in range(4):
        a.receive_update(f"room-{i}", _mk_update(f"doc {i}", 20 + i))
        a.flush()
        ra.tick()  # each doc dirties on its own tick: distinct ages
    _run(net, (a, b), (ra, rb), 250)
    assert ra.metrics.deferrals.value > before
    for i in range(4):
        assert b.text(f"room-{i}") == f"doc {i}"


def test_coalesced_updates_counted():
    a, b, ra, rb, net = _mk_pair(geo_kw=dict(link_budget_bps=80))
    _run(net, (a, b), (ra, rb), 12)
    before = ra.metrics.coalesced.value
    # many updates to ONE doc between scheduler ticks: later marks
    # absorb into the already-dirty entry instead of shipping their
    # own frames (the coalesce path)
    for i in range(6):
        a.receive_update("room", _mk_update(f"edit {i} ", 30 + i))
        a.flush()
    assert ra.metrics.coalesced.value > before
    _run(net, (a, b), (ra, rb), 400)
    assert a.text("room") == b.text("room")


def test_link_reconnect_backoff_and_revival():
    a, b, ra, rb, net = _mk_pair()
    _run(net, (a, b), (ra, rb), 20)
    la, lb = ra.links["B"], rb.links["A"]
    assert la.session.state == "live"
    # sever the WAN; connect_fn returns None while it is down
    down = {"down": True}
    ta2 = {}

    def connect_a():
        if down["down"]:
            return None
        return ta2["t"]

    def connect_b():
        if down["down"]:
            return None
        return ta2["u"]

    la.connect_fn = connect_a
    lb.connect_fn = connect_b
    net.kill(la.session.transport, lb.session.transport)
    assert la.session.state == "reconnecting"
    for _ in range(30):
        ra.tick()
        rb.tick()
    # the detector convicted the dead link
    assert ra.detector.state_of("B") in ("suspect", "dead")
    n_attempts_window = la._reconnect_attempts
    assert n_attempts_window >= 1  # backoff is retrying
    # WAN heals
    down["down"] = False
    ta2["t"], ta2["u"] = net.pair("geo:A", "geo:B")
    a.receive_update("post-heal", _mk_update("after the partition"))
    _run(net, (a, b), (ra, rb), 120)
    assert la.session.state == "live"
    assert b.text("post-heal") == "after the partition"
    assert ra.detector.state_of("B") == "alive"
    assert la.n_reconnects == 1
    # resumed, not full-resynced: seq spaces carried across the outage
    assert la.session.n_full_resyncs == 1


def test_epoch_poll_rehomes_links():
    """An upstream routing-epoch bump (fleet/cluster failover) advances
    the region fencing epoch and rehomes every link."""

    class _Table:
        epoch = 3

    a, b, ra, rb, net = _mk_pair()
    _run(net, (a, b), (ra, rb), 20)
    a.table = _Table()  # facade grows a routing table mid-flight
    ra.tick()  # baseline observation: no rehome
    e0 = ra.epoch
    a.table.epoch = 4
    ra.tick()
    assert ra.epoch == e0 + 1
    assert ra.links["B"].floor["epoch"] == ra.epoch
    assert ra.links["B"].session.routing_epoch == ra.epoch
    # push entry point dedups against the poll
    ra.notify_epoch(4)
    assert ra.epoch == e0 + 1


def test_snapshot_shape_for_statusz():
    a, b, ra, rb, net = _mk_pair()
    _run(net, (a, b), (ra, rb), 20)
    snap = a.statusz()["geo"]
    assert snap["region"] == "A"
    assert len(snap["links"]) == 1
    row = snap["links"][0]
    for key in ("link", "state", "detector", "outbox", "dirty_docs",
                "lag_bytes", "lag_seconds", "reconnects", "resumes",
                "full_resyncs", "dead_letters", "floor"):
        assert key in row
    # and the metrics snapshot used by ytpu_top carries the same block
    assert a.metrics_snapshot()["geo"]["region"] == "A"


def test_geo_config_env_knobs(monkeypatch):
    monkeypatch.setenv("YTPU_GEO_REGION", "eu-west")
    monkeypatch.setenv("YTPU_GEO_LINK_BUDGET_BPS", "125000")
    monkeypatch.setenv("YTPU_GEO_TICK_MS", "20")
    monkeypatch.setenv("YTPU_GEO_RECONNECT_BASE", "8")
    monkeypatch.setenv("YTPU_GEO_RECONNECT_CAP", "128")
    monkeypatch.setenv("YTPU_GEO_RECONNECT_JITTER", "0.5")
    cfg = GeoConfig()
    assert cfg.region == "eu-west"
    assert cfg.link_budget_bps == 125000
    assert cfg.tick_ms == 20
    assert cfg.reconnect_base == 8
    assert cfg.reconnect_cap == 128
    assert cfg.reconnect_jitter == 0.5
    assert cfg.budget_per_tick() == 2500


def test_geo_json_payload_shape(tmp_path):
    """The KIND_GEO payload is the documented JSON contract: an empty
    guid (link state is region-scoped, not per-doc) and a
    ``{peer, sid, seq, epoch}`` JSON body."""
    from yjs_tpu.persistence.recovery import iter_file_events, scan_wal

    p = TpuProvider(2, backend="cpu", wal_dir=str(tmp_path))
    p.journal_geo_link("B", sid=1, seq=2, epoch=3)
    p.close(checkpoint=False)
    _, segs = scan_wal(str(tmp_path))
    recs = [
        val for _, path in segs
        for kind, val, *_ in iter_file_events(path, final=False)
        if kind == "record" and val.kind == KIND_GEO
    ]
    assert recs, "journal_geo_link must land a KIND_GEO record"
    assert recs[-1].guid == ""
    info = json.loads(recs[-1].payload.decode("utf-8"))
    assert info == {"peer": "B", "sid": 1, "seq": 2, "epoch": 3}
