"""Undo/redo (scenarios modeled on reference tests/undo-redo.tests.js).

Note: like the reference, these tests do not assert struct-store equality —
keep-flags on the undo site cause benign divergence in GC state.
"""

import yjs_tpu as Y
from yjs_tpu.core import ContentType, Item
from helpers import init


def test_undo_text(rng):
    result = init(rng, users=3)
    tc = result["testConnector"]
    text0, text1 = result["text0"], result["text1"]
    undo_manager = Y.UndoManager(text0)

    # items added & deleted in the same transaction are not undone
    text0.insert(0, "test")
    text0.delete(0, 4)
    undo_manager.undo()
    assert text0.to_string() == ""

    # follow redone items
    text0.insert(0, "a")
    undo_manager.stop_capturing()
    text0.delete(0, 1)
    undo_manager.stop_capturing()
    undo_manager.undo()
    assert text0.to_string() == "a"
    undo_manager.undo()
    assert text0.to_string() == ""

    text0.insert(0, "abc")
    text1.insert(0, "xyz")
    tc.sync_all()
    undo_manager.undo()
    assert text0.to_string() == "xyz"
    undo_manager.redo()
    assert text0.to_string() == "abcxyz"
    tc.sync_all()
    text1.delete(0, 1)
    tc.sync_all()
    undo_manager.undo()
    assert text0.to_string() == "xyz"
    undo_manager.redo()
    assert text0.to_string() == "bcxyz"
    # formatting marks
    text0.format(1, 3, {"bold": True})
    assert text0.to_delta() == [
        {"insert": "b"},
        {"insert": "cxy", "attributes": {"bold": True}},
        {"insert": "z"},
    ]
    undo_manager.undo()
    assert text0.to_delta() == [{"insert": "bcxyz"}]
    undo_manager.redo()
    assert text0.to_delta() == [
        {"insert": "b"},
        {"insert": "cxy", "attributes": {"bold": True}},
        {"insert": "z"},
    ]


def test_double_undo():
    doc = Y.Doc()
    text = doc.get_text("")
    text.insert(0, "1221")
    manager = Y.UndoManager(text)
    text.insert(2, "3")
    text.insert(3, "3")
    manager.undo()
    manager.undo()
    text.insert(2, "3")
    assert text.to_string() == "12321"


def test_undo_map(rng):
    result = init(rng, users=2)
    tc = result["testConnector"]
    map0, map1 = result["map0"], result["map1"]
    map0.set("a", 0)
    undo_manager = Y.UndoManager(map0)
    map0.set("a", 1)
    undo_manager.undo()
    assert map0.get("a") == 0
    undo_manager.redo()
    assert map0.get("a") == 1
    # sub-types: restore a whole type
    sub_type = Y.YMap()
    map0.set("a", sub_type)
    sub_type.set("x", 42)
    assert map0.to_json() == {"a": {"x": 42}}
    undo_manager.undo()
    assert map0.get("a") == 1
    undo_manager.redo()
    assert map0.to_json() == {"a": {"x": 42}}
    tc.sync_all()
    # content overwritten by another user: undo is skipped
    map1.set("a", 44)
    tc.sync_all()
    undo_manager.undo()
    assert map0.get("a") == 44
    undo_manager.redo()
    assert map0.get("a") == 44
    # setting value multiple times within one capture
    map0.set("b", "initial")
    undo_manager.stop_capturing()
    map0.set("b", "val1")
    map0.set("b", "val2")
    undo_manager.stop_capturing()
    undo_manager.undo()
    assert map0.get("b") == "initial"


def test_undo_array(rng):
    result = init(rng, users=3)
    tc = result["testConnector"]
    array0, array1 = result["array0"], result["array1"]
    undo_manager = Y.UndoManager(array0)
    array0.insert(0, [1, 2, 3])
    array1.insert(0, [4, 5, 6])
    tc.sync_all()
    assert array0.to_json() == [1, 2, 3, 4, 5, 6]
    undo_manager.undo()
    assert array0.to_json() == [4, 5, 6]
    undo_manager.redo()
    assert array0.to_json() == [1, 2, 3, 4, 5, 6]
    tc.sync_all()
    array1.delete(0, 1)  # user1 deletes [1]
    tc.sync_all()
    undo_manager.undo()
    assert array0.to_json() == [4, 5, 6]
    undo_manager.redo()
    assert array0.to_json() == [2, 3, 4, 5, 6]
    array0.delete(0, 5)
    # test nested types
    ymap = Y.YMap()
    array0.insert(0, [ymap])
    assert array0.to_json() == [{}]
    undo_manager.stop_capturing()
    ymap.set("a", 1)
    assert array0.to_json() == [{"a": 1}]
    undo_manager.undo()
    assert array0.to_json() == [{}]
    undo_manager.undo()
    assert array0.to_json() == [2, 3, 4, 5, 6]
    undo_manager.redo()
    assert array0.to_json() == [{}]
    undo_manager.redo()
    assert array0.to_json() == [{"a": 1}]


def test_undo_xml():
    doc = Y.Doc()
    xml0 = doc.get("undefined", Y.YXmlElement)
    undo_manager = Y.UndoManager(xml0)
    child = Y.YXmlElement("p")
    xml0.insert(0, [child])
    text_child = Y.YXmlText("content")
    child.insert(0, [text_child])
    assert xml0.to_string() == "<undefined><p>content</p></undefined>"
    undo_manager.stop_capturing()
    text_child.format(3, 4, {"bold": {"color": "red"}})
    assert (
        xml0.to_string()
        == '<undefined><p>con<bold color="red">tent</bold></p></undefined>'
    )
    undo_manager.undo()
    assert xml0.to_string() == "<undefined><p>content</p></undefined>"
    undo_manager.redo()
    assert (
        xml0.to_string()
        == '<undefined><p>con<bold color="red">tent</bold></p></undefined>'
    )


def test_undo_events():
    doc = Y.Doc()
    text0 = doc.get_text("text")
    undo_manager = Y.UndoManager(text0)
    received = {}

    def on_added(event, um):
        received["added"] = event["stackItem"]
        event["stackItem"].meta["test"] = 42

    def on_popped(event, um):
        received["popped"] = event["stackItem"].meta.get("test")

    undo_manager.on("stack-item-added", on_added)
    undo_manager.on("stack-item-popped", on_popped)
    text0.insert(0, "abc")
    undo_manager.undo()
    assert received["popped"] == 42


def test_track_class():
    doc = Y.Doc()
    text0 = doc.get_text("text")
    undo_manager = Y.UndoManager(text0, tracked_origins={int})
    doc.transact(lambda txn: text0.insert(0, "abc"), 42)
    assert text0.to_string() == "abc"
    undo_manager.undo()
    assert text0.to_string() == ""
    # untracked origin is ignored
    doc.transact(lambda txn: text0.insert(0, "xyz"), "string-origin")
    undo_manager.undo()
    assert text0.to_string() == "xyz"


# note: the reference's later "undo until change performed" (#373) behavior
# is NOT in v13.4.9 — popStackItem pops exactly one stack item regardless of
# whether a change was performed (reference UndoManager.js:62,121), so that
# scenario is intentionally not ported.


def test_type_scope(rng):
    """Scope filtering across nested types (reference undo-redo.tests.js
    testTypeScope)."""
    result = init(rng, users=3)
    array0 = result["array0"]
    text0 = Y.YText()
    text1 = Y.YText()
    array0.insert(0, [text0, text1])
    um = Y.UndoManager(text0)
    um_both = Y.UndoManager([text0, text1])
    text1.insert(0, "abc")
    assert len(um.undo_stack) == 0
    assert len(um_both.undo_stack) == 1
    assert text1.to_string() == "abc"
    um.undo()
    assert text1.to_string() == "abc"
    um_both.undo()
    assert text1.to_string() == ""


def test_undo_delete_filter(rng):
    """delete_filter keeps non-empty nested maps alive through undo
    (reference undo-redo.tests.js testUndoDeleteFilter)."""
    from yjs_tpu.core import ContentType, Item

    result = init(rng, users=3)
    array0 = result["array0"]

    def keep_filter(item):
        return not isinstance(item, Item) or (
            isinstance(item.content, ContentType)
            and len(item.content.type._map) == 0
        )

    um = Y.UndoManager(array0, delete_filter=keep_filter)
    map0 = Y.YMap()
    map0.set("hi", 1)
    map1 = Y.YMap()
    array0.insert(0, [map0, map1])
    um.undo()
    assert array0.length == 1
    assert len(list(array0.get(0).keys())) == 1
