"""Process-native cluster acceptance (ISSUE 14 tentpole).

Real OS shard processes (``yjs_tpu.cluster.shard``) under the
:class:`Supervisor`, fronted by the y-websocket gateway, with live
session peers attached over real sockets.  The headline contract:
``kill -9`` of the owner shard mid-flush → the supervisor restarts it
through ``recover()`` (or fails over past the restart budget), every
surviving peer reconverges byte-identically with at most one full
resync, and no acked update is lost — the BUSY refusal keeps unacked
frames in the session outbox until the shard is back."""

import importlib.util
import io
import json
import os
import signal
import socket
import sys
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "examples"))
from socket_connector import SocketConnector  # noqa: E402

import yjs_tpu as Y  # noqa: E402
from yjs_tpu.cluster import (  # noqa: E402
    ClusterConfig,
    Gateway,
    GatewayConfig,
    RpcBusy,
    RpcError,
    Supervisor,
)

pytestmark = pytest.mark.cluster

# tight supervision so one kill costs ~a second of test wall time, not
# the production defaults' five
FAST = dict(heartbeat_s=0.15, restart_backoff_s=0.05, busy_retry_ticks=4)


def _connect(gw_port: int, room: str, client_id: int):
    doc = Y.Doc(gc=False)
    doc.client_id = client_id
    sock = socket.create_connection(("127.0.0.1", gw_port), timeout=30)
    conn = SocketConnector(doc, sock, room=room, peer=f"peer-{client_id}")
    conn.connect()
    return doc, conn


def _texts(pairs):
    out = []
    for doc, conn in pairs:
        with conn.lock:
            out.append(doc.get_text("text").to_string())
    return out


def _wait_equal(pairs, deadline_s: float = 60.0, require=()):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        texts = _texts(pairs)
        if (
            len(set(texts)) == 1
            and texts[0] != ""
            and all(tok in texts[0] for tok in require)
        ):
            return texts[0]
        time.sleep(0.05)
    raise AssertionError(f"no convergence: {_texts(pairs)!r}")


def _wait_outcome(sup, outcome: str, deadline_s: float = 90.0) -> dict:
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        report = sup.recovery_report()
        if report["outcomes"].get(outcome, 0) >= 1:
            return report
        time.sleep(0.1)
    raise AssertionError(
        f"supervision never reported {outcome!r}: {sup.recovery_report()}"
    )


def test_kill9_owner_mid_flush_reconverges_with_zero_acked_loss(tmp_path):
    """The ISSUE 14 acceptance scenario end to end."""
    snap_dir = str(tmp_path / "snap")
    sup = Supervisor(
        3, str(tmp_path / "wal"), docs_per_shard=8,
        config=ClusterConfig(
            restart_max=2, snapshot_dir=snap_dir, snapshot_s=0.5, **FAST
        ),
    ).start()
    gw = Gateway(sup, config=GatewayConfig(port=0)).start()
    pairs = []
    try:
        room = "accept-room"
        a = _connect(gw.port, room, 1)
        b = _connect(gw.port, room, 2)
        pairs = [a, b]
        with a[1].lock:
            a[0].get_text("text").insert(0, "[A0]")
        with b[1].lock:
            b[0].get_text("text").insert(0, "[B0]")
        _wait_equal(pairs, require=("[A0]", "[B0]"))

        owner = sup.owner_of(room)
        pid = sup._shards[owner].pid
        assert pid is not None

        # an edit right before the kill: its frame is acked only once
        # the shard durably holds it, so either it lands in the WAL and
        # survives the replay, or it stays unacked in the session
        # outbox and retransmits after the restart — never lost
        with a[1].lock:
            a[0].get_text("text").insert(0, "[A-preckill]")
        os.kill(pid, signal.SIGKILL)

        # edits DURING the outage from both sides: the gateway answers
        # BUSY (shard mid-restart) and the sessions hold + retransmit
        with a[1].lock:
            a[0].get_text("text").insert(0, "[A-outage]")
        with b[1].lock:
            b[0].get_text("text").insert(0, "[B-outage]")

        report = _wait_outcome(sup, "recovered")
        ev = report["events"][0]
        assert ev["shard"] == owner
        assert ev["outcome"] == "recovered"
        assert ev["unavailable_s"] > 0
        assert report["epoch"] >= 1
        # the restarted child replayed its WAL (the pre-kill edits were
        # flushed durably before their frames were acked)
        assert "records_applied" in (ev.get("recovery") or {})

        final = _wait_equal(
            pairs,
            require=("[A0]", "[B0]", "[A-preckill]",
                     "[A-outage]", "[B-outage]"),
        )
        # identical CRDT state on both peers, not just equal text (the
        # sv map is key-order-agnostic on the wire, so compare decoded)
        with a[1].lock:
            sv_a = Y.decode_state_vector(Y.encode_state_vector(a[0]))
        with b[1].lock:
            sv_b = Y.decode_state_vector(Y.encode_state_vector(b[0]))
        assert sv_a == sv_b

        # the cluster's own copy agrees with the peers (retry while the
        # routed shard finishes settling)
        deadline = time.time() + 30
        cluster_text = None
        while time.time() < deadline:
            try:
                cluster_text = sup.text(room)
                if cluster_text == final:
                    break
            except (RpcBusy, RpcError):
                pass
            time.sleep(0.1)
        assert cluster_text == final

        # ≤ 1 full resync per surviving session, and nothing acked was
        # dropped: outboxes drain to empty once the shard is back
        for doc, conn in pairs:
            with conn.lock:
                snap = conn.session.snapshot()
            assert snap["full_resyncs"] <= 1, snap
        deadline = time.time() + 30
        while time.time() < deadline:
            depths = []
            for doc, conn in pairs:
                with conn.lock:
                    depths.append(conn.session.snapshot()["outbox_depth"])
            if depths == [0, 0]:
                break
            time.sleep(0.1)
        assert depths == [0, 0], f"undrained outboxes: {depths}"

        # the monitor's periodic file drop federated through the kill:
        # per-shard snapshots + the cluster report ytpu_top tails
        deadline = time.time() + 15
        while time.time() < deadline:
            if os.path.exists(os.path.join(snap_dir, "cluster.json")):
                break
            time.sleep(0.1)
        assert os.path.exists(os.path.join(snap_dir, "cluster.json"))
        assert any(
            name.startswith("shard-") and name.endswith(".json")
            for name in os.listdir(snap_dir)
        )
    finally:
        for doc, conn in pairs:
            conn.close()
        gw.close()
        sup.close()


def test_failover_promotes_replica_past_restart_budget(tmp_path):
    """With a zero restart budget a SIGKILL is a permanent loss: the
    ring successor's journal-only replica records materialize via a
    recover-restart and the room rehomes — text survives the shard."""
    sup = Supervisor(
        3, str(tmp_path / "wal"), docs_per_shard=8,
        config=ClusterConfig(restart_max=0, **FAST),
    ).start()
    try:
        room = "failover-room"
        doc = Y.Doc(gc=False)
        doc.client_id = 9
        doc.get_text("text").insert(0, "survives the shard")
        assert sup.receive_update(room, Y.encode_state_as_update(doc))
        sup.flush(room)
        assert sup.text(room) == "survives the shard"

        owner = sup.owner_of(room)
        replica = sup.replica_of(room)
        assert replica is not None and replica != owner
        os.kill(sup._shards[owner].pid, signal.SIGKILL)

        report = _wait_outcome(sup, "failover")
        ev = report["events"][0]
        assert ev["outcome"] == "failover"
        assert ev["shard"] == owner
        assert ev["promoted"] >= 1
        assert report["shards"][owner]["state"] == "lost"
        assert report["epoch"] >= 1

        new_owner = sup.owner_of(room)
        assert new_owner != owner
        deadline = time.time() + 30
        text = None
        while time.time() < deadline:
            try:
                text = sup.text(room)
                break
            except (RpcBusy, RpcError):
                time.sleep(0.1)
        assert text == "survives the shard"

        # post-failover writes land on the promoted owner
        doc.get_text("text").insert(0, "and keeps going: ")
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                assert sup.receive_update(
                    room, Y.encode_state_as_update(doc)
                )
                break
            except (RpcBusy, RpcError):
                time.sleep(0.1)
        assert sup.text(room) == "and keeps going: survives the shard"
    finally:
        sup.close()


def test_hung_shard_convicted_by_heartbeat_probe(tmp_path):
    """A shard whose process is alive and socket open but which stopped
    serving (here: SIGSTOP) is invisible to ``proc.poll()`` and
    ``client.alive`` — only the monitor's heartbeat probe can convict
    it.  Two unanswered probes must force a restart-through-recover."""
    sup = Supervisor(
        2, str(tmp_path / "wal"), docs_per_shard=8,
        config=ClusterConfig(probe_timeout_s=0.5, **FAST),
    ).start()
    hung_pid = None
    try:
        room = "hang-room"
        doc = Y.Doc(gc=False)
        doc.client_id = 11
        doc.get_text("text").insert(0, "before the hang")
        assert sup.receive_update(room, Y.encode_state_as_update(doc))
        sup.flush(room)

        owner = sup.owner_of(room)
        hung_pid = sup._shards[owner].pid
        os.kill(hung_pid, signal.SIGSTOP)

        report = _wait_outcome(sup, "recovered")
        ev = report["events"][0]
        assert ev["shard"] == owner
        assert ev["outcome"] == "recovered"
        # the replacement serves the room again, WAL replayed
        deadline = time.time() + 30
        text = None
        while time.time() < deadline:
            try:
                text = sup.text(room)
                break
            except (RpcBusy, RpcError):
                time.sleep(0.1)
        assert text == "before the hang"
        assert sup._shards[owner].pid != hung_pid
    finally:
        if hung_pid is not None:
            try:
                os.kill(hung_pid, signal.SIGKILL)
            except OSError:
                pass
        sup.close()


def test_spawn_ready_timeout_kills_silent_child(tmp_path):
    """A child that starts but never prints its ready line must fail
    the spawn at ``spawn_timeout_s`` — not block the caller forever
    (during a restart the caller is the monitor thread, i.e. all
    supervision) — and must not leak the process."""
    import subprocess

    sup = Supervisor(
        1, str(tmp_path / "wal"),
        config=ClusterConfig(spawn_timeout_s=0.5, **FAST),
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        stdout=subprocess.PIPE, text=True,
    )
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="timed out"):
        sup._read_ready(proc)
    assert time.monotonic() - t0 < 10.0
    assert proc.poll() is not None  # killed, not leaked

    # and a child that dies before ready reports its exit code
    proc = subprocess.Popen(
        [sys.executable, "-c", "raise SystemExit(3)"],
        stdout=subprocess.PIPE, text=True,
    )
    with pytest.raises(RuntimeError, match="exited before ready"):
        sup._read_ready(proc)


def test_supervisor_facade_and_federated_metrics(tmp_path):
    """The FleetRouter-shaped facade over RPC: sv/diff/text round-trip,
    and the federated snapshot carries every shard's families plus the
    supervisor's own cluster gauges."""
    sup = Supervisor(
        2, str(tmp_path / "wal"), docs_per_shard=8,
        config=ClusterConfig(**FAST),
    ).start()
    try:
        doc = Y.Doc(gc=False)
        doc.client_id = 5
        doc.get_text("text").insert(0, "facade")
        assert sup.receive_update("room-f", Y.encode_state_as_update(doc))
        assert sup.text("room-f") == "facade"

        sv = sup.state_vector_bytes("room-f")
        assert sv and sv != b"\x00"
        diff = sup.diff_update("room-f", b"\x00")
        probe = Y.Doc()
        Y.apply_update(probe, diff)
        assert probe.get_text("text").to_string() == "facade"
        # a caught-up peer gets an empty-ish diff, not the full doc
        assert len(sup.diff_update("room-f", sv)) < len(diff)

        snap = sup.metrics_snapshot()
        assert snap["federation"]["sources"], snap["federation"]
        names = set(snap["counters"]) | set(snap["gauges"])
        # every shard's engine families federate, and the supervisor's
        # own process-global cluster families layer in
        assert any(n.startswith("ytpu_cluster_") for n in names), names
        assert any(n.startswith("ytpu_") and "cluster" not in n
                   for n in names), names
    finally:
        sup.close()


# -- satellite 2: FleetRouter.recovery_report + ytpu_top --cluster ------------


def test_fleet_recovery_report_matches_supervisor_shape(tmp_path):
    """The in-process fleet reports recovery outcomes in the SAME
    structured shape the supervisor emits, so one renderer serves
    both (``ytpu_top --cluster``)."""
    from yjs_tpu.fleet import FleetRouter

    wal = str(tmp_path / "fleet")
    fleet = FleetRouter(
        n_shards=2, docs_per_shard=8, backend="cpu", wal_dir=wal
    )
    doc = Y.Doc(gc=False)
    doc.client_id = 3
    doc.get_text("text").insert(0, "fleet doc")
    fleet.receive_update("room-r", Y.encode_state_as_update(doc))
    fleet.flush()
    fresh = fleet.recovery_report()
    assert fresh["kind"] == "fleet"
    assert fresh["outcomes"] == {"recovered": 0, "failover": 0}
    assert all(r["outcome"] == "fresh" for r in fresh["shards"])
    fleet.close()

    recovered = FleetRouter.recover(wal, docs_per_shard=8, backend="cpu")
    report = recovered.recovery_report()
    try:
        assert report["kind"] == "fleet"
        assert report["outcomes"]["recovered"] >= 1
        for key in ("epoch", "shards", "events", "outcomes", "resolution"):
            assert key in report
        for kind in ("completed", "aborted", "fenced"):
            assert kind in report["resolution"]
        row = report["shards"][0]
        for key in ("shard", "state", "pid", "port", "restarts",
                    "outcome", "records_applied"):
            assert key in row
        assert any(
            r["records_applied"] >= 1 for r in report["shards"]
        ), report["shards"]
    finally:
        recovered.close()


def _load_script(name):
    root = Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        name, root / "scripts" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_ytpu_top_cluster_mode_renders_supervision_panel(tmp_path):
    top = _load_script("ytpu_top")
    report = {
        "kind": "cluster",
        "epoch": 2,
        "shards": [
            {"shard": 0, "state": "live", "pid": 41, "port": 9001,
             "restarts": 0, "outcome": "fresh", "records_applied": 0},
            {"shard": 1, "state": "lost", "pid": 42, "port": 9002,
             "restarts": 3, "outcome": "recovered",
             "records_applied": 17},
        ],
        "events": [{"shard": 1, "outcome": "failover", "epoch": 2,
                    "unavailable_s": 1.25,
                    "resolution": {"completed": 0, "aborted": 0,
                                   "fenced": 1}}],
        "outcomes": {"recovered": 0, "failover": 1},
        "resolution": {"completed": 0, "aborted": 0, "fenced": 1},
    }
    (tmp_path / "cluster.json").write_text(json.dumps(report))
    (tmp_path / "shard-000.json").write_text(
        json.dumps({"counters": {}, "gauges": {}, "histograms": {}})
    )
    out = io.StringIO()
    top.run_plain(
        top.ClusterDirSource(str(tmp_path)),
        interval=0.01, iterations=1, out=out,
    )
    frame = out.getvalue()
    assert "cluster epoch 2" in frame
    assert "failover" in frame and "recovered" in frame
    assert "unavailable=1.25s" in frame
    # cluster.json is the panel, NOT a shard row; shard-000 federates
    assert "CLUSTER" in frame and "shard-000" in frame
    lines = [ln for ln in frame.splitlines() if ln.startswith("cluster")]
    assert lines, frame
    # an empty dir (report not dumped yet) renders a placeholder panel
    empty = tmp_path / "empty"
    empty.mkdir()
    src = top.ClusterDirSource(str(empty))
    assert "no cluster.json" in src.header()


def test_cluster_launcher_parses_compose_shaped_config():
    """`scripts/ytpu_cluster.py --config` speaks the docker-compose
    shape: replicas -> shard count, published port -> gateway port,
    environment in both map and KEY=VALUE-list form."""
    launcher = _load_script("ytpu_cluster")
    got = launcher.parse_compose({
        "services": {
            "shard": {
                "deploy": {"replicas": 5},
                "environment": {"YTPU_CLUSTER_HEARTBEAT_S": "0.15"},
            },
            "gateway": {
                "ports": ["8765:8765"],
                "environment": ["YTPU_GATEWAY_TICK_S=0.01"],
            },
        }
    })
    assert got["shards"] == 5
    assert got["gateway_port"] == 8765
    assert got["env"] == {
        "YTPU_CLUSTER_HEARTBEAT_S": "0.15",
        "YTPU_GATEWAY_TICK_S": "0.01",
    }
    # irrelevant compose content (volumes, extra services) is ignored
    assert launcher.parse_compose({"services": {"redis": {}}}) == {
        "shards": None, "gateway_port": None, "env": {},
    }


def test_cluster_launcher_smoke_round_trips_an_edit(tmp_path):
    """The CI probe: launch 1 shard + gateway from a compose-shaped
    config file, push one edit through the session dialect, verify it
    server-side, exit 0."""
    import subprocess

    cfg = tmp_path / "cluster.json"
    cfg.write_text(json.dumps({
        "services": {
            "shard": {
                "deploy": {"replicas": 1},
                "environment": {"YTPU_CLUSTER_HEARTBEAT_S": "0.15"},
            },
            "gateway": {"ports": ["0:0"]},
        }
    }))
    root = Path(__file__).resolve().parent.parent
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, str(root / "scripts" / "ytpu_cluster.py"),
         "--config", str(cfg), "--smoke",
         "--wal-root", str(tmp_path / "wal")],
        capture_output=True, text=True, timeout=120, env=env, cwd=str(root),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "smoke: OK" in proc.stdout
    assert "1 shard(s) up" in proc.stdout
