"""Export-contract parity with the reference public API (VERDICT r4
item 10): every name the reference exports from src/index.js:2-76 must
exist on ``yjs_tpu`` under the same (camelCase/JS) name.  The list is
parsed from the reference source itself so drift is impossible.

Documented deviations (asserted below so they stay deliberate):
- none — the full list resolves.  AbstractStruct is a stateless exported
  base that GC/Item genuinely subclass (core.py absorbs the reference's
  two concrete call paths into the subclasses; the base carries the
  contract).
"""

import re
from pathlib import Path

import pytest

import yjs_tpu as Y

_REF_INDEX = Path("/root/reference/src/index.js")


def _reference_exports() -> list[str]:
    src = _REF_INDEX.read_text()
    block = re.search(r"export\s*\{(.*?)\}", src, re.S).group(1)
    names = []
    for raw in block.split(","):
        raw = raw.split("//")[0].strip()  # strip trailing line comments
        if not raw:
            continue
        m = re.match(r"(\w+)(?:\s+as\s+(\w+))?$", raw)
        assert m, f"unparsed export entry: {raw!r}"
        names.append(m.group(2) or m.group(1))
    return names


@pytest.mark.skipif(not _REF_INDEX.exists(), reason="reference not present")
def test_reference_export_contract():
    names = _reference_exports()
    assert len(names) >= 70  # sanity: the whole list parsed
    missing = [n for n in names if not hasattr(Y, n)]
    assert not missing, f"exports missing vs reference index.js: {missing}"


def test_abstract_struct_is_the_real_base():
    assert issubclass(Y.Item, Y.AbstractStruct)
    assert issubclass(Y.GC, Y.AbstractStruct)
    # the base is stateless: subclass layouts are unchanged
    assert Y.AbstractStruct.__slots__ == ()


def test_js_type_aliases_are_identities():
    assert Y.Array is Y.YArray
    assert Y.Map is Y.YMap
    assert Y.Text is Y.YText
    assert Y.XmlText is Y.YXmlText
    assert Y.XmlElement is Y.YXmlElement
    assert Y.XmlFragment is Y.YXmlFragment
    assert Y.XmlHook is Y.YXmlHook


def test_create_delete_set_roundtrip():
    ds = Y.createDeleteSet()
    assert ds.clients == {}
    Y.add_to_delete_set(ds, 1, 0, 3)
    assert Y.is_deleted(ds, Y.createID(1, 2))
    assert not Y.is_deleted(ds, Y.createID(1, 3))
