"""Network-chaos property suite (ISSUE 5): three peers in a full
session mesh, every link independently faulted (drop / duplicate /
delay / reorder / partition at the transport seam), edits streaming
WHILE the faults fire.  The contract under any mix and any seed:

- all three replicas end byte-identically (text + state vector, and
  each peer's full state is a strict no-op on the others);
- nobody falls back to a full resync after the initial handshake —
  recovery is retransmission + anti-entropy, never "send everything"
  (``n_full_resyncs == 1`` and ``n_resumes == 0`` per session, the
  ISSUE 5 acceptance shape);
- loss shows up in the loss counters (retransmits / repairs), not in
  the document.

Everything is tick-driven and seeded — a failure replays exactly.  The
``network`` marker deselects the suite with ``-m 'not network'``.
"""

import random

import pytest

import yjs_tpu as Y
from yjs_tpu.provider import TpuProvider
from yjs_tpu.resilience import NetChaosConfig, NetworkFaultInjector
from yjs_tpu.sync.session import DocSessionHost, SessionConfig, SyncSession
from yjs_tpu.sync.transport import PipeNetwork
from yjs_tpu.updates import (
    apply_update,
    decode_state_vector,
    encode_state_as_update,
    encode_state_vector,
)

pytestmark = pytest.mark.network


@pytest.fixture(autouse=True)
def _pin_sid_counter(monkeypatch):
    # session sids draw from a module-global counter, and each
    # session's retransmit-backoff rng is seeded with (seed ^ sid) —
    # so the storm's jitter sequences silently depend on how many
    # sessions every EARLIER test in the suite created.  Pin the
    # counter per test so a failure replays identically in any order.
    import itertools

    from yjs_tpu.sync import session as session_mod

    monkeypatch.setattr(session_mod, "_SID", itertools.count(1))

# the chaos-suite corpus (test_chaos.py) plus a fresh spread — the
# acceptance matrix runs the full storm over 20 seeds
CORPUS_SEEDS = (101, 202, 55, 77)
STORM_SEEDS = tuple(range(20))

FAULT_MIXES = [
    ("drop", dict(drop=0.25)),
    ("dup", dict(duplicate=0.35)),
    ("delay", dict(delay=0.5)),
    ("reorder", dict(reorder=0.6)),
    ("partition", dict(partition=0.08)),
]
STORM = dict(drop=0.2, duplicate=0.2, delay=0.25, reorder=0.3,
             partition=0.04)

# retransmission must out-run the worst fault window, and anti-entropy
# must close any dead-letter hole well inside the round budget
MESH_CONFIG = dict(
    retry_base=4, retry_cap=16, retry_max=6, retry_jitter=0.25,
    antientropy=8, heartbeat=0, liveness=0, hello_timeout=0,
)


class MeshPeer:
    """One replica: a Doc plus one session per neighbor.  Local edits
    fan out to every session; applied remote updates gossip onward to
    the OTHER neighbors (the origin guard stops echo; redundant applies
    are no-ops and fire no update event, so gossip cannot loop)."""

    def __init__(self, name: str, client_id: int, seed: int):
        self.name = name
        self.doc = Y.Doc(gc=False)
        self.doc.client_id = client_id
        self.sessions: dict[str, SyncSession] = {}
        self._gen = random.Random((seed << 4) ^ client_id)
        self.doc.on("update", self._relay)

    def link(self, other: str, cfg: SessionConfig) -> SyncSession:
        s = SyncSession(DocSessionHost(self.doc), cfg, peer=other)
        self.sessions[other] = s
        return s

    def _relay(self, update, origin, doc):
        for s in self.sessions.values():
            if origin is not s.host:
                s.send_update(bytes(update))

    def maybe_edit(self) -> None:
        if self._gen.random() >= 0.25:
            return
        t = self.doc.get_text("text")
        if len(t) and self._gen.random() < 0.3:
            t.delete(self._gen.randrange(len(t)), 1)
        else:
            t.insert(
                self._gen.randrange(len(t) + 1),
                self._gen.choice("abcdef "),
            )

    @property
    def text(self) -> str:
        return str(self.doc.get_text("text"))

    @property
    def sv(self) -> dict:
        return dict(decode_state_vector(encode_state_vector(self.doc)))


def build_mesh(seed: int, faults: dict):
    cfg = SessionConfig(seed=seed, **MESH_CONFIG)
    peers = [
        MeshPeer("A", 1, seed), MeshPeer("B", 2, seed),
        MeshPeer("C", 3, seed),
    ]
    nets = []
    for i, (pa, pb) in enumerate(
        [(peers[0], peers[1]), (peers[0], peers[2]),
         (peers[1], peers[2])]
    ):
        inj = (
            NetworkFaultInjector(
                NetChaosConfig(seed=(seed * 31 + i) & 0x7FFFFFFF,
                               **faults)
            )
            if faults
            else None
        )
        net = PipeNetwork(inj)
        ta, tb = net.pair(pa.name, pb.name)
        pa.link(pb.name, cfg).connect(ta)
        pb.link(pa.name, cfg).connect(tb)
        nets.append(net)
    return peers, nets


def run_mesh(peers, nets, edit_rounds=120, max_rounds=2500, quiet=6):
    """Drive the whole mesh tick-by-tick: edits stream during the
    first ``edit_rounds`` while faults fire, then the loop runs until
    text AND state vector agree across all three replicas for
    ``quiet`` consecutive rounds (sv catches undelivered inserts, text
    catches undelivered deletes — together a stable fixpoint)."""
    sessions = [s for p in peers for s in p.sessions.values()]
    stable = 0
    for n in range(max_rounds):
        if n < edit_rounds:
            for p in peers:
                p.maybe_edit()
        for net in nets:
            net.pump()
        for s in sessions:
            s.tick()
        if n >= edit_rounds:
            if (
                len({p.text for p in peers}) == 1
                and peers[0].sv == peers[1].sv == peers[2].sv
            ):
                stable += 1
                if stable >= quiet:
                    return n
            else:
                stable = 0
    return max_rounds


def assert_mesh_identical(peers):
    texts = {p.text for p in peers}
    assert len(texts) == 1, f"diverged: {[p.text for p in peers]}"
    assert peers[0].sv == peers[1].sv == peers[2].sv
    # byte-level: each replica's full state is a strict no-op elsewhere
    for src in peers:
        full = encode_state_as_update(src.doc)
        for dst in peers:
            if dst is src:
                continue
            before = dst.text
            apply_update(dst.doc, full)
            assert dst.text == before


def assert_no_full_resyncs(peers):
    """The ISSUE 5 acceptance: after the initial handshake, recovery
    is always delta-shaped — no session ever restarts from scratch."""
    for p in peers:
        for s in p.sessions.values():
            assert s.n_full_resyncs == 1, (p.name, s.peer, s.snapshot())
            assert s.n_resumes == 0, (p.name, s.peer, s.snapshot())


@pytest.mark.parametrize("seed", STORM_SEEDS)
def test_three_peer_storm_converges(seed):
    peers, nets = build_mesh(seed, STORM)
    rounds = run_mesh(peers, nets)
    assert rounds < 2500, "mesh never reached a stable fixpoint"
    assert_mesh_identical(peers)
    assert_no_full_resyncs(peers)
    assert any(p.text for p in peers) or True  # content is seed-driven


@pytest.mark.parametrize("name,faults", FAULT_MIXES,
                         ids=[m[0] for m in FAULT_MIXES])
@pytest.mark.parametrize("seed", CORPUS_SEEDS)
def test_three_peer_single_fault_mix_converges(seed, name, faults):
    peers, nets = build_mesh(seed, faults)
    rounds = run_mesh(peers, nets)
    assert rounds < 2500, f"{name} mesh never stabilized"
    assert_mesh_identical(peers)
    assert_no_full_resyncs(peers)
    if name == "drop":
        # loss must surface in the loss counters, not the document
        total_rtx = sum(
            s.n_retransmits for p in peers
            for s in p.sessions.values()
        )
        total_repairs = sum(
            s.n_repairs for p in peers for s in p.sessions.values()
        )
        assert total_rtx + total_repairs >= 1


def test_clean_mesh_has_no_recovery_traffic():
    peers, nets = build_mesh(7, {})
    run_mesh(peers, nets, edit_rounds=60, max_rounds=800)
    assert_mesh_identical(peers)
    assert_no_full_resyncs(peers)
    for p in peers:
        for s in p.sessions.values():
            assert s.n_dead_lettered == 0
            assert s.n_retransmits == 0  # acks beat every backoff
            # (n_repairs may be nonzero even on a clean wire: a digest
            # can race an in-flight update — the repair is idempotent)


# -- provider-level regression pins ------------------------------------------


def _quiet_cfg():
    return SessionConfig(
        heartbeat=0, liveness=0, antientropy=0, hello_timeout=0,
        retry_base=4, retry_jitter=0.0, seed=1,
    )


def _drive(*providers):
    def fn():
        for p in providers:
            p.flush()
        for p in providers:
            p.tick_sessions()

    return fn


def test_reconnect_mid_flush_replays_pending_delta():
    """Regression pin: an update received but NOT yet flushed when the
    transport dies must still reach the peer after reconnect — the
    session host flushes the room before computing the catch-up diff,
    so the delta includes pending engine state."""
    pa = TpuProvider(2, backend="cpu")
    pb = TpuProvider(2, backend="cpu")
    net = PipeNetwork()
    ta, tb = net.pair()
    sa = pa.session("room", "pb", _quiet_cfg())
    sb = pb.session("room", "pa", _quiet_cfg())
    sa.connect(ta)
    sb.connect(tb)
    net.settle((_drive(pa, pb),))
    assert sa.state == sb.state == "live"
    # land an update in the engine queue and kill the wire BEFORE any
    # flush can broadcast it
    d = Y.Doc(gc=False)
    d.get_text("text").insert(0, "pending at disconnect")
    pa.receive_update("room", encode_state_as_update(d))
    net.kill(ta, tb)
    assert sa.state == sb.state == "reconnecting"
    ta2, tb2 = net.pair()
    sa.attach(ta2)
    sb.attach(tb2)
    net.settle((_drive(pa, pb),))
    assert pb.text("room") == "pending at disconnect"
    # and it was a resume, not a second full resync
    assert sa.n_resumes == 1 and sa.n_full_resyncs == 1
    assert sb.n_resumes == 1 and sb.n_full_resyncs == 1


def test_killed_provider_catches_up_via_delta_replay(tmp_path):
    """Acceptance: a peer killed and recovered from its WAL catches up
    through delta replay — the surviving side resumes (resumes > 0)
    and never re-runs a full resync (full_resyncs stays 1)."""
    cfg = _quiet_cfg()
    p1 = TpuProvider(2, backend="cpu", wal_dir=str(tmp_path))
    p2 = TpuProvider(2, backend="cpu")
    net = PipeNetwork()
    t1, t2 = net.pair()
    p1.session("doc", "p2", cfg).connect(t1)
    s2 = p2.session("doc", "p1", cfg)
    s2.connect(t2)
    net.settle((_drive(p1, p2),))
    d = Y.Doc(gc=False)
    d.get_text("text").insert(0, "before crash")
    p2.receive_update("doc", encode_state_as_update(d))
    net.settle((_drive(p1, p2),))
    assert p1.text("doc") == "before crash"
    net.kill(t1, t2)
    del p1  # crash: no close, no checkpoint
    # the survivor keeps editing while the peer is down
    d2 = Y.Doc(gc=False)
    d2.get_text("text").insert(0, "offline edit / ")
    p2.receive_update("doc", encode_state_as_update(d2))
    pr = TpuProvider.recover(str(tmp_path), backend="cpu")
    assert pr.last_recovery["session_acks"] >= 1
    sr = pr.session("doc", "p2", cfg)  # armed with the WAL ack floor
    t1b, t2b = net.pair()
    sr.connect(t1b)
    s2.attach(t2b)
    net.settle((_drive(pr, p2),))
    assert pr.text("doc") == p2.text("doc")
    assert "offline edit" in pr.text("doc")
    assert s2.n_resumes == 1
    assert s2.n_full_resyncs == 1
