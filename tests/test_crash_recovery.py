"""Crash-point chaos harness (ISSUE 3 acceptance): kill a journaling
provider at randomized points under the full transport-fault mix, tear
and bit-flip its WAL files, recover, and require byte-identical
reconvergence with an uninterrupted reference.

Both providers consume the SAME faulted stream (one injector pass), so
any divergence is recovery's fault, not the transport's.  The crash is
``WriteAheadLog.abandon()`` — the file handle is dropped with no
seal-time fsync, leaving the directory exactly as a killed process
would.  Mid-log at-rest damage (a flipped bit in a sealed segment) must
land in the dead-letter queue, never abort the replay.
"""

from __future__ import annotations

import random

import pytest

import yjs_tpu as Y
from yjs_tpu.lib0 import encoding
from yjs_tpu.lib0.encoding import Encoder
from yjs_tpu.persistence import WalConfig, list_segments
from yjs_tpu.provider import TpuProvider
from yjs_tpu.resilience import ChaosConfig, ChaosInjector, DiskFaultInjector
from yjs_tpu.sync import protocol

pytestmark = [pytest.mark.chaos, pytest.mark.durability]

ROOM = "room"
BACKENDS = ("cpu", "auto")
# the test_chaos.py "everything" mix: every fault class at once
EVERYTHING = dict(
    corrupt=0.15, truncate=0.1, duplicate=0.25, reorder=0.6, drop=0.15
)


def client_updates(seed: int, n_ops: int = 50, n_clients: int = 3):
    """Per-op incremental updates from independent editing clients
    (same traffic texture as tests/test_chaos.py)."""
    gen = random.Random(seed)
    docs = []
    updates: list[bytes] = []
    for k in range(n_clients):
        d = Y.Doc(gc=False)
        d.client_id = 1000 + k
        d.on("update", lambda u, origin, doc: updates.append(bytes(u)))
        docs.append(d)
    for _ in range(n_ops):
        d = gen.choice(docs)
        t = d.get_text("text")
        if len(t) and gen.random() < 0.3:
            t.delete(gen.randrange(len(t)), 1)
        else:
            t.insert(gen.randrange(len(t) + 1), gen.choice("abcdef "))
    return updates


def frame(update: bytes) -> bytes:
    enc = Encoder()
    encoding.write_var_uint(enc, protocol.MESSAGE_YJS_UPDATE)
    encoding.write_var_uint8_array(enc, update)
    return enc.to_bytes()


def sync_repair(pa: TpuProvider, pb: TpuProvider, rounds: int = 5) -> None:
    """Clean bidirectional step1/step2 exchange (post-chaos heal)."""
    for _ in range(rounds):
        reply = pb.handle_sync_message(ROOM, pa.sync_step1(ROOM))
        if reply is not None:
            pa.handle_sync_message(ROOM, reply)
        reply = pa.handle_sync_message(ROOM, pb.sync_step1(ROOM))
        if reply is not None:
            pb.handle_sync_message(ROOM, reply)


def canonical(prov: TpuProvider) -> bytes:
    """merge_updates-normalized full state: equal stores yield
    IDENTICAL bytes regardless of split/arrival history."""
    return Y.merge_updates([prov.encode_state_as_update(ROOM)])


@pytest.mark.parametrize("backend", BACKENDS)
@pytest.mark.parametrize("crash_seed", range(10))
def test_crash_recover_reconverges_bytewise(backend, crash_seed, tmp_path):
    updates = client_updates(seed=400 + crash_seed)
    frames = [frame(u) for u in updates]
    # ONE injector pass feeds BOTH replicas: identical faulted stream
    inj = ChaosInjector(
        ChaosConfig(seed=crash_seed, **EVERYTHING), kind="frame"
    )
    faulted = inj.apply(frames)
    assert sum(inj.fault_counts.values()) > 0

    ref = TpuProvider(2, backend=backend)
    victim = TpuProvider(
        2,
        backend=backend,
        wal_dir=tmp_path,
        wal_config=WalConfig(segment_bytes=256, fsync="never"),
    )
    for f in faulted:
        ref.handle_sync_message(ROOM, f)

    crash_rng = random.Random(9000 + crash_seed)
    c = crash_rng.randrange(1, len(faulted))
    for k, f in enumerate(faulted[:c]):
        victim.handle_sync_message(ROOM, f)
        if k == c // 2 and k > 0:
            victim.checkpoint()  # compaction mid-life, like production
    victim.wal.abandon()  # kill -9

    # disk damage on what the dead process left behind
    disk = DiskFaultInjector(seed=7000 + crash_seed)
    segs = list_segments(tmp_path)
    flipped = False
    if segs:
        disk.tear(segs[-1][1])  # torn tail on the active segment
        if len(segs) > 1:
            flipped = disk.bitflip(segs[0][1], lo=8) >= 0

    victim = TpuProvider.recover(
        tmp_path,
        n_docs=2,
        backend=backend,
        wal_config=WalConfig(segment_bytes=256, fsync="never"),
    )
    if flipped:
        assert victim.last_recovery["corrupt_records"] >= 1
        assert any(
            d["reason"].startswith("wal-corrupt")
            for d in victim.dead_letters()
        )

    # the rest of the stream arrives at the recovered victim
    for f in faulted[c:]:
        victim.handle_sync_message(ROOM, f)

    # heal: quarantine backoff cleared (operator readmission, as in
    # test_chaos), then clean sync rounds
    ref.engine.health.reset(None)
    victim.engine.health.reset(None)
    sync_repair(ref, victim)

    assert victim.text(ROOM) == ref.text(ROOM)
    assert victim.state_vector(ROOM) == ref.state_vector(ROOM)
    assert canonical(victim) == canonical(ref)
