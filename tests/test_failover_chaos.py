"""Failover chaos suite (ISSUE 8 acceptance): primaries are killed at
the nastiest moments — mid-flush with the engine queue dirty and the
replication outbox undrained, and inside the checkpoint window right
after WAL compaction folded the replica history — and a three-peer
session mesh rides through a primary kill under the full
``YTPU_CHAOS_NET_*`` fault mix (drop / duplicate / delay / reorder /
partition) across 20 seeds.

The contract everywhere: byte-identical convergence against
uninterrupted reference docs, zero acknowledged-update loss, exactly
one owner per doc after promotion, and no session ever falls back to a
second full resync (``n_full_resyncs == 1``).

Deterministic end to end: seeded edits, seeded fault injectors, a
jitter-free detector config so conviction lands on an exact tick.
"""

import random

import pytest

import yjs_tpu as Y
from yjs_tpu.fleet import FailoverConfig, FleetRouter
from yjs_tpu.persistence import WalConfig
from yjs_tpu.provider import TpuProvider
from yjs_tpu.resilience import NetChaosConfig, NetworkFaultInjector
from yjs_tpu.sync.session import SessionConfig
from yjs_tpu.sync.transport import PipeNetwork
from yjs_tpu.updates import (
    apply_update,
    encode_state_as_update,
    encode_state_vector,
)

pytestmark = [
    pytest.mark.failover, pytest.mark.fleet, pytest.mark.chaos,
]

SMALL = WalConfig(segment_bytes=256, fsync="never")
FAST = FailoverConfig(suspect_ticks=2, confirm_ticks=1, jitter_ticks=0)

# the full fault mix from the network-chaos acceptance matrix, and the
# same 20-seed spread
STORM = dict(drop=0.2, duplicate=0.2, delay=0.25, reorder=0.3,
             partition=0.04)
STORM_SEEDS = tuple(range(20))

MESH_CONFIG = dict(
    retry_base=4, retry_cap=16, retry_max=6, retry_jitter=0.25,
    antientropy=8, heartbeat=0, liveness=0, hello_timeout=0,
)


def seeded_rooms(seed, n_rooms=6, n_ops=10):
    out = {}
    for j in range(n_rooms):
        gen = random.Random(seed * 1000 + j)
        d = Y.Doc(gc=False)
        d.client_id = 100 + j
        updates = []
        d.on("update", lambda u, origin, doc: updates.append(bytes(u)))
        t = d.get_text("text")
        for _ in range(n_ops):
            if len(t) and gen.random() < 0.3:
                t.delete(gen.randrange(len(t)), 1)
            else:
                t.insert(gen.randrange(len(t) + 1), gen.choice("abcdef "))
        out[f"room-{j}"] = (d, updates)
    return out


def edit(doc, text, pos=0):
    sv = encode_state_vector(doc)
    doc.get_text("text").insert(pos, text)
    return encode_state_as_update(doc, sv)


def canonical(fleet, guid):
    return Y.merge_updates([fleet.encode_state_as_update(guid)])


def canonical_doc(doc):
    return Y.merge_updates([encode_state_as_update(doc)])


def slot_owners(fleet):
    out = {}
    for k, p in enumerate(fleet.shards):
        if fleet._is_stub(k):
            continue
        for g in p.guids():
            out.setdefault(g, []).append(k)
    return out


def convict(fleet, shard, budget=16):
    for _ in range(budget):
        fleet.tick()
        if shard in fleet._down:
            return
    raise AssertionError(f"shard {shard} never convicted")


def test_kill_primary_mid_flush_loses_nothing(tmp_path):
    """The primary dies with acknowledged updates still sitting in its
    engine queue (never flushed) and in the replication outbox (never
    drained).  Acknowledged means durable: promotion must surface every
    one of them from the synchronous absorb / queued-outbox paths."""
    fleet = FleetRouter(
        3, 4, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
        failover_config=FAST,
    )
    rooms = seeded_rooms(seed=11)
    for g, (_d, ups) in rooms.items():
        for u in ups:
            fleet.receive_update(g, u)
    fleet.flush()
    fleet.tick()  # replica copies seeded
    victim = fleet.owner_of("room-0")
    owned = [g for g in rooms if fleet.owner_of(g) == victim]
    assert owned
    # a fresh acked tail per owned doc: engine queue dirty, outbox
    # undrained — then the machine dies before any flush or tick
    for g in owned:
        fleet.receive_update(g, edit(rooms[g][0], "tail!"))
    fleet.kill_shard(victim)
    convict(fleet, victim)
    for g, (d, _ups) in rooms.items():
        assert fleet.owner_of(g) is not None
        assert canonical(fleet, g) == canonical_doc(d), g
    assert all(len(v) == 1 for v in slot_owners(fleet).values())
    # and the survivors keep taking traffic
    g = owned[0]
    fleet.receive_update(g, edit(rooms[g][0], "post-failover "))
    assert canonical(fleet, g) == canonical_doc(rooms[g][0])


def test_kill_primary_during_checkpoint_window(tmp_path):
    """WAL compaction folds only owned docs — a primary killed right
    inside the checkpoint window (replica history just compacted away,
    one more acked edit in flight) must still promote losslessly from
    the reseeded replica state plus the undrained outbox."""
    fleet = FleetRouter(
        3, 4, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
        failover_config=FAST,
    )
    rooms = seeded_rooms(seed=12)
    for g, (_d, ups) in rooms.items():
        for u in ups:
            fleet.receive_update(g, u)
    fleet.flush()
    fleet.tick()
    fleet.checkpoint()  # compacts every WAL, reseeds every replica pair
    victim = fleet.owner_of("room-0")
    owned = [g for g in rooms if fleet.owner_of(g) == victim]
    # one acked edit lands between the checkpoint and the crash
    fleet.receive_update(
        "room-0", edit(rooms["room-0"][0], "in the window ")
    )
    fleet.kill_shard(victim)
    convict(fleet, victim)
    for g, (d, _ups) in rooms.items():
        assert canonical(fleet, g) == canonical_doc(d), g
    assert all(len(v) == 1 for v in slot_owners(fleet).values())
    assert "in the window" in fleet.text("room-0")
    # a re-crash after the failover replays to the same single owner
    for k, p in enumerate(fleet.shards):
        if not fleet._is_stub(k):
            p.wal.abandon()
    owners = {g: fleet.owner_of(g) for g in rooms}
    del fleet
    rec = FleetRouter.recover(tmp_path, backend="cpu", wal_config=SMALL)
    for g, (d, _ups) in rooms.items():
        assert rec.owner_of(g) == owners[g]
        assert canonical(rec, g) == canonical_doc(d), g


# -- the 20-seed storm matrix ------------------------------------------------


def _storm_mesh(seed: int, tmp_path):
    """Fleet + two peer providers in a full session mesh, every link
    faulted with the storm mix."""
    cfg = SessionConfig(seed=seed, **MESH_CONFIG)
    fleet = FleetRouter(
        3, 2, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
        failover_config=FAST,
    )
    pa = TpuProvider(1, backend="cpu")
    pb = TpuProvider(1, backend="cpu")
    nets, sessions = [], []
    links = [
        (fleet, "fleet", pa, "A"),
        (fleet, "fleet", pb, "B"),
        (pa, "A", pb, "B"),
    ]
    for i, (x, xn, y, yn) in enumerate(links):
        inj = NetworkFaultInjector(
            NetChaosConfig(seed=(seed * 31 + i) & 0x7FFFFFFF, **STORM)
        )
        net = PipeNetwork(inj)
        tx, ty = net.pair(xn, yn)
        sx = x.session("room", yn, cfg)
        sy = y.session("room", xn, cfg)
        sx.connect(tx)
        sy.connect(ty)
        nets.append(net)
        sessions += [sx, sy]
    return fleet, pa, pb, nets, sessions


@pytest.mark.parametrize("seed", STORM_SEEDS)
def test_storm_mesh_survives_primary_kill(seed, tmp_path):
    fleet, pa, pb, nets, sessions = _storm_mesh(seed, tmp_path)
    gen = random.Random(seed)
    # three uninterrupted reference editors, one per replica
    refs = {}
    for name, cid in (("fleet", 1), ("A", 2), ("B", 3)):
        d = Y.Doc(gc=False)
        d.client_id = cid
        refs[name] = d
    targets = {"fleet": fleet, "A": pa, "B": pb}
    all_updates = []

    def maybe_edit(name):
        if gen.random() >= 0.35:
            return
        d = refs[name]
        u = edit(d, gen.choice("abcdef "), gen.randrange(
            len(str(d.get_text("text"))) + 1
        ))
        # acked on return: the storm may not lose it, failover may not
        # lose it
        targets[name].receive_update("room", u)
        all_updates.append(u)

    def pump_all():
        for net in nets:
            net.pump()
        fleet.tick()
        for p in (pa, pb):
            p.flush()
            p.tick_sessions()

    edit_rounds, killed = 40, False
    stable, victim = 0, None
    for n in range(1500):
        if n < edit_rounds:
            for name in ("fleet", "A", "B"):
                maybe_edit(name)
        if n == 15:
            # the primary dies mid-storm with edits still streaming
            victim = fleet.owner_of("room")
            if victim is not None:
                fleet.kill_shard(victim)
                killed = True
        pump_all()
        if n >= edit_rounds:
            texts = {fleet.text("room"), pa.text("room"), pb.text("room")}
            if len(texts) == 1 and all(
                s.state == "live" for s in sessions
            ):
                stable += 1
                if stable >= 6:
                    break
            else:
                stable = 0
    assert killed and victim in fleet._down
    assert stable >= 6, "mesh never reached a live, converged fixpoint"
    # byte-identical across all three replicas
    assert fleet.text("room") == pa.text("room") == pb.text("room")
    # zero acknowledged-update loss: the merged reference stream IS the
    # converged state
    expected = Y.Doc(gc=False)
    apply_update(expected, Y.merge_updates(all_updates))
    assert fleet.text("room") == str(expected.get_text("text"))
    # recovery was retransmission + rehome, never a second full resync
    for s in sessions:
        assert s.n_full_resyncs == 1, (seed, s.peer, s.snapshot())
