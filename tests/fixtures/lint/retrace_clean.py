"""Known-clean: dynamic sizes flow through a bucketing helper before
reaching the jitted kernel."""
from functools import partial

import jax
import jax.numpy as jnp


def _bucket(n):
    return max(8, 1 << max(0, n - 1).bit_length())


@partial(jax.jit, static_argnums=(1,))
def padded_kernel(xs, n):
    return xs


def clean_bucketed(xs, items):
    n = _bucket(len(items))
    return padded_kernel(jnp.zeros(n), _bucket(len(items)))
