"""Known-bad: the cluster's cross-process ingress seams with neither a
TraceContext, nor an SLO feed, nor a delegation to another seam — a
frame entering here is invisible to causal tracing and never counts
against the convergence objective."""


class Shard:
    def handle_rpc_request(self, method, payload, ctx):  # BAD
        self.log.append((method, payload))
        return {"ok": True}


class GatewayConn:
    def handle_client_message(self, data):  # BAD
        self.frames.append(bytes(data))
