"""Known-bad: an attribute written under the class lock is read
lock-free from another method — the torn-scrape race."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def add(self, x):
        with self._lock:
            self._items.append(x)

    def peek(self):
        return self._items[-1]  # BAD: guarded attr read without the lock
