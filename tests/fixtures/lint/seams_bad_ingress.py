"""Known-bad: an ingress seam that neither establishes a TraceContext
nor feeds the SLO pipeline nor delegates to another seam."""


class Shard:
    def receive_update(self, update):  # BAD: no trace, no slo, no delegate
        self.log.append(update)
        return True
