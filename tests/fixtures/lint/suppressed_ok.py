"""Suppression fixture: a real donation finding silenced by a reasoned
inline disable — deleting the comment must reproduce it."""
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(1,))
def step(statics, dyn):
    return dyn


def intentional_probe(statics, dyn):
    out = step(statics, dyn)
    probe = dyn.shape  # ytpu-lint: disable=donation-aliasing -- fixture: metadata-only read, shape survives donation
    return out, probe
