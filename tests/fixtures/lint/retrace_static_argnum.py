"""Known-bad: a len()-derived value at a static_argnums position —
every distinct value is a separate compile-cache entry."""
from functools import partial

import jax


@partial(jax.jit, static_argnums=(1,))
def sized_kernel(xs, n):
    return xs


def bad_static(xs, items):
    return sized_kernel(xs, len(items))  # BAD: unbucketed static value
