"""Known-clean: every access to the guarded attribute holds the lock."""
import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def put(self, v):
        with self._lock:
            self._value = v

    def get(self):
        with self._lock:
            return self._value
