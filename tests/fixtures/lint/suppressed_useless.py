"""Suppression fixture: a disable that matches nothing — the hazard is
gone, so the comment itself is the finding."""


def add(a, b):
    return a + b  # ytpu-lint: disable=donation-aliasing -- fixture: nothing here to suppress
