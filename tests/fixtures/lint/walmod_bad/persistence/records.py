"""Known-bad WAL kinds module: KIND_ROTATE is neither mapped in
KIND_NAMES nor referenced by the recovery handler."""

KIND_UPDATE = 1
KIND_ACK = 2
KIND_ROTATE = 3

KIND_NAMES = {
    KIND_UPDATE: "update",
    KIND_ACK: "ack",
}
