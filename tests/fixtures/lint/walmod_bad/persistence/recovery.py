"""Known-bad handler: replays UPDATE and ACK but silently skips any
ROTATE record — the 3 a.m. recovery bug the lint front-loads."""

from .records import KIND_ACK, KIND_UPDATE


def replay(rec):
    if rec.kind == KIND_UPDATE:
        return "update"
    if rec.kind == KIND_ACK:
        return "ack"
    return None
