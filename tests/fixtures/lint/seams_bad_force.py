"""Known-bad: a failure-path record() attaches a trace at severity
error but the function never .force()-samples the context."""


def fail_path(recorder, ctx, err):
    recorder.record(  # BAD: trace may have been head-sampled away
        "replication",
        "mirror_failed",
        severity="error",
        trace=ctx,
        detail=str(err),
    )
