"""Suppression fixture: the disable works but carries no '-- reason',
so the runner reports bare-suppression on top."""
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(1,))
def step(statics, dyn):
    return dyn


def undocumented_probe(statics, dyn):
    out = step(statics, dyn)
    probe = dyn.shape  # ytpu-lint: disable=donation-aliasing
    return out, probe
