"""Known-clean WAL kinds module: every kind is mapped in KIND_NAMES."""

KIND_UPDATE = 1
KIND_ACK = 2
KIND_ROTATE = 3

KIND_NAMES = {
    KIND_UPDATE: "update",
    KIND_ACK: "ack",
    KIND_ROTATE: "rotate",
}
