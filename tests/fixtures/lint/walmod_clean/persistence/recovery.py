"""Known-clean handler: every declared kind is dispatched."""

from . import records


def replay(rec):
    if rec.kind == records.KIND_UPDATE:
        return "update"
    if rec.kind == records.KIND_ACK:
        return "ack"
    if rec.kind == records.KIND_ROTATE:
        return "rotate"
    return None
