"""Known-bad: reads the splatted tuple after a *args splat covered a
donated position."""
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(2,))
def fused(statics, idx, dyn):
    return dyn


def bad_splat(statics, args):
    out = fused(statics, *args)
    probe = args[1]  # BAD: the splat covered the donated position
    return out, probe
