"""Known-bad: two methods take the same two locks in opposite order —
the ABBA deadlock the ordering graph exists to catch."""
import threading


class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:
                return 1

    def backward(self):
        with self._b:
            with self._a:  # BAD: closes the a->b->a cycle
                return 2
