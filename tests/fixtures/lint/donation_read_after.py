"""Known-bad: reads a buffer after donating it to a jitted kernel."""
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(1,))
def step(statics, dyn):
    return dyn


def bad_read_after(statics, dyn):
    out = step(statics, dyn)
    return dyn.sum() + out  # BAD: dyn was donated at the call above
