"""Known-clean seams: the ingress adopts a context and feeds the SLO
pipeline; the failure path force-samples before recording; a second
ingress delegates both obligations to a routed seam."""


class Router:
    def receive_update(self, update):
        ctx = self.tracer.current_context()
        self.slo.receive(update.doc_id)
        return ctx

    def handle_sync_message(self, msg):
        return self.shards[0].receive_update(msg)


def fail_path(recorder, ctx, err):
    ctx = ctx.force("mirror_failed")
    recorder.record(
        "replication",
        "mirror_failed",
        severity="error",
        trace=ctx,
        detail=str(err),
    )
