"""Known-clean: the canonical same-statement rebind after donation."""
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(1,))
def advance(statics, dyn):
    return dyn


def clean_rebind(statics, dyn):
    dyn = advance(statics, dyn)
    return dyn
