"""Known-clean cluster seams: the RPC ingress adopts the carried
context and delegates data traffic to the provider's own seam; the
gateway ingress adopts-or-mints and routes through the cluster facade
(itself a seam), via a same-class private helper — the checker
searches helpers one level deep."""


class Shard:
    def handle_rpc_request(self, method, payload, ctx):
        with self.obs.use_context(ctx):
            return self._dispatch(method, payload)

    def _dispatch(self, method, payload):
        if method == "update":
            return self.provider.receive_update(payload["guid"],
                                                payload["update"])
        return self.provider.handle_sync_message(payload["guid"],
                                                 payload["frame"])


class GatewayConn:
    def handle_client_message(self, data):
        ctx = self.obs.current_context() or self.obs.mint_for_update(data)
        with self.obs.use_context(ctx):
            self._dispatch_client(data)

    def _dispatch_client(self, data):
        return self.cluster.handle_sync_message(self.room, data)
