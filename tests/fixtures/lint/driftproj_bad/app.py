"""Known-bad drift: reads an undocumented knob and registers an
undocumented metric family."""
import os


def setup(registry):
    wal_dir = os.environ.get("YTPU_WAL_DIR", "/tmp/wal")
    depth = int(os.environ.get("YTPU_SECRET_DEPTH", "4"))  # BAD: no README row
    flushes = registry.counter("ytpu_flush_total", "flushes", unit="flushes")
    hidden = registry.counter("ytpu_hidden_total", "BAD: no README row")
    return wal_dir, depth, flushes, hidden
