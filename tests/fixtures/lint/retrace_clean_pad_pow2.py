"""Known-clean: the segment-planner idiom (ISSUE 15) — per-call query
counts are padded up to a pow2 bucket BEFORE the jitted kernel sees
them, and the result is sliced back down, so unique-per-chunk sizes
never mint new trace signatures."""
import jax
import jax.numpy as jnp
import numpy as np


def _bucket_pow2(n, minimum=64):
    return max(minimum, 1 << max(0, int(n) - 1).bit_length())


def _pad_pow2(arr, n_pad, fill):
    out = np.full(n_pad, fill, arr.dtype)
    out[: arr.shape[0]] = arr
    return out


@jax.jit
def lookup_kernel(flat_keys, query_keys):
    return jnp.searchsorted(flat_keys, query_keys, side="right") - 1


def clean_padded_lookup(flat_keys, queries):
    nq = _bucket_pow2(len(queries))
    q = _pad_pow2(queries, nq, -1)
    ranks = lookup_kernel(jnp.asarray(flat_keys), jnp.asarray(q))
    return np.asarray(ranks)[: len(queries)]
