"""Known-bad: inline array ctor sized by a per-call length fed to a
jitted kernel — every distinct length retraces."""
import jax
import jax.numpy as jnp


@jax.jit
def kernel(xs):
    return xs


def bad_inline(items):
    return kernel(jnp.zeros(len(items)))  # BAD: unbucketed dynamic shape
