"""Known-clean drift: every knob read and metric registered has its
README row, and nothing documented is dead."""
import os


def setup(registry):
    wal_dir = os.environ.get("YTPU_WAL_DIR", "/tmp/wal")
    flushes = registry.counter("ytpu_flush_total", "flushes", unit="flushes")
    return wal_dir, flushes
