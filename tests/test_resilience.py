"""Failure isolation (ISSUE 2 tentpole): transactional per-doc flush
rollback, the health state machine, the dead-letter queue, replay, and
the validating decoder seam — including the committed corrupt fixture
set (tests/fixtures/corrupt/, scripts/gen_corrupt_fixtures.py)."""

import json
from pathlib import Path

import pytest

import yjs_tpu as Y
from yjs_tpu.ops.engine import BatchEngine
from yjs_tpu.provider import TpuProvider
from yjs_tpu.resilience import (
    DEGRADED,
    HEALTHY,
    QUARANTINED,
    DeadLetterQueue,
    HealthTracker,
)
from yjs_tpu.updates import InvalidUpdate, validate_update

FIXTURES = Path(__file__).parent / "fixtures" / "corrupt"


def _update(text="hello", client=None):
    d = Y.Doc(gc=False)
    if client is not None:
        d.client_id = client
    d.get_text("text").insert(0, text)
    return Y.encode_state_as_update(d)


# -- validate_update ---------------------------------------------------------


def test_validate_update_accepts_valid():
    info = validate_update(_update("abc"))
    assert info["structs"] >= 1
    assert info["bytes"] > 0


def test_validate_update_rejects_garbage():
    for bad in (b"", b"\xff", b"\xff\xff\xff\xff", b"\x05hello", None, "str"):
        with pytest.raises(InvalidUpdate):
            validate_update(bad)


def test_corrupt_fixtures_all_rejected():
    manifest = json.loads((FIXTURES / "manifest.json").read_text())
    assert manifest["cases"], "fixture set must not be empty"
    kinds = {c["kind"] for c in manifest["cases"]}
    assert kinds == {"bitflip", "truncation", "varint_overflow"}
    for case in manifest["cases"]:
        payload = (FIXTURES / case["file"]).read_bytes()
        assert len(payload) == case["bytes"]
        with pytest.raises(InvalidUpdate):
            validate_update(payload)
    # the uncorrupted twin is clean — the cases fail because of the
    # damage, not because the base was bad
    validate_update((FIXTURES / "valid_base.bin").read_bytes())


# -- health state machine ----------------------------------------------------


def test_health_transitions_and_backoff():
    h = HealthTracker(threshold=3, backoff_base=4, backoff_cap=16, recovery=2)
    assert h.state(7) == HEALTHY and not h.tracked
    assert h.record_failure(7, "boom") == DEGRADED
    assert h.record_failure(7, "boom") == DEGRADED
    assert h.record_failure(7, "boom") == QUARANTINED
    assert not h.admissible(7)
    for _ in range(4):
        h.tick()
    # backoff expired: lazy re-admission into degraded probation
    assert h.admissible(7)
    assert h.state(7) == DEGRADED
    # one more failure from probation re-quarantines immediately at the
    # doubled sentence (consecutive counter reset on re-admission, so it
    # takes threshold failures again)
    for _ in range(3):
        h.record_failure(7, "again")
    rec = h.record(7)
    assert rec["state"] == QUARANTINED
    assert rec["n_quarantines"] == 2
    assert rec["quarantined_until"] - h.tick_count == 8  # 4 * 2**1


def test_health_backoff_cap():
    h = HealthTracker(threshold=1, backoff_base=4, backoff_cap=16, recovery=1)
    for k in range(6):
        h.record_failure(1, "x")
        until = h.record(1)["quarantined_until"]
        assert until - h.tick_count == min(16, 4 * 2**k)
        # serve the sentence, re-admit, fail again
        while not h.admissible(1):
            h.tick()


def test_health_recovery_frees_record():
    h = HealthTracker(threshold=3, recovery=2)
    h.record_failure(5, "x")
    assert h.tracked and h.state(5) == DEGRADED
    h.record_success(5)
    assert h.tracked  # one success is not enough
    h.record_success(5)
    assert not h.tracked and h.state(5) == HEALTHY


def test_health_reset():
    h = HealthTracker(threshold=1)
    h.record_failure(1, "x")
    h.record_failure(2, "x")
    h.reset(1)
    assert h.state(1) == HEALTHY and h.state(2) == QUARANTINED
    h.reset()
    assert not h.tracked


# -- dead-letter queue -------------------------------------------------------


def test_dlq_bounded_drop_oldest():
    q = DeadLetterQueue(maxlen=3)
    for k in range(5):
        q.append(doc=k, update=bytes([k]), v2=False, reason=f"r{k}")
    assert len(q) == 3
    assert q.total == 5 and q.dropped == 2
    assert [e.doc for e in q] == [2, 3, 4]  # oldest evicted first
    snap = q.snapshot()
    assert snap["depth"] == 3 and snap["capacity"] == 3


def test_dlq_list_and_take():
    q = DeadLetterQueue(maxlen=10)
    for k in range(6):
        q.append(doc=k % 2, update=b"u", v2=False, reason="invalid-update: x")
    assert len(q.list(doc=0)) == 3
    taken = q.take(doc=1)
    assert [e.doc for e in taken] == [1, 1, 1]
    assert len(q) == 3 and not q.list(doc=1)
    # seq-targeted take
    seqs = [e.seq for e in q.list()][:1]
    assert len(q.take(seqs=seqs)) == 1
    assert len(q) == 2
    assert q.snapshot()["reasons"] == {"invalid-update": 2}


# -- transactional flush isolation ------------------------------------------


def test_flush_isolates_one_poisoned_doc():
    n = 8
    bad = 3
    eng = BatchEngine(n)
    for i in range(n):
        eng.queue_update(i, _update(f"doc{i} ", client=100 + i))
    eng.flush()
    for i in range(n):
        eng.queue_update(i, _update("more ", client=200 + i))
    eng.queue_update(bad, b"\xff\xff\xff\xff\xff")  # poison
    eng.flush()  # must NOT raise
    # N-1 docs completed the batch; the poisoned doc kept its good state
    for i in range(n):
        assert f"doc{i} " in eng.text(i)
        assert "more " in eng.text(i)
    snap = eng.resilience_snapshot()
    assert snap["n_rollbacks"] == 1
    assert eng.rollbacks[0]["doc"] == bad
    letters = eng.dead_letters.list(doc=bad)
    assert len(letters) == 1
    assert letters[0].reason.startswith("invalid-update:")
    assert letters[0].update == b"\xff\xff\xff\xff\xff"  # bytes retrievable
    m = eng.last_flush_metrics
    assert m["n_rolled_back"] == 1
    assert m["n_demoted"] >= 1
    # engine is NOT wedged: later flushes work
    eng.queue_update(0, _update("again ", client=300))
    eng.flush()
    assert "again " in eng.text(0)


def test_flush_isolation_python_mirror(monkeypatch):
    monkeypatch.setenv("YTPU_NO_NATIVE_PLAN", "1")
    eng = BatchEngine(4)
    for i in range(4):
        eng.queue_update(i, _update(f"d{i} ", client=50 + i))
    eng.queue_update(2, b"\x01\xff\xff\xff")
    eng.flush()
    for i in range(4):
        assert f"d{i} " in eng.text(i)
    assert eng.last_flush_metrics["n_rolled_back"] == 1


def test_corrupt_fixtures_quarantine_not_wedge():
    manifest = json.loads((FIXTURES / "manifest.json").read_text())
    eng = BatchEngine(2)
    eng.queue_update(0, _update("keep ", client=1))
    eng.queue_update(1, _update("other ", client=2))
    eng.flush()
    for case in manifest["cases"]:
        eng.queue_update(0, (FIXTURES / case["file"]).read_bytes())
        eng.flush()  # never raises, never wedges
    assert "keep " in eng.text(0)
    assert "other " in eng.text(1)
    assert eng.dead_letters.total >= 1
    # the clean twin still integrates (on the healthy doc)
    eng.health.reset()
    eng.queue_update(1, (FIXTURES / "valid_base.bin").read_bytes())
    eng.flush()


def test_strict_mode_raises(monkeypatch):
    monkeypatch.setenv("YTPU_RESILIENCE_DISABLED", "1")
    eng = BatchEngine(2)
    eng.queue_update(0, _update("x"))
    eng.queue_update(1, b"\xff\xff\xff\xff")
    with pytest.raises(Exception):
        eng.flush()


# -- quarantine + replay -----------------------------------------------------


def test_quarantine_diverts_then_replay_reintegrates(monkeypatch):
    monkeypatch.setenv("YTPU_RESILIENCE_THRESHOLD", "2")
    monkeypatch.setenv("YTPU_RESILIENCE_BACKOFF", "100")
    eng = BatchEngine(2)
    eng.queue_update(0, _update("base ", client=9))
    eng.flush()
    for _ in range(2):  # threshold failures -> quarantine
        eng.queue_update(0, b"\xff\xff\xff")
        eng.flush()
    assert eng.health.state(0) == QUARANTINED
    good = _update("recovered ", client=10)
    assert eng.queue_update(0, good) is False  # diverted, not applied
    assert any(e.reason == "quarantined" for e in eng.dead_letters.list(doc=0))
    assert "recovered" not in eng.text(0)
    # operator repairs + replays: poison letters need a repair that
    # drops them; the diverted good bytes re-integrate
    res = eng.replay_dead_letters(
        doc=0,
        readmit=True,
        repair=lambda e: e.update if e.reason == "quarantined" else None,
    )
    assert res["replayed"] == 1
    assert res["requeued"] == 2  # the two poison letters, left queued
    eng.flush()
    assert "recovered " in eng.text(0)
    assert "base " in eng.text(0)


def test_replay_revalidates():
    eng = BatchEngine(1)
    eng.dead_letters.append(0, b"\xff\xff", False, "quarantined")
    res = eng.replay_dead_letters(doc=0, readmit=True)
    assert res == {
        "replayed": 0, "requeued": 0, "failed": 1, "truncated": 0,
    }
    letters = eng.dead_letters.list(doc=0)
    assert len(letters) == 1
    assert letters[0].reason.startswith("replay-invalid:")


# -- provider surface --------------------------------------------------------


def test_provider_receive_update_quarantine_aware(monkeypatch):
    monkeypatch.setenv("YTPU_RESILIENCE_THRESHOLD", "1")
    monkeypatch.setenv("YTPU_RESILIENCE_BACKOFF", "100")
    p = TpuProvider(2)
    assert p.receive_update("r", _update("ok ", client=1)) is True
    assert p.text("r") == "ok "
    p.receive_update("r", b"\xff\xff\xff")
    p.flush()
    assert p.health("r")["state"] == QUARANTINED
    assert p.health() == {"degraded": 0, "quarantined": 1,
                          "tick": p.engine.health.tick_count}
    assert p.receive_update("r", _update("late ", client=2)) is False
    assert "late" not in p.text("r")
    # operator replay (readmit defaults True at the provider surface)
    res = p.replay_dead_letters(
        "r", repair=lambda e: e.update if e.reason == "quarantined" else None
    )
    assert res["replayed"] == 1
    assert "late " in p.text("r")
    assert p.health("r")["state"] == HEALTHY


def test_provider_dirty_not_stuck_on_device_policy(monkeypatch):
    # backend='device' raises on demotions AFTER integrating; the dirty
    # flag must not stay set or every accessor re-flushes forever
    p = TpuProvider(2, backend="device")
    p.receive_update("r", _update("ok ", client=1))
    p.receive_update("r", b"\xff\xff\xff")  # will demote via rollback
    with pytest.raises(RuntimeError):
        p.flush()
    assert p._dirty is False  # integrated: nothing left to flush
    with pytest.raises(RuntimeError):
        p.flush()  # still alerts (fallback persists) ...
    assert p.engine.text(0) == "ok "  # ... but no data was lost


def test_provider_tolerant_sync_frames():
    from yjs_tpu.lib0 import encoding
    from yjs_tpu.lib0.encoding import Encoder

    p = TpuProvider(2)
    p.receive_update("r", _update("keep ", client=3))
    # unknown frame type
    enc = Encoder()
    encoding.write_var_uint(enc, 42)
    encoding.write_var_uint8_array(enc, b"zz")
    assert p.handle_sync_message("r", enc.to_bytes()) is None
    # corrupt update payload
    enc = Encoder()
    encoding.write_var_uint(enc, 2)
    encoding.write_var_uint8_array(enc, b"\xff\xff\xff")
    assert p.handle_sync_message("r", enc.to_bytes()) is None
    # truncated frame (empty)
    assert p.handle_sync_message("r", b"") is None
    # corrupt step-1 state vector
    enc = Encoder()
    encoding.write_var_uint(enc, 0)
    encoding.write_var_uint8_array(enc, b"\xff\xff\xff\xff")
    assert p.handle_sync_message("r", enc.to_bytes()) is None
    assert p.text("r") == "keep "  # room unharmed, not demoted
    assert p.engine.health.state(0) == HEALTHY
    reasons = {e["reason"].split(":", 1)[0] for e in p.dead_letters("r")}
    assert reasons == {"unknown-frame", "bad-frame"}


def test_protocol_reader_skips_unknown_frames():
    from yjs_tpu.lib0 import encoding
    from yjs_tpu.lib0.decoding import Decoder
    from yjs_tpu.lib0.encoding import Encoder
    from yjs_tpu.obs import global_registry
    from yjs_tpu.sync import protocol

    fam = global_registry().get("ytpu_sync_messages_total")
    child = fam.labels(dir="read", type="unknown")
    before = child.value
    enc = Encoder()
    encoding.write_var_uint(enc, 9)
    rc = protocol.read_sync_message(Decoder(enc.to_bytes()), Encoder(), Y.Doc())
    assert rc == protocol.MESSAGE_UNKNOWN
    assert child.value == before + 1
