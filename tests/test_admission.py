"""Admission-control suite (ISSUE 10): token buckets, weighted-fair
queuing, brownout hysteresis, BUSY backpressure, WAL journaling of
shed-but-accepted traffic, and the admission/tiering interplay.

Everything runs on tick-time with seeded RNGs — a failure replays
byte-for-byte.  In tier-1; the ``admission`` marker deselects it with
``-m 'not admission'`` (scripts/ci_check.sh also runs it standalone).
"""

from __future__ import annotations

import random

import pytest

import yjs_tpu as Y
from yjs_tpu.admission import (
    AdmissionConfig,
    AdmissionRejected,
    TokenBucket,
    WeightedFairQueue,
)
from yjs_tpu.admission.brownout import (
    COALESCE,
    NORMAL,
    REJECT_WRITES,
    SHED_BACKGROUND,
    BrownoutController,
)
from yjs_tpu.fleet import FleetRouter
from yjs_tpu.persistence import WalConfig
from yjs_tpu.provider import TpuProvider
from yjs_tpu.sync import protocol
from yjs_tpu.sync.session import (
    MESSAGE_YTPU_SESSION,
    DocSessionHost,
    SessionConfig,
    SyncSession,
    encode_busy,
)
from yjs_tpu.sync.transport import PipeNetwork
from yjs_tpu.tiering import TierConfig
from yjs_tpu.updates import encode_state_as_update, encode_state_vector

pytestmark = pytest.mark.admission

ROOM = "tenant0/room"


def frame(update: bytes) -> bytes:
    from yjs_tpu.lib0.encoding import Encoder, write_var_uint8_array

    enc = Encoder()
    from yjs_tpu.lib0 import encoding

    encoding.write_var_uint(enc, protocol.MESSAGE_YJS_UPDATE)
    write_var_uint8_array(enc, update)
    return enc.to_bytes()


def doc_update(client_id: int, text: str, doc=None):
    d = doc if doc is not None else Y.Doc(gc=False)
    if doc is None:
        d.client_id = client_id
    sv = encode_state_vector(d)
    d.get_text("text").insert(len(str(d.get_text("text"))), text)
    return d, encode_state_as_update(d, sv)


# -- primitives -------------------------------------------------------------


def test_token_bucket_lazy_refill():
    tb = TokenBucket(rate=2.0, burst=4.0, tick=0)
    for _ in range(4):
        assert tb.take()
    assert not tb.take()
    tb.refill_to(1)
    assert tb.tokens == 2.0
    # refill is capped at burst, however long the bucket idled
    tb.refill_to(100)
    assert tb.tokens == 4.0
    # refill never runs time backwards
    tb.refill_to(50)
    assert tb.tick == 100


def test_wfq_flood_cannot_starve_and_is_deterministic():
    def fill(q):
        for i in range(10):
            q.push("abuser", f"a{i}")
        for i in range(2):
            q.push("quiet", f"q{i}")

    q1, q2 = WeightedFairQueue(), WeightedFairQueue()
    fill(q1)
    fill(q2)
    order = [q1.pop() for _ in range(len(q1))]
    # byte-identical drain order on an identical push sequence
    assert order == [q2.pop() for _ in range(len(q2))]
    # the quiet tenant's 2 items drain inside the first 4 pops — the
    # abuser's backlog only delays the abuser
    head = [t for t, _ in order[:4]]
    assert head.count("quiet") == 2
    assert q1.depth_of("abuser") == 0 and len(q1) == 0


def test_brownout_hysteresis_does_not_flap():
    b = BrownoutController(up_ticks=2, down_ticks=4)
    # one bad tick is not enough to climb
    assert b.observe(SHED_BACKGROUND, "queue-high") == NORMAL
    assert b.observe(SHED_BACKGROUND, "queue-high") == SHED_BACKGROUND
    # climbing is one level per hysteresis window, even if the target
    # is far above
    b2 = BrownoutController(up_ticks=2, down_ticks=4)
    levels = [b2.observe(REJECT_WRITES, "queue-full") for _ in range(6)]
    assert levels == [0, 1, 1, 2, 2, 3]
    # an alternating good/bad signal never leaves normal (no flapping)
    b3 = BrownoutController(up_ticks=2, down_ticks=4)
    for i in range(20):
        lvl = b3.observe(SHED_BACKGROUND if i % 2 else NORMAL, "x")
        assert lvl == NORMAL
    # stepping down needs down_ticks consecutive clean observations
    down = [b2.observe(NORMAL, "recovered") for _ in range(12)]
    assert down[:3] == [3, 3, 3]
    assert down[3] == COALESCE
    assert down[-1] == NORMAL


# -- provider seam ----------------------------------------------------------


def test_disabled_is_passthrough():
    p = TpuProvider(2)
    assert not p.admission.enabled
    d = None
    for _ in range(8):
        d, u = doc_update(1, "x", d)
        p.receive_update(ROOM, u)
    p.flush()
    snap = p.admission.snapshot()
    assert snap["enabled"] is False
    assert p.text(ROOM) == str(d.get_text("text"))


def test_queue_then_drain_converges():
    p = TpuProvider(
        2,
        admission_config=AdmissionConfig(
            enabled=True, tenant_rate=1.0, tenant_burst=2,
            doc_rate=1.0, doc_burst=2, queue_max=64, drain_batch=32,
        ),
    )
    d = None
    for i in range(10):
        d, u = doc_update(1, f"w{i} ", d)
        assert p.receive_update(ROOM, u)
    snap = p.admission.snapshot()
    assert snap["queued"] == 8 and snap["admitted"] == 2
    p.flush()
    assert p.admission.snapshot()["queue_depth"] == 0
    assert p.text(ROOM) == str(d.get_text("text"))


def test_queue_full_rejects_typed():
    p = TpuProvider(
        2,
        admission_config=AdmissionConfig(
            enabled=True, tenant_rate=0.0, tenant_burst=1,
            doc_rate=0.0, doc_burst=1, queue_max=2, retry_after=5,
        ),
    )
    d = None
    accepted = 0
    with pytest.raises(AdmissionRejected) as ei:
        for i in range(6):
            d, u = doc_update(1, f"w{i}", d)
            p.receive_update(ROOM, u)
            accepted += 1
    assert accepted == 3  # 1 bucket token + 2 queue slots
    assert ei.value.reason == "queue-full"
    assert ei.value.tenant == "tenant0"
    assert ei.value.retry_after == 5
    snap = p.admission.snapshot()
    assert snap["rejected"].get("queue-full", 0) >= 1


def test_queued_updates_survive_crash(tmp_path):
    cfg = AdmissionConfig(
        enabled=True, tenant_rate=0.0, tenant_burst=1,
        doc_rate=0.0, doc_burst=1, queue_max=64,
    )
    p = TpuProvider(
        2, wal_dir=tmp_path, wal_config=WalConfig(fsync="never"),
        admission_config=cfg,
    )
    d = None
    for i in range(6):
        d, u = doc_update(1, f"w{i} ", d)
        p.receive_update(ROOM, u)
    # 5 of 6 sit in the fair queue, never integrated — but journaled
    assert p.admission.snapshot()["queue_depth"] == 5
    p.wal.abandon()  # kill -9 before any drain
    v = TpuProvider.recover(
        tmp_path, n_docs=2, wal_config=WalConfig(fsync="never"),
    )
    assert v.text(ROOM) == str(d.get_text("text"))


def test_admission_transitions_journaled_and_recovered(tmp_path):
    p = TpuProvider(
        2, wal_dir=tmp_path, wal_config=WalConfig(fsync="never"),
        admission_config=AdmissionConfig(enabled=True),
    )
    d, u = doc_update(1, "seed")
    p.receive_update(ROOM, u)
    p.journal_admission("shed-background", "queue-high", 3)
    p.journal_admission("coalesce", "queue-high", 5)
    p.wal.abandon()
    v = TpuProvider.recover(
        tmp_path, n_docs=2, wal_config=WalConfig(fsync="never"),
        admission_config=AdmissionConfig(enabled=True),
    )
    assert v.last_recovery["adm_transitions"] == 2
    assert v.last_recovery["adm_level"] == "coalesce"
    # the live controller restarts at normal: pre-crash pressure is
    # historical context, not current load
    assert v.admission.level == NORMAL


def test_reject_writes_still_serves_reads():
    p = TpuProvider(
        2, admission_config=AdmissionConfig(enabled=True),
    )
    d, u = doc_update(1, "served")
    p.receive_update(ROOM, u)
    p.flush()
    p.admission.brownout.force(REJECT_WRITES, "test")
    # a sync STEP_1 (read path) is answered normally
    from yjs_tpu.lib0 import encoding
    from yjs_tpu.lib0.encoding import Encoder, write_var_uint8_array

    enc = Encoder()
    encoding.write_var_uint(enc, protocol.MESSAGE_YJS_SYNC_STEP_1)
    write_var_uint8_array(enc, encode_state_vector(Y.Doc(gc=False)))
    reply = p.handle_sync_message(ROOM, enc.to_bytes())
    assert reply is not None and reply[0] != MESSAGE_YTPU_SESSION
    # a write is refused with a BUSY envelope, not integrated
    before = p.text(ROOM)
    d, u2 = doc_update(1, " dropped", d)
    busy = p.handle_sync_message(ROOM, frame(u2))
    assert busy is not None and busy[0] == MESSAGE_YTPU_SESSION
    assert p.text(ROOM) == before
    assert p.admission.snapshot()["rejected"].get("reject-writes", 0) >= 1


def test_plain_reader_skips_busy_envelope():
    # a BUSY envelope handed to a plain y-protocols reader is counted
    # as unknown and skipped, never a crash or a spurious reply
    p = TpuProvider(1)
    reply = p.handle_sync_message("tenant0/plain", encode_busy(8))
    assert reply is None


def test_busy_roundtrip_session_no_loss():
    """A session client bursting far over rate is BUSY'd, backs off,
    retransmits, and converges byte-identically — refused frames are
    never acked, so nothing is lost."""
    p = TpuProvider(
        2,
        admission_config=AdmissionConfig(
            enabled=True, tenant_rate=0.5, tenant_burst=1,
            doc_rate=0.5, doc_burst=1, queue_max=2, retry_after=2,
        ),
    )
    net = PipeNetwork()
    cfg = SessionConfig(
        retry_base=2, retry_cap=8, retry_max=8, retry_jitter=0.0,
        antientropy=0, heartbeat=0, liveness=0, hello_timeout=0, seed=3,
    )
    d = Y.Doc(gc=False)
    d.client_id = 9
    client = SyncSession(DocSessionHost(d), cfg, peer="server")
    server = p.session(ROOM, "client", cfg)
    tc, ts = net.pair("client", "server")
    client.connect(tc)
    server.connect(ts)
    # settle the handshake first: a pre-LIVE burst would coalesce into
    # the STEP_2 answer and never meet the per-update gate
    for _ in range(8):
        net.pump()
        client.tick()
        p.flush()
        p.tick_sessions()
    assert client.state == "live"
    for i in range(8):
        sv = encode_state_vector(d)
        d.get_text("text").insert(len(str(d.get_text("text"))), f"w{i}")
        client.send_update(encode_state_as_update(d, sv))
    for _ in range(160):
        net.pump()
        client.tick()
        p.flush()
        p.tick_sessions()
        if (
            not net.in_flight
            and not client._outbox
            and p.admission.snapshot()["queue_depth"] == 0
            and p.text(ROOM) == str(d.get_text("text"))
        ):
            break
    assert p.text(ROOM) == str(d.get_text("text"))
    assert client.n_busy_backoffs > 0
    assert p.engine.dead_letters.total == 0
    assert client.n_full_resyncs <= 1


# -- satellites -------------------------------------------------------------


def test_replay_dead_letters_bounded():
    p = TpuProvider(1)
    d, u = doc_update(1, "x")
    p.receive_update(ROOM, u)
    p.flush()
    doc = p.doc_id(ROOM)
    for i in range(10):
        p.engine.dead_letters.append(doc, b"\xff\xff", False, "test")
    res = p.replay_dead_letters(ROOM, max_letters=4)
    assert res["truncated"] == 6
    # the 6 untaken letters stay queued (plus any replay re-failures)
    assert len(p.engine.dead_letters.list(doc=doc)) == 6 + res["failed"]
    counters = p.metrics_snapshot()["counters"]
    assert counters.get(
        "ytpu_resilience_dlq_replay_truncated_total", {}
    ).get("", 0) >= 1
    # 0 = unbounded: the remainder drains in one pass
    res2 = p.replay_dead_letters(ROOM, max_letters=0)
    assert res2["truncated"] == 0


def test_provider_full_dead_letters_typed_and_feeds_admission():
    p = TpuProvider(
        1, admission_config=AdmissionConfig(enabled=True),
    )
    d, u = doc_update(1, "first")
    p.receive_update("tenant0/one", u)
    p.flush()
    from yjs_tpu.provider import _ProviderSessionHost

    # the host seam directly: session() would veto at doc_id() before
    # any frame flows, but an established session whose slot was lost
    # hits ProviderFullError mid-frame exactly here
    host = _ProviderSessionHost(p, "tenant1/two", "peer")
    d2, u2 = doc_update(2, "overflow")
    reply = host.handle_frame(frame(u2))
    # the frame is refused with BUSY, dead-lettered with a typed
    # reason, and the full event feeds the brownout's signal set
    assert reply is not None and reply[0] == MESSAGE_YTPU_SESSION
    letters = p.engine.dead_letters.list()
    assert any("admission-full" in e.reason for e in letters)
    assert p.admission.snapshot()["full_events"].get("provider", 0) >= 1


def test_overcommitted_fleet_demotes_never_full():
    """Satellite 3: admission x tiering — an overcommitted fleet under
    admission pressure auto-demotes to make headroom instead of
    surfacing ProviderFullError, and stays byte-identical with the
    plan cache and replication at defaults (both on)."""
    fleet = FleetRouter(
        2, 2,
        tier_config=TierConfig(enabled=True),
        admission_config=AdmissionConfig(
            enabled=True, tenant_rate=64.0, tenant_burst=256,
            doc_rate=64.0, doc_burst=256, occupancy_high=0.5,
            headroom=1,
        ),
    )
    rng = random.Random(13)
    refs = {}
    guids = [f"tenant{i % 3}/room-{i}" for i in range(12)]
    for round_ in range(6):
        for g in guids:
            if rng.random() < 0.6:
                d = refs.get(g)
                if d is None:
                    d = Y.Doc(gc=False)
                    d.client_id = 100 + guids.index(g)
                    refs[g] = d
                _, u = doc_update(0, f"r{round_} ", d)
                fleet.receive_update(g, u)
        fleet.flush()
        fleet.tick()
    snap = fleet.admission.snapshot()
    # 12 docs through 4 slots: headroom maintenance had to demote
    assert not any(snap["full_events"].values())
    assert snap["demotions"] > 0
    for g, d in refs.items():
        assert fleet.text(g) == str(d.get_text("text")), g


def test_fleet_shares_one_controller():
    fleet = FleetRouter(
        3, 4,
        admission_config=AdmissionConfig(
            enabled=True, tenant_rate=0.0, tenant_burst=2,
            doc_rate=64.0, doc_burst=64, queue_max=64,
        ),
    )
    for prov in fleet.shards:
        assert prov.admission is fleet.admission
    # one tenant's bucket is fleet-wide: updates to docs landing on
    # different shards still share the 2-token budget
    d = {}
    for i in range(6):
        g = f"tenantX/doc-{i}"
        dd, u = doc_update(50 + i, "z")
        d[g] = dd
        fleet.receive_update(g, u)
    snap = fleet.admission.snapshot()
    assert snap["admitted"] == 2 and snap["queued"] == 4
    fleet.flush()
    assert fleet.admission.snapshot()["queue_depth"] == 0
    for g, dd in d.items():
        assert fleet.text(g) == str(dd.get_text("text"))
