"""Opt-in extensive fuzz — the deep-history analogue of the reference's
CI-extensive run (`npm test -- --production --repitition-time 10000`,
reference package.json:15-16; randomized instances scale 6 → 100 000
iterations in reference tests/y-map.tests.js:499-606).

Skipped unless YTPU_FUZZ_ITERS is set, e.g.:

    YTPU_FUZZ_ITERS=10000 JAX_PLATFORMS=cpu python -m pytest \
        tests/test_extensive.py -q

Covers all three layers VERDICT item 8 names: the CPU reference core
(ported op tables under the disconnect/reconnect connector), the batch
engine, and the sharded engine on the virtual 8-device mesh.  Recorded
runs live in tests/EXTENSIVE_RUNS.md.
"""

import os
import random

import pytest

import yjs_tpu as Y
from yjs_tpu.ops import BatchEngine

from helpers import apply_random_tests
from test_yarray import ARRAY_MODS
from test_ymap import MAP_MODS
from test_ytext import TEXT_MODS

ITERS = int(os.environ.get("YTPU_FUZZ_ITERS", "0"))

pytestmark = pytest.mark.skipif(
    ITERS <= 0, reason="set YTPU_FUZZ_ITERS>=1 for the extensive fuzz run"
)


# -- r5 op-table extensions: undo + snapshot ops mixed into the fuzz ---------
# (VERDICT r4 item 7: the deep fuzz must also drive the undo and snapshot
# machinery, not only plain edits)


def _undo_mod_for(type_getter, attr):
    """Random undo/redo against a per-user UndoManager scoped to one root
    type.  Undo emits ordinary updates, so the convergence oracle is
    unchanged; what this adds is redone-chain + deleted-struct traffic in
    every random delivery order."""

    def _mod(user, gen):
        um = getattr(user, attr, None)
        if um is None:
            um = Y.UndoManager(type_getter(user), capture_timeout=0)
            setattr(user, attr, um)
        if gen.random() < 0.6 and um.undo_stack:
            um.undo()
        elif um.redo_stack:
            um.redo()

    return _mod


def _snapshot_mod(user, gen):
    """Random snapshot capture + codec roundtrip; restore parity is
    checked on non-gc docs (the engine fuzz below covers restore on its
    gc=False docs every run)."""
    snap = Y.snapshot(user)
    enc = Y.encode_snapshot(snap)
    assert Y.equal_snapshots(Y.decode_snapshot(enc), snap)
    if not user.gc:
        d2 = Y.create_doc_from_snapshot(user, snap)
        assert d2.get_text("text").to_string() == user.get_text("text").to_string()


EXT_ARRAY_MODS = ARRAY_MODS + [
    _undo_mod_for(lambda u: u.get_array("array"), "_fuzz_undo_array"),
    _snapshot_mod,
]
EXT_MAP_MODS = MAP_MODS + [
    _undo_mod_for(lambda u: u.get_map("map"), "_fuzz_undo_map"),
    _snapshot_mod,
]
EXT_TEXT_MODS = TEXT_MODS + [
    _undo_mod_for(lambda u: u.get_text("text"), "_fuzz_undo_text"),
    _snapshot_mod,
]


def _compare_content(users):
    """Content-level convergence oracle for undo-mixed runs: ``redone``
    pointers are replica-local (reference Item.js:555-579 mergeWith needs
    ``redone === null``), so the undoing replica merges runs differently
    than its peers and struct-store IDENTITY legitimately diverges; the
    rendered content and the pending queues must still agree exactly."""
    for u in users:
        u.connect()
    while users[0].tc.flush_all_messages():
        pass
    ref = users[0]
    for u in users[1:]:
        assert u.get_array("array").to_json() == ref.get_array("array").to_json()
        assert u.get_map("map").to_json() == ref.get_map("map").to_json()
        assert (
            u.get("xml", Y.YXmlElement).to_string()
            == ref.get("xml", Y.YXmlElement).to_string()
        )
        assert u.get_text("text").to_delta() == ref.get_text("text").to_delta()
    for u in users:
        assert len(u.store.pending_delete_readers) == 0
        assert len(u.store.pending_stack) == 0
        assert len(u.store.pending_clients_struct_refs) == 0


# -- CPU reference core under the random-delivery connector -----------------
# plain tables keep the full struct-store-identity oracle; the *_mixed
# variants drive the same tables with undo/snapshot ops folded in under
# the content-level oracle (see _compare_content for why)


def test_extensive_array(rng):
    apply_random_tests(rng, ARRAY_MODS, ITERS)


def test_extensive_map(rng):
    apply_random_tests(rng, MAP_MODS, ITERS)


def test_extensive_text(rng):
    apply_random_tests(rng, TEXT_MODS, ITERS)


def test_extensive_array_mixed(rng):
    apply_random_tests(rng, EXT_ARRAY_MODS, ITERS, compare_fn=_compare_content)


def test_extensive_map_mixed(rng):
    apply_random_tests(rng, EXT_MAP_MODS, ITERS, compare_fn=_compare_content)


def test_extensive_text_mixed(rng):
    apply_random_tests(rng, EXT_TEXT_MODS, ITERS, compare_fn=_compare_content)


# -- batch engine / sharded batch engine -------------------------------------


def _engine_fuzz(gen: random.Random, n_ops: int, mesh=None) -> None:
    """Deep mixed text+map+multiroot trace with randomized delivery into the
    engine (incremental flushes, so splits/pending paths see deep histories),
    checked against the CPU core oracle at the end.

    r5: updates fan out to FOUR engine rooms (docs 0..3, each receiving an
    independent random prefix), and YTPU_FLUSH_CHUNK=2 forces every flush
    through the chunked plan/transfer-overlap path; random engine
    snapshots assert SV-vs-mirror equality mid-run, and per-client
    UndoManagers add redone-chain traffic to the delivered updates."""
    n_clients = 4
    docs = []
    for i in range(n_clients):
        d = Y.Doc(gc=False)
        d.client_id = i + 1
        docs.append(d)
    upds = [[] for _ in range(n_clients)]
    for i, d in enumerate(docs):
        d.on("update", lambda u, origin, _d, i=i: upds[i].append(u))
    undo_mgrs = [
        Y.UndoManager(d.get_text("text"), capture_timeout=0) for d in docs
    ]

    n_rooms = n_clients  # one engine room per client stream
    eng = BatchEngine(8 if mesh is not None else n_rooms, mesh=mesh)
    # prefix of upds[i] already queued to engine room i
    delivered = [0] * n_clients
    flush_every = max(40, n_ops // 200)

    def deliver_some():
        i = gen.randrange(n_clients)
        take = gen.randint(1, max(1, len(upds[i]) - delivered[i]))
        for u in upds[i][delivered[i] : delivered[i] + take]:
            eng.queue_update(i, u)
        delivered[i] = min(len(upds[i]), delivered[i] + take)

    for step in range(n_ops):
        i = gen.randrange(n_clients)
        d = docs[i]
        op = gen.random()
        if op < 0.5:
            t = d.get_text(gen.choice(["text", "notes"]))
            ln = len(t.to_string())
            if gen.random() < 0.65 or ln == 0:
                t.insert(gen.randint(0, ln), gen.choice(["x", "yy", "zz ", "🙂"]))
            else:
                pos = gen.randrange(ln)
                t.delete(pos, min(gen.randint(1, 3), ln - pos))
        elif op < 0.75:
            d.get_map("map").set(gen.choice("abcde"), gen.randrange(1000))
        elif op < 0.85:
            d.get_map("map").delete(gen.choice("abcde"))
        elif op < 0.95:  # nested shared types on the device path
            key = gen.choice("nm")
            cur = d.get_map("map").get(key)
            if cur is None or not hasattr(cur, "insert"):
                d.get_map("map").set(key, Y.YText())
            else:
                cur.insert(len(cur.to_string()), gen.choice(["n", "est "]))
        else:
            arr = d.get_map("map").get("arr")
            if arr is None or not hasattr(arr, "to_json"):
                d.get_map("map").set("arr", Y.YArray())
            else:
                arr.insert(0, [gen.randrange(50)])
        if gen.random() < 0.04:  # undo/redo traffic into the streams
            um = undo_mgrs[i]
            if gen.random() < 0.6 and um.undo_stack:
                um.undo()
            elif um.redo_stack:
                um.redo()
        if gen.random() < 0.3:  # random partial cross-client sync
            src, dst = gen.randrange(n_clients), gen.randrange(n_clients)
            for u in upds[src]:
                Y.apply_update(docs[dst], u)
        if gen.random() < 0.2:
            deliver_some()
        if step and step % flush_every == 0:
            eng.flush()
            if gen.random() < 0.1:
                # engine snapshot mid-run: SV must equal the mirror's
                room = gen.randrange(n_rooms)
                snap = eng.snapshot(room)
                assert {
                    c: v for c, v in snap.sv.items() if v > 0
                } == eng.state_vector(room)

    # quiesce: everyone sees everything, every engine room included
    all_updates = [u for us in upds for u in us]
    gen.shuffle(all_updates)
    for d in docs:
        for u in all_updates:
            Y.apply_update(d, u)
    for room in range(n_rooms):
        for u in all_updates:
            eng.queue_update(room, u)
    eng.flush()

    ref = docs[0]
    for other in docs[1:]:
        for name in ("text", "notes"):
            assert other.get_text(name).to_string() == ref.get_text(name).to_string()
        assert other.get_map("map").to_json() == ref.get_map("map").to_json()
    for room in range(n_rooms):
        for name in ("text", "notes"):
            assert eng.text(room, name) == ref.get_text(name).to_string()
        assert eng.map_json(room, "map") == ref.get_map("map").to_json()
        assert eng.state_vector(room) == {
            c: v for c, v in Y.get_state_vector(ref.store).items() if v > 0
        }
        assert not eng.has_pending(room)
    # engine snapshot restore parity on the quiesced state
    snap = eng.snapshot(0)
    restored = eng.create_doc_from_snapshot(0, snap)
    assert restored.get_text("text").to_string() == ref.get_text("text").to_string()
    assert not eng.fallback, f"unexpected demotions: {eng.demotions}"


def test_extensive_engine(rng, monkeypatch):
    # chunk of 2 over 4 rooms: every flush exercises the chunked
    # plan/transfer-overlap path (capacity growth across chunks included)
    monkeypatch.setenv("YTPU_FLUSH_CHUNK", "2")
    _engine_fuzz(rng, ITERS)


def test_extensive_engine_sharded(rng, monkeypatch):
    import jax

    if len(jax.devices("cpu")) < 8:
        pytest.skip("needs 8 virtual cpu devices")
    from yjs_tpu.parallel import doc_mesh

    monkeypatch.setenv("YTPU_FLUSH_CHUNK", "2")
    _engine_fuzz(rng, ITERS, mesh=doc_mesh(8, backend="cpu"))
