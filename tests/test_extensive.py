"""Opt-in extensive fuzz — the deep-history analogue of the reference's
CI-extensive run (`npm test -- --production --repitition-time 10000`,
reference package.json:15-16; randomized instances scale 6 → 100 000
iterations in reference tests/y-map.tests.js:499-606).

Skipped unless YTPU_FUZZ_ITERS is set, e.g.:

    YTPU_FUZZ_ITERS=10000 JAX_PLATFORMS=cpu python -m pytest \
        tests/test_extensive.py -q

Covers all three layers VERDICT item 8 names: the CPU reference core
(ported op tables under the disconnect/reconnect connector), the batch
engine, and the sharded engine on the virtual 8-device mesh.  Recorded
runs live in tests/EXTENSIVE_RUNS.md.
"""

import os
import random

import pytest

import yjs_tpu as Y
from yjs_tpu.ops import BatchEngine

from helpers import apply_random_tests
from test_yarray import ARRAY_MODS
from test_ymap import MAP_MODS
from test_ytext import TEXT_MODS

ITERS = int(os.environ.get("YTPU_FUZZ_ITERS", "0"))

pytestmark = pytest.mark.skipif(
    ITERS <= 0, reason="set YTPU_FUZZ_ITERS>=1 for the extensive fuzz run"
)


# -- CPU reference core under the random-delivery connector -----------------


def test_extensive_array(rng):
    apply_random_tests(rng, ARRAY_MODS, ITERS)


def test_extensive_map(rng):
    apply_random_tests(rng, MAP_MODS, ITERS)


def test_extensive_text(rng):
    apply_random_tests(rng, TEXT_MODS, ITERS)


# -- batch engine / sharded batch engine -------------------------------------


def _engine_fuzz(gen: random.Random, n_ops: int, mesh=None) -> None:
    """Deep mixed text+map+multiroot trace with randomized delivery into the
    engine (incremental flushes, so splits/pending paths see deep histories),
    checked against the CPU core oracle at the end."""
    n_clients = 4
    docs = []
    for i in range(n_clients):
        d = Y.Doc(gc=False)
        d.client_id = i + 1
        docs.append(d)
    upds = [[] for _ in range(n_clients)]
    for i, d in enumerate(docs):
        d.on("update", lambda u, origin, _d, i=i: upds[i].append(u))

    eng = BatchEngine(8 if mesh is not None else 1, mesh=mesh)
    delivered = [0] * n_clients  # prefix of upds[i] already queued to engine
    flush_every = max(40, n_ops // 200)

    def deliver_some():
        i = gen.randrange(n_clients)
        take = gen.randint(1, max(1, len(upds[i]) - delivered[i]))
        for u in upds[i][delivered[i] : delivered[i] + take]:
            eng.queue_update(0, u)
        delivered[i] = min(len(upds[i]), delivered[i] + take)

    for step in range(n_ops):
        i = gen.randrange(n_clients)
        d = docs[i]
        op = gen.random()
        if op < 0.5:
            t = d.get_text(gen.choice(["text", "notes"]))
            ln = len(t.to_string())
            if gen.random() < 0.65 or ln == 0:
                t.insert(gen.randint(0, ln), gen.choice(["x", "yy", "zz ", "🙂"]))
            else:
                pos = gen.randrange(ln)
                t.delete(pos, min(gen.randint(1, 3), ln - pos))
        elif op < 0.75:
            d.get_map("map").set(gen.choice("abcde"), gen.randrange(1000))
        elif op < 0.85:
            d.get_map("map").delete(gen.choice("abcde"))
        elif op < 0.95:  # nested shared types on the device path
            key = gen.choice("nm")
            cur = d.get_map("map").get(key)
            if cur is None or not hasattr(cur, "insert"):
                d.get_map("map").set(key, Y.YText())
            else:
                cur.insert(len(cur.to_string()), gen.choice(["n", "est "]))
        else:
            arr = d.get_map("map").get("arr")
            if arr is None or not hasattr(arr, "to_json"):
                d.get_map("map").set("arr", Y.YArray())
            else:
                arr.insert(0, [gen.randrange(50)])
        if gen.random() < 0.3:  # random partial cross-client sync
            src, dst = gen.randrange(n_clients), gen.randrange(n_clients)
            for u in upds[src]:
                Y.apply_update(docs[dst], u)
        if gen.random() < 0.2:
            deliver_some()
        if step and step % flush_every == 0:
            eng.flush()

    # quiesce: everyone sees everything, engine included
    all_updates = [u for us in upds for u in us]
    gen.shuffle(all_updates)
    for d in docs:
        for u in all_updates:
            Y.apply_update(d, u)
    for u in all_updates:
        eng.queue_update(0, u)
    eng.flush()

    ref = docs[0]
    for other in docs[1:]:
        for name in ("text", "notes"):
            assert other.get_text(name).to_string() == ref.get_text(name).to_string()
        assert other.get_map("map").to_json() == ref.get_map("map").to_json()
    for name in ("text", "notes"):
        assert eng.text(0, name) == ref.get_text(name).to_string()
    assert eng.map_json(0, "map") == ref.get_map("map").to_json()
    assert eng.state_vector(0) == {
        c: v for c, v in Y.get_state_vector(ref.store).items() if v > 0
    }
    assert not eng.has_pending(0)
    assert not eng.fallback, f"unexpected demotions: {eng.demotions}"


def test_extensive_engine(rng):
    _engine_fuzz(rng, ITERS)


def test_extensive_engine_sharded(rng):
    import jax

    if len(jax.devices("cpu")) < 8:
        pytest.skip("needs 8 virtual cpu devices")
    from yjs_tpu.parallel import doc_mesh

    _engine_fuzz(rng, ITERS, mesh=doc_mesh(8, backend="cpu"))
