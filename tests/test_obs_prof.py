"""yjs_tpu.obs.prof: compile-aware cost attribution (ISSUE 4 tentpole).

Covers: call-signature mirroring and shape buckets, compile / cache-hit
/ retrace accounting (incl. the retrace-detection contract with
offending shapes), device-mode timing, device-memory gauges, host batch
op histograms, WAL append latency, Chrome-trace flow/metadata export,
torn-scrape safety under a concurrent flusher, and the ytpu_top /
ytpu_stats dashboard surfaces.
"""

import importlib.util
import io
import json
import os
import threading
import time
from pathlib import Path

import pytest

import yjs_tpu as Y
from yjs_tpu.obs.prof import (
    KernelProfiler,
    call_signature,
    host_timed,
    kernel_profiler,
    profiled,
    shape_bucket,
)
from yjs_tpu.obs.registry import MetricsRegistry
from yjs_tpu.obs.trace import Tracer
from yjs_tpu.ops import BatchEngine
from yjs_tpu.provider import TpuProvider
from yjs_tpu.updates import encode_state_as_update

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


ROOT = Path(__file__).resolve().parent.parent


def _update(text="hello"):
    d = Y.Doc(gc=False)
    d.get_text("text").insert(0, text)
    return encode_state_as_update(d)


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, ROOT / "scripts" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fresh_profiler():
    return KernelProfiler(
        registry=MetricsRegistry(), tracer=Tracer(enabled=True)
    )


# -- signatures & buckets ----------------------------------------------------


def test_call_signature_distinguishes_shapes_dtypes_and_statics():
    a8 = jnp.zeros((8,), jnp.int32)
    a16 = jnp.zeros((16,), jnp.int32)
    f8 = jnp.zeros((8,), jnp.float32)
    assert call_signature((a8,), {}) != call_signature((a16,), {})
    assert call_signature((a8,), {}) != call_signature((f8,), {})
    assert call_signature((a8,), {}) == call_signature((a8,), {})
    # hashable statics participate by VALUE (they are part of jax's key)
    assert call_signature((a8, 3), {}) != call_signature((a8, 4), {})


def test_shape_bucket_pow2_and_scalar():
    assert shape_bucket(call_signature((1, 2.5), {})) == "scalar"
    sig = call_signature((jnp.zeros((3, 3)),), {})
    assert shape_bucket(sig) == "le_16"  # 9 elements -> next pow2
    sig = call_signature((jnp.zeros((8,)), jnp.zeros((64,))), {})
    assert shape_bucket(sig) == "le_64"  # largest leaf wins


# -- compile / hit / retrace accounting --------------------------------------


def test_profiler_compile_then_cache_hits():
    p = _fresh_profiler()
    fn = jax.jit(lambda x: x + 1)
    x = jnp.zeros((4,), jnp.int32)
    for _ in range(3):
        out = p.call("k", fn, (x,), {})
    assert int(out[0]) == 1
    snap = p.snapshot()["kernels"]["k"]
    assert snap["compiles"] == 1
    assert snap["hits"] == 2
    assert snap["retraces"] == 0
    assert snap["hit_rate"] == pytest.approx(2 / 3)


def test_retrace_detection_records_offending_shapes():
    p = _fresh_profiler()
    fn = jax.jit(lambda x: x * 2)
    p.call("grow", fn, (jnp.zeros((8,), jnp.int32),), {})
    p.call("grow", fn, (jnp.zeros((32,), jnp.int32),), {})  # NEW signature
    snap = p.snapshot()
    assert snap["kernels"]["grow"]["retraces"] == 1
    assert snap["kernels"]["grow"]["compiles"] == 2
    (event,) = snap["retrace_events"]
    assert event["kernel"] == "grow"
    assert event["shape"] == "le_32"
    assert "int32[32]" in event["signature"]  # the offending abstract shape
    assert event["n_signatures"] == 2
    assert event["compile_s"] >= 0.0
    # the retrace also lands as a tracer instant for Perfetto
    names = [e["name"] for e in p.tracer.trace_events()]
    assert "ytpu.prof.retrace" in names


def test_retrace_events_bounded():
    from yjs_tpu.obs.prof import RETRACE_EVENTS_MAX

    p = _fresh_profiler()
    assert p.retrace_events.maxlen == RETRACE_EVENTS_MAX


def test_profiled_decorator_transparent_when_disabled(monkeypatch):
    calls = []

    @profiled("nope")
    def fn(x):
        calls.append(x)
        return x + 1

    monkeypatch.setenv("YTPU_OBS_DISABLED", "1")
    before = dict(kernel_profiler().snapshot()["kernels"])
    assert fn(1) == 2
    assert calls == [1]
    assert kernel_profiler().snapshot()["kernels"] == before
    assert fn.__wrapped__ is not None  # introspection survives wrapping


def test_device_mode_records_device_seconds(monkeypatch):
    p = _fresh_profiler()
    fn = jax.jit(lambda x: x + 1)
    x = jnp.zeros((4,), jnp.int32)
    p.call("dev", fn, (x,), {})  # compile with device mode off
    monkeypatch.setenv("YTPU_PROF_DEVICE", "1")
    p.call("dev", fn, (x,), {})  # cached, but routed through the slow path
    fam = p.registry.get("ytpu_prof_device_seconds")
    counts = {
        labels["kernel"]: series.count for labels, series in fam.samples()
    }
    assert counts.get("dev") == 1
    assert p.snapshot()["kernels"]["dev"]["hits"] == 1


# -- engine / provider integration -------------------------------------------


def test_engine_flush_populates_prof_families():
    eng = BatchEngine(2)
    eng.queue_update(0, _update())
    eng.flush()
    snap = kernel_profiler().snapshot()["kernels"]
    assert snap, "no kernel attributed during a flush"
    # the device apply path compiles at least one engine kernel
    assert any(rec["compiles"] >= 1 for rec in snap.values())
    # prof families ride the provider/engine exposition (global merge)
    text = eng.metrics_text()
    assert "ytpu_prof_compiles_total" in text


def test_device_memory_gauges_after_flush():
    eng = BatchEngine(4)
    eng.queue_update(0, _update())
    eng.flush()
    table = eng.obs.registry.get("ytpu_prof_device_table_bytes")
    sizes = {
        labels["table"]: series.value for labels, series in table.samples()
    }
    assert sizes.get("right_link", 0) > 0
    assert sizes.get("deleted", 0) > 0
    total = eng.obs.registry.get("ytpu_prof_device_bytes_total")
    assert sum(s.value for _, s in total.samples()) >= sum(sizes.values())
    occ = eng.obs.registry.get("ytpu_prof_slot_occupancy")
    (sample,) = list(occ.samples())
    assert sample[1].value == pytest.approx(1 / 4)  # 1 active doc of 4


def test_slot_occupancy_tracks_release(tmp_path):
    prov = TpuProvider(4)
    prov.receive_update("a", _update("a"))
    prov.receive_update("b", _update("b"))
    prov.flush()
    occ = prov.engine.obs.registry.get("ytpu_prof_slot_occupancy")
    assert list(occ.samples())[0][1].value == pytest.approx(2 / 4)
    prov.release_doc("a")
    prov.receive_update("b", _update("bb"))
    prov.flush()
    assert list(occ.samples())[0][1].value == pytest.approx(1 / 4)


def test_batch_ops_record_host_histogram():
    from yjs_tpu.ops.batch import merge_updates_columnar

    before = _op_count("merge_updates")
    merged = merge_updates_columnar([_update("a"), _update("b")])
    assert merged  # real output, instrumentation is transparent
    assert _op_count("merge_updates") == before + 1


def _op_count(op):
    fam = kernel_profiler().registry.get("ytpu_prof_batch_op_seconds")
    for labels, series in fam.samples():
        if labels.get("op") == op:
            return series.count
    return 0


def test_wal_append_latency_histogram(tmp_path):
    prov = TpuProvider(2, wal_dir=str(tmp_path))
    prov.receive_update("room", _update())
    fam = prov.engine.obs.registry.get("ytpu_wal_append_seconds")
    assert fam.count == 1
    assert fam.summary()["max"] > 0.0


# -- chrome trace: metadata + flow linking -----------------------------------


def test_trace_flow_links_receive_to_flush():
    prov = TpuProvider(2)
    prov.receive_update("room", _update())
    prov.flush()
    events = prov.engine.obs.tracer.trace_events()
    starts = [e for e in events if e["ph"] == "s"]
    ends = [e for e in events if e["ph"] == "f"]
    assert len(starts) == 1 and len(ends) == 1
    assert starts[0]["id"] == ends[0]["id"]  # same flow arrow
    assert ends[0]["bp"] == "e"  # binds to the enclosing flush slice
    # the arrow leaves the receive span and lands inside the flush span
    names = [e["name"] for e in events]
    assert "ytpu.provider.receive_update" in names
    assert "ytpu.provider.flush" in names
    # process/thread metadata present so Perfetto labels the lanes
    meta = {e["name"] for e in events if e["ph"] == "M"}
    assert meta >= {"process_name", "thread_name"}


def test_tracer_thread_naming():
    tr = Tracer(enabled=True)
    tr.name_thread("flusher")
    tr.instant("tick")
    events = tr.trace_events()
    thread_meta = [
        e for e in events
        if e["ph"] == "M" and e["name"] == "thread_name"
    ]
    assert thread_meta[0]["args"]["name"] == "flusher"


# -- concurrency: scrapes never observe torn state ---------------------------


def test_concurrent_scrape_never_torn():
    """Exposition scrapes run against a provider that is concurrently
    flushing and recovering dead letters: every scrape must parse, and
    `provider.metrics` copies must stay defensive (mutating one can
    never corrupt the ring)."""
    prov = TpuProvider(8)
    stop = threading.Event()
    errors = []

    def flusher():
        k = 0
        while not stop.is_set():
            try:
                k += 1
                prov.receive_update(f"room{k % 8}", _update(f"edit {k}"))
                if k % 8 == 0:  # exercise the dead-letter path too
                    prov.handle_sync_message(f"room{k % 8}", b"\x02\xff\xff")
                prov.flush()
            except Exception as e:  # pragma: no cover - the failure mode
                errors.append(e)
                return

    t = threading.Thread(target=flusher, daemon=True)
    t.start()
    deadline = time.time() + 2.0
    scrapes = 0
    try:
        while time.time() < deadline:
            text = prov.metrics_text()
            assert "ytpu_engine_flushes_total" in text
            snap = prov.metrics_snapshot()
            json.dumps(snap)  # JSON-able even mid-flush
            m = prov.metrics
            if m is not None:
                m["n_docs_flushed"] = -999  # defensive copy: no effect
                assert prov.engine.last_flush_metrics["n_docs_flushed"] != -999
            prov.slo_snapshot()
            scrapes += 1
    finally:
        stop.set()
        t.join(timeout=5)
    assert not errors
    assert scrapes > 0


# -- dashboards --------------------------------------------------------------


def test_ytpu_top_collect_and_render(tmp_path):
    top = _load_script("ytpu_top")
    prov = TpuProvider(4)
    prov.receive_update("room", _update())
    prov.flush()
    snap = prov.metrics_snapshot()
    row = top.collect_row("prov-a", snap, None, 2.0)
    assert row["flushes"] >= 1
    assert row["slo"] in ("ok", "warning", "page")
    assert row["conv p50"].endswith("ms")
    frame = top.render([row], 2.0)
    assert "prov-a" in frame and "fleet verdict" in frame
    # rates derive from consecutive polls of monotonic counters
    snap2 = prov.metrics_snapshot()
    row2 = top.collect_row("prov-a", snap2, row, 2.0)
    assert row2["docs/s"] == "0.0"  # nothing flushed between polls


def test_ytpu_top_file_source_and_run_plain(tmp_path):
    top = _load_script("ytpu_top")
    prov = TpuProvider(2)
    prov.receive_update("room", _update())
    prov.flush()
    path = tmp_path / "prov.json"
    path.write_text(json.dumps(prov.metrics_snapshot()))
    out = io.StringIO()
    top.run_plain(
        top.FileSource([str(path)]), interval=0.01, iterations=2, out=out
    )
    frames = out.getvalue()
    assert frames.count("ytpu_top") == 2
    assert "prov" in frames
    # unreadable file renders an empty row instead of crashing
    rows = top.FileSource([str(tmp_path / "missing.json")]).poll()
    assert rows[0][1] == {}


def test_ytpu_stats_groups_and_watch(tmp_path):
    stats = _load_script("ytpu_stats")
    prov = TpuProvider(2, wal_dir=str(tmp_path))
    prov.receive_update("room", _update())
    prov.flush()
    text = stats.render_snapshot(prov.metrics_snapshot())
    for section in (
        "engine", "provider", "durability (WAL)",
        "cost attribution (prof)", "convergence SLO", "slo verdict",
    ):
        assert section in text, f"missing section {section!r}"
    out = io.StringIO()
    stats._watch(
        lambda: stats.render_snapshot(prov.metrics_snapshot()),
        interval=0.01, iterations=2, out=out,
    )
    assert out.getvalue().count("--- ") == 2


def test_knob_regex_covers_prof_and_slo():
    mod = _load_script("check_metrics_schema")
    knobs = mod.resilience_knobs_in_code()
    assert "YTPU_PROF_DEVICE" in knobs
    assert "YTPU_SLO_CONVERGENCE_MS" in knobs
    assert "YTPU_SLO_WINDOW" in knobs


def test_host_timed_decorator_transparent_and_recording(monkeypatch):
    @host_timed("unit_op")
    def op(x):
        return x * 2

    before = _op_count("unit_op")
    assert op(21) == 42
    assert _op_count("unit_op") == before + 1
    monkeypatch.setenv("YTPU_OBS_DISABLED", "1")
    assert op(2) == 4
    assert _op_count("unit_op") == before + 1  # disabled: not recorded
