"""AbstractConnector contract + the SocketConnector transport example
(reference src/utils/AbstractConnector.js:16-26; y-protocols sync flow)."""

import socket
import sys
import time
from pathlib import Path

import yjs_tpu as Y
from yjs_tpu.utils.abstract_connector import AbstractConnector

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "examples"))
from socket_connector import SocketConnector  # noqa: E402


def test_abstract_connector_contract():
    d = Y.Doc()
    c = AbstractConnector(d, awareness={"user": "x"})
    assert c.doc is d
    assert c.awareness == {"user": "x"}
    got = []
    c.on("synced", lambda v: got.append(v))
    c.emit("synced", [True])
    assert got == [True]
    # exported at the package root like the reference index.js contract
    assert Y.AbstractConnector is AbstractConnector


def test_socket_connector_two_peer_convergence():
    a_sock, b_sock = socket.socketpair()
    da = Y.Doc(gc=False)
    da.client_id = 1
    db = Y.Doc(gc=False)
    db.client_id = 2
    da.get_text("text").insert(0, "A-offline. ")
    db.get_text("text").insert(0, "B-offline. ")

    ca = SocketConnector(da, a_sock)
    cb = SocketConnector(db, b_sock)
    ca.connect()
    cb.connect()
    def texts():
        # doc reads share each connector's lock with its rx thread
        with ca.lock:
            ta = da.get_text("text").to_string()
        with cb.lock:
            tb = db.get_text("text").to_string()
        return ta, tb

    deadline = time.time() + 10
    while time.time() < deadline:
        ta, tb = texts()
        if ta == tb and ta != "":
            break
        time.sleep(0.05)
    ta, tb = texts()
    assert ta == tb, "handshake did not converge"

    # live incremental updates after the handshake (doc mutations share
    # the connector's doc lock with its receive thread)
    with ca.lock:
        da.get_text("text").insert(0, "[live-A]")
    with cb.lock:
        db.get_map("meta").set("k", 7)
    def maps():
        with ca.lock:
            ma = da.get_map("meta").to_json()
        with cb.lock:
            mb = db.get_map("meta").to_json()
        return ma, mb

    deadline = time.time() + 10
    while time.time() < deadline:
        ta, tb = texts()
        ma, mb = maps()
        if ta == tb and ma == mb:
            break
        time.sleep(0.05)
    ta, tb = texts()
    ma, mb = maps()
    assert ta == tb
    assert ma == mb == {"k": 7}
    ca.close()
    cb.close()


def test_socket_connector_close_joins_threads_without_dropping_frames():
    """The satellite-1 shutdown pin: ``close()`` drains every frame the
    session handed the transport before the FIN hits the wire, and the
    ticker plus both transport threads JOIN — no leaked threads, no
    dropped unacked frames."""
    a_sock, b_sock = socket.socketpair()
    da = Y.Doc(gc=False)
    da.client_id = 1
    db = Y.Doc(gc=False)
    db.client_id = 2

    ca = SocketConnector(da, a_sock)
    cb = SocketConnector(db, b_sock)
    ca.connect()
    cb.connect()
    deadline = time.time() + 10
    while time.time() < deadline:
        with ca.lock:
            live = ca.session.state == "live"
        if live:
            break
        time.sleep(0.02)
    with ca.lock:
        assert ca.session.state == "live"

    # edit, then close immediately: the DATA frame is in the transport
    # outbox, not yet on the wire — close must flush it, not drop it
    with ca.lock:
        da.get_text("text").insert(0, "final words")
    ca.close()

    assert ca.join(timeout=5.0), "connector threads did not join on close"
    assert not ca._transport._tx.is_alive()
    assert not ca._transport._rx.is_alive()
    assert not ca._ticker.is_alive()
    assert ca._transport.queued == 0, "close dropped queued frames"

    # the peer (still open) receives the pre-close frame
    deadline = time.time() + 10
    while time.time() < deadline:
        with cb.lock:
            tb = db.get_text("text").to_string()
        if tb == "final words":
            break
        time.sleep(0.05)
    assert tb == "final words", f"peer saw {tb!r}"
    cb.close()
    assert cb.join(timeout=5.0)
