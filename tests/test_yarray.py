"""YArray behavior + randomized convergence tests (scenarios modeled on
reference tests/y-array.tests.js)."""

import random

import pytest

import yjs_tpu as Y
from helpers import apply_random_tests, compare, init


def test_basic_insert_delete():
    doc = Y.Doc()
    arr = doc.get_array("arr")
    arr.insert(0, [1, 2, 3])
    arr.insert(1, ["x"])
    assert arr.to_json() == [1, "x", 2, 3]
    arr.delete(1, 2)
    assert arr.to_json() == [1, 3]
    arr.push([4])
    arr.unshift([0])
    assert arr.to_json() == [0, 1, 3, 4]
    assert arr.get(2) == 3
    assert arr.length == 4
    assert arr.slice(1, 3) == [1, 3]
    assert arr.slice(-2) == [3, 4]


def test_types_as_content():
    doc = Y.Doc()
    arr = doc.get_array("arr")
    nested = Y.YArray()
    arr.insert(0, [nested])
    nested.insert(0, ["inner"])
    m = Y.YMap({"k": 1})
    arr.push([m])
    assert arr.to_json() == [["inner"], {"k": 1}]


def test_insert_three_elements_try_re_get(rng):
    result = init(rng, users=2)
    array0, array1 = result["array0"], result["array1"]
    array0.insert(0, [1, True, False])
    assert array0.to_json() == [1, True, False]
    result["testConnector"].flush_all_messages()
    assert array1.to_json() == [1, True, False]
    compare(result["users"])


def test_concurrent_inserts_converge(rng):
    result = init(rng, users=3)
    array0, array1, array2 = result["array0"], result["array1"], result["array2"]
    array0.insert(0, [0])
    array1.insert(0, [1])
    array2.insert(0, [2])
    compare(result["users"])


def test_insertions_in_late_sync(rng):
    result = init(rng, users=3)
    tc = result["testConnector"]
    tc.flush_all_messages()
    result["users"][1].disconnect()
    result["users"][2].disconnect()
    result["array0"].insert(1, ["user0"]) if result["array0"].length > 0 else result[
        "array0"
    ].insert(0, ["user0"])
    result["array1"].insert(0, ["user1"])
    result["array2"].insert(0, ["user2"])
    result["users"][1].connect()
    result["users"][2].connect()
    compare(result["users"])


def test_disconnect_really_prevents_sending_messages(rng):
    result = init(rng, users=3)
    tc = result["testConnector"]
    array0, array1 = result["array0"], result["array1"]
    tc.flush_all_messages()
    result["users"][1].disconnect()
    array0.insert(0, ["x"])
    assert array1.to_json() == []
    result["users"][1].connect()
    compare(result["users"])


def test_delete_insert_circular(rng):
    result = init(rng, users=2)
    array0 = result["array0"]
    array0.insert(0, ["A", "B", "C"])
    array0.delete(1, 1)
    array0.insert(1, ["b"])
    assert array0.to_json() == ["A", "b", "C"]
    compare(result["users"])


def test_observer_event():
    doc = Y.Doc()
    arr = doc.get_array("arr")
    fired = {}

    def obs(event, txn):
        fired["added"] = len(event.changes["added"])
        fired["deleted"] = len(event.changes["deleted"])
        fired["delta"] = event.changes["delta"]

    arr.observe(obs)
    arr.insert(0, [1, 2])
    assert fired["added"] == 1
    assert fired["delta"] == [{"insert": [1, 2]}]
    arr.delete(0, 1)
    assert fired["deleted"] == 1
    assert fired["delta"] == [{"delete": 1}]


def test_observe_deep():
    doc = Y.Doc()
    arr = doc.get_array("arr")
    events = []
    arr.observe_deep(lambda evts, txn: events.append(evts))
    nested = Y.YMap()
    arr.insert(0, [nested])
    assert len(events) == 1
    nested.set("key", "value")
    assert len(events) == 2
    assert events[1][0].path == [0]


# -- randomized convergence fuzzing (reference y-array.tests.js:386-502) ----

_unique_counter = [0]


def _unique_number():
    _unique_counter[0] += 1
    return _unique_counter[0]


def _insert_generic(user, gen: random.Random):
    arr = user.get_array("array")
    pos = gen.randint(0, arr.length)
    arr.insert(pos, [_unique_number() for _ in range(gen.randint(1, 4))])


def _insert_type_array(user, gen: random.Random):
    arr = user.get_array("array")
    pos = gen.randint(0, arr.length)
    nested = Y.YArray()
    arr.insert(pos, [nested])
    nested.insert(0, [gen.randint(0, 10), gen.randint(0, 10)])


def _insert_text(user, gen: random.Random):
    arr = user.get_array("array")
    pos = gen.randint(0, arr.length)
    arr.insert(pos, ["str" + str(gen.randint(0, 100))])


def _delete_generic(user, gen: random.Random):
    arr = user.get_array("array")
    length = arr.length
    if length > 0:
        pos = gen.randint(0, length - 1)
        del_length = min(gen.randint(1, 2), length - pos)
        if gen.random() < 0.5:
            item = arr.get(pos)
            if isinstance(item, Y.YArray) and item.length > 0:
                pos2 = gen.randint(0, item.length - 1)
                item.delete(pos2, min(gen.randint(1, 2), item.length - pos2))
                return
        arr.delete(pos, del_length)


ARRAY_MODS = [_insert_generic, _insert_type_array, _insert_text, _delete_generic]


@pytest.mark.parametrize("iterations", [6, 40, 120])
def test_repeat_random_array_ops(rng, iterations):
    apply_random_tests(rng, ARRAY_MODS, iterations)


def test_slice():
    """(reference y-array.tests.js testSlice)."""
    doc = Y.Doc()
    arr = doc.get_array("array")
    arr.insert(0, [1, 2, 3])
    assert arr.slice(0) == [1, 2, 3]
    assert arr.slice(1) == [2, 3]
    assert arr.slice(0, -1) == [1, 2]
    arr.insert(0, [0])
    assert arr.slice(0) == [0, 1, 2, 3]
    assert arr.slice(0, 2) == [0, 1]


def test_concurrent_insert_delete_with_three_conflicts(rng):
    """(reference y-array.tests.js
    testConcurrentInsertDeleteWithThreeConflicts)."""
    result = init(rng, users=3)
    array0, array1, array2 = (
        result["array0"], result["array1"], result["array2"]
    )
    array0.insert(0, ["x", "y", "z"])
    result["testConnector"].flush_all_messages()
    array0.insert(1, [0])
    array1.delete(0, 1)
    array1.delete(1, 1)
    array2.insert(1, [2])
    compare(result["users"])


def test_deletions_in_late_sync(rng):
    """(reference y-array.tests.js testDeletionsInLateSync)."""
    result = init(rng, users=2)
    array0, array1 = result["array0"], result["array1"]
    array0.insert(0, ["x", "y"])
    result["testConnector"].flush_all_messages()
    result["users"][1].disconnect()
    array1.delete(1, 1)
    array0.delete(0, 2)
    result["users"][1].connect()
    compare(result["users"])


def test_insert_then_merge_delete_on_sync(rng):
    """(reference y-array.tests.js testInsertThenMergeDeleteOnSync)."""
    result = init(rng, users=2)
    array0, array1 = result["array0"], result["array1"]
    array0.insert(0, ["x", "y", "z"])
    result["testConnector"].flush_all_messages()
    result["users"][0].disconnect()
    array1.delete(0, 3)
    result["users"][0].connect()
    compare(result["users"])


def test_garbage_collector(rng):
    """(reference y-array.tests.js testGarbageCollector)."""
    result = init(rng, users=3)
    array0 = result["array0"]
    array0.insert(0, ["x", "y", "z"])
    result["testConnector"].flush_all_messages()
    result["users"][0].disconnect()
    array0.delete(0, 3)
    result["users"][0].connect()
    result["testConnector"].flush_all_messages()
    compare(result["users"])


def test_insert_and_delete_events(rng):
    """(reference y-array.tests.js testInsertAndDeleteEvents)."""
    result = init(rng, users=2)
    array0 = result["array0"]
    seen = []
    array0.observe(lambda e, _tr=None: seen.append(e))
    array0.insert(0, [0, 1, 2])
    assert len(seen) == 1
    array0.delete(0, 1)
    assert len(seen) == 2
    array0.delete(0, 2)
    assert len(seen) == 3
    compare(result["users"])


def test_nested_observer_events(rng):
    """Observer re-entrancy: an insert from inside an observer fires the
    observer again AFTER the current call completes (reference
    y-array.tests.js testNestedObserverEvents)."""
    result = init(rng, users=2)
    array0 = result["array0"]
    vals = []

    def obs(e, _tr=None):
        if array0.length == 1:
            array0.insert(1, [1])
            vals.append(0)
        else:
            vals.append(1)

    array0.observe(obs)
    array0.insert(0, [0])
    assert vals == [0, 1]
    assert array0.to_array() == [0, 1]
    compare(result["users"])


def test_change_event_payload(rng):
    """event.changes added/deleted sizes + delta shapes (reference
    y-array.tests.js testChangeEvent)."""
    result = init(rng, users=2)
    array0 = result["array0"]
    box = {}

    def obs(e, _tr=None):
        box["changes"] = e.changes

    array0.observe(obs)
    new_arr = Y.YArray()
    array0.insert(0, [new_arr, 4, "dtrn"])
    ch = box.pop("changes")
    assert len(ch["added"]) == 2 and len(ch["deleted"]) == 0
    assert ch["delta"] == [{"insert": [new_arr, 4, "dtrn"]}]
    array0.delete(0, 2)
    ch = box.pop("changes")
    assert len(ch["added"]) == 0 and len(ch["deleted"]) == 2
    assert ch["delta"] == [{"delete": 2}]
    array0.insert(1, [0.1])
    ch = box.pop("changes")
    assert len(ch["added"]) == 1 and len(ch["deleted"]) == 0
    assert ch["delta"] == [{"retain": 1}, {"insert": [0.1]}]
    compare(result["users"])


def test_event_target_is_set_correctly(rng):
    """(reference y-array.tests.js testEventTargetIsSetCorrectlyOnLocal /
    OnRemote)."""
    result = init(rng, users=3)
    array0, array1 = result["array0"], result["array1"]
    box = {}
    array0.observe(lambda e, _tr=None: box.__setitem__("t", e.target))
    array0.insert(0, ["stuff"])
    assert box["t"] is array0
    box2 = {}
    array1.observe(lambda e, _tr=None: box2.__setitem__("t", e.target))
    result["testConnector"].flush_all_messages()
    assert box2["t"] is array1
    compare(result["users"])


def test_iterating_array_containing_types():
    """(reference y-array.tests.js testIteratingArrayContainingTypes)."""
    y = Y.Doc()
    arr = y.get_array("arr")
    for i in range(10):
        m = Y.YMap()
        m.set("value", i)
        arr.push([m])
    for cnt, item in enumerate(arr.to_array()):
        assert item.get("value") == cnt
