"""YArray behavior + randomized convergence tests (scenarios modeled on
reference tests/y-array.tests.js)."""

import random

import pytest

import yjs_tpu as Y
from helpers import apply_random_tests, compare, init


def test_basic_insert_delete():
    doc = Y.Doc()
    arr = doc.get_array("arr")
    arr.insert(0, [1, 2, 3])
    arr.insert(1, ["x"])
    assert arr.to_json() == [1, "x", 2, 3]
    arr.delete(1, 2)
    assert arr.to_json() == [1, 3]
    arr.push([4])
    arr.unshift([0])
    assert arr.to_json() == [0, 1, 3, 4]
    assert arr.get(2) == 3
    assert arr.length == 4
    assert arr.slice(1, 3) == [1, 3]
    assert arr.slice(-2) == [3, 4]


def test_types_as_content():
    doc = Y.Doc()
    arr = doc.get_array("arr")
    nested = Y.YArray()
    arr.insert(0, [nested])
    nested.insert(0, ["inner"])
    m = Y.YMap({"k": 1})
    arr.push([m])
    assert arr.to_json() == [["inner"], {"k": 1}]


def test_insert_three_elements_try_re_get(rng):
    result = init(rng, users=2)
    array0, array1 = result["array0"], result["array1"]
    array0.insert(0, [1, True, False])
    assert array0.to_json() == [1, True, False]
    result["testConnector"].flush_all_messages()
    assert array1.to_json() == [1, True, False]
    compare(result["users"])


def test_concurrent_inserts_converge(rng):
    result = init(rng, users=3)
    array0, array1, array2 = result["array0"], result["array1"], result["array2"]
    array0.insert(0, [0])
    array1.insert(0, [1])
    array2.insert(0, [2])
    compare(result["users"])


def test_insertions_in_late_sync(rng):
    result = init(rng, users=3)
    tc = result["testConnector"]
    tc.flush_all_messages()
    result["users"][1].disconnect()
    result["users"][2].disconnect()
    result["array0"].insert(1, ["user0"]) if result["array0"].length > 0 else result[
        "array0"
    ].insert(0, ["user0"])
    result["array1"].insert(0, ["user1"])
    result["array2"].insert(0, ["user2"])
    result["users"][1].connect()
    result["users"][2].connect()
    compare(result["users"])


def test_disconnect_really_prevents_sending_messages(rng):
    result = init(rng, users=3)
    tc = result["testConnector"]
    array0, array1 = result["array0"], result["array1"]
    tc.flush_all_messages()
    result["users"][1].disconnect()
    array0.insert(0, ["x"])
    assert array1.to_json() == []
    result["users"][1].connect()
    compare(result["users"])


def test_delete_insert_circular(rng):
    result = init(rng, users=2)
    array0 = result["array0"]
    array0.insert(0, ["A", "B", "C"])
    array0.delete(1, 1)
    array0.insert(1, ["b"])
    assert array0.to_json() == ["A", "b", "C"]
    compare(result["users"])


def test_observer_event():
    doc = Y.Doc()
    arr = doc.get_array("arr")
    fired = {}

    def obs(event, txn):
        fired["added"] = len(event.changes["added"])
        fired["deleted"] = len(event.changes["deleted"])
        fired["delta"] = event.changes["delta"]

    arr.observe(obs)
    arr.insert(0, [1, 2])
    assert fired["added"] == 1
    assert fired["delta"] == [{"insert": [1, 2]}]
    arr.delete(0, 1)
    assert fired["deleted"] == 1
    assert fired["delta"] == [{"delete": 1}]


def test_observe_deep():
    doc = Y.Doc()
    arr = doc.get_array("arr")
    events = []
    arr.observe_deep(lambda evts, txn: events.append(evts))
    nested = Y.YMap()
    arr.insert(0, [nested])
    assert len(events) == 1
    nested.set("key", "value")
    assert len(events) == 2
    assert events[1][0].path == [0]


# -- randomized convergence fuzzing (reference y-array.tests.js:386-502) ----

_unique_counter = [0]


def _unique_number():
    _unique_counter[0] += 1
    return _unique_counter[0]


def _insert_generic(user, gen: random.Random):
    arr = user.get_array("array")
    pos = gen.randint(0, arr.length)
    arr.insert(pos, [_unique_number() for _ in range(gen.randint(1, 4))])


def _insert_type_array(user, gen: random.Random):
    arr = user.get_array("array")
    pos = gen.randint(0, arr.length)
    nested = Y.YArray()
    arr.insert(pos, [nested])
    nested.insert(0, [gen.randint(0, 10), gen.randint(0, 10)])


def _insert_text(user, gen: random.Random):
    arr = user.get_array("array")
    pos = gen.randint(0, arr.length)
    arr.insert(pos, ["str" + str(gen.randint(0, 100))])


def _delete_generic(user, gen: random.Random):
    arr = user.get_array("array")
    length = arr.length
    if length > 0:
        pos = gen.randint(0, length - 1)
        del_length = min(gen.randint(1, 2), length - pos)
        if gen.random() < 0.5:
            item = arr.get(pos)
            if isinstance(item, Y.YArray) and item.length > 0:
                pos2 = gen.randint(0, item.length - 1)
                item.delete(pos2, min(gen.randint(1, 2), item.length - pos2))
                return
        arr.delete(pos, del_length)


ARRAY_MODS = [_insert_generic, _insert_type_array, _insert_text, _delete_generic]


@pytest.mark.parametrize("iterations", [6, 40, 120])
def test_repeat_random_array_ops(rng, iterations):
    apply_random_tests(rng, ARRAY_MODS, iterations)
