"""Property-style transport-fault tests (ISSUE 2 satellite): updates
duplicated, reordered, and redelivered across providers always converge
to identical ``text()`` / state vectors — the CRDT idempotency and
commutativity contract (reference README.md:650-652) holds through the
provider/engine batch path, not just the CPU core.

Randomness comes from the deterministic per-test ``rng`` fixture
(conftest.py): failures reproduce, new YTPU_TEST_SEED values explore new
schedules."""

import yjs_tpu as Y
from yjs_tpu.provider import TpuProvider

ROOM = "r"


def _edit_stream(rng, n_ops=50, n_clients=3):
    """Incremental per-op updates from independent clients + the oracle
    text they merge to."""
    docs = []
    updates = []
    for k in range(n_clients):
        d = Y.Doc(gc=False)
        d.client_id = 7000 + k
        d.on("update", lambda u, origin, doc: updates.append(bytes(u)))
        docs.append(d)
    for _ in range(n_ops):
        d = rng.choice(docs)
        t = d.get_text("text")
        if len(t) and rng.random() < 0.3:
            t.delete(rng.randrange(len(t)), 1)
        else:
            t.insert(rng.randrange(len(t) + 1), rng.choice("abcdefgh "))
    oracle = Y.Doc(gc=False)
    for u in updates:
        Y.apply_update(oracle, u)
    return updates, str(oracle.get_text("text"))


def _settle(p):
    """Flush until parked (causally unready) traffic stops resolving."""
    for _ in range(8):
        p.flush()
        if not p.engine.has_pending(p.doc_id(ROOM)):
            break
    return p.text(ROOM)


def test_duplicated_updates_converge(rng):
    updates, oracle = _edit_stream(rng)
    pa, pb = TpuProvider(1), TpuProvider(1)
    for u in updates:
        for _ in range(rng.randrange(1, 4)):  # deliver 1-3 copies
            pa.receive_update(ROOM, u)
        pb.receive_update(ROOM, u)
    assert _settle(pa) == oracle
    assert _settle(pb) == oracle
    assert pa.state_vector(ROOM) == pb.state_vector(ROOM)


def test_reordered_updates_converge(rng):
    updates, oracle = _edit_stream(rng)
    shuffled = list(updates)
    rng.shuffle(shuffled)
    pa, pb = TpuProvider(1), TpuProvider(1)
    for u in shuffled:
        pa.receive_update(ROOM, u)
    for u in updates:
        pb.receive_update(ROOM, u)
    assert _settle(pa) == oracle
    assert _settle(pb) == oracle
    assert pa.state_vector(ROOM) == pb.state_vector(ROOM)


def test_redelivered_after_flush_converges(rng):
    """Redelivery of ALREADY-INTEGRATED updates (at-least-once
    transports) is a no-op, including interleaved with fresh traffic."""
    updates, oracle = _edit_stream(rng)
    p = TpuProvider(1)
    seen = []
    for u in updates:
        p.receive_update(ROOM, u)
        seen.append(u)
        if rng.random() < 0.2:
            p.flush()
            for old in rng.sample(seen, min(len(seen), 5)):
                p.receive_update(ROOM, old)
    # full redelivery storm at the end
    for u in rng.sample(updates, len(updates)):
        p.receive_update(ROOM, u)
    assert _settle(p) == oracle


def test_mixed_schedules_cross_converge(rng):
    """Every provider sees the same updates under a DIFFERENT schedule
    (order, duplication, flush points) — all end byte-identical."""
    updates, oracle = _edit_stream(rng)
    provs = [TpuProvider(1) for _ in range(3)]
    for p in provs:
        sched = list(updates)
        rng.shuffle(sched)
        for u in sched:
            p.receive_update(ROOM, u)
            if rng.random() < 0.5:
                p.receive_update(ROOM, u)  # immediate duplicate
            if rng.random() < 0.1:
                p.flush()
    texts = [_settle(p) for p in provs]
    assert texts == [oracle] * 3
    svs = [p.state_vector(ROOM) for p in provs]
    assert svs[0] == svs[1] == svs[2]
