"""Pytest config: force a virtual 8-device CPU mesh for sharding tests
(the real TPU path is exercised by bench.py / the driver)."""

import hashlib
import os
import random

import pytest

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# hermetic + fast: the suite never needs a real accelerator (bench.py and
# the driver exercise the TPU path); forcing the CPU platform keeps engine
# tests off a potentially contended/skewed tunnel chip.  The ambient env
# may pin JAX_PLATFORMS to an accelerator plugin and site hooks may have
# imported jax already, so set both the env and the live config (backends
# are not initialized yet at conftest time).  YTPU_TEST_PLATFORM overrides.
_platform = os.environ.get("YTPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
# engine list/text/map/delta exports read back DEVICE state in tests so
# the oracle comparisons validate the kernels' output (typed events are
# host-plan-derived by design; production defaults to the host list walk
# and test_host_export_matches_device pins the two equal)
os.environ.setdefault("YTPU_EXPORT_DEVICE", "1")
import sys

if "jax" in sys.modules:
    import jax

    try:
        jax.config.update("jax_platforms", _platform)
    except Exception:
        pass


def pytest_configure(config):
    # registered here (no pytest.ini) so -W error runs stay clean:
    # "slow" gates long soak tests out of tier-1 (-m 'not slow');
    # "chaos" tags the fault-injection convergence suite — in tier-1 by
    # default (deterministic seeds), deselectable with -m 'not chaos'
    config.addinivalue_line(
        "markers", "slow: long soak tests excluded from tier-1"
    )
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection convergence tests",
    )
    # "durability" tags the WAL/recovery suite (ISSUE 3) — in tier-1 by
    # default (tmp-dir local, deterministic), deselectable with
    # -m 'not durability'
    config.addinivalue_line(
        "markers",
        "durability: write-ahead-log persistence and crash-recovery tests",
    )
    # "network" tags the session-layer suite (ISSUE 5) — in tier-1 by
    # default (in-memory pipes, deterministic seeds), deselectable with
    # -m 'not network'
    config.addinivalue_line(
        "markers",
        "network: peer-session, retransmission, and network-chaos tests",
    )
    # "fleet" tags the sharded-provider-fleet suite (ISSUE 6) — in
    # tier-1 by default (deterministic, tmp-dir WALs), deselectable
    # with -m 'not fleet'; ci_check.sh also runs it standalone first
    config.addinivalue_line(
        "markers",
        "fleet: doc-sharded fleet routing, migration, and rebalancing "
        "tests",
    )
    # "tiering" tags the heat-driven doc-lifecycle suite (ISSUE 7) —
    # in tier-1 by default (deterministic, injected clocks, tmp-dir
    # WALs), deselectable with -m 'not tiering'; ci_check.sh also runs
    # it standalone
    config.addinivalue_line(
        "markers",
        "tiering: hot/warm/cold doc lifecycle, demand promotion, and "
        "tier GC tests",
    )
    # "failover" tags the replication + failure-detection suite
    # (ISSUE 8) — in tier-1 by default (tick-deterministic detector,
    # seeded chaos), deselectable with -m 'not failover';
    # ci_check.sh also runs it standalone
    config.addinivalue_line(
        "markers",
        "failover: shard replication, failure detection, and "
        "automatic-failover tests",
    )
    # "planner" tags the plan-cache + segment-planning suite (ISSUE 9)
    # — in tier-1 by default (deterministic seeded traces),
    # deselectable with -m 'not planner'; ci_check.sh also runs it
    # standalone
    config.addinivalue_line(
        "markers",
        "planner: frontier-keyed plan cache and segment-sorted "
        "planning tests",
    )
    # "admission" tags the rate-limit + brownout suite (ISSUE 10) — in
    # tier-1 by default (tick-deterministic controller, tmp-dir WALs),
    # deselectable with -m 'not admission'; ci_check.sh also runs it
    # standalone
    config.addinivalue_line(
        "markers",
        "admission: token-bucket rate limits, weighted-fair queuing, "
        "and brownout degradation tests",
    )
    # "loadgen" tags the multi-tenant overload-harness suite (ISSUE 10)
    # — in tier-1 by default (seeded tick-deterministic load), it is
    # the slowest of the marker suites, deselectable with
    # -m 'not loadgen'
    config.addinivalue_line(
        "markers",
        "loadgen: seeded multi-tenant overload harness tests",
    )
    # "tracing" tags the causal-tracing + flight-recorder + federation
    # suite (ISSUE 11) — in tier-1 by default (deterministic hashed
    # trace ids), deselectable with -m 'not tracing'; ci_check.sh also
    # runs it standalone
    config.addinivalue_line(
        "markers",
        "tracing: distributed trace propagation, black-box flight "
        "recorder, and metrics-federation tests",
    )
    # "flushpipe" tags the pipelined-flush + donation + adaptive-tick
    # suite (ISSUE 12) — in tier-1 by default (seeded traces, byte-
    # identity oracles), deselectable with -m 'not flushpipe';
    # ci_check.sh also runs it standalone first
    config.addinivalue_line(
        "markers",
        "flushpipe: pipelined flush path, buffer donation, and "
        "adaptive flush-tick tests",
    )
    # "analysis" tags the ytpu-lint static-analysis suite (ISSUE 13) —
    # in tier-1 by default (pure-ast, fixtures are parsed not
    # imported), deselectable with -m 'not analysis'; ci_check.sh also
    # runs it standalone
    config.addinivalue_line(
        "markers",
        "analysis: ytpu-lint checker, suppression, and baseline tests",
    )
    # "cluster" tags the process-native cluster suite (ISSUE 14) — in
    # tier-1 by default (real OS processes on loopback sockets, tmp-dir
    # WALs; it spawns real shard subprocesses so it is among the slower
    # marker suites), deselectable with -m 'not cluster'; ci_check.sh
    # also runs it standalone first
    config.addinivalue_line(
        "markers",
        "cluster: multiprocess shard supervisor, RPC fabric, and "
        "y-websocket gateway tests",
    )
    # "admin" tags the per-process introspection plane (ISSUE 16):
    # HTTP admin endpoints, health/readiness probes, scrape-mode
    # federation, and the bench-regression gate's comparison logic
    config.addinivalue_line(
        "markers",
        "admin: HTTP admin endpoints, health probes, scrape "
        "federation, and bench-gate tests",
    )
    # "geo" tags the multi-region active-active replication suite
    # (ISSUE 17) — in tier-1 by default (in-memory pipes, seeded WAN
    # chaos, tmp-dir WALs), deselectable with -m 'not geo'; ci_check.sh
    # also runs it standalone first
    config.addinivalue_line(
        "markers",
        "geo: multi-region replication, WAN chaos convergence, and "
        "partition-recovery tests",
    )
    # "tsdb" tags the embedded time-series store suite (ISSUE 19) — in
    # tier-1 by default (injected clocks, tmp-dir persistence),
    # deselectable with -m 'not tsdb'; ci_check.sh also runs it
    # standalone first
    config.addinivalue_line(
        "markers",
        "tsdb: embedded TSDB codec, downsampling, persistence, "
        "torn-read, and range-query tests",
    )
    # "cost" tags the cost-attribution ledger suite (ISSUE 19) — in
    # tier-1 by default (deterministic seams), deselectable with
    # -m 'not cost'
    config.addinivalue_line(
        "markers",
        "cost: per-doc/per-tenant cost-ledger attribution, top-K "
        "bounding, and capacity-model tests",
    )


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """On failure, surface the deterministic seeds a test ran with so
    the exact chaos/loadgen schedule can be replayed from the report
    alone (the seeds live in fixtures/attributes, not the traceback)."""
    outcome = yield
    report = outcome.get_result()
    if report.when != "call" or not report.failed:
        return
    seeds = {}
    env_seed = os.environ.get("YTPU_TEST_SEED")
    if env_seed is not None:
        seeds["YTPU_TEST_SEED"] = env_seed
    for attr in ("chaos_seed", "loadgen_seed", "seed"):
        v = getattr(item, attr, None)
        if v is not None:
            seeds[attr] = v
    if seeds:
        report.sections.append((
            "deterministic seeds",
            " ".join(f"{k}={v}" for k, v in sorted(seeds.items())),
        ))


@pytest.fixture
def rng(request):
    """Deterministic per-test PRNG; vary YTPU_TEST_SEED for new random runs
    (the reference randomizes via lib0/testing's per-run seeds)."""
    seed = os.environ.get("YTPU_TEST_SEED", "0")
    digest = hashlib.md5(f"{request.node.nodeid}:{seed}".encode()).hexdigest()
    return random.Random(int(digest[:16], 16))
