"""Pytest config: force a virtual 8-device CPU mesh for sharding tests
(the real TPU path is exercised by bench.py / the driver)."""

import hashlib
import os
import random

import pytest

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()


@pytest.fixture
def rng(request):
    """Deterministic per-test PRNG; vary YTPU_TEST_SEED for new random runs
    (the reference randomizes via lib0/testing's per-run seeds)."""
    seed = os.environ.get("YTPU_TEST_SEED", "0")
    digest = hashlib.md5(f"{request.node.nodeid}:{seed}".encode()).hexdigest()
    return random.Random(int(digest[:16], 16))
