"""Multi-tenant overload harness suite (ISSUE 10 acceptance): the
seeded tick-deterministic load generator drives mixed-profile
populations (editors, idlers, a reconnector, a lossy link, direct
abusive writers) against a replicated fleet at a computed multiple of
its admission capacity, and asserts the contracts the admission layer
sells: zero acked-update loss, byte-identical convergence, an unpaged
interactive SLO while background sheds, bounded brownout recovery, and
delta-resume (not full-resync) failover under brownout.

In tier-1; the ``loadgen`` marker deselects it with ``-m 'not
loadgen'`` (scripts/ci_check.sh also runs it standalone).
"""

from __future__ import annotations

import pytest

from yjs_tpu.admission import AdmissionConfig
from yjs_tpu.fleet import FailoverConfig, FleetRouter
from yjs_tpu.loadgen import LoadGen, LoadGenConfig
from yjs_tpu.persistence import WalConfig

pytestmark = pytest.mark.loadgen


def overloaded_fleet(**adm_kw):
    base = dict(
        enabled=True, tenant_rate=1.0, tenant_burst=4,
        doc_rate=1.0, doc_burst=4, queue_max=64, drain_batch=32,
        down_ticks=4,
    )
    base.update(adm_kw)
    return FleetRouter(2, 32, admission_config=AdmissionConfig(**base))


def run_harness(fleet, seed=42, ticks=120, **lg_kw):
    lg = LoadGen(fleet, LoadGenConfig(seed=seed, n_clients=12, **lg_kw))
    lg.run(ticks)
    lg.drain()
    return lg


def test_seed_determinism():
    reports = []
    for _ in range(2):
        lg = run_harness(overloaded_fleet(), seed=42, ticks=60)
        reports.append(lg.report())
    # byte-identical replay: same seed, same schedule, same outcome
    assert reports[0] == reports[1]


def test_seed_changes_schedule():
    a = run_harness(overloaded_fleet(), seed=42, ticks=60).report()
    b = run_harness(overloaded_fleet(), seed=43, ticks=60).report()
    assert a["edits"] != b["edits"] or a["admission"] != b["admission"]


def test_2x_overload_invariants(request):
    request.node.loadgen_seed = 42
    fleet = overloaded_fleet()
    lg = run_harness(fleet, seed=42, ticks=120)
    rep = lg.report()
    assert rep["overload_factor"] >= 2.0
    assert rep["shed_fraction"] > 0.05  # the surplus really shed
    # the harness contracts: no acked loss (byte-identical rooms), the
    # interactive SLO never paged, brownout back at normal
    lg.assert_invariants()
    # every session paid exactly its one initial full resync
    assert all(v <= 1 for v in rep["session_full_resyncs"])


def test_brownout_engages_and_recovers(request):
    request.node.loadgen_seed = 7
    fleet = overloaded_fleet(
        tenant_rate=0.5, tenant_burst=2, doc_rate=0.5, doc_burst=2,
        queue_max=16, drain_batch=4, up_ticks=2, down_ticks=6,
    )
    lg = run_harness(fleet, seed=7, ticks=120, flush_every=8)
    rep = lg.report()
    # ~4x offered: the controller must actually climb...
    assert rep["overload_factor"] >= 2.0
    assert rep["max_level"] >= 1
    assert rep["transitions"]
    # ...journal/meter each step (levels only move one step at a time,
    # and every transition carries a typed reason)
    names = ("normal", "shed-background", "coalesce", "reject-writes")
    order = {n: i for i, n in enumerate(names)}
    for t in rep["transitions"]:
        assert abs(order[t["to"]] - order[t["from"]]) == 1
        assert t["reason"]
    # ...and return to normal within a bounded window once load stops
    assert rep["recovery_ticks"] <= 200
    lg.assert_invariants()


@pytest.mark.chaos
def test_kill_primary_during_brownout(request, tmp_path):
    """Acceptance: a primary dies while the fleet is browned out; the
    survivors fail over via delta resume (full_resyncs stays at the one
    initial handshake each) and the drained fleet is byte-identical."""
    request.node.loadgen_seed = 7
    fleet = FleetRouter(
        3, 32, wal_dir=tmp_path,
        wal_config=WalConfig(fsync="never"),
        failover_config=FailoverConfig(
            suspect_ticks=2, confirm_ticks=1, jitter_ticks=0,
        ),
        admission_config=AdmissionConfig(
            enabled=True, tenant_rate=0.5, tenant_burst=2,
            doc_rate=0.5, doc_burst=2, queue_max=16, drain_batch=4,
            up_ticks=2, down_ticks=6,
        ),
    )
    lg = LoadGen(fleet, LoadGenConfig(seed=7, n_clients=12, flush_every=8))
    state = {"killed": None, "revived": False}

    def on_tick(lg_):
        adm = fleet.admission
        if state["killed"] is None and adm.level >= 1 and lg_.tick >= 24:
            # the brownout is live: kill the primary of the first
            # session room mid-traffic
            guid = next(
                c.guid for c in lg_.clients if hasattr(c, "session")
            )
            victim = fleet.owner_of(guid)
            if victim is not None:
                fleet.kill_shard(victim)
                state["killed"] = victim
        elif (
            state["killed"] is not None
            and not state["revived"]
            and state["killed"] in fleet._down
        ):
            fleet.revive_shard(state["killed"])
            state["revived"] = True

    lg.run(120, on_tick=on_tick)
    assert state["killed"] is not None, "brownout never engaged"
    assert state["revived"]
    lg.drain()
    rep = lg.report()
    assert rep["max_level"] >= 1
    lg.assert_invariants()
    # delta-resume failover: each surviving session's only full resync
    # is its initial handshake
    assert rep["session_full_resyncs"]
    assert all(v == 1 for v in rep["session_full_resyncs"])
