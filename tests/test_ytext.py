"""YText behavior + formatting + randomized convergence (scenarios modeled
on reference tests/y-text.tests.js)."""

import random

import pytest

import yjs_tpu as Y
from helpers import apply_random_tests, compare, init


def test_basic_insert_delete(rng):
    result = init(rng, users=2)
    text0 = result["text0"]
    text0.insert(0, "abc")
    assert text0.to_string() == "abc"
    text0.delete(0, 1)
    text0.delete(1, 1)
    assert text0.to_string() == "b"
    text0.insert(0, "z")
    assert text0.to_string() == "zb"
    result["testConnector"].flush_all_messages()
    assert result["text1"].to_string() == "zb"
    compare(result["users"])


def test_concurrent_inserts(rng):
    result = init(rng, users=3)
    result["text0"].insert(0, "abc")
    result["testConnector"].flush_all_messages()
    result["text0"].insert(1, "0")
    result["text1"].insert(1, "1")
    result["text2"].insert(2, "2")
    compare(result["users"])


def test_formatting_basic():
    doc = Y.Doc()
    text = doc.get_text("text")
    text.insert(0, "bold plain", {"bold": True})
    text.format(4, 6, {"bold": None})
    delta = text.to_delta()
    assert delta == [
        {"insert": "bold", "attributes": {"bold": True}},
        {"insert": " plain"},
    ]


def test_formatting_overlap():
    doc = Y.Doc()
    text = doc.get_text("text")
    text.insert(0, "abcdef")
    text.format(0, 4, {"bold": True})
    text.format(2, 4, {"italic": True})
    assert text.to_delta() == [
        {"insert": "ab", "attributes": {"bold": True}},
        {"insert": "cd", "attributes": {"bold": True, "italic": True}},
        {"insert": "ef", "attributes": {"italic": True}},
    ]


def test_insert_inherits_attributes():
    doc = Y.Doc()
    text = doc.get_text("text")
    text.insert(0, "ab", {"bold": True})
    # inserting inside the bold range without explicit attrs inherits bold
    text.insert(1, "X")
    assert text.to_delta() == [{"insert": "aXb", "attributes": {"bold": True}}]


def test_delta_event():
    doc = Y.Doc()
    text = doc.get_text("text")
    deltas = []
    text.observe(lambda e, txn: deltas.append(e.delta))
    text.insert(0, "abc", {"bold": True})
    assert deltas[-1] == [{"insert": "abc", "attributes": {"bold": True}}]
    text.delete(0, 1)
    assert deltas[-1] == [{"delete": 1}]
    text.insert(2, "z")
    assert deltas[-1] == [{"retain": 2}, {"insert": "z", "attributes": {"bold": True}}]


def test_apply_delta():
    doc = Y.Doc()
    text = doc.get_text("text")
    text.apply_delta(
        [
            {"insert": "Gandalf", "attributes": {"bold": True}},
            {"insert": " the "},
            {"insert": "Grey", "attributes": {"color": "#ccc"}},
        ]
    )
    assert text.to_delta() == [
        {"insert": "Gandalf", "attributes": {"bold": True}},
        {"insert": " the "},
        {"insert": "Grey", "attributes": {"color": "#ccc"}},
    ]
    text.apply_delta([{"retain": 7}, {"delete": 5}, {"insert": ", "}])
    assert text.to_string() == "Gandalf, Grey"


def test_embed():
    doc = Y.Doc()
    text = doc.get_text("text")
    text.insert(0, "ab")
    text.insert_embed(1, {"image": "x.png"}, {"width": 100})
    delta = text.to_delta()
    assert delta == [
        {"insert": "a"},
        {"insert": {"image": "x.png"}, "attributes": {"width": 100}},
        {"insert": "b"},
    ]


def test_text_attributes():
    doc = Y.Doc()
    text = doc.get_text("text")
    text.set_attribute("block", "quote")
    assert text.get_attribute("block") == "quote"
    assert text.get_attributes() == {"block": "quote"}
    text.remove_attribute("block")
    assert text.get_attributes() == {}


def test_surrogate_pair_split():
    doc = Y.Doc()
    text = doc.get_text("text")
    text.insert(0, "a\U0001f600b")  # astral char occupies 2 UTF-16 units
    assert text.length == 4
    # delete only the first half of the surrogate pair: both halves become FFFD
    text.delete(1, 1)
    assert text.length == 3
    u = Y.encode_state_as_update(doc)
    doc2 = Y.Doc()
    Y.apply_update(doc2, u)
    assert doc2.get_text("text").to_string() == doc.get_text("text").to_string()


def test_concurrent_formatting_converges(rng):
    result = init(rng, users=3)
    result["text0"].insert(0, "abcdef")
    result["testConnector"].flush_all_messages()
    result["text0"].format(0, 6, {"bold": True})
    result["text1"].format(0, 3, {"italic": True})
    result["text2"].delete(2, 2)
    compare(result["users"])


def test_large_insertions(rng):
    result = init(rng, users=2)
    text0 = result["text0"]
    gen = rng
    for _ in range(200):
        pos = gen.randint(0, text0.length)
        text0.insert(pos, "a")
    for _ in range(40):
        if text0.length > 2:
            pos = gen.randint(0, text0.length - 2)
            text0.delete(pos, 2)
    compare(result["users"])


# -- randomized fuzz with quill-like ops (reference y-text.tests.js:555-619)

_ATTRS = [{}, {"bold": True}, {"italic": True}, {"color": "red"}]


def _insert_text(user, gen: random.Random):
    text = user.get_text("text")
    pos = gen.randint(0, text.length)
    attrs = gen.choice(_ATTRS)
    s = "text" + str(gen.randint(0, 100)) + " "
    if attrs:
        text.insert(pos, s, attrs)
    else:
        text.insert(pos, s)


def _delete_text(user, gen: random.Random):
    text = user.get_text("text")
    if text.length > 0:
        pos = gen.randint(0, text.length - 1)
        text.delete(pos, min(gen.randint(1, 4), text.length - pos))


def _format_text(user, gen: random.Random):
    text = user.get_text("text")
    if text.length > 0:
        pos = gen.randint(0, text.length - 1)
        length = min(gen.randint(1, 5), text.length - pos)
        attrs = gen.choice([{"bold": True}, {"bold": None}, {"italic": True}])
        text.format(pos, length, attrs)


def _insert_embed(user, gen: random.Random):
    text = user.get_text("text")
    pos = gen.randint(0, text.length)
    text.insert_embed(pos, {"image": "img.png"})


TEXT_MODS = [_insert_text, _delete_text, _format_text, _insert_embed]


@pytest.mark.parametrize("iterations", [6, 40, 100])
def test_repeat_random_text_ops(rng, iterations):
    apply_random_tests(rng, TEXT_MODS, iterations)


def test_get_delta_with_embeds(rng):
    """(reference y-text.tests.js testGetDeltaWithEmbeds)."""
    result = init(rng, users=1)
    text0 = result["text0"]
    text0.apply_delta([{"insert": {"linebreak": "s"}}])
    assert text0.to_delta() == [{"insert": {"linebreak": "s"}}]


def test_to_json(rng):
    """(reference y-text.tests.js testToJson)."""
    result = init(rng, users=1)
    text0 = result["text0"]
    text0.insert(0, "abc", {"bold": True})
    assert text0.to_json() == "abc"


def test_to_delta_embed_attributes(rng):
    """(reference y-text.tests.js testToDeltaEmbedAttributes)."""
    result = init(rng, users=1)
    text0 = result["text0"]
    text0.insert(0, "ab", {"bold": True})
    text0.insert_embed(1, {"image": "imageSrc.png"}, {"width": 100})
    assert text0.to_delta() == [
        {"insert": "a", "attributes": {"bold": True}},
        {"insert": {"image": "imageSrc.png"}, "attributes": {"width": 100}},
        {"insert": "b", "attributes": {"bold": True}},
    ]


def test_to_delta_embed_no_attributes(rng):
    """(reference y-text.tests.js testToDeltaEmbedNoAttributes)."""
    result = init(rng, users=1)
    text0 = result["text0"]
    text0.insert(0, "ab", {"bold": True})
    text0.insert_embed(1, {"image": "imageSrc.png"})
    assert text0.to_delta() == [
        {"insert": "a", "attributes": {"bold": True}},
        {"insert": {"image": "imageSrc.png"}},
        {"insert": "b", "attributes": {"bold": True}},
    ]


def test_formatting_removed(rng):
    """Format-cleanup corner: deleting every formatted char leaves one
    struct (reference y-text.tests.js testFormattingRemoved)."""
    result = init(rng, users=1)
    text0 = result["text0"]
    text0.insert(0, "ab", {"bold": True})
    text0.delete(0, 2)
    assert len(Y.get_type_children(text0)) == 1


def test_formatting_removed_in_mid_text(rng):
    """(reference y-text.tests.js testFormattingRemovedInMidText)."""
    result = init(rng, users=1)
    text0 = result["text0"]
    text0.insert(0, "1234")
    text0.insert(2, "ab", {"bold": True})
    text0.delete(2, 2)
    assert len(Y.get_type_children(text0)) == 3


def test_append_chars(rng):
    """(reference y-text.tests.js testAppendChars, N scaled down)."""
    result = init(rng, users=1)
    text0 = result["text0"]
    n = 3000
    for _ in range(n):
        text0.insert(text0.length, "a")
    assert text0.length == n


def test_text_snapshot_diff(rng):
    """Two-snapshot diff with ychange (reference y-text.tests.js
    testSnapshot)."""
    result = init(rng, users=1)
    text0 = result["text0"]
    doc0 = text0.doc
    doc0.gc = False
    text0.apply_delta([{"insert": "abcd"}])
    snapshot1 = Y.snapshot(doc0)
    text0.apply_delta([{"retain": 1}, {"insert": "x"}, {"delete": 1}])
    snapshot2 = Y.snapshot(doc0)
    text0.apply_delta(
        [{"retain": 2}, {"delete": 3}, {"insert": "x"}, {"delete": 1}]
    )
    assert text0.to_delta(snapshot1) == [{"insert": "abcd"}]
    assert text0.to_delta(snapshot2) == [{"insert": "axcd"}]
    state2_diff = text0.to_delta(snapshot2, snapshot1)
    for v in state2_diff:
        if "attributes" in v and "ychange" in v["attributes"]:
            v["attributes"]["ychange"].pop("user", None)
    assert state2_diff == [
        {"insert": "a"},
        {"insert": "x", "attributes": {"ychange": {"type": "added"}}},
        {"insert": "b", "attributes": {"ychange": {"type": "removed"}}},
        {"insert": "cd"},
    ]


def test_text_snapshot_delete_after(rng):
    """(reference y-text.tests.js testSnapshotDeleteAfter)."""
    result = init(rng, users=1)
    text0 = result["text0"]
    text0.doc.gc = False
    text0.apply_delta([{"insert": "abcd"}])
    snapshot1 = Y.snapshot(text0.doc)
    text0.apply_delta([{"retain": 4}, {"insert": "e"}])
    assert text0.to_delta(snapshot1) == [{"insert": "abcd"}]
