"""Snapshots (scenarios modeled on reference tests/snapshot.tests.js)."""

import yjs_tpu as Y


def test_basic_restore_snapshot():
    doc = Y.Doc(gc=False)
    doc.get_array("array").insert(0, ["hello"])
    snap = Y.snapshot(doc)
    doc.get_array("array").insert(1, ["world"])
    doc_restored = Y.create_doc_from_snapshot(doc, snap)
    assert doc_restored.get_array("array").to_json() == ["hello"]
    assert doc.get_array("array").to_json() == ["hello", "world"]


def test_empty_restore_snapshot():
    doc = Y.Doc(gc=False)
    snap = Y.snapshot(doc)
    snap.sv[9999] = 0
    doc.get_array("array").insert(0, ["world"])
    doc_restored = Y.create_doc_from_snapshot(doc, snap)
    assert doc_restored.get_array("array").to_json() == []
    # now this snapshot reflects the latest state; should still work
    snap2 = Y.snapshot(doc)
    doc_restored2 = Y.create_doc_from_snapshot(doc, snap2)
    assert doc_restored2.get_array("array").to_json() == ["world"]


def test_restore_snapshot_with_subtype():
    doc = Y.Doc(gc=False)
    doc.get_array("array").insert(0, [Y.YText("when")])
    snap = Y.snapshot(doc)
    doc.get_array("array").get(0).insert(0, "out ")
    doc_restored = Y.create_doc_from_snapshot(doc, snap)
    assert [t.to_string() for t in doc_restored.get_array("array").to_array()] == ["when"]
    assert [t.to_string() for t in doc.get_array("array").to_array()] == ["out when"]


def test_restore_deleted_item():
    doc = Y.Doc(gc=False)
    doc.get_array("array").insert(0, ["item1", "item2"])
    snap = Y.snapshot(doc)
    doc.get_array("array").delete(0)
    doc_restored = Y.create_doc_from_snapshot(doc, snap)
    assert doc_restored.get_array("array").to_json() == ["item1", "item2"]


def test_restore_left_item():
    doc = Y.Doc(gc=False)
    doc.get_array("array").insert(0, ["item1"])
    doc.get_map("map").set("test", "ok")
    doc.get_array("array").insert(0, ["item0"])
    snap = Y.snapshot(doc)
    doc.get_array("array").insert(0, ["item-1"])
    doc_restored = Y.create_doc_from_snapshot(doc, snap)
    assert doc_restored.get_array("array").to_json() == ["item0", "item1"]
    assert doc_restored.get_map("map").get("test") == "ok"


def test_ydoc_snapshot_visibility_text():
    doc = Y.Doc(gc=False)
    text = doc.get_text("text")
    text.insert(0, "world!")
    snapshot1 = Y.snapshot(doc)
    text.insert(0, "hello ")
    snapshot2 = Y.snapshot(doc)
    text.delete(0, 5)
    # render with two-snapshot diff + ychange attribution
    delta = text.to_delta(snapshot2, snapshot1)
    assert any(
        op.get("attributes", {}).get("ychange", {}).get("type") == "added"
        for op in delta
    )
    state1 = text.to_delta(snapshot1)
    assert state1 == [{"insert": "world!"}]
    state2 = text.to_delta(snapshot2)
    assert state2 == [{"insert": "hello world!"}]


def test_snapshot_encoding_roundtrip():
    doc = Y.Doc(gc=False)
    doc.get_text("t").insert(0, "abc")
    doc.get_text("t").delete(1, 1)
    snap = Y.snapshot(doc)
    for enc, dec in (
        (Y.encode_snapshot, Y.decode_snapshot),
        (Y.encode_snapshot_v2, Y.decode_snapshot_v2),
    ):
        restored = dec(enc(snap))
        assert Y.equal_snapshots(snap, restored)


def test_is_visible():
    doc = Y.Doc(gc=False)
    text = doc.get_text("t")
    text.insert(0, "abc")
    snap = Y.snapshot(doc)
    text.insert(3, "later")
    item = text._start
    assert Y.is_visible(item, snap)
    assert Y.is_visible(item, None) == (not item.deleted)


def test_deleted_items_base():
    """(reference snapshot.tests.js testDeletedItemsBase)."""
    doc = Y.Doc(gc=False)
    doc.get_array("array").insert(0, ["item1"])
    doc.get_array("array").delete(0, 1)
    snap = Y.snapshot(doc)
    doc.get_array("array").insert(0, ["item0"])
    restored = Y.create_doc_from_snapshot(doc, snap)
    assert restored.get_array("array").to_array() == []
    assert doc.get_array("array").to_array() == ["item0"]


def test_deleted_items_2():
    """(reference snapshot.tests.js testDeletedItems2)."""
    doc = Y.Doc(gc=False)
    doc.get_array("array").insert(0, ["item1", "item2", "item3"])
    doc.get_array("array").delete(1, 1)
    snap = Y.snapshot(doc)
    doc.get_array("array").insert(0, ["item0"])
    restored = Y.create_doc_from_snapshot(doc, snap)
    assert restored.get_array("array").to_array() == ["item1", "item3"]
    assert doc.get_array("array").to_array() == ["item0", "item1", "item3"]


def test_dependent_changes(rng):
    """(reference snapshot.tests.js testDependentChanges)."""
    from helpers import init

    result = init(rng, users=2)
    array0, array1 = result["array0"], result["array1"]
    tcn = result["testConnector"]
    array0.doc.gc = False
    array1.doc.gc = False
    array0.insert(0, ["user1item1"])
    tcn.sync_all()
    array1.insert(1, ["user2item1"])
    tcn.sync_all()
    snap = Y.snapshot(array0.doc)
    array0.insert(2, ["user1item2"])
    tcn.sync_all()
    array1.insert(3, ["user2item2"])
    tcn.sync_all()
    restored0 = Y.create_doc_from_snapshot(array0.doc, snap)
    assert restored0.get_array("array").to_array() == [
        "user1item1", "user2item1"
    ]
    restored1 = Y.create_doc_from_snapshot(array1.doc, snap)
    assert restored1.get_array("array").to_array() == [
        "user1item1", "user2item1"
    ]
