"""Pipelined-flush suite (ISSUE 12 acceptance).

The correctness bar for the single pipelined flush path is byte-identity:
``YTPU_FLUSH_PIPELINE=1`` (double-buffered staging, donated device
tables, async dispatch) must produce the same encoded states, texts, and
emitted deltas as ``=0`` (the synchronous A/B path) under every seeded
trace shape — including a primary killed mid-pipelined-flush and a
crash-mid-flush WAL recovery.  On top of that: a cached plan adopted
AFTER the leader's tables were donated must never alias freed device
buffers, and the adaptive flush tick must tighten under SLO burn, widen
when idle, and coalesce under brownout.

Deterministic seeded traces; in tier-1; the ``flushpipe`` marker
deselects it with ``-m 'not flushpipe'`` and ci_check.sh runs it
standalone first.
"""

import random

import pytest

import yjs_tpu as Y
from yjs_tpu.fleet import FailoverConfig, FleetRouter
from yjs_tpu.obs import FLUSH_METRICS_SCHEMA
from yjs_tpu.ops import BatchEngine, plan_cache
from yjs_tpu.ops.native_mirror import native_plan_available
from yjs_tpu.persistence import WalConfig
from yjs_tpu.provider import FlushTickController, TpuProvider
from yjs_tpu.updates import (
    apply_update,
    encode_state_as_update,
    encode_state_vector,
)

pytestmark = pytest.mark.flushpipe

SMALL = WalConfig(segment_bytes=256, fsync="never")
FAST = FailoverConfig(suspect_ticks=2, confirm_ticks=1, jitter_ticks=0)

# the 20-seed corpus from the acceptance matrix, cycling trace shapes
CORPUS_SEEDS = tuple(range(20))
SHAPES = ("prepend", "interleaved", "storm")


@pytest.fixture(autouse=True)
def _fresh_cache():
    plan_cache.reset_cache()
    yield
    plan_cache.reset_cache()


# -- seeded traces ------------------------------------------------------------


def make_trace(shape: str, seed: int, n_ops: int = 60) -> list[bytes]:
    """Incremental updates from concurrent seeded editors (the
    test_plan_cache texture: prepend / interleaved / conflict-storm).
    Generated ONCE per seed — both pipeline modes replay the SAME
    bytes, so any divergence is the flush path's fault."""
    n_clients = 4 if shape == "storm" else 3
    sync_p = 0.05 if shape == "storm" else 0.4
    gen = random.Random(seed)
    docs = []
    for k in range(n_clients):
        d = Y.Doc(gc=False)
        d.client_id = 100 + k
        docs.append(d)
    out = []
    for _ in range(n_ops):
        j = gen.randrange(n_clients)
        d = docs[j]
        t = d.get_text("text")
        sv = encode_state_vector(d)
        if shape == "prepend":
            t.insert(0, gen.choice("abcdef") * gen.randint(1, 3))
        elif shape == "storm":
            t.insert(min(len(t), gen.randrange(3)), gen.choice("xyz "))
        elif len(t) and gen.random() < 0.25:
            t.delete(gen.randrange(len(t)), 1)
        else:
            t.insert(gen.randrange(len(t) + 1), gen.choice("abcdef "))
        out.append(encode_state_as_update(d, sv))
        if gen.random() < sync_p:
            k = gen.randrange(n_clients)
            if k != j:
                apply_update(docs[k], encode_state_as_update(d))
    return out


def run_engine(updates, n_docs, pipeline, monkeypatch, flush_every=5):
    """Drive one engine over ``updates`` (broadcast to every doc);
    returns encoded states, texts, emitted deltas, and the flush-metrics
    keysets + last metrics dict."""
    monkeypatch.setenv("YTPU_FLUSH_PIPELINE", "1" if pipeline else "0")
    eng = BatchEngine(n_docs)
    deltas = {i: [] for i in range(n_docs)}
    eng.on_update(lambda i, u: deltas[i].append(u))
    keysets = set()
    for j, u in enumerate(updates):
        for i in range(n_docs):
            eng.queue_update(i, u)
        if (j + 1) % flush_every == 0 or j == len(updates) - 1:
            eng.flush()
            keysets.add(frozenset(eng.last_flush_metrics))
    states = [
        Y.merge_updates([eng.encode_state_as_update(i)])
        for i in range(n_docs)
    ]
    texts = [eng.text(i) for i in range(n_docs)]
    return states, texts, deltas, keysets, eng


def oracle_state(updates) -> bytes:
    d = Y.Doc(gc=False)
    for u in updates:
        apply_update(d, u)
    return Y.merge_updates([encode_state_as_update(d)])


# -- one dispatch path --------------------------------------------------------


def test_exactly_one_flush_dispatch_path():
    """The three pre-ISSUE-12 flush bodies are gone: every kernel
    launch funnels through the single ``_dispatch`` seam."""
    assert hasattr(BatchEngine, "_dispatch")
    assert hasattr(BatchEngine, "_flush_bulk")
    for legacy in ("_flush_apply", "_flush_apply_batched"):
        assert not hasattr(BatchEngine, legacy), legacy


# -- metrics schema: every path, both modes -----------------------------------


@pytest.mark.parametrize("pipeline", [True, False])
@pytest.mark.parametrize("kernel", ["apply", "levels", "seq"])
def test_schema_complete_on_every_path(kernel, pipeline, monkeypatch):
    """Every flush entry point (native batched apply, python apply,
    device-YATA levels/seq) emits the ONE shared metrics schema —
    including the pipeline fields — in both pipeline modes."""
    monkeypatch.setenv("YTPU_KERNEL", kernel)
    updates = make_trace("interleaved", seed=3, n_ops=20)
    _s, _t, _d, keysets, eng = run_engine(updates, 2, pipeline, monkeypatch)
    assert keysets == {frozenset(FLUSH_METRICS_SCHEMA)}
    m = eng.last_flush_metrics
    assert m["t_pack_overlap_s"] >= 0.0
    assert m["t_device_wait_s"] >= 0.0
    assert m["flush_donated"] in (0, 1)
    if not pipeline:
        # sync A/B path: each dispatch is drained before the next, so
        # the pipeline never reports depth
        assert m["pipeline_depth"] == 0


def test_python_mirror_path_emits_schema(monkeypatch):
    monkeypatch.setenv("YTPU_NO_NATIVE_PLAN", "1")
    updates = make_trace("interleaved", seed=4, n_ops=20)
    _s, _t, _d, keysets, _e = run_engine(updates, 2, True, monkeypatch)
    assert keysets == {frozenset(FLUSH_METRICS_SCHEMA)}


def _distinct_doc_engine(n_docs, monkeypatch, mode="device"):
    """One engine whose docs each carry a DISTINCT trace (no cache
    dedup), flushed once cold — the fan-out shape plan_threads must
    report (ISSUE 15 satellite: it used to report 1 on batched paths)."""
    monkeypatch.setenv("YTPU_PLAN_SEGMENT", mode)
    monkeypatch.setenv("YTPU_PLAN_CACHE", "0")
    eng = BatchEngine(n_docs)
    for i in range(n_docs):
        for u in make_trace("interleaved", seed=100 + i, n_ops=12):
            eng.queue_update(i, u)
    eng.flush()
    return eng.last_flush_metrics


def test_plan_threads_reports_py_chunk_fanout(monkeypatch):
    """Python path, device mode: the whole-chunk segment planner
    co-plans every cold doc in one call — plan_threads reports that
    fan-out, not 1."""
    monkeypatch.setenv("YTPU_NO_NATIVE_PLAN", "1")
    m = _distinct_doc_engine(4, monkeypatch)
    assert m["plan_threads"] == 4
    # the off lane plans per doc, serially
    m_off = _distinct_doc_engine(4, monkeypatch, mode="off")
    assert m_off["plan_threads"] == 1


def test_plan_threads_reports_native_pool_width(monkeypatch):
    if not native_plan_available():
        pytest.skip("native plancore unavailable")
    monkeypatch.setenv("YTPU_PLAN_THREADS", "3")
    m = _distinct_doc_engine(4, monkeypatch)
    # min(configured pool width, cold docs in the batch)
    assert m["plan_threads"] == 3


def test_steady_state_flush_donates(monkeypatch):
    """After the warm-up flush sized the tables, steady-state pipelined
    flushes reallocate nothing: donation hit rate 1.0."""
    updates = make_trace("interleaved", seed=5, n_ops=40)
    monkeypatch.setenv("YTPU_FLUSH_PIPELINE", "1")
    eng = BatchEngine(2)
    for u in updates[:20]:
        for i in range(2):
            eng.queue_update(i, u)
    eng.flush()  # warm-up: allocates, may grow
    for u in updates[20:]:
        for i in range(2):
            eng.queue_update(i, u)
    eng.flush()
    m = eng.last_flush_metrics
    if m["realloc_bytes"] == 0:  # no growth this flush: must donate
        assert m["flush_donated"] == 1
    assert m["pipeline_depth"] >= 1


# -- donation aliasing (satellite 2) ------------------------------------------


@pytest.mark.parametrize("native", [True, False])
def test_cached_plan_adopted_after_donation_no_alias(native, monkeypatch):
    """A follower adopting a cached plan AFTER the leader's device
    tables were donated (and the leader kept flushing, recycling that
    memory) must replay byte-identically — the entry may hold host
    state only, never a donated ``jax.Array``."""
    if native and not native_plan_available():
        pytest.skip("native plancore unavailable")
    if not native:
        monkeypatch.setenv("YTPU_NO_NATIVE_PLAN", "1")
    monkeypatch.setenv("YTPU_PLAN_CACHE", "1")
    monkeypatch.setenv("YTPU_FLUSH_PIPELINE", "1")
    updates = make_trace("prepend", seed=6, n_ops=40)
    extra = make_trace("interleaved", seed=7, n_ops=40)
    # leader populates the cache; every one of its dispatches donated
    # the tables the cached plans were built against
    s1, t1, _d, _k, leader = run_engine(updates, 2, True, monkeypatch)
    # leader keeps flushing OTHER traffic: the donated buffers are
    # freed and their memory recycled before the follower replays
    for j, u in enumerate(extra):
        leader.queue_update(0, u)
        if (j + 1) % 5 == 0:
            leader.flush()
    leader.flush()
    # follower replays the original trace purely from cache hits
    s2, t2, _d2, _k2, follower = run_engine(updates, 2, True, monkeypatch)
    assert s2 == s1
    assert t2 == t1
    assert s2[0] == oracle_state(updates)
    m = follower.last_flush_metrics
    if native:
        assert m["plan_cache_hits"] > 0


# -- the 20-seed pipeline on/off corpus (satellite 3) -------------------------


@pytest.mark.parametrize("seed", CORPUS_SEEDS)
def test_pipeline_on_off_byte_identical(seed, monkeypatch):
    """Acceptance bar: the SAME update bytes through pipeline-on and
    pipeline-off engines converge to byte-identical states, texts, and
    emitted deltas — across all 20 corpus seeds / 3 trace shapes."""
    updates = make_trace(SHAPES[seed % 3], seed=100 + seed)
    plan_cache.reset_cache()
    s_on, t_on, d_on, keys_on, _e = run_engine(
        updates, 2, True, monkeypatch
    )
    plan_cache.reset_cache()
    s_off, t_off, d_off, keys_off, _e = run_engine(
        updates, 2, False, monkeypatch
    )
    assert t_on == t_off
    assert s_on == s_off
    assert d_on == d_off
    assert keys_on == keys_off == {frozenset(FLUSH_METRICS_SCHEMA)}
    assert s_on[0] == oracle_state(updates)


# -- kill-primary-mid-pipelined-flush -----------------------------------------


def _seeded_rooms(seed, n_rooms=4, n_ops=8):
    out = {}
    for j in range(n_rooms):
        gen = random.Random(seed * 1000 + j)
        d = Y.Doc(gc=False)
        d.client_id = 100 + j
        t = d.get_text("text")
        updates = []
        d.on("update", lambda u, origin, doc: updates.append(bytes(u)))
        for _ in range(n_ops):
            t.insert(gen.randrange(len(t) + 1), gen.choice("abcdef "))
        out[f"room-{j}"] = (d, updates)
    return out


def _edit(doc, text):
    sv = encode_state_vector(doc)
    doc.get_text("text").insert(0, text)
    return encode_state_as_update(doc, sv)


def _convict(fleet, shard, budget=16):
    for _ in range(budget):
        fleet.tick()
        if shard in fleet._down:
            return
    raise AssertionError(f"shard {shard} never convicted")


@pytest.mark.fleet
@pytest.mark.chaos
@pytest.mark.parametrize("pipeline", [True, False])
def test_kill_primary_mid_pipelined_flush(pipeline, tmp_path, monkeypatch):
    """The primary dies right after a pipelined flush — async dispatches
    possibly still in flight — with a fresh acked tail never flushed.
    Failover must surface every acked byte in both pipeline modes."""
    monkeypatch.setenv("YTPU_FLUSH_PIPELINE", "1" if pipeline else "0")
    fleet = FleetRouter(
        3, 4, wal_dir=tmp_path, wal_config=SMALL, failover_config=FAST
    )
    rooms = _seeded_rooms(seed=21)
    for g, (_d, ups) in rooms.items():
        for u in ups:
            fleet.receive_update(g, u)
    fleet.flush()  # pipelined: returns with dispatches still in flight
    fleet.tick()  # replica copies seeded
    victim = fleet.owner_of("room-0")
    owned = [g for g in rooms if fleet.owner_of(g) == victim]
    assert owned
    for g in owned:  # acked but never flushed: the nastiest tail
        fleet.receive_update(g, _edit(rooms[g][0], "tail!"))
    fleet.kill_shard(victim)
    _convict(fleet, victim)
    for g, (d, _ups) in rooms.items():
        assert fleet.owner_of(g) is not None
        got = Y.merge_updates([fleet.encode_state_as_update(g)])
        want = Y.merge_updates([encode_state_as_update(d)])
        assert got == want, g


# -- crash-mid-flush WAL recovery ---------------------------------------------


@pytest.mark.durability
@pytest.mark.chaos
@pytest.mark.parametrize("pipeline", [True, False])
def test_crash_mid_flush_wal_recovery(pipeline, tmp_path, monkeypatch):
    """kill -9 between flushes (pipeline possibly mid-dispatch, dirty
    updates journaled but unflushed): recovery replays the WAL to the
    exact same bytes in both pipeline modes."""
    monkeypatch.setenv("YTPU_FLUSH_PIPELINE", "1" if pipeline else "0")
    updates = make_trace("interleaved", seed=8, n_ops=40)
    ref = TpuProvider(2)
    for u in updates:
        ref.receive_update("room", u)
    ref.flush()
    victim = TpuProvider(2, wal_dir=tmp_path, wal_config=SMALL)
    c = len(updates) // 2
    for j, u in enumerate(updates[:c]):
        victim.receive_update("room", u)
        if (j + 1) % 5 == 0:
            victim.flush()
    # a flush just dispatched + more acked updates queued behind it —
    # then the process dies with no seal-time fsync
    victim.receive_update("room", updates[c - 1])
    victim.wal.abandon()
    rec = TpuProvider.recover(
        tmp_path, n_docs=2, wal_config=SMALL
    )
    for u in updates[c:]:
        rec.receive_update("room", u)
    rec.flush()
    got = Y.merge_updates([rec.encode_state_as_update("room")])
    want = Y.merge_updates([ref.encode_state_as_update("room")])
    assert got == want


# -- adaptive flush tick ------------------------------------------------------


def test_tick_controller_widens_idle_tightens_on_burn(monkeypatch):
    monkeypatch.setenv("YTPU_FLUSH_TICK_MIN_MS", "2")
    monkeypatch.setenv("YTPU_FLUSH_TICK_MAX_MS", "64")
    monkeypatch.setenv("YTPU_FLUSH_TICK_GROW", "2")
    c = FlushTickController()
    assert c.window("ok") == 2.0
    # idle ticks widen geometrically, clamped at the max
    for want in (4.0, 8.0, 16.0, 32.0, 64.0, 64.0):
        c.applied(0.0, c.window("ok"), busy=False)
        assert c.window("ok") == want
    # busy ticks hold the window
    c.applied(0.0, c.window("ok"), busy=True)
    assert c.window("ok") == 64.0
    # an SLO burn verdict snaps straight back to the minimum
    assert c.window("page") == 2.0
    assert c.window("ok") == 2.0  # and stays there until idle again


def test_tick_controller_brownout_inputs():
    c = FlushTickController()
    # force_coalesce pins the window to the maximum regardless of state
    assert c.window("ok", coalesce=True) == c.max_ms
    # the brownout scale multiplies (never divides) the window
    assert c.window("ok", scale=4.0) == c.min_ms * 4.0
    assert c.window("ok", scale=0.25) == c.min_ms


def test_tick_controller_due_and_history():
    c = FlushTickController()
    assert c.due(0.0, 10.0)  # first tick is always due
    c.applied(0.0, 10.0, busy=True)
    assert not c.due(0.005, 10.0)
    assert c.due(0.010, 10.0)
    c.applied(0.010, 12.0, busy=True)
    p = c.percentiles()
    assert p["p50_ms"] in (10.0, 12.0) and p["p99_ms"] == 12.0


def test_provider_flush_tick(monkeypatch):
    monkeypatch.setenv("YTPU_FLUSH_TICK_MIN_MS", "2")
    prov = TpuProvider(2)
    d = Y.Doc(gc=False)
    d.get_text("text").insert(0, "hello")
    prov.receive_update("room", encode_state_as_update(d))
    assert prov.flush_tick(now=0.0) is True  # dirty + due: flushed
    assert prov.text("room") == "hello"
    # idle tick: runs (due), flushes nothing, widens the window
    w0 = prov.flush_ticks.window_ms
    assert prov.flush_tick(now=1.0) is False
    assert prov.flush_ticks.window_ms > w0
    # inside the widened window: not due, dirty work waits
    prov.receive_update("room", _edit(d, "x"))
    assert prov.flush_tick(now=1.0005) is False
    assert prov._dirty
    # past the window: the queued edit flushes
    assert prov.flush_tick(now=2.0) is True
    assert prov.text("room") == "xhello"


@pytest.mark.fleet
def test_fleet_flush_tick_fans_out(tmp_path):
    fleet = FleetRouter(2, 4, wal_dir=tmp_path, wal_config=SMALL)
    d = Y.Doc(gc=False)
    d.get_text("text").insert(0, "fan-out")
    fleet.receive_update("room-a", encode_state_as_update(d))
    assert fleet.flush_tick(now=0.0) is True
    assert fleet.text("room-a") == "fan-out"
    assert fleet.flush_tick(now=100.0) is False  # everyone idle
