"""Gateway wire compatibility (ISSUE 14, satellite 3).

Drives the cluster gateway's y-websocket dialect with raw v13.4.9
frames — including the Yjs-generated compat fixture documents — and
asserts byte-identical step2/update responses, the unknown-message
tolerance contract, and awareness passthrough.  Runs over
:class:`LocalCluster` (in-process fleet): the dialect code is identical
over the multiprocess fabric, which ``tests/test_cluster.py`` covers."""

import base64
import hashlib
import json
import os
import socket
import tempfile
import threading
import time

import pytest

import yjs_tpu as Y
from yjs_tpu.cluster import Gateway, LocalCluster
from yjs_tpu.cluster.config import GatewayConfig
from yjs_tpu.cluster.gateway import (
    MESSAGE_AWARENESS,
    MESSAGE_QUERY_AWARENESS,
    MESSAGE_SYNC,
    ws_accept_key,
)
from yjs_tpu.fleet import FleetRouter
from yjs_tpu.lib0 import decoding, encoding
from yjs_tpu.lib0.decoding import Decoder
from yjs_tpu.lib0.encoding import Encoder
from yjs_tpu.sync import protocol

pytestmark = pytest.mark.cluster

FIXTURES = json.load(
    open(os.path.join(os.path.dirname(__file__), "fixtures", "compat_v1.json"))
)


class WsClient:
    """A minimal stdlib y-websocket client: RFC 6455 handshake, masked
    binary frames out, buffered unmasked frames in."""

    def __init__(self, port: int, room: str):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=20)
        self._buf = b""
        key = base64.b64encode(os.urandom(16)).decode("ascii")
        self.sock.sendall(
            (
                f"GET /{room} HTTP/1.1\r\nHost: t\r\nUpgrade: websocket\r\n"
                f"Connection: Upgrade\r\nSec-WebSocket-Key: {key}\r\n"
                "Sec-WebSocket-Version: 13\r\n\r\n"
            ).encode("ascii")
        )
        resp = b""
        while b"\r\n\r\n" not in resp:
            chunk = self.sock.recv(4096)
            if not chunk:
                raise AssertionError("handshake EOF")
            resp += chunk
        head, _, rest = resp.partition(b"\r\n\r\n")
        self._buf = rest  # a coalesced first frame stays buffered
        assert b" 101 " in head.split(b"\r\n")[0] + b" ", head
        # the server must prove it hashed our key (RFC 6455 §4.2.2)
        accept = [
            ln.split(b":", 1)[1].strip()
            for ln in head.split(b"\r\n")
            if ln.lower().startswith(b"sec-websocket-accept")
        ]
        assert accept and accept[0].decode() == ws_accept_key(key)

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(4096)
            if not chunk:
                raise AssertionError("unexpected EOF")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def read_message(self) -> bytes:
        while True:
            hdr = self._recv_exact(2)
            opcode = hdr[0] & 0x0F
            ln = hdr[1] & 0x7F
            if ln == 126:
                ln = int.from_bytes(self._recv_exact(2), "big")
            elif ln == 127:
                ln = int.from_bytes(self._recv_exact(8), "big")
            payload = self._recv_exact(ln) if ln else b""
            if opcode in (0x1, 0x2):
                return payload
            if opcode == 0x8:
                raise AssertionError("server closed")
            # ping/pong/continuation: skip for these single-frame tests

    def send(self, payload: bytes) -> None:
        mask = os.urandom(4)
        masked = bytes(b ^ mask[i & 3] for i, b in enumerate(payload))
        n = len(payload)
        hdr = bytes([0x82])
        if n < 126:
            hdr += bytes([0x80 | n])
        elif n < 1 << 16:
            hdr += bytes([0x80 | 126]) + n.to_bytes(2, "big")
        else:
            hdr += bytes([0x80 | 127]) + n.to_bytes(8, "big")
        self.sock.sendall(hdr + mask + masked)

    def send_sync(self, inner: bytes) -> None:
        enc = Encoder()
        encoding.write_var_uint(enc, MESSAGE_SYNC)
        self.send(enc.to_bytes() + inner)

    def read_sync(self) -> bytes:
        """Next sync message's inner frame (skips awareness traffic)."""
        while True:
            msg = self.read_message()
            dec = Decoder(msg)
            if decoding.read_var_uint(dec) == MESSAGE_SYNC:
                return bytes(msg[dec.pos:])

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def sync_step1_frame(sv: bytes) -> bytes:
    enc = Encoder()
    encoding.write_var_uint(enc, protocol.MESSAGE_YJS_SYNC_STEP_1)
    encoding.write_var_uint8_array(enc, sv)
    return enc.to_bytes()


def sync_step2_frame(update: bytes) -> bytes:
    enc = Encoder()
    encoding.write_var_uint(enc, protocol.MESSAGE_YJS_SYNC_STEP_2)
    encoding.write_var_uint8_array(enc, update)
    return enc.to_bytes()


def sync_update_frame(update: bytes) -> bytes:
    enc = Encoder()
    encoding.write_var_uint(enc, protocol.MESSAGE_YJS_UPDATE)
    encoding.write_var_uint8_array(enc, update)
    return enc.to_bytes()


@pytest.fixture(scope="module")
def gw():
    fleet = FleetRouter(
        n_shards=2, docs_per_shard=16, backend="cpu",
        wal_dir=tempfile.mkdtemp(prefix="ytpu-gwwire-"),
    )
    gateway = Gateway(
        LocalCluster(fleet), config=GatewayConfig(port=0)
    ).start()
    yield gateway
    gateway.close()
    fleet.close()


def test_ws_handshake_opens_with_step1(gw):
    c = WsClient(gw.port, "hs-room")
    inner = c.read_sync()
    dec = Decoder(inner)
    assert decoding.read_var_uint(dec) == protocol.MESSAGE_YJS_SYNC_STEP_1
    decoding.read_var_uint8_array(dec)  # a well-formed state vector
    assert not dec.has_content()
    c.close()


@pytest.mark.parametrize(
    "name,root,getter",
    [
        ("testArrayCompatibilityV1", "array", "to_json"),
        ("testMapDecodingCompatibilityV1", "map", "to_json"),
        ("testTextDecodingCompatibilityV1", "text", "to_delta"),
    ],
)
def test_compat_fixture_step2_byte_identical(gw, name, root, getter):
    """Seed a room with a Yjs-v13-generated document, then drive the
    gateway with a raw step 1 and assert the step 2 payload is
    byte-identical to the engine's own diff — the gateway adds and
    removes nothing on the wire."""
    fx = FIXTURES[name]
    old = base64.b64decode(fx["oldDoc"])
    room = f"compat-{root}"
    assert gw.cluster.receive_update(room, old)
    gw.cluster.flush(room)
    reference = gw.cluster.diff_update(room, b"\x00")

    c = WsClient(gw.port, room)
    c.read_sync()  # server's opening step1
    c.send_sync(sync_step1_frame(b"\x00"))  # empty SV: give me everything
    inner = c.read_sync()
    dec = Decoder(inner)
    assert decoding.read_var_uint(dec) == protocol.MESSAGE_YJS_SYNC_STEP_2
    payload = decoding.read_var_uint8_array(dec)
    assert payload == reference, (
        f"step2 not byte-identical: {hashlib.sha256(payload).hexdigest()[:16]}"
        f" != {hashlib.sha256(reference).hexdigest()[:16]}"
    )
    # and the bytes integrate to exactly the recorded fixture value
    doc = Y.Doc()
    Y.apply_update(doc, payload)
    got = getattr(getattr(doc, f"get_{root}")(root), getter)()
    assert got == fx["oldVal"]
    c.close()


def test_ws_update_applies_and_fans_out(gw):
    room = "fanout-room"
    a = WsClient(gw.port, room)
    b = WsClient(gw.port, room)
    a.read_sync()
    b.read_sync()

    doc = Y.Doc(gc=False)
    doc.client_id = 77
    doc.get_text("text").insert(0, "ws edit")
    update = Y.encode_state_as_update(doc)
    a.send_sync(sync_update_frame(update))

    deadline = time.time() + 15
    while time.time() < deadline:
        if gw.cluster.text(room) == "ws edit":
            break
        time.sleep(0.05)
    assert gw.cluster.text(room) == "ws edit"

    # the room's other member receives a flush-merged update frame
    inner = b.read_sync()
    dec = Decoder(inner)
    assert decoding.read_var_uint(dec) == protocol.MESSAGE_YJS_UPDATE
    merged = decoding.read_var_uint8_array(dec)
    doc_b = Y.Doc()
    Y.apply_update(doc_b, merged)
    assert doc_b.get_text("text").to_string() == "ws edit"
    a.close()
    b.close()


def test_unknown_outer_message_skipped(gw):
    """The y-protocols tolerance contract: an unknown outer type is
    counted and skipped; the connection keeps serving sync traffic."""
    room = "tolerant-room"
    c = WsClient(gw.port, room)
    c.read_sync()
    before = gw.metrics.unknown.value
    c.send(bytes([42]) + b"\x01\x02\x03")  # outer type 42: not a thing
    c.send_sync(sync_step1_frame(b"\x00"))  # must still be answered
    inner = c.read_sync()
    assert inner[0] == protocol.MESSAGE_YJS_SYNC_STEP_2
    assert gw.metrics.unknown.value == before + 1
    c.close()


def test_step2_from_plain_reader_applies(gw):
    """A plain y-protocols reader answers our step1 with step2; the
    gateway must apply it exactly like an update."""
    room = "plain-step2"
    c = WsClient(gw.port, room)
    c.read_sync()
    doc = Y.Doc(gc=False)
    doc.client_id = 88
    doc.get_text("text").insert(0, "via step2")
    c.send_sync(sync_step2_frame(Y.encode_state_as_update(doc)))
    deadline = time.time() + 15
    while time.time() < deadline:
        if gw.cluster.text(room) == "via step2":
            break
        time.sleep(0.05)
    assert gw.cluster.text(room) == "via step2"
    c.close()


def test_split_get_still_sniffs_websocket_dialect(gw):
    """TCP may deliver the request head split — a first segment of just
    ``G`` must still classify as the ws dialect, not fall through to a
    raw length-prefixed frame parse that kills the connection."""
    sock = socket.create_connection(("127.0.0.1", gw.port), timeout=20)
    key = base64.b64encode(os.urandom(16)).decode("ascii")
    request = (
        "GET /split-room HTTP/1.1\r\nHost: t\r\nUpgrade: websocket\r\n"
        f"Connection: Upgrade\r\nSec-WebSocket-Key: {key}\r\n"
        "Sec-WebSocket-Version: 13\r\n\r\n"
    ).encode("ascii")
    sock.sendall(request[:1])  # just 'G'
    time.sleep(0.3)  # let the sniffer peek the short head
    sock.sendall(request[1:])
    resp = b""
    while b"\r\n\r\n" not in resp:
        chunk = sock.recv(4096)
        assert chunk, "gateway dropped the split-GET connection"
        resp += chunk
    assert b" 101 " in resp.split(b"\r\n")[0] + b" "
    sock.close()


def test_localcluster_fanout_runs_on_dispatch_thread(tmp_path):
    """The deadlock-fix pin: LocalCluster must deliver ``on_update``
    from its dedicated dispatch thread, never synchronously from inside
    the fleet's flush — that path runs under the facade lock, and a
    subscriber taking the gateway lock there would invert the
    gateway's gw._lock → cluster-lock order."""
    fleet = FleetRouter(
        n_shards=1, docs_per_shard=8, backend="cpu",
        wal_dir=str(tmp_path / "wal"),
    )
    cluster = LocalCluster(fleet)
    try:
        seen = []
        done = threading.Event()

        def on_update(guid, update):
            seen.append(threading.current_thread().name)
            # re-entering the facade from the callback must be legal
            # (the gateway reads state vectors during fan-out handling)
            cluster.state_vector_bytes(guid)
            done.set()

        cluster.on_update = on_update
        doc = Y.Doc(gc=False)
        doc.client_id = 7
        doc.get_text("text").insert(0, "thread pin")
        assert cluster.receive_update(
            "pin-room", Y.encode_state_as_update(doc)
        )
        cluster.flush("pin-room")
        assert done.wait(30), "fan-out never fired"
        assert seen[0] == "ytpu-localcluster-evt"
    finally:
        cluster.close()


def test_awareness_passthrough_and_query(gw):
    room = "aware-room"
    a = WsClient(gw.port, room)
    b = WsClient(gw.port, room)
    a.read_sync()
    b.read_sync()

    # a fabricated awareness update payload (opaque to the gateway)
    enc = Encoder()
    encoding.write_var_uint(enc, MESSAGE_AWARENESS)
    encoding.write_var_uint8_array(enc, b"\x01\x02awareness-blob")
    frame = enc.to_bytes()
    a.send(frame)

    # b receives the passthrough byte-identically
    msg = b.read_message()
    assert msg == frame

    # a late joiner can query the cached state
    late = WsClient(gw.port, room)
    late.read_sync()
    enc = Encoder()
    encoding.write_var_uint(enc, MESSAGE_QUERY_AWARENESS)
    late.send(enc.to_bytes())
    msg = late.read_message()
    assert msg == frame
    a.close()
    b.close()
    late.close()
