"""Fleet chaos suite (ISSUE 6 acceptance): a sharded fleet under doc
churn is killed mid-migration (every WAL abandoned, double-delivery
window open, an in-flight edit in the air) and recovered from the
per-shard WAL root.  Pins: byte-identical convergence against
uninterrupted CPU reference docs, every doc owned by EXACTLY one shard,
and the recovered fleet keeps taking traffic.

Deterministic end to end (seeded edits, blake2b placement, simulated
crashes via ``WriteAheadLog.abandon``).  In tier-1 under the ``fleet``
+ ``chaos`` + ``durability`` markers.
"""

import random

import pytest

import yjs_tpu as Y
from yjs_tpu.fleet import FleetConfig, FleetRouter
from yjs_tpu.persistence import WalConfig
from yjs_tpu.updates import encode_state_as_update, encode_state_vector

pytestmark = [
    pytest.mark.fleet, pytest.mark.chaos, pytest.mark.durability,
]

SMALL = WalConfig(segment_bytes=256, fsync="never")


def seeded_rooms(seed, n_rooms=8, n_ops=12):
    """room -> (reference Doc, incremental update stream), seeded."""
    out = {}
    for j in range(n_rooms):
        gen = random.Random(seed * 1000 + j)
        d = Y.Doc(gc=False)
        d.client_id = 100 + j
        updates = []
        d.on("update", lambda u, origin, doc: updates.append(bytes(u)))
        t = d.get_text("text")
        for _ in range(n_ops):
            if len(t) and gen.random() < 0.3:
                t.delete(gen.randrange(len(t)), 1)
            else:
                t.insert(gen.randrange(len(t) + 1), gen.choice("abcdef "))
        out[f"room-{j}"] = (d, updates)
    return out


def edit(doc, text, pos=0):
    """One more reference edit, returned as its incremental update."""
    sv = encode_state_vector(doc)
    doc.get_text("text").insert(pos, text)
    return encode_state_as_update(doc, sv)


def canonical(fleet, guid):
    return Y.merge_updates([fleet.encode_state_as_update(guid)])


def canonical_doc(doc):
    return Y.merge_updates([encode_state_as_update(doc)])


def slot_owners(fleet):
    """guid -> [shards actually holding an engine slot for it]."""
    out = {}
    for k, p in enumerate(fleet.shards):
        for g in p.guids():
            out.setdefault(g, []).append(k)
    return out


def crash(fleet):
    """Kill every shard: no close, no checkpoint, handles dropped."""
    for p in fleet.shards:
        p.wal.abandon()


def test_kill_fleet_mid_migration_recovers_to_single_owner(tmp_path):
    rooms = seeded_rooms(seed=6)
    cfg = FleetConfig(
        rebalance_high=0.75, rebalance_target=0.5, rebalance_batch=4,
    )
    fleet = FleetRouter(
        3, 4, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
        config=cfg,
    )
    # churn: 8 rooms of seeded traffic — past any single shard's 4
    # slots, so admission only works because placement sharded
    for g, (_d, ups) in rooms.items():
        for u in ups:
            fleet.receive_update(g, u)
    fleet.flush()
    # a rebalance pass (shards that filled to the high watermark shed;
    # every move is itself an intent+release-journaled migration)
    fleet.tick()

    # open a migration window, then lose power with it OPEN and an
    # in-flight edit double-delivered but never released
    guid = "room-0"
    src = fleet.shard_of(guid)
    dst = next(
        k for k in fleet.live_shards
        if k != src and fleet._load(k) < fleet._capacity(k)
    )
    fleet.begin_migration(guid, dst)
    fleet.receive_update(guid, edit(rooms[guid][0], "tail!"))
    fleet.flush()
    crash(fleet)
    del fleet

    rec = FleetRouter.recover(
        tmp_path, docs_per_shard=4, backend="cpu", wal_config=SMALL,
    )
    # the open intent resolved by completing the handoff (the
    # destination had journaled the doc's state)
    res = rec.last_recovery["resolution"]
    assert res["completed"] == 1 and res["deduped"] == 0
    assert rec.owner_of(guid) == dst

    # exactly one shard holds each doc, and the routing table agrees
    own = slot_owners(rec)
    assert sorted(own) == sorted(rooms)
    for g, holders in own.items():
        assert holders == [rec.owner_of(g)]

    # byte-identical reconvergence — including the in-window tail edit
    for g, (d, _ups) in rooms.items():
        assert rec.text(g) == str(d.get_text("text"))
        assert canonical(rec, g) == canonical_doc(d)

    # the recovered fleet is live: more traffic converges
    for g in ("room-0", "room-5"):
        rec.receive_update(g, edit(rooms[g][0], "after "))
        assert rec.text(g) == str(rooms[g][0].get_text("text"))


def test_intent_only_crash_aborts_to_source(tmp_path):
    """Crash between the intent append and the state transfer: the
    destination never admitted the doc, so recovery aborts the
    migration and the source keeps sole ownership."""
    fleet = FleetRouter(
        2, 2, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
    )
    d = Y.Doc(gc=False)
    d.client_id = 1
    d.get_text("text").insert(0, "stay")
    fleet.receive_update("room", encode_state_as_update(d))
    fleet.flush()
    src = fleet.shard_of("room")
    fleet.shards[src].journal_migration("room", 1 - src, fleet.table.epoch)
    crash(fleet)
    del fleet

    rec = FleetRouter.recover(
        tmp_path, docs_per_shard=2, backend="cpu", wal_config=SMALL,
    )
    res = rec.last_recovery["resolution"]
    assert res["aborted"] == 1 and res["completed"] == 0
    assert rec.owner_of("room") == src
    assert slot_owners(rec)["room"] == [src]
    assert rec.text("room") == "stay"


def test_release_marker_closes_the_window_durably(tmp_path):
    """Crash AFTER complete_migration: the source's release record is
    the durable handoff marker, so recovery resurrects nothing on the
    source and resolves no intents."""
    fleet = FleetRouter(
        2, 2, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
    )
    d = Y.Doc(gc=False)
    d.client_id = 2
    d.get_text("text").insert(0, "moved")
    fleet.receive_update("room", encode_state_as_update(d))
    src = fleet.shard_of("room")
    fleet.migrate_doc("room", 1 - src)
    crash(fleet)
    del fleet

    rec = FleetRouter.recover(
        tmp_path, docs_per_shard=2, backend="cpu", wal_config=SMALL,
    )
    res = rec.last_recovery["resolution"]
    assert res == {
        "completed": 0, "aborted": 0, "deduped": 0,
        "fenced": 0, "replicas_folded": 0, "replica_promoted": 0,
    }
    assert rec.owner_of("room") == 1 - src
    assert slot_owners(rec)["room"] == [1 - src]
    assert rec.text("room") == "moved"


def test_checkpoint_then_crash_keeps_open_window_recoverable(tmp_path):
    """Compaction drops the segment the intent lived in; the fleet
    checkpoint re-journals open intents, so a crash AFTER a checkpoint
    taken mid-window still resolves to exactly one owner."""
    fleet = FleetRouter(
        2, 2, backend="cpu", wal_dir=tmp_path, wal_config=SMALL,
    )
    d = Y.Doc(gc=False)
    d.client_id = 3
    d.get_text("text").insert(0, "compact me")
    fleet.receive_update("room", encode_state_as_update(d))
    src = fleet.shard_of("room")
    dst = 1 - src
    fleet.begin_migration("room", dst)
    fleet.checkpoint()
    fleet.receive_update("room", edit(d, "late "))  # still double-delivers
    fleet.flush()
    crash(fleet)
    del fleet

    rec = FleetRouter.recover(
        tmp_path, docs_per_shard=2, backend="cpu", wal_config=SMALL,
    )
    assert rec.last_recovery["resolution"]["completed"] == 1
    assert rec.owner_of("room") == dst
    assert slot_owners(rec)["room"] == [dst]
    assert rec.text("room") == "late compact me"
    assert canonical(rec, "room") == canonical_doc(d)
