"""Server-side undo for device-resident rooms (utils/server_undo.py +
TpuProvider.enable_undo/undo/redo) — parity-pinned against a pure-CPU
doc driving the reference-exact UndoManager (utils/undo.py, the twin of
src/utils/UndoManager.js:19-296)."""

import yjs_tpu as Y
from yjs_tpu.provider import TpuProvider
from yjs_tpu.utils.undo import UndoManager


def _client_edit(doc, sv, fn):
    """Apply ``fn`` to a client doc, return (incremental update, new sv)."""
    fn(doc)
    u = Y.encode_state_as_update(doc, sv)
    return u, Y.encode_state_vector(doc)


def test_provider_undo_basic():
    prov = TpuProvider(n_docs=2)
    prov.enable_undo("room")
    c = Y.Doc(gc=False)
    sv = None
    u, sv = _client_edit(c, sv, lambda d: d.get_text("text").insert(0, "hello"))
    prov.receive_update("room", u, undoable=True)
    u, sv = _client_edit(c, sv, lambda d: d.get_text("text").insert(5, " world"))
    prov.receive_update("room", u, undoable=True)
    prov.flush()
    assert prov.text("room") == "hello world"

    undo_u = prov.undo("room")
    assert undo_u is not None
    # both edits landed within one capture window, so they merged into a
    # single stack item and undo reverts both (reference
    # UndoManager.js:199-205 merge rule)
    assert prov.text("room") == ""

    redo_u = prov.redo("room")
    assert redo_u is not None
    assert prov.text("room") == "hello world"
    # the returned updates replay identically on any peer
    peer = Y.Doc(gc=False)
    Y.apply_update(peer, prov.encode_state_as_update("room"))
    assert peer.get_text("text").to_string() == "hello world"


def test_provider_undo_capture_timeout_zero_separates_items():
    prov = TpuProvider(n_docs=1)
    prov.enable_undo("r", capture_timeout=0)
    c = Y.Doc(gc=False)
    sv = None
    for word in ("a", "b", "c"):
        u, sv = _client_edit(
            c, sv, lambda d, w=word: d.get_text("text").insert(
                len(d.get_text("text").to_string()), w
            )
        )
        prov.receive_update("r", u, undoable=True)
    prov.flush()
    assert prov.text("r") == "abc"
    prov.undo("r")
    assert prov.text("r") == "ab"
    prov.undo("r")
    assert prov.text("r") == "a"
    prov.redo("r")
    assert prov.text("r") == "ab"
    prov.undo("r")
    assert prov.text("r") == "a"
    prov.undo("r")
    assert prov.text("r") == ""
    assert prov.undo("r") is None  # stack exhausted


def test_provider_undo_does_not_revert_foreign_edits():
    """Undo must only revert tracked-origin changes — a second client's
    concurrent edits survive (reference trackedOrigins filter)."""
    prov = TpuProvider(n_docs=1)
    prov.enable_undo("r", capture_timeout=0)
    a = Y.Doc(gc=False)
    b = Y.Doc(gc=False)
    a.client_id, b.client_id = 1, 2
    ua, sva = _client_edit(a, None, lambda d: d.get_text("text").insert(0, "AAA"))
    prov.receive_update("r", ua, undoable=True)
    Y.apply_update(b, ua)
    ub, svb = _client_edit(b, None, lambda d: d.get_text("text").insert(3, "BBB"))
    prov.receive_update("r", ub, undoable=False)  # foreign client
    prov.flush()
    assert prov.text("r") == "AAABBB"
    prov.undo("r")
    assert prov.text("r") == "BBB"  # only A's edit reverted
    prov.redo("r")
    assert prov.text("r") == "AAABBB"


def test_server_undo_parity_with_cpu_undo_manager():
    """The room's undo/redo sequence lands on the same text as a pure-CPU
    doc driving the reference UndoManager over the same edits."""
    # CPU oracle: one doc, local edits through an UndoManager
    oracle = Y.Doc(gc=False)
    oracle.client_id = 7
    text = oracle.get_text("text")
    um = UndoManager(text, capture_timeout=0, tracked_origins={"me"})

    prov = TpuProvider(n_docs=1)
    prov.enable_undo("r", capture_timeout=0)
    sv = None
    client = Y.Doc(gc=False)
    client.client_id = 7

    def step(fn):
        nonlocal sv
        oracle.transact(lambda _t: fn(text), "me")
        fn2u, _ = _client_edit(client, sv, lambda d: fn(d.get_text("text")))
        sv = Y.encode_state_vector(client)
        prov.receive_update("r", fn2u, undoable=True)

    step(lambda t: t.insert(0, "one "))
    step(lambda t: t.insert(4, "two "))
    step(lambda t: t.delete(0, 2))
    step(lambda t: t.format(0, 3, {"bold": True}))
    prov.flush()
    assert prov.text("r") == text.to_string()

    for op in ("undo", "undo", "redo", "undo", "undo", "undo", "redo"):
        getattr(um, op)()
        getattr(prov, op)("r")
        assert prov.text("r") == text.to_string(), op
        assert prov.to_delta("r") == text.to_delta(), op


def test_provider_undo_embeds_and_deletes():
    """Undo of embeds + deletions (reference undo-redo.tests.js scenarios)."""
    prov = TpuProvider(n_docs=1)
    prov.enable_undo("r", capture_timeout=0)
    c = Y.Doc(gc=False)
    sv = None
    u, sv = _client_edit(
        c, sv, lambda d: d.get_text("text").insert_embed(
            0, {"image": "x.png"}
        )
    )
    prov.receive_update("r", u, undoable=True)
    u, sv = _client_edit(c, sv, lambda d: d.get_text("text").insert(1, "cap"))
    prov.receive_update("r", u, undoable=True)
    prov.flush()
    assert prov.to_delta("r") == [
        {"insert": {"image": "x.png"}},
        {"insert": "cap"},
    ]
    prov.undo("r")
    assert prov.to_delta("r") == [{"insert": {"image": "x.png"}}]
    prov.undo("r")
    assert prov.to_delta("r") == []
    prov.redo("r")
    assert prov.to_delta("r") == [{"insert": {"image": "x.png"}}]
