"""XML types (scenarios modeled on reference tests/y-xml.tests.js)."""

import yjs_tpu as Y
from helpers import compare, init


def test_custom_typings():
    doc = Y.Doc()
    xml = doc.get_xml_fragment("xml")
    p = Y.YXmlElement("p")
    xml.insert(0, [p])
    txt = Y.YXmlText("text")
    p.insert(0, [txt])
    assert xml.to_string() == "<p>text</p>"


def test_attributes_and_siblings():
    doc = Y.Doc()
    xml = doc.get("xml", Y.YXmlElement)
    el = Y.YXmlElement("div")
    xml.insert(0, [el])
    el.set_attribute("class", "x")
    el.set_attribute("about", "y")
    assert el.get_attribute("class") == "x"
    assert el.get_attributes() == {"class": "x", "about": "y"}
    assert el.to_string() == '<div about="y" class="x"></div>'
    el.remove_attribute("about")
    assert el.get_attributes() == {"class": "x"}
    el2 = Y.YXmlElement("span")
    xml.insert(1, [el2])
    assert el.next_sibling is el2
    assert el2.prev_sibling is el
    assert el2.next_sibling is None


def test_tree_walker_query_selector():
    doc = Y.Doc()
    xml = doc.get_xml_fragment("xml")
    div = Y.YXmlElement("div")
    xml.insert(0, [div])
    p1 = Y.YXmlElement("p")
    p2 = Y.YXmlElement("p")
    span = Y.YXmlElement("span")
    div.insert(0, [p1, span, p2])
    ps = xml.query_selector_all("p")
    assert ps == [p1, p2]
    assert xml.query_selector("span") is span
    assert xml.query_selector("nope") is None
    all_elems = list(xml.create_tree_walker(lambda t: isinstance(t, Y.YXmlElement)))
    assert all_elems == [div, p1, span, p2]


def test_xml_text_formatting_to_string():
    doc = Y.Doc()
    xml = doc.get_xml_fragment("xml")
    txt = Y.YXmlText()
    xml.insert(0, [txt])
    txt.insert(0, "bold", {"b": {}})
    # insert without attributes inherits the active formatting
    txt.insert(4, "more")
    assert xml.to_string() == "<b>boldmore</b>"
    # explicit empty attributes escape the formatting range
    txt.insert(8, "plain", {})
    assert xml.to_string() == "<b>boldmore</b>plain"


def test_xml_sync(rng):
    from helpers import compare, init

    result = init(rng, users=3)
    xml0 = result["xml0"]
    p = Y.YXmlElement("p")
    xml0.insert(0, [p])
    p.set_attribute("id", "42")
    result["testConnector"].flush_all_messages()
    assert result["xml1"].to_string() == xml0.to_string()
    compare(result["users"])


def test_xml_hook():
    doc = Y.Doc()
    xml = doc.get_xml_fragment("xml")
    hook = Y.YXmlHook("custom-component")
    xml.insert(0, [hook])
    hook.set("prop", "value")
    # replicate
    doc2 = Y.Doc()
    Y.apply_update(doc2, Y.encode_state_as_update(doc))
    restored = doc2.get_xml_fragment("xml").get(0)
    assert isinstance(restored, Y.YXmlHook)
    assert restored.hook_name == "custom-component"
    assert restored.get("prop") == "value"


def test_xml_fragment_first_child():
    doc = Y.Doc()
    xml = doc.get_xml_fragment("xml")
    assert xml.first_child is None
    a = Y.YXmlElement("a")
    xml.insert(0, [a])
    assert xml.first_child is a


def test_xml_events(rng):
    """attributesChanged / childListChanged, local + remote (reference
    y-xml.tests.js testEvents)."""
    result = init(rng, users=2)
    xml0, xml1 = result["xml0"], result["xml1"]
    box = {}
    xml0.observe(lambda e, _tr=None: box.__setitem__("l", e))
    xml1.observe(lambda e, _tr=None: box.__setitem__("r", e))

    def fresh(side):
        # stale events must not satisfy later steps' assertions
        return box.pop(side)

    xml0.set_attribute("key", "value")
    assert "key" in fresh("l").attributes_changed
    result["testConnector"].flush_all_messages()
    assert "key" in fresh("r").attributes_changed
    xml0.remove_attribute("key")
    assert "key" in fresh("l").attributes_changed
    result["testConnector"].flush_all_messages()
    assert "key" in fresh("r").attributes_changed
    xml0.insert(0, [Y.YXmlText("some text")])
    assert fresh("l").child_list_changed
    result["testConnector"].flush_all_messages()
    assert fresh("r").child_list_changed
    xml0.delete(0, 1)
    assert fresh("l").child_list_changed
    result["testConnector"].flush_all_messages()
    assert fresh("r").child_list_changed
    compare(result["users"])


def test_insert_after():
    """(reference y-xml.tests.js testInsertafter)."""
    import pytest

    ydoc = Y.Doc()
    yxml = ydoc.get_xml_fragment("xml")
    first = Y.YXmlText()
    second = Y.YXmlElement("p")
    third = Y.YXmlElement("p")
    deepsecond1 = Y.YXmlElement("span")
    deepsecond2 = Y.YXmlText()
    second.insert_after(None, [deepsecond1])
    second.insert_after(deepsecond1, [deepsecond2])
    yxml.insert_after(None, [first, second])
    yxml.insert_after(second, [third])
    assert yxml.length == 3
    assert second.get(0) is deepsecond1
    assert second.get(1) is deepsecond2
    assert yxml.to_array() == [first, second, third]
    el = Y.YXmlElement("p")
    with pytest.raises(LookupError):
        el.insert_after(deepsecond1, [Y.YXmlText()])
