"""Geo WAN-chaos property suite (ISSUE 17): three regions — each a
full single-site provider — joined active-active over per-link
GeoReplicators, every WAN link independently faulted with the full
profile (drop / duplicate / delay / reorder / symmetric partition /
one-way partition / deterministic flapping), edits streaming WHILE the
faults fire.  The contract under any mix and any seed:

- every region ends byte-identical per room (text + state vector);
- zero acked-update loss: every update a region's ingress accepted
  appears in every region's converged state;
- nobody falls back to a full resync after the initial handshake
  (``full_resyncs == 1`` per link), and after a region is kill -9'd
  and recovered from its WAL the surviving links RESUME from the
  journaled ack floor (``resumes >= 1``, ``full_resyncs`` still 1).

Everything is tick-driven and seeded — a failure replays exactly.  The
``geo`` marker deselects the suite with ``-m 'not geo'``.
"""

import random

import pytest

import yjs_tpu as Y
from yjs_tpu.geo import GeoConfig, GeoReplicator
from yjs_tpu.provider import TpuProvider
from yjs_tpu.resilience import NetChaosConfig, NetworkFaultInjector
from yjs_tpu.sync.session import SessionConfig
from yjs_tpu.sync.transport import PipeNetwork
from yjs_tpu.updates import encode_state_as_update

pytestmark = [pytest.mark.geo, pytest.mark.chaos]

CORPUS_SEEDS = tuple(range(20))

# the full WAN storm: every classic fault plus the geo-profile faults
# (asymmetric one-way partitions and deterministic link flapping)
WAN_STORM = dict(
    drop=0.15, duplicate=0.15, delay=0.2, reorder=0.25, partition=0.03,
    oneway=0.03, flap_ticks=11,
)

# retransmission must out-run the worst fault window (flap-down is
# flap_ticks rounds long), and anti-entropy must close any retry-cap
# hole well inside the round budget
GEO_SESSION = dict(
    retry_base=4, retry_cap=16, retry_max=6, retry_jitter=0.25,
    antientropy=8, heartbeat=0, liveness=0, hello_timeout=0,
)

REGIONS = ("A", "B", "C")
ROOMS = ("room-0", "room-1", "room-2")


def _mk_update(token: str, client_id: int) -> bytes:
    d = Y.Doc(gc=False)
    d.client_id = client_id
    d.get_text("text").insert(0, token)
    return encode_state_as_update(d)


class GeoMesh:
    """Three regions in a full WAN mesh, each link its own faulted
    PipeNetwork; tracks every accepted token for the acked-loss
    oracle."""

    PAIRS = (("A", "B"), ("A", "C"), ("B", "C"))

    def __init__(self, seed: int, faults: dict, wal_dirs=None):
        self.seed = seed
        self.session_cfg = SessionConfig(seed=seed, **GEO_SESSION)
        self.provs: dict[str, TpuProvider] = {}
        self.reps: dict[str, GeoReplicator] = {}
        self.nets: dict[tuple[str, str], PipeNetwork] = {}
        # (src, dst) -> {"t": transport | None}; links reconnect
        # through these, so tests heal a WAN cut by swapping the holder
        self.holders: dict[tuple[str, str], dict] = {}
        self.accepted: dict[str, set] = {r: set() for r in ROOMS}
        self._gen = random.Random(seed)
        self._n_edits = 0
        for i, r in enumerate(REGIONS):
            wal = None if wal_dirs is None else str(wal_dirs[r])
            self.provs[r] = TpuProvider(8, backend="cpu", wal_dir=wal)
            self.reps[r] = GeoReplicator(
                self.provs[r],
                GeoConfig(region=r, seed=seed * 7 + i,
                          reconnect_cap=8),
            )
        for i, (x, y) in enumerate(self.PAIRS):
            inj = (
                NetworkFaultInjector(NetChaosConfig(
                    seed=(seed * 31 + i) & 0x7FFFFFFF, **faults,
                ))
                if faults
                else None
            )
            self.nets[(x, y)] = PipeNetwork(inj)
            self.connect(x, y)

    def connect(self, x: str, y: str) -> None:
        tx, ty = self.nets[(x, y)].pair(f"geo:{x}", f"geo:{y}")
        hx = self.holders.setdefault((x, y), {"t": None})
        hy = self.holders.setdefault((y, x), {"t": None})
        hx["t"], hy["t"] = tx, ty
        for region, peer, h in ((x, y, hx), (y, x, hy)):
            if peer not in self.reps[region].links:
                self.reps[region].add_peer(
                    peer, (lambda hh: (lambda: hh["t"]))(h),
                    session_config=self.session_cfg,
                )

    def maybe_edit(self, region: str) -> None:
        if self._gen.random() >= 0.3:
            return
        self._n_edits += 1
        token = f"[{region}{self._n_edits}]"
        room = ROOMS[self._gen.randrange(len(ROOMS))]
        client = 1000 * (REGIONS.index(region) + 1) + self._n_edits
        if self.provs[region].receive_update(
            room, _mk_update(token, client)
        ):
            # the ingress ACCEPTED this update: it may never be lost
            self.accepted[room].add(token)

    def step(self, editing: bool = False) -> None:
        for r in REGIONS:
            if editing:
                self.maybe_edit(r)
        for p in self.provs.values():
            p.flush()
        for rep in self.reps.values():
            rep.tick()
        for net in self.nets.values():
            net.pump()

    def converged(self) -> bool:
        for room in ROOMS:
            texts = set()
            svs = []
            for p in self.provs.values():
                texts.add(p.text(room) if room in p.guids() else "")
                svs.append(
                    p.state_vector(room) if room in p.guids() else {}
                )
            if len(texts) != 1:
                return False
            if any(sv != svs[0] for sv in svs[1:]):
                return False
        return True

    def all_live(self) -> bool:
        """Every geo link finished its handshake.  Convergence alone is
        not stability: texts can agree transitively (A<->C, C<->B)
        while one link is still in backoff — and the backoff rng is
        sid-keyed, so how long that takes depends on how many sessions
        the process created before this test."""
        return all(
            link.session.state == "live"
            for rep in self.reps.values()
            for link in rep.links.values()
        )

    def run(self, edit_rounds=50, max_rounds=2500, quiet=12) -> int:
        stable = 0
        for n in range(max_rounds):
            self.step(editing=n < edit_rounds)
            if n >= edit_rounds:
                if self.converged() and self.all_live():
                    stable += 1
                    if stable >= quiet:
                        return n
                else:
                    stable = 0
        return max_rounds

    def assert_identical_and_lossless(self) -> None:
        for room in ROOMS:
            texts = {
                p.text(room) if room in p.guids() else ""
                for p in self.provs.values()
            }
            assert len(texts) == 1, f"{room} diverged: {texts}"
            final = next(iter(texts))
            missing = [
                t for t in self.accepted[room] if t not in final
            ]
            assert not missing, (
                f"acked updates lost in {room}: {missing}"
            )

    def assert_no_full_resyncs(self) -> None:
        for r, rep in self.reps.items():
            for peer, link in rep.links.items():
                s = link.session
                assert s.n_full_resyncs == 1, (r, peer, s.snapshot())
                assert s.n_resumes == 0, (r, peer, s.snapshot())


@pytest.mark.parametrize("seed", CORPUS_SEEDS)
def test_three_region_wan_storm_converges(seed):
    mesh = GeoMesh(seed, WAN_STORM)
    rounds = mesh.run()
    assert rounds < 2500, "geo mesh never reached a stable fixpoint"
    mesh.assert_identical_and_lossless()
    mesh.assert_no_full_resyncs()


def test_clean_geo_mesh_has_no_recovery_traffic():
    mesh = GeoMesh(5, {})
    mesh.run(edit_rounds=40, max_rounds=800)
    mesh.assert_identical_and_lossless()
    mesh.assert_no_full_resyncs()
    for rep in mesh.reps.values():
        for link in rep.links.values():
            assert link.n_dead_letters == 0
            assert link.session.n_retransmits == 0


def test_region_kill9_recovers_and_resumes(tmp_path):
    """The ISSUE 17 acceptance: kill -9 one region mid-storm under the
    full WAN fault mix, recover it from its journaled WAL, heal the
    partition — byte-identical convergence, zero acked loss, and the
    surviving regions RESUME their links from the journaled ack floor
    instead of full-resyncing (``full_resyncs`` stays 1 per link,
    ``resumes >= 1`` toward the recovered region)."""
    seed = 11
    wal_dirs = {r: tmp_path / r for r in REGIONS}
    mesh = GeoMesh(seed, WAN_STORM, wal_dirs=wal_dirs)
    # storm phase: edits stream while every link is faulted
    for n in range(60):
        mesh.step(editing=True)
    # settle enough that A has acked SOMETHING from each peer — the
    # journaled recv floors are what arm the resume hints after
    # recovery — without requiring convergence
    for n in range(400):
        mesh.step()
        if all(
            mesh.reps["A"].links[p].floor["seq"] >= 1
            for p in ("B", "C")
        ):
            break
    assert all(
        mesh.reps["A"].links[p].floor["seq"] >= 1 for p in ("B", "C")
    ), "storm never let A ack anything; no floor to resume from"
    old_epoch = mesh.reps["A"].epoch

    # kill -9: region A vanishes — no close, no checkpoint; its WAN
    # transports die with the process and the survivors' connect_fn
    # holders go empty (the WAN route to A is down)
    for x, y in (("A", "B"), ("A", "C")):
        net = mesh.nets[(x, y)]
        ha, hs = mesh.holders[(x, y)], mesh.holders[(y, x)]
        net.kill(*(h["t"] for h in (ha, hs) if h["t"] is not None))
        ha["t"] = hs["t"] = None
    del mesh.provs["A"], mesh.reps["A"]

    # the survivors keep editing into the outage; their A-links sit in
    # reconnect backoff against the empty holders
    for n in range(40):
        mesh.maybe_edit("B")
        mesh.maybe_edit("C")
        for r in ("B", "C"):
            mesh.provs[r].flush()
            mesh.reps[r].tick()
        for net in mesh.nets.values():
            net.pump()
    for r in ("B", "C"):
        assert mesh.reps[r].links["A"].session.state == "reconnecting"
        assert mesh.reps[r].detector.state_of("A") in ("suspect", "dead")

    # recover A from its WAL: journaled KIND_GEO floors arm resume
    # hints, and the new fencing epoch is past every journaled one
    pa = TpuProvider.recover(str(wal_dirs["A"]), backend="cpu")
    assert pa.last_recovery["geo_links"] >= 1
    ra = GeoReplicator(
        pa, GeoConfig(region="A", seed=seed * 7, reconnect_cap=8),
    )
    assert ra.epoch > old_epoch
    mesh.provs["A"] = pa
    mesh.reps["A"] = ra
    survivors_before = {
        r: {
            "resumes": mesh.reps[r].links["A"].session.n_resumes,
            "resyncs": mesh.reps[r].links["A"].session.n_full_resyncs,
        }
        for r in ("B", "C")
    }
    # heal the WAN: fresh faulted pipes land in the connect_fn holders;
    # the recovered replicator arms resume hints from the journaled
    # floors and the survivors' links pick the route up from backoff
    mesh.connect("A", "B")
    mesh.connect("A", "C")

    rounds = mesh.run(edit_rounds=0)
    assert rounds < 2500, "mesh never converged after recovery"
    mesh.assert_identical_and_lossless()
    for r in ("B", "C"):
        s = mesh.reps[r].links["A"].session
        before = survivors_before[r]
        assert s.n_full_resyncs == before["resyncs"] == 1, (
            r, s.snapshot(),
        )
        assert s.n_resumes == before["resumes"] + 1, (r, s.snapshot())
    # B<->C never went down: still on their original handshake
    assert mesh.reps["B"].links["C"].session.n_full_resyncs == 1
    assert mesh.reps["C"].links["B"].session.n_full_resyncs == 1
