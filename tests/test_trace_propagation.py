"""Cross-peer trace propagation over the session wire (ISSUE 11):
sampled updates carry the 25-byte trace-context as an optional trailing
key on the type-121 DATA envelope; the receiver adopts the SAME trace
id (in-process via ``use_context`` around ``handle_frame``); unsampled
traffic omits the key entirely; retransmits re-carry the same identity.

Plus the negative compatibility matrix (satellite 6): pre-PR envelope
readers decode only ``seq + inner`` and never touch the trailing key,
stock y-protocols v13.4.9 readers skip the whole unknown type-121
message, and v13.2-era fixture updates ride inside a traced frame
byte-for-byte intact.
"""

import base64
import json
import os

import pytest

import yjs_tpu as Y
from yjs_tpu.lib0 import decoding, encoding
from yjs_tpu.lib0.decoding import Decoder
from yjs_tpu.lib0.encoding import Encoder
from yjs_tpu.obs.dist import (
    TRACE_CTX_LEN,
    TraceContext,
    current_context,
    mint_for_update,
    trace_metrics,
)
from yjs_tpu.sync import protocol
from yjs_tpu.sync.session import (
    K_DATA,
    MESSAGE_YTPU_SESSION,
    DocSessionHost,
    SessionConfig,
    SyncSession,
)
from yjs_tpu.sync.transport import PipeNetwork
from yjs_tpu.updates import encode_state_as_update, encode_state_vector

pytestmark = [pytest.mark.tracing, pytest.mark.network]


def quiet_config(**kw):
    base = dict(
        heartbeat=0, liveness=0, antientropy=0, hello_timeout=0,
        retry_base=4, retry_jitter=0.0, seed=1,
    )
    base.update(kw)
    return SessionConfig(**base)


class SpyHost(DocSessionHost):
    """DocSessionHost that records the trace context in force during
    each ``handle_frame`` — what a downstream provider would observe."""

    def __init__(self, doc):
        super().__init__(doc)
        self.contexts = []

    def handle_frame(self, frame):
        self.contexts.append(current_context())
        return super().handle_frame(frame)


def make_pair(net=None, text_a=""):
    net = net if net is not None else PipeNetwork()
    da, db = Y.Doc(gc=False), Y.Doc(gc=False)
    da.client_id, db.client_id = 1, 2
    if text_a:
        da.get_text("t").insert(0, text_a)
    ta, tb = net.pair("a", "b")
    hb = SpyHost(db)
    sa = SyncSession(DocSessionHost(da), quiet_config(), peer="b")
    sb = SyncSession(hb, quiet_config(), peer="a")
    sa.connect(ta)
    sb.connect(tb)
    net.settle((sa.tick, sb.tick))
    assert sa.state == sb.state == "live"
    hb.contexts.clear()  # handshake frames carry no trace
    return net, (da, sa), (db, sb, hb)


def _carried():
    m = trace_metrics().carried
    return (m.labels(dir="send").value, m.labels(dir="recv").value)


class ScriptedInjector:
    """Drops the frame indices listed in ``drops`` (0-based enqueue
    order), delivers everything else next round."""

    def __init__(self, drops=()):
        self.drops = set(drops)
        self.n = 0

    def fates(self, frame):
        i = self.n
        self.n += 1
        return [None] if i in self.drops else [0]

    def partitioned(self):
        return False

    def maybe_reorder(self, batch):
        return batch


# -- positive: sampled carry --------------------------------------------------


def test_sampled_update_carries_trace_to_peer(monkeypatch):
    monkeypatch.setenv("YTPU_TRACE_SAMPLE", "1")
    net, (da, sa), (db, sb, hb) = make_pair(text_a="base ")
    sent_before, recv_before = _carried()
    sv = encode_state_vector(da)
    da.get_text("t").insert(5, "traced")
    update = encode_state_as_update(da, sv)
    sa.send_update(update)
    net.settle((sa.tick, sb.tick))
    assert str(db.get_text("t")) == "base traced"
    sent_after, recv_after = _carried()
    assert sent_after == sent_before + 1
    assert recv_after == recv_before + 1
    # the receiver adopted the EXACT context the sender minted from the
    # raw update bytes — same trace id at both peers, one stitched trace
    got = [c for c in hb.contexts if c is not None]
    assert got, "receiver never saw a trace context"
    want = mint_for_update(update)
    assert got[0].sampled
    assert got[0].trace_hex == want.trace_hex
    assert got[0].span_hex == want.span_hex


def test_unsampled_update_omits_key_entirely(monkeypatch):
    monkeypatch.setenv("YTPU_TRACE_SAMPLE", "0")
    net, (da, sa), (db, sb, hb) = make_pair(text_a="base ")
    sent_before, recv_before = _carried()
    sv = encode_state_vector(da)
    da.get_text("t").insert(5, "cold")
    sa.send_update(encode_state_as_update(da, sv))
    net.settle((sa.tick, sb.tick))
    # convergence is byte-identical with the key absent...
    assert str(db.get_text("t")) == "base cold"
    assert Y.merge_updates([encode_state_as_update(db)]) == Y.merge_updates(
        [encode_state_as_update(da)]
    )
    # ...and the wire never carried a context in either direction
    assert _carried() == (sent_before, recv_before)
    assert all(c is None for c in hb.contexts)


def test_retransmit_recarries_same_trace(monkeypatch):
    monkeypatch.setenv("YTPU_TRACE_SAMPLE", "1")
    inj = ScriptedInjector()
    net, (da, sa), (db, sb, hb) = make_pair(
        net=PipeNetwork(inj), text_a="base "
    )
    sent_before, _ = _carried()
    inj.drops = {inj.n}  # drop exactly the DATA frame sent next
    sv = encode_state_vector(da)
    da.get_text("t").insert(0, "lost-then-found ")
    update = encode_state_as_update(da, sv)
    sa.send_update(update)
    net.settle((sa.tick, sb.tick), max_rounds=100, idle_rounds=10)
    assert str(db.get_text("t")).startswith("lost-then-found ")
    assert sa.n_retransmits >= 1
    # the retransmitted frame re-carried the SAME stored context: one
    # send-carry per wire attempt, and the peer adopted the original id
    sent_after, _ = _carried()
    assert sent_after >= sent_before + 2
    got = [c for c in hb.contexts if c is not None]
    assert got and got[0].trace_hex == mint_for_update(update).trace_hex


# -- negative: compatibility --------------------------------------------------


def _traced_data_frame(seq, inner, ctx):
    """A DATA envelope with the trailing trace key, built byte-by-byte
    exactly as ``SyncSession._data_frame`` does."""
    enc = Encoder()
    encoding.write_var_uint(enc, MESSAGE_YTPU_SESSION)
    encoding.write_var_uint(enc, K_DATA)
    encoding.write_var_uint(enc, seq)
    encoding.write_var_uint8_array(enc, inner)
    encoding.write_var_uint8_array(enc, ctx.to_bytes())
    return enc.to_bytes()


def test_prepr_reader_never_touches_trailing_trace_key():
    """A pre-PR session reader decodes ``seq`` + ``inner`` and stops —
    the trailing key must be pure surplus, leaving the inner payload
    byte-for-byte intact."""
    inner = b"\x02\x01\x05hello"
    ctx = mint_for_update(b"whatever").force()
    frame = _traced_data_frame(7, inner, ctx)
    dec = Decoder(frame)
    assert decoding.read_var_uint(dec) == MESSAGE_YTPU_SESSION
    assert decoding.read_var_uint(dec) == K_DATA
    assert decoding.read_var_uint(dec) == 7
    assert bytes(decoding.read_var_uint8_array(dec)) == inner
    # the surplus is exactly the one trailing key: length varint + blob
    assert dec.has_content()
    trailing = bytes(decoding.read_var_uint8_array(dec))
    assert len(trailing) == TRACE_CTX_LEN
    assert TraceContext.from_bytes(trailing) == ctx
    assert not dec.has_content()


def test_stock_v13_reader_skips_traced_envelope():
    """Stock y-protocols v13.4.9 treats the whole type-121 message as
    unknown — with or without the trace key: no exception, no output,
    no doc damage."""
    d = Y.Doc(gc=False)
    ctx = mint_for_update(b"payload").force()
    frame = _traced_data_frame(1, b"\x00\x01\x00", ctx)
    out = Encoder()
    mtype = protocol.read_sync_message(Decoder(frame), out, d, "x")
    assert mtype == protocol.MESSAGE_UNKNOWN
    assert out.to_bytes() == b""


def test_v13_fixture_update_rides_traced_frame_intact():
    """A v13.2-generated update (compat fixture) carried as the inner
    payload of a traced frame survives the pre-PR decode path unchanged
    and still integrates to the recorded value."""
    fx = json.load(open(os.path.join(
        os.path.dirname(__file__), "fixtures", "compat_v1.json"
    )))["testTextDecodingCompatibilityV1"]
    old = base64.b64decode(fx["oldDoc"])
    ctx = mint_for_update(old).force()
    frame = _traced_data_frame(3, old, ctx)
    dec = Decoder(frame)
    decoding.read_var_uint(dec)  # 121
    decoding.read_var_uint(dec)  # K_DATA
    decoding.read_var_uint(dec)  # seq
    recovered = bytes(decoding.read_var_uint8_array(dec))
    assert recovered == old
    doc = Y.Doc()
    Y.apply_update(doc, recovered)
    assert doc.get_text("text").to_delta() == fx["oldVal"]


def test_session_roundtrip_with_key_absent_from_old_sender(monkeypatch):
    """A frame built WITHOUT the trailing key (what a pre-PR sender
    emits) is exactly what today's receiver sees on unsampled traffic:
    parsed as no-context, applied, acked — proven here by driving a
    whole session exchange with sampling off and asserting zero carries
    plus clean convergence (the absent path IS the common path)."""
    monkeypatch.setenv("YTPU_TRACE_SAMPLE", "0")
    net, (da, sa), (db, sb, hb) = make_pair()
    before = _carried()
    for i in range(5):
        sv = encode_state_vector(da)
        da.get_text("t").insert(0, f"op{i} ")
        sa.send_update(encode_state_as_update(da, sv))
        net.settle((sa.tick, sb.tick))
    assert str(da.get_text("t")) == str(db.get_text("t"))
    assert sa.outbox_depth == 0
    assert _carried() == before
