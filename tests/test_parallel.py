"""Sharded engine tests on the virtual 8-device CPU mesh (conftest sets
XLA_FLAGS=--xla_force_host_platform_device_count=8)."""

import numpy as np
import pytest

import jax

import yjs_tpu as Y
from yjs_tpu.ops import BatchEngine
from yjs_tpu.parallel import doc_mesh, sharded_state_vectors


@pytest.fixture(scope="module")
def mesh8():
    # the virtual 8-device host mesh (XLA_FLAGS in conftest); the axon TPU
    # plugin keeps the default backend, so ask for cpu explicitly
    if len(jax.devices("cpu")) < 8:
        pytest.skip("needs 8 virtual cpu devices")
    return doc_mesh(8, backend="cpu")


def build_docs(n):
    docs = []
    for i in range(n):
        d = Y.Doc(gc=False)
        d.client_id = 1000 + i
        t = d.get_text("text")
        t.insert(0, f"doc{i}-")
        t.insert(len(t.to_string()), "payload " * (i % 4 + 1))
        t.delete(1, 2)
        docs.append(d)
    return docs


def test_sharded_flush_matches_cpu(mesh8):
    n = 16
    docs = build_docs(n)
    eng = BatchEngine(n, mesh=mesh8)
    for i, d in enumerate(docs):
        eng.queue_update(i, Y.encode_state_as_update(d))
    eng.flush()
    assert eng.last_metrics is not None and eng.last_metrics["integrated"] > 0
    for i, d in enumerate(docs):
        assert eng.text(i) == d.get_text("text").to_string()
        assert eng.state_vector(i) == {
            c: v for c, v in Y.get_state_vector(d.store).items() if v > 0
        }


def test_sharded_incremental_concurrent(mesh8):
    n = 8
    docs = build_docs(n)
    eng = BatchEngine(n, mesh=mesh8)
    for i, d in enumerate(docs):
        eng.queue_update(i, Y.encode_state_as_update(d))
    eng.flush()
    # second round: concurrent remote edits from a second client per doc
    for i, d in enumerate(docs):
        remote = Y.Doc(gc=False)
        remote.client_id = 2000 + i
        Y.apply_update(remote, Y.encode_state_as_update(d))
        remote.get_text("text").insert(0, "R:")
        u = Y.encode_state_as_update(remote, Y.encode_state_vector(d))
        Y.apply_update(d, u)
        eng.queue_update(i, u)
    eng.flush()
    for i, d in enumerate(docs):
        assert eng.text(i) == d.get_text("text").to_string()


def test_engine_batched_svs_use_sharded_kernel(mesh8):
    # state_vectors_batched on a meshed engine routes through
    # sharded_state_vectors (padding the doc subset to the mesh axis)
    n = 8
    docs = build_docs(n)
    eng = BatchEngine(n, mesh=mesh8)
    for i, d in enumerate(docs):
        eng.queue_update(i, Y.encode_state_as_update(d))
    eng.flush()
    subset = [0, 3, 5]  # not a multiple of the axis size: exercises padding
    svs = eng.state_vectors_batched(subset)
    for j, i in enumerate(subset):
        assert svs[j] == {
            c: v for c, v in Y.get_state_vector(docs[i].store).items() if v > 0
        }
    # the sharded shard_map kernel actually served the request
    assert eng._sharded_sv


def test_sharded_levels_kernel_path(mesh8):
    """YTPU_KERNEL=levels keeps the shard_map YATA step working on the
    mesh (the on-device integration form; default is the sharded bulk
    apply)."""
    import os

    os.environ["YTPU_KERNEL"] = "levels"
    try:
        n = 8
        docs = build_docs(n)
        eng = BatchEngine(n, mesh=mesh8)
        for i, d in enumerate(docs):
            eng.queue_update(i, Y.encode_state_as_update(d))
        eng.flush()
        assert eng.last_metrics is not None
        assert eng.last_metrics["integrated"] > 0
        for i, d in enumerate(docs):
            assert eng.text(i) == d.get_text("text").to_string()
    finally:
        os.environ.pop("YTPU_KERNEL", None)


def test_meshed_engine_arrays_stay_on_mesh(mesh8):
    """Every device array of a meshed engine lives on the mesh's devices —
    an unpinned transfer would land on the default backend/device instead
    (the r1/r2 MULTICHIP failure mode: a virtual CPU mesh engine touching
    the real accelerator)."""
    mesh_devs = set(mesh8.devices.flat)
    n = 8
    docs = build_docs(n)
    eng = BatchEngine(n, mesh=mesh8, compact_min_rows=4)

    def check_all():
        arrays = {
            "_right": eng._right,
            "_deleted": eng._deleted,
            "_starts": eng._starts,
            **{f"statics[{k}]": v for k, v in (eng._statics or {}).items()},
        }
        for name, arr in arrays.items():
            if arr is None:
                continue
            devs = set(arr.devices())
            assert devs == mesh_devs, (
                f"{name} on {devs}, expected the full mesh {mesh_devs}"
            )

    for i, d in enumerate(docs):
        eng.queue_update(i, Y.encode_state_as_update(d))
    eng.flush()
    check_all()
    # second flush: exercises the statics scatter, capacity growth, and
    # (compact_min_rows=4) the compaction read-back/scatter path
    for i, d in enumerate(docs):
        sv = Y.encode_state_vector(d)
        d.get_text("text").insert(0, "x" * 40)
        eng.queue_update(i, Y.encode_state_as_update(d, sv))
    eng.flush()
    check_all()
    # sync kernels on a meshed engine must also stay on-mesh
    eng.state_vectors_batched(list(range(n)))
    eng.sync_step2_batch([(i, None) for i in range(n)])
    check_all()
    for i, d in enumerate(docs):
        assert eng.text(i) == d.get_text("text").to_string()


def test_sharded_state_vector_kernel(mesh8):
    b, n, slots = 8, 16, 4
    rng = np.random.RandomState(0)
    row_slot = rng.randint(-1, slots, size=(b, n)).astype(np.int32)
    row_end = rng.randint(1, 100, size=(b, n)).astype(np.int32)
    sv_fn = sharded_state_vectors(mesh8, slots)
    sv = np.asarray(sv_fn(row_slot, row_end))
    for bi in range(b):
        for s in range(slots):
            mask = row_slot[bi] == s
            expect = row_end[bi][mask].max() if mask.any() else 0
            assert sv[bi, s] == expect


def test_meshed_provider_full_surface(mesh8):
    """The whole Provider surface on a sharded engine: receive/flush,
    sync handshake, snapshot capture + scoped render, server undo —
    device-resident rooms over the mesh throughout."""
    from yjs_tpu.provider import TpuProvider

    prov = TpuProvider(n_docs=16, mesh=mesh8)
    prov.enable_undo("room-0", capture_timeout=0)
    clients = []
    for i in range(16):
        d = Y.Doc(gc=False)
        d.client_id = 3000 + i
        d.get_text("text").insert(0, f"room{i} hello")
        clients.append(d)
        prov.receive_update(
            f"room-{i}", Y.encode_state_as_update(d), undoable=(i == 0)
        )
    prov.flush()
    snap = prov.snapshot("room-3")
    for i, d in enumerate(clients):
        d.get_text("text").insert(0, "more! ")
        prov.receive_update(
            f"room-{i}",
            Y.encode_state_as_update(d, None),
            undoable=(i == 0),
        )
    prov.flush()
    assert prov.engine.last_metrics["integrated"] > 0  # psum'd collectives
    for i, d in enumerate(clients):
        assert prov.text(f"room-{i}") == d.get_text("text").to_string()
    # snapshot-scoped render on a meshed room
    assert prov.to_delta("room-3", snapshot=snap) == [
        {"insert": "room3 hello"}
    ]
    # sync handshake: a fresh peer pulls room-5 over the wire frames
    from yjs_tpu.lib0.encoding import Encoder
    from yjs_tpu.sync import protocol

    peer = Y.Doc(gc=False)
    enc = Encoder()
    protocol.write_sync_step1(enc, peer)
    reply = prov.handle_sync_message("room-5", enc.to_bytes())
    assert reply
    from yjs_tpu.lib0.decoding import Decoder

    out = Encoder()
    protocol.read_sync_message(Decoder(reply), out, peer, "prov")
    assert (
        peer.get_text("text").to_string()
        == clients[5].get_text("text").to_string()
    )
    # server-side undo against the meshed room
    prov.undo("room-0")
    assert prov.text("room-0") == "room0 hello"
    prov.redo("room-0")
    assert prov.text("room-0") == "more! room0 hello"
    assert prov.engine.fallback == {}  # everything stayed device-resident
