"""Plan-cache + segment-planning suite (ISSUE 9).

The correctness bar for the frontier-keyed plan cache is byte-identical
convergence: under every seeded trace shape (prepend-heavy, interleaved,
conflict-storm), an engine with the cache on must produce the same
encoded state AND the same emitted deltas as one with the cache off —
including across demotion→promotion round trips and failover promotion,
where a stale mirror must never alias a cached entry.

Deterministic seeded traces; in tier-1; the ``planner`` marker
deselects it with ``-m 'not planner'`` and ci_check.sh runs it
standalone first.
"""

import random

import numpy as np
import pytest

import yjs_tpu as Y
from yjs_tpu.obs import FLUSH_METRICS_SCHEMA
from yjs_tpu.ops import BatchEngine
from yjs_tpu.ops import plan_cache
from yjs_tpu.ops.columns import DocMirror
from yjs_tpu.ops.native_mirror import native_plan_available
from yjs_tpu.updates import (
    apply_update,
    encode_state_as_update,
    encode_state_vector,
)

pytestmark = pytest.mark.planner


@pytest.fixture(autouse=True)
def _fresh_cache():
    """Each test starts and ends with an empty process-global cache."""
    plan_cache.reset_cache()
    yield
    plan_cache.reset_cache()


# -- seeded trace shapes ------------------------------------------------------


def make_trace(shape: str, seed: int, n_ops: int = 150) -> list[bytes]:
    """Incremental updates from ``n_clients`` concurrent editors.

    ``prepend``: every insert at position 0 (maximal fragmentation);
    ``interleaved``: random positions, frequent cross-sync;
    ``storm``: 4 clients colliding at near-identical positions with rare
    syncs, so updates arrive causally out of order (pending queues).
    """
    n_clients = 4 if shape == "storm" else 3
    sync_p = 0.05 if shape == "storm" else 0.4
    gen = random.Random(seed)
    docs = []
    for k in range(n_clients):
        d = Y.Doc(gc=False)
        d.client_id = 100 + k
        docs.append(d)
    out = []
    for _ in range(n_ops):
        j = gen.randrange(n_clients)
        d = docs[j]
        t = d.get_text("text")
        sv = encode_state_vector(d)
        if shape == "prepend":
            t.insert(0, gen.choice("abcdef") * gen.randint(1, 3))
        elif shape == "storm":
            t.insert(min(len(t), gen.randrange(3)), gen.choice("xyz "))
        elif len(t) and gen.random() < 0.25:
            t.delete(gen.randrange(len(t)), 1)
        else:
            t.insert(gen.randrange(len(t) + 1), gen.choice("abcdef "))
        out.append(encode_state_as_update(d, sv))
        if gen.random() < sync_p:
            k = gen.randrange(n_clients)
            if k != j:
                apply_update(docs[k], encode_state_as_update(d))
    return out


def run_engine(updates, n_docs, cache_on, monkeypatch, flush_every=5):
    """Drive one engine over ``updates`` (broadcast to every doc),
    returning encoded states, texts, per-doc emitted deltas, and summed
    flush metrics."""
    monkeypatch.setenv("YTPU_PLAN_CACHE", "1" if cache_on else "0")
    eng = BatchEngine(n_docs)
    deltas = {i: [] for i in range(n_docs)}
    eng.on_update(lambda i, u: deltas[i].append(u))
    sums = {"plan_cache_hits": 0, "plan_cache_misses": 0,
            "plan_fastpath_structs": 0}
    keysets = set()
    for j, u in enumerate(updates):
        for i in range(n_docs):
            eng.queue_update(i, u)
        if (j + 1) % flush_every == 0 or j == len(updates) - 1:
            eng.flush()
            m = eng.last_flush_metrics
            keysets.add(frozenset(m))
            for k in sums:
                sums[k] += m[k]
    states = [eng.encode_state_as_update(i) for i in range(n_docs)]
    texts = [eng.text(i) for i in range(n_docs)]
    return states, texts, deltas, sums, keysets


# -- cache-on vs cache-off byte-identity --------------------------------------


@pytest.mark.parametrize("shape", ["prepend", "interleaved", "storm"])
def test_cache_on_off_byte_identical(shape, monkeypatch):
    updates = make_trace(shape, seed=42)
    plan_cache.reset_cache()
    s_on, t_on, d_on, sums_on, keys_on = run_engine(
        updates, 3, True, monkeypatch
    )
    plan_cache.reset_cache()
    s_off, t_off, d_off, sums_off, keys_off = run_engine(
        updates, 3, False, monkeypatch
    )
    assert t_on == t_off
    assert s_on == s_off
    assert d_on == d_off
    # identical docs in one batch: the cache (or leader grouping) must
    # have served the duplicates; cache-off plans every doc cold
    assert sums_on["plan_cache_hits"] > 0
    assert sums_off["plan_cache_hits"] == 0
    # ONE metrics schema for both modes — no key drift
    assert keys_on == keys_off == {frozenset(FLUSH_METRICS_SCHEMA)}


def test_cross_engine_replay_is_all_hits(monkeypatch):
    """A second engine replaying the same trace is served entirely from
    the cache and still converges byte-identically."""
    monkeypatch.setenv("YTPU_PLAN_CACHE", "1")
    updates = make_trace("interleaved", seed=7)
    s1, t1, _d, _s, _k = run_engine(updates, 2, True, monkeypatch)
    s2, t2, _d, sums2, _k = run_engine(updates, 2, True, monkeypatch)
    assert (s1, t1) == (s2, t2)
    assert sums2["plan_cache_misses"] == 0
    assert sums2["plan_cache_hits"] > 0


def test_python_mirror_path_byte_identical(monkeypatch):
    monkeypatch.setenv("YTPU_NO_NATIVE_PLAN", "1")
    updates = make_trace("interleaved", seed=13)
    plan_cache.reset_cache()
    s_on, t_on, d_on, sums_on, _ = run_engine(updates, 2, True, monkeypatch)
    plan_cache.reset_cache()
    s_off, t_off, d_off, _s, _ = run_engine(updates, 2, False, monkeypatch)
    assert (t_on, s_on, d_on) == (t_off, s_off, d_off)
    assert sums_on["plan_cache_hits"] > 0


# -- frontier keying: a stale mirror can never alias --------------------------


def test_same_staged_bytes_different_history_do_not_alias(monkeypatch):
    """Two docs staging the SAME update bytes on DIFFERENT integrated
    states must plan independently — the frontier, not the staged
    digest, carries the history."""
    monkeypatch.setenv("YTPU_PLAN_CACHE", "1")
    d = Y.Doc(gc=False)
    d.client_id = 7
    t = d.get_text("text")
    t.insert(0, "base ")
    u1 = encode_state_as_update(d)
    sv = encode_state_vector(d)
    t.insert(5, "tail")
    u2 = encode_state_as_update(d, sv)

    eng = BatchEngine(2)
    eng.queue_update(0, u1)
    eng.flush()
    # doc 0 stages u2 on top of u1; doc 1 stages u2 on an EMPTY doc
    # (u2 alone is causally unready there — it must park as pending,
    # not adopt doc 0's post-plan state)
    eng.queue_update(0, u2)
    eng.queue_update(1, u2)
    eng.flush()
    assert eng.text(0) == "base tail"
    assert eng.text(1) == ""  # pending, not aliased
    eng.queue_update(1, u1)
    eng.flush()
    assert eng.text(1) == "base tail"


def test_reset_doc_reseeds_frontier(monkeypatch):
    """A reset slot re-planning the same bytes aliases the ORIGINAL
    fresh-doc entry — correct reuse — and converges identically."""
    monkeypatch.setenv("YTPU_PLAN_CACHE", "1")
    updates = make_trace("prepend", seed=3, n_ops=40)
    eng = BatchEngine(1)
    for u in updates:
        eng.queue_update(0, u)
    eng.flush()
    expect = eng.text(0)
    eng.reset_doc(0)
    assert eng.text(0) == ""
    for u in updates:
        eng.queue_update(0, u)
    eng.flush()
    assert eng.text(0) == expect


def test_plan_error_poisons_frontier():
    m = DocMirror("text")
    m.ingest(b"\xff\xffgarbage", False)
    key_before = m.plan_key()
    with pytest.raises(Exception):
        m.prepare_step()
    assert m.plan_frontier != key_before[1]
    # and no two poisons collide
    assert plan_cache.poison_frontier() != plan_cache.poison_frontier()


def test_demotion_promotion_roundtrip_byte_identical(monkeypatch):
    """Warm demote → demand promote → more traffic, cache on vs off:
    the promoted mirror's folded frontier keeps it from aliasing any
    pre-compaction entry."""
    from yjs_tpu.provider import TpuProvider
    from yjs_tpu.tiering import TierConfig

    def upd(text, cid=1, at=0):
        d = Y.Doc(gc=False)
        d.client_id = cid
        d.get_text("text").insert(at, text)
        return encode_state_as_update(d)

    def drive(cache_on):
        monkeypatch.setenv("YTPU_PLAN_CACHE", "1" if cache_on else "0")
        plan_cache.reset_cache()
        p = TpuProvider(2, tier_config=TierConfig(enabled=True))
        p.receive_update("r", upd("round trip "))
        p.flush()
        assert p.demote_doc("r", "warm")
        # demand promotion (hydrate_doc_columns under the hood), then
        # more traffic through the promoted mirror
        assert p.text("r") == "round trip "
        p.receive_update("r", upd("second", cid=2))
        p.flush()
        return Y.merge_updates([p.encode_state_as_update("r")]), p.text("r")

    assert drive(True) == drive(False)


def test_failover_promotion_byte_identical(tmp_path, monkeypatch):
    """Shard death + replica promotion with the cache on (the default):
    promoted slots rebuild from journals and must converge to the
    uninterrupted reference byte-for-byte."""
    from yjs_tpu.fleet import FailoverConfig, FleetRouter
    from yjs_tpu.persistence import WalConfig

    monkeypatch.setenv("YTPU_PLAN_CACHE", "1")
    assert plan_cache.get_cache() is not None
    fleet = FleetRouter(
        3, 4, backend="cpu", wal_dir=tmp_path,
        wal_config=WalConfig(segment_bytes=256, fsync="never"),
        failover_config=FailoverConfig(
            suspect_ticks=2, confirm_ticks=1, jitter_ticks=0
        ),
    )
    rooms = {}
    for j in range(4):
        d = Y.Doc(gc=False)
        d.client_id = 100 + j
        g = f"room-{j}"
        rooms[g] = d
        for step in range(6):
            sv = encode_state_vector(d)
            d.get_text("text").insert(0, f"{j}:{step} ")
            fleet.receive_update(g, encode_state_as_update(d, sv))
    fleet.flush()
    fleet.tick()  # drain the replication outbox
    victim = fleet.owner_of("room-0")
    fleet.kill_shard(victim)
    for _ in range(16):
        fleet.tick()
        if victim in fleet._down:
            break
    else:
        raise AssertionError("victim never convicted")
    for g, d in rooms.items():
        ref = Y.merge_updates([encode_state_as_update(d)])
        assert Y.merge_updates([fleet.encode_state_as_update(g)]) == ref
    # the recovered fleet keeps converging on post-failover traffic
    d = rooms["room-0"]
    sv = encode_state_vector(d)
    d.get_text("text").insert(0, "after! ")
    fleet.receive_update("room-0", encode_state_as_update(d, sv))
    assert fleet.text("room-0") == d.get_text("text").to_string()


# -- segment-sorted planning kernels ------------------------------------------


def test_anchor_lookup_np_matches_jax_and_bruteforce(rng):
    from yjs_tpu.ops import kernels

    n_slots, per_slot, n_q = 5, 40, 64
    flat_slot = np.repeat(np.arange(n_slots), per_slot)
    starts = np.sort(
        np.asarray(
            [[rng.randrange(1000) for _ in range(per_slot)]
             for _ in range(n_slots)]
        ),
        axis=1,
    ).ravel()
    q_slot = np.asarray(
        [rng.randrange(-1, n_slots) for _ in range(n_q)], np.int64
    )
    q_clock = np.asarray(
        [rng.randrange(1100) for _ in range(n_q)], np.int64
    )
    got_np = kernels.plan_anchor_lookup(
        flat_slot, starts, q_slot, q_clock, backend="np"
    )
    got_jax = kernels.plan_anchor_lookup(
        flat_slot, starts, q_slot, q_clock, backend="jax"
    )
    assert (np.asarray(got_np) == np.asarray(got_jax)).all()
    key = flat_slot * 2000 + starts  # clocks < 1100 < 2000: no overlap
    for i in range(n_q):
        if q_slot[i] < 0:
            assert got_np[i] == -1
            continue
        qk = q_slot[i] * 2000 + q_clock[i]
        expect = int(np.searchsorted(key, qk, side="right")) - 1
        assert got_np[i] == expect


def test_conflict_scan_np_matches_jax(rng):
    from yjs_tpu.ops import kernels

    n = 96
    client = np.asarray([rng.randrange(3) for _ in range(n)], np.int64)
    clock = np.cumsum([rng.randrange(1, 4) for _ in range(n)])
    length = np.asarray([rng.randrange(1, 4) for _ in range(n)], np.int64)
    o_cl = np.roll(client, 1)
    o_ck = np.roll(clock, 1)
    # degrade a third of the chain links to foreign origins
    for i in range(0, n, 3):
        o_cl[i] = -1
    r_cl = np.full(n, -1, np.int64)
    r_ck = np.zeros(n, np.int64)
    a = kernels.plan_conflict_scan(
        client, clock, length, o_cl, o_ck, r_cl, r_ck, backend="np"
    )
    b = kernels.plan_conflict_scan(
        client, clock, length, o_cl, o_ck, r_cl, r_ck, backend="jax"
    )
    for x, y in zip(a, b):
        assert (np.asarray(x) == np.asarray(y)).all()


@pytest.mark.parametrize("shape", ["prepend", "interleaved", "storm"])
def test_segment_hints_do_not_change_plans(shape, monkeypatch):
    """The segment fast path is a pure accelerator: hints on vs off must
    yield identical plans and identical mirror state."""
    updates = make_trace(shape, seed=5, n_ops=80)

    def drive(segment):
        monkeypatch.setenv("YTPU_PLAN_SEGMENT", segment)
        m = DocMirror("text")
        plans = []
        for j, u in enumerate(updates):
            m.ingest(u, False)
            if (j + 1) % 4 == 0 or j == len(updates) - 1:
                p = m.prepare_step()
                plans.append(
                    (p.sched, p.splits, p.link_rows, p.link_vals,
                     p.head_segs, p.head_vals, sorted(p.delete_rows))
                )
        return plans, m.encode_state_as_update(), m.plan_frontier

    p_on, s_on, f_on = drive("np")
    p_off, s_off, f_off = drive("off")
    assert p_on == p_off
    assert s_on == s_off
    assert f_on == f_off


def test_fastpath_structs_counted(monkeypatch):
    monkeypatch.setenv("YTPU_PLAN_SEGMENT", "np")
    updates = make_trace("prepend", seed=9, n_ops=60)
    m = DocMirror("text")
    for u in updates:
        m.ingest(u, False)
    p = m.prepare_step()
    assert p.fastpath_structs > 0
    assert p.fastpath_structs <= len(p.sched)


# -- cache mechanics ----------------------------------------------------------


def test_cache_eviction_respects_caps(monkeypatch):
    monkeypatch.setenv("YTPU_PLAN_CACHE", "1")
    monkeypatch.setenv("YTPU_PLAN_CACHE_CAP", "4")
    plan_cache.reset_cache()
    updates = make_trace("interleaved", seed=21, n_ops=60)
    eng = BatchEngine(1)
    for j, u in enumerate(updates):
        eng.queue_update(0, u)
        if (j + 1) % 3 == 0:
            eng.flush()
    eng.flush()
    cache = plan_cache.get_cache()
    assert len(cache) <= 4
    assert cache.stats()["bytes"] >= 0


def test_cache_disabled_plans_cold(monkeypatch):
    monkeypatch.setenv("YTPU_PLAN_CACHE", "0")
    assert plan_cache.get_cache() is None
    eng = BatchEngine(2)
    d = Y.Doc(gc=False)
    d.client_id = 1
    d.get_text("text").insert(0, "no cache")
    u = encode_state_as_update(d)
    eng.queue_update(0, u)
    eng.queue_update(1, u)
    eng.flush()
    m = eng.last_flush_metrics
    assert m["plan_cache_hits"] == 0
    assert eng.text(0) == eng.text(1) == "no cache"


@pytest.mark.skipif(
    not native_plan_available(), reason="native plan core unavailable"
)
def test_plan_threads_reports_actual_width(monkeypatch):
    """plan_threads is the width the flush actually used: bounded by the
    batch, and 1 on an all-hit flush."""
    monkeypatch.setenv("YTPU_PLAN_CACHE", "1")
    d = Y.Doc(gc=False)
    d.client_id = 1
    d.get_text("text").insert(0, "threads")
    u = encode_state_as_update(d)
    eng = BatchEngine(4)
    for i in range(4):
        eng.queue_update(i, u)
    eng.flush()
    first = eng.last_flush_metrics["plan_threads"]
    assert 1 <= first <= 4  # one cold leader in a 4-doc chunk
    eng2 = BatchEngine(4)
    for i in range(4):
        eng2.queue_update(i, u)
    eng2.flush()
    assert eng2.last_flush_metrics["plan_threads"] == 1  # all hits
    assert eng2.last_flush_metrics["plan_cache_misses"] == 0


def test_timer_split_is_consistent():
    updates = make_trace("interleaved", seed=31, n_ops=30)
    eng = BatchEngine(2)
    for u in updates:
        eng.queue_update(0, u)
        eng.queue_update(1, u)
    eng.flush()
    m = eng.last_flush_metrics
    assert m["t_plan_cached_s"] + m["t_plan_cold_s"] <= m["t_plan_s"] + 1e-6
    assert m["plan_cache_hits"] + m["plan_cache_misses"] >= 1


def test_invalidation_counter_has_reasons():
    from yjs_tpu.obs import global_registry, registry_snapshot

    def series():
        snap = registry_snapshot(global_registry())
        return dict(
            snap["counters"].get("ytpu_plan_cache_invalidations_total", {})
        )

    before = series()
    eng = BatchEngine(1)
    d = Y.Doc(gc=False)
    d.client_id = 1
    d.get_text("text").insert(0, "x")
    eng.queue_update(0, encode_state_as_update(d))
    eng.flush()
    eng.reset_doc(0)
    after = series()
    assert after.get("reason=reset", 0) == before.get("reason=reset", 0) + 1
