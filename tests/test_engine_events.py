"""Engine-path typed events vs the CPU doc's YEvent on the same traffic
(r2-VERDICT item 6: observe for engine-hosted docs, reference
YEvent.js:85-187, AbstractType.js:360-389)."""

import pytest

import yjs_tpu as Y
from yjs_tpu.ids import find_root_type_key
from yjs_tpu.ops import BatchEngine
from yjs_tpu.types.events import YEvent, get_path_to


def cpu_events_for(doc, update):
    """Apply one update to a CPU doc, capturing YEvent-shaped dicts."""
    captured = []

    def after_transaction(transaction, d):
        for typ in transaction.changed:
            root = typ
            while root._item is not None:
                root = root._item.parent
            ev = YEvent(typ, transaction)
            ch = ev.changes
            if not ch["delta"] and not ch["keys"]:
                continue
            captured.append({
                "path": [find_root_type_key(root)] + get_path_to(root, typ),
                "delta": ch["delta"],
                "keys": ch["keys"],
            })
    doc.on("afterTransaction", after_transaction)
    Y.apply_update(doc, update)
    doc.off("afterTransaction", after_transaction)
    return captured


def _old_repr(v):
    # nested shared types compare by kind: the engine's oldValue is an
    # unbound type shell (the mirror holds nested content in its own
    # segments), the CPU's is the live instance
    if hasattr(v, "to_json") and not isinstance(v, (str, bytes)):
        return type(v).__name__
    return repr(v)


def norm(events):
    """Order-independent comparable form."""
    def freeze(ev):
        return (
            tuple(ev["path"]),
            tuple(
                tuple(sorted(op.items(), key=lambda kv: kv[0]))
                if not any(isinstance(v, list) for v in op.values())
                else (("insert", tuple(op["insert"])),)
                for op in ev["delta"]
            ),
            tuple(sorted(
                (k, v["action"], _old_repr(v["oldValue"]))
                for k, v in ev["keys"].items()
            )),
        )
    return sorted(freeze(e) for e in events)


def session_updates(rng, n_rounds=40, nested=False):
    a = Y.Doc(gc=False); a.client_id = 11
    b = Y.Doc(gc=False); b.client_id = 22
    updates = []
    for _ in range(n_rounds):
        for d in (a, b):
            sv = Y.encode_state_vector(d)
            t = d.get_text("text")
            m = d.get_map("meta")
            arr = d.get_array("list")
            op = rng.random()
            if op < 0.4 or len(t) == 0:
                t.insert(rng.randint(0, len(t)), rng.choice(
                    ["hey ", "ho ", "let's ", "go "]))
            elif op < 0.55:
                pos = rng.randrange(len(t))
                t.delete(pos, min(rng.randint(1, 4), len(t) - pos))
            elif op < 0.7:
                m.set(rng.choice("xyz"), rng.randint(0, 9))
            elif op < 0.8 and m.get(rng.choice("xyz")) is not None:
                k = rng.choice("xyz")
                if m.get(k) is not None:
                    m.delete(k)
            elif op < 0.9:
                arr.insert(rng.randint(0, len(arr)), [rng.randint(0, 99)])
            elif nested:
                nm = Y.YMap()
                m.set("nested", nm)
                nm.set("deep", rng.randint(0, 9))
            updates.append(Y.encode_state_as_update(d, sv))
        if rng.random() < 0.5:
            ua = Y.encode_state_as_update(a, Y.encode_state_vector(b))
            ub = Y.encode_state_as_update(b, Y.encode_state_vector(a))
            Y.apply_update(b, ua)
            Y.apply_update(a, ub)
    return updates


@pytest.mark.parametrize("nested", [False, True])
def test_engine_events_match_cpu(rng, nested):
    updates = session_updates(rng, nested=nested)
    cpu = Y.Doc(gc=False)
    eng = BatchEngine(1)
    got: list = []
    eng.observe(0, lambda doc, evs: got.extend(evs))
    for u in updates:
        expect = cpu_events_for(cpu, u)
        got.clear()
        eng.queue_update(0, u)
        eng.flush()
        assert norm(got) == norm(expect), f"events diverged on update"


def test_provider_observe_path_filter(rng):
    from yjs_tpu.provider import TpuProvider

    p = TpuProvider(2)
    text_evs, all_evs = [], []
    p.observe("room", ["text"], lambda g, ev: text_evs.append(ev))
    p.observe("room", [], lambda g, ev: all_evs.append(ev))
    d = Y.Doc(gc=False)
    d.client_id = 5
    d.get_text("text").insert(0, "hi")
    d.get_map("meta").set("k", 1)
    p.receive_update("room", Y.encode_state_as_update(d))
    p.flush()
    assert any(ev["path"] == ["text"] for ev in text_evs)
    assert all(ev["path"][0] == "text" for ev in text_evs)
    assert {tuple(ev["path"]) for ev in all_evs} >= {("text",), ("meta",)}
    delta = next(ev for ev in text_evs if ev["path"] == ["text"])["delta"]
    assert delta == [{"insert": ["h", "i"]}]


def test_events_after_demotion(rng):
    """Demoted docs keep delivering the same event shape via the CPU core."""
    eng = BatchEngine(1)
    got: list = []
    eng.observe(0, lambda doc, evs: got.extend(evs))
    d = Y.Doc(gc=False)
    d.client_id = 7
    d.get_text("text").insert(0, "ab")
    eng.queue_update(0, Y.encode_state_as_update(d))
    eng.flush()
    assert got and got[0]["path"] == ["text"]
    got.clear()
    # subdoc traffic demotes the doc; the demoting flush's own changes
    # still deliver (the CPU bridge attaches at the pre-flush boundary of
    # the replay), and events keep flowing afterwards
    sub = Y.Doc()
    d.get_map("m").set("sub", sub)
    eng.queue_update(0, Y.encode_state_as_update(d, None))
    eng.flush()
    assert 0 in eng.fallback
    assert any(
        ev["path"] == ["m"] and "sub" in ev["keys"] for ev in got
    ), got
    got.clear()
    sv = Y.encode_state_vector(d)
    d.get_text("text").insert(2, "cd")
    eng.queue_update(0, Y.encode_state_as_update(d, sv))
    eng.flush()
    assert any(
        ev["path"] == ["text"] and {"retain": 2} in ev["delta"]
        for ev in got
    )


def test_engine_to_delta_matches_cpu(rng):
    """Mirror-served attributed delta vs the CPU doc (r2-VERDICT item 9,
    reference YText.toDelta YText.js:936-1030)."""
    a = Y.Doc(gc=False); a.client_id = 31
    b = Y.Doc(gc=False); b.client_id = 32
    updates = []
    for _ in range(120):
        for d in (a, b):
            sv = Y.encode_state_vector(d)
            t = d.get_text("text")
            op = rng.random()
            if op < 0.4 or len(t) == 0:
                t.insert(rng.randint(0, len(t)), rng.choice(
                    ["plain ", "words "]))
            elif op < 0.6 and len(t) > 2:
                pos = rng.randrange(len(t) - 1)
                t.format(pos, rng.randint(1, min(4, len(t) - pos)), rng.choice([
                    {"bold": True}, {"italic": True}, {"bold": None},
                    {"color": "red"},
                ]))
            elif op < 0.75:
                pos = rng.randrange(len(t))
                t.delete(pos, min(rng.randint(1, 4), len(t) - pos))
            elif op < 0.85:
                t.insert_embed(rng.randint(0, len(t)), {"img": "x.png"})
            else:
                t.insert(rng.randint(0, len(t)), "styled",
                         rng.choice([{"bold": True}, {"em": True}]))
            updates.append(Y.encode_state_as_update(d, sv))
        if rng.random() < 0.5:
            ua = Y.encode_state_as_update(a, Y.encode_state_vector(b))
            ub = Y.encode_state_as_update(b, Y.encode_state_vector(a))
            Y.apply_update(b, ua)
            Y.apply_update(a, ub)
    ua = Y.encode_state_as_update(a, Y.encode_state_vector(b))
    Y.apply_update(b, ua)
    updates.append(ua)

    cpu = Y.Doc(gc=False)
    eng = BatchEngine(1)
    for j, u in enumerate(updates):
        Y.apply_update(cpu, u)
        eng.queue_update(0, u)
        if j % 7 == 6:
            eng.flush()
            assert eng.to_delta(0) == cpu.get_text("text").to_delta()
    eng.flush()
    assert eng.to_delta(0) == cpu.get_text("text").to_delta()
    assert eng.to_delta(0)  # non-trivial traffic produced ops


def test_engine_xml_string_matches_cpu(rng):
    """Engine-served XML serialization vs the CPU doc (reference
    YXmlFragment/YXmlElement/YXmlText toString)."""
    a = Y.Doc(gc=False); a.client_id = 41
    b = Y.Doc(gc=False); b.client_id = 42
    updates = []
    tags = ["div", "p", "span"]
    for _ in range(60):
        for d in (a, b):
            sv = Y.encode_state_vector(d)
            frag = d.get_xml_fragment("xml")
            op = rng.random()
            if op < 0.35 or len(frag) == 0:
                el = Y.YXmlElement(rng.choice(tags))
                frag.insert(rng.randint(0, len(frag)), [el])
            elif op < 0.55:
                el = frag.get(rng.randrange(len(frag)))
                if isinstance(el, Y.YXmlElement):
                    el.set_attribute(rng.choice("ab"), str(rng.randint(0, 9)))
                    if rng.random() < 0.4:
                        child = Y.YXmlText()
                        el.insert(0, [child])
            elif op < 0.7:
                el = frag.get(rng.randrange(len(frag)))
                if isinstance(el, Y.YXmlElement) and len(el) > 0:
                    sub = el.get(0)
                    if isinstance(sub, Y.YXmlText):
                        sub.insert(0, rng.choice(["hi ", "yo "]))
                        if rng.random() < 0.5 and len(sub) > 1:
                            sub.format(0, 2, {"b": {"w": "1"}})
            elif op < 0.85:
                pos = rng.randrange(len(frag))
                frag.delete(pos, 1)
            else:
                t = Y.YXmlText()
                frag.insert(rng.randint(0, len(frag)), [t])
            updates.append(Y.encode_state_as_update(d, sv))
        if rng.random() < 0.5:
            ua = Y.encode_state_as_update(a, Y.encode_state_vector(b))
            ub = Y.encode_state_as_update(b, Y.encode_state_vector(a))
            Y.apply_update(b, ua)
            Y.apply_update(a, ub)
    ua = Y.encode_state_as_update(a, Y.encode_state_vector(b))
    Y.apply_update(b, ua)
    updates.append(ua)

    cpu = Y.Doc(gc=False)
    eng = BatchEngine(1, root_name="xml")
    for j, u in enumerate(updates):
        Y.apply_update(cpu, u)
        eng.queue_update(0, u)
        if j % 9 == 8:
            eng.flush()
            assert eng.xml_string(0) == cpu.get_xml_fragment("xml").to_string()
    eng.flush()
    expect = cpu.get_xml_fragment("xml").to_string()
    assert eng.xml_string(0) == expect
    assert expect  # non-trivial traffic


# ---------------------------------------------------------------------------
# VERDICT r4 item 6: event-path INDEX parity.  getPathTo (YEvent.js:207-228)
# counts undeleted ITEMS before the nested type — a count that depends on
# run-merge state, which differs between the CPU store (merges eagerly at
# cleanup) and the mirror (merges only at compaction).  These sessions put
# nested types inside ARRAYS behind char-by-char typed prefixes (one update
# per keystroke = maximally merge-sensitive) and behind deletions, for all
# three list kinds: array, xml children, and nested array-in-array.
# ---------------------------------------------------------------------------


def _nested_list_session(rng, n_rounds=30):
    a = Y.Doc(gc=False); a.client_id = 31
    b = Y.Doc(gc=False); b.client_id = 42
    updates = []
    nested_keys = []
    for rnd in range(n_rounds):
        for d in (a, b):
            sv = Y.encode_state_vector(d)
            arr = d.get_array("list")
            xml = d.get("xml", Y.YXmlElement)
            op = rng.random()
            if op < 0.35:
                # char-by-char prefix typing: each keystroke is its own
                # update, so the mirror holds N rows where the CPU store
                # holds one merged item
                arr.insert(rng.randint(0, len(arr)), [rng.choice("abcdef")])
            elif op < 0.5:
                nm = Y.YMap()
                arr.insert(rng.randint(0, len(arr)), [nm])
                nm.set("born", rnd)
            elif op < 0.6 and len(arr):
                pos = rng.randrange(len(arr))
                arr.delete(pos, 1)
            elif op < 0.75:
                # edit a nested map that lives at some array index: the
                # event path is ["list", <item-count index>]
                for i in range(len(arr)):
                    v = arr.get(i)
                    if hasattr(v, "set"):
                        v.set(rng.choice("pq"), rnd)
                        break
                else:
                    arr.insert(0, [rng.randint(0, 9)])
            elif op < 0.85:
                t = Y.YXmlText()
                xml.insert(rng.randint(0, xml.length), [t])
                t.insert(0, rng.choice(["hi", "yo"]))
            else:
                # edit an existing xml text child -> path ["xml", index]
                n = xml._first_child() if hasattr(xml, "_first_child") else None
                edited = False
                for i in range(xml.length):
                    c = xml.get(i)
                    if isinstance(c, Y.YXmlText):
                        c.insert(len(c.to_string()), "!")
                        edited = True
                        break
                if not edited:
                    xml.insert(0, [Y.YXmlText()])
            updates.append(Y.encode_state_as_update(d, sv))
        if rng.random() < 0.5:
            ua = Y.encode_state_as_update(a, Y.encode_state_vector(b))
            ub = Y.encode_state_as_update(b, Y.encode_state_vector(a))
            Y.apply_update(b, ua)
            Y.apply_update(a, ub)
    del nested_keys
    return updates


def _norm_types(events):
    """norm() with nested-type delta inserts compared by KIND: the engine
    materializes unbound shells for nested types while the CPU yields the
    live instances, so identity can never match (same convention as
    _old_repr for map values)."""
    out = []
    for ev in events:
        ev = dict(ev)
        delta = []
        for op in ev.get("delta", []):
            if isinstance(op.get("insert"), list):
                op = dict(op)
                op["insert"] = [
                    type(v).__name__
                    if hasattr(v, "to_json") and not isinstance(v, (str, bytes))
                    else v
                    for v in op["insert"]
                ]
            delta.append(op)
        ev["delta"] = delta
        out.append(ev)
    return norm(out)


def test_event_path_parity_nested_lists(rng):
    """CPU-vs-engine path equality for nested types in arrays/xml under
    merge-sensitive traffic (the r4 documented divergence, now fixed by
    counting CPU-merged-item runs in ops/events._path_of)."""
    updates = _nested_list_session(rng)
    cpu = Y.Doc(gc=False)
    eng = BatchEngine(1)
    got: list = []
    eng.observe(0, lambda doc, evs: got.extend(evs))
    for u in updates:
        expect = cpu_events_for(cpu, u)
        got.clear()
        eng.queue_update(0, u)
        eng.flush()
        assert _norm_types(got) == _norm_types(expect), "event paths diverged"


def test_event_path_parity_after_compaction(rng):
    """Same parity with a 4-row compaction threshold: compacted mirrors
    merge rows themselves, so the run-grouping must stay consistent."""
    updates = _nested_list_session(rng, n_rounds=20)
    cpu = Y.Doc(gc=False)
    eng = BatchEngine(1, gc=False, compact_min_rows=4)
    got: list = []
    eng.observe(0, lambda doc, evs: got.extend(evs))
    for u in updates:
        expect = cpu_events_for(cpu, u)
        got.clear()
        eng.queue_update(0, u)
        eng.flush()
        assert _norm_types(got) == _norm_types(expect), "event paths diverged post-compaction"
