"""Session-layer suite (ISSUE 5): the SyncSession state machine over
deterministic in-memory transports — handshake (fresh + resume), plain-
protocol negotiation, ack/retransmit/backoff/dead-letter, backpressure
coalescing, heartbeat/liveness, the anti-entropy repair loop, and the
provider/WAL integration (session registry, ack journaling, recovery
resume hints).

Everything runs on tick-time (no wall clocks): a failure replays
byte-for-byte.  In tier-1; the ``network`` marker deselects it with
``-m 'not network'``.
"""

import pytest

import yjs_tpu as Y
from yjs_tpu.lib0 import encoding
from yjs_tpu.lib0.decoding import Decoder
from yjs_tpu.lib0.encoding import Encoder
from yjs_tpu.provider import TpuProvider
from yjs_tpu.sync import protocol
from yjs_tpu.sync.session import (
    DocSessionHost,
    SessionConfig,
    SessionMetrics,
    SyncSession,
)
from yjs_tpu.sync.transport import CallbackTransport, PipeNetwork
from yjs_tpu.updates import encode_state_as_update, encode_state_vector

pytestmark = pytest.mark.network


def quiet_config(**kw):
    """Timers off unless a test turns one on — each behavior is tested
    in isolation."""
    base = dict(
        heartbeat=0, liveness=0, antientropy=0, hello_timeout=0,
        retry_base=4, retry_jitter=0.0, seed=1,
    )
    base.update(kw)
    return SessionConfig(**base)


def make_pair(net=None, cfg_a=None, cfg_b=None, text_a="", text_b=""):
    net = net if net is not None else PipeNetwork()
    da, db = Y.Doc(gc=False), Y.Doc(gc=False)
    da.client_id, db.client_id = 1, 2
    if text_a:
        da.get_text("t").insert(0, text_a)
    if text_b:
        db.get_text("t").insert(0, text_b)
    ta, tb = net.pair("a", "b")
    sa = SyncSession(DocSessionHost(da), cfg_a or quiet_config(), peer="b")
    sb = SyncSession(DocSessionHost(db), cfg_b or quiet_config(), peer="a")
    return net, (da, sa, ta), (db, sb, tb)


def edit_and_send(doc, sess, pos, s):
    sv = encode_state_vector(doc)
    doc.get_text("t").insert(pos, s)
    sess.send_update(encode_state_as_update(doc, sv))


class ScriptedInjector:
    """Minimal injector: drops the frame indices listed in ``drops``
    (0-based enqueue order), delivers everything else next round."""

    def __init__(self, drops=()):
        self.drops = set(drops)
        self.n = 0

    def fates(self, frame):
        i = self.n
        self.n += 1
        return [None] if i in self.drops else [0]

    def partitioned(self):
        return False

    def maybe_reorder(self, batch):
        return batch


# -- handshake ---------------------------------------------------------------


def test_fresh_handshake_exchanges_state():
    net, (da, sa, ta), (db, sb, tb) = make_pair(
        text_a="hello ", text_b="world"
    )
    sa.connect(ta)
    sb.connect(tb)
    net.settle((sa.tick, sb.tick))
    assert sa.state == sb.state == "live"
    assert str(da.get_text("t")) == str(db.get_text("t"))
    assert sa.n_full_resyncs == sb.n_full_resyncs == 1
    assert sa.n_resumes == sb.n_resumes == 0
    assert not sa.plain_mode and not sb.plain_mode


def test_live_updates_flow_with_acks():
    net, (da, sa, ta), (db, sb, tb) = make_pair(text_a="base")
    sa.connect(ta)
    sb.connect(tb)
    net.settle((sa.tick, sb.tick))
    edit_and_send(da, sa, 4, "+one")
    edit_and_send(da, sa, 8, "+two")
    net.settle((sa.tick, sb.tick))
    assert str(db.get_text("t")) == "base+one+two"
    assert sa.outbox_depth == 0  # acked and pruned
    assert sa.n_retransmits == 0


def test_handshake_epoch_settles_seq_space_once():
    # both HELLO and WELCOME carry the fresh-handshake verdict; a
    # second send-side reset would recycle seq numbers the peer has
    # already recorded, making the next update look like a duplicate
    net, (da, sa, ta), (db, sb, tb) = make_pair(text_a="seed ")
    sa.connect(ta)
    sb.connect(tb)
    net.settle((sa.tick, sb.tick))
    first_seq = sa._send_seq  # the handshake diff consumed >= 1
    edit_and_send(da, sa, 0, "x")
    assert sa._send_seq == first_seq + 1
    net.settle((sa.tick, sb.tick))
    assert str(db.get_text("t")) == "xseed "


def test_reconnect_resumes_without_full_resync():
    net, (da, sa, ta), (db, sb, tb) = make_pair(text_a="persist ")
    sa.connect(ta)
    sb.connect(tb)
    net.settle((sa.tick, sb.tick))
    net.kill(ta, tb)
    assert sa.state == sb.state == "reconnecting"
    # edits made while disconnected coalesce into a catch-up delta
    edit_and_send(da, sa, 0, ">> ")
    assert sa.n_coalesced == 1
    ta2, tb2 = net.pair("a2", "b2")
    sa.attach(ta2)
    sb.attach(tb2)
    net.settle((sa.tick, sb.tick))
    assert sa.state == sb.state == "live"
    assert str(da.get_text("t")) == str(db.get_text("t")) == ">> persist "
    assert sa.n_resumes == sb.n_resumes == 1
    assert sa.n_full_resyncs == sb.n_full_resyncs == 1  # only the first


def test_fresh_peer_instance_forces_full_resync():
    net, (da, sa, ta), (db, sb, tb) = make_pair(text_a="one ")
    sa.connect(ta)
    sb.connect(tb)
    net.settle((sa.tick, sb.tick))
    net.kill(ta, tb)
    # the peer process died: a brand-new session (no resume state)
    db2 = Y.Doc(gc=False)
    db2.client_id = 3
    sb2 = SyncSession(DocSessionHost(db2), quiet_config(), peer="a")
    ta2, tb2 = net.pair()
    sa.attach(ta2)
    sb2.connect(tb2)
    net.settle((sa.tick, sb2.tick))
    assert str(db2.get_text("t")) == "one "
    # the survivor counted a second full resync, not a resume
    assert sa.n_full_resyncs == 2 and sa.n_resumes == 0


# -- plain-protocol interop --------------------------------------------------


def plain_peer(doc, transport):
    """A peer speaking only the plain y-protocols flow (the v13.4.9
    interop target): tolerant read loop, replies ride the same pipe."""

    def on_frame(frame):
        dec = Decoder(frame)
        enc = Encoder()
        protocol.read_sync_message(dec, enc, doc, "plain-peer")
        out = enc.to_bytes()
        if out:
            transport.send(out)

    transport.on_frame = on_frame


def test_negotiates_down_to_plain_protocol():
    net = PipeNetwork()
    ds = Y.Doc(gc=False)
    ds.client_id = 1
    dp = Y.Doc(gc=False)
    dp.client_id = 2
    dp.get_text("t").insert(0, "plain content")
    ts, tp = net.pair()
    sess = SyncSession(DocSessionHost(ds), quiet_config(), peer="plain")
    plain_peer(dp, tp)
    sess.connect(ts)
    # the plain peer initiates step 1 (a y-websocket server would)
    enc = Encoder()
    protocol.write_sync_step1(enc, dp)
    tp.send(enc.to_bytes())
    net.settle((sess.tick,))
    assert sess.plain_mode and sess.state == "live"
    assert str(ds.get_text("t")) == "plain content"
    # updates in both directions keep flowing, unenveloped
    sv = encode_state_vector(ds)
    ds.get_text("t").insert(0, "S:")
    sess.send_update(encode_state_as_update(ds, sv))
    net.settle((sess.tick,))
    assert str(dp.get_text("t")) == str(ds.get_text("t"))


def test_hello_timeout_falls_back_to_plain_step1():
    # a plain peer that never initiates (a server awaiting step 1):
    # after hello_timeout silent ticks the session probes with a bare
    # step 1 instead of waiting forever
    net = PipeNetwork()
    ds = Y.Doc(gc=False)
    dp = Y.Doc(gc=False)
    dp.get_text("t").insert(0, "lazy server")
    ts, tp = net.pair()
    sess = SyncSession(
        DocSessionHost(ds), quiet_config(hello_timeout=3), peer="srv"
    )
    plain_peer(dp, tp)
    sess.connect(ts)
    net.settle((sess.tick,), max_rounds=50, idle_rounds=6)
    assert sess.plain_mode
    assert str(ds.get_text("t")) == "lazy server"


def test_plain_reader_skips_session_envelope():
    # the envelope message type must be invisible to a tolerant plain
    # reader: counted as unknown, never an exception, never doc damage
    d = Y.Doc(gc=False)
    enc = Encoder()
    encoding.write_var_uint(enc, 121)  # MESSAGE_YTPU_SESSION
    encoding.write_var_uint(enc, 0)  # K_HELLO
    encoding.write_var_uint(enc, 1)
    dec = Decoder(enc.to_bytes())
    out = Encoder()
    mtype = protocol.read_sync_message(dec, out, d, "x")
    assert mtype == protocol.MESSAGE_UNKNOWN
    assert out.to_bytes() == b""


# -- retransmission ----------------------------------------------------------


def test_dropped_frame_retransmits_and_converges():
    inj = ScriptedInjector()
    net, (da, sa, ta), (db, sb, tb) = make_pair(net=PipeNetwork(inj))
    sa.connect(ta)
    sb.connect(tb)
    net.settle((sa.tick, sb.tick))
    # drop exactly the next enqueued frame (the DATA we send below)
    inj.drops = {inj.n}
    edit_and_send(da, sa, 0, "lost-then-found")
    net.settle((sa.tick, sb.tick), max_rounds=100, idle_rounds=10)
    assert str(db.get_text("t")) == "lost-then-found"
    assert sa.n_retransmits >= 1
    assert sa.outbox_depth == 0


def test_backoff_grows_exponentially_and_deterministically():
    cfg = quiet_config()
    s = SyncSession(DocSessionHost(Y.Doc(gc=False)), cfg, peer="x")
    delays = [s._backoff(k) for k in range(1, 6)]
    assert delays == [4, 8, 16, 32, 64]  # base 4, jitter 0, cap 64
    capped = SyncSession(
        DocSessionHost(Y.Doc(gc=False)),
        quiet_config(retry_cap=16),
        peer="x",
    )
    assert [capped._backoff(k) for k in range(1, 6)] == [4, 8, 16, 16, 16]
    # jitter is seeded: two sessions with the same seed, same schedule
    j1 = SyncSession(
        DocSessionHost(Y.Doc(gc=False)), quiet_config(retry_jitter=0.5),
        peer="x",
    )
    j2 = SyncSession(
        DocSessionHost(Y.Doc(gc=False)), quiet_config(retry_jitter=0.5),
        peer="x",
    )
    j2.sid = j1.sid  # jitter keys off (seed, sid)
    import random as _r

    j1._rng = _r.Random(1)
    j2._rng = _r.Random(1)
    assert [j1._backoff(k) for k in (1, 2, 3)] == [
        j2._backoff(k) for k in (1, 2, 3)
    ]


def test_retry_cap_dead_letters_payload():
    class DropData:
        """Deliver handshake, drop every frame after it."""

        def __init__(self):
            self.arm = False

        def fates(self, frame):
            return [None] if self.arm else [0]

        def partitioned(self):
            return False

        def maybe_reorder(self, batch):
            return batch

    inj = DropData()
    net, (da, sa, ta), (db, sb, tb) = make_pair(
        net=PipeNetwork(inj),
        cfg_a=quiet_config(retry_base=1, retry_cap=2, retry_max=3),
    )
    sa.connect(ta)
    sb.connect(tb)
    net.settle((sa.tick, sb.tick))
    inj.arm = True  # black hole from here on
    edit_and_send(da, sa, 0, "doomed")
    for _ in range(30):
        net.pump()
        sa.tick()
        sb.tick()
    assert sa.outbox_depth == 0  # expired out of the outbox
    assert sa.n_dead_lettered == 1
    payload, reason = sa.host.dead_letters[-1]
    assert "net-retry-exhausted" in reason
    # the dead-lettered payload is the framed inner update — replayable
    dec = Decoder(payload)
    from yjs_tpu.lib0 import decoding as dmod

    assert dmod.read_var_uint(dec) == protocol.MESSAGE_YJS_UPDATE


# -- backpressure ------------------------------------------------------------


def test_outbox_high_watermark_enters_lagging_and_coalesces():
    net, (da, sa, ta), (db, sb, tb) = make_pair(
        cfg_a=quiet_config(outbox_high=3, outbox_low=0, retry_base=64)
    )
    sa.connect(ta)
    sb.connect(tb)
    net.settle((sa.tick, sb.tick))
    # stop delivering: acks never come back, the outbox can only grow
    for k in range(8):
        edit_and_send(da, sa, 0, f"{k}")
    assert sa.state == "lagging"
    assert sa.n_coalesced >= 1
    assert sa.outbox_depth <= 3
    # drain the wire again: the coalesced delta catches the peer up
    net.settle((sa.tick, sb.tick), max_rounds=300, idle_rounds=10)
    assert sa.state == "live"
    assert str(db.get_text("t")) == str(da.get_text("t"))


def test_lagging_sheds_unsent_frames_not_sent_ones():
    net, (da, sa, ta), (db, sb, tb) = make_pair(
        cfg_a=quiet_config(outbox_high=2, outbox_low=0, retry_base=64)
    )
    sa.connect(ta)
    sb.connect(tb)
    net.settle((sa.tick, sb.tick))
    # a backlog where one frame never made the wire (the transport
    # refused it mid-queue): entering lagging must shed it — the
    # coalesced delta supersedes it — but KEEP sent-once frames, whose
    # seqs the peer may already hold (ack accounting needs them)
    sa._outbox = [
        {"seq": 1, "inner": b"x", "attempts": 1, "next_retry": 99,
         "sent": True},
        {"seq": 2, "inner": b"y", "attempts": 0, "next_retry": 99,
         "sent": False},
    ]
    sa._send_seq = 2
    sa._enter_lagging()
    assert sa.state == "lagging"
    assert sa.n_shed == 1
    assert [e["seq"] for e in sa._outbox] == [1]


# -- heartbeat / liveness ----------------------------------------------------


def test_heartbeats_keep_idle_session_alive():
    net, (da, sa, ta), (db, sb, tb) = make_pair(
        cfg_a=quiet_config(heartbeat=2, liveness=8),
        cfg_b=quiet_config(heartbeat=2, liveness=8),
    )
    sa.connect(ta)
    sb.connect(tb)
    net.settle((sa.tick, sb.tick))
    for _ in range(40):  # 5x the liveness window, zero data traffic
        net.pump()
        sa.tick()
        sb.tick()
    assert sa.state == sb.state == "live"
    assert sa.n_liveness_timeouts == 0


def test_liveness_timeout_detects_mute_peer():
    net, (da, sa, ta), (db, sb, tb) = make_pair(
        cfg_a=quiet_config(heartbeat=2, liveness=6)
    )
    sa.connect(ta)
    sb.connect(tb)
    net.settle((sa.tick, sb.tick))
    tb.on_frame = lambda frame: None  # peer goes silent (half-open link)
    for _ in range(20):
        net.pump()
        sa.tick()
    assert sa.state == "reconnecting"
    assert sa.n_liveness_timeouts == 1


# -- anti-entropy ------------------------------------------------------------


def test_antientropy_heals_silent_divergence():
    net, (da, sa, ta), (db, sb, tb) = make_pair(
        cfg_a=quiet_config(antientropy=4),
        cfg_b=quiet_config(antientropy=4),
    )
    sa.connect(ta)
    sb.connect(tb)
    net.settle((sa.tick, sb.tick))
    # divergence the wire never saw: a local edit NOT sent (exactly the
    # post-dead-letter / shed-frame hole anti-entropy exists to close)
    da.get_text("t").insert(0, "silent change")
    net.settle((sa.tick, sb.tick), max_rounds=100, idle_rounds=8)
    assert str(db.get_text("t")) == "silent change"
    assert sa.n_repairs >= 1


def test_antientropy_idle_sessions_send_digests_not_repairs():
    net, (da, sa, ta), (db, sb, tb) = make_pair(
        cfg_a=quiet_config(antientropy=3),
        cfg_b=quiet_config(antientropy=3),
        text_a="same",
    )
    sa.connect(ta)
    sb.connect(tb)
    net.settle((sa.tick, sb.tick), max_rounds=60, idle_rounds=5)
    assert sa.n_repairs == 0  # nothing to heal: digests found parity
    assert str(da.get_text("t")) == str(db.get_text("t"))


# -- provider integration ----------------------------------------------------


def drive(pa, pb):
    def fn():
        pa.flush()
        pb.flush()
        pa.tick_sessions()
        pb.tick_sessions()

    return fn


def test_provider_session_registry_and_snapshot():
    pa = TpuProvider(2, backend="cpu")
    pb = TpuProvider(2, backend="cpu")
    net = PipeNetwork()
    ta, tb = net.pair()
    sa = pa.session("room", "pb", quiet_config())
    assert pa.session("room", "pb") is sa  # get-or-create
    sb = pb.session("room", "pa", quiet_config())
    sa.connect(ta)
    sb.connect(tb)
    net.settle((drive(pa, pb),))
    d = Y.Doc(gc=False)
    d.get_text("text").insert(0, "via provider")
    pa.receive_update("room", encode_state_as_update(d))
    net.settle((drive(pa, pb),))
    assert pb.text("room") == "via provider"
    rows = pa.sessions_snapshot()
    assert len(rows) == 1
    row = rows[0]
    assert row["guid"] == "room" and row["peer"] == "pb"
    assert row["state"] == "live" and row["sent"] >= 1
    for key in ("outbox_depth", "retransmits", "last_ack_age", "resumes"):
        assert key in row
    # the metrics snapshot carries the same rows for dashboards
    snap = pa.metrics_snapshot()
    assert snap["sessions"][0]["guid"] == "room"
    pa.close_session("room", "pb")
    assert pa.sessions_snapshot() == []
    # a closed (room, peer) gets a FRESH session on the next ask
    assert pa.session("room", "pb", quiet_config()) is not sa


def test_provider_sessions_share_net_metric_families():
    pa = TpuProvider(1, backend="cpu")
    names = set(pa.engine.obs.registry.names())
    for fam in (
        "ytpu_net_sessions",
        "ytpu_net_frames_total",
        "ytpu_net_retransmits_total",
        "ytpu_net_resumes_total",
        "ytpu_net_full_resyncs_total",
        "ytpu_net_antientropy_repairs_total",
        "ytpu_net_outbox_depth",
    ):
        assert fam in names, fam


def test_provider_bad_frame_routes_to_room_dlq():
    pa = TpuProvider(1, backend="cpu")
    pb = TpuProvider(1, backend="cpu")
    net = PipeNetwork()
    ta, tb = net.pair()
    pa.session("room", "pb", quiet_config()).connect(ta)
    pb.session("room", "pa", quiet_config()).connect(tb)
    net.settle((drive(pa, pb),))
    # a damaged envelope injected at the transport seam
    enc = Encoder()
    encoding.write_var_uint(enc, 121)
    encoding.write_var_uint(enc, 2)  # K_DATA ...
    ta.send(enc.to_bytes() + b"\xff")  # ... with a torn body
    net.settle((drive(pa, pb),))
    letters = pb.dead_letters("room")
    assert any("net-" in e["reason"] for e in letters)


def test_wal_journals_acks_and_recovery_resumes(tmp_path):
    cfg = quiet_config()
    p1 = TpuProvider(2, backend="cpu", wal_dir=str(tmp_path))
    p2 = TpuProvider(2, backend="cpu")
    net = PipeNetwork()
    t1, t2 = net.pair()
    p1.session("doc", "p2", cfg).connect(t1)
    s2 = p2.session("doc", "p1", cfg)
    s2.connect(t2)
    net.settle((drive(p1, p2),))
    d = Y.Doc(gc=False)
    d.get_text("text").insert(0, "durable")
    p2.receive_update("doc", encode_state_as_update(d))
    net.settle((drive(p1, p2),))
    assert p1.text("doc") == "durable"
    # crash p1 (no close, no checkpoint); sever the wire
    net.kill(t1, t2)
    del p1
    pr = TpuProvider.recover(str(tmp_path), backend="cpu")
    assert pr.last_recovery["session_acks"] >= 1
    sr = pr.session("doc", "p2", cfg)
    t1b, t2b = net.pair()
    sr.connect(t1b)
    s2.attach(t2b)
    net.settle((drive(pr, p2),))
    assert pr.text("doc") == p2.text("doc") == "durable"
    # the SURVIVOR resumed (saw its own sid echoed back): delta replay,
    # no second full resync — the ISSUE 5 acceptance shape
    assert s2.n_resumes == 1
    assert s2.n_full_resyncs == 1


def test_session_admission_veto_leaves_no_half_registration():
    # regression (ISSUE 6 satellite): session() used to register the
    # flush bridge and could leave a half-registered peer behind when
    # doc_id vetoed with ProviderFullError — the carcass was then
    # ticked and snapshotted forever
    from yjs_tpu.provider import ProviderFullError

    pa = TpuProvider(1, backend="cpu")
    d = Y.Doc(gc=False)
    d.get_text("text").insert(0, "occupies the only slot")
    pa.receive_update("a", encode_state_as_update(d))
    with pytest.raises(ProviderFullError):
        pa.session("b", "peer", quiet_config())
    assert ("b", "peer") not in pa._sessions
    assert pa.sessions_snapshot() == []
    assert not pa._sessions_bridged  # the veto registered no bridge
    # admission works once a slot frees up — nothing stale in the way
    pa.release_doc("a")
    sess = pa.session("b", "peer", quiet_config())
    assert pa._sessions[("b", "peer")] is sess and not sess._closed


def test_release_doc_under_live_session_reconverges_without_resync():
    # ISSUE 6 satellite: evicting a room (release_doc) while a peer
    # session holds it must not wedge the session — the next inbound
    # delta re-admits the room into a fresh slot and the anti-entropy
    # loop heals the evicted history, with NO second full resync
    cfg = quiet_config(antientropy=2)
    pa = TpuProvider(2, backend="cpu")
    pb = TpuProvider(2, backend="cpu")
    net = PipeNetwork()
    ta, tb = net.pair()
    sa = pa.session("room", "pb", cfg)
    sb = pb.session("room", "pa", cfg)
    sa.connect(ta)
    sb.connect(tb)
    net.settle((drive(pa, pb),))
    d = Y.Doc(gc=False)
    d.client_id = 11
    d.get_text("text").insert(0, "kept")
    pb.receive_update("room", encode_state_as_update(d))
    net.settle((drive(pa, pb),))
    assert pa.text("room") == "kept"

    pa.release_doc("room")  # evict while both sessions are live
    sv = encode_state_vector(d)
    d.get_text("text").insert(0, "next ")
    pb.receive_update("room", encode_state_as_update(d, sv))
    net.settle((drive(pa, pb),), max_rounds=120, idle_rounds=5)
    assert pa.text("room") == pb.text("room") == "next kept"
    # byte-identical stores after the repair
    assert Y.merge_updates([pa.encode_state_as_update("room")]) == (
        Y.merge_updates([pb.encode_state_as_update("room")])
    )
    # the handshake's full resync stayed the only one
    assert sa.n_full_resyncs == 1 and sb.n_full_resyncs == 1
    assert sa.state == sb.state == "live"


def test_checkpoint_preserves_ack_floors(tmp_path):
    cfg = quiet_config()
    p1 = TpuProvider(2, backend="cpu", wal_dir=str(tmp_path))
    p2 = TpuProvider(2, backend="cpu")
    net = PipeNetwork()
    t1, t2 = net.pair()
    p1.session("doc", "p2", cfg).connect(t1)
    s2 = p2.session("doc", "p1", cfg)
    s2.connect(t2)
    net.settle((drive(p1, p2),))
    d = Y.Doc(gc=False)
    d.get_text("text").insert(0, "pre-checkpoint")
    p2.receive_update("doc", encode_state_as_update(d))
    net.settle((drive(p1, p2),))
    p1.checkpoint()  # compaction must re-journal the ack floors
    net.kill(t1, t2)
    del p1
    pr = TpuProvider.recover(str(tmp_path), backend="cpu")
    assert pr.last_recovery["session_acks"] >= 1
    assert pr.text("doc") == "pre-checkpoint"


# -- connector lifecycle hooks -----------------------------------------------


def test_abstract_connector_lifecycle_hooks_default_noop():
    from yjs_tpu.utils.abstract_connector import AbstractConnector

    c = AbstractConnector(Y.Doc(gc=False))
    c.on_connect()
    c.on_disconnect("closed")
    c.on_error(RuntimeError("x"))  # default hooks absorb silently

    events = []

    class Hooked(AbstractConnector):
        def on_connect(self):
            events.append("connect")

        def on_disconnect(self, reason="closed"):
            events.append(f"disconnect:{reason}")

        def on_error(self, exc):
            events.append(f"error:{type(exc).__name__}")

    h = Hooked(Y.Doc(gc=False))
    h.on_connect()
    h.on_error(ValueError("boom"))
    h.on_disconnect("eof")
    assert events == ["connect", "error:ValueError", "disconnect:eof"]


# -- dashboards --------------------------------------------------------------


def test_ytpu_top_renders_session_rows():
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "ytpu_top_session_test",
        pathlib.Path(__file__).resolve().parent.parent
        / "scripts" / "ytpu_top.py",
    )
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)

    pa = TpuProvider(2, backend="cpu")
    pb = TpuProvider(2, backend="cpu")
    net = PipeNetwork()
    ta, tb = net.pair()
    pa.session("room", "pb", quiet_config()).connect(ta)
    pb.session("room", "pa", quiet_config()).connect(tb)
    net.settle((drive(pa, pb),))
    row = top.collect_row("prov-a", pa.metrics_snapshot(), None, 1.0)
    assert row["sessions"] and row["sessions"][0]["state"] == "live"
    frame = top.render([row], 1.0)
    assert "peer" in frame and "outbox" in frame and "room" in frame
