"""PermanentUserData + the content-ref dispatch table (reference
tests/encoding.tests.js testPermanentUserData / testStructReferences)."""

import yjs_tpu as Y
from yjs_tpu.core import (
    content_refs,
    read_content_any,
    read_content_binary,
    read_content_deleted,
    read_content_doc,
    read_content_embed,
    read_content_format,
    read_content_json,
    read_content_string,
    read_content_type,
)


def test_struct_references():
    """The wire content-ref table wiring (reference encoding.tests.js
    testStructReferences): ref N must dispatch to the right reader, or
    every udpate with that content kind decodes as garbage."""
    assert len(content_refs) == 10
    assert content_refs[1] is read_content_deleted
    assert content_refs[2] is read_content_json
    assert content_refs[3] is read_content_binary
    assert content_refs[4] is read_content_string
    assert content_refs[5] is read_content_embed
    assert content_refs[6] is read_content_format
    assert content_refs[7] is read_content_type
    assert content_refs[8] is read_content_any
    assert content_refs[9] is read_content_doc


def test_permanent_user_data():
    """(reference encoding.tests.js testPermanentUserData)."""
    ydoc1 = Y.Doc(gc=False)
    ydoc2 = Y.Doc(gc=False)
    pd1 = Y.PermanentUserData(ydoc1)
    pd2 = Y.PermanentUserData(ydoc2)
    pd1.set_user_mapping(ydoc1, ydoc1.client_id, "user a")
    pd2.set_user_mapping(ydoc2, ydoc2.client_id, "user b")
    ydoc1.get_text("").insert(0, "xhi")
    ydoc1.get_text("").delete(0, 1)
    ydoc2.get_text("").insert(0, "hxxi")
    ydoc2.get_text("").delete(1, 2)
    Y.apply_update(ydoc2, Y.encode_state_as_update(ydoc1))
    Y.apply_update(ydoc1, Y.encode_state_as_update(ydoc2))

    # user lookup by live client id and by deleted-item id
    assert pd1.get_user_by_client_id(ydoc1.client_id) == "user a"
    assert pd1.get_user_by_client_id(ydoc2.client_id) == "user b"
    from yjs_tpu.core import create_delete_set_from_struct_store
    from yjs_tpu.ids import create_id

    ds = create_delete_set_from_struct_store(ydoc1.store)
    del_item = ds.clients[ydoc1.client_id][0]
    assert (
        pd1.get_user_by_deleted_id(
            create_id(ydoc1.client_id, del_item.clock)
        )
        == "user a"
    )
    # the remote peer's deletions arrived as an encoded DeleteSet through
    # the users-map observer — attribute them to "user b" on doc1's side
    del_item_b = ds.clients[ydoc2.client_id][0]
    assert (
        pd1.get_user_by_deleted_id(
            create_id(ydoc2.client_id, del_item_b.clock)
        )
        == "user b"
    )

    # a third doc synced from doc1 re-attaches under the same name
    ydoc3 = Y.Doc(gc=False)
    Y.apply_update(ydoc3, Y.encode_state_as_update(ydoc1))
    pd3 = Y.PermanentUserData(ydoc3)
    pd3.set_user_mapping(ydoc3, ydoc3.client_id, "user a")
    assert pd3.get_user_by_client_id(ydoc1.client_id) == "user a"
