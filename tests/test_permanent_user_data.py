"""PermanentUserData + the content-ref dispatch table (reference
tests/encoding.tests.js testPermanentUserData / testStructReferences)."""

import yjs_tpu as Y
from yjs_tpu.core import (
    content_refs,
    read_content_any,
    read_content_binary,
    read_content_deleted,
    read_content_doc,
    read_content_embed,
    read_content_format,
    read_content_json,
    read_content_string,
    read_content_type,
)


def test_struct_references():
    """The wire content-ref table wiring (reference encoding.tests.js
    testStructReferences): ref N must dispatch to the right reader, or
    every udpate with that content kind decodes as garbage."""
    assert len(content_refs) == 10
    assert content_refs[1] is read_content_deleted
    assert content_refs[2] is read_content_json
    assert content_refs[3] is read_content_binary
    assert content_refs[4] is read_content_string
    assert content_refs[5] is read_content_embed
    assert content_refs[6] is read_content_format
    assert content_refs[7] is read_content_type
    assert content_refs[8] is read_content_any
    assert content_refs[9] is read_content_doc


def test_permanent_user_data():
    """(reference encoding.tests.js testPermanentUserData)."""
    ydoc1 = Y.Doc(gc=False)
    ydoc2 = Y.Doc(gc=False)
    pd1 = Y.PermanentUserData(ydoc1)
    pd2 = Y.PermanentUserData(ydoc2)
    pd1.set_user_mapping(ydoc1, ydoc1.client_id, "user a")
    pd2.set_user_mapping(ydoc2, ydoc2.client_id, "user b")
    ydoc1.get_text("").insert(0, "xhi")
    ydoc1.get_text("").delete(0, 1)
    ydoc2.get_text("").insert(0, "hxxi")
    ydoc2.get_text("").delete(1, 2)
    Y.apply_update(ydoc2, Y.encode_state_as_update(ydoc1))
    Y.apply_update(ydoc1, Y.encode_state_as_update(ydoc2))

    # user lookup by live client id and by deleted-item id
    assert pd1.get_user_by_client_id(ydoc1.client_id) == "user a"
    assert pd1.get_user_by_client_id(ydoc2.client_id) == "user b"
    from yjs_tpu.core import create_delete_set_from_struct_store
    from yjs_tpu.ids import create_id

    ds = create_delete_set_from_struct_store(ydoc1.store)
    del_item = ds.clients[ydoc1.client_id][0]
    assert (
        pd1.get_user_by_deleted_id(
            create_id(ydoc1.client_id, del_item.clock)
        )
        == "user a"
    )
    # the remote peer's deletions arrived as an encoded DeleteSet through
    # the users-map observer — attribute them to "user b" on doc1's side
    del_item_b = ds.clients[ydoc2.client_id][0]
    assert (
        pd1.get_user_by_deleted_id(
            create_id(ydoc2.client_id, del_item_b.clock)
        )
        == "user b"
    )

    # a third doc synced from doc1 re-attaches under the same name
    ydoc3 = Y.Doc(gc=False)
    Y.apply_update(ydoc3, Y.encode_state_as_update(ydoc1))
    pd3 = Y.PermanentUserData(ydoc3)
    pd3.set_user_mapping(ydoc3, ydoc3.client_id, "user a")
    assert pd3.get_user_by_client_id(ydoc1.client_id) == "user a"


def test_engine_room_user_data_parity():
    """Engine-path attribution (VERDICT r4 Missing #3): clients maintain
    PermanentUserData in the room as usual; the provider answers
    user_by_client_id / user_by_deleted_id from mirror columns and must
    agree with a CPU PermanentUserData fed the same traffic."""
    from yjs_tpu.provider import TpuProvider

    # two editing clients, each with its own PUD mapping
    d1 = Y.Doc(gc=False)
    d1.client_id = 71
    d2 = Y.Doc(gc=False)
    d2.client_id = 72
    pd1 = Y.PermanentUserData(d1)
    pd1.set_user_mapping(d1, d1.client_id, "alice")
    pd2 = Y.PermanentUserData(d2)
    pd2.set_user_mapping(d2, d2.client_id, "bob")

    def sync():
        u1 = Y.encode_state_as_update(d1, Y.encode_state_vector(d2))
        u2 = Y.encode_state_as_update(d2, Y.encode_state_vector(d1))
        Y.apply_update(d2, u1)
        Y.apply_update(d1, u2)

    sync()
    d1.get_text("text").insert(0, "alice writes. ")
    sync()
    d2.get_text("text").insert(0, "bob writes. ")
    sync()
    # alice deletes bob's prefix; bob deletes part of alice's text
    d1.get_text("text").delete(0, 4)   # "bob "
    sync()
    d2.get_text("text").delete(0, 8)   # "writes. "
    sync()

    # server room receives everything
    prov = TpuProvider(n_docs=2)
    prov.receive_update("room", Y.encode_state_as_update(d1))
    prov.flush()
    assert prov.engine.fallback == {}, prov.engine.demotions
    rud = prov.user_data("room")

    # CPU oracle on a third replica
    cpu = Y.Doc(gc=False)
    oracle = Y.PermanentUserData(cpu)
    Y.apply_update(cpu, Y.encode_state_as_update(d1))

    assert rud.user_by_client_id(71) == oracle.get_user_by_client_id(71) == "alice"
    assert rud.user_by_client_id(72) == oracle.get_user_by_client_id(72) == "bob"
    assert rud.user_by_client_id(999) is None

    # attribution of every deleted id agrees with the oracle, and both
    # deleters actually show up (the test is vacuous otherwise)
    seen = set()
    for client, dels in cpu.store.clients.items():
        for s in dels:
            if s.deleted:
                for clk in (s.id.clock, s.id.clock + s.length - 1):
                    who_cpu = oracle.get_user_by_deleted_id(
                        Y.createID(client, clk)
                    )
                    who_eng = rud.user_by_deleted_id(Y.createID(client, clk))
                    assert who_eng == who_cpu, (client, clk, who_eng, who_cpu)
                    if who_cpu:
                        seen.add(who_cpu)
    assert seen == {"alice", "bob"}

    # late traffic invalidates the cache: a new mapping becomes visible
    d3 = Y.Doc(gc=False)
    d3.client_id = 73
    Y.apply_update(d3, Y.encode_state_as_update(d1))
    pd3 = Y.PermanentUserData(d3)
    pd3.set_user_mapping(d3, 73, "carol")
    prov.receive_update(
        "room", Y.encode_state_as_update(d3, Y.encode_state_vector(d1))
    )
    prov.flush()
    assert rud.user_by_client_id(73) == "carol"


def test_engine_room_user_data_delete_only_update():
    """Regression (r5 review): a DELETE-ONLY update must invalidate the
    RoomUserData cache.  Deleting the users-map entry removes the
    attribution from the live-state view (documented deviation: the
    reference's observer dicts never forget)."""
    from yjs_tpu.provider import TpuProvider

    d = Y.Doc(gc=False)
    d.client_id = 81
    pd = Y.PermanentUserData(d)
    pd.set_user_mapping(d, 81, "dave")
    prov = TpuProvider(n_docs=1)
    prov.receive_update("room", Y.encode_state_as_update(d))
    prov.flush()
    rud = prov.user_data("room")
    assert rud.user_by_client_id(81) == "dave"
    # delete-only update authored on a PUD-free replica (the reference's
    # own observer crashes on users-entry deletion — @experimental): the
    # room must still see the removal
    d2 = Y.Doc(gc=False)
    d2.client_id = 82
    Y.apply_update(d2, Y.encode_state_as_update(d))
    sv = Y.encode_state_vector(d2)
    d2.get_map("users").delete("dave")
    prov.receive_update("room", Y.encode_state_as_update(d2, sv))
    prov.flush()
    assert prov.engine.fallback == {}
    assert rud.user_by_client_id(81) is None  # stale cache would say "dave"
