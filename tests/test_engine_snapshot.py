"""Engine-path snapshots: capture, snapshot-scoped delta rendering, and
createDocFromSnapshot for DEVICE-RESIDENT rooms — parity-pinned against
the CPU core's utils/snapshot.py + YText.to_delta (which are themselves
the reference twins of src/utils/Snapshot.js:27-202 and
src/types/YText.js:936-1030)."""

import random

import yjs_tpu as Y
from yjs_tpu.ops import BatchEngine
from yjs_tpu.utils.snapshot import (
    create_doc_from_snapshot,
    decode_snapshot,
    encode_snapshot,
    equal_snapshots,
    snapshot as cpu_snapshot,
)


def _mk_engine_and_doc(updates):
    """One device-resident room + one CPU oracle, fed the same updates."""
    eng = BatchEngine(1)
    d = Y.Doc(gc=False)
    for u in updates:
        eng.queue_update(0, u)
        Y.apply_update(d, u)
    eng.flush()
    return eng, d


def _edit_updates(seed=0, rounds=6, clients=2):
    """Two clients interleaving inserts/deletes/formats on 'text'."""
    rng = random.Random(seed)
    docs = [Y.Doc(gc=False) for _ in range(clients)]
    for i, d in enumerate(docs):
        d.client_id = 100 + i
    out = []
    svs = [None] * clients
    for _r in range(rounds):
        i = rng.randrange(clients)
        d = docs[i]
        t = d.get_text("text")
        n = len(t.to_string())
        op = rng.random()
        if op < 0.55 or n == 0:
            pos = rng.randint(0, n)
            t.insert(pos, rng.choice(["ab", "xyz", "\U0001F600", "Q"]))
        elif op < 0.8:
            pos = rng.randint(0, n - 1)
            t.delete(pos, min(rng.randint(1, 3), n - pos))
        else:
            pos = rng.randint(0, max(0, n - 2))
            t.format(pos, min(2, n - pos), {"bold": True})
        u = Y.encode_state_as_update(d, svs[i])
        svs[i] = Y.encode_state_vector(d)
        out.append(u)
        # cross-deliver so the two clients actually interleave
        for j, other in enumerate(docs):
            if j != i:
                Y.apply_update(other, u)
    return out


def test_engine_snapshot_capture_matches_cpu():
    for seed in range(4):
        updates = _edit_updates(seed=seed)
        eng, d = _mk_engine_and_doc(updates)
        es = eng.snapshot(0)
        cs = cpu_snapshot(d)
        assert equal_snapshots(es, cs), f"seed={seed}"
        # codec interop: engine snapshots ride the standard wire form
        assert equal_snapshots(decode_snapshot(encode_snapshot(es)), cs)


def test_engine_snapshot_scoped_delta_parity():
    for seed in range(6):
        updates = _edit_updates(seed=seed, rounds=8)
        k = len(updates) // 2
        # oracle doc built incrementally; snapshot mid-history
        eng = BatchEngine(1)
        d = Y.Doc(gc=False)
        for u in updates[:k]:
            eng.queue_update(0, u)
            Y.apply_update(d, u)
        eng.flush()
        snap_mid_e = eng.snapshot(0)
        snap_mid_c = cpu_snapshot(d)
        assert equal_snapshots(snap_mid_e, snap_mid_c)
        for u in updates[k:]:
            eng.queue_update(0, u)
            Y.apply_update(d, u)
        eng.flush()
        snap_end_c = cpu_snapshot(d)
        t = d.get_text("text")
        # point-in-time view
        assert eng.to_delta(0, snapshot=snap_mid_c) == t.to_delta(
            snap_mid_c
        ), f"seed={seed} point-in-time"
        # two-snapshot diff with ychange attribution
        assert eng.to_delta(
            0, snapshot=snap_end_c, prev_snapshot=snap_mid_c
        ) == t.to_delta(snap_end_c, snap_mid_c), f"seed={seed} diff"
        # custom compute_ychange passthrough
        cy = lambda kind, _id: {"type": kind, "user": _id.client}
        assert eng.to_delta(
            0, snapshot=snap_end_c, prev_snapshot=snap_mid_c,
            compute_ychange=cy,
        ) == t.to_delta(snap_end_c, snap_mid_c, cy), f"seed={seed} ychange"


def test_engine_create_doc_from_snapshot():
    for seed in range(3):
        updates = _edit_updates(seed=seed, rounds=8)
        k = len(updates) // 2
        eng = BatchEngine(1)
        d = Y.Doc(gc=False)
        for u in updates[:k]:
            eng.queue_update(0, u)
            Y.apply_update(d, u)
        eng.flush()
        snap = eng.snapshot(0)
        text_at_snap = d.get_text("text").to_string()
        for u in updates[k:]:
            eng.queue_update(0, u)
            Y.apply_update(d, u)
        eng.flush()
        rewound = eng.create_doc_from_snapshot(0, snap)
        # PARITY is the contract: the CPU reference path itself repairs
        # surrogate pairs split by post-snapshot edits to U+FFFD
        # (ContentString split rule), so compare against it — not
        # against the raw pre-edit text
        cpu_rewound = create_doc_from_snapshot(d, snap)
        assert (
            rewound.get_text("text").to_string()
            == cpu_rewound.get_text("text").to_string()
        ), f"seed={seed}"
        if "�" not in cpu_rewound.get_text("text").to_string():
            assert rewound.get_text("text").to_string() == text_at_snap


def test_engine_snapshot_survives_compaction():
    """Rows merged by engine compaction after the snapshot still render
    the point-in-time view exactly (element-level ds visibility makes
    merged runs transparent)."""
    for seed in range(3):
        updates = _edit_updates(seed=10 + seed, rounds=10)
        k = len(updates) // 2
        eng = BatchEngine(1, compact_min_rows=2)  # compact aggressively
        d = Y.Doc(gc=False)
        for u in updates[:k]:
            eng.queue_update(0, u)
            Y.apply_update(d, u)
        eng.flush()
        snap = cpu_snapshot(d)
        assert equal_snapshots(eng.snapshot(0), snap)
        for u in updates[k:]:
            eng.queue_update(0, u)
            Y.apply_update(d, u)
            eng.flush()  # per-update flushes -> compactions fire
        t = d.get_text("text")
        assert eng.text(0) == t.to_string()
        assert eng.to_delta(0, snapshot=snap) == t.to_delta(snap), (
            f"seed={seed}"
        )


def test_provider_snapshot_surface():
    from yjs_tpu.provider import TpuProvider

    prov = TpuProvider(n_docs=2)
    guid = "room-a"
    d = Y.Doc(gc=False)
    d.get_text("text").insert(0, "hello world")
    prov.receive_update(guid, Y.encode_state_as_update(d))
    prov.flush()
    snap = prov.snapshot(guid)
    d.get_text("text").insert(5, " brave")
    prov.receive_update(guid, Y.encode_state_as_update(d))
    prov.flush()
    assert prov.text(guid) == "hello brave world"
    # point-in-time render from the still-device-resident room
    assert prov.to_delta(guid, snapshot=snap) == [{"insert": "hello world"}]
    rewound = prov.create_doc_from_snapshot(guid, snap)
    assert rewound.get_text("text").to_string() == "hello world"
    # the room itself was never demoted
    assert prov.engine.fallback == {}
