"""Fleet suite (ISSUE 6): the doc-sharded provider fleet — bounded-load
consistent-hash placement, the versioned routing table, the provider
facade, live migration with double delivery, drain/scale-out, the churn
rebalancer, per-shard mesh placement, and the fleet session fan-out.

Everything is deterministic (blake2b placement, tick-time sessions,
seeded edits).  In tier-1; ``scripts/ci_check.sh`` also runs it first as
a standalone smoke via the ``fleet`` marker.
"""

import pytest

import yjs_tpu as Y
from yjs_tpu.fleet import (
    FleetConfig,
    FleetFullError,
    FleetRouter,
    HashRing,
    RoutingTable,
    stable_hash,
)
from yjs_tpu.provider import TpuProvider
from yjs_tpu.sync.session import SessionConfig
from yjs_tpu.sync.transport import PipeNetwork
from yjs_tpu.updates import encode_state_as_update, encode_state_vector

pytestmark = pytest.mark.fleet


def quiet_config(**kw):
    base = dict(
        heartbeat=0, liveness=0, antientropy=0, hello_timeout=0,
        retry_base=4, retry_jitter=0.0, seed=1,
    )
    base.update(kw)
    return SessionConfig(**base)


def update_for(text, client_id=99):
    d = Y.Doc(gc=False)
    d.client_id = client_id
    d.get_text("text").insert(0, text)
    return encode_state_as_update(d)


def drive(fleet, peer):
    def fn():
        fleet.flush()
        peer.flush()
        fleet.tick_sessions()
        peer.tick_sessions()

    return fn


# -- hash ring ---------------------------------------------------------------


def test_stable_hash_is_process_stable_and_64_bit():
    # blake2b, not hash(): the value below must never change across
    # processes or releases — routing tables depend on it
    assert stable_hash("room-0") == stable_hash("room-0")
    assert stable_hash("room-0") != stable_hash("room-1")
    for k in ("", "a", "room/x", "☃"):
        assert 0 <= stable_hash(k) < (1 << 64)


def test_ring_owner_deterministic_across_instances():
    a = HashRing(range(8), vnodes=32)
    b = HashRing(range(8), vnodes=32)
    guids = [f"doc-{i}" for i in range(500)]
    assert [a.owner(g) for g in guids] == [b.owner(g) for g in guids]
    # membership means every shard actually gets traffic
    assert len({a.owner(g) for g in guids}) == 8


def test_ring_minimal_movement_on_membership_change():
    before = HashRing(range(8), vnodes=64)
    guids = [f"doc-{i}" for i in range(2000)]
    owners = {g: before.owner(g) for g in guids}
    before.add(8)  # scale out 8 -> 9
    moved = sum(1 for g in guids if before.owner(g) != owners[g])
    # classic consistent hashing: ~1/9 of docs re-home, never a reshuffle
    assert 0 < moved < len(guids) * 0.25
    # every doc that moved, moved TO the new shard
    assert all(
        before.owner(g) == 8 for g in guids if before.owner(g) != owners[g]
    )


def test_bounded_load_sheds_off_hot_shard():
    ring = HashRing(range(4), vnodes=64)
    loads = {s: 0 for s in range(4)}
    caps = {s: 1000 for s in range(4)}
    placed = {}
    for i in range(400):
        g = f"doc-{i}"
        s, _shed = ring.place(g, loads.get, caps.get, 1.25)
        loads[s] += 1
        placed[g] = s
    # the ceiling held: no shard exceeds ceil(1.25 * total / N) by more
    # than the +1 headroom the formula grants per placement
    assert max(loads.values()) <= (1.25 * (400 + 1) / 4) + 1
    # and at least one doc was diverted off its natural owner
    assert any(ring.owner(g) != placed[g] for g in placed)


def test_place_fallback_and_fleet_full():
    ring = HashRing(range(2), vnodes=16)
    # both shards over the bound but one has a hard slot free: the
    # least-loaded one takes it rather than failing
    s, shed = ring.place("doc", {0: 10, 1: 9}.get, {0: 10, 1: 10}.get, 0.5)
    assert s == 1 and shed
    with pytest.raises(FleetFullError):
        ring.place("doc", {0: 10, 1: 10}.get, {0: 10, 1: 10}.get, 1.25)


def test_routing_table_versioned():
    t = RoutingTable()
    assert t.epoch == 0 and t.lookup("a") is None
    t.assign("a", 2)
    assert t.epoch == 0  # bare assign does not version
    t.assign("b", 2, bump=True)
    assert t.epoch == 1
    assert t.docs_on(2) == ["a", "b"]
    t.unassign("a", bump=True)
    assert t.epoch == 2 and t.lookup("a") is None
    snap = t.snapshot()
    assert snap["n_docs"] == 1 and snap["per_shard"] == {2: 1}


# -- fleet facade ------------------------------------------------------------


def test_fleet_admits_past_single_shard_capacity():
    fleet = FleetRouter(3, 2, backend="cpu")
    for i in range(5):  # one shard caps at 2; the fleet holds 6
        fleet.receive_update(f"doc-{i}", update_for(f"text {i}"))
    fleet.flush()
    for i in range(5):
        assert fleet.text(f"doc-{i}") == f"text {i}"
        owner = fleet.owner_of(f"doc-{i}")
        assert owner is not None
        assert fleet.shards[owner].has_doc(f"doc-{i}")
    assert fleet.doc_count == 5 and fleet.capacity == 6
    fleet.receive_update("doc-5", update_for("last slot"))
    with pytest.raises(FleetFullError):
        fleet.receive_update("doc-6", update_for("no room"))


def test_fleet_speaks_the_provider_surface():
    fleet = FleetRouter(2, 2, backend="cpu")
    fleet.receive_update("room", update_for("surface"))
    ref = TpuProvider(1, backend="cpu")
    ref.receive_update("room", update_for("surface"))
    assert fleet.text("room") == ref.text("room") == "surface"
    assert fleet.state_vector("room") == ref.state_vector("room")
    assert Y.merge_updates([fleet.encode_state_as_update("room")]) == (
        Y.merge_updates([ref.encode_state_as_update("room")])
    )
    assert isinstance(fleet.sync_step1("room"), bytes)
    h = fleet.health()
    assert len(h["shards"]) == 2 and h["fleet"]["docs"] == 1
    snap = fleet.fleet_snapshot()
    assert snap["n_shards"] == snap["live_shards"] == 2
    assert snap["capacity"] == 4 and snap["migrations_active"] == 0
    row = snap["shards"][0]
    for key in ("shard", "docs", "capacity", "occupancy", "state",
                "dlq", "sessions", "migrating", "mig_in", "mig_out"):
        assert key in row


# -- live migration ----------------------------------------------------------


def test_migrate_doc_preserves_bytes_frees_slot_bumps_epoch():
    fleet = FleetRouter(2, 2, backend="cpu")
    fleet.receive_update("room", update_for("move me"))
    src = fleet.shard_of("room")
    dst = 1 - src
    before = Y.merge_updates([fleet.encode_state_as_update("room")])
    epoch0 = fleet.table.epoch
    fleet.migrate_doc("room", dst)
    assert fleet.owner_of("room") == dst
    assert fleet.table.epoch == epoch0 + 1
    assert not fleet.shards[src].has_doc("room")  # slot freed for reuse
    assert fleet.shards[dst].has_doc("room")
    assert fleet.text("room") == "move me"
    assert Y.merge_updates([fleet.encode_state_as_update("room")]) == before


def test_double_delivery_window_loses_no_inflight_update():
    fleet = FleetRouter(2, 2, backend="cpu")
    d = Y.Doc(gc=False)
    d.client_id = 7
    d.get_text("text").insert(0, "base")
    fleet.receive_update("room", encode_state_as_update(d))
    src = fleet.shard_of("room")
    dst = 1 - src
    fleet.begin_migration("room", dst)
    assert fleet.fleet_snapshot()["migrations_active"] == 1
    # an edit lands INSIDE the window: both shards must journal it
    sv = encode_state_vector(d)
    d.get_text("text").insert(0, "tail-")
    fleet.receive_update("room", encode_state_as_update(d, sv))
    fleet.complete_migration("room")
    assert fleet.owner_of("room") == dst
    assert fleet.text("room") == "tail-base"


def test_migration_misuse_is_typed():
    fleet = FleetRouter(2, 2, backend="cpu")
    fleet.receive_update("room", update_for("x"))
    src = fleet.shard_of("room")
    with pytest.raises(ValueError):
        fleet.migrate_doc("room", src)  # already lives there
    with pytest.raises(ValueError):
        fleet.migrate_doc("room", 99)  # not a shard
    with pytest.raises(RuntimeError):
        fleet.complete_migration("room")  # no window open
    fleet.begin_migration("room", 1 - src)
    with pytest.raises(RuntimeError):
        fleet.begin_migration("room", 1 - src)  # already migrating
    fleet.complete_migration("room")


def test_drain_shard_retires_and_excludes_from_placement():
    fleet = FleetRouter(3, 4, backend="cpu")
    for i in range(6):
        fleet.receive_update(f"doc-{i}", update_for(f"t{i}"))
    texts = {f"doc-{i}": f"t{i}" for i in range(6)}
    victim = fleet.shard_of("doc-0")
    on_victim = len(fleet.shards[victim].guids())
    moved = fleet.drain_shard(victim)
    assert moved == on_victim >= 1
    assert not fleet.shards[victim].guids()
    assert victim not in fleet.live_shards
    assert fleet.fleet_snapshot()["shards"][victim]["state"] == "retired"
    for g, t in texts.items():
        assert fleet.text(g) == t
        assert fleet.owner_of(g) != victim
    # future placements never propose the retired shard
    for i in range(6, 8):  # 2 live shards x 4 slots hold 8 docs total
        fleet.receive_update(f"doc-{i}", update_for("new"))
        assert fleet.owner_of(f"doc-{i}") != victim
    assert fleet.drain_shard(victim) == 0  # idempotent


def test_drain_fails_fast_when_rest_of_fleet_lacks_slots():
    fleet = FleetRouter(2, 2, backend="cpu")
    for i in range(4):  # full fleet: nowhere to move anything
        fleet.receive_update(f"doc-{i}", update_for(f"t{i}"))
    victim = fleet.shard_of("doc-0")
    snapshot_before = fleet.fleet_snapshot()
    with pytest.raises(FleetFullError, match="add_shard"):
        fleet.drain_shard(victim)
    # the veto left the fleet untouched — no half-drained wedge
    assert victim in fleet.live_shards
    assert fleet.fleet_snapshot() == snapshot_before


def test_add_shard_grows_capacity_and_joins_ring():
    fleet = FleetRouter(2, 2, backend="cpu")
    for i in range(4):
        fleet.receive_update(f"doc-{i}", update_for(f"t{i}"))
    with pytest.raises(FleetFullError):
        fleet.receive_update("doc-4", update_for("full"))
    epoch0 = fleet.table.epoch
    k = fleet.add_shard()
    assert k == 2 and fleet.capacity == 6
    assert fleet.table.epoch == epoch0 + 1
    fleet.receive_update("doc-4", update_for("fits now"))
    assert fleet.owner_of("doc-4") == k  # only shard with room
    assert fleet.text("doc-4") == "fits now"


# -- rebalancer --------------------------------------------------------------


def hot_fleet(high=0.75, target=0.5, batch=8):
    cfg = FleetConfig(
        rebalance_high=high, rebalance_target=target, rebalance_batch=batch,
    )
    fleet = FleetRouter(2, 4, backend="cpu", config=cfg)
    for i in range(4):
        fleet.receive_update(f"doc-{i}", update_for(f"t{i}"))
    # herd everything onto shard 0 so it sits at occupancy 1.0
    for i in range(4):
        if fleet.shard_of(f"doc-{i}") != 0:
            fleet.migrate_doc(f"doc-{i}", 0)
    assert fleet.shards[0].occupancy == 1.0
    return fleet


def test_rebalancer_sheds_hot_shard_to_target():
    fleet = hot_fleet()
    decisions = fleet.tick()
    moves = [d for d in decisions if d["action"] == "move"]
    assert moves and all(d["src"] == 0 for d in moves)
    # shed down to target occupancy (0.5 * 4 slots = 2 docs), texts kept
    assert len(fleet.shards[0].guids()) == 2
    for i in range(4):
        assert fleet.text(f"doc-{i}") == f"t{i}"
    # a balanced fleet's next tick is a no-op
    assert fleet.tick() == []


def test_rebalancer_moves_coldest_docs_first():
    fleet = hot_fleet()
    fleet.session("doc-0", "peer", quiet_config())  # doc-0 is now warm
    moves = [d for d in fleet.rebalancer.plan() if d["action"] == "move"]
    assert [d["guid"] for d in moves] == ["doc-1", "doc-2"]  # sessionless


def test_rebalancer_records_stuck_when_nowhere_to_move():
    cfg = FleetConfig(
        rebalance_high=0.75, rebalance_target=0.5, rebalance_batch=4,
    )
    fleet = FleetRouter(2, 2, backend="cpu", config=cfg)
    for i in range(4):  # both shards at 1.0: no destination qualifies
        fleet.receive_update(f"doc-{i}", update_for(f"t{i}"))
    decisions = fleet.tick()
    assert decisions and all(d["action"] == "stuck" for d in decisions)
    assert fleet.doc_count == 4  # nothing thrashed


# -- mesh placement ----------------------------------------------------------


def test_shard_meshes_partition_devices_contiguously():
    from yjs_tpu.parallel import shard_meshes

    meshes = shard_meshes(4, devices_per_shard=2)  # conftest: 8 cpu devs
    assert len(meshes) == 4
    seen = []
    for m in meshes:
        assert m is not None and m.devices.size == 2
        seen.extend(d.id for d in m.devices.flat)
    assert seen == sorted(seen) and len(set(seen)) == 8  # disjoint, dealt

    # more shards than devices: the degraded mode is explicit Nones
    assert shard_meshes(16) == [None] * 16
    with pytest.raises(ValueError):
        shard_meshes(0)


# -- observability -----------------------------------------------------------


def test_fleet_metric_families_registered_globally():
    from yjs_tpu.obs import global_registry

    FleetRouter(1, 1, backend="cpu")
    names = set(global_registry().names())
    for fam in (
        "ytpu_fleet_shards",
        "ytpu_fleet_docs",
        "ytpu_fleet_shard_docs",
        "ytpu_fleet_shard_occupancy",
        "ytpu_fleet_routing_epoch",
        "ytpu_fleet_placements_total",
        "ytpu_fleet_migrations_total",
        "ytpu_fleet_migration_seconds",
        "ytpu_fleet_double_delivered_total",
        "ytpu_fleet_rebalance_decisions_total",
    ):
        assert fam in names, fam


def test_fleet_gauges_track_state():
    from yjs_tpu.obs import global_registry

    fleet = FleetRouter(2, 2, backend="cpu")
    fleet.receive_update("room", update_for("x"))
    fleet._refresh_gauges()
    r = global_registry()
    assert r.get("ytpu_fleet_shards").value == 2
    assert r.get("ytpu_fleet_docs").value == 1
    assert r.get("ytpu_fleet_routing_epoch").value == fleet.table.epoch
    occ = {
        labels["shard"]: series.value
        for labels, series in r.get("ytpu_fleet_shard_occupancy").samples()
        if labels["shard"] in ("0", "1")
    }
    owner = str(fleet.shard_of("room"))
    assert occ[owner] == 0.5


def test_ytpu_top_renders_fleet_table():
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "ytpu_top_fleet_test",
        pathlib.Path(__file__).resolve().parent.parent
        / "scripts" / "ytpu_top.py",
    )
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)

    fleet = FleetRouter(3, 2, backend="cpu")
    for i in range(4):
        fleet.receive_update(f"doc-{i}", update_for(f"t{i}"))
    fleet.session("doc-0", "peer", quiet_config())
    row = top.collect_row("fleet-a", fleet.metrics_snapshot(), None, 1.0)
    assert row["fleet"] and len(row["fleet"]) == 3
    frame = top.render([row], 1.0)
    assert "fleet:" in frame and "occup" in frame and "shard" in frame


# -- sessions over the fleet -------------------------------------------------


def test_fleet_sessions_fan_out_across_shards():
    fleet = FleetRouter(2, 2, backend="cpu")
    peer = TpuProvider(4, backend="cpu")
    net = PipeNetwork()
    rooms = [f"doc-{i}" for i in range(3)]
    for g in rooms:
        tf, tp = net.pair(f"f-{g}", f"p-{g}")
        fleet.session(g, "peer", quiet_config()).connect(tf)
        peer.session(g, "fleet", quiet_config()).connect(tp)
    net.settle((drive(fleet, peer),))
    # the rooms span both shards yet one facade serves them all
    assert len({fleet.shard_of(g) for g in rooms}) == 2
    for g in rooms:
        peer.receive_update(g, update_for(f"from peer {g}"))
    net.settle((drive(fleet, peer),))
    for g in rooms:
        assert fleet.text(g) == f"from peer {g}"
    # and the reverse direction: fleet-side traffic reaches the peer
    fleet.receive_update(rooms[0], update_for("from fleet", client_id=5))
    net.settle((drive(fleet, peer),))
    assert "from fleet" in peer.text(rooms[0])
    rows = fleet.sessions_snapshot()
    assert len(rows) == 3
    assert all(row["shard"] == fleet.shard_of(row["guid"]) for row in rows)


def test_fleet_session_survives_live_migration():
    fleet = FleetRouter(2, 2, backend="cpu")
    peer = TpuProvider(1, backend="cpu")
    net = PipeNetwork()
    tf, tp = net.pair()
    sf = fleet.session("room", "peer", quiet_config(antientropy=2))
    sp = peer.session("room", "fleet", quiet_config(antientropy=2))
    sf.connect(tf)
    sp.connect(tp)
    net.settle((drive(fleet, peer),))
    peer.receive_update("room", update_for("pre-move"))
    net.settle((drive(fleet, peer),))
    assert fleet.text("room") == "pre-move"
    src = fleet.shard_of("room")
    fleet.migrate_doc("room", 1 - src)
    # the session re-homed in place: no reconnect, epoch current, and
    # rehome() forced a digest so divergence heals immediately
    assert sf.routing_epoch == fleet.table.epoch
    assert not sf._closed and sf.state == "live"
    net.settle((drive(fleet, peer),), max_rounds=60, idle_rounds=3)
    peer.receive_update("room", update_for("post-move", client_id=3))
    net.settle((drive(fleet, peer),), max_rounds=60, idle_rounds=3)
    assert "post-move" in fleet.text("room")
    assert fleet.text("room") == peer.text("room")
    assert sf.n_full_resyncs == 1 and sp.n_full_resyncs == 1


def test_fleet_session_admission_is_atomic():
    fleet = FleetRouter(1, 1, backend="cpu")
    fleet.receive_update("a", update_for("occupies the only slot"))
    with pytest.raises(ValueError):  # ProviderFullError
        fleet.session("b", "peer", quiet_config())
    assert ("b", "peer") not in fleet._sessions  # veto left no entry
    fleet.shards[0].release_doc("a")
    sess = fleet.session("b", "peer", quiet_config())  # now admits
    assert fleet._sessions[("b", "peer")] is sess
