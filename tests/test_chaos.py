"""Chaos suite (ISSUE 2): deterministic fault injection at the sync
seams, asserting replica convergence under every fault mix.

Two provider replicas receive the same client-update stream through
independently seeded :class:`ChaosInjector` instances (different faults
hit each side), then run the normal 2-step sync repair.  The contract:
whatever the transport does — corrupt, truncate, duplicate, reorder,
drop — the replicas end IDENTICAL (text, state vector, encoded SV
bytes).  Content lost by BOTH sides may be absent, but never divergent;
lossless mixes (dup/reorder only) must match the oracle exactly.

Every test is seeded — a failure replays byte-for-byte.  Runs in tier-1
(the ``chaos`` marker deselects it with ``-m 'not chaos'``).
"""

import random

import pytest

import yjs_tpu as Y
from yjs_tpu.lib0 import encoding
from yjs_tpu.lib0.encoding import Encoder
from yjs_tpu.provider import TpuProvider
from yjs_tpu.resilience import ChaosConfig, ChaosInjector
from yjs_tpu.sync import protocol

pytestmark = pytest.mark.chaos

ROOM = "room"
BACKENDS = ("cpu", "auto")


def client_updates(seed: int, n_ops: int = 60, n_clients: int = 3):
    """Per-op incremental updates from independent editing clients (the
    captured doc.on('update') stream a transport would carry)."""
    gen = random.Random(seed)
    docs = []
    updates: list[bytes] = []
    for k in range(n_clients):
        d = Y.Doc(gc=False)
        d.client_id = 1000 + k
        d.on("update", lambda u, origin, doc: updates.append(bytes(u)))
        docs.append(d)
    for _ in range(n_ops):
        d = gen.choice(docs)
        t = d.get_text("text")
        if len(t) and gen.random() < 0.3:
            t.delete(gen.randrange(len(t)), 1)
        else:
            t.insert(gen.randrange(len(t) + 1), gen.choice("abcdef "))
    oracle = Y.Doc(gc=False)
    for u in updates:
        Y.apply_update(oracle, u)
    return updates, oracle.get_text("text").__str__()


def frame(update: bytes) -> bytes:
    enc = Encoder()
    encoding.write_var_uint(enc, protocol.MESSAGE_YJS_UPDATE)
    encoding.write_var_uint8_array(enc, update)
    return enc.to_bytes()


def sync_repair(pa: TpuProvider, pb: TpuProvider, rounds: int = 3) -> None:
    """Clean bidirectional step1/step2 exchange (the post-chaos network
    heal); several rounds unpark causal cascades."""
    for _ in range(rounds):
        reply = pb.handle_sync_message(ROOM, pa.sync_step1(ROOM))
        if reply is not None:
            pa.handle_sync_message(ROOM, reply)
        reply = pa.handle_sync_message(ROOM, pb.sync_step1(ROOM))
        if reply is not None:
            pb.handle_sync_message(ROOM, reply)


def assert_identical(pa: TpuProvider, pb: TpuProvider) -> None:
    assert pa.text(ROOM) == pb.text(ROOM)
    assert pa.state_vector(ROOM) == pb.state_vector(ROOM)
    # byte-level identity: each replica's full state is a strict no-op
    # on the other (the encoded SV itself may order clients differently
    # — both are valid wire encodings of the same vector)
    for src, dst in ((pa, pb), (pb, pa)):
        text_before = dst.text(ROOM)
        dst.receive_update(
            ROOM, src.engine.encode_state_as_update(src.doc_id(ROOM))
        )
        assert dst.text(ROOM) == text_before
        assert dst.state_vector(ROOM) == src.state_vector(ROOM)


FAULT_MIXES = {
    "dup_reorder": dict(duplicate=0.4, reorder=0.8),
    "corrupt": dict(corrupt=0.25),
    "truncate": dict(truncate=0.25),
    "drop": dict(drop=0.25),
    "everything": dict(
        corrupt=0.15, truncate=0.1, duplicate=0.25, reorder=0.6, drop=0.15
    ),
}


@pytest.mark.parametrize("backend", BACKENDS)
@pytest.mark.parametrize("mix", sorted(FAULT_MIXES))
def test_replicas_converge_under_faults(backend, mix):
    updates, oracle_text = client_updates(seed=101)
    frames = [frame(u) for u in updates]
    pa = TpuProvider(2, backend=backend)
    pb = TpuProvider(2, backend=backend)
    inj_a = ChaosInjector(ChaosConfig(seed=7, **FAULT_MIXES[mix]), kind="frame")
    inj_b = ChaosInjector(ChaosConfig(seed=8, **FAULT_MIXES[mix]), kind="frame")
    for f in inj_a.apply(frames):
        pa.handle_sync_message(ROOM, f)
    for f in inj_b.apply(frames):
        pb.handle_sync_message(ROOM, f)
    sync_repair(pa, pb)
    assert_identical(pa, pb)
    # chaos actually happened (deterministic given the seeds)
    assert sum(inj_a.fault_counts.values()) > 0
    assert sum(inj_b.fault_counts.values()) > 0
    if mix == "dup_reorder":
        # lossless faults: the converged replicas match the oracle too
        assert pa.text(ROOM) == oracle_text
    # frame tolerance never demotes or quarantines the room
    assert pa.health(ROOM)["state"] == "healthy"
    assert pb.health(ROOM)["state"] == "healthy"


@pytest.mark.parametrize("backend", BACKENDS)
def test_raw_update_chaos_quarantines_not_wedges(backend):
    """Corrupt RAW updates (no frame seam to reject them early) reach
    the engine: isolation rolls back, health quarantines, and the two
    replicas still converge after sync repair + replay."""
    updates, _ = client_updates(seed=202, n_ops=40)
    pa = TpuProvider(2, backend=backend)
    pb = TpuProvider(2, backend=backend)
    inj = ChaosInjector(ChaosConfig(seed=3, corrupt=0.2), kind="update")
    for u in inj.apply(updates):
        pa.receive_update(ROOM, u)
        pa.flush()
    for u in updates:  # pb gets the clean stream
        pb.receive_update(ROOM, u)
    assert inj.fault_counts["corrupt"] > 0
    assert pa.engine.dead_letters.total > 0
    sync_repair(pa, pb)
    assert_identical(pa, pb)


def test_injector_deterministic():
    updates, _ = client_updates(seed=55, n_ops=20)
    cfg = dict(corrupt=0.3, truncate=0.2, duplicate=0.3, reorder=0.9, drop=0.2)
    out1 = ChaosInjector(ChaosConfig(seed=42, **cfg)).apply(updates)
    out2 = ChaosInjector(ChaosConfig(seed=42, **cfg)).apply(updates)
    out3 = ChaosInjector(ChaosConfig(seed=43, **cfg)).apply(updates)
    assert out1 == out2
    assert out1 != out3  # seed actually matters


def test_corruption_is_always_detectable():
    """The detectability contract: every corrupt/truncate product fails
    validate_update — a corruption that still decoded would be silent
    divergence (Byzantine), which the harness must never inject."""
    from yjs_tpu.updates import InvalidUpdate, validate_update

    updates, _ = client_updates(seed=77, n_ops=30)
    inj = ChaosInjector(ChaosConfig(seed=5))
    for u in updates:
        for bad in (inj.corrupt(u), inj.truncate(u)):
            with pytest.raises(InvalidUpdate):
                validate_update(bad)


def test_chaos_config_from_env(monkeypatch):
    for k in ("CORRUPT", "TRUNCATE", "DUP", "REORDER", "DROP"):
        monkeypatch.delenv(f"YTPU_CHAOS_{k}", raising=False)
    assert not ChaosConfig.from_env().any_faults()
    monkeypatch.setenv("YTPU_CHAOS_SEED", "99")
    monkeypatch.setenv("YTPU_CHAOS_CORRUPT", "0.5")
    monkeypatch.setenv("YTPU_CHAOS_DUP", "2.5")  # clamped to 1.0
    monkeypatch.setenv("YTPU_CHAOS_DROP", "bogus")  # ignored -> 0
    cfg = ChaosConfig.from_env()
    assert cfg.seed == 99
    assert cfg.corrupt == 0.5
    assert cfg.duplicate == 1.0
    assert cfg.drop == 0.0
    assert cfg.any_faults()


def test_chaos_fault_counters_exported():
    from yjs_tpu.obs import global_registry

    fam = global_registry().get("ytpu_chaos_faults_total")
    drop_child = fam.labels(fault="drop")
    before = drop_child.value
    inj = ChaosInjector(ChaosConfig(seed=1, drop=1.0))
    inj.apply([b"x", b"y", b"z"])
    assert drop_child.value == before + 3
