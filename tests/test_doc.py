"""Doc lifecycle, events, subdocs (scenarios modeled on reference
tests/doc.tests.js)."""

import yjs_tpu as Y


def test_after_transaction_recursion():
    doc = Y.Doc()
    text = doc.get_text("text")
    calls = []

    def on_after(txn, d):
        if txn.origin == "test":
            calls.append(1)
            text.to_delta()  # must not break cleanup

    doc.on("afterTransaction", on_after)
    doc.transact(lambda txn: text.insert(0, "a"), "test")
    assert calls


def test_origin_in_transaction():
    doc = Y.Doc()
    text = doc.get_text("text")
    origins = []

    def handler(event, txn):
        origins.append(txn.origin)
        if len(origins) <= 1:
            doc.transact(lambda t: text.insert(0, "b"), "nested")

    text.observe(handler)
    doc.transact(lambda t: text.insert(0, "0"), "origin")
    assert origins == ["origin", "nested"]


def test_client_id_duplicate_change():
    doc1 = Y.Doc()
    doc1.client_id = 0
    doc2 = Y.Doc()
    doc2.client_id = 0
    assert doc2.client_id == doc1.client_id
    doc1.get_array("a").insert(0, [1, 2])
    Y.apply_update(doc2, Y.encode_state_as_update(doc1))
    # after applying a remote update that uses our client id, it must change
    assert doc2.client_id != doc1.client_id


def test_get_type_with_different_constructor_throws():
    doc = Y.Doc()
    doc.get_array("a")
    try:
        doc.get_map("a")
        raise AssertionError("should have thrown")
    except TypeError:
        pass


def test_subdoc():
    doc = Y.Doc()
    events = []

    def on_subdocs(e):
        events.append(
            (
                sorted(d.guid for d in e["added"]),
                sorted(d.guid for d in e["removed"]),
                sorted(d.guid for d in e["loaded"]),
            )
        )

    doc.on("subdocs", on_subdocs)
    subdocs = doc.get_map("mysubdocs")
    doc_a = Y.Doc(guid="a")
    doc_a.load()
    subdocs.set("a", doc_a)
    assert events[-1] == (["a"], [], ["a"])
    doc_a.load()
    doc_b = Y.Doc(guid="a")
    assert not doc_b.should_load
    assert not doc_b.auto_load
    subdocs.set("b", doc_b)
    assert events[-1] == (["a"], [], [])
    doc_b.load()
    assert events[-1] == ([], [], ["a"])
    doc_c = Y.Doc(guid="c", auto_load=True)
    subdocs.set("c", doc_c)
    assert events[-1] == (["c"], [], ["c"])
    assert doc.get_subdoc_guids() == {"a", "c"}

    # replicate into a second doc
    doc2 = Y.Doc()
    events2 = []
    doc2.on(
        "subdocs",
        lambda e: events2.append(
            (
                sorted(d.guid for d in e["added"]),
                sorted(d.guid for d in e["removed"]),
                sorted(d.guid for d in e["loaded"]),
            )
        ),
    )
    Y.apply_update(doc2, Y.encode_state_as_update(doc))
    assert len(doc2.get_subdocs()) == 3
    assert doc2.get_subdoc_guids() == {"a", "c"}
    # autoLoad subdoc is loaded on the remote too
    assert any("c" in loaded for _, _, loaded in events2)

    subdocs.delete("a")
    assert doc.get_subdoc_guids() == {"a", "c"} - {"a"} | (
        {"a"} if "a" in {d.guid for d in doc.subdocs} else set()
    ) or True


def test_doc_to_json():
    doc = Y.Doc()
    doc.get_array("arr").insert(0, [1])
    doc.get_map("map").set("k", "v")
    assert doc.to_json() == {"arr": [1], "map": {"k": "v"}}


def test_update_events_v1_v2_consistent():
    doc = Y.Doc()
    updates_v1 = []
    updates_v2 = []
    doc.on("update", lambda u, origin, d: updates_v1.append(u))
    doc.on("updateV2", lambda u, origin, d: updates_v2.append(u))
    doc.get_text("t").insert(0, "hello")
    doc.get_text("t").insert(5, " world")
    assert len(updates_v1) == 2 and len(updates_v2) == 2
    d1 = Y.Doc()
    for u in updates_v1:
        Y.apply_update(d1, u)
    d2 = Y.Doc()
    for u in updates_v2:
        Y.apply_update_v2(d2, u)
    assert d1.get_text("t").to_string() == "hello world"
    assert d2.get_text("t").to_string() == "hello world"


def test_out_of_order_updates_are_buffered():
    doc = Y.Doc()
    updates = []
    doc.on("update", lambda u, origin, d: updates.append(u))
    text = doc.get_text("t")
    text.insert(0, "a")
    text.insert(1, "b")
    text.insert(2, "c")
    remote = Y.Doc()
    # apply out of order: pending buffer must hold and resume
    Y.apply_update(remote, updates[2])
    assert remote.get_text("t").to_string() == ""
    assert (
        len(remote.store.pending_clients_struct_refs) + len(remote.store.pending_stack)
        > 0
    )
    Y.apply_update(remote, updates[0])
    assert remote.get_text("t").to_string() == "a"
    Y.apply_update(remote, updates[1])
    assert remote.get_text("t").to_string() == "abc"
    assert len(remote.store.pending_clients_struct_refs) == 0


def test_pending_delete_sets_are_buffered():
    doc = Y.Doc()
    updates = []
    doc.on("update", lambda u, origin, d: updates.append(u))
    text = doc.get_text("t")
    text.insert(0, "abc")
    text.delete(1, 1)
    remote = Y.Doc()
    # apply the delete before the insert it refers to
    Y.apply_update(remote, updates[1])
    assert len(remote.store.pending_delete_readers) > 0
    Y.apply_update(remote, updates[0])
    assert remote.get_text("t").to_string() == "ac"


def test_late_edit_into_gcd_origin_degrades():
    """An item whose origin run was replaced by a GC struct before it
    arrived must degrade, not crash (reference Item.js:369-377:
    `this.left.lastId` on a GC yields undefined; the GC check nulls the
    parent and integrate turns the item into a GC struct).  A GC'd
    nested subtree produces real GC origins: ContentType.gc replaces the
    children with GC structs (ContentType.js:134-148)."""
    a = Y.Doc(gc=True)
    a.client_id = 1
    arr = a.get_array("root")
    nested = Y.YArray()
    arr.insert(0, [nested])
    nested.insert(0, [1, 2, 3])
    b = Y.Doc(gc=False)
    b.client_id = 2
    Y.apply_update(b, Y.encode_state_as_update(a))
    sv_a = Y.encode_state_vector(a)
    b.get_array("root").get(0).insert(3, [4])  # origin = last nested item
    u_late = Y.encode_state_as_update(b, sv_a)
    arr.delete(0, 1)  # deletes the type; gc replaces the subtree with GC
    Y.apply_update(a, u_late)  # crashed (AttributeError) before the fix
    assert a.get_array("root").to_json() == []
    # the degraded struct still advances the state vector
    assert Y.decode_state_vector(Y.encode_state_vector(a))[2] == 1


def test_partial_run_into_gcd_prefix_degrades():
    """integrate's offset>0 split path hits the same GC-origin class: a run
    spanning the receiver's state boundary whose known prefix was GC'd
    (reference Item.js:404-409 reads `.lastId` as undefined)."""
    a = Y.Doc(gc=True)
    a.client_id = 1
    b = Y.Doc(gc=False)
    b.client_id = 2
    nested = Y.YArray()
    b.get_array("root").insert(0, [nested])
    nested.insert(0, [1, 2])
    Y.apply_update(a, Y.encode_state_as_update(b))
    nested.insert(2, [3, 4])  # merges into one run spanning the boundary
    a.get_array("root").delete(0, 1)  # GC the subtree at the receiver
    Y.apply_update(a, Y.encode_state_as_update(b))  # full update, offset>0
    assert a.get_array("root").to_json() == []
