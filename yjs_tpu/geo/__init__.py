"""Geo-distributed active-active replication (ISSUE 17).

See :mod:`yjs_tpu.geo.replicator` for the region driver and
:mod:`yjs_tpu.geo.space` for the doc-space codecs and session host.
"""

from .replicator import (
    GeoConfig,
    GeoLink,
    GeoMetrics,
    GeoReplicator,
    GeoSession,
)
from .space import (
    SpaceSessionHost,
    decode_space_sv,
    decode_space_update,
    encode_space_sv,
    encode_space_update,
)

__all__ = [
    "GeoConfig",
    "GeoLink",
    "GeoMetrics",
    "GeoReplicator",
    "GeoSession",
    "SpaceSessionHost",
    "decode_space_sv",
    "decode_space_update",
    "encode_space_sv",
    "encode_space_update",
]
