"""Doc-space codecs + the session host seam for inter-region links.

A geo link peers per doc-SPACE, not per doc (ISSUE 17): one type-121
:class:`~yjs_tpu.sync.session.SyncSession` carries every room a region
holds, so N regions x M docs costs N-1 sessions per region instead of
(N-1) x M.  The session machinery is reused byte-for-byte — seq/ack,
retransmit backoff, resume-vs-full-resync handshakes, digests — by
giving it a host whose "state vector" and "updates" are COMPOSITE:

    space state vector:   varint n | n x (varstring guid,
                                         varint8array per-doc sv)
    space update payload: varint n | n x (varstring guid,
                                          varint8array per-doc update)

Composite payloads ride the wire inside the ordinary
``MESSAGE_YJS_UPDATE`` framing the session already wraps around inner
frames, so nothing in session.py knows the difference; only the two
seams that PARSE host bytes (the anti-entropy digest comparison and the
inbound frame handler) are overridden — see
:class:`~yjs_tpu.geo.replicator.GeoSession` and
:meth:`SpaceSessionHost.handle_frame`.
"""

from __future__ import annotations

from ..coding import default_ds_encoder
from ..lib0 import decoding, encoding
from ..lib0.decoding import Decoder
from ..lib0.encoding import Encoder
from ..obs import dist as obs_dist
from ..obs.blackbox import flight_recorder
from ..sync import protocol
from ..updates import decode_state_vector, write_state_vector

__all__ = [
    "SpaceSessionHost",
    "decode_space_sv",
    "decode_space_update",
    "encode_space_sv",
    "encode_space_update",
]


def _sv_bytes(sv: dict[int, int]) -> bytes:
    enc = default_ds_encoder()
    write_state_vector(enc, sv)
    return enc.to_bytes()


def encode_space_sv(svs: dict[str, dict[int, int]]) -> bytes:
    """``{guid: per-doc sv dict}`` -> composite space state vector."""
    enc = Encoder()
    encoding.write_var_uint(enc, len(svs))
    for guid in sorted(svs):
        encoding.write_var_string(enc, guid)
        encoding.write_var_uint8_array(enc, _sv_bytes(svs[guid]))
    return enc.to_bytes()


def decode_space_sv(data: bytes | None) -> dict[str, dict[int, int]]:
    """Inverse of :func:`encode_space_sv`.  Empty/absent/unparseable
    bytes decode to ``{}`` ("the peer has nothing"), which makes every
    doc look ahead — the safe direction: the diff then carries full
    state and the CRDT merge absorbs any overlap."""
    if not data:
        return {}
    out: dict[str, dict[int, int]] = {}
    try:
        dec = Decoder(bytes(data))
        n = decoding.read_var_uint(dec)
        for _ in range(n):
            guid = decoding.read_var_string(dec)
            out[guid] = decode_state_vector(
                bytes(decoding.read_var_uint8_array(dec))
            )
    except Exception:
        return {}
    return out


def encode_space_update(parts: list[tuple[str, bytes]]) -> bytes:
    """``[(guid, update bytes), ...]`` -> composite space update."""
    enc = Encoder()
    encoding.write_var_uint(enc, len(parts))
    for guid, upd in parts:
        encoding.write_var_string(enc, guid)
        encoding.write_var_uint8_array(enc, upd)
    return enc.to_bytes()


def decode_space_update(data: bytes) -> list[tuple[str, bytes]]:
    """Inverse of :func:`encode_space_update`.  Raises on malformed
    bytes — the caller dead-letters (session transports are content-
    clean by the chaos detectability contract, so a parse failure here
    is a real bug, not line noise)."""
    dec = Decoder(bytes(data))
    n = decoding.read_var_uint(dec)
    out = []
    for _ in range(n):
        guid = decoding.read_var_string(dec)
        out.append((guid, bytes(decoding.read_var_uint8_array(dec))))
    return out


# a V1 update of "nothing" (0 struct clients + empty delete set)
_EMPTY_UPDATE_LEN = 2


class SpaceSessionHost:
    """The :class:`~yjs_tpu.sync.session.SyncSession` host seam served
    by a whole region facade (a :class:`~yjs_tpu.provider.TpuProvider`,
    a :class:`~yjs_tpu.fleet.FleetRouter`, or a cluster
    :class:`~yjs_tpu.cluster.Supervisor`) instead of one room.

    The facade needs: ``receive_update(guid, update, internal=True)``,
    a per-doc state-vector surface (``state_vector(guid) -> dict`` or
    ``state_vector_bytes(guid) -> bytes``), and a per-doc diff surface
    (``encode_state_as_update(guid, sv)`` or ``diff_update(guid, sv)``)
    — both spellings are probed so every existing facade qualifies
    without change.  Doc discovery prefers ``facade.guids()``; facades
    without one (the RPC supervisor) fall back to the tracked set the
    replicator feeds from its update bridge and remote applies.
    """

    __slots__ = ("facade", "link", "_tracked")

    def __init__(self, facade, link=None):
        self.facade = facade
        self.link = link  # GeoLink back-pointer (floors, loss counting)
        self._tracked: set[str] = set()

    # -- doc discovery -------------------------------------------------------

    def track(self, guid: str) -> None:
        self._tracked.add(guid)

    def docs(self) -> list[str]:
        fn = getattr(self.facade, "guids", None)
        if callable(fn):
            names = set(fn())
        else:
            names = set()
            shards = getattr(self.facade, "shards", None)
            if shards:
                for p in shards:
                    try:
                        names.update(p.guids())
                    except Exception:
                        continue  # a dead shard hides nothing durable
        names.update(self._tracked)
        return sorted(names)

    # -- per-doc facade adapters ---------------------------------------------

    def _doc_sv_bytes(self, guid: str) -> bytes:
        fn = getattr(self.facade, "state_vector_bytes", None)
        if fn is not None:
            return fn(guid)
        return _sv_bytes(self.facade.state_vector(guid))

    def _doc_diff(self, guid: str, sv: bytes | None) -> bytes:
        fn = getattr(self.facade, "encode_state_as_update", None)
        if fn is not None:
            return fn(guid, sv if sv else None)
        return self.facade.diff_update(guid, sv if sv else None)

    # -- the session host seam -----------------------------------------------

    def state_vector(self) -> bytes:
        svs = {}
        for guid in self.docs():
            try:
                svs[guid] = decode_state_vector(self._doc_sv_bytes(guid))
            except Exception:
                continue
        return encode_space_sv(svs)

    def diff_update(self, sv: bytes | None) -> bytes:
        """Composite diff: per doc, everything the peer space's sv says
        it lacks.  Docs the peer has never heard of ship full state."""
        theirs = decode_space_sv(sv)
        parts: list[tuple[str, bytes]] = []
        for guid in self.docs():
            target = theirs.get(guid)
            try:
                upd = self._doc_diff(
                    guid, encode_sv_dict(target) if target else None
                )
            except Exception:
                continue
            if len(upd) > _EMPTY_UPDATE_LEN:
                parts.append((guid, upd))
        return encode_space_update(parts)

    def ahead_behind(self, peer_sv: bytes) -> tuple[bool, bool]:
        """The digest comparison at space granularity (the stock
        session parses its host's sv as ONE doc vector, which composite
        bytes are not — :class:`GeoSession` routes here instead)."""
        theirs = decode_space_sv(peer_sv)
        ahead = behind = False
        seen = set()
        for guid in self.docs():
            seen.add(guid)
            try:
                mine = decode_state_vector(self._doc_sv_bytes(guid))
            except Exception:
                continue
            t = theirs.get(guid, {})
            if any(c > t.get(k, 0) for k, c in mine.items()):
                ahead = True
            if any(c > mine.get(k, 0) for k, c in t.items()):
                behind = True
            if ahead and behind:
                return True, True
        # docs only the peer holds: we are behind on those
        if any(g not in seen for g in theirs):
            behind = True
        return ahead, behind

    def apply_update(self, payload: bytes) -> None:
        """Integrate one composite payload: per doc, through the
        region's normal ingress (``internal=True`` — WAN replication is
        already-admitted traffic, like migration and failover state
        transfer).  Emits the ``flow_end`` half of the cross-region
        Perfetto arrow minted by the sending link."""
        link = self.link
        for guid, upd in decode_space_update(payload):
            self.track(guid)
            if link is not None:
                link.note_remote_apply(guid, upd)
            self.facade.receive_update(guid, upd, internal=True)

    def handle_frame(self, frame: bytes) -> bytes | None:
        """Inbound inner frame from the peer session.  WAN links only
        ever wrap composite payloads in ``MESSAGE_YJS_UPDATE`` framing;
        anything else is tolerated-and-counted like the plain reader."""
        try:
            dec = Decoder(bytes(frame))
            mtype = decoding.read_var_uint(dec)
            if mtype != protocol.MESSAGE_YJS_UPDATE:
                return None
            payload = bytes(decoding.read_var_uint8_array(dec))
        except Exception:
            self.dead_letter(frame, "geo-bad-frame")
            return None
        self.apply_update(payload)
        return None

    def dead_letter(self, payload: bytes, reason: str) -> None:
        """A frame the link layer gave up on.  There is no single room
        to attribute it to, so it lands in the blackbox (force-sampled
        by the session's retry-cap path) and on the link's loss
        counter; the anti-entropy digest owns the repair."""
        ctx = obs_dist.current_context()
        if ctx is not None:
            # loss evidence must survive production sampling rates
            ctx = ctx.force("geo-link-dead-letter")
        flight_recorder().record(
            "geo", "link_dead_letter", severity="warning",
            trace=(ctx.trace_hex if ctx is not None else None),
            peer=(self.link.region if self.link is not None else None),
            reason=reason, size=len(payload),
        )
        if self.link is not None:
            self.link.note_dead_letter(reason)

    def journal_ack(self, sid: int, seq: int) -> None:
        if self.link is not None:
            self.link.on_recv_floor(sid, seq)


def encode_sv_dict(sv: dict[int, int]) -> bytes:
    """Public spelling of the per-doc sv dict -> bytes encoder (the
    replicator's delta scheduler uses it for diff targets)."""
    return _sv_bytes(sv)
