"""Geo-distributed active-active replication (ISSUE 17).

N independent regions — each a full single-site deployment (provider,
fleet, or process cluster) — join into one document space over
inter-region links that ride the existing type-121 session machinery:

- :class:`GeoSession` subclasses :class:`~yjs_tpu.sync.session.
  SyncSession`, inheriting seq/ack, retransmit backoff, resume-vs-full-
  resync handshakes, BUSY backpressure, and the anti-entropy loop
  unchanged; only the digest comparison is overridden (composite space
  state vectors are not one doc's vector) plus a convergence-latency
  stamp on the outbox.
- :class:`GeoLink` owns one remote region: a budgeted delta scheduler
  (the generalization of the lagging-peer single-pending-delta path —
  per-link byte budget from ``YTPU_GEO_LINK_BUDGET_BPS``, oldest-doc-
  first under pressure), exponential-backoff reconnect with seeded
  jitter, and the journaled ack floor (``KIND_GEO``) that lets a
  kill -9'd region RESUME its links instead of full-resyncing.
- :class:`GeoReplicator` is the per-region driver: peers with every
  other region per doc-space, bridges the facade's update stream into
  per-link dirty sets, runs the PR 8 alive→suspect→dead
  :class:`~yjs_tpu.fleet.failover.FailureDetector` over link health,
  and extends the PR 14 epoch event stream with region-level fencing
  epochs (a recovering region bumps its epoch; every link re-digests).

Knobs (``YTPU_GEO_*``): ``YTPU_GEO_REGION``,
``YTPU_GEO_LINK_BUDGET_BPS`` (0 = unlimited),
``YTPU_GEO_TICK_MS``, ``YTPU_GEO_RECONNECT_BASE``,
``YTPU_GEO_RECONNECT_CAP``, ``YTPU_GEO_RECONNECT_JITTER``.
Metrics: the ``ytpu_geo_*`` families (README "Geo replication").
"""

from __future__ import annotations

import inspect
import os
import random

from ..fleet.failover import (
    ALIVE,
    DEAD,
    SUSPECT,
    FailoverConfig,
    FailureDetector,
)
from ..lib0 import decoding
from ..lib0.encoding import Encoder
from ..obs import dist as obs_dist
from ..obs import global_registry
from ..obs.blackbox import flight_recorder
from ..sync import protocol
from ..sync.session import (
    LIVE,
    RECONNECTING,
    SessionConfig,
    SyncSession,
    _EMPTY_UPDATE_LEN,
)
from ..updates import decode_state_vector
from .space import (
    SpaceSessionHost,
    decode_space_update,
    encode_space_update,
    encode_sv_dict,
)

__all__ = [
    "GeoConfig",
    "GeoLink",
    "GeoMetrics",
    "GeoReplicator",
    "GeoSession",
]


def _env_int(name: str, default: int, lo: int = 0) -> int:
    try:
        return max(lo, int(os.environ.get(name, default)))
    except (TypeError, ValueError):
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return max(0.0, float(os.environ.get(name, default)))
    except (TypeError, ValueError):
        return default


class GeoConfig:
    """Resolved geo knobs (constructor args beat ``YTPU_GEO_*`` env
    beats defaults).  Ticks are replicator ticks; ``tick_ms`` converts
    them to wall time for the byte budget and the lag gauges."""

    __slots__ = ("region", "seed", "link_budget_bps", "tick_ms",
                 "reconnect_base", "reconnect_cap", "reconnect_jitter")

    def __init__(
        self,
        region: str | None = None,
        seed: int | None = None,
        link_budget_bps: int | None = None,
        tick_ms: int | None = None,
        reconnect_base: int | None = None,
        reconnect_cap: int | None = None,
        reconnect_jitter: float | None = None,
    ):
        self.region = (
            region if region is not None
            else os.environ.get("YTPU_GEO_REGION", "local")
        )
        self.seed = (
            seed if seed is not None else _env_int("YTPU_GEO_SEED", 0)
        )
        # bytes/second each link may ship; 0 = unlimited.  The per-tick
        # allowance is bps * tick_ms / 1000, accumulated while idle (up
        # to 4 ticks' worth) so a quiet link can burst one batch.
        self.link_budget_bps = (
            link_budget_bps if link_budget_bps is not None
            else _env_int("YTPU_GEO_LINK_BUDGET_BPS", 0)
        )
        self.tick_ms = max(
            1,
            tick_ms if tick_ms is not None
            else _env_int("YTPU_GEO_TICK_MS", 10, lo=1),
        )
        self.reconnect_base = max(
            1,
            reconnect_base if reconnect_base is not None
            else _env_int("YTPU_GEO_RECONNECT_BASE", 2, lo=1),
        )
        self.reconnect_cap = max(
            self.reconnect_base,
            reconnect_cap if reconnect_cap is not None
            else _env_int("YTPU_GEO_RECONNECT_CAP", 64, lo=1),
        )
        self.reconnect_jitter = (
            reconnect_jitter if reconnect_jitter is not None
            else _env_float("YTPU_GEO_RECONNECT_JITTER", 0.25)
        )

    def budget_per_tick(self) -> int:
        """Byte allowance one link accrues per tick (0 = unlimited)."""
        if not self.link_budget_bps:
            return 0
        return max(1, self.link_budget_bps * self.tick_ms // 1000)


class GeoMetrics:
    """The ``ytpu_geo_*`` instrument bundle (process-global registry by
    default, same dedup contract as the other metric bundles)."""

    def __init__(self, registry=None):
        r = registry if registry is not None else global_registry()
        self.registry = r
        self.links = r.gauge(
            "ytpu_geo_links",
            "Inter-region links by health state "
            "(alive / suspect / dead)",
            labelnames=("state",),
        )
        self.lag_bytes = r.gauge(
            "ytpu_geo_link_lag_bytes",
            "Unacked + unscheduled bytes queued toward one remote "
            "region (outbox inner frames plus pending dirty-doc diffs "
            "are not counted until scheduled)",
            labelnames=("link",),
        )
        self.lag_seconds = r.gauge(
            "ytpu_geo_link_lag_seconds",
            "Age of the oldest unshipped dirty doc or unacked frame on "
            "one link, in tick_ms-derived seconds",
            labelnames=("link",),
        )
        self.reconnects = r.counter(
            "ytpu_geo_reconnects_total",
            "Link transport reattachments after loss, per link",
            labelnames=("link",),
        )
        self.coalesced = r.counter(
            "ytpu_geo_coalesced_updates_total",
            "Local updates absorbed into an already-dirty doc's pending "
            "delta instead of shipping their own frame (the coalesce "
            "ratio's numerator; delta frames are the denominator)",
        )
        self.delta_frames = r.counter(
            "ytpu_geo_delta_frames_total",
            "Composite delta batches shipped across all links",
        )
        self.delta_bytes = r.counter(
            "ytpu_geo_delta_bytes_total",
            "Composite delta payload bytes shipped across all links",
        )
        self.deferrals = r.counter(
            "ytpu_geo_budget_deferrals_total",
            "Dirty docs deferred to a later tick because the link's "
            "byte budget was exhausted (oldest-doc-first under "
            "pressure)",
        )
        self.convergence = r.histogram(
            "ytpu_geo_convergence_seconds",
            "Cross-region convergence lag: local enqueue of a delta "
            "frame to the remote ack confirming integrate, in "
            "tick_ms-derived seconds",
            unit="s",
        )
        self.epoch = r.gauge(
            "ytpu_geo_epoch",
            "This region's fencing epoch (bumps on crash recovery and "
            "on upstream routing-epoch changes)",
        )
        self.dead_letters = r.counter(
            "ytpu_geo_dead_letters_total",
            "Frames a WAN link gave up on (retry cap / unparseable); "
            "anti-entropy owns the repair",
        )


class GeoSession(SyncSession):
    """A :class:`SyncSession` whose host is a doc SPACE.

    Everything rides the parent unchanged except the two seams that
    parse host bytes as one doc's state vector: the anti-entropy digest
    comparison (composite vectors compare per doc via
    ``host.ahead_behind``) and a convergence-latency stamp on outbox
    entries so the ack that confirms remote integrate observes the
    cross-region lag histogram."""

    def __init__(self, host, config=None, metrics=None, peer="geo",
                 geo_metrics=None, tick_ms: int = 10):
        super().__init__(host, config=config, metrics=metrics, peer=peer)
        self._geo_metrics = geo_metrics
        self._tick_ms = max(1, int(tick_ms))

    # -- convergence stamps --------------------------------------------------

    def _queue_data(self, inner, trace=None):
        super()._queue_data(inner, trace)
        if self._outbox:
            self._outbox[-1]["geo_t"] = self._tick

    def _drop_acked(self, cum: int) -> None:
        gm = self._geo_metrics
        if gm is not None and self._outbox:
            for e in self._outbox:
                if e["seq"] <= cum and "geo_t" in e:
                    gm.convergence.observe(
                        (self._tick - e["geo_t"]) * self._tick_ms / 1000.0
                    )
        super()._drop_acked(cum)

    # -- composite digest ----------------------------------------------------

    def _on_digest(self, dec) -> None:
        peer_sv = decoding.read_var_uint8_array(dec)
        self._peer_sv = peer_sv
        pol = self.policy
        if pol is not None and getattr(pol, "antientropy_paused", False):
            return
        ahead, behind = self.host.ahead_behind(bytes(peer_sv))
        if ahead:
            diff = self.host.diff_update(bytes(peer_sv))
            if len(diff) > _EMPTY_UPDATE_LEN:
                self.n_repairs += 1
                self.metrics.repairs.inc()
                inner = Encoder()
                protocol.write_update(inner, diff)
                self._queue_data(inner.to_bytes())
        if behind and self._tick - self._last_digest >= 2:
            self._send_digest()


class GeoLink:
    """One remote region: a :class:`GeoSession` plus the budgeted delta
    scheduler, reconnect backoff, and the journaled ack floor."""

    def __init__(self, replicator: "GeoReplicator", region: str,
                 connect_fn, session_config: SessionConfig | None = None):
        self.replicator = replicator
        self.region = str(region)
        self.connect_fn = connect_fn
        cfg = replicator.config
        self.host = SpaceSessionHost(replicator.facade, link=self)
        self.session = GeoSession(
            self.host,
            config=session_config,
            peer=f"geo:{self.region}",
            geo_metrics=replicator.metrics,
            tick_ms=cfg.tick_ms,
        )
        # oldest-doc-first dirty queue: guid -> first-dirty tick
        # (python dicts preserve insertion order; re-dirtying an
        # already-queued doc keeps its ORIGINAL position and age)
        self._dirty: dict[str, int] = {}
        # per-doc local sv at last scheduled send: the diff target that
        # makes scheduled batches incremental between digests
        self._sent_sv: dict[str, dict[int, int]] = {}
        self._budget = 0
        # cost telemetry (ISSUE 19): docs the byte budget held back —
        # their bytes count as kind="deferred" when they finally ship
        self._deferred: set[str] = set()
        self.shipped_bytes = 0
        self.deferred_bytes = 0
        # reconnect backoff, seeded per link (the FailureDetector
        # keyed-stream pattern) so N links never stampede a reconnect
        self._rng = random.Random(
            f"geo:{cfg.seed}:{cfg.region}:{self.region}"
        )
        self._reconnect_attempts = 0
        self._next_reconnect = 0
        self.n_reconnects = 0
        self.n_dead_letters = 0
        # the journaled floor: peer session id + cumulative recv seq
        # at this region's fencing epoch
        self.floor = {"sid": 0, "seq": 0, "epoch": replicator.epoch}

    # -- callbacks from the host/session -------------------------------------

    def on_recv_floor(self, sid: int, seq: int) -> None:
        self.floor = {
            "sid": int(sid), "seq": int(seq),
            "epoch": self.replicator.epoch,
        }
        self.replicator._journal_floor(self.region, self.floor)

    def note_dead_letter(self, reason: str) -> None:
        self.n_dead_letters += 1
        self.replicator.metrics.dead_letters.inc()

    def note_remote_apply(self, guid: str, update: bytes) -> None:
        """A doc arrived FROM this link: close the cross-region flow
        arrow the origin region opened for these bytes."""
        tracer = self.replicator._tracer()
        if tracer is None:
            return
        ctx = obs_dist.mint_for_update(update, salt=b"geo")
        if ctx.sampled:
            tracer.flow_end(
                "ytpu.geo", obs_dist.flow_id_for((ctx.trace_hex, "wan")),
                guid=guid, link=self.region,
            )

    # -- local update intake --------------------------------------------------

    def mark_dirty(self, guid: str, tick: int) -> None:
        if guid in self._dirty:
            # absorbed into the doc's pending delta: the coalesce path
            self.replicator.metrics.coalesced.inc()
            return
        self._dirty[guid] = tick
        self.host.track(guid)

    # -- the clock ------------------------------------------------------------

    def tick(self, now: int) -> None:
        sess = self.session
        sess.tick()
        if sess.state == RECONNECTING:
            self._maybe_reconnect(now)
            return
        self._pump_dirty(now)

    def _maybe_reconnect(self, now: int) -> None:
        if now < self._next_reconnect:
            return
        cfg = self.replicator.config
        self._reconnect_attempts += 1
        base = min(
            cfg.reconnect_cap,
            cfg.reconnect_base * (1 << min(self._reconnect_attempts, 16)),
        )
        jitter = 1.0 + cfg.reconnect_jitter * self._rng.random()
        self._next_reconnect = now + max(1, int(base * jitter))
        transport = None
        try:
            transport = self.connect_fn()
        except Exception:
            transport = None
        if transport is None:
            return
        self.session.attach(transport)
        self._reconnect_attempts = 0
        self.n_reconnects += 1
        self.replicator.metrics.reconnects.labels(
            link=self.region
        ).inc()
        # the partition may have eaten our incremental bookkeeping:
        # fall back to handshake-sv diff targets on the next schedule
        self._sent_sv.clear()

    def _pump_dirty(self, now: int) -> None:
        """The budgeted delta scheduler: oldest-doc-first composite
        batches, capped by the per-tick byte allowance."""
        cfg = self.replicator.config
        per_tick = cfg.budget_per_tick()
        if per_tick:
            self._budget = min(self._budget + per_tick, 4 * per_tick)
        sess = self.session
        if not self._dirty or sess.state != LIVE:
            return
        if sess._pending_delta or self._tick_busy(sess):
            # the session-level coalesced delta (BUSY window, lagging
            # recovery) supersedes scheduling; docs stay dirty
            return
        metrics = self.replicator.metrics
        parts: list[tuple[str, bytes]] = []
        spent = 0
        for guid in list(self._dirty):
            if per_tick and parts and spent >= self._budget:
                # budget exhausted: everything younger waits its turn
                metrics.deferrals.inc()
                self._deferred.update(self._dirty)
                break
            try:
                sv = self._doc_sv(guid)
                target = self._sent_sv.get(guid)
                upd = self.host._doc_diff(
                    guid, encode_sv_dict(target) if target else None
                )
            except Exception:
                # the doc vanished mid-schedule (demotion race): the
                # anti-entropy digest re-discovers it if it returns
                self._dirty.pop(guid, None)
                continue
            self._dirty.pop(guid, None)
            if len(upd) <= _EMPTY_UPDATE_LEN:
                continue
            parts.append((guid, upd))
            spent += len(upd)
            if sv is not None:
                self._sent_sv[guid] = sv
        if not parts:
            return
        payload = encode_space_update(parts)
        if per_tick:
            self._budget = max(0, self._budget - len(payload))
        metrics.delta_frames.inc()
        metrics.delta_bytes.inc(len(payload))
        self._account_shipment(payload, parts)
        self._send_payload(payload)

    def _tick_busy(self, sess) -> bool:
        return sess._tick < sess._busy_until

    def _ledger(self):
        """The cost ledger behind the region facade, when one exists
        (a provider facade carries its own; a fleet facade is probed
        through its first shard — per-link totals, not per-shard)."""
        facade = self.replicator.facade
        cost = getattr(facade, "cost", None)
        if cost is not None:
            return cost
        shards = getattr(facade, "shards", None)
        if shards:
            try:
                return getattr(shards[0], "cost", None)
            except Exception:
                return None
        return None

    def _account_shipment(self, payload: bytes,
                          parts: list[tuple[str, bytes]]) -> None:
        """Per-link WAN byte telemetry (ISSUE 19 satellite): every
        payload counts as shipped; parts whose doc the budget deferred
        earlier additionally count as deferred, now that they left."""
        cost = self._ledger()
        self.shipped_bytes += len(payload)
        if cost is not None:
            cost.geo_bytes(self.region, len(payload), kind="shipped")
        late = 0
        for guid, upd in parts:
            if guid in self._deferred:
                self._deferred.discard(guid)
                late += len(upd)
        if late:
            self.deferred_bytes += late
            if cost is not None:
                cost.geo_bytes(self.region, late, kind="deferred")

    def _doc_sv(self, guid: str) -> dict[int, int] | None:
        try:
            return decode_state_vector(self.host._doc_sv_bytes(guid))
        except Exception:
            return None

    def _send_payload(self, payload: bytes) -> None:
        """Ship one composite payload and open the cross-region flow
        arrow (closed by the remote's ``note_remote_apply``)."""
        tracer = self.replicator._tracer()
        ctx = None
        # the arrow is minted per PART (per doc update) so one trace
        # spans origin region -> WAN hop -> remote integrate -> visible
        if tracer is not None:
            for guid, upd in decode_space_update(payload):
                c = obs_dist.mint_for_update(upd, salt=b"geo")
                if c.sampled:
                    tracer.flow_start(
                        "ytpu.geo",
                        obs_dist.flow_id_for((c.trace_hex, "wan")),
                        guid=guid, link=self.region,
                    )
                    if ctx is None:
                        ctx = c
        with obs_dist.use_context(ctx):
            self.session.send_update(payload)

    # -- introspection --------------------------------------------------------

    def lag_bytes(self) -> int:
        return sum(len(e["inner"]) for e in self.session._outbox)

    def lag_ticks(self, now: int) -> int:
        oldest = None
        if self._dirty:
            oldest = next(iter(self._dirty.values()))
        for e in self.session._outbox:
            t = e.get("geo_t")
            if t is not None and (oldest is None or t < oldest):
                oldest = t
        return 0 if oldest is None else max(0, now - oldest)

    def snapshot(self, now: int, det_state: str) -> dict:
        sess = self.session
        return {
            "link": self.region,
            "state": sess.state,
            "detector": det_state,
            "outbox": len(sess._outbox),
            "dirty_docs": len(self._dirty),
            "lag_bytes": self.lag_bytes(),
            "lag_seconds": round(
                self.lag_ticks(now)
                * self.replicator.config.tick_ms / 1000.0, 3,
            ),
            "reconnects": self.n_reconnects,
            "resumes": sess.n_resumes,
            "full_resyncs": sess.n_full_resyncs,
            "dead_letters": self.n_dead_letters,
            "shipped_bytes": self.shipped_bytes,
            "deferred_bytes": self.deferred_bytes,
            "floor": dict(self.floor),
        }


class GeoReplicator:
    """Per-region driver joining one region facade into the geo mesh.

    ``facade`` is anything with the region surface (see
    :class:`SpaceSessionHost`); ``connect_fn`` per peer returns a fresh
    :class:`~yjs_tpu.sync.transport.Transport` toward that region, or
    ``None`` while the WAN is down (the reconnect backoff retries).
    """

    def __init__(self, facade, config: GeoConfig | None = None,
                 metrics: GeoMetrics | None = None,
                 detector_config: FailoverConfig | None = None):
        self.facade = facade
        self.config = config if config is not None else GeoConfig()
        self.metrics = metrics if metrics is not None else GeoMetrics()
        self.region = self.config.region
        self.links: dict[str, GeoLink] = {}
        self.now = 0
        # link-health: the PR 8 detector, keyed by region name.  A link
        # "answers the probe" while its transport is attached; detached
        # (reconnecting) links miss until suspect -> dead, and a
        # successful reattach revives them.
        self.detector = FailureDetector(
            (),
            detector_config
            if detector_config is not None
            else FailoverConfig(seed=self.config.seed),
        )
        # region fencing epoch: resumes from the max journaled link
        # epoch + 1 after a crash (the restart is a new fencing era —
        # remote regions see the bump in statusz and the epoch gauge)
        self._recovered: dict[str, dict] = dict(
            getattr(facade, "_recovered_geo", None) or {}
        )
        self.epoch = (
            max(
                (int(f.get("epoch", 0)) for f in self._recovered.values()),
                default=-1,
            )
            + 1
        )
        self.metrics.epoch.set(self.epoch)
        # upstream (PR 14) routing epoch last folded into the fencing
        # epoch; None until the first tick observes a baseline so
        # startup never fires a spurious region-wide rehome
        self._upstream_seen: int | None = None
        self._bridge_installed = False
        # advertise on the facade so statusz/ytpu_top find the rows
        try:
            facade.geo = self
        except Exception:
            pass

    # -- wiring ----------------------------------------------------------------

    def _tracer(self):
        eng = getattr(self.facade, "engine", None)
        obs = getattr(eng, "obs", None)
        return getattr(obs, "tracer", None)

    def _install_bridge(self) -> None:
        if self._bridge_installed:
            return
        self._bridge_installed = True
        reg = getattr(self.facade, "on_update", None)
        if inspect.ismethod(reg):
            reg(self._on_local_update)
            return
        # attribute-style seam (the cluster supervisor): chain any
        # previously-installed gateway callback
        prev = reg if callable(reg) else None

        def chained(guid, update, _prev=prev):
            if _prev is not None:
                _prev(guid, update)
            self._on_local_update(guid, update)

        try:
            self.facade.on_update = chained
        except Exception:
            pass

    def add_peer(self, region: str, connect_fn,
                 session_config: SessionConfig | None = None) -> GeoLink:
        """Join one remote region: builds the link + session, arms the
        journaled resume floor, and connects if the WAN is up."""
        region = str(region)
        if region in self.links:
            return self.links[region]
        self._install_bridge()
        link = GeoLink(self, region, connect_fn,
                       session_config=session_config)
        hint = self._recovered.get(region)
        if hint is not None:
            link.session.set_resume_hint(hint["sid"], hint["seq"])
            link.floor = {
                "sid": hint["sid"], "seq": hint["seq"],
                "epoch": self.epoch,
            }
        self.links[region] = link
        self.detector.add(region)
        transport = None
        try:
            transport = connect_fn()
        except Exception:
            transport = None
        if transport is not None:
            link.session.connect(transport)
        return link

    def remove_peer(self, region: str) -> None:
        link = self.links.pop(str(region), None)
        if link is not None:
            link.session.close()
        self.detector.remove(str(region))

    # -- local update intake ----------------------------------------------------

    def _on_local_update(self, guid: str, update: bytes) -> None:
        """The facade's flush-emitted update stream: every doc that
        changed (locally-authored or transit traffic from another
        region — the CRDT merge dedups the echo) dirties every link."""
        for link in self.links.values():
            link.mark_dirty(guid, self.now)

    # -- fencing epochs ----------------------------------------------------------

    def notify_epoch(self, epoch: int) -> None:
        """The PR 14 epoch event stream reaches the WAN: an upstream
        routing-epoch bump (failover, shard restart) advances this
        region's FENCING epoch — a separate monotonic counter, since
        routing epochs are local to each region — and makes every live
        link offer a digest immediately, so cross-region divergence
        from the local handoff window heals now instead of an
        anti-entropy interval later.  Facades with an ``epoch`` surface
        (cluster supervisor, fleet routing table) are also polled each
        :meth:`tick`; this push entry point exists for event-driven
        callers (``Supervisor.on_epoch``)."""
        epoch = int(epoch)
        if self._upstream_seen is not None and epoch <= self._upstream_seen:
            return
        self._upstream_seen = epoch
        self._advance_epoch()

    def _advance_epoch(self) -> None:
        self.epoch += 1
        self.metrics.epoch.set(self.epoch)
        flight_recorder().record(
            "geo", "epoch_advanced", region=self.region, epoch=self.epoch,
        )
        for link in self.links.values():
            link.floor["epoch"] = self.epoch
            link.session.rehome(self.epoch)
            self._journal_floor(link.region, link.floor)

    def _upstream_epoch(self) -> int | None:
        ep = getattr(self.facade, "epoch", None)  # cluster supervisor
        if isinstance(ep, int):
            return ep
        table = getattr(self.facade, "table", None)  # fleet router
        ep = getattr(table, "epoch", None)
        return ep if isinstance(ep, int) else None

    # -- durability ---------------------------------------------------------------

    def _journal_floor(self, region: str, floor: dict) -> None:
        fn = getattr(self.facade, "journal_geo_link", None)
        if fn is not None:
            fn(region, floor["sid"], floor["seq"], floor["epoch"])

    def link_floors(self) -> dict[str, dict]:
        """Live floors for checkpoint re-journaling (see
        ``TpuProvider._journal_geo_floors``)."""
        return {
            r: dict(link.floor)
            for r, link in self.links.items()
            if link.floor.get("sid")
        }

    # -- the clock ----------------------------------------------------------------

    def tick(self) -> None:
        """One unit of geo time: session clocks, reconnect backoff, the
        delta scheduler, link-health probes, and gauge refresh."""
        self.now += 1
        # fold upstream routing-epoch movement into the fencing epoch
        # (event-driven facades also push through notify_epoch; the
        # seen-tracking dedups the two paths)
        up = self._upstream_epoch()
        if up is not None:
            if self._upstream_seen is None:
                self._upstream_seen = up  # baseline, no rehome
            elif up > self._upstream_seen:
                self._upstream_seen = up
                self._advance_epoch()
        for region in sorted(self.links):
            self.links[region].tick(self.now)
        # DEAD links are skipped by the probe round (the detector stops
        # probing the confirmed-dead), so a reattached link must be
        # revived explicitly before the round or it stays dead forever
        for region, link in self.links.items():
            if (
                self._link_attached(link)
                and self.detector.state_of(region) != ALIVE
            ):
                self.detector.revive(region)

        def probe(region):
            link = self.links.get(region)
            return link is not None and self._link_attached(link)

        self.detector.tick(probe)
        self._refresh_gauges()

    @staticmethod
    def _link_attached(link: GeoLink) -> bool:
        sess = link.session
        return (
            sess.transport is not None
            and sess.state != RECONNECTING
            and not sess._closed
        )

    def _refresh_gauges(self) -> None:
        m = self.metrics
        counts = {ALIVE: 0, SUSPECT: 0, DEAD: 0}
        for region, link in self.links.items():
            st = self.detector.state_of(region)
            counts[st] = counts.get(st, 0) + 1
            m.lag_bytes.labels(link=region).set(link.lag_bytes())
            m.lag_seconds.labels(link=region).set(
                link.lag_ticks(self.now) * self.config.tick_ms / 1000.0
            )
        for st, n in counts.items():
            m.links.labels(state=st).set(n)

    # -- introspection -------------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``/statusz`` "geo" row and the ytpu_top feed."""
        return {
            "region": self.region,
            "epoch": self.epoch,
            "tick": self.now,
            "links": [
                self.links[r].snapshot(
                    self.now, self.detector.state_of(r)
                )
                for r in sorted(self.links)
            ],
        }

    def close(self) -> None:
        for link in self.links.values():
            link.session.close()
