"""Mesh + shard_map wrappers for the batch engine.

The reference has no cross-process parallelism — docs are independent, so the
TPU-native scaling story (SURVEY.md §2 parallelism table) is: shard the *doc
batch* axis across the device mesh with ``shard_map``; ICI collectives are
used for global metrics and state-vector gathers, not for integration itself
(no cross-doc communication exists to translate).

Axes:
- ``docs``: the data-parallel axis — every [B, ...] array is sharded on its
  leading dim.
- ``rows`` (optional, 2D mesh): a sequence-parallel-style axis over the item
  table for reduction kernels (state vectors via per-shard segment-max +
  ``pmax``), the long-document analogue of sequence parallelism.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8: VMA checking is on by default; our kernels create
    # unvarying intermediates inside the mapped fn, so disable it
    from jax import shard_map as _shard_map_impl

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_impl(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_impl(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )

from ..obs.prof import profiled
from ..ops import kernels


def doc_mesh(
    n_devices: int | None = None, axis: str = "docs", backend: str | None = None
) -> Mesh:
    """A 1-D mesh over the doc-batch axis.

    ``backend='cpu'`` builds the virtual host mesh (with
    ``--xla_force_host_platform_device_count=N``) even when a real
    accelerator is the default platform — the multi-chip dry-run path.
    """
    devs = jax.devices(backend) if backend else jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices, backend has {len(devs)}"
            )
        devs = devs[:n_devices]
    import numpy as np

    return Mesh(np.array(devs), (axis,))


def shard_meshes(
    n_shards: int,
    axis: str = "docs",
    backend: str | None = None,
    devices_per_shard: int | None = None,
) -> list[Mesh | None]:
    """Partition the device list into per-shard 1-D doc meshes — the
    fleet's device-placement map (ISSUE 6): shard ``k`` of a
    :class:`yjs_tpu.fleet.FleetRouter` runs its engine over mesh ``k``,
    so the fleet spans the whole pod while each shard's collectives stay
    inside its own device group.

    Devices are dealt out contiguously (ICI neighbors stay together on
    real TPU topologies).  When the backend has fewer devices than
    shards, every entry is ``None`` — the fleet then runs unmeshed on
    the default device, which is the correct degraded mode for laptops
    and single-chip hosts.
    """
    if n_shards <= 0:
        raise ValueError(f"n_shards must be positive, got {n_shards}")
    devs = jax.devices(backend) if backend else jax.devices()
    if devices_per_shard is None:
        devices_per_shard = len(devs) // n_shards
    if devices_per_shard < 1 or len(devs) < n_shards * devices_per_shard:
        return [None] * n_shards
    import numpy as np

    return [
        Mesh(
            np.array(devs[k * devices_per_shard : (k + 1) * devices_per_shard]),
            (axis,),
        )
        for k in range(n_shards)
    ]


def sharded_batch_step(mesh: Mesh, axis: str = "docs"):
    """The engine step sharded over the doc axis.

    Returns a jitted fn with the signature of
    :func:`yjs_tpu.ops.kernels.batch_step_levels` plus a replicated metrics
    dict (psum over ICI) so every host sees global progress counters.
    """
    spec = P(axis)

    def local_step(statics, dyn, splits, lv_sched, delete_rows, scratch_base):
        out = jax.vmap(kernels._doc_step_levels)(
            statics, dyn, splits, lv_sched, delete_rows, scratch_base
        )
        integrated = jnp.sum(lv_sched[..., 0] >= 0)
        deleted = jnp.sum(delete_rows >= 0)
        metrics = {
            "integrated": lax.psum(integrated, axis),
            "deleted": lax.psum(deleted, axis),
        }
        return out, metrics

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec, spec),
        out_specs=((spec, spec, spec), P()),
    )
    # donate the persistent dyn buffers like kernels.batch_step does
    return profiled("sharded_batch_step")(
        jax.jit(sharded, donate_argnums=(1,))
    )


def sharded_apply_plan(mesh: Mesh, axis: str, k_dn: int, k_sp: int,
                       k_h: int, k_d: int):
    """The bulk-apply flush sharded over the doc axis: each shard scatters
    its own lanes block into its dyn shard locally (docs are independent —
    no cross-shard communication except the psum'd progress counters).

    lanes: [n_shards, 4*B_local + k_dn + 2*k_sp + 2*k_h + k_d] i32,
    sharded on axis 0; dyn arrays sharded on their doc axis.
    """
    spec = P(axis)

    def local_apply(dyn, lanes):
        lanes1 = lanes[0].astype(jnp.int32)  # int16 lanes widen on device
        b_loc = dyn[0].shape[0]
        out = kernels.apply_lanes(dyn, lanes1, k_dn, k_sp, k_h, k_d)
        integrated = jnp.sum(lanes1[: 2 * b_loc])  # dense + sparse counts
        deleted = jnp.sum(lanes1[3 * b_loc : 4 * b_loc])
        metrics = {
            "integrated": lax.psum(integrated, axis),
            "deleted": lax.psum(deleted, axis),
        }
        return out, metrics

    sharded = shard_map(
        local_apply,
        mesh=mesh,
        in_specs=((spec, spec, spec), spec),
        out_specs=((spec, spec, spec), P()),
    )
    return profiled("sharded_apply_plan")(
        jax.jit(sharded, donate_argnums=(0,))
    )


def sharded_state_vectors(mesh: Mesh, n_slots: int, axis: str = "docs", row_axis: str | None = None):
    """State vectors over a sharded doc batch; with a 2-D mesh the item-table
    axis is also sharded and reduced with pmax over ICI (the segment-max of
    StructStore.getStateVector, reference StructStore.js:49-56)."""

    def local_sv(row_slot, row_end):
        sv = kernels.state_vector_kernel(row_slot, row_end, n_slots)
        if row_axis is not None:
            sv = lax.pmax(sv, row_axis)
        return sv

    if row_axis is None:
        in_spec = P(axis)
        out_spec = P(axis)
    else:
        in_spec = P(axis, row_axis)
        out_spec = P(axis)
    return profiled("sharded_state_vectors")(
        jax.jit(
            shard_map(
                local_sv,
                mesh=mesh,
                in_specs=(in_spec, in_spec),
                out_specs=out_spec,
            )
        )
    )
