"""Device-mesh parallelism: shard the doc batch across TPU cores."""

from .mesh import (  # noqa: F401
    doc_mesh,
    shard_meshes,
    sharded_batch_step,
    sharded_state_vectors,
)
