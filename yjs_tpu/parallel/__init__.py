"""Device-mesh parallelism: shard the doc batch across TPU cores."""

from .mesh import doc_mesh, sharded_batch_step, sharded_state_vectors  # noqa: F401
