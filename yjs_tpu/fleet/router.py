"""FleetRouter: N TpuProvider shards behind one provider facade (ISSUE 6).

One provider caps the deployment at single-device slot capacity
(``ProviderFullError``).  The fleet is the architectural unlock the
ROADMAP names: docs are placed onto shards by the bounded-load
consistent-hash ring (:mod:`yjs_tpu.fleet.hashring`), each shard runs
its own :class:`~yjs_tpu.provider.TpuProvider` — optionally on its own
device mesh from :func:`yjs_tpu.parallel.shard_meshes` — and the router
speaks the same surface a single provider does (``receive_update`` /
``handle_sync_message`` / ``session`` / ``text`` / ``checkpoint``), so
callers scale out by swapping the constructor.

**Live migration** rides the seams earlier PRs built, in an order that
makes a crash at ANY point recoverable to exactly one owner:

1. the source journals a ``KIND_MIGRATE`` intent (crash here: the
   destination never saw the doc → recovery aborts, source keeps it);
2. the source's full state is exported and applied to the destination,
   which journals it as ordinary updates (crash here: both WALs hold the
   doc + a pending intent → recovery completes the handoff, transferring
   the source's final state so no tail update is lost);
3. the *double-delivery window* opens: in-flight updates and session
   frames are delivered to BOTH shards — the CRDT's idempotent,
   commutative merge dedupes, so nothing is dropped or reordered;
4. ``release_doc()`` on the source journals the release (the durable
   "handoff complete" marker), its final export is re-applied to the
   destination, the routing table bumps its epoch, and live sessions
   ``rehome()`` — an immediate anti-entropy digest repairs anything that
   raced the window.

The :class:`~yjs_tpu.fleet.rebalance.Rebalancer` ticks on shard
occupancy to migrate docs off shards approaching full and to drain a
shard for removal.  Knobs: ``YTPU_FLEET_VNODES``,
``YTPU_FLEET_LOAD_FACTOR``, ``YTPU_FLEET_REBALANCE_HIGH``,
``YTPU_FLEET_REBALANCE_TARGET``, ``YTPU_FLEET_REBALANCE_BATCH``.
"""

from __future__ import annotations

import os
import time
from pathlib import Path

from ..admission import AdmissionController
from ..obs import dist as obs_dist
from ..obs import global_registry
from ..obs.admin import maybe_start_admin
from ..obs.blackbox import flight_recorder
from ..obs.expo import prometheus_text, registry_snapshot
from ..obs.federate import FederationMetrics, federate_snapshots
from ..provider import ProviderFullError, TpuProvider
from ..sync.session import (
    SessionConfig,
    SessionMetrics,
    SyncSession,
    encode_busy,
)
from .hashring import (
    FleetFullError,
    HashRing,
    RoutingTable,
    _env_float,
    _env_int,
)
from .failover import (
    DeadShard,
    FailoverCoordinator,
    FailoverMetrics,
    FailureDetector,
    ShardDownError,
)
from .rebalance import Rebalancer
from .replication import ReplicationManager

__all__ = ["FleetConfig", "FleetMetrics", "FleetRouter", "FleetFullError"]


class FleetConfig:
    """Resolved fleet knobs (constructor args beat ``YTPU_FLEET_*`` env
    beats defaults, same precedence as SessionConfig/WalConfig)."""

    __slots__ = (
        "vnodes", "load_factor", "rebalance_high", "rebalance_target",
        "rebalance_batch",
    )

    def __init__(
        self,
        vnodes: int | None = None,
        load_factor: float | None = None,
        rebalance_high: float | None = None,
        rebalance_target: float | None = None,
        rebalance_batch: int | None = None,
    ):
        def pick(v, env, default, conv):
            return v if v is not None else conv(env, default)

        self.vnodes = pick(vnodes, "YTPU_FLEET_VNODES", 64, _env_int)
        self.load_factor = pick(
            load_factor, "YTPU_FLEET_LOAD_FACTOR", 1.25, _env_float
        )
        self.rebalance_high = pick(
            rebalance_high, "YTPU_FLEET_REBALANCE_HIGH", 0.85, _env_float
        )
        self.rebalance_target = pick(
            rebalance_target, "YTPU_FLEET_REBALANCE_TARGET", 0.6, _env_float
        )
        self.rebalance_batch = pick(
            rebalance_batch, "YTPU_FLEET_REBALANCE_BATCH", 4, _env_int
        )


class FleetMetrics:
    """The ``ytpu_fleet_*`` instrument bundle.

    Registered on the process-global registry by default: provider
    exposition already merges the global registry, so every shard's
    ``metrics_text()`` carries the fleet families without extra wiring
    (and re-registration is a cheap name-dedup no-op)."""

    def __init__(self, registry=None):
        r = registry if registry is not None else global_registry()
        self.registry = r
        self.shards = r.gauge(
            "ytpu_fleet_shards",
            "Live (non-retired) shards in the fleet",
        )
        self.docs = r.gauge(
            "ytpu_fleet_docs",
            "Docs currently admitted across all shards",
        )
        self.shard_docs = r.gauge(
            "ytpu_fleet_shard_docs",
            "Docs admitted on one shard",
            labelnames=("shard",),
        )
        self.shard_occupancy = r.gauge(
            "ytpu_fleet_shard_occupancy",
            "Admitted docs / slot capacity of one shard (1.0 = next "
            "admission raises ProviderFullError)",
            labelnames=("shard",),
        )
        self.epoch = r.gauge(
            "ytpu_fleet_routing_epoch",
            "Routing-table version; bumps on every ownership or "
            "membership change",
        )
        self.placements = r.counter(
            "ytpu_fleet_placements_total",
            "First-touch doc placements, by kind (ring = natural owner, "
            "shed = bounded-load diverted off a hot shard)",
            labelnames=("kind",),
        )
        self.migrations = r.counter(
            "ytpu_fleet_migrations_total",
            "Completed doc migrations, by reason (manual / rebalance / "
            "drain / recovery-complete / recovery-abort / "
            "recovery-dedupe)",
            labelnames=("reason",),
        )
        self.migration_seconds = r.histogram(
            "ytpu_fleet_migration_seconds",
            "Wall time of one live doc migration (intent + export + "
            "apply + release)",
            unit="s",
        )
        self.double_delivered = r.counter(
            "ytpu_fleet_double_delivered_total",
            "Updates/frames delivered to both shards inside a "
            "migration's double-delivery window (deduped by CRDT "
            "idempotence)",
        )
        self.rebalance = r.counter(
            "ytpu_fleet_rebalance_decisions_total",
            "Rebalancer tick decisions, by action (move / stuck)",
            labelnames=("action",),
        )


class _FleetSessionHost:
    """Session host that resolves the OWNING shard per call, so a live
    :class:`SyncSession` rides a migration without reconnecting: the
    facade re-points, the seq spaces survive, and frames inside the
    double-delivery window reach both shards."""

    __slots__ = ("fleet", "guid", "peer")

    def __init__(self, fleet: "FleetRouter", guid: str, peer: str):
        self.fleet = fleet
        self.guid = guid
        self.peer = peer

    def _prov(self) -> TpuProvider:
        return self.fleet.provider_for(self.guid)

    def state_vector(self) -> bytes:
        p = self._prov()
        p.flush()
        return p.engine.encode_state_vector(p.doc_id(self.guid))

    def diff_update(self, sv: bytes | None) -> bytes:
        return self._prov().encode_state_as_update(self.guid, sv)

    def apply_update(self, update: bytes) -> None:
        self.fleet.receive_update(self.guid, update)

    def handle_frame(self, frame: bytes) -> bytes | None:
        fleet = self.fleet
        try:
            return fleet._handle_frame_routed(self.guid, frame)
        except (ProviderFullError, FleetFullError) as e:
            # Capacity exhaustion must not escape into the transport
            # pump: feed the admission controller (brownout signal +
            # tiering headroom), keep the bytes as replicated typed
            # dead-letter evidence, push back on the peer with BUSY.
            kind = "fleet" if isinstance(e, FleetFullError) else "provider"
            fleet.admission.note_full(kind)
            full_reason = f"admission-full: {e} (peer {self.peer})"
            fleet.repl.enqueue_dlq(
                self.guid, bytes(frame), False, full_reason
            )
            own = fleet.owner_of(self.guid)
            if own is not None and not fleet._is_stub(own):
                try:
                    fleet.shards[own].engine._dead_letter(
                        -1, bytes(frame), False, full_reason
                    )
                except ShardDownError:
                    fleet.detector.report_down(own)
            return encode_busy(fleet.admission.retry_after)

    def dead_letter(self, payload: bytes, reason: str) -> None:
        full_reason = f"{reason} (peer {self.peer})"
        try:
            p = self._prov()
            try:
                doc = p.doc_id(self.guid)
            except ProviderFullError:
                self.fleet.admission.note_full("provider")
                doc = -1
            p.engine._dead_letter(
                doc, bytes(payload), False, full_reason,
            )
        except FleetFullError:
            self.fleet.admission.note_full("fleet")
        except ShardDownError:
            own = self.fleet.owner_of(self.guid)
            if own is not None:
                self.fleet.detector.report_down(own)
        # quarantined evidence is replicated: it must survive the shard
        # that quarantined it
        self.fleet.repl.enqueue_dlq(
            self.guid, bytes(payload), False, full_reason
        )

    def journal_ack(self, sid: int, seq: int) -> None:
        try:
            self._prov().journal_session_ack(
                self.guid, self.peer, sid, seq
            )
        except ShardDownError:
            own = self.fleet.owner_of(self.guid)
            if own is not None:
                self.fleet.detector.report_down(own)
        # receive floors fan out too — a promoted replica's WAL must
        # let surviving peers resume, not resync
        self.fleet.repl.enqueue_ack(self.guid, self.peer, sid, seq)


class FleetRouter:
    """Doc-sharded provider fleet behind a single provider facade."""

    def __init__(
        self,
        n_shards: int | None = None,
        docs_per_shard: int | None = None,
        root_name: str = "text",
        gc: bool = False,
        backend: str = "auto",
        wal_dir=None,
        wal_config=None,
        meshes=None,
        config: FleetConfig | None = None,
        registry=None,
        providers: list[TpuProvider] | None = None,
        tier_config=None,
        repl_config=None,
        failover_config=None,
        admission_config=None,
    ):
        self.config = config if config is not None else FleetConfig()
        # ONE admission controller shared by every shard: per-tenant
        # buckets and the brownout level are fleet-wide, and the fleet
        # tick drives the clock (claim_ticker below)
        self.admission = AdmissionController(
            admission_config, registry=registry
        )
        self._root_name = root_name
        self._gc = gc
        self._backend = backend
        self._wal_config = wal_config
        self._tier_config = tier_config
        if wal_dir is None:
            wal_dir = os.environ.get("YTPU_WAL_DIR")
        self.wal_root = Path(wal_dir) if wal_dir else None

        if providers is not None:
            if n_shards is not None and n_shards != len(providers):
                raise ValueError("n_shards conflicts with providers list")
            self.shards = list(providers)
            self._docs_per_shard = docs_per_shard or max(
                (p.engine.n_docs for p in self.shards), default=1
            )
        else:
            if n_shards is None or n_shards < 1:
                raise ValueError(f"need n_shards >= 1, got {n_shards}")
            if docs_per_shard is None or docs_per_shard < 1:
                raise ValueError(
                    f"need docs_per_shard >= 1, got {docs_per_shard}"
                )
            self._docs_per_shard = docs_per_shard
            self.shards = [
                TpuProvider(
                    docs_per_shard,
                    root_name=root_name,
                    mesh=meshes[k] if meshes else None,
                    gc=gc,
                    backend=backend,
                    # "" (not None) when fleet-level journaling is off:
                    # None would make every shard fall back to
                    # YTPU_WAL_DIR and share one directory
                    wal_dir=self._shard_wal_dir(k),
                    wal_config=wal_config,
                    tier_config=tier_config,
                    admission=self.admission,
                )
                for k in range(n_shards)
            ]

        self.ring = HashRing(
            range(len(self.shards)), vnodes=self.config.vnodes
        )
        self.table = RoutingTable()
        self.metrics = FleetMetrics(registry)
        self._session_metrics = SessionMetrics(self.metrics.registry)
        self._sessions: dict[tuple[str, str], SyncSession] = {}
        self._update_listeners: list = []
        # guid -> {"src", "dst", "reason", "t0"} while a migration's
        # double-delivery window is open
        self._migrating: dict[str, dict] = {}
        # shards drained out of placement (still indexable: shard ids
        # are positional and must stay stable)
        self._retired: set[int] = set()
        # per-shard migration traffic for the ytpu_top fleet table
        self._mig_in: dict[int, int] = {}
        self._mig_out: dict[int, int] = {}
        # stats of the replay that built this fleet (recover())
        self.last_recovery: dict | None = None
        # shards whose machine is gone (failed over, fenced out of the
        # ring); distinct from _retired, which is a graceful drain
        self._down: set[int] = set()
        # killed providers kept for revival (the chaos/fencing path)
        self._corpses: dict[int, TpuProvider] = {}
        for k, prov in enumerate(self.shards):
            prov.shard_id = k
            self._attach_bridge(k, prov)
            # externally-built providers (recover(), tests) arrive with
            # private controllers: rebind them onto the shared one
            if prov.admission is not self.admission:
                prov.admission.detach(prov)
                prov.admission = self.admission
            self.admission.attach(prov)
        self.admission.claim_ticker(self)
        # cross-shard metrics federation (ISSUE 11): ytpu_fed_* families
        # register at construction so the schema checker sees them
        self.fed_metrics = FederationMetrics(self.metrics.registry)
        self.failover_metrics = FailoverMetrics(self.metrics.registry)
        self.detector = FailureDetector(
            range(len(self.shards)),
            config=failover_config,
            metrics=self.failover_metrics,
        )
        self.repl = ReplicationManager(self, config=repl_config)
        self.failover = FailoverCoordinator(
            self, metrics=self.failover_metrics
        )
        self.rebalancer = Rebalancer(self)
        # admin plane (ISSUE 16): ONE endpoint for the whole fleet —
        # shard providers that auto-started their own (YTPU_ADMIN_PORT
        # set) hand the plane over to the router's federated view
        for prov in self.shards:
            if getattr(prov, "admin", None) is not None:
                prov.admin.close()
                prov.admin = None
        self.admin = maybe_start_admin(self, "fleet")
        self._refresh_gauges()

    # -- construction helpers ------------------------------------------------

    def _shard_wal_dir(self, k: int) -> str:
        return str(self.wal_root / f"shard-{k:03d}") if self.wal_root else ""

    def _is_stub(self, k: int) -> bool:
        return isinstance(self.shards[k], DeadShard)

    def _unhealthy(self) -> set[int]:
        """Shards no placement, replication, or migration may target:
        gracefully retired, confirmed down, or currently suspect."""
        return (
            self._retired | self._down | set(self.detector.suspects())
        )

    def shard_healthy(self, k: int) -> bool:
        """True when the shard is a valid migration/placement
        destination (the rebalancer's gate, satellite of ISSUE 8)."""
        return k not in self._unhealthy() and not self._is_stub(k)

    def _attach_bridge(self, k: int, prov: TpuProvider) -> None:
        """Fan this shard's flush-emitted updates out to fleet sessions
        and listeners.  Inside a doc's double-delivery window the
        DESTINATION's emissions are suppressed: the source is still the
        owner of record, and forwarding both would send every peer each
        delta twice (harmless to the CRDT, wasteful on the wire)."""

        def bridge(guid, update, _k=k):
            mig = self._migrating.get(guid)
            if mig is not None and mig["dst"] == _k:
                return
            if mig is None:
                own = self.table.lookup(guid)
                if own is not None and own != _k:
                    # fencing at the wire: a shard that lost ownership
                    # (failover promoted a replica while it was gone)
                    # keeps its engine state but its emissions go
                    # nowhere — exactly-one-owner seen by every peer
                    return
            for (g, _peer), sess in list(self._sessions.items()):
                if g == guid:
                    sess.send_update(update)
            for cb in self._update_listeners:
                cb(guid, update)

        prov.on_update(bridge)

    # -- routing -------------------------------------------------------------

    def shard_of(self, guid: str) -> int:
        """The owning shard id, placing the doc on first touch."""
        mig = self._migrating.get(guid)
        if mig is not None:
            return mig["src"]
        s = self.table.lookup(guid)
        if s is not None:
            return s
        return self._place(guid)

    def owner_of(self, guid: str) -> int | None:
        """Current owner per the routing table; None if never placed.
        No placement side effect (assertions and dashboards)."""
        mig = self._migrating.get(guid)
        if mig is not None:
            return mig["src"]
        return self.table.lookup(guid)

    def provider_for(self, guid: str) -> TpuProvider:
        return self.shards[self.shard_of(guid)]

    def _load(self, s: int) -> int:
        # resident (hot+warm+cold), not slot occupancy: a tiered shard
        # is "loaded" by what it owns, not by what fits on device
        if self._is_stub(s):
            return 0
        return self.shards[s].resident_docs

    def _capacity(self, s: int) -> int:
        if self._is_stub(s):
            # a dead shard the detector hasn't convicted yet: zero
            # capacity keeps bounded-load placement off it without
            # letting the corpse raise mid-scoring
            return 0
        p = self.shards[s]
        n = p.engine.n_docs
        if p.tiers.enabled:
            return n * p.tiers.config.overcommit
        return n

    def _place(self, guid: str) -> int:
        try:
            s, shed = self.ring.place(
                guid,
                self._load,
                self._capacity,
                self.config.load_factor,
                exclude=self._unhealthy(),
            )
        except FleetFullError:
            self.metrics.placements.labels(kind="full").inc()
            raise
        self.table.assign(guid, s)
        self.metrics.placements.labels(
            kind="shed" if shed else "ring"
        ).inc()
        return s

    # -- provider facade -----------------------------------------------------

    @property
    def n_shards(self) -> int:
        return len(self.shards)

    @property
    def live_shards(self) -> list[int]:
        return [
            k for k in range(len(self.shards))
            if k not in self._retired and k not in self._down
        ]

    @property
    def doc_count(self) -> int:
        # resident across tiers (equals slot count with tiering off)
        return sum(
            p.resident_docs for k, p in enumerate(self.shards)
            if not self._is_stub(k)
        )

    @property
    def capacity(self) -> int:
        return sum(
            self._capacity(k) for k in self.live_shards
        )

    def receive_update(
        self, guid: str, update: bytes, v2: bool = False,
        undoable: bool = False, internal: bool = False,
    ) -> bool:
        """Queue one room update on its owning shard.  Inside a
        migration window the update is double-delivered (source AND
        destination journal + integrate it); the CRDT merge is
        idempotent, so the duplicate is free and the handoff can never
        drop an in-flight edit.  ``internal`` marks fleet-generated
        traffic (migration/failover/recovery state transfers) that must
        bypass admission control — it was already admitted once."""
        mig = self._migrating.get(guid)
        k = self.shard_of(guid)
        # the fleet seam is an ingress: adopt (or mint) the update's
        # trace context HERE so the replication fan-out and migration
        # double-delivery below run under the same causal identity the
        # owning shard stamps on its spans
        ctx = obs_dist.current_context()
        if ctx is None:
            ctx = obs_dist.mint_for_update(bytes(update))
        with obs_dist.use_context(ctx):
            try:
                accepted = self.shards[k].receive_update(
                    guid, update, v2=v2, undoable=undoable,
                    internal=internal,
                )
            except ShardDownError:
                # the primary's machine is gone but the detector hasn't
                # convicted it yet: the update is accepted ONLY if it
                # can be journaled synchronously on a replica — an ack
                # we hand out must never depend on the corpse alone
                self.detector.report_down(k)
                if not self.repl.absorb(guid, update, v2=v2):
                    raise
                accepted = True
            else:
                if accepted:
                    self.repl.enqueue_update(guid, update, v2=v2)
            if mig is not None:
                try:
                    # the primary already admitted this update;
                    # re-gating the duplicate would double-charge the
                    # tenant's bucket
                    self.shards[mig["dst"]].receive_update(
                        guid, update, v2=v2, internal=True
                    )
                    self.metrics.double_delivered.inc()
                except ShardDownError:
                    self.detector.report_down(mig["dst"])
        return accepted

    def _handle_frame_routed(self, guid: str, frame: bytes):
        mig = self._migrating.get(guid)
        k = self.shard_of(guid)
        try:
            reply = self.shards[k].handle_sync_message(guid, frame)
        except ShardDownError:
            # drop the frame: the session layer's ack/retransmit and
            # the post-failover rehome digest repair anything lost in
            # the unavailability window
            self.detector.report_down(k)
            reply = None
        if mig is not None:
            # the destination sees the same frame (updates journal on
            # its WAL; read frames produce a reply we discard)
            try:
                self.shards[mig["dst"]].handle_sync_message(guid, frame)
                self.metrics.double_delivered.inc()
            except ShardDownError:
                self.detector.report_down(mig["dst"])
        return reply

    def handle_sync_message(self, guid: str, message: bytes):
        return self._handle_frame_routed(guid, message)

    def sync_step1(self, guid: str) -> bytes:
        return self.provider_for(guid).sync_step1(guid)

    def text(self, guid: str) -> str:
        return self.provider_for(guid).text(guid)

    def state_vector(self, guid: str) -> dict[int, int]:
        return self.provider_for(guid).state_vector(guid)

    def encode_state_as_update(
        self, guid: str, target_sv: bytes | None = None
    ) -> bytes:
        return self.provider_for(guid).encode_state_as_update(
            guid, target_sv
        )

    def flush(self) -> None:
        for k in self.live_shards:
            if not self._is_stub(k):
                self.shards[k].flush()

    def flush_tick(self, now: float | None = None) -> bool:
        """Adaptive flush tick fan-out (ISSUE 12): each live shard's
        provider applies its own batch window, so a shard under brownout
        coalesces while a burning shard flushes every tick.  Returns
        True if any shard flushed."""
        flushed = False
        for k in self.live_shards:
            if not self._is_stub(k):
                flushed = self.shards[k].flush_tick(now) or flushed
        return flushed

    def health(self) -> dict:
        return {
            "shards": [
                {"shard": k, "state": "down"} if self._is_stub(k)
                else p.health()
                for k, p in enumerate(self.shards)
            ],
            "fleet": self.fleet_snapshot(),
        }

    def dead_letters(self, guid: str | None = None) -> list[dict]:
        if guid is not None:
            return self.provider_for(guid).dead_letters(guid)
        out = []
        for k, p in enumerate(self.shards):
            if not self._is_stub(k):
                out.extend(p.dead_letters())
        return out

    def checkpoint(self) -> list[dict | None]:
        """Checkpoint every shard's WAL, then re-journal any still-open
        migration intents (compaction drops the segments they lived in;
        a crash after the checkpoint must still see the window), and
        reseed replica copies the same way — compaction folds only
        OWNED docs, so each replica pair gets one fresh full-state
        record from its live owner."""
        out = [
            None if self._is_stub(k) else p.checkpoint()
            for k, p in enumerate(self.shards)
        ]
        for guid, mig in sorted(self._migrating.items()):
            if self._is_stub(mig["src"]):
                continue
            self.shards[mig["src"]].journal_migration(
                guid, mig["dst"], self.table.epoch
            )
        self.repl.rejournal_after_checkpoint()
        return out

    def close(self, checkpoint: bool = True) -> None:
        if getattr(self, "admin", None) is not None:
            self.admin.close()
            self.admin = None
        for k, p in enumerate(self.shards):
            if not self._is_stub(k):
                p.close(checkpoint=checkpoint)

    # -- sessions ------------------------------------------------------------

    def session(
        self, guid: str, peer: str = "peer",
        config: SessionConfig | None = None,
    ) -> SyncSession:
        """Get-or-create the fleet-level peer session for (room, peer).
        Same contract as ``TpuProvider.session`` — admission atomic
        with registration — but the host re-resolves the owning shard
        per call, so the session survives live migration."""
        key = (guid, str(peer))
        sess = self._sessions.get(key)
        if sess is not None:
            if not sess._closed:
                return sess
            del self._sessions[key]
        # place + admit first: a veto must leave no registry entry
        prov = self.provider_for(guid)
        prov.doc_id(guid)
        host = _FleetSessionHost(self, guid, str(peer))
        sess = SyncSession(
            host, config=config, metrics=self._session_metrics,
            peer=str(peer),
        )
        # arm the journaled receive floor, same as TpuProvider.session:
        # a recovered/promoted owner's WAL knows how far this peer got,
        # so the reconnect handshake RESUMES instead of full-resyncing
        hint = prov._recovered_acks.get(key)
        if hint is not None:
            sess.set_resume_hint(*hint)
        sess.policy = self.admission
        sess.routing_epoch = self.table.epoch
        self._sessions[key] = sess
        return sess

    def close_session(self, guid: str, peer: str) -> None:
        sess = self._sessions.pop((guid, str(peer)), None)
        if sess is not None:
            sess.close()
        self._session_metrics.set_state_gauges(self._sessions.values())

    def tick_sessions(self) -> None:
        for (guid, _peer), sess in list(self._sessions.items()):
            try:
                sess.tick()
            except ShardDownError:
                # the session's home shard died inside the conviction
                # window: skip this tick (ack/retransmit repairs once
                # failover rehomes the session) and feed the detector
                # so conviction isn't gated on the next probe
                k = self.table.lookup(guid)
                if k is not None:
                    self.detector.report_down(k)
        self._session_metrics.set_state_gauges(self._sessions.values())

    def sessions_snapshot(self) -> list[dict]:
        rows = []
        for (guid, _peer), sess in sorted(self._sessions.items()):
            row = sess.snapshot()
            row["guid"] = guid
            row["shard"] = self.owner_of(guid)
            rows.append(row)
        self._session_metrics.set_state_gauges(self._sessions.values())
        return rows

    def on_update(self, callback) -> None:
        """Register ``callback(guid, update_bytes)`` across the whole
        fleet (the per-shard bridges fan into it)."""
        self._update_listeners.append(callback)

    # -- live migration ------------------------------------------------------

    def begin_migration(
        self, guid: str, dst: int, reason: str = "manual"
    ) -> None:
        """Open the double-delivery window: journal the intent on the
        source, seed the destination with the source's full state.
        From here until :meth:`complete_migration`, updates and session
        frames for the doc reach BOTH shards."""
        if guid in self._migrating:
            raise RuntimeError(f"{guid!r} is already migrating")
        src = self.shard_of(guid)
        if dst == src:
            raise ValueError(f"{guid!r} already lives on shard {dst}")
        if (
            not (0 <= dst < len(self.shards))
            or not self.shard_healthy(dst)
        ):
            raise ValueError(f"shard {dst} is not a live destination")
        src_p, dst_p = self.shards[src], self.shards[dst]
        src_p.doc_id(guid)  # KeyError-grade misuse surfaces as admission
        t0 = time.perf_counter()
        # intent FIRST: recovery treats "intent without release" as the
        # open window and resolves by whether dst journaled the doc.  If
        # the seed transfer below vetoes (destination full), the stale
        # intent is harmless — dst never admitted the doc, so recovery
        # aborts to the source.
        src_p.journal_migration(guid, dst, self.table.epoch)
        src_p.flush()
        state = src_p.encode_state_as_update(guid)
        dst_p.receive_update(guid, state, internal=True)
        self._migrating[guid] = {
            "src": src, "dst": dst, "reason": reason, "t0": t0,
        }
        flight_recorder().record(
            "fleet", "migration_begin", guid=guid, shard=src,
            dst=dst, reason=reason, epoch=self.table.epoch,
        )

    def complete_migration(self, guid: str) -> None:
        """Close the window: release on the source (journals the
        durable handoff marker + frees the slot), re-apply the final
        export to the destination (idempotent), bump the routing epoch,
        re-home live sessions."""
        mig = self._migrating.get(guid)
        if mig is None:
            raise RuntimeError(f"{guid!r} is not migrating")
        src, dst = mig["src"], mig["dst"]
        # the doc's heat travels with it — a hot doc must not land on
        # the destination looking like the coldest room there
        self.shards[dst].tiers.adopt_heat(
            guid, self.shards[src].tiers.heat_of(guid)
        )
        final = self.shards[src].release_doc(guid)
        self.shards[dst].receive_update(guid, final, internal=True)
        del self._migrating[guid]
        self.table.assign(guid, dst)
        epoch = self.table.bump()
        # ownership changed: the destination journals a primary role
        # marker under the new epoch (recovery's fencing tiebreaker),
        # sheds any replica-copy bookkeeping it had for the doc, and
        # re-journals the live sessions' receive floors so a crash of
        # the NEW owner still resumes peers instead of resyncing them
        self.shards[dst].journal_repl_role(guid, "primary", epoch)
        self.repl.owner_changed(guid, dst)
        self.repl.rejournal_acks(guid, dst)
        self._mig_out[src] = self._mig_out.get(src, 0) + 1
        self._mig_in[dst] = self._mig_in.get(dst, 0) + 1
        self.metrics.migrations.labels(reason=mig["reason"]).inc()
        self.metrics.migration_seconds.observe(
            time.perf_counter() - mig["t0"]
        )
        self.metrics.epoch.set(epoch)
        flight_recorder().record(
            "fleet", "migration_complete", guid=guid, shard=dst,
            src=src, reason=mig["reason"], epoch=epoch,
        )
        for (g, _peer), sess in sorted(self._sessions.items()):
            if g == guid:
                sess.rehome(epoch)

    def migrate_doc(
        self, guid: str, dst: int, reason: str = "manual"
    ) -> None:
        """One-shot live migration (begin + complete)."""
        self.begin_migration(guid, dst, reason=reason)
        self.complete_migration(guid)

    def drain_shard(self, shard: int) -> int:
        """Migrate every doc off ``shard`` and retire it from placement
        (scale-in / maintenance).  Returns docs moved.  The shard id
        stays valid — ids are positional — but the ring stops proposing
        it and the rebalancer stops reading it."""
        if not (0 <= shard < len(self.shards)):
            raise ValueError(f"unknown shard {shard}")
        if shard in self._retired:
            return 0
        # fail BEFORE retiring anything: a drain that would wedge
        # mid-way (no free slots for the remainder) must not leave the
        # fleet half-mutated
        # suspect/dead shards are not drain destinations (satellite of
        # ISSUE 8): count free capacity on HEALTHY shards only, so the
        # fail-fast math can't promise slots a dying shard won't honor
        free_elsewhere = sum(
            self._capacity(k) - self._load(k)
            for k in self.live_shards
            if k != shard and self.shard_healthy(k)
        )
        need = self.shards[shard].resident_docs
        if need > free_elsewhere:
            raise FleetFullError(
                f"cannot drain shard {shard}: {need} docs to move but "
                f"only {free_elsewhere} free slots elsewhere — "
                "add_shard() first"
            )
        self.ring.remove(shard)
        self._retired.add(shard)
        moved = 0
        # resident_guids, not guids(): demoted (warm/cold) docs must
        # leave a retiring shard too — migration promotes them first
        for guid in self.shards[shard].tiers.resident_guids():
            if guid in self._migrating:
                continue
            dst, _shed = self.ring.place(
                guid, self._load, self._capacity,
                self.config.load_factor, exclude=self._unhealthy(),
            )
            self.migrate_doc(guid, dst, reason="drain")
            moved += 1
        self.table.bump()
        self._refresh_gauges()
        return moved

    def add_shard(self, docs: int | None = None, mesh=None) -> int:
        """Scale out: append a fresh shard, join it to the ring.  Only
        ~1/N of FUTURE placements land on it by consistent hashing; the
        rebalancer migrates existing load over as occupancy demands."""
        k = len(self.shards)
        prov = TpuProvider(
            docs or self._docs_per_shard,
            root_name=self._root_name,
            mesh=mesh,
            gc=self._gc,
            backend=self._backend,
            wal_dir=self._shard_wal_dir(k),
            wal_config=self._wal_config,
            tier_config=self._tier_config,
            admission=self.admission,
        )
        prov.shard_id = k
        self.shards.append(prov)
        self._attach_bridge(k, prov)
        self.ring.add(k)
        self.detector.add(k)
        self.table.bump()
        self._refresh_gauges()
        return k

    # -- ticking + introspection --------------------------------------------

    def tick(self) -> list[dict]:
        """One fleet tick: session time, one failure-detector probe
        round (confirmed deaths fail over immediately), a replication
        drain, then a rebalancer pass.  Returns the rebalance
        decisions."""
        self.tick_sessions()
        self.admission.tick()
        for k, _old, new in self.detector.tick(self._probe):
            if new == "dead":
                self.fail_over(k)
        self.repl.drain()
        decisions = self.rebalancer.tick()
        for k in self.live_shards:
            if not self._is_stub(k):
                self.shards[k].tick_tiering()
        self._refresh_gauges()
        return decisions

    def _probe(self, k: int) -> bool:
        try:
            self.shards[k].heartbeat()
            return True
        except ShardDownError:
            return False

    # -- failure detection + failover ---------------------------------------

    def fail_over(self, shard: int, reason: str = "heartbeat") -> dict:
        """Promote replicas for every doc the shard owns and fence it
        out of routing (called by ``tick()`` on a confirmed death, or
        directly by an operator)."""
        return self.failover.fail_over(shard, reason=reason)

    def kill_shard(self, shard: int) -> None:
        """Chaos: the shard's machine vanishes NOW — no flush, no
        checkpoint, WAL left as a killed process would leave it
        (``abandon``).  Every subsequent call into the shard raises
        :class:`ShardDownError` until the detector convicts it and
        ``tick()`` fails it over."""
        if not (0 <= shard < len(self.shards)):
            raise ValueError(f"unknown shard {shard}")
        if self._is_stub(shard):
            return
        prov = self.shards[shard]
        if prov.wal is not None:
            prov.wal.abandon()
        # its queued admission entries die with it — they were journaled
        # + replicated at enqueue, so failover recovers them
        self.admission.detach(prov)
        self._corpses[shard] = prov
        self.shards[shard] = DeadShard(shard)
        flight_recorder().record(
            "fleet", "shard_killed", severity="warning", shard=shard,
        )

    def revive_shard(self, shard: int) -> dict:
        """Bring a failed-over shard back as an EMPTY primary-less
        member (fresh provider, same WAL directory — the journal
        indices continue).  Fencing: any doc the corpse still held in
        memory that now belongs elsewhere is merge-released into the
        current owner (CRDT-idempotent, so a tail the corpse accepted
        right before death is recovered, never double-applied); a doc
        failover declared LOST (no replica) is re-placed from the
        corpse's copy.  The revived shard never resumes ownership by
        itself — that is the split-brain the fencing epoch exists to
        prevent."""
        corpse = self._corpses.pop(shard, None)
        if corpse is None or not self._is_stub(shard):
            raise ValueError(f"shard {shard} was not killed")
        fresh = TpuProvider(
            self._docs_per_shard,
            root_name=self._root_name,
            gc=self._gc,
            backend=self._backend,
            wal_dir=self._shard_wal_dir(shard),
            wal_config=self._wal_config,
            tier_config=self._tier_config,
            admission=self.admission,
        )
        fresh.shard_id = shard
        self.shards[shard] = fresh
        self._attach_bridge(shard, fresh)
        self._down.discard(shard)
        if shard not in self._retired:
            self.ring.add(shard)
        self.detector.revive(shard)
        fenced: list[str] = []
        readopted: list[str] = []
        for guid in corpse.guids():
            try:
                corpse.flush()
                state = corpse.encode_state_as_update(guid)
            except Exception:
                # the corpse's in-memory copy is unreadable (mid-flush
                # kill); the replicas already carried everything acked
                continue
            own = self.owner_of(guid)
            if own is None:
                # failover declared it lost (no replica existed): the
                # corpse's copy is the only one — re-place it fresh
                self.receive_update(guid, state, internal=True)
                readopted.append(guid)
            elif own != shard:
                self.shards[own].receive_update(guid, state, internal=True)
                self.failover_metrics.fenced.inc()
                fenced.append(guid)
        epoch = self.table.bump()
        self.metrics.epoch.set(epoch)
        self._refresh_gauges()
        flight_recorder().record(
            "fleet", "shard_revived", shard=shard, epoch=epoch,
            fenced=len(fenced), readopted=len(readopted),
        )
        return {
            "shard": shard,
            "epoch": epoch,
            "fenced": sorted(fenced),
            "readopted": sorted(readopted),
        }

    def _refresh_gauges(self) -> None:
        m = self.metrics
        m.shards.set(len(self.live_shards))
        m.docs.set(self.doc_count)
        m.epoch.set(self.table.epoch)
        for k, p in enumerate(self.shards):
            lab = str(k)
            if self._is_stub(k):
                m.shard_docs.labels(shard=lab).set(0)
                m.shard_occupancy.labels(shard=lab).set(0.0)
                continue
            m.shard_docs.labels(shard=lab).set(len(p._guids))
            m.shard_occupancy.labels(shard=lab).set(round(p.occupancy, 6))

    def _shard_role(self, k: int) -> str:
        """One word for the ytpu_top ROLE column: what this shard IS
        to the docs it touches right now."""
        if self._is_stub(k) or k in self._down:
            return "dead"
        if self.detector.state_of(k) == "suspect":
            return "suspect"
        if k in self._retired:
            return "retired"
        if self.table.docs_on(k):
            return "primary"
        if self.repl.copies_on(k):
            return "replica"
        return "idle"

    def fleet_snapshot(self) -> dict:
        """JSON-able fleet state — the ``ytpu_top`` fleet-table feed."""
        self._refresh_gauges()
        rows = []
        migrating_by_shard: dict[int, int] = {}
        for mig in self._migrating.values():
            for s in (mig["src"], mig["dst"]):
                migrating_by_shard[s] = migrating_by_shard.get(s, 0) + 1
        for k, p in enumerate(self.shards):
            dead = self._is_stub(k)
            if dead:
                state = "down"
            elif k in self._down:
                state = "down"
            elif k in self._retired:
                state = "retired"
            else:
                state = "live"
            rows.append({
                "shard": k,
                "docs": 0 if dead else len(p._guids),
                "capacity": 0 if dead else p.engine.n_docs,
                "occupancy": 0.0 if dead else round(p.occupancy, 4),
                "resident": 0 if dead else p.resident_docs,
                "warm": 0 if dead else len(p.tiers.warm),
                "cold": 0 if dead else len(p.tiers.cold),
                "state": state,
                "role": self._shard_role(k),
                "dlq": 0 if dead else len(p.engine.dead_letters),
                "sessions": sum(
                    1 for (g, _pr) in self._sessions
                    if self.owner_of(g) == k
                ),
                "migrating": migrating_by_shard.get(k, 0),
                "mig_in": self._mig_in.get(k, 0),
                "mig_out": self._mig_out.get(k, 0),
                "repl_docs": len(self.repl.copies_on(k)),
                "repl_lag": self.repl.lag(k),
            })
        return {
            "epoch": self.table.epoch,
            "n_shards": len(self.shards),
            "live_shards": len(self.live_shards),
            "docs": self.doc_count,
            "capacity": self.capacity,
            "migrations_active": len(self._migrating),
            "replication": self.repl.snapshot(),
            "admission": self.admission.snapshot(),
            "shards": rows,
        }

    def metrics_snapshot(self) -> dict:
        """FEDERATED fleet snapshot (ISSUE 11): every live shard's
        engine-local registry is merged — counters sum across shards,
        gauges keep per-shard ``shard=<k>,role=<role>`` series plus the
        summed unlabeled aggregate, histograms merge count-weighted —
        and the process-global registry (fleet/replication/failover/
        admission families every shard shares) is layered in ONCE,
        un-summed.  The first live shard still contributes the
        non-registry keys (``slo``, ``tiers``, ``flush`` history), and
        the structured ``fleet`` / ``sessions`` / ``admission`` feeds
        ride along as before."""
        base: dict = {}
        sources = []
        for k, p in enumerate(self.shards):
            if self._is_stub(k):
                continue
            if not base:
                base = p.metrics_snapshot()
            sources.append({
                "label": str(k),
                "role": self._shard_role(k),
                "snapshot": registry_snapshot(p.engine.obs.registry),
            })
        # observe BEFORE scraping the global registry so the federation
        # families in this very snapshot are current
        self.fed_metrics.observe(len(sources))
        snap = dict(base)
        snap.update(federate_snapshots(
            sources, global_snapshot=registry_snapshot(global_registry())
        ))
        snap["fleet"] = self.fleet_snapshot()
        snap["sessions"] = self.sessions_snapshot()
        snap["admission"] = self.admission.snapshot()
        return snap

    # -- admin-plane surface (ISSUE 16) -------------------------------------

    def metrics_text(self) -> str:
        """Prometheus exposition over every live shard's registry plus
        the process-global families — the fleet's ``/metrics`` body."""
        regs = [
            p.engine.obs.registry
            for k, p in enumerate(self.shards)
            if not self._is_stub(k)
        ]
        regs.append(global_registry())
        return prometheus_text(*regs)

    def statusz(self) -> dict:
        """The fleet's ``/statusz`` page: topology epoch, per-shard
        occupancy rows, session table, and admission verdict."""
        fs = self.fleet_snapshot()
        adm = fs["admission"]
        return {
            "role": "fleet",
            "epoch": fs["epoch"],
            "n_shards": fs["n_shards"],
            "live_shards": fs["live_shards"],
            "docs": fs["docs"],
            "capacity": fs["capacity"],
            "migrations_active": fs["migrations_active"],
            "shards": fs["shards"],
            "sessions": self.sessions_snapshot(),
            "admission": {
                "level": adm["level"],
                "level_name": adm["level_name"],
                "queue_depth": adm["queue_depth"],
            },
            # inter-region replication (ISSUE 17): present when a
            # GeoReplicator is attached over this fleet facade
            "geo": (
                None if getattr(self, "geo", None) is None
                else self.geo.snapshot()
            ),
        }

    def readiness(self) -> dict:
        """``/readyz`` for the in-process fleet: at least one live
        shard, no shard mid-recovery, brownout below reject-writes."""
        live = len(self.live_shards)
        recovering = any(
            getattr(p, "recovering", False)
            for k, p in enumerate(self.shards)
            if not self._is_stub(k)
        )
        level = self.admission.brownout.level
        ready = live > 0 and not recovering and level < 3
        return {
            "ready": ready,
            "checks": {
                "live_shards": live,
                "recovery_complete": not recovering,
                "brownout_level": level,
                "accepting_writes": level < 3,
            },
        }

    def recovery_report(self) -> dict:
        """Per-shard recovery outcomes in the SAME structured shape the
        cluster :class:`~yjs_tpu.cluster.supervisor.Supervisor` reports
        (ISSUE 14 satellite): one row per shard with its replay
        outcome, plus the ownership-resolution totals from the last
        :meth:`recover`.  A fleet built fresh reports every shard as
        ``fresh`` with zeroed resolutions — ``ytpu_top --cluster``
        renders both identically."""
        rec = self.last_recovery or {}
        shard_stats = rec.get("shards") or []
        rows = []
        for k, p in enumerate(self.shards):
            stats = (
                shard_stats[k] if k < len(shard_stats) else None
            ) or p.last_recovery or {}
            if self._is_stub(k):
                state = "lost"
            elif k in self._down:
                state = "down"
            else:
                state = "live"
            rows.append({
                "shard": k,
                "state": state,
                "pid": os.getpid(),
                "port": 0,
                "restarts": 0,
                "outcome": "recovered" if stats else "fresh",
                # replayed work: tail records plus checkpoint snapshots
                # (a gracefully-closed shard restores from its snapshot)
                "records_applied": stats.get("records_applied", 0)
                + stats.get("snapshots_applied", 0),
            })
        resolution = dict(rec.get("resolution") or {})
        for kind in ("completed", "aborted", "fenced"):
            resolution.setdefault(kind, 0)
        recovered = sum(1 for r in rows if r["outcome"] == "recovered")
        return {
            "kind": "fleet",
            "epoch": self.table.epoch,
            "shards": rows,
            "events": [],
            "outcomes": {"recovered": recovered, "failover": 0},
            "resolution": resolution,
        }

    # -- recovery ------------------------------------------------------------

    @classmethod
    def recover(
        cls,
        wal_root,
        docs_per_shard: int | None = None,
        root_name: str = "text",
        gc: bool = False,
        backend: str = "auto",
        wal_config=None,
        meshes=None,
        config: FleetConfig | None = None,
        registry=None,
        tier_config=None,
    ) -> "FleetRouter":
        """Rebuild a fleet from a crashed predecessor's WAL root
        (``shard-000/``, ``shard-001/``, ... subdirectories).

        Each shard replays snapshot-then-tail via
        ``TpuProvider.recover``; then ownership is resolved to exactly
        one shard per doc: a pending migration intent whose destination
        journaled the doc is COMPLETED (the source's final state is
        transferred, then released — the crash landed inside the
        double-delivery window, so the destination may be missing the
        source's tail but never the reverse after the transfer); an
        intent whose destination never admitted the doc is ABORTED (the
        source keeps it).  Both resolutions journal durably, so
        re-crashing mid-recovery re-converges to the same owner."""
        root = Path(wal_root)
        by_idx: dict[int, Path] = {}
        for d in root.iterdir():
            if not (d.is_dir() and d.name.startswith("shard-")):
                continue
            try:
                by_idx[int(d.name.split("-", 1)[1])] = d
            except ValueError:
                continue
        if not by_idx:
            raise ValueError(f"no shard-*/ WAL directories under {root}")
        recovered: dict[int, TpuProvider] = {
            k: TpuProvider.recover(
                d,
                n_docs=docs_per_shard,
                root_name=root_name,
                mesh=meshes[k] if meshes else None,
                gc=gc,
                backend=backend,
                wal_config=wal_config,
                tier_config=tier_config,
            )
            for k, d in sorted(by_idx.items())
        }
        # shard ids are positional: a WAL directory lost with its
        # machine leaves a gap, filled by an empty member at the same
        # id (its docs live on as replica copies on surviving shards,
        # promoted by the role resolution below)
        n_docs_fill = docs_per_shard or max(
            (p.engine.n_docs for p in recovered.values()), default=1
        )
        shards = [
            recovered.get(k) or TpuProvider(
                n_docs_fill,
                root_name=root_name,
                mesh=meshes[k] if meshes else None,
                gc=gc,
                backend=backend,
                wal_dir=str(root / f"shard-{k:03d}"),
                wal_config=wal_config,
                tier_config=tier_config,
            )
            for k in range(max(by_idx) + 1)
        ]
        fleet = cls(
            docs_per_shard=docs_per_shard,
            root_name=root_name,
            gc=gc,
            backend=backend,
            wal_dir=str(root),
            wal_config=wal_config,
            config=config,
            registry=registry,
            providers=shards,
            tier_config=tier_config,
        )
        resolved = {"completed": 0, "aborted": 0, "deduped": 0}
        for k, p in enumerate(shards):
            pending = (p.last_recovery or {}).get(
                "migrations_pending"
            ) or {}
            for guid, intent in sorted(pending.items()):
                dst = intent.get("dst", -1)
                dst_ok = 0 <= dst < len(shards) and dst != k
                # tier_of, not has_doc: a recovered doc may have landed
                # warm/cold — it is still owned by that shard
                src_has = p.tiers.tier_of(guid) is not None
                dst_has = (
                    dst_ok
                    and shards[dst].tiers.tier_of(guid) is not None
                )
                if src_has and dst_has:
                    # window was open: destination journaled state, so
                    # complete the handoff — transfer the source's
                    # final export (it may hold a tail the destination
                    # missed), then release
                    final = p.release_doc(guid)
                    shards[dst].receive_update(guid, final, internal=True)
                    fleet.metrics.migrations.labels(
                        reason="recovery-complete"
                    ).inc()
                    resolved["completed"] += 1
                elif src_has:
                    # destination never admitted the doc: abort to src
                    fleet.metrics.migrations.labels(
                        reason="recovery-abort"
                    ).inc()
                    resolved["aborted"] += 1
                # dst-only / neither: the release record already
                # replayed — the migration finished before the crash
        resolved["fenced"] = 0
        resolved["replicas_folded"] = 0
        resolved["replica_promoted"] = 0
        # journaled role markers per shard: guid -> {"role", "epoch"}
        roles = [
            ((p.last_recovery or {}).get("repl_roles") or {})
            for p in shards
        ]
        claims: dict[str, list[tuple[int, str | None, int]]] = {}
        for k, p in enumerate(shards):
            for guid in p.guids():
                info = roles[k].get(guid) or {}
                claims.setdefault(guid, []).append(
                    (k, info.get("role"), int(info.get("epoch", 0)))
                )
        for guid, cs in sorted(claims.items()):
            # fencing-epoch rules: replica-marked holders are never
            # owner candidates while any primary claim survives;
            # conflicting primary claims resolve to the HIGHEST
            # journaled epoch (the latest failover/migration won), ties
            # and unmarked holders (epoch 0) to the lowest shard id
            primaries = sorted(
                ((e, k) for (k, role, e) in cs if role != "replica"),
                key=lambda t: (-t[0], t[1]),
            )
            if primaries:
                owner = primaries[0][1]
            else:
                # only replica copies survived (the primary's WAL
                # directory is gone): promote the freshest-marked one
                owner = sorted(
                    ((e, k) for (k, role, e) in cs),
                    key=lambda t: (-t[0], t[1]),
                )[0][1]
                fleet.failover_metrics.promotions.labels(
                    outcome="recovered"
                ).inc()
                resolved["replica_promoted"] += 1
            fleet.table.assign(guid, owner)
            for (k, role, _e) in cs:
                if k == owner:
                    continue
                # fold the losing copy into the owner, then release it
                # (CRDT-idempotent merge: a tail only the loser held is
                # recovered, shared state dedupes)
                final = shards[k].release_doc(guid)
                shards[owner].receive_update(guid, final, internal=True)
                if role == "replica":
                    reason = "recovery-replica"
                    resolved["replicas_folded"] += 1
                elif primaries and primaries[0][0] > _e:
                    # a primary claim (marked, or an original unmarked
                    # owner at epoch 0) outlived by a higher fencing
                    # epoch: the stale primary is fenced, not deduped
                    reason = "recovery-fenced"
                    resolved["fenced"] += 1
                else:
                    reason = "recovery-dedupe"
                    resolved["deduped"] += 1
                fleet.metrics.migrations.labels(reason=reason).inc()
        fleet.table.bump()
        fleet.repl.repair_all()
        fleet.last_recovery = {
            "shards": [p.last_recovery for p in shards],
            "resolution": resolved,
        }
        fleet._refresh_gauges()
        return fleet
