"""Consistent-hash doc→shard placement with bounded loads (ISSUE 6).

The ring is the classic Karger construction: every shard projects
``vnodes`` virtual points onto a 64-bit keyspace and a doc lands on the
first point clockwise of its own hash.  Two properties make it the
right router for a provider fleet:

- **determinism** — placement is a pure function of (guid, shard set,
  vnodes), so any process that knows the membership computes the same
  answer; no coordination service required;
- **minimal movement** — adding or removing a shard re-homes only the
  docs whose arc changed (~1/N of the fleet), which is exactly the
  churn bill a drain or scale-out should pay.

Plain consistent hashing still tolerates ~O(log N / log log N) skew, and
a skewed shard is not a cosmetic problem here: a full shard raises
``ProviderFullError``.  So placement uses the *bounded-load* variant
(Mirrokni et al., "Consistent Hashing with Bounded Loads"): a shard may
hold at most ``ceil(c · (docs+1) / N)`` docs (``c`` = load factor,
``YTPU_FLEET_LOAD_FACTOR``, default 1.25); a doc whose natural owner is
at the bound walks clockwise to the next shard under it — the hot shard
*sheds*, and placement degrades gracefully toward round-robin as the
fleet fills instead of tipping one shard over.

:class:`RoutingTable` is the *versioned* record of where every admitted
doc actually lives.  The ring proposes, the table remembers: migrations
and bounded-load shedding mean a doc's home can differ from its natural
ring owner, and the ``epoch`` counter (bumped on every membership or
ownership change) is what sessions carry so a peer can tell a stale
route from a current one.
"""

from __future__ import annotations

import bisect
import hashlib
import math
import os


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def stable_hash(key: str) -> int:
    """64-bit stable hash of a string key.

    blake2b, not ``hash()``: placement must agree across processes and
    Python's string hash is salted per-process (PYTHONHASHSEED).
    """
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )


class HashRing:
    """The consistent-hash ring over integer shard ids."""

    def __init__(self, shards=(), vnodes: int | None = None):
        self.vnodes = (
            vnodes
            if vnodes is not None
            else _env_int("YTPU_FLEET_VNODES", 64)
        )
        if self.vnodes < 1:
            raise ValueError(f"vnodes must be positive, got {self.vnodes}")
        self._shards: set[int] = set()
        self._points: list[tuple[int, int]] = []  # sorted (hash, shard)
        self._hashes: list[int] = []  # parallel keys for bisect
        for s in shards:
            self.add(int(s))

    def __contains__(self, shard: int) -> bool:
        return shard in self._shards

    def __len__(self) -> int:
        return len(self._shards)

    @property
    def shards(self) -> list[int]:
        return sorted(self._shards)

    def add(self, shard: int) -> None:
        if shard in self._shards:
            return
        self._shards.add(shard)
        for v in range(self.vnodes):
            h = stable_hash(f"shard-{shard}#{v}")
            bisect.insort(self._points, (h, shard))
        self._hashes = [h for h, _ in self._points]

    def remove(self, shard: int) -> None:
        if shard not in self._shards:
            return
        self._shards.discard(shard)
        self._points = [(h, s) for h, s in self._points if s != shard]
        self._hashes = [h for h, _ in self._points]

    def walk(self, guid: str):
        """Shards in ring order starting at the guid's point, each
        yielded once — the preference list bounded-load placement
        walks."""
        if not self._points:
            return
        i = bisect.bisect_right(self._hashes, stable_hash(guid))
        n = len(self._points)
        seen: set[int] = set()
        for k in range(n):
            s = self._points[(i + k) % n][1]
            if s not in seen:
                seen.add(s)
                yield s

    def owner(self, guid: str) -> int:
        """The natural (unbounded) ring owner."""
        for s in self.walk(guid):
            return s
        raise ValueError("empty ring")

    def place(
        self,
        guid: str,
        load,
        capacity,
        load_factor: float | None = None,
        exclude=(),
    ) -> tuple[int, bool]:
        """Bounded-load placement: ``(shard, shed)``.

        ``load(shard)`` / ``capacity(shard)`` are callables (the fleet
        passes live occupancy; the bench passes plain arrays).  The doc
        goes to the first shard in ring order that is under BOTH its
        hard capacity and the bounded-load ceiling
        ``ceil(c · (total+1) / N)``; ``shed`` is True when that was not
        the natural owner (the hot shard shed).  If every shard is at
        the ceiling the least-loaded shard with a free slot takes it;
        with no free slot anywhere the fleet is genuinely full and
        ``FleetFullError`` is raised.
        """
        c = (
            load_factor
            if load_factor is not None
            else _env_float("YTPU_FLEET_LOAD_FACTOR", 1.25)
        )
        live = [s for s in self._shards if s not in exclude]
        if not live:
            raise FleetFullError("no live shards in the ring")
        total = sum(load(s) for s in live)
        bound = math.ceil(c * (total + 1) / len(live))
        first = None
        for s in self.walk(guid):
            if s in exclude:
                continue
            if first is None:
                first = s
            if load(s) < min(capacity(s), bound):
                return s, (s != first)
        fallback = [s for s in live if load(s) < capacity(s)]
        if not fallback:
            raise FleetFullError(
                f"fleet is full ({total} docs across {len(live)} shards); "
                f"no shard has a free slot for {guid!r}"
            )
        return min(fallback, key=lambda s: (load(s), s)), True


class FleetFullError(ValueError):
    """Every live shard is at hard capacity — the fleet-level analogue
    of :class:`yjs_tpu.provider.ProviderFullError` (both subclass
    ``ValueError``, so a caller's existing full-handling catches
    either).  Defined here, import-light, so the 100k-doc placement
    bench can drive the ring without touching the provider stack."""


class RoutingTable:
    """Versioned doc→shard assignment map.

    ``epoch`` increments on every ownership or membership change; it is
    the number sessions carry (``SyncSession.rehome``) so "which shard
    owns this doc" is always answerable as of a specific version, and a
    crash-recovered fleet can prove its view is newer than a peer's.
    """

    def __init__(self):
        self.epoch = 0
        self.assignments: dict[str, int] = {}

    def lookup(self, guid: str) -> int | None:
        return self.assignments.get(guid)

    def assign(self, guid: str, shard: int, bump: bool = False) -> None:
        self.assignments[guid] = shard
        if bump:
            self.epoch += 1

    def unassign(self, guid: str, bump: bool = False) -> None:
        self.assignments.pop(guid, None)
        if bump:
            self.epoch += 1

    def bump(self) -> int:
        self.epoch += 1
        return self.epoch

    def docs_on(self, shard: int) -> list[str]:
        return sorted(
            g for g, s in self.assignments.items() if s == shard
        )

    def snapshot(self) -> dict:
        per_shard: dict[int, int] = {}
        for s in self.assignments.values():
            per_shard[s] = per_shard.get(s, 0) + 1
        return {
            "epoch": self.epoch,
            "n_docs": len(self.assignments),
            "per_shard": per_shard,
        }
