"""Failure detection and automatic failover for the shard fleet (ISSUE 8).

PRs 3/5/6 made shard crashes *recoverable* — WAL replay, session resume,
single-owner migration — but every path still needed an operator to run
``FleetRouter.recover()`` while the dead shard's docs sat offline.  This
module is the *survivability* half of replication: a tick-deterministic
heartbeat failure detector (suspect → confirmed-dead with jittered,
per-shard thresholds and an injectable clock, the same determinism
discipline as ``SyncSession.tick`` and the resilience health tracker)
and a failover coordinator that promotes the freshest replica under a
monotonic fencing epoch.

Fencing rules (the split-brain contract):

- the :class:`~yjs_tpu.fleet.hashring.RoutingTable` epoch is the fencing
  token — every failover bumps it exactly once, and the promoted shard
  journals a ``KIND_REPL`` primary marker carrying that epoch;
- a revived stale primary is *fenced out*: the routing table no longer
  points at it, the fleet's update bridge suppresses emissions from
  non-owners, and ``FleetRouter.revive_shard`` merge-releases any doc
  the corpse still holds into the current owner (CRDT-idempotent, so a
  late tail the dead shard accepted before the kill is recovered, never
  double-applied);
- post-crash, recovery compares journaled primary-marker epochs — the
  highest epoch wins ownership and lower claims are merged + released
  (``recovery-fenced``), so re-crashing after a failover still converges
  to exactly one owner.

Knobs: ``YTPU_FAILOVER_SUSPECT_TICKS``, ``YTPU_FAILOVER_CONFIRM_TICKS``,
``YTPU_FAILOVER_JITTER_TICKS``, ``YTPU_FAILOVER_SEED``.  Metrics: the
``ytpu_failover_*`` families (README "Replication & failover").
"""

from __future__ import annotations

import random
import time

from ..obs import global_registry
from ..obs import dist as obs_dist
from ..obs.blackbox import flight_recorder
from .hashring import _env_int

__all__ = [
    "DeadShard",
    "FailoverConfig",
    "FailoverCoordinator",
    "FailoverMetrics",
    "FailureDetector",
    "ShardDownError",
]

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"

_STATE_CODE = {ALIVE: 0, SUSPECT: 1, DEAD: 2}


class ShardDownError(RuntimeError):
    """Raised by any call into a shard whose machine is gone (the
    chaos harness installs a :class:`DeadShard` stub).  The router
    treats it as a failure-detector signal and reroutes to replicas."""


class DeadShard:
    """Stub installed by ``FleetRouter.kill_shard``: the machine is
    gone, so EVERY attribute access raises :class:`ShardDownError` —
    exactly the behavior a network peer would observe.  Only the shard
    id survives (it names the corpse in error messages)."""

    def __init__(self, shard_id: int):
        object.__setattr__(self, "shard_id", shard_id)

    def __getattr__(self, name: str):
        raise ShardDownError(
            f"shard {object.__getattribute__(self, 'shard_id')} is down"
        )

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"DeadShard({object.__getattribute__(self, 'shard_id')})"


class FailoverConfig:
    """Resolved failure-detector knobs (constructor args beat
    ``YTPU_FAILOVER_*`` env beats defaults)."""

    __slots__ = ("suspect_ticks", "confirm_ticks", "jitter_ticks", "seed")

    def __init__(
        self,
        suspect_ticks: int | None = None,
        confirm_ticks: int | None = None,
        jitter_ticks: int | None = None,
        seed: int | None = None,
    ):
        def pick(v, env, default):
            return v if v is not None else _env_int(env, default)

        # consecutive missed heartbeats before a shard turns suspect
        self.suspect_ticks = max(
            1, pick(suspect_ticks, "YTPU_FAILOVER_SUSPECT_TICKS", 3)
        )
        # additional misses before suspect is confirmed dead
        self.confirm_ticks = max(
            1, pick(confirm_ticks, "YTPU_FAILOVER_CONFIRM_TICKS", 2)
        )
        # per-shard deterministic jitter added to both thresholds so a
        # correlated blip doesn't stampede every shard into failover on
        # the same tick (seeded, so chaos tests replay exactly)
        self.jitter_ticks = max(
            0, pick(jitter_ticks, "YTPU_FAILOVER_JITTER_TICKS", 1)
        )
        self.seed = pick(seed, "YTPU_FAILOVER_SEED", 0)


class FailoverMetrics:
    """The ``ytpu_failover_*`` instrument bundle (process-global
    registry by default, same dedup contract as FleetMetrics)."""

    def __init__(self, registry=None):
        r = registry if registry is not None else global_registry()
        self.registry = r
        self.heartbeats = r.counter(
            "ytpu_failover_heartbeats_total",
            "Failure-detector heartbeat probes, by outcome (ok / miss)",
            labelnames=("outcome",),
        )
        self.shard_state = r.gauge(
            "ytpu_failover_shard_state",
            "Failure-detector verdict per shard "
            "(0 = alive, 1 = suspect, 2 = dead)",
            labelnames=("shard",),
        )
        self.suspects = r.counter(
            "ytpu_failover_suspects_total",
            "alive->suspect transitions declared by the failure detector",
        )
        self.deaths = r.counter(
            "ytpu_failover_deaths_total",
            "suspect->dead confirmations (each triggers one failover)",
        )
        self.promotions = r.counter(
            "ytpu_failover_promotions_total",
            "Per-doc failover resolutions, by outcome (promoted = a "
            "replica took ownership; lost = no replica held the doc)",
            labelnames=("outcome",),
        )
        self.fenced = r.counter(
            "ytpu_failover_fenced_total",
            "Docs a revived stale primary still held that were fenced "
            "out (merge-released into the current owner)",
        )
        self.seconds = r.histogram(
            "ytpu_failover_seconds",
            "Wall time of one shard failover (promotion + catch-up + "
            "session rehome)",
            unit="s",
        )
        self.unavailable_ticks = r.histogram(
            "ytpu_failover_unavailable_ticks",
            "Detector ticks from a dead shard's first missed heartbeat "
            "to failover completion (the availability gap writes ride "
            "out on replicas)",
        )


class FailureDetector:
    """Tick-deterministic heartbeat failure detector.

    Time is the injectable tick counter — ``tick(probe)`` advances it —
    so every suspect/confirm timeline is replayable.  Per shard, the
    suspect and confirm thresholds carry a deterministic jitter drawn
    from ``seed`` (distinct shards never share an exact timeout).
    Demand-driven evidence (``report_down`` from a failed request) and
    probe evidence share one miss counter, capped at one miss per tick
    so a request storm cannot fast-forward the clock.
    """

    def __init__(self, shards=(), config: FailoverConfig | None = None,
                 metrics: FailoverMetrics | None = None):
        self.config = config if config is not None else FailoverConfig()
        self.metrics = metrics
        self.now = 0
        self._state: dict[int, str] = {}
        self._misses: dict[int, int] = {}
        self._first_miss: dict[int, int] = {}
        self._miss_tick: dict[int, int] = {}
        self._thresholds: dict[int, tuple[int, int]] = {}
        for k in shards:
            self.add(int(k))

    def add(self, shard: int) -> None:
        if shard in self._state:
            return
        cfg = self.config
        rng = random.Random(f"failover:{cfg.seed}:{shard}")
        j1 = rng.randrange(cfg.jitter_ticks + 1)
        j2 = rng.randrange(cfg.jitter_ticks + 1)
        suspect_at = cfg.suspect_ticks + j1
        dead_at = suspect_at + cfg.confirm_ticks + j2
        self._thresholds[shard] = (suspect_at, dead_at)
        self._state[shard] = ALIVE
        self._misses[shard] = 0
        self._set_gauge(shard)

    def remove(self, shard: int) -> None:
        for d in (self._state, self._misses, self._first_miss,
                  self._miss_tick, self._thresholds):
            d.pop(shard, None)

    def state_of(self, shard: int) -> str:
        return self._state.get(shard, ALIVE)

    def healthy(self, shard: int) -> bool:
        return self._state.get(shard, ALIVE) == ALIVE

    def suspects(self) -> list[int]:
        return sorted(k for k, s in self._state.items() if s == SUSPECT)

    def dead(self) -> list[int]:
        return sorted(k for k, s in self._state.items() if s == DEAD)

    def first_miss_tick(self, shard: int) -> int | None:
        return self._first_miss.get(shard)

    def _set_gauge(self, shard: int) -> None:
        if self.metrics is not None:
            self.metrics.shard_state.labels(shard=str(shard)).set(
                _STATE_CODE[self._state.get(shard, ALIVE)]
            )

    def _miss(self, shard: int) -> str | None:
        """Record one miss (at most one per tick); returns the new
        state when the miss caused a transition."""
        if self._state.get(shard, ALIVE) == DEAD:
            return None
        if self._miss_tick.get(shard) == self.now:
            return None
        self._miss_tick[shard] = self.now
        self._misses[shard] = self._misses.get(shard, 0) + 1
        self._first_miss.setdefault(shard, self.now)
        suspect_at, dead_at = self._thresholds.get(
            shard,
            (self.config.suspect_ticks,
             self.config.suspect_ticks + self.config.confirm_ticks),
        )
        state = self._state.get(shard, ALIVE)
        if state == ALIVE and self._misses[shard] >= suspect_at:
            self._state[shard] = SUSPECT
            if self.metrics is not None:
                self.metrics.suspects.inc()
            self._set_gauge(shard)
            return SUSPECT
        if state == SUSPECT and self._misses[shard] >= dead_at:
            self._state[shard] = DEAD
            if self.metrics is not None:
                self.metrics.deaths.inc()
            self._set_gauge(shard)
            return DEAD
        return None

    def report_down(self, shard: int) -> str | None:
        """Demand-driven evidence: a request into the shard raised
        :class:`ShardDownError`.  Counts as this tick's miss."""
        return self._miss(shard)

    def force_dead(self, shard: int) -> None:
        """Operator override: skip the suspect window (used by explicit
        ``FleetRouter.fail_over`` calls, never by the tick loop)."""
        if self._state.get(shard) == DEAD:
            return
        self._state[shard] = DEAD
        self._first_miss.setdefault(shard, self.now)
        if self.metrics is not None:
            self.metrics.deaths.inc()
        self._set_gauge(shard)

    def revive(self, shard: int) -> None:
        self._state[shard] = ALIVE
        self._misses[shard] = 0
        self._first_miss.pop(shard, None)
        self._miss_tick.pop(shard, None)
        self._set_gauge(shard)

    def tick(self, probe) -> list[tuple[int, str, str]]:
        """Advance the clock one tick and probe every non-dead shard.
        ``probe(shard)`` returns True when the shard answered.  Returns
        the transitions ``[(shard, old_state, new_state), ...]`` this
        tick caused, in shard order."""
        self.now += 1
        transitions: list[tuple[int, str, str]] = []
        for k in sorted(self._state):
            state = self._state[k]
            if state == DEAD:
                continue
            ok = False
            try:
                ok = bool(probe(k))
            except ShardDownError:
                ok = False
            if self.metrics is not None:
                self.metrics.heartbeats.labels(
                    outcome="ok" if ok else "miss"
                ).inc()
            if ok:
                self._misses[k] = 0
                self._first_miss.pop(k, None)
                if state == SUSPECT:
                    # a suspect that answers again was a blip, not a
                    # death: back to alive, counters reset
                    self._state[k] = ALIVE
                    self._set_gauge(k)
                    transitions.append((k, SUSPECT, ALIVE))
                continue
            new = self._miss(k)
            if new is not None:
                transitions.append((k, state, new))
        return transitions


class FailoverCoordinator:
    """Promotes replicas when the detector confirms a shard dead.

    Bound to one FleetRouter; the promotion path reuses the seams the
    fleet already has — ``RoutingTable`` epochs for fencing,
    ``SyncSession.rehome`` for live-session repair, and the replication
    manager's journaled copies for WAL-assisted catch-up."""

    def __init__(self, fleet, metrics: FailoverMetrics | None = None):
        self.fleet = fleet
        self.metrics = (
            metrics if metrics is not None
            else FailoverMetrics(fleet.metrics.registry)
        )

    def fail_over(self, shard: int, reason: str = "heartbeat") -> dict:
        """Resolve every doc the dead shard owned onto its freshest
        replica, fence the corpse out of routing, and re-home live
        sessions.  One epoch bump covers the whole failover (the
        fencing token); per-doc primary markers journal that epoch so
        post-crash recovery keeps the promotion."""
        fleet = self.fleet
        m = self.metrics
        t0 = time.perf_counter()
        det = fleet.detector
        det.force_dead(shard)

        # ONE forced-sampled episode trace ties the conviction, every
        # promotion, and every session rehome together in the black-box
        # dump (and in any Perfetto trace a promoted shard exports).
        # Minted from the fencing state, so a replayed chaos run with
        # the same seed produces the same trace id.
        ctx = obs_dist.mint_for_update(
            f"failover:{shard}:{fleet.table.epoch}:{det.now}".encode(),
            salt=b"failover",
        ).force("failover")
        bb = flight_recorder()
        bb.record(
            "failover", "conviction", severity="error", shard=shard,
            trace=ctx.trace_hex, reason=reason, detector_tick=det.now,
        )

        # resolve migrations the corpse was part of FIRST: the window's
        # double delivery makes the counterpart shard the freshest copy
        # by construction
        mig_promotions: list[str] = []
        for guid, mig in sorted(list(fleet._migrating.items())):
            if mig["src"] == shard:
                del fleet._migrating[guid]
                if mig["dst"] not in fleet._down and not fleet._is_stub(
                    mig["dst"]
                ):
                    # the seeded destination takes over mid-window
                    fleet.table.assign(guid, mig["dst"])
                    mig_promotions.append(guid)
                else:
                    fleet.table.unassign(guid)
            elif mig["dst"] == shard:
                # destination died mid-window: abort to the source (its
                # journaled intent resolves the same way post-crash)
                del fleet._migrating[guid]

        promoted: list[tuple[str, int]] = []
        lost: list[str] = []
        for guid in fleet.table.docs_on(shard):
            new_owner = fleet.repl.promote(guid, exclude={shard})
            if new_owner is None:
                # no replica ever saw the doc (factor 0, or it died
                # before any fan-out): the doc is offline until the
                # corpse's WAL is recovered or the shard revives
                fleet.table.unassign(guid)
                lost.append(guid)
                m.promotions.labels(outcome="lost").inc()
                bb.record(
                    "failover", "doc_lost", severity="warning",
                    guid=guid, shard=shard, trace=ctx.trace_hex,
                )
                continue
            fleet.table.assign(guid, new_owner)
            promoted.append((guid, new_owner))
            m.promotions.labels(outcome="promoted").inc()
            bb.record(
                "failover", "promotion", guid=guid, shard=new_owner,
                trace=ctx.trace_hex, src=shard,
            )

        # fence the corpse out of placement and replication
        fleet.ring.remove(shard)
        fleet._down.add(shard)
        fleet.repl.drop_shard(shard)

        # ONE monotonic fencing-epoch bump for the whole failover
        epoch = fleet.table.bump()
        fleet.metrics.epoch.set(epoch)
        for guid in mig_promotions:
            owner = fleet.table.lookup(guid)
            promoted.append((guid, owner))
            m.promotions.labels(outcome="promoted").inc()
            bb.record(
                "failover", "promotion", guid=guid, shard=owner,
                trace=ctx.trace_hex, src=shard, via="migration",
            )
        for guid, owner in promoted:
            fleet.shards[owner].journal_repl_role(guid, "primary", epoch)
            fleet.repl.rejournal_acks(guid, owner)
            fleet.shards[owner].engine.obs.tracer.instant(
                "ytpu.failover.promote", guid=guid, shard=owner,
                trace=ctx.trace_hex, epoch=epoch,
            )
        # live sessions resume against the new primary: rehome forces
        # an immediate anti-entropy digest; seq spaces survive, so the
        # repair is a targeted diff, never a full resync.  The episode
        # context stays installed so frames and replication records
        # emitted by the repair carry the failover's trace id.
        affected = {g for g, _o in promoted} | set(lost)
        with obs_dist.use_context(ctx):
            for (g, peer), sess in sorted(fleet._sessions.items()):
                if g in affected:
                    sess.rehome(epoch)
                    bb.record(
                        "failover", "rehome", guid=g, shard=shard,
                        trace=ctx.trace_hex, peer=peer, epoch=epoch,
                    )
            fleet.repl.repair_all()

        first_miss = det.first_miss_tick(shard)
        gap = det.now - first_miss if first_miss is not None else 0
        m.unavailable_ticks.observe(gap)
        m.seconds.observe(time.perf_counter() - t0)
        fleet._refresh_gauges()
        bb.record(
            "failover", "complete", shard=shard, trace=ctx.trace_hex,
            epoch=epoch, promoted=len(promoted), lost=len(lost),
            unavailable_ticks=gap,
        )
        bb.dump(
            "failover", shard=shard, cause=reason, epoch=epoch,
            trace=ctx.trace_hex,
        )
        return {
            "shard": shard,
            "reason": reason,
            "epoch": epoch,
            "promoted": sorted(g for g, _o in promoted),
            "lost": sorted(lost),
            "unavailable_ticks": gap,
        }
