"""Shard replication: journaled doc copies that survive a primary's
death (ISSUE 8).

Every update the fleet accepts is fanned out to R replica shards
(``YTPU_REPL_FACTOR``, default 1) chosen by walking the consistent-hash
ring past the owner — the same successor order placement would pick, so
replica locations are deterministic and rebalance-stable.  Replication
is **journal-only**: a replica appends the fanned-out records
(``KIND_UPDATE`` / ``KIND_ACK`` / ``KIND_DLQ``, under a ``KIND_REPL``
role marker) to its OWN write-ahead log without admitting the doc into
an engine slot.  That keeps slot accounting, bounded-load placement,
and the rebalancer's occupancy math untouched by replication — a
replica costs disk, not device memory — and makes promotion exactly the
recovery path the WAL already guarantees: scan the replica's journal,
integrate the doc's records, flush.

Delivery is asynchronous through a bounded per-shard outbox drained on
every fleet tick.  Overflow never drops: the outbox applies
backpressure by draining inline (``ytpu_repl_backpressure_total``).
Zero-acknowledged-loss has one more hole to plug — a primary that dies
*before the first drain* — so the freshness oracle counts queued outbox
entries as recoverable state, and ``FleetRouter.receive_update``
falls back to :meth:`ReplicationManager.absorb` (synchronous journal on
a replica) when the primary's machine is already gone, refusing the
update entirely if no replica can journal it.  An acknowledged update
is therefore always on at least one surviving WAL.

Checkpoint interplay: WAL compaction folds only docs the shard OWNS, so
a replica's journaled copies would vanish with their segments.
``FleetRouter.checkpoint`` therefore calls
:meth:`rejournal_after_checkpoint`, which reseeds every replica pair
with the live owner's full state (one record, counted by
``ytpu_repl_reseeds_total``) — the same move migration's seed step
makes, and idempotent for the same CRDT reason.

Knobs: ``YTPU_REPL_FACTOR``, ``YTPU_REPL_OUTBOX_MAX``,
``YTPU_REPL_BATCH``.  Metrics: the ``ytpu_repl_*`` families.
"""

from __future__ import annotations

import base64
import json
from collections import deque

from ..obs import global_registry
from ..obs.blackbox import flight_recorder
from ..obs.dist import current_context, flow_id_for
from ..persistence import KIND_DLQ, KIND_UPDATE
from ..persistence.recovery import iter_file_events, scan_wal
from ..provider import ProviderFullError
from .failover import ShardDownError
from .hashring import _env_int

__all__ = ["ReplicationConfig", "ReplicationManager", "ReplicationMetrics"]

# cap on dead letters mirrored per doc (matches the engine's own DLQ
# bounding philosophy: newest evidence wins)
_LETTER_CAP = 32


class ReplicationConfig:
    """Resolved replication knobs (constructor args beat ``YTPU_REPL_*``
    env beats defaults)."""

    __slots__ = ("factor", "outbox_max", "batch")

    def __init__(
        self,
        factor: int | None = None,
        outbox_max: int | None = None,
        batch: int | None = None,
    ):
        def pick(v, env, default):
            return v if v is not None else _env_int(env, default)

        # replicas per doc; 0 disables fan-out (failover then only
        # recovers docs inside a migration window)
        self.factor = max(0, pick(factor, "YTPU_REPL_FACTOR", 1))
        # queued records per replica shard before backpressure drains
        # inline (never drops)
        self.outbox_max = max(1, pick(outbox_max, "YTPU_REPL_OUTBOX_MAX", 256))
        # records applied per replica per drain pass
        self.batch = max(1, pick(batch, "YTPU_REPL_BATCH", 64))


class ReplicationMetrics:
    """The ``ytpu_repl_*`` instrument bundle."""

    def __init__(self, registry=None):
        r = registry if registry is not None else global_registry()
        self.registry = r
        self.records = r.counter(
            "ytpu_repl_records_total",
            "Records journaled onto replica WALs, by kind (update / ack "
            "/ dlq / seed = full-state reseed after checkpoint or "
            "absorb)",
            labelnames=("kind",),
        )
        self.outbox_depth = r.gauge(
            "ytpu_repl_outbox_depth",
            "Replication records queued toward one replica shard",
            labelnames=("shard",),
        )
        self.lag = r.gauge(
            "ytpu_repl_lag",
            "Accepted-but-not-yet-journaled updates across all docs "
            "replicated to one shard (0 = replica WALs are current)",
            labelnames=("shard",),
        )
        self.replica_docs = r.gauge(
            "ytpu_repl_replica_docs",
            "Docs one shard holds journaled replica copies of",
            labelnames=("shard",),
        )
        self.backpressure = r.counter(
            "ytpu_repl_backpressure_total",
            "Outbox-overflow events resolved by draining inline "
            "(replication never drops on overflow)",
        )
        self.reseeds = r.counter(
            "ytpu_repl_reseeds_total",
            "Full-state replica reseeds (post-checkpoint re-journal, "
            "or first copy on a new replica)",
        )
        self.stalls = r.counter(
            "ytpu_repl_stalls_total",
            "Drain passes skipped or aborted per replica, by reason "
            "(suspect / down / error)",
            labelnames=("reason",),
        )


class ReplicationManager:
    """Fan-out, lag tracking, and WAL-assisted promotion for one fleet.

    All state is host-side bookkeeping over the shards' own WALs; the
    durable truth is always the journals themselves (recovery rebuilds
    roles from ``KIND_REPL`` markers with no help from this object)."""

    def __init__(self, fleet, config: ReplicationConfig | None = None,
                 metrics: ReplicationMetrics | None = None):
        self.fleet = fleet
        self.config = config if config is not None else ReplicationConfig()
        self.metrics = (
            metrics if metrics is not None
            else ReplicationMetrics(fleet.metrics.registry)
        )
        # per-doc primary-accepted sequence high watermark
        self._hwm: dict[str, int] = {}
        # (guid, shard) -> highest seq journaled on that replica
        self._applied: dict[tuple[str, int], int] = {}
        # (guid, shard) pairs whose replica role marker is journaled
        self._marked: set[tuple[str, int]] = set()
        # shard -> queued fan-out entries (kind, guid, data)
        self._outbox: dict[int, deque] = {}
        # in-memory mirror for WAL-less shards: (guid, shard) -> entries
        self._mem: dict[tuple[str, int], list] = {}
        # last heat observed on the owner (travels with promotion)
        self._heat: dict[str, float] = {}
        # mirrored dead letters per doc, newest-last, bounded
        self._letters: dict[str, list[dict]] = {}

    # -- placement -----------------------------------------------------------

    def replicas_of(self, guid: str, exclude=()) -> list[int]:
        """The R replica shards for a doc: ring successors past the
        owner, skipping unhealthy/retired shards.  Deterministic, so
        the freshness oracle and recovery agree on where copies live."""
        if self.config.factor <= 0:
            return []
        fleet = self.fleet
        owner = fleet.owner_of(guid)
        bad = set(exclude) | fleet._unhealthy()
        out: list[int] = []
        for k in fleet.ring.walk(guid):
            if k == owner or k in bad or k in out:
                continue
            out.append(k)
            if len(out) >= self.config.factor:
                break
        return out

    # -- fan-out enqueue -----------------------------------------------------

    def _push(self, dst: int, entry: tuple) -> None:
        q = self._outbox.setdefault(dst, deque())
        q.append(entry)
        if len(q) > self.config.outbox_max:
            # bounded outbox, unbounded durability: overflow drains
            # inline instead of dropping
            self.metrics.backpressure.inc()
            self._drain_one(dst, budget=len(q))
        self.metrics.outbox_depth.labels(shard=str(dst)).set(
            len(self._outbox.get(dst, ()))
        )

    def enqueue_update(self, guid: str, update: bytes, v2: bool = False
                       ) -> None:
        """Fan one accepted update out to the doc's replicas
        (asynchronous: queued now, journaled on the next drain)."""
        targets = self.replicas_of(guid)
        seq = self._hwm.get(guid, 0) + 1
        self._hwm[guid] = seq
        if not targets:
            return
        ctx = current_context()
        trace_hex = (
            ctx.trace_hex if ctx is not None and ctx.sampled else None
        )
        owner = self.fleet.owner_of(guid)
        if owner is not None:
            try:
                shard = self.fleet.shards[owner]
                self._heat[guid] = shard.tiers.heat_of(guid)
                # cost attribution (ISSUE 19): fan-out bytes land on the
                # owner's ledger — the doc that wrote is the doc that pays
                # for every replica copy
                shard.cost.repl_bytes(guid, len(update) * len(targets))
            except ShardDownError:
                pass
        for dst in targets:
            if trace_hex is not None and owner is not None:
                # flow arrow: opened on the primary's tracer here, closed
                # on the replica's tracer when the record is journaled
                # (the id is hash-derived, so the two halves match even
                # when the tracers export separately and merge later)
                try:
                    self.fleet.shards[owner].engine.obs.tracer.flow_start(
                        "ytpu.repl.fanout",
                        flow_id_for((trace_hex, "repl", guid, seq, dst)),
                        guid=guid, dst=dst, trace=trace_hex,
                    )
                except ShardDownError:
                    pass
            self._push(
                dst,
                ("update", guid, (seq, bytes(update), bool(v2), trace_hex)),
            )

    def enqueue_ack(self, guid: str, peer: str, sid: int, seq: int) -> None:
        """Fan a session receive-floor ack out to the replicas, so a
        promoted replica's WAL lets surviving sessions RESUME instead
        of full-resyncing."""
        for dst in self.replicas_of(guid):
            self._push(dst, ("ack", guid, (str(peer), int(sid), int(seq))))

    def enqueue_dlq(self, guid: str, update: bytes, v2: bool, reason: str
                    ) -> None:
        """Mirror one dead letter to the replicas (quarantined evidence
        must survive the primary that quarantined it)."""
        letter = {
            "guid": guid,
            "v2": bool(v2),
            "reason": str(reason),
            "update": base64.b64encode(bytes(update)).decode("ascii"),
        }
        kept = self._letters.setdefault(guid, [])
        kept.append(dict(letter))
        del kept[:-_LETTER_CAP]
        targets = self.replicas_of(guid)
        ctx = current_context()
        if ctx is not None:
            ctx.force("dlq_mirror")
        flight_recorder().record(
            "replication", "dlq_mirror", severity="warning", guid=guid,
            trace=ctx.trace_hex if ctx is not None else None,
            reason=str(reason), replicas=len(targets),
        )
        for dst in targets:
            self._push(dst, ("dlq", guid, (letter,)))

    def absorb(self, guid: str, update: bytes, v2: bool = False) -> bool:
        """Synchronous last-resort journal: the primary's machine is
        already gone, so the update is journaled directly on the doc's
        replicas (no outbox).  Returns False — caller must refuse the
        update — when not a single replica could journal it; True means
        the bytes are durable somewhere and failover will carry them."""
        owner = self.fleet.owner_of(guid)
        exclude = {owner} if owner is not None else set()
        seq = self._hwm.get(guid, 0) + 1
        ctx = current_context()
        if ctx is not None:
            ctx.force("absorb")
        trace_hex = (
            ctx.trace_hex if ctx is not None and ctx.sampled else None
        )
        count = 0
        for dst in self.replicas_of(guid, exclude=exclude):
            try:
                self._apply(dst, ("update", guid,
                                  (seq, bytes(update), bool(v2),
                                   trace_hex)))
            except ShardDownError:
                self.fleet.detector.report_down(dst)
                continue
            count += 1
        flight_recorder().record(
            "replication", "absorb",
            severity="warning" if count else "error", guid=guid,
            trace=ctx.trace_hex if ctx is not None else None,
            replicas=count,
        )
        if count == 0:
            return False
        self._hwm[guid] = seq
        return True

    # -- drain ---------------------------------------------------------------

    def _apply(self, dst: int, entry: tuple) -> None:
        """Journal one fan-out entry on the replica shard's WAL.
        Raises :class:`ShardDownError` when the shard is gone (caller
        reports to the detector and keeps the queue)."""
        kind, guid, data = entry
        prov = self.fleet.shards[dst]
        if (guid, dst) not in self._marked:
            prov.journal_repl_role(
                guid, "replica", self.fleet.table.epoch,
                primary=self.fleet.owner_of(guid),
            )
            self._marked.add((guid, dst))
        if kind == "update":
            seq, payload, v2 = data[:3]
            trace_hex = data[3] if len(data) > 3 else None
            if not prov.journal_replica_record(
                KIND_UPDATE, guid, payload, v2=v2
            ):
                # WAL-less shard: keep an in-memory mirror so promotion
                # still has the bytes (durability is only as good as
                # the process, same as the primary's own slots)
                self._mem.setdefault((guid, dst), []).append(
                    (seq, payload, v2)
                )
            key = (guid, dst)
            if seq > self._applied.get(key, 0):
                self._applied[key] = seq
            if trace_hex is not None:
                prov.engine.obs.tracer.flow_end(
                    "ytpu.repl.fanout",
                    flow_id_for((trace_hex, "repl", guid, seq, dst)),
                    guid=guid, shard=dst, trace=trace_hex,
                )
            self.metrics.records.labels(kind="update").inc()
        elif kind == "ack":
            peer, sid, seq = data
            self._applied.setdefault((guid, dst), 0)
            prov.journal_session_ack(guid, peer, sid, seq)
            self.metrics.records.labels(kind="ack").inc()
        elif kind == "dlq":
            (letter,) = data
            self._applied.setdefault((guid, dst), 0)
            prov.journal_replica_record(
                KIND_DLQ, guid,
                json.dumps(
                    {"schema": 1, "letters": [letter]},
                    separators=(",", ":"),
                ).encode("utf-8"),
            )
            self.metrics.records.labels(kind="dlq").inc()

    def _drain_one(self, dst: int, budget: int | None = None) -> int:
        q = self._outbox.get(dst)
        if not q:
            return 0
        fleet = self.fleet
        if dst in fleet._down or fleet._is_stub(dst):
            self.metrics.stalls.labels(reason="down").inc()
            return 0
        n = len(q) if budget is None else min(budget, len(q))
        done = 0
        for _ in range(n):
            entry = q[0]
            try:
                self._apply(dst, entry)
            except ShardDownError:
                fleet.detector.report_down(dst)
                self.metrics.stalls.labels(reason="error").inc()
                break
            q.popleft()
            done += 1
        self.metrics.outbox_depth.labels(shard=str(dst)).set(len(q))
        return done

    def drain(self, full: bool = False) -> int:
        """One replication pass: apply up to ``batch`` queued records
        per replica (all of them when ``full``).  Suspect shards are
        skipped — their queues hold until the detector acquits or
        convicts them."""
        det = self.fleet.detector
        total = 0
        for dst in sorted(self._outbox):
            if not self._outbox[dst]:
                continue
            state = det.state_of(dst)
            if state == "suspect":
                self.metrics.stalls.labels(reason="suspect").inc()
                continue
            total += self._drain_one(
                dst, budget=None if full else self.config.batch
            )
        self._refresh_gauges()
        return total

    def repair_all(self) -> int:
        """Drain every outbox to empty (post-failover catch-up)."""
        return self.drain(full=True)

    def flush_for(self, guid: str, dst: int) -> None:
        """Apply every queued entry for one (doc, replica) pair NOW —
        promotion must not leave accepted updates stranded in the
        outbox."""
        q = self._outbox.get(dst)
        if not q:
            return
        keep = deque()
        for entry in q:
            if entry[1] == guid:
                self._apply(dst, entry)
            else:
                keep.append(entry)
        self._outbox[dst] = keep
        self.metrics.outbox_depth.labels(shard=str(dst)).set(len(keep))

    # -- freshness + promotion ----------------------------------------------

    def _candidates(self, guid: str, exclude=()) -> list[tuple[int, int]]:
        """``(score, shard)`` per surviving replica, freshest first
        (score ties break to the LOWEST shard id, so every node in a
        partitioned fleet elects the same winner).  Queued outbox
        entries count: promotion flushes them before materializing."""
        fleet = self.fleet
        bad = set(exclude) | fleet._down | fleet._retired
        scores: dict[int, int] = {}
        for (g, s), seq in self._applied.items():
            if g == guid and s not in bad and not fleet._is_stub(s):
                scores[s] = max(scores.get(s, 0), seq)
        for g, s in self._marked | set(self._mem):
            if g == guid and s not in bad and not fleet._is_stub(s):
                scores.setdefault(s, 0)
        for s, q in self._outbox.items():
            if s in bad or fleet._is_stub(s):
                continue
            for kind, g, data in q:
                if g != guid:
                    continue
                seq = data[0] if kind == "update" else 0
                scores[s] = max(scores.get(s, 0), seq)
        return sorted(
            ((seq, s) for s, seq in scores.items()),
            key=lambda t: (-t[0], t[1]),
        )

    def freshest(self, guid: str, exclude=()) -> int | None:
        cands = self._candidates(guid, exclude)
        return cands[0][1] if cands else None

    def promote(self, guid: str, exclude=()) -> int | None:
        """Make the freshest surviving replica the doc's primary:
        flush its queued fan-out, admit the doc, integrate the copy
        from its own WAL (WAL-assisted catch-up), carry heat and dead
        letters over.  Tries the next-freshest on admission overflow.
        Returns the promoted shard, or None when no replica holds the
        doc.  The CALLER owns routing: table assignment, the fencing
        epoch bump, and the primary role marker."""
        for _score, cand in self._candidates(guid, exclude):
            prov = self.fleet.shards[cand]
            try:
                self.flush_for(guid, cand)
                self._materialize(prov, guid)
            except ShardDownError:
                self.fleet.detector.report_down(cand)
                continue
            except ProviderFullError:
                continue
            prov.tiers.adopt_heat(guid, self._heat.get(guid, 0.0))
            doc = prov.doc_id(guid)
            for e in self._letters.get(guid, ()):
                prov.engine._dead_letter(
                    doc, base64.b64decode(e.get("update", "")),
                    bool(e.get("v2")), e.get("reason", "replicated"),
                )
            # the promoted shard is no longer a replica of the doc
            self._applied.pop((guid, cand), None)
            self._marked.discard((guid, cand))
            self._mem.pop((guid, cand), None)
            return cand
        return None

    def _materialize(self, prov, guid: str) -> int:
        """Integrate a replica's journaled copy of one doc into its
        engine.  Reads the shard's OWN WAL tail (appends flush to the
        OS on every record, so live segments are readable in-process);
        WAL-less shards integrate from the in-memory mirror."""
        doc = prov.doc_id(guid)
        eng = prov.engine
        applied = 0
        if prov.wal is not None:
            _ckpt, segs = scan_wal(prov.wal.dir)
            for _idx, path in segs:
                for ev in iter_file_events(path, final=False):
                    if ev[0] != "record":
                        continue
                    rec = ev[1]
                    if rec.guid != guid or rec.kind != KIND_UPDATE:
                        continue
                    if eng.queue_update(doc, rec.payload, v2=rec.v2):
                        applied += 1
        for _seq, payload, v2 in sorted(
            self._mem.get((guid, prov.shard_id), ())
        ):
            if eng.queue_update(doc, payload, v2=v2):
                applied += 1
        if applied:
            prov._dirty = True
            prov.flush()
        return applied

    # -- durability interplay ------------------------------------------------

    def rejournal_after_checkpoint(self) -> int:
        """Reseed every replica pair after WAL compaction: checkpoints
        fold only OWNED docs, so the replica's journaled copy must be
        re-established — one full-state record from the live owner
        (idempotent), plus role marker, mirrored letters, and current
        session ack floors."""
        fleet = self.fleet
        pairs = sorted(set(self._applied) | self._marked)
        reseeded = 0
        for guid, dst in pairs:
            owner = fleet.owner_of(guid)
            if owner is None or owner in fleet._down:
                continue
            try:
                src = fleet.shards[owner]
                src.flush()
                state = src.encode_state_as_update(guid)
                prov = fleet.shards[dst]
                prov.journal_repl_role(
                    guid, "replica", fleet.table.epoch, primary=owner
                )
                if prov.journal_replica_record(KIND_UPDATE, guid, state):
                    self._applied[(guid, dst)] = self._hwm.get(guid, 0)
                self._marked.add((guid, dst))
                for e in self._letters.get(guid, ()):
                    prov.journal_replica_record(
                        KIND_DLQ, guid,
                        json.dumps(
                            {"schema": 1, "letters": [e]},
                            separators=(",", ":"),
                        ).encode("utf-8"),
                    )
            except ShardDownError:
                fleet.detector.report_down(dst)
                continue
            self.metrics.reseeds.inc()
            self.metrics.records.labels(kind="seed").inc()
            reseeded += 1
            self.rejournal_acks(guid, dst)
        return reseeded

    def rejournal_acks(self, guid: str, dst: int) -> None:
        """Journal every live session's receive floor for a doc onto
        one shard's WAL — the promoted/reseeded owner must know the
        floors or post-crash recovery forces full resyncs."""
        fleet = self.fleet
        prov = fleet.shards[dst]
        for (g, peer), sess in sorted(fleet._sessions.items()):
            if g != guid:
                continue
            sid, seq = sess.ack_floor
            prov.journal_session_ack(guid, peer, sid, seq)

    # -- lifecycle + introspection -------------------------------------------

    def drop_shard(self, shard: int) -> None:
        """Forget a dead shard's queues and copies (its journal is
        gone with the machine; revival re-enters through fencing)."""
        self._outbox.pop(shard, None)
        for key in [k for k in self._applied if k[1] == shard]:
            del self._applied[key]
        self._marked = {p for p in self._marked if p[1] != shard}
        for key in [k for k in self._mem if k[1] == shard]:
            del self._mem[key]
        lab = str(shard)
        self.metrics.outbox_depth.labels(shard=lab).set(0)
        self.metrics.lag.labels(shard=lab).set(0)
        self.metrics.replica_docs.labels(shard=lab).set(0)

    def owner_changed(self, guid: str, new_owner: int) -> None:
        """A doc's ownership moved onto ``new_owner`` (migration
        complete / failover promotion): it is no longer a replica of
        the doc it now serves."""
        self._applied.pop((guid, new_owner), None)
        self._marked.discard((guid, new_owner))
        self._mem.pop((guid, new_owner), None)
        q = self._outbox.get(new_owner)
        if q:
            self._outbox[new_owner] = deque(
                e for e in q if e[1] != guid
            )

    def forget_doc(self, guid: str) -> None:
        """Drop all replication state for a doc (released/lost)."""
        self._hwm.pop(guid, None)
        self._heat.pop(guid, None)
        self._letters.pop(guid, None)
        for key in [k for k in self._applied if k[0] == guid]:
            del self._applied[key]
        self._marked = {p for p in self._marked if p[0] != guid}
        for key in [k for k in self._mem if k[0] == guid]:
            del self._mem[key]
        for q in self._outbox.values():
            stale = [e for e in q if e[1] == guid]
            for e in stale:
                q.remove(e)

    def copies_on(self, shard: int) -> list[str]:
        return sorted(
            {g for (g, s) in (set(self._applied) | self._marked)
             if s == shard}
        )

    def lag(self, shard: int) -> int:
        """Accepted-minus-journaled updates across every doc this
        shard replicates (queued outbox entries keep it honest)."""
        total = 0
        for (g, s), seq in self._applied.items():
            if s == shard:
                total += max(0, self._hwm.get(g, 0) - seq)
        seen = {g for (g, s) in self._applied if s == shard}
        for kind, g, data in self._outbox.get(shard, ()):
            if kind == "update" and g not in seen:
                total += 1
        return total

    def _refresh_gauges(self) -> None:
        shards = set(self._outbox) | {s for (_g, s) in self._applied}
        shards |= {s for (_g, s) in self._marked}
        for s in shards:
            lab = str(s)
            self.metrics.outbox_depth.labels(shard=lab).set(
                len(self._outbox.get(s, ()))
            )
            self.metrics.lag.labels(shard=lab).set(self.lag(s))
            self.metrics.replica_docs.labels(shard=lab).set(
                len(self.copies_on(s))
            )

    def snapshot(self) -> dict:
        """JSON-able replication state (ytpu_stats / bench feeds)."""
        self._refresh_gauges()
        return {
            "factor": self.config.factor,
            "docs_tracked": len(self._hwm),
            "outbox": {
                str(s): len(q) for s, q in sorted(self._outbox.items()) if q
            },
            "lag": {
                str(s): self.lag(s)
                for s in sorted(
                    {x for (_g, x) in set(self._applied) | self._marked}
                )
            },
            "replica_docs": {
                str(s): len(self.copies_on(s))
                for s in sorted(
                    {x for (_g, x) in set(self._applied) | self._marked}
                )
            },
        }
