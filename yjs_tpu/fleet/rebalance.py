"""Churn rebalancer: migrate docs off shards approaching full (ISSUE 6).

The bounded-load ring keeps FIRST-TOUCH placement even, but live fleets
skew afterwards: docs are released, shards are added, one tenant's rooms
all go hot.  The rebalancer is the corrective loop — each
``FleetRouter.tick()`` it reads per-shard occupancy (the same gauge
``ytpu_prof_slot_occupancy``/``ytpu_fleet_shard_occupancy`` exposes) and
migrates docs from any shard above the high watermark down toward the
target, bounded per tick so rebalancing spreads its cost instead of
stampeding the fleet.

Policy, deterministic end to end (chaos tests replay it exactly):

- a shard triggers when ``occupancy >= YTPU_FLEET_REBALANCE_HIGH``
  (default 0.85 — close enough to ``ProviderFullError`` to matter, far
  enough to finish moving before admission fails);
- it sheds down to ``YTPU_FLEET_REBALANCE_TARGET`` (default 0.6),
  coldest docs first: sessionless rooms before sessioned ones
  (migrating a room nobody is attached to is free, migrating a live
  room costs a digest round), then ascending REAL heat score from the
  shard's :class:`~yjs_tpu.tiering.HeatTracker` — the room least
  likely to be touched again moves first.  With tiering disabled every
  score is 0.0 and the order degrades to the old deterministic
  guid sort;
- at most ``YTPU_FLEET_REBALANCE_BATCH`` migrations per tick (default
  4) across the whole fleet;
- destinations are the least-loaded live shards with free slots; a
  fleet with nowhere to put a doc records a ``stuck`` decision (the
  operator's cue to ``add_shard``) rather than thrashing.
"""

from __future__ import annotations


class Rebalancer:
    """Occupancy-driven migration planner bound to one FleetRouter."""

    def __init__(self, fleet):
        self.fleet = fleet

    def _pick_destination(self, src: int) -> int | None:
        """Least-loaded live shard with a free slot (ties break to the
        lowest id — determinism beats spread at this scale)."""
        fleet = self.fleet
        best = None
        best_load = None
        for k in fleet.live_shards:
            if k == src:
                continue
            # never rebalance ONTO a shard the failure detector holds
            # suspect (or dead but unconvicted): a migration into a
            # dying shard is data movement toward the cliff edge
            if not fleet.shard_healthy(k):
                continue
            load = fleet._load(k)
            if load >= fleet._capacity(k):
                continue
            # a destination at/above the high watermark would trigger
            # itself next tick: moving load there is churn, not balance
            if fleet._capacity(k) and (
                (load + 1) / fleet._capacity(k)
                > fleet.config.rebalance_high
            ):
                continue
            if best_load is None or load < best_load:
                best, best_load = k, load
        return best

    def plan(self) -> list[dict]:
        """The moves one tick would make (dry run, same determinism)."""
        fleet = self.fleet
        cfg = fleet.config
        sessioned = {g for (g, _p) in fleet._sessions}
        moves: list[dict] = []
        budget = cfg.rebalance_batch
        for src in fleet.live_shards:
            if budget <= 0:
                break
            # a suspect/dead source has nothing safely readable to
            # migrate; failover, not rebalancing, resolves it
            if not fleet.shard_healthy(src):
                continue
            cap = fleet._capacity(src)
            if not cap or fleet._load(src) / cap < cfg.rebalance_high:
                continue
            target_docs = int(cfg.rebalance_target * cap)
            excess = fleet._load(src) - target_docs
            tm = fleet.shards[src].tiers
            candidates = sorted(
                fleet.shards[src].guids(),
                key=lambda g: (g in sessioned, tm.heat_of(g), g),
            )
            for guid in candidates[:max(0, excess)]:
                if budget <= 0:
                    break
                if guid in fleet._migrating:
                    continue
                dst = self._pick_destination(src)
                if dst is None:
                    moves.append(
                        {"action": "stuck", "guid": guid, "src": src}
                    )
                    budget -= 1
                    break
                moves.append(
                    {"action": "move", "guid": guid,
                     "src": src, "dst": dst}
                )
                budget -= 1
        return moves

    def tick(self) -> list[dict]:
        """Plan and execute one rebalance pass; returns the decisions
        (executed moves carry ``action="move"``)."""
        fleet = self.fleet
        decisions = self.plan()
        for d in decisions:
            fleet.metrics.rebalance.labels(action=d["action"]).inc()
            if d["action"] == "move":
                fleet.migrate_doc(d["guid"], d["dst"], reason="rebalance")
        return decisions
