"""Doc-sharded provider fleet (ISSUE 6).

One :class:`TpuProvider` caps the deployment at single-device slot
capacity.  :class:`FleetRouter` puts N provider shards behind the same
facade: bounded-load consistent-hash placement
(:class:`HashRing`), a versioned :class:`RoutingTable`, cross-shard
session fan-out, live doc migration over the WAL's
intent/release records, and an occupancy-driven :class:`Rebalancer`.
Crash recovery (:meth:`FleetRouter.recover`) replays every shard's WAL
and resolves mid-migration crashes to exactly one owner.

Knobs: ``YTPU_FLEET_VNODES``, ``YTPU_FLEET_LOAD_FACTOR``,
``YTPU_FLEET_REBALANCE_HIGH``, ``YTPU_FLEET_REBALANCE_TARGET``,
``YTPU_FLEET_REBALANCE_BATCH``.  Metrics: the ``ytpu_fleet_*``
families (README "Fleet").
"""

from .hashring import (
    FleetFullError,
    HashRing,
    RoutingTable,
    stable_hash,
)
from .rebalance import Rebalancer
from .router import FleetConfig, FleetMetrics, FleetRouter

__all__ = [
    "FleetConfig",
    "FleetFullError",
    "FleetMetrics",
    "FleetRouter",
    "HashRing",
    "Rebalancer",
    "RoutingTable",
    "stable_hash",
]
