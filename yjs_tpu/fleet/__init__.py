"""Doc-sharded provider fleet (ISSUE 6 + ISSUE 8).

One :class:`TpuProvider` caps the deployment at single-device slot
capacity.  :class:`FleetRouter` puts N provider shards behind the same
facade: bounded-load consistent-hash placement
(:class:`HashRing`), a versioned :class:`RoutingTable`, cross-shard
session fan-out, live doc migration over the WAL's
intent/release records, and an occupancy-driven :class:`Rebalancer`.
Crash recovery (:meth:`FleetRouter.recover`) replays every shard's WAL
and resolves mid-migration crashes to exactly one owner.

ISSUE 8 adds survivability: every accepted update fans out to R
replica shards (:class:`ReplicationManager`, journal-only copies on the
replicas' own WALs), a tick-deterministic heartbeat
:class:`FailureDetector` convicts dead shards (suspect → dead with
jittered thresholds), and :class:`FailoverCoordinator` promotes the
freshest replica under a monotonic fencing epoch — a revived stale
primary is fenced out, never split-brained.

Knobs: ``YTPU_FLEET_*``, ``YTPU_REPL_*``, ``YTPU_FAILOVER_*``.
Metrics: the ``ytpu_fleet_*``, ``ytpu_repl_*``, and ``ytpu_failover_*``
families (README "Fleet" and "Replication & failover").
"""

from .failover import (
    DeadShard,
    FailoverConfig,
    FailoverCoordinator,
    FailoverMetrics,
    FailureDetector,
    ShardDownError,
)
from .hashring import (
    FleetFullError,
    HashRing,
    RoutingTable,
    stable_hash,
)
from .rebalance import Rebalancer
from .replication import (
    ReplicationConfig,
    ReplicationManager,
    ReplicationMetrics,
)
from .router import FleetConfig, FleetMetrics, FleetRouter

__all__ = [
    "DeadShard",
    "FailoverConfig",
    "FailoverCoordinator",
    "FailoverMetrics",
    "FailureDetector",
    "FleetConfig",
    "FleetFullError",
    "FleetMetrics",
    "FleetRouter",
    "HashRing",
    "Rebalancer",
    "ReplicationConfig",
    "ReplicationManager",
    "ReplicationMetrics",
    "RoutingTable",
    "ShardDownError",
    "stable_hash",
]
