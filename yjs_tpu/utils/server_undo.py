"""Server-side undo/redo for device-resident rooms.

The reference UndoManager (src/utils/UndoManager.js:19-296) is item-graph
surgery: popping a stack item walks the struct store, follows persistent
``redone`` pointers left by earlier undos, pins kept items, and rebuilds
deleted items with fresh ids (redoItem, Item.js).  That state — redone
links, keep flags, the item graph itself — must PERSIST between undo and
redo calls, so a correct server-side undo cannot be recomputed on demand
from the engine's columnar state.

Design: an opt-in PER-ROOM CPU REPLICA.  :class:`RoomUndo` feeds every
update the room receives into a ``Doc(gc=False)`` replica and runs the
reference-exact :class:`~yjs_tpu.utils.undo.UndoManager` on it.  Calling
``undo()``/``redo()`` performs the reverting transaction on the replica,
captures the update it emits, and hands it back for the engine + the
room's peers — the device-resident room applies it through the normal
batched flush path like any other client edit.

Why not a native/device undo: undo volume is interactive (a keypress,
not a batch); the work is pointer-chasing over exactly the item graph
the CPU core already models; and the replica is required anyway for the
persistent redone/keep state.  Rooms that never enable undo pay nothing;
rooms that do pay one CPU replica — the same cost profile as the
reference, where the UndoManager's host doc IS that replica.
"""

from __future__ import annotations

from ..core import Doc
from ..updates import apply_update, apply_update_v2
from .undo import UndoManager

#: origin tag for updates that should land on the room's undo stack
TRACKED = "room-undo-tracked"

_GETTERS = {
    "text": Doc.get_text,
    "map": Doc.get_map,
    "array": Doc.get_array,
    "xml": Doc.get_xml_fragment,
}


class RoomUndo:
    """Reference-semantics undo/redo stack for one provider room.

    ``scopes`` is a list of ``(kind, name)`` root-type scopes (kind in
    ``text|map|array|xml``) the stack tracks — the UndoManager scope
    filter (reference UndoManager.js:19-41).  Updates fed with
    ``tracked=True`` (or an origin in ``tracked_origins``) are undoable;
    everything else is foreign traffic that undo must not revert."""

    def __init__(
        self,
        initial_state: bytes | None,
        scopes=(("text", "text"),),
        capture_timeout: float = 500,
        delete_filter=None,
    ):
        self.replica = Doc(gc=False)
        if initial_state:
            apply_update(self.replica, initial_state)
        scope_types = [
            _GETTERS[kind](self.replica, name) for kind, name in scopes
        ]
        self.manager = UndoManager(
            scope_types,
            capture_timeout=capture_timeout,
            delete_filter=delete_filter,
            tracked_origins={TRACKED},
        )

    # -- update ingestion ---------------------------------------------------

    def apply_update(self, update: bytes, tracked: bool, v2: bool = False):
        """Feed one room update into the replica.  ``tracked`` updates
        land on the undo stack; foreign ones only advance the state."""
        origin = TRACKED if tracked else "room-undo-foreign"
        if v2:
            apply_update_v2(self.replica, update, origin)
        else:
            apply_update(self.replica, update, origin)

    # -- undo / redo --------------------------------------------------------

    def _capture(self, op) -> bytes | None:
        collected: list[bytes] = []

        def on_update(update, _origin, _doc):
            collected.append(update)

        self.replica.on("update", on_update)
        try:
            popped = op()
        finally:
            self.replica.off("update", on_update)
        if popped is None or not collected:
            return None
        if len(collected) == 1:
            return collected[0]
        from ..updates import merge_updates

        return merge_updates(collected)

    def undo(self) -> bytes | None:
        """Revert the room's last tracked change; returns the update to
        apply to the room (and broadcast), or None if nothing to undo."""
        return self._capture(self.manager.undo)

    def redo(self) -> bytes | None:
        return self._capture(self.manager.redo)

    @property
    def can_undo(self) -> bool:
        return bool(self.manager.undo_stack)

    @property
    def can_redo(self) -> bool:
        return bool(self.manager.redo_stack)

    def stop_capturing(self) -> None:
        self.manager.stop_capturing()

    def clear(self) -> None:
        self.manager.clear()
