"""AbstractConnector: the interchangeable-connector contract
(reference src/utils/AbstractConnector.js:16-26).

All connectors hold the doc they bind and an (optional) awareness
instance and speak through the Observable event surface; like the
reference, this is typing/contract information more than machinery —
``examples/socket_connector.py`` shows a real transport built on it.
"""

from __future__ import annotations

from ..lib0.observable import Observable


class AbstractConnector(Observable):
    """Base class all connectors implement to stay interchangeable.

    Note (mirroring the reference): this interface is experimental and
    inheriting it is optional — it serves as the contract's shape.
    """

    def __init__(self, ydoc, awareness=None):
        super().__init__()
        self.doc = ydoc
        self.awareness = awareness
