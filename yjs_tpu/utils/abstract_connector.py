"""AbstractConnector: the interchangeable-connector contract
(reference src/utils/AbstractConnector.js:16-26).

All connectors hold the doc they bind and an (optional) awareness
instance and speak through the Observable event surface; like the
reference, this is typing/contract information more than machinery —
``examples/socket_connector.py`` shows a real transport built on it.
"""

from __future__ import annotations

from ..lib0.observable import Observable


class AbstractConnector(Observable):
    """Base class all connectors implement to stay interchangeable.

    Note (mirroring the reference): this interface is experimental and
    inheriting it is optional — it serves as the contract's shape.

    Subclasses get lifecycle hooks — default no-ops, so existing
    connectors keep working unchanged:

    - :meth:`on_connect` — the transport reached the peer (fired on
      every successful (re)connect, not just the first);
    - :meth:`on_disconnect` — the transport was lost or closed;
      ``reason`` is a short human string (``"closed"``, ``"eof"``,
      ``"liveness-timeout"``, ...);
    - :meth:`on_error` — a transport-layer exception the connector
      absorbed (the session/retransmit machinery handles recovery;
      this is the observation point).
    """

    def __init__(self, ydoc, awareness=None):
        super().__init__()
        self.doc = ydoc
        self.awareness = awareness

    def on_connect(self) -> None:
        """Called when the underlying transport comes up."""

    def on_disconnect(self, reason: str = "closed") -> None:
        """Called when the underlying transport goes away."""

    def on_error(self, exc: BaseException) -> None:
        """Called when the connector absorbs a transport error."""
