"""RelativePosition: stable cursors that survive concurrent edits
(reference src/utils/RelativePosition.js)."""

from __future__ import annotations

from ..core import ContentType, Item, follow_redone, get_state
from ..ids import ID, compare_ids, create_id, find_root_type_key, read_id, write_id
from ..lib0 import decoding, encoding
from ..lib0.decoding import Decoder
from ..lib0.encoding import Encoder


class RelativePosition:
    __slots__ = ("type", "tname", "item")

    def __init__(self, type_: ID | None, tname: str | None, item: ID | None):
        self.type = type_
        self.tname = tname
        self.item = item

    def to_json(self) -> dict:
        out = {}
        if self.type is not None:
            out["type"] = {"client": self.type.client, "clock": self.type.clock}
        if self.tname is not None:
            out["tname"] = self.tname
        if self.item is not None:
            out["item"] = {"client": self.item.client, "clock": self.item.clock}
        return out


def create_relative_position_from_json(json: dict) -> RelativePosition:
    type_ = json.get("type")
    item = json.get("item")
    return RelativePosition(
        create_id(type_["client"], type_["clock"]) if type_ else None,
        json.get("tname") or None,
        create_id(item["client"], item["clock"]) if item else None,
    )


class AbsolutePosition:
    __slots__ = ("type", "index")

    def __init__(self, type_, index: int):
        self.type = type_
        self.index = index


def create_absolute_position(type_, index: int) -> AbsolutePosition:
    return AbsolutePosition(type_, index)


def create_relative_position(type_, item: ID | None) -> RelativePosition:
    typeid = None
    tname = None
    if type_._item is None:
        tname = find_root_type_key(type_)
    else:
        typeid = create_id(type_._item.id.client, type_._item.id.clock)
    return RelativePosition(typeid, tname, item)


def create_relative_position_from_type_index(type_, index: int) -> RelativePosition:
    t = type_._start
    while t is not None:
        if not t.deleted and t.countable:
            if t.length > index:
                # found the position inside the list
                return create_relative_position(type_, create_id(t.id.client, t.id.clock + index))
            index -= t.length
        t = t.right
    return create_relative_position(type_, None)


def write_relative_position(encoder: Encoder, rpos: RelativePosition) -> Encoder:
    if rpos.item is not None:
        encoding.write_var_uint(encoder, 0)
        write_id(encoder, rpos.item)
    elif rpos.tname is not None:
        # position at end of list; type stored in doc.share
        encoding.write_uint8(encoder, 1)
        encoding.write_var_string(encoder, rpos.tname)
    elif rpos.type is not None:
        # position at end of list; type attached to an item
        encoding.write_uint8(encoder, 2)
        write_id(encoder, rpos.type)
    else:
        raise RuntimeError("invalid relative position")
    return encoder


def encode_relative_position(rpos: RelativePosition) -> bytes:
    encoder = Encoder()
    write_relative_position(encoder, rpos)
    return encoder.to_bytes()


def read_relative_position(decoder: Decoder) -> RelativePosition:
    type_ = None
    tname = None
    item_id = None
    case = decoding.read_var_uint(decoder)
    if case == 0:
        item_id = read_id(decoder)
    elif case == 1:
        tname = decoding.read_var_string(decoder)
    elif case == 2:
        type_ = read_id(decoder)
    return RelativePosition(type_, tname, item_id)


def decode_relative_position(buf: bytes) -> RelativePosition:
    return read_relative_position(Decoder(buf))


def create_absolute_position_from_relative_position(rpos: RelativePosition, doc) -> AbsolutePosition | None:
    """(reference RelativePosition.js:214-262)."""
    store = doc.store
    right_id = rpos.item
    type_id = rpos.type
    tname = rpos.tname
    type_ = None
    index = 0
    if right_id is not None:
        if get_state(store, right_id.client) <= right_id.clock:
            return None
        right, diff = follow_redone(store, right_id)
        if type(right) is not Item:
            return None
        type_ = right.parent
        if type_._item is None or not type_._item.deleted:
            index = 0 if right.deleted or not right.countable else diff
            n = right.left
            while n is not None:
                if not n.deleted and n.countable:
                    index += n.length
                n = n.left
    else:
        if tname is not None:
            type_ = doc.get(tname)
        elif type_id is not None:
            if get_state(store, type_id.client) <= type_id.clock:
                # type does not exist yet
                return None
            item, _ = follow_redone(store, type_id)
            if type(item) is Item and type(item.content) is ContentType:
                type_ = item.content.type
            else:
                # garbage collected
                return None
        else:
            raise RuntimeError("invalid relative position")
        index = type_._length
    return create_absolute_position(type_, index)


def compare_relative_positions(a: RelativePosition | None, b: RelativePosition | None) -> bool:
    return a is b or (
        a is not None
        and b is not None
        and a.tname == b.tname
        and compare_ids(a.item, b.item)
        and compare_ids(a.type, b.type)
    )
