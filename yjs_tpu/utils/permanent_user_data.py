"""PermanentUserData: user↔clientID/DeleteSet attribution stored inside a
shared YMap (reference src/utils/PermanentUserData.js).

The reference defers some writes with ``setTimeout(0)``; here they run
synchronously after the current transaction, which preserves convergence.
"""

from __future__ import annotations

from ..coding import DSDecoderV1, DSEncoderV1
from ..core import DeleteSet, is_deleted, merge_delete_sets, read_delete_set, write_delete_set
from ..ids import ID
from ..lib0.decoding import Decoder


class PermanentUserData:
    def __init__(self, doc, store_type=None):
        if store_type is None:
            store_type = doc.get_map("users")
        self.yusers = store_type
        self.doc = doc
        self.clients: dict[int, str] = {}
        self.dss: dict[str, DeleteSet] = {}

        def init_user(user, user_description):
            ds = user.get("ds")
            ids = user.get("ids")

            def add_client_id(clientid, *_args):
                self.clients[clientid] = user_description

            def _on_ds(event, _txn):
                for item in event.changes["added"]:
                    for encoded_ds in item.content.get_content():
                        if isinstance(encoded_ds, (bytes, bytearray)):
                            self.dss[user_description] = merge_delete_sets(
                                [
                                    self.dss.get(user_description, DeleteSet()),
                                    read_delete_set(DSDecoderV1(Decoder(bytes(encoded_ds)))),
                                ]
                            )

            ds.observe(_on_ds)
            self.dss[user_description] = merge_delete_sets(
                ds.map(
                    lambda encoded_ds, i, t: read_delete_set(
                        DSDecoderV1(Decoder(bytes(encoded_ds)))
                    )
                )
            )

            def _on_ids(event, _txn):
                for item in event.changes["added"]:
                    for clientid in item.content.get_content():
                        add_client_id(clientid)

            ids.observe(_on_ids)
            ids.for_each(add_client_id)

        def _on_users(event, _txn):
            for user_description in event.keys_changed:
                init_user(store_type.get(user_description), user_description)

        store_type.observe(_on_users)
        store_type.for_each(lambda user, key, _t: init_user(user, key))

    def set_user_mapping(self, doc, clientid: int, user_description: str, filter=None) -> None:
        """(reference PermanentUserData.js:77-120)."""
        from ..types.yarray import YArray
        from ..types.ymap import YMap

        if filter is None:
            filter = lambda _txn, _ds: True  # noqa: E731
        users = self.yusers
        user = users.get(user_description)
        if user is None:
            user = YMap()
            user.set("ids", YArray())
            user.set("ds", YArray())
            users.set(user_description, user)
        users.get(user_description).get("ids").push([clientid])

        state = {"user": users.get(user_description)}

        def _on_users(event, _txn):
            user_overwrite = users.get(user_description)
            if user_overwrite is not state["user"]:
                # user object was overwritten: port data to the new object
                user_local = user_overwrite
                state["user"] = user_local
                for cid, desc in list(self.clients.items()):
                    if user_description == desc:
                        user_local.get("ids").push([cid])
                encoder = DSEncoderV1()
                ds = self.dss.get(user_description)
                if ds:
                    write_delete_set(encoder, ds)
                    user_local.get("ds").push([encoder.to_bytes()])

        users.observe(_on_users)

        def _after_transaction(transaction, _doc):
            yds = state["user"].get("ds")
            ds = transaction.delete_set
            if transaction.local and ds.clients and filter(transaction, ds):
                encoder = DSEncoderV1()
                write_delete_set(encoder, ds)
                yds.push([encoder.to_bytes()])

        doc.on("afterTransaction", _after_transaction)

    def get_user_by_client_id(self, clientid: int) -> str | None:
        return self.clients.get(clientid)

    def get_user_by_deleted_id(self, id: ID) -> str | None:
        for user_description, ds in self.dss.items():
            if is_deleted(ds, id):
                return user_description
        return None
