"""Snapshot: DeleteSet + state vector = a point-in-time view
(reference src/utils/Snapshot.js)."""

from __future__ import annotations

from ..coding import DSDecoderV1, DSDecoderV2, DSEncoderV2, UpdateEncoderV2, default_ds_encoder
from ..core import (
    DeleteSet,
    Doc,
    create_delete_set_from_struct_store,
    find_index_ss,
    get_item_clean_start,
    get_state,
    get_state_vector,
    is_deleted,
    iterate_deleted_structs,
    read_delete_set,
    write_delete_set,
)
from ..ids import create_id
from ..lib0 import encoding
from ..lib0.decoding import Decoder
from ..updates import apply_update_v2, read_state_vector, write_state_vector


class Snapshot:
    __slots__ = ("ds", "sv")

    def __init__(self, ds: DeleteSet, sv: dict[int, int]):
        self.ds = ds
        self.sv = sv


def equal_snapshots(snap1: Snapshot, snap2: Snapshot) -> bool:
    ds1 = snap1.ds.clients
    ds2 = snap2.ds.clients
    sv1 = snap1.sv
    sv2 = snap2.sv
    if len(sv1) != len(sv2) or len(ds1) != len(ds2):
        return False
    for key, value in sv1.items():
        if sv2.get(key) != value:
            return False
    for client, dsitems1 in ds1.items():
        dsitems2 = ds2.get(client, [])
        if len(dsitems1) != len(dsitems2):
            return False
        for d1, d2 in zip(dsitems1, dsitems2):
            if d1.clock != d2.clock or d1.len != d2.len:
                return False
    return True


def encode_snapshot_v2(snapshot: Snapshot, encoder=None) -> bytes:
    if encoder is None:
        encoder = DSEncoderV2()
    write_delete_set(encoder, snapshot.ds)
    write_state_vector(encoder, snapshot.sv)
    return encoder.to_bytes()


def encode_snapshot(snapshot: Snapshot) -> bytes:
    return encode_snapshot_v2(snapshot, default_ds_encoder())


def decode_snapshot_v2(buf: bytes, decoder=None) -> Snapshot:
    if decoder is None:
        decoder = DSDecoderV2(Decoder(buf))
    return Snapshot(read_delete_set(decoder), read_state_vector(decoder))


def decode_snapshot(buf: bytes) -> Snapshot:
    return decode_snapshot_v2(buf, DSDecoderV1(Decoder(buf)))


def create_snapshot(ds: DeleteSet, sm: dict[int, int]) -> Snapshot:
    return Snapshot(ds, sm)


def empty_snapshot() -> Snapshot:
    return create_snapshot(DeleteSet(), {})


def snapshot(doc: Doc) -> Snapshot:
    return create_snapshot(
        create_delete_set_from_struct_store(doc.store), get_state_vector(doc.store)
    )


def is_visible(item, snap: Snapshot | None) -> bool:
    """Point-in-time visibility (reference Snapshot.js:133-135)."""
    if snap is None:
        return not item.deleted
    return (
        item.id.client in snap.sv
        and snap.sv.get(item.id.client, 0) > item.id.clock
        and not is_deleted(snap.ds, item.id)
    )


_SPLIT_META_KEY = "split_snapshot_affected_structs"


def split_snapshot_affected_structs(transaction, snap: Snapshot) -> None:
    """Pre-split items at snapshot boundaries, memoized per transaction
    (reference Snapshot.js:141-154)."""
    meta = transaction.meta.setdefault(_SPLIT_META_KEY, set())
    store = transaction.doc.store
    if snap not in meta:
        for client, clock in snap.sv.items():
            if clock < get_state(store, client):
                get_item_clean_start(transaction, create_id(client, clock))
        iterate_deleted_structs(transaction, snap.ds, lambda item: None)
        meta.add(snap)


def create_doc_from_snapshot(origin_doc: Doc, snap: Snapshot, new_doc: Doc | None = None) -> Doc:
    """Re-encode truncated history into a fresh doc; requires gc off
    (reference Snapshot.js:162-202)."""
    if origin_doc.gc:
        raise RuntimeError("originDoc must not be garbage collected")
    if new_doc is None:
        new_doc = Doc()
    sv = snap.sv
    ds = snap.ds
    encoder = UpdateEncoderV2()

    def _encode(transaction):
        size = sum(1 for clock in sv.values() if clock > 0)
        encoding.write_var_uint(encoder.rest_encoder, size)
        for client, clock in sv.items():
            if clock == 0:
                continue
            if clock < get_state(origin_doc.store, client):
                get_item_clean_start(transaction, create_id(client, clock))
            structs = origin_doc.store.clients.get(client, [])
            last_struct_index = find_index_ss(structs, clock - 1)
            encoding.write_var_uint(encoder.rest_encoder, last_struct_index + 1)
            encoder.write_client(client)
            encoding.write_var_uint(encoder.rest_encoder, 0)
            for i in range(last_struct_index + 1):
                structs[i].write(encoder, 0)
        write_delete_set(encoder, ds)

    origin_doc.transact(_encode)
    apply_update_v2(new_doc, encoder.to_bytes(), "snapshot")
    return new_doc
