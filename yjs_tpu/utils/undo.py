"""UndoManager: selective, scope-filtered undo/redo
(reference src/utils/UndoManager.js)."""

from __future__ import annotations

import time as _time

from ..core import (
    GC,
    DeleteSet,
    Item,
    get_item_clean_start,
    get_state,
    is_parent_of,
    iterate_deleted_structs,
    iterate_structs,
    keep_item,
    merge_delete_sets,
    redo_item,
    follow_redone,
    transact,
)
from ..ids import create_id
from ..lib0.observable import Observable


class StackItem:
    __slots__ = ("ds", "before_state", "after_state", "meta")

    def __init__(self, ds: DeleteSet, before_state: dict, after_state: dict):
        self.ds = ds
        self.before_state = before_state
        self.after_state = after_state
        self.meta: dict = {}


def _pop_stack_item(undo_manager: "UndoManager", stack: list, event_type: str):
    """(reference UndoManager.js:42-134)."""
    result = None
    doc = undo_manager.doc
    scope = undo_manager.scope

    def _run(transaction):
        nonlocal result
        while stack and result is None:
            store = doc.store
            stack_item = stack.pop()
            items_to_redo: set = set()
            items_to_delete: list = []
            performed_change = False
            for client, end_clock in stack_item.after_state.items():
                start_clock = stack_item.before_state.get(client, 0)
                length = end_clock - start_clock
                structs = store.clients.get(client)
                if start_clock != end_clock:
                    # keep the created range split-aligned before iterating
                    get_item_clean_start(transaction, create_id(client, start_clock))
                    if end_clock < get_state(doc.store, client):
                        get_item_clean_start(transaction, create_id(client, end_clock))

                    def _collect(struct):
                        if type(struct) is Item:
                            if struct.redone is not None:
                                item, diff = follow_redone(store, struct.id)
                                if diff > 0:
                                    item = get_item_clean_start(
                                        transaction, create_id(item.id.client, item.id.clock + diff)
                                    )
                                if item.length > length:
                                    get_item_clean_start(
                                        transaction, create_id(item.id.client, end_clock)
                                    )
                                struct = item
                            if not struct.deleted and any(
                                is_parent_of(type_, struct) for type_ in scope
                            ):
                                items_to_delete.append(struct)

                    iterate_structs(transaction, structs, start_clock, length, _collect)

            def _collect_redo(struct):
                clock = struct.id.clock
                client = struct.id.client
                start_clock = stack_item.before_state.get(client, 0)
                end_clock = stack_item.after_state.get(client, 0)
                if (
                    type(struct) is Item
                    and any(is_parent_of(type_, struct) for type_ in scope)
                    and not (start_clock <= clock < end_clock)
                ):
                    items_to_redo.add(struct)

            iterate_deleted_structs(transaction, stack_item.ds, _collect_redo)
            for struct in items_to_redo:
                performed_change = (
                    redo_item(transaction, struct, items_to_redo) is not None
                ) or performed_change
            # delete in reverse so children are deleted before parents
            for item in reversed(items_to_delete):
                if undo_manager.delete_filter(item):
                    item.delete(transaction)
                    performed_change = True
            # v13.4.9 quirk: result is set unconditionally (performed_change
            # is tracked but unused, reference UndoManager.js:62,121)
            del performed_change
            result = stack_item
        for type_, sub_props in transaction.changed.items():
            if None in sub_props and type_._search_marker is not None:
                type_._search_marker.clear()

    transact(doc, _run, undo_manager)
    if result is not None:
        undo_manager.emit(
            "stack-item-popped", [{"stackItem": result, "type": event_type}, undo_manager]
        )
    return result


class UndoManager(Observable):
    """Track transactions on a set of scope types and selectively revert
    them.  ``tracked_origins`` filters which transaction origins count."""

    def __init__(
        self,
        type_scope,
        capture_timeout: float = 500,
        delete_filter=None,
        tracked_origins: set | None = None,
    ):
        super().__init__()
        self.scope = type_scope if isinstance(type_scope, list) else [type_scope]
        self.delete_filter = delete_filter if delete_filter is not None else (lambda item: True)
        self.tracked_origins = tracked_origins if tracked_origins is not None else {None}
        self.tracked_origins.add(self)
        self.undo_stack: list[StackItem] = []
        self.redo_stack: list[StackItem] = []
        self.undoing = False
        self.redoing = False
        self.doc = self.scope[0].doc
        self.last_change = 0.0
        self.capture_timeout = capture_timeout
        self.doc.on("afterTransaction", self._after_transaction)

    def _tracks_origin(self, origin) -> bool:
        try:
            if origin in self.tracked_origins:
                return True
        except TypeError:
            pass
        return origin is not None and type(origin) in self.tracked_origins

    def _after_transaction(self, transaction, _doc) -> None:
        """(reference UndoManager.js:183-219)."""
        if not any(
            type_ in transaction.changed_parent_types for type_ in self.scope
        ) or not self._tracks_origin(transaction.origin):
            return
        undoing = self.undoing
        redoing = self.redoing
        stack = self.redo_stack if undoing else self.undo_stack
        if undoing:
            self.stop_capturing()  # next undo should not merge into last item
        elif not redoing:
            self.redo_stack = []
        before_state = transaction.before_state
        after_state = transaction.after_state
        now = _time.time() * 1000
        if (
            now - self.last_change < self.capture_timeout
            and stack
            and not undoing
            and not redoing
        ):
            last_op = stack[-1]
            last_op.ds = merge_delete_sets([last_op.ds, transaction.delete_set])
            last_op.after_state = after_state
        else:
            stack.append(StackItem(transaction.delete_set, before_state, after_state))
        if not undoing and not redoing:
            self.last_change = now

        def _keep(item):
            if type(item) is Item and any(is_parent_of(type_, item) for type_ in self.scope):
                keep_item(item, True)

        iterate_deleted_structs(transaction, transaction.delete_set, _keep)
        self.emit(
            "stack-item-added",
            [
                {
                    "stackItem": stack[-1],
                    "origin": transaction.origin,
                    "type": "redo" if undoing else "undo",
                },
                self,
            ],
        )

    def clear(self) -> None:
        def _run(transaction):
            def clear_item(stack_item):
                def _unkeep(item):
                    if type(item) is Item and any(
                        is_parent_of(type_, item) for type_ in self.scope
                    ):
                        keep_item(item, False)

                iterate_deleted_structs(transaction, stack_item.ds, _unkeep)

            for stack_item in self.undo_stack:
                clear_item(stack_item)
            for stack_item in self.redo_stack:
                clear_item(stack_item)

        self.doc.transact(_run)
        self.undo_stack = []
        self.redo_stack = []

    def stop_capturing(self) -> None:
        self.last_change = 0.0

    def undo(self):
        self.undoing = True
        try:
            return _pop_stack_item(self, self.undo_stack, "undo")
        finally:
            self.undoing = False

    def redo(self):
        self.redoing = True
        try:
            return _pop_stack_item(self, self.redo_stack, "redo")
        finally:
            self.redoing = False
