"""Durability for the provider fleet (ISSUE 3).

Three pieces, all zero-dependency host-side code:

- :mod:`records` — the CRC-checksummed, length-prefixed record codec
  shared by segments and checkpoints;
- :mod:`wal` — :class:`WriteAheadLog`: per-provider append-only journal
  with segment rotation, a configurable fsync policy, and
  ``checkpoint()`` compaction (sealed segments folded into per-doc
  ``encode_state_as_update`` snapshots, y-leveldb style);
- :mod:`recovery` — ``replay_wal`` / ``TpuProvider.recover``:
  snapshot-then-tail replay tolerating torn tails (truncate at the
  first bad checksum on the final segment) and mid-log corruption
  (``validate_update`` → dead-letter queue, resync, continue).

Env knobs: ``YTPU_WAL_DIR`` (journal every provider constructed without
an explicit ``wal_dir``), ``YTPU_WAL_SEGMENT_BYTES`` (rotation
threshold, default 4 MiB), ``YTPU_WAL_FSYNC`` =
``always | interval | never`` (default ``interval``), and
``YTPU_WAL_FSYNC_INTERVAL`` (appends per fsync in interval mode,
default 64).  Metrics land in the ``ytpu_wal_*`` families (see
:class:`WalMetrics`); README "Durability" documents the format and the
fsync tradeoffs.
"""

from .records import (
    FLAG_V2,
    HEADER_SIZE,
    KIND_ACK,
    KIND_ADM,
    KIND_DLQ,
    KIND_GEO,
    KIND_MIGRATE,
    KIND_NAMES,
    KIND_RELEASE,
    KIND_REPL,
    KIND_SNAPSHOT,
    KIND_TIER,
    KIND_UPDATE,
    decode_tier_payload,
    encode_tier_payload,
    MAX_GUID,
    MAX_PAYLOAD,
    REC_MAGIC,
    SEG_HEADER,
    SNAP_HEADER,
    WalRecord,
    encode_record,
    try_decode_at,
)
from .recovery import (
    count_guids,
    iter_file_events,
    replay_wal,
    scan_wal,
)
from .wal import (
    WalConfig,
    WalMetrics,
    WriteAheadLog,
    list_checkpoints,
    list_segments,
)

__all__ = [
    "FLAG_V2",
    "HEADER_SIZE",
    "KIND_ACK",
    "KIND_ADM",
    "KIND_DLQ",
    "KIND_GEO",
    "KIND_MIGRATE",
    "KIND_NAMES",
    "KIND_RELEASE",
    "KIND_REPL",
    "KIND_SNAPSHOT",
    "KIND_TIER",
    "KIND_UPDATE",
    "MAX_GUID",
    "MAX_PAYLOAD",
    "REC_MAGIC",
    "SEG_HEADER",
    "SNAP_HEADER",
    "WalConfig",
    "WalMetrics",
    "WalRecord",
    "WriteAheadLog",
    "count_guids",
    "decode_tier_payload",
    "encode_record",
    "encode_tier_payload",
    "iter_file_events",
    "list_checkpoints",
    "list_segments",
    "replay_wal",
    "scan_wal",
    "try_decode_at",
]
