"""Per-provider append-only write-ahead log with snapshot compaction.

Layout of a WAL directory (one per provider):

    wal-00000000.log          sealed segment (oldest surviving)
    wal-00000001.log          ...
    wal-00000002.log          active segment (appends go here)
    checkpoint-00000001.snap  newest checkpoint: full per-doc snapshots
                              + the dead-letter-queue dump; covers every
                              segment with index < 1

Appends are length-prefixed CRC-checksummed records (see records.py).
When the active segment passes ``segment_bytes`` it is sealed and a new
one opened.  ``checkpoint()`` folds everything written so far into
per-doc ``encode_state_as_update`` snapshots (the y-leveldb compaction
model: an update log is only a delayed snapshot) and deletes the
covered segments — recovery then replays snapshot-then-tail.

Env knobs (constructor args win over env):

- ``YTPU_WAL_DIR`` — enables journaling for every provider constructed
  without an explicit ``wal_dir``
- ``YTPU_WAL_SEGMENT_BYTES`` — rotation threshold (default 4 MiB)
- ``YTPU_WAL_FSYNC`` — ``always`` (fsync per append: zero-loss, pays a
  disk round trip per update), ``interval`` (default; fsync every
  ``YTPU_WAL_FSYNC_INTERVAL`` appends — bounded loss window, amortized
  cost), ``never`` (flush to the OS only; a host crash may lose the
  page-cache tail, a process crash loses nothing)
- ``YTPU_WAL_FSYNC_INTERVAL`` — appends between fsyncs in ``interval``
  mode (default 64)
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from pathlib import Path

from .records import (
    KIND_DLQ,
    KIND_NAMES,
    KIND_SNAPSHOT,
    SEG_HEADER,
    SNAP_HEADER,
    encode_record,
)

SEGMENT_RE = re.compile(r"wal-(\d{8})\.log$")
CHECKPOINT_RE = re.compile(r"checkpoint-(\d{8})\.snap$")

_FSYNC_MODES = ("always", "interval", "never")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class WalConfig:
    """Rotation + fsync policy (env-derived defaults)."""

    __slots__ = ("segment_bytes", "fsync", "fsync_interval")

    def __init__(
        self,
        segment_bytes: int | None = None,
        fsync: str | None = None,
        fsync_interval: int | None = None,
    ):
        if segment_bytes is None:
            segment_bytes = _env_int("YTPU_WAL_SEGMENT_BYTES", 4 << 20)
        self.segment_bytes = max(1, segment_bytes)
        if fsync is None:
            fsync = os.environ.get("YTPU_WAL_FSYNC", "interval")
        if fsync not in _FSYNC_MODES:
            raise ValueError(
                f"YTPU_WAL_FSYNC must be one of {_FSYNC_MODES}, got {fsync!r}"
            )
        self.fsync = fsync
        if fsync_interval is None:
            fsync_interval = _env_int("YTPU_WAL_FSYNC_INTERVAL", 64)
        self.fsync_interval = max(1, fsync_interval)

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class _Noop:
    def inc(self, amount=1):
        pass

    def observe(self, value):
        pass

    def labels(self, **kw):
        return self


class WalMetrics:
    """The ``ytpu_wal_*`` instrument bundle.

    Registered unconditionally at provider construction (registry=the
    engine's) so exposition and scripts/check_metrics_schema.py see the
    families whether or not a WAL is attached; a standalone
    WriteAheadLog (fixture generator, tests) passes ``registry=None``
    and gets no-ops.
    """

    def __init__(self, registry=None):
        if registry is None:
            noop = _Noop()
            self.records = self.bytes = self.fsyncs = noop
            self.segments = self.compactions = self.reclaimed = noop
            self.recoveries = self.replayed = noop
            self.torn = self.corrupt = self.replay_seconds = noop
            self.append_seconds = self.overflow = noop
            return
        self.records = registry.counter(
            "ytpu_wal_records_appended_total",
            "Records appended to the write-ahead log, by record kind",
            labelnames=("kind",),
        )
        self.bytes = registry.counter(
            "ytpu_wal_bytes_appended_total",
            "Encoded record bytes appended to the write-ahead log",
            unit="bytes",
        )
        self.fsyncs = registry.counter(
            "ytpu_wal_fsyncs_total",
            "fsync calls issued by the write-ahead log",
        )
        self.segments = registry.counter(
            "ytpu_wal_segments_sealed_total",
            "WAL segments sealed (rotation or checkpoint)",
        )
        self.compactions = registry.counter(
            "ytpu_wal_compactions_total",
            "Checkpoints written (sealed segments folded into per-doc "
            "snapshots)",
        )
        self.reclaimed = registry.counter(
            "ytpu_wal_compaction_reclaimed_bytes_total",
            "Segment + stale-checkpoint bytes deleted by compaction",
            unit="bytes",
        )
        self.recoveries = registry.counter(
            "ytpu_wal_recoveries_total",
            "Recovery replays run, by outcome (clean / torn_tail / "
            "corrupt_records / empty)",
            labelnames=("outcome",),
        )
        self.replayed = registry.counter(
            "ytpu_wal_replay_records_total",
            "Records processed during recovery replay, by disposition",
            labelnames=("disposition",),
        )
        self.torn = registry.counter(
            "ytpu_wal_torn_tail_truncations_total",
            "Final-segment torn tails truncated during recovery",
        )
        self.corrupt = registry.counter(
            "ytpu_wal_corrupt_records_total",
            "Mid-log corrupt records found during recovery (routed to "
            "the dead-letter queue)",
        )
        self.replay_seconds = registry.histogram(
            "ytpu_wal_replay_seconds",
            "Wall time of one recovery replay (snapshot + tail)",
            unit="s",
        )
        self.append_seconds = registry.histogram(
            "ytpu_wal_append_seconds",
            "Wall time of one WAL append (encode + write + policy fsync)",
            unit="s",
        )
        self.overflow = registry.counter(
            "ytpu_wal_recovery_overflow_total",
            "Replayed records whose doc could not be admitted "
            "(ProviderFullError) and were routed to the dead-letter "
            "queue with a wal-overflow: reason",
        )


def list_segments(path) -> list[tuple[int, Path]]:
    """(index, path) of every WAL segment in the directory, ascending."""
    out = []
    for p in Path(path).iterdir():
        m = SEGMENT_RE.match(p.name)
        if m:
            out.append((int(m.group(1)), p))
    out.sort()
    return out


def list_checkpoints(path) -> list[tuple[int, Path]]:
    """(upto, path) of every checkpoint file, ascending by coverage."""
    out = []
    for p in Path(path).iterdir():
        m = CHECKPOINT_RE.match(p.name)
        if m:
            out.append((int(m.group(1)), p))
    out.sort()
    return out


class WriteAheadLog:
    """Append-only journal for one provider.

    Existing segments in the directory are treated as sealed history
    (recovery reads them; this writer never touches their contents) —
    appends always start a NEW segment, so a crashed predecessor's torn
    tail can be truncated by recovery without racing the live writer.
    """

    def __init__(self, path, config: WalConfig | None = None, metrics=None,
                 tracer=None):
        self.dir = Path(path)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.config = config if config is not None else WalConfig()
        self.metrics = metrics if metrics is not None else WalMetrics(None)
        # optional host tracer (yjs_tpu.obs.Tracer): journal latency
        # becomes a span inside the provider's receive/flush timeline
        self._tracer = tracer
        existing = list_segments(self.dir)
        ckpts = list_checkpoints(self.dir)
        self._next_index = max(
            [i + 1 for i, _ in existing] + [u for u, _ in ckpts] + [0]
        )
        # the first index THIS writer owns: recovery replays strictly
        # below it, so the replay can never consume its own appends
        self.first_index = self._next_index
        self._f = None
        self._path: Path | None = None
        self._size = 0
        self._appends = 0
        self._closed = False
        self._dead = False

    # -- appending -----------------------------------------------------------

    def _open_next(self) -> None:
        self._index = self._next_index
        self._next_index += 1
        self._path = self.dir / f"wal-{self._index:08d}.log"
        self._f = open(self._path, "wb")
        self._f.write(SEG_HEADER)
        self._size = len(SEG_HEADER)

    def _seal(self) -> None:
        if self._f is None:
            return
        self._f.flush()
        if self.config.fsync != "never":
            os.fsync(self._f.fileno())
            self.metrics.fsyncs.inc()
        self._f.close()
        self._f = None
        self.metrics.segments.inc()

    def append(
        self, kind: int, guid: str, payload: bytes, v2: bool = False
    ) -> tuple[Path, int, int]:
        """Journal one record (durability per the fsync policy).

        Returns a ``(path, offset, length)`` locator for the record just
        written — the cold tier (ISSUE 7) keeps locators instead of
        payload bytes and reads the record back on promotion.  Locators
        dangle once ``checkpoint()`` deletes the segment; holders must
        re-journal after a checkpoint (the ack-floor idiom)."""
        if self._dead:
            raise RuntimeError("WAL abandoned (simulated crash)")
        if self._closed:
            raise RuntimeError("WAL is closed")
        t0 = time.perf_counter()
        rec = encode_record(kind, guid, payload, v2)
        if self._f is None or self._size >= self.config.segment_bytes:
            self._seal()
            self._open_next()
        offset = self._size
        self._f.write(rec)
        # flush to the OS on every append: in-process readers (tests,
        # the crash harness) must see exactly what a crashed process
        # would leave behind — fsync is the only policy-gated cost
        self._f.flush()
        self._size += len(rec)
        self._appends += 1
        self.metrics.records.labels(kind=KIND_NAMES[kind]).inc()
        self.metrics.bytes.inc(len(rec))
        cfg = self.config
        if cfg.fsync == "always" or (
            cfg.fsync == "interval" and self._appends % cfg.fsync_interval == 0
        ):
            os.fsync(self._f.fileno())
            self.metrics.fsyncs.inc()
        dt = time.perf_counter() - t0
        self.metrics.append_seconds.observe(dt)
        if self._tracer is not None and self._tracer.enabled:
            # record as a completed span (retroactively: the duration is
            # already known, no context-manager overhead on the hot path)
            self._tracer._events.append((
                "ytpu.wal.append", "X",
                (t0 - self._tracer._t0) * 1e6, dt * 1e6,
                threading.get_ident(), {"kind": KIND_NAMES[kind]}, None,
            ))
        return (self._path, offset, len(rec))

    # -- compaction ----------------------------------------------------------

    def checkpoint(
        self,
        doc_snapshots: list[tuple[str, bytes]],
        dlq_state: dict | None = None,
    ) -> dict:
        """Fold the log into a checkpoint file and truncate the history.

        ``doc_snapshots`` are (guid, encode_state_as_update bytes) pairs
        reflecting EVERYTHING journaled so far (the caller flushes
        first).  The active segment is sealed, the checkpoint is
        written+fsynced+atomically renamed, and only then are the
        covered segments and older checkpoints deleted — a crash at any
        point leaves either the old history or the new checkpoint fully
        intact (replaying both, where they overlap, is safe by update
        idempotence)."""
        if self._dead:
            raise RuntimeError("WAL abandoned (simulated crash)")
        self._seal()
        upto = self._next_index
        final = self.dir / f"checkpoint-{upto:08d}.snap"
        tmp = final.with_suffix(".snap.tmp")
        snap_bytes = 0
        with open(tmp, "wb") as f:
            f.write(SNAP_HEADER)
            for guid, snap in doc_snapshots:
                rec = encode_record(KIND_SNAPSHOT, guid, snap)
                f.write(rec)
                snap_bytes += len(rec)
            if dlq_state is not None:
                rec = encode_record(
                    KIND_DLQ, "", json.dumps(dlq_state).encode("utf-8")
                )
                f.write(rec)
                snap_bytes += len(rec)
            f.flush()
            if self.config.fsync != "never":
                os.fsync(f.fileno())
                self.metrics.fsyncs.inc()
        os.replace(tmp, final)
        reclaimed = 0
        removed = 0
        for idx, p in list_segments(self.dir):
            if idx < upto:
                reclaimed += p.stat().st_size
                p.unlink()
                removed += 1
        for cov, p in list_checkpoints(self.dir):
            if cov < upto:
                reclaimed += p.stat().st_size
                p.unlink()
        self.metrics.compactions.inc()
        self.metrics.reclaimed.inc(reclaimed)
        return {
            "checkpoint": str(final),
            "docs": len(doc_snapshots),
            "snapshot_bytes": snap_bytes,
            "segments_removed": removed,
            "reclaimed_bytes": reclaimed,
        }

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Seal the active segment and stop accepting appends."""
        if not self._dead:
            self._seal()
        self._closed = True

    def abandon(self) -> None:
        """Simulated crash (the chaos harness): drop the file handle
        with NO seal-time fsync and refuse all further appends — the
        directory is left exactly as a killed process would leave it."""
        if self._f is not None:
            self._f.close()
            self._f = None
        self._dead = True
