"""WAL record codec: CRC-checksummed, length-prefixed binary records.

Every durable byte the provider writes — journaled updates, compaction
snapshots, dead-letter-queue dumps, slot releases — travels in one
record format so a single reader serves segments and checkpoints alike:

    segment file header:    b"YTPUWAL1"   (checkpoint: b"YTPUSNP1")
    record header (14 B, little-endian):
        magic        u16    0x7EA1
        kind         u8     1=update 2=snapshot 3=dlq 4=release 5=ack
                            6=migrate 7=tier 8=repl 9=adm 10=geo
        flags        u8     bit0 = payload uses the V2 update encoding
        guid_len     u16
        payload_len  u32
        crc32        u32    over kind..payload_len + guid + payload
    guid     utf-8 bytes
    payload  bytes

The CRC covers everything except the magic and itself, so any single
flipped bit — header or body — fails the check (CRC-32 detects all
burst errors up to 32 bits).  The magic exists purely for
resynchronization: a reader that hits a corrupt record in a sealed
segment scans forward for the next magic and keeps going.
"""

from __future__ import annotations

import json
import struct
import zlib

SEG_HEADER = b"YTPUWAL1"
SNAP_HEADER = b"YTPUSNP1"

REC_MAGIC = b"\xa1\x7e"
_HDR = struct.Struct("<2sBBHII")
HEADER_SIZE = _HDR.size  # 14

KIND_UPDATE = 1
KIND_SNAPSHOT = 2
KIND_DLQ = 3
KIND_RELEASE = 4
KIND_ACK = 5
# migration intent (ISSUE 6): journaled on the SOURCE shard before any
# state reaches the destination, so crash-mid-migration recovery can
# resolve ownership to exactly one shard.  Payload is JSON
# {"dst": shard, "epoch": routing_epoch}; a later KIND_RELEASE for the
# same guid marks the handoff complete.
KIND_MIGRATE = 6
# tier demotion marker (ISSUE 7): journaled when a doc leaves the hot
# tier.  Payload is a length-prefixed JSON meta header ({"tier": "warm"
# or "cold", "heat": score, "letters": [...]}) followed by the doc's
# full ``encode_state_as_update`` bytes at demotion time — recovery
# replays the state like a snapshot, then places the doc in the
# recorded tier unless LATER records show it was touched again.
KIND_TIER = 7
# replication role marker (ISSUE 8): journaled on a shard whose WAL
# holds a doc it does not OWN (a replica copy), and on a shard that
# just won ownership via failover promotion.  Payload is JSON
# {"role": "replica" | "primary", "epoch": fencing_epoch,
# "primary": shard?}; the LAST marker for a guid stands and a
# KIND_RELEASE clears it.  Recovery uses the markers to resolve
# ownership without treating replica journals as split-brain owners,
# and to fence a stale primary's claim behind a newer promotion epoch.
KIND_REPL = 8
# admission brownout transition (ISSUE 10): journaled on every attached
# provider's WAL when the fleet brownout controller changes degradation
# level, so a post-incident recovery can reconstruct exactly when and
# why service was degraded.  Guid is empty (the record is fleet-scoped,
# not doc-scoped); payload is JSON {"level": name, "reason": str,
# "tick": controller_tick}.  Recovery surfaces a count and the last
# level in its stats; the live level always restarts at "normal".
KIND_ADM = 9
# geo link state (ISSUE 17): journaled by a region's GeoReplicator when
# an inter-region link's ack floor advances or its fencing epoch moves.
# Guid is empty (link state is region-scoped, not doc-scoped); payload
# is JSON {"peer": region, "sid": session_id, "seq": recv_floor,
# "epoch": fencing_epoch}.  The LAST record per peer stands.  Recovery
# surfaces the floors as resume hints so a region recovering from
# kill -9 re-HELLOs its WAN links with the journaled floor and resumes
# retransmission instead of full-resyncing every doc in the space.
KIND_GEO = 10
KIND_NAMES = {
    KIND_UPDATE: "update",
    KIND_SNAPSHOT: "snapshot",
    KIND_DLQ: "dlq",
    KIND_RELEASE: "release",
    KIND_ACK: "ack",
    KIND_MIGRATE: "migrate",
    KIND_TIER: "tier",
    KIND_REPL: "repl",
    KIND_ADM: "adm",
    KIND_GEO: "geo",
}

FLAG_V2 = 1

# sanity bounds the reader trusts header lengths against — a corrupt
# length field must not make it allocate or skip gigabytes
MAX_GUID = 4096
MAX_PAYLOAD = 1 << 26  # 64 MiB


class WalRecord:
    """One decoded record."""

    __slots__ = ("kind", "guid", "payload", "v2")

    def __init__(self, kind: int, guid: str, payload: bytes, v2: bool):
        self.kind = kind
        self.guid = guid
        self.payload = payload
        self.v2 = v2

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"WalRecord({KIND_NAMES.get(self.kind, self.kind)}, "
            f"guid={self.guid!r}, bytes={len(self.payload)}, v2={self.v2})"
        )


def encode_record(
    kind: int, guid: str, payload: bytes, v2: bool = False
) -> bytes:
    if kind not in KIND_NAMES:
        raise ValueError(f"unknown record kind {kind}")
    guid_b = guid.encode("utf-8")
    if len(guid_b) > MAX_GUID:
        raise ValueError(f"guid too long ({len(guid_b)} > {MAX_GUID})")
    if len(payload) > MAX_PAYLOAD:
        raise ValueError(f"payload too large ({len(payload)} > {MAX_PAYLOAD})")
    flags = FLAG_V2 if v2 else 0
    body = struct.pack("<BBHI", kind, flags, len(guid_b), len(payload))
    crc = zlib.crc32(body)
    crc = zlib.crc32(guid_b, crc)
    crc = zlib.crc32(payload, crc)
    return (
        _HDR.pack(REC_MAGIC, kind, flags, len(guid_b), len(payload), crc)
        + guid_b
        + bytes(payload)
    )


def try_decode_at(data: bytes, pos: int):
    """Attempt one record at ``pos``.

    Returns ``("ok", WalRecord, end)`` for a valid record,
    ``("bad_crc", payload_or_None, end)`` when the header parses but the
    checksum fails (payload is the best-effort body slice),
    ``("bad_header", None, pos)`` when the bytes at ``pos`` are not a
    plausible record header, or ``("short", None, pos)`` when the record
    (header or body) extends past the end of the buffer — a torn write
    if this is the final segment.
    """
    n = len(data)
    if n - pos < HEADER_SIZE:
        return ("short", None, pos)
    magic, kind, flags, guid_len, payload_len, crc = _HDR.unpack_from(
        data, pos
    )
    if magic != REC_MAGIC or kind not in KIND_NAMES:
        return ("bad_header", None, pos)
    if guid_len > MAX_GUID or payload_len > MAX_PAYLOAD:
        return ("bad_header", None, pos)
    end = pos + HEADER_SIZE + guid_len + payload_len
    if end > n:
        return ("short", None, pos)
    guid_b = data[pos + HEADER_SIZE : pos + HEADER_SIZE + guid_len]
    payload = data[pos + HEADER_SIZE + guid_len : end]
    body = struct.pack("<BBHI", kind, flags, guid_len, payload_len)
    want = zlib.crc32(body)
    want = zlib.crc32(guid_b, want)
    want = zlib.crc32(payload, want)
    if want != crc:
        return ("bad_crc", payload, end)
    try:
        guid = guid_b.decode("utf-8")
    except UnicodeDecodeError:
        # CRC passed but the guid is not utf-8: only possible for bytes
        # we never wrote — treat as unparseable
        return ("bad_header", None, pos)
    return ("ok", WalRecord(kind, guid, payload, bool(flags & FLAG_V2)), end)


def encode_tier_payload(
    tier: str, heat: float, update: bytes, letters: list | None = None
) -> bytes:
    """KIND_TIER payload: ``u32 meta_len | meta JSON | update bytes``.

    ``letters`` are JSON-able dead-letter dicts (base64 update bodies,
    the DLQ snapshot shape) that rode out of the slot with the doc."""
    meta: dict = {"tier": tier, "heat": round(float(heat), 6)}
    if letters:
        meta["letters"] = letters
    mb = json.dumps(meta, separators=(",", ":")).encode("utf-8")
    return struct.pack("<I", len(mb)) + mb + bytes(update)


def decode_tier_payload(payload: bytes) -> tuple[dict, bytes]:
    """Inverse of :func:`encode_tier_payload` → (meta, update bytes)."""
    if len(payload) < 4:
        raise ValueError("tier payload too short for meta length")
    (mlen,) = struct.unpack_from("<I", payload, 0)
    if 4 + mlen > len(payload):
        raise ValueError("tier payload meta overruns record")
    meta = json.loads(payload[4 : 4 + mlen].decode("utf-8"))
    if not isinstance(meta, dict) or meta.get("tier") not in (
        "hot",  # promotion marker: clears any earlier demote marker
        "warm",
        "cold",
    ):
        raise ValueError(f"tier payload meta invalid: {meta!r}")
    return meta, payload[4 + mlen :]


def resync(data: bytes, pos: int) -> int:
    """Next candidate record offset at or after ``pos`` (the next magic
    occurrence), or ``len(data)`` when none remains."""
    i = data.find(REC_MAGIC, pos)
    return len(data) if i < 0 else i
